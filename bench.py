"""Benchmark: AFNS5 Kalman log-likelihood throughput, device vs 1-thread CPU.

Measures the BASELINE.md north-star metric — loglik evals/sec for a 5-factor
arbitrage-free NS model on a Liu–Wu-shaped monthly panel (N=20 maturities,
T=360 months) — as a batch of independent parameter draws evaluated in one
jit+vmap'd scan on the accelerator, against a single-thread NumPy oracle that
mirrors the reference's per-step CPU loop (BLAS pinned to 1 thread,
/root/reference/test.jl:15-18).

Prints ONE JSON line:
  {"metric": ..., "value": <device evals/sec>, "unit": "evals/s",
   "vs_baseline": <device/CPU speedup>}

Robustness: this container reaches its single TPU through the axon PJRT relay,
whose backend init can wedge indefinitely if a previous client died holding
the claim.  The measurement therefore runs in a watchdog subprocess
(BENCH_DEVICE_TIMEOUT, default 900 s); on timeout/failure it reruns itself on
CPU (JAX, still jit+vmap batched) so the driver always gets its JSON line.
"""

import json
import math
import os
import subprocess
import sys
import time
from functools import partial

os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")
os.environ.setdefault("MKL_NUM_THREADS", "1")

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmarks"))
import common as _common  # noqa: E402  (shared grad-agreement criterion)

BATCH = int(os.environ.get("BENCH_BATCH", "1024"))
N_MATURITIES = 20
T_MONTHS = 360
CPU_EVALS = int(os.environ.get("BENCH_CPU_EVALS", "3"))

MATURITIES = np.array([3, 6, 9, 12, 15, 18, 21, 24, 30, 36, 48, 60, 72, 84,
                       96, 108, 120, 180, 240, 360], dtype=np.float64) / 12.0


def make_panel(seed=0, T=T_MONTHS):
    """Synthetic Liu–Wu-shaped panel from a stationary 5-factor AFNS DGP.
    ``T`` overrides the monthly default for the long-panel bench
    (``BENCH_LONGT``: daily/intraday-scale histories, T up to 20k)."""
    rng = np.random.default_rng(seed)
    lam1, lam2 = 0.5, 0.15
    Z = np.ones((N_MATURITIES, 5))
    for col, lam in ((1, lam1), (3, lam2)):
        tau = lam * MATURITIES
        Z[:, col] = (1 - np.exp(-tau)) / tau
        Z[:, col + 1] = Z[:, col] - np.exp(-tau)
    Phi = np.diag([0.98, 0.94, 0.9, 0.92, 0.88])
    delta = np.array([0.08, -0.06, 0.03, -0.02, 0.01])
    x = np.linalg.solve(np.eye(5) - Phi, delta)
    data = np.zeros((N_MATURITIES, T))
    for t in range(T):
        x = delta + Phi @ x + 0.05 * rng.standard_normal(5)
        data[:, t] = Z @ x + 0.02 * rng.standard_normal(N_MATURITIES)
    return data + 4.0


def make_param_batch(spec, B, seed=1):
    rng = np.random.default_rng(seed)
    p = np.zeros(spec.n_params)
    p[0], p[1] = math.log(0.5), math.log(0.15)
    p[2] = 4e-4
    k = 3
    for j in range(5):
        for i in range(j + 1):
            p[k] = 0.05 + 0.01 * i if i == j else 0.002
            k += 1
    p[18:23] = [4.0, -1.0, 0.5, -0.3, 0.2]
    p[23:48] = np.diag([0.98, 0.94, 0.9, 0.92, 0.88]).reshape(-1)
    batch = np.tile(p, (B, 1))
    # jitter the decay drivers and transition diagonal per draw (stationary)
    batch[:, 0:2] += 0.1 * rng.standard_normal((B, 2))
    for idx in (23, 29, 35, 41, 47):
        batch[:, idx] = np.clip(batch[:, idx] + 0.01 * rng.standard_normal(B), 0.5, 0.995)
    return batch


# --------------------------------------------------------------------------
# single-thread CPU oracle (the reference-equivalent per-step loop)
# --------------------------------------------------------------------------

def cpu_loglik(Z, adj, Phi, delta, Omega_state, obs_var, data):
    N, T = data.shape
    Ms = Phi.shape[0]
    Omega_obs = obs_var * np.eye(N)
    beta = np.linalg.solve(np.eye(Ms) - Phi, delta)
    P = np.linalg.solve(np.eye(Ms * Ms) - np.kron(Phi, Phi),
                        Omega_state.reshape(-1)).reshape(Ms, Ms)
    loglik = 0.0
    c = N * math.log(2 * math.pi)
    for t in range(T - 1):
        y = data[:, t]
        v = y - (Z @ beta + adj)
        F = Z @ P @ Z.T + Omega_obs
        F_inv = np.linalg.inv(F)
        K = P @ Z.T @ F_inv
        beta = delta + Phi @ (beta + K @ v)
        P = Phi @ ((np.eye(Ms) - K @ Z) @ P) @ Phi.T + Omega_state
        if t > 0:
            _, logdet = np.linalg.slogdet(F)
            loglik -= 0.5 * (logdet + v @ F_inv @ v + c)
    return loglik


def main():
    import jax
    import jax.numpy as jnp

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.models import api
    from yieldfactormodels_jl_tpu.models.afns import afns_loadings, yield_adjustment
    from yieldfactormodels_jl_tpu.models.params import unpack_kalman

    spec, _ = create_model("AFNS5", tuple(MATURITIES), float_type="float32")
    data = make_panel()
    batch = make_param_batch(spec, BATCH)

    # ---- CPU baseline: single-thread per-step loop, float64 ----
    kp0 = unpack_kalman(spec, jnp.asarray(batch[0], dtype=jnp.float64)
                        if jax.config.jax_enable_x64 else jnp.asarray(batch[0]))
    Z0 = np.asarray(afns_loadings(jnp.asarray(batch[0, 0:2]), jnp.asarray(MATURITIES), 5),
                    dtype=np.float64)
    Om0 = np.asarray(kp0.Omega_state, dtype=np.float64)
    adj0 = np.asarray(yield_adjustment(jnp.asarray(batch[0, 0:2]), jnp.asarray(Om0),
                                       jnp.asarray(MATURITIES), 5), dtype=np.float64)
    t0 = time.perf_counter()
    for _ in range(CPU_EVALS):
        ll_cpu = cpu_loglik(Z0, adj0, np.asarray(kp0.Phi, dtype=np.float64),
                            np.asarray(kp0.delta, dtype=np.float64), Om0,
                            float(kp0.obs_var), data)
    cpu_per_eval = (time.perf_counter() - t0) / CPU_EVALS
    cpu_evals_per_sec = 1.0 / cpu_per_eval

    # ---- device: one jit+vmap batch ----
    # The public api.get_loss kalman path is the univariate sequential-update
    # kernel (rank-1 FMAs, Cholesky-free); the joint-form filter is timed too
    # as a cross-check.  The headline number is the public-API path.
    from yieldfactormodels_jl_tpu.models import kalman as kalman_joint

    dev_data = jnp.asarray(data, dtype=spec.dtype)
    dev_batch = jnp.asarray(batch, dtype=spec.dtype)

    def timed(fn, arg=None):
        """fn: jitted batch function (B, n_params) -> (B,)."""
        if arg is None:
            arg = dev_batch
        out = jax.block_until_ready(fn(arg))  # compile + warm
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(arg)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps, out

    def batch_fn(loss_fn):
        return jax.jit(jax.vmap(lambda p: loss_fn(spec, p, dev_data)))

    dev_time, out = timed(batch_fn(api.get_loss))
    t_joint, out_joint = timed(batch_fn(kalman_joint.get_loss))

    # Pallas fused kernel (Mosaic, TPU only): the headline switches to it when
    # it compiles and cross-checks against the univariate path.
    if jax.devices()[0].platform == "tpu":
        from yieldfactormodels_jl_tpu.ops import pallas_kf

        # tile-rows sweep: the kernel is latency-bound on its serial
        # dependency chain, so wider tiles (more independent vregs per op)
        # can pipeline better — keep whichever wins (BASELINE.md roofline).
        # Per-variant try/except: a Mosaic failure on one width (e.g. VMEM
        # pressure at rows=32) must not discard a working variant.
        best = None
        rows_ctx = []
        for rows in (8, 16, 32):
            try:
                t_r, out_r = timed(jax.jit(partial(
                    pallas_kf.batched_loglik, spec, data=dev_data,
                    tile_rows=rows)))
                rows_ctx.append(f"rows{rows}={BATCH / t_r:.0f}")
                if best is None or t_r < best[0]:
                    best = (t_r, out_r, rows)
            except Exception as e:
                rows_ctx.append(f"rows{rows}=failed({type(e).__name__})")
        if best is not None:
            t_pallas, out_pallas, best_rows = best
            pallas_rate = (f"{BATCH / t_pallas:.2f} "
                           f"[{' '.join(rows_ctx)}; best rows={best_rows}]")
        else:
            out_pallas, pallas_rate = None, f"failed [{' '.join(rows_ctx)}]"
    else:
        out_pallas, pallas_rate = None, "skipped (interpret)"
    # ---- gradient engines: value+grad per eval (the MLE hot path) ----
    # fused = differentiable Pallas kernel (ops/pallas_kf_grad); reference
    # point = vmapped jax.value_and_grad through the univariate scan.
    from yieldfactormodels_jl_tpu.estimation.optimize import fused_objectives
    from yieldfactormodels_jl_tpu.models.params import untransform_params
    from yieldfactormodels_jl_tpu.ops import univariate_kf

    raw_batch = jax.jit(jax.vmap(lambda c: untransform_params(spec, c)))(dev_batch)
    grad_ctx = ""
    try:
        if jax.devices()[0].platform != "tpu":
            # CPU-fallback rounds must still emit adjoint-correctness evidence
            # (VERDICT r3 item 6: two consecutive fallback BENCH files carried
            # zero signal for exactly the path under suspicion).  Tiny
            # interpret-mode f64 grad parity — the same contract
            # tests/test_pallas_grad.py pins, small enough for the watchdog.
            # Runs in a SUBPROCESS: it needs jax_enable_x64 at import, which
            # must not leak into this process's remaining sections, and its
            # own failure modes (the N=20 interpret-grad graph stalled
            # XLA:CPU >35 min before the shapes were cut to N=5) stay
            # bounded by the 600 s timeout instead of eating the watchdog.
            genv = {**os.environ, "JAX_ENABLE_X64": "1"}
            # pin the child to CPU explicitly: without this it would
            # auto-register the axon plugin and dial the TPU tunnel, and a
            # child SIGKILLed by the timeout while holding the relay claim
            # wedges the TPU (CLAUDE.md TPU access rules) — CPU-pinned, the
            # hard timeout is safe
            genv["JAX_PLATFORMS"] = "cpu"
            genv.pop("PALLAS_AXON_POOL_IPS", None)
            # never let a persistent compile cache serve host-specific
            # XLA:CPU AOT artifacts across containers (SIGILL risk —
            # see benchmarks/hw_verify.py); device callers like
            # device_recover.py export this for the TPU steps
            genv.pop("JAX_COMPILATION_CACHE_DIR", None)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--grad-parity"],
                env=genv, capture_output=True, text=True, timeout=600)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            grad_ctx = (f"; {tail}" if "grad-parity" in tail else
                        f"; grad-parity subprocess failed rc="
                        f"{proc.returncode} ({tail[:200]})")
            grad_ctx += "; grad throughput skipped (interpret-mode off-TPU)"
        else:
            _, fused_vag = fused_objectives(spec, dev_data, 0, dev_data.shape[1])
            t_fused_vg, (fv, fg) = timed(jax.jit(fused_vag), arg=raw_batch)

            def vmap_vag(X):
                def single(r):
                    from yieldfactormodels_jl_tpu.models.params import transform_params
                    v = -univariate_kf.get_loss(spec, transform_params(spec, r),
                                                dev_data)
                    return jnp.where(jnp.isfinite(v), v, 1e12)
                return jax.vmap(jax.value_and_grad(single))(X)

            t_vmap_vg, (vv, vg) = timed(jax.jit(vmap_vag), arg=raw_batch)
            bg = np.isfinite(np.asarray(fv)) & (np.asarray(fv) < 1e12) & \
                np.isfinite(np.asarray(vv)) & (np.asarray(vv) < 1e12)
            # elementwise comparison is meaningless here (f32 cancellation
            # noise); the shared direction+norm criterion lives in
            # benchmarks/common.py
            vg_agree, _ = _common.grad_agreement(np.asarray(fg)[bg],
                                                 np.asarray(vg)[bg])
            grad_ctx = (f"; grad evals/s: fused {BATCH / t_fused_vg:.2f} | "
                        f"vmap-AD {BATCH / t_vmap_vg:.2f}; grads agree: {vg_agree}")
    except Exception as e:  # never kill the bench line
        grad_ctx += f"; grad bench failed ({type(e).__name__}: {e})"

    # ---- score-driven flagship (the reference's OWN hot path) ----
    # 1SSD-NNS (test.jl:22-27): one lax.scan whose every step takes an inner
    # jax.grad of the neural measurement loss — value+grad here is
    # second-order AD through the scan, the hardest kernel in the repo
    # (SURVEY §2.6).  Throughput rides the same vmap batching thesis.
    ssd_ctx = ""
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
        sspec, _ = create_model("1SSD-NNS", tuple(MATURITIES),
                                float_type="float32")
        sb = 256 if on_tpu else 32
        srng = np.random.default_rng(5)
        sp = np.asarray(_common.ssd_nns_params(sspec), dtype=np.float64)
        sbatch = jnp.asarray(
            np.tile(sp, (sb, 1))
            + 0.01 * srng.standard_normal((sb, sspec.n_params)),
            dtype=sspec.dtype)
        sval = jax.jit(jax.vmap(lambda p: api.get_loss(sspec, p, dev_data)))
        t_sv, out_sv = timed(sval, arg=sbatch)
        sfin = int(np.isfinite(np.asarray(out_sv)).sum())
        if on_tpu:
            # the fused value kernel (ops/pallas_ssd): whole pass per grid
            # program — the config-6 latency fix; cross-checked loosely
            # (recursion amplifies f32 rounding, tests/test_pallas_ssd.py)
            try:
                from yieldfactormodels_jl_tpu.ops.pallas_ssd import (
                    batched_loss as ssd_kernel)

                t_sk, out_sk = timed(jax.jit(partial(
                    ssd_kernel, sspec, data=dev_data)), arg=sbatch)
                bk = np.isfinite(np.asarray(out_sv)) & \
                    np.isfinite(np.asarray(out_sk))
                k_agree = bool(bk.any()) and np.allclose(
                    np.asarray(out_sk)[bk], np.asarray(out_sv)[bk], rtol=2e-2)
                skern = (f" | pallas-value {sb / t_sk:.2f} "
                         f"(agree={k_agree})")
            except Exception as e:
                skern = f" | pallas-value failed ({type(e).__name__})"
        else:
            skern = ""
        if on_tpu:
            svag = jax.jit(jax.vmap(jax.value_and_grad(
                lambda p: api.get_loss(sspec, p, dev_data))))
            t_sg, _ = timed(svag, arg=sbatch)
            sgrad = f" | value+grad {sb / t_sg:.2f} (2nd-order AD through the scan)"
        else:
            # the grad-of-grad compile alone costs ~35 s on CPU; skip it on
            # the fallback path so the watchdog budget stays safe (same
            # reasoning as the fused grad bench above)
            sgrad = " | value+grad skipped (cpu fallback: compile-heavy)"
        ssd_ctx = (f"; 1SSD-NNS (batch {sb}) evals/s: value {sb / t_sv:.2f}"
                   f"{skern}{sgrad}, finite {sfin}/{sb}")
    except Exception as e:  # never kill the bench line
        ssd_ctx = f"; ssd bench failed ({type(e).__name__}: {e})"

    # ---- online serving microbenchmark (opt-in: BENCH_SERVING=1) ----
    # p50/p99 update+forecast latency at the headline config through the
    # serving layer's precompiled programs (serving/) — a context line only,
    # the stdout JSON schema is unchanged.  Runs inside the same watchdog/
    # CPU-fallback orchestration as everything else in main().
    serving_ctx = ""
    if os.environ.get("BENCH_SERVING", "0") not in ("0", ""):
        try:
            from yieldfactormodels_jl_tpu.serving import (YieldCurveService,
                                                          freeze_snapshot)

            reps = int(os.environ.get("BENCH_SERVING_REPS", "200"))
            snap = freeze_snapshot(spec, dev_batch[0], dev_data)
            svc = YieldCurveService(snap)
            svc.warmup(horizons=(12,), batch_sizes=(1,))
            for i in range(reps):
                svc.update(i, dev_data[:, i % T_MONTHS])
                svc.forecast(12)
            s = svc.latency_summary()
            serving_ctx = (
                f"; serving latency ms (reps={reps}): "
                f"update p50 {s['update']['p50'] * 1e3:.3f} / "
                f"p99 {s['update']['p99'] * 1e3:.3f} | "
                f"forecast-h12 p50 {s['forecast']['p50'] * 1e3:.3f} / "
                f"p99 {s['forecast']['p99'] * 1e3:.3f}")
        except Exception as e:  # never kill the bench line
            serving_ctx = f"; serving bench failed ({type(e).__name__}: {e})"

    # ---- orchestration microbenchmark (opt-in: BENCH_ORCH=1) ----
    # tasks/sec and chaos-resume overhead for a 2-worker in-process rolling
    # run through the leased queue (orchestration/).  Runs in a CPU-pinned
    # subprocess (same idiom as the grad-parity child): the workload is
    # host-side coordination + tiny RW predicts, and a TPU claim for it
    # would violate the relay-safety rules for zero benefit.
    orch_ctx = ""
    if os.environ.get("BENCH_ORCH", "0") not in ("0", ""):
        try:
            oenv = {**os.environ, "JAX_PLATFORMS": "cpu"}
            oenv.pop("PALLAS_AXON_POOL_IPS", None)
            oenv.pop("JAX_COMPILATION_CACHE_DIR", None)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--orch-bench"],
                env=oenv, capture_output=True, text=True, timeout=600)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            orch_ctx = (f"; {tail}" if "orch-bench" in tail else
                        f"; orch-bench subprocess failed rc="
                        f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            orch_ctx = f"; orch bench failed ({type(e).__name__}: {e})"

    # ---- sustained-load harness (opt-in: BENCH_LOAD=1) ----
    # closed-loop mixed traffic (updates / forecasts / scenario fans) through
    # the resilient gateway (serving/gateway.py) with the request-path chaos
    # seams ARMED (slow_update latency injection + queue_stall worker
    # stalls): max sustained QPS from an unpaced capacity probe, then a paced
    # run at ~1.25x capacity so backpressure/shedding/deadline-degradation
    # actually exercise.  Every failure must surface as a shed, degraded, or
    # structured-error response — an unhandled exception fails the section.
    load_ctx = ""
    if os.environ.get("BENCH_LOAD", "0") not in ("0", ""):
        try:
            from yieldfactormodels_jl_tpu.orchestration import chaos as _chaos
            from yieldfactormodels_jl_tpu.robustness import loadgen
            from yieldfactormodels_jl_tpu.serving import (ServingGateway,
                                                          YieldCurveService,
                                                          freeze_snapshot)

            dur = float(os.environ.get("BENCH_LOAD_SECONDS", "2.0"))
            chaos_spec = os.environ.get(
                "BENCH_LOAD_CHAOS", "slow_update:0.05,queue_stall:0.05")
            from yieldfactormodels_jl_tpu.serving import BucketLattice

            lsvc = YieldCurveService(
                freeze_snapshot(spec, dev_batch[0], dev_data),
                lattice=BucketLattice(horizons=(8,), batch_sizes=(1, 4, 16),
                                      scenario_counts=(8,)),
                self_heal=True)
            # stall (300 ms) > queue_age (250 ms) > typical flush: a fired
            # queue_stall ages the head past the admission limit (sheds) and
            # past queued deadlines (degraded answers) — the seams must
            # actually exercise the degradation paths, not just tick counters
            gw = ServingGateway(lsvc, queue_max=64, queue_age_ms=250.0,
                                deadline_ms=250.0, slow_update_s=0.05,
                                queue_stall_s=0.30)
            # the WHOLE lattice (service.warmup's batch_sizes default is
            # (1,)): a mid-run compile would spike the flush-cost estimate
            lsvc.warmup(batch_sizes=(1, 4, 16), scenario_counts=(8,))
            cap = loadgen.measure_capacity(gw, dev_data, n=96)
            _chaos.configure(chaos_spec, seed=0)
            try:
                rep = loadgen.run_load(gw, dev_data, duration_s=dur,
                                       offered_qps=1.25 * cap,
                                       horizon=8, n_scenarios=8)
            finally:
                _chaos.reset()
            rep.max_sustained_qps = round(cap, 2)
            print(f"# sustained-load[chaos={chaos_spec}]: "
                  + json.dumps(rep.to_dict()), file=sys.stderr)
            load_ctx = (
                f"; sustained-load (chaos-armed): p50 {rep.p50_ms:.2f} / "
                f"p99 {rep.p99_ms:.2f} / p999 {rep.p999_ms:.2f} ms, "
                f"max sustained {cap:.1f} qps, shed {100 * rep.shed_rate:.1f}%"
                f", degraded {100 * rep.degraded_rate:.1f}%")
        except Exception as e:  # never kill the bench line
            load_ctx = f"; load bench failed ({type(e).__name__}: {e})"
        # mesh-scaling dimension (DESIGN §16): sharded-store throughput vs
        # mesh size at fixed total registry capacity.  Always a CPU-pinned
        # subprocess with the 8-virtual-device mesh (the single-chip relay
        # exposes no multi-device mesh; the honest stamp rides the JSON) —
        # XLA_FLAGS must precede jax init, hence the subprocess.
        try:
            menv = {**os.environ, "JAX_PLATFORMS": "cpu"}
            menv.pop("PALLAS_AXON_POOL_IPS", None)
            menv.pop("JAX_COMPILATION_CACHE_DIR", None)
            menv["XLA_FLAGS"] = (menv.get("XLA_FLAGS", "")
                                 + " --xla_force_host_platform_device_"
                                   "count=8").strip()
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--load-mesh-bench"],
                env=menv, capture_output=True, text=True, timeout=900)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            load_ctx += ("; " + tail if "load-mesh-bench" in tail else
                         f"; load-mesh-bench subprocess failed rc="
                         f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            load_ctx += f"; load-mesh bench failed ({type(e).__name__}: {e})"
        # working-set dimension (DESIGN §21): the tiered store's capacity
        # ledger — hit rate, promotion latency, and states-per-chip when the
        # working set overflows hot residency.  Same CPU-pinned
        # 8-virtual-device subprocess recipe as the mesh sweep.
        try:
            tenv = {**os.environ, "JAX_PLATFORMS": "cpu"}
            tenv.pop("PALLAS_AXON_POOL_IPS", None)
            tenv.pop("JAX_COMPILATION_CACHE_DIR", None)
            tenv["XLA_FLAGS"] = (tenv.get("XLA_FLAGS", "")
                                 + " --xla_force_host_platform_device_"
                                   "count=8").strip()
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--load-tier-bench"],
                env=tenv, capture_output=True, text=True, timeout=900)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            load_ctx += ("; " + tail if "load-tier-bench" in tail else
                         f"; load-tier-bench subprocess failed rc="
                         f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            load_ctx += f"; load-tier bench failed ({type(e).__name__}: {e})"
        # streaming dimension (DESIGN §23): the scenario-subscription hub's
        # delta-refresh ratio — sustained fan answers/sec vs the per-update
        # full stress_fan recompute, plus refresh p50/p99 and answer-time
        # staleness p99.  Same CPU-pinned 8-virtual-device subprocess
        # recipe as the mesh/tier sweeps.
        try:
            fenv = {**os.environ, "JAX_PLATFORMS": "cpu"}
            fenv.pop("PALLAS_AXON_POOL_IPS", None)
            fenv.pop("JAX_COMPILATION_CACHE_DIR", None)
            fenv["XLA_FLAGS"] = (fenv.get("XLA_FLAGS", "")
                                 + " --xla_force_host_platform_device_"
                                   "count=8").strip()
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--load-fan-bench"],
                env=fenv, capture_output=True, text=True, timeout=900)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            load_ctx += ("; " + tail if "load-fan-bench" in tail else
                         f"; load-fan-bench subprocess failed rc="
                         f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            load_ctx += f"; load-fan bench failed ({type(e).__name__}: {e})"
        # recovery dimension (DESIGN §24): shard-loss fault domains — kill
        # shards mid-sustained-load, measure detection→rebuilt MTTR p50/p99
        # and the degraded-answer rate, and verify zero lost accepted
        # updates against a fault-free twin.  Same CPU-pinned
        # 8-virtual-device subprocess recipe as the other load columns.
        try:
            renv = {**os.environ, "JAX_PLATFORMS": "cpu"}
            renv.pop("PALLAS_AXON_POOL_IPS", None)
            renv.pop("JAX_COMPILATION_CACHE_DIR", None)
            renv["XLA_FLAGS"] = (renv.get("XLA_FLAGS", "")
                                 + " --xla_force_host_platform_device_"
                                   "count=8").strip()
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--load-recovery-bench"],
                env=renv, capture_output=True, text=True, timeout=900)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            load_ctx += ("; " + tail if "load-recovery-bench" in tail else
                         f"; load-recovery-bench subprocess failed rc="
                         f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            load_ctx += (f"; load-recovery bench failed "
                         f"({type(e).__name__}: {e})")

    # ---- long-panel engine split (opt-in: BENCH_LONGT=1) ----
    # sequential univariate scan vs the O(log T) associative-scan engine at
    # T in {360, 5k, 20k} (docs/DESIGN.md §13) — the engine-dispatch policy's
    # evidence base: where the tree starts beating the scan.  On TPU it runs
    # IN-PROCESS (ONE client at a time — a subprocess would race this
    # process for the relay claim, CLAUDE.md TPU rules); on fallback rounds
    # a CPU-pinned subprocess gets the 8-virtual-device mesh (XLA_FLAGS must
    # precede jax init) so the time-sharded line is exercised like the
    # MULTICHIP dry-runs.  The main JSON's device_fallback/fallback_reason
    # stamp covers this section like every other.
    longt_ctx = ""
    if os.environ.get("BENCH_LONGT", "0") not in ("0", ""):
        try:
            if jax.devices()[0].platform == "tpu":
                longt_ctx = "; " + _longt_line()
            else:
                lenv = {**os.environ, "JAX_PLATFORMS": "cpu"}
                lenv.pop("PALLAS_AXON_POOL_IPS", None)
                lenv.pop("JAX_COMPILATION_CACHE_DIR", None)
                lenv["XLA_FLAGS"] = (lenv.get("XLA_FLAGS", "")
                                     + " --xla_force_host_platform_device_"
                                       "count=8").strip()
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--longt-bench"],
                    env=lenv, capture_output=True, text=True, timeout=900)
                tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
                longt_ctx = (f"; {tail}" if "longt-bench" in tail else
                             f"; longt-bench subprocess failed rc="
                             f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            longt_ctx = f"; longt bench failed ({type(e).__name__}: {e})"

    # ---- fused scenario lattice (opt-in: BENCH_SCEN=1) ----
    # ROADMAP item 4 / docs/DESIGN.md §14: the (resample × λ) bootstrap
    # plane, the SV particle-filter draw sweep, and the six-shock stress fan
    # — BASELINE configs 5 and 3 plus the serving fan — as ONE donated,
    # compile-once program, head-to-head against the SUM of the separate
    # drivers' walls on the same backend (all warm; the drivers pay their
    # own index generation / transfer / stat dispatch rounds and one launch
    # per shock, which is exactly what fusion deletes — on the TPU relay
    # every extra launch also pays the network round-trip).  p50 of
    # BENCH_SCEN_REPS walls; a second figure isolates the fan ratio.
    scen_ctx = ""
    if os.environ.get("BENCH_SCEN", "0") not in ("0", ""):
        try:
            from tests.oracle import stable_ns_params
            from yieldfactormodels_jl_tpu.estimation import scenario as _scen
            from yieldfactormodels_jl_tpu.estimation.bootstrap import (
                bootstrap_lambda_grid)
            from yieldfactormodels_jl_tpu.parallel.mesh import (
                particle_filter_sharded)

            R = int(os.environ.get("BENCH_SCEN_R", "256"))
            G = int(os.environ.get("BENCH_SCEN_G", "16"))
            D = int(os.environ.get("BENCH_SCEN_D", "8"))
            PN = int(os.environ.get("BENCH_SCEN_PARTICLES", "128"))
            sreps = int(os.environ.get("BENCH_SCEN_REPS", "5"))
            nspec, _ = create_model("NS", tuple(MATURITIES),
                                    float_type="float32")
            ns_p = stable_ns_params(nspec)
            grid = np.linspace(0.15, 1.0, G)
            kdraws = _common.stationary_draws(spec, np.asarray(dev_batch[0]),
                                              D, scale=0.02)
            skey = jax.random.PRNGKey(0)
            fan_shocks = _scen.standard_fan(spec)
            fh, fn_ = 12, 32

            def run_lat(prev):
                # the config-3 + config-5 union ONLY — the acceptance
                # comparison; the fan is isolated below (in-module it
                # schedules worse on XLA:CPU than its standalone program,
                # so folding it in would blur the config-3/5 head-to-head)
                return _scen.evaluate_lattice(
                    dev_data, static_spec=nspec, static_params=ns_p,
                    lambda_grid=grid, n_resamples=R, kalman_spec=spec,
                    kalman_params=dev_batch[0],
                    sv_draws=(prev["sv_draws"] if prev else kdraws),
                    n_particles=PN, key=skey, recycle=prev)

            # the separate drivers: config-5, config-3, and one launch per
            # shock (serving's historical fan)
            from yieldfactormodels_jl_tpu.ops.smoother import forward_moments
            _, mouts = forward_moments(spec, dev_batch[0], dev_data, 0,
                                       dev_data.shape[1], "univariate")
            fb, fP = mouts["beta_upd"][-1], mouts["P_upd"][-1]

            def run_boot():
                return jax.block_until_ready(bootstrap_lambda_grid(
                    nspec, ns_p, dev_data, grid, n_resamples=R, key=skey))

            def run_pf():
                return jax.block_until_ready(particle_filter_sharded(
                    spec, kdraws, dev_data, n_particles=PN))

            def run_fan_per_shock():
                return [jax.block_until_ready(_scen.stress_fan(
                    spec, dev_batch[0], fb, fP, (s,), fh, fn_, key=skey))
                    for s in fan_shocks]

            def one_fan():
                return jax.block_until_ready(_scen.stress_fan(
                    spec, dev_batch[0], fb, fP, fan_shocks, fh, fn_,
                    key=skey))

            # warm/compile everything, then INTERLEAVE fused and driver reps
            # so background contention on this 1-core box drifts into both
            # sides equally (CLAUDE.md: pinned measurements contend)
            sout = jax.block_until_ready(run_lat(None))
            run_boot(), run_pf(), run_fan_per_shock(), one_fan()
            walls, wb, wp, wfS, wf1 = [], [], [], [], []
            for _ in range(sreps):
                t0 = time.perf_counter()
                sout = jax.block_until_ready(run_lat(sout))
                walls.append(time.perf_counter() - t0)
                t0 = time.perf_counter(); run_boot()
                wb.append(time.perf_counter() - t0)
                t0 = time.perf_counter(); run_pf()
                wp.append(time.perf_counter() - t0)
                t0 = time.perf_counter(); run_fan_per_shock()
                wfS.append(time.perf_counter() - t0)
                t0 = time.perf_counter(); one_fan()
                wf1.append(time.perf_counter() - t0)
            w_fused = float(np.median(walls))
            w_boot, w_pf = float(np.median(wb)), float(np.median(wp))
            w_fanS, w_fan1 = float(np.median(wfS)), float(np.median(wf1))
            cells = R * G + D
            ratio = (w_boot + w_pf) / w_fused
            scen_ctx = (
                f"; scenario-lattice[R={R} G={G} D={D}x{PN}p]: fused "
                f"{w_fused * 1e3:.0f} ms p50 ({cells / w_fused:.0f} cells/s)"
                f" vs config-5+3 drivers {w_boot * 1e3:.0f}+"
                f"{w_pf * 1e3:.0f} ms -> {ratio:.2f}x; stress-fan[S="
                f"{len(fan_shocks)} h={fh} n={fn_}]: one-launch "
                f"{w_fan1 * 1e3:.1f} ms vs per-shock {w_fanS * 1e3:.1f} ms "
                f"-> {w_fanS / w_fan1:.2f}x")
        except Exception as e:  # never kill the bench line
            scen_ctx = f"; scen bench failed ({type(e).__name__}: {e})"

    # ---- second-order multi-start MLE (opt-in: BENCH_NEWTON=1) ----
    # LBFGS-only vs the coarse-LBFGS -> trust-region-Newton cascade
    # (ops/newton.py, docs/DESIGN.md §17) at matched g_tol on the
    # config-2-shaped multi-start.  ALWAYS a CPU-pinned float64 subprocess
    # (the comparison is an optimizer-convergence claim, not a device
    # throughput claim; matched-tolerance convergence in f32 is
    # noise-bound) — the main JSON's device_fallback stamp covers it.
    newton_ctx = ""
    if os.environ.get("BENCH_NEWTON", "0") not in ("0", ""):
        try:
            nenv = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "JAX_ENABLE_X64": "1"}
            nenv.pop("PALLAS_AXON_POOL_IPS", None)
            nenv.pop("JAX_COMPILATION_CACHE_DIR", None)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--newton-bench"],
                env=nenv, capture_output=True, text=True, timeout=3600)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            newton_ctx = (f"; {tail}" if "newton-bench" in tail else
                          f"; newton-bench subprocess failed rc="
                          f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            newton_ctx = f"; newton bench failed ({type(e).__name__}: {e})"

    # ---- amortized estimation (opt-in: BENCH_AMORT=1) ----
    # train-once surrogate + warm amortized+polish vs cold LBFGS-only at
    # matched g_tol (docs/DESIGN.md §20).  ALWAYS a CPU-pinned float64
    # subprocess — the same optimizer-convergence-claim rationale as
    # BENCH_NEWTON; the main JSON's device_fallback stamp covers it.
    amort_ctx = ""
    if os.environ.get("BENCH_AMORT", "0") not in ("0", ""):
        try:
            aenv = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "JAX_ENABLE_X64": "1"}
            aenv.pop("PALLAS_AXON_POOL_IPS", None)
            aenv.pop("JAX_COMPILATION_CACHE_DIR", None)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--amort-bench"],
                env=aenv, capture_output=True, text=True, timeout=3600)
            tail = (proc.stdout.strip().splitlines() or ["no output"])[-1]
            amort_ctx = (f"; {tail}" if "amort-bench" in tail else
                         f"; amort-bench subprocess failed rc="
                         f"{proc.returncode} ({tail[:200]})")
        except Exception as e:  # never kill the bench line
            amort_ctx = f"; amort bench failed ({type(e).__name__}: {e})"

    # ---- robustness microbenchmark (opt-in: BENCH_ROBUST=1) ----
    # (a) healthy-path cost of the failure-taxonomy channel: the same jitted
    # batch evaluated through get_loss vs get_loss_coded — the codes ride
    # carries the kernels already thread, so the ratio must be ≈1 (and plain
    # get_loss callers have the code DCE'd entirely); (b) p50/p99 of a
    # chaos-injected serving rebuild (YFM_CHAOS numeric seam nan_curve →
    # health watch → last-good restore), the recovery path priced.
    robust_ctx = ""
    if os.environ.get("BENCH_ROBUST", "0") not in ("0", ""):
        try:
            from yieldfactormodels_jl_tpu.ops import univariate_kf

            t_plain, _ = timed(batch_fn(univariate_kf.get_loss))
            t_coded, _ = timed(jax.jit(jax.vmap(
                lambda p: univariate_kf.get_loss_coded(spec, p, dev_data))))

            from yieldfactormodels_jl_tpu.orchestration import chaos as _chaos
            from yieldfactormodels_jl_tpu.serving import (YieldCurveService,
                                                          freeze_snapshot)

            reps = int(os.environ.get("BENCH_ROBUST_REPS", "200"))
            svc = YieldCurveService(
                freeze_snapshot(spec, dev_batch[0], dev_data),
                self_heal=True)
            svc.warmup()
            _chaos.configure("nan_curve:0.1", seed=0)
            for i in range(reps):
                svc.update(i, dev_data[:, i % T_MONTHS])
            _chaos.reset()
            s = svc.latency_summary()
            rb = s.get("rebuild", {"p50": float("nan"), "p99": float("nan")})
            robust_ctx = (
                f"; robustness: coded-loss overhead {t_coded / t_plain:.3f}x "
                f"({BATCH / t_coded:.2f} vs {BATCH / t_plain:.2f} evals/s); "
                f"chaos-injected rebuilds {svc.rebuilds}/{reps} updates, "
                f"rebuild ms p50 {rb['p50'] * 1e3:.3f} / "
                f"p99 {rb['p99'] * 1e3:.3f}")
        except Exception as e:  # never kill the bench line
            robust_ctx = f"; robust bench failed ({type(e).__name__}: {e})"

    n_finite = int(np.isfinite(np.asarray(out)).sum())
    # the joint form runs its matmuls/Cholesky through bf16 MXU passes on TPU
    # f32, so cross-check with a loose tolerance on the finite intersection
    both = np.isfinite(np.asarray(out)) & np.isfinite(np.asarray(out_joint))
    agree = bool(both.any()) and np.allclose(
        np.asarray(out)[both], np.asarray(out_joint)[both], rtol=2e-2)
    dev_evals_per_sec = BATCH / dev_time

    # ---- roofline accounting (BASELINE.md "MFU / roofline") ----
    # univariate filter, per draw per time step (Ms = state dim, N = obs):
    #   per observation: zP = Pz (2Ms²) + f (2Ms) + K (Ms) + β (2Ms)
    #                    + P -= K zPᵀ (2Ms²) + ll (≈6)  ≈ 4Ms² + 5Ms + 6
    #   transition: Φβ (2Ms²) + ΦPΦᵀ (4Ms³) + +Ω (Ms²) + symmetrize (2Ms²)
    Ms = spec.state_dim
    per_obs = 4 * Ms * Ms + 5 * Ms + 6
    per_step = N_MATURITIES * per_obs + 4 * Ms**3 + 5 * Ms * Ms + 2 * Ms
    flops_per_eval = per_step * T_MONTHS

    def gflops(rate):
        return rate * flops_per_eval / 1e9

    platform = jax.devices()[0].platform
    if out_pallas is not None:
        bp = np.isfinite(np.asarray(out)) & np.isfinite(np.asarray(out_pallas))
        pallas_agree = bool(bp.any()) and np.allclose(
            np.asarray(out)[bp], np.asarray(out_pallas)[bp], rtol=2e-2)
    else:
        pallas_agree = False
    # headline = fastest kernel that agrees with the validated univariate path
    # (the pallas fused kernel when it compiled and cross-checks)
    headline, kern = dev_evals_per_sec, "univariate"
    if out_pallas is not None and pallas_agree and BATCH / t_pallas > headline:
        headline, kern = BATCH / t_pallas, "pallas"
    # device-fallback honesty (VERDICT r3 / ROADMAP item 3: rounds r02-r05
    # silently posed CPU numbers as the trajectory): every BENCH JSON says
    # explicitly whether this was a device measurement, and why not if not —
    # the orchestrator threads its reason through BENCH_FALLBACK_REASON
    device_fallback = platform != "tpu"
    fallback_reason = ""
    if device_fallback:
        fallback_reason = os.environ.get(
            "BENCH_FALLBACK_REASON",
            f"jax platform is {platform!r} (no TPU visible to this process)")
    result = {
        "metric": f"AFNS5 Kalman loglik evals/sec (N={N_MATURITIES}, T={T_MONTHS}, "
                  f"batch={BATCH}, {platform}, {kern})",
        "value": round(headline, 2),
        "unit": "evals/s",
        "vs_baseline": round(headline / cpu_evals_per_sec, 2),
        "device_fallback": device_fallback,
        "fallback_reason": fallback_reason,
    }
    print(json.dumps(result))
    # context to stderr so stdout stays one JSON line
    print(f"# cpu 1-thread: {cpu_evals_per_sec:.2f} evals/s; device({platform}): "
          f"api/univariate {dev_evals_per_sec:.2f} | joint {BATCH / t_joint:.2f} "
          f"| pallas {pallas_rate} evals/s; kernels agree: joint={agree} "
          f"pallas={pallas_agree}; finite: {n_finite}/{BATCH}; "
          f"cpu ll sample {ll_cpu:.2f}{grad_ctx}{ssd_ctx}{serving_ctx}"
          f"{load_ctx}{orch_ctx}{longt_ctx}{scen_ctx}{newton_ctx}"
          f"{amort_ctx}{robust_ctx}; "
          f"roofline: {flops_per_eval/1e6:.3f} MFLOP/eval -> "
          f"univariate {gflops(dev_evals_per_sec):.1f} | "
          f"joint {gflops(BATCH / t_joint):.1f} | "
          f"pallas "
          f"{gflops(BATCH / t_pallas) if out_pallas is not None else float('nan'):.1f}"
          f" GFLOP/s achieved (VPU-class work; see BASELINE.md)",
          file=sys.stderr)


def _grad_parity():
    """Interpret-mode f64 adjoint parity at tiny shapes (subprocess mode —
    needs JAX_ENABLE_X64=1 at import, which must not leak into the main
    bench process; see the CPU-fallback grad section)."""
    import jax
    import jax.numpy as jnp

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.ops import pallas_kf_grad, univariate_kf

    # tiny shapes INCLUDING the maturity axis: interpret-mode pallas traces
    # the kernel body (T × N-unrolled chain, forward + checkpointed reverse)
    # into one flat XLA graph, and at N=20 that graph takes XLA:CPU tens of
    # minutes to compile; at N=5 it's seconds.  The adjoint contract is
    # shape-independent (tests/test_pallas_grad.py pins it at N=6).
    spec, _ = create_model("AFNS5", tuple(MATURITIES[::4]), float_type="float64")
    gB, gT = 4, 12
    gdata = jnp.asarray(make_panel()[::4, :gT], jnp.float64)
    gp = jnp.asarray(make_param_batch(spec, gB), jnp.float64)

    def tot_kernel(pb):
        return jnp.sum(pallas_kf_grad.batched_loglik_diff(
            spec, pb, gdata, interpret=True, dtype=jnp.float64))

    def tot_ref(pb):
        return jnp.sum(jax.vmap(
            lambda q: univariate_kf.get_loss(spec, q, gdata))(pb))

    g_got = np.asarray(jax.grad(tot_kernel)(gp))
    g_ref = np.asarray(jax.grad(tot_ref)(gp))
    ok, detail = _common.grad_agreement(g_got, g_ref,
                                        cos_min=1 - 1e-9, norm_tol=1e-6)
    print(f"grad-parity[interpret f64, B={gB} T={gT}]: "
          f"{'PASS' if ok else 'FAIL'} ({detail})")
    return 0 if ok else 1


def _longt_line():
    """Measure the BENCH_LONGT section and return its one context line:
    sequential vs associative-scan loglik evals/s at T ∈ {360, 5k, 20k},
    plus the time-sharded assoc variant (panel ``P(None, "time")`` over the
    mesh — 8 virtual devices on the CPU fallback path, whatever the real
    topology exposes on device), plus — unless ``BENCH_LONGT_TVL=0`` — the
    NONLINEAR column (docs/DESIGN.md §19): the sequential TVλ EKF vs the
    iterated-SLR engine on single-chain value+grad at the same T grid, and
    the second-order tangent split (sequential vs tree-composed Fisher HVP
    under the T-switch) at T = 5k, and — unless ``BENCH_LONGT_MSED=0`` —
    the SCORE-DRIVEN column: the sequential MSED scan vs the score-tree
    engine (ops/score_scan.py) on single-chain value+grad at the same T
    grid.  Callable both in-process (TPU rounds)
    and from the ``--longt-bench`` subprocess (CPU fallback rounds)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.ops import assoc_scan, univariate_kf
    from yieldfactormodels_jl_tpu.parallel.mesh import make_mesh

    B = int(os.environ.get("BENCH_LONGT_BATCH", "8"))
    Ts = tuple(int(t) for t in os.environ.get(
        "BENCH_LONGT_TS", "360,5000,20000").split(","))
    spec, _ = create_model("AFNS5", tuple(MATURITIES), float_type="float32")
    batch = jnp.asarray(make_param_batch(spec, B), dtype=spec.dtype)
    p1 = batch[0]
    mesh = make_mesh(axis_name="time")
    n_dev = int(mesh.devices.size)
    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P(None, "time"))

    def timed(fn, arg, reps=2):
        out = jax.block_until_ready(fn(arg))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(arg)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps, out

    parts, ratio_at_max = [], float("nan")
    for T in Ts:
        try:
            data = jnp.asarray(make_panel(seed=7, T=T), dtype=spec.dtype)
            # batched VALUE throughput (the A/B-grid / NM-probe regime)
            t_seq, out_seq = timed(jax.jit(jax.vmap(
                lambda p: univariate_kf.get_loss(spec, p, data))), batch)
            t_assoc, out_assoc = timed(jax.jit(jax.vmap(
                lambda p: assoc_scan.get_loss(spec, p, data))), batch)
            both = np.isfinite(np.asarray(out_seq)) \
                & np.isfinite(np.asarray(out_assoc))
            # loose: a 20k-term f32 sum carries real cancellation noise
            agree = bool(both.any()) and np.allclose(
                np.asarray(out_seq)[both], np.asarray(out_assoc)[both],
                rtol=2e-2)
            # single-chain VALUE+GRADIENT latency — the regime the engine
            # exists for (ISSUE/DESIGN §13: long histories latency-bound on
            # one sequential chain; reverse-mode through a T-step scan
            # replays/stashes the whole trajectory, the tree reverses as
            # vectorized passes)
            t_svg, _ = timed(jax.jit(jax.value_and_grad(
                lambda p: univariate_kf.get_loss(spec, p, data))), p1)
            t_avg, _ = timed(jax.jit(jax.value_and_grad(
                lambda p: assoc_scan.get_loss(spec, p, data))), p1)
            if T % n_dev == 0 and os.environ.get(
                    "BENCH_LONGT_SHARDED", "0") not in ("0", ""):
                # opt-in time-sharded flavor: panel P(None, "time"), params
                # replicated (the time_parallel.py layout).  Off by default:
                # on the 1-core 8-virtual-device fallback mesh the blocked
                # prefix's chunk reshape crosses shard boundaries, so the
                # collective traffic prices in with no parallel silicon to
                # pay for it — the MULTICHIP dry-runs own correctness there.
                assoc_sh_fn = jax.jit(
                    jax.vmap(lambda p, dat: assoc_scan.get_loss(spec, p, dat),
                             in_axes=(0, None)),
                    in_shardings=(repl, data_sh), out_shardings=repl)
                sharded = jax.device_put(data, data_sh)
                t_sh, _ = timed(lambda pb: assoc_sh_fn(pb, sharded), batch)
                sh_txt = f" | assoc-sharded{n_dev} {B / t_sh:.2f}"
            else:
                sh_txt = ""
            parts.append(
                f"T={T} value[B={B}] seq {B / t_seq:.2f} | assoc "
                f"{B / t_assoc:.2f}{sh_txt} evals/s (agree={agree}), "
                f"grad[1-chain] seq {t_svg * 1e3:.0f} | assoc "
                f"{t_avg * 1e3:.0f} ms")
            if T == max(Ts):
                ratio_at_max = t_svg / t_avg
        except Exception as e:  # per-T isolation: one OOM ≠ no line
            parts.append(f"T={T} failed ({type(e).__name__})")

    # ---- nonlinear (TVλ) column: sequential EKF vs iterated SLR ----
    tvl_ratio_at_max = float("nan")
    if os.environ.get("BENCH_LONGT_TVL", "1") not in ("0", ""):
        try:
            from tests.oracle import stable_tvl_params
            from yieldfactormodels_jl_tpu.ops import slr_scan

            tspec, _ = create_model("TVλ", tuple(MATURITIES),
                                    float_type="float32")
            tp = jnp.asarray(stable_tvl_params(tspec, np.float32))
        except Exception as e:
            # same isolation contract as the per-T loops: a TVλ setup
            # failure must not discard the AFNS5 parts already measured
            parts.append(f"tvl setup failed ({type(e).__name__})")
            tspec = None
        for T in Ts if tspec is not None else ():
            try:
                data = jnp.asarray(make_panel(seed=7, T=T),
                                   dtype=tspec.dtype)
                t_seq, v_seq = timed(jax.jit(jax.value_and_grad(
                    lambda p: univariate_kf.get_loss(tspec, p, data))), tp)
                t_slr, v_slr = timed(jax.jit(jax.value_and_grad(
                    lambda p: slr_scan.get_loss(tspec, p, data))), tp)
                agree = bool(np.isfinite(float(v_seq[0]))
                             and np.isclose(float(v_seq[0]),
                                            float(v_slr[0]), rtol=2e-2))
                parts.append(
                    f"tvl T={T} grad[1-chain] seq {t_seq * 1e3:.0f} | slr "
                    f"{t_slr * 1e3:.0f} ms (agree={agree})")
                if T == max(Ts):
                    tvl_ratio_at_max = t_seq / t_slr
            except Exception as e:
                parts.append(f"tvl T={T} failed ({type(e).__name__})")
        # second-order tangent split: the Fisher HVP's linearize sweep over
        # the assoc elements vs the sequential carry (the provider the
        # T-switch flips, ops/newton._innovations).  Measured on the AFNS5
        # constant-Z spec — deliberately independent of the TVλ setup
        # above, so a TVλ failure cannot suppress it.
        try:
            from yieldfactormodels_jl_tpu import config as _cfg2
            from yieldfactormodels_jl_tpu.models.params import (
                untransform_params as _untransform)
            from yieldfactormodels_jl_tpu.ops import newton as _newton2

            Tn = 5000 if 5000 in Ts else max(Ts)
            data = jnp.asarray(make_panel(seed=7, T=Tn), dtype=spec.dtype)
            raw = jnp.asarray(_untransform(spec, p1))
            u = jnp.ones_like(raw)
            hvp = jax.jit(lambda r, d_: _newton2.fisher_hvp(
                spec, r, u, d_, 0, Tn))
            t_hseq, _ = timed(lambda r: hvp(r, data), raw)
            prev_switch = _cfg2.loglik_t_switch()  # restore, don't clobber
            _cfg2.set_loglik_t_switch(1)
            try:
                hvp_t = jax.jit(lambda r, d_: _newton2.fisher_hvp(
                    spec, r, u, d_, 0, Tn))
                t_htree, _ = timed(lambda r: hvp_t(r, data), raw)
            finally:
                _cfg2.set_loglik_t_switch(prev_switch)
            parts.append(f"newton-tangent@T={Tn} fisher-hvp seq "
                         f"{t_hseq * 1e3:.0f} | tree {t_htree * 1e3:.0f} ms "
                         f"({t_hseq / t_htree:.2f}x)")
        except Exception as e:
            parts.append(f"newton-tangent failed ({type(e).__name__})")

    # ---- score-driven (MSED) column: sequential scan vs score tree ----
    msed_ratio_at_max = float("nan")
    if os.environ.get("BENCH_LONGT_MSED", "1") not in ("0", ""):
        try:
            from tests.oracle import stable_msed_params
            from yieldfactormodels_jl_tpu.models import score_driven as _sd
            from yieldfactormodels_jl_tpu.ops import score_scan

            mspec, _ = create_model("SD-NS", tuple(MATURITIES),
                                    float_type="float32")
            mparam = jnp.asarray(stable_msed_params(mspec, np.float32))
        except Exception as e:
            # same isolation contract as the TVλ setup above
            parts.append(f"msed setup failed ({type(e).__name__})")
            mspec = None
        for T in Ts if mspec is not None else ():
            try:
                data = jnp.asarray(make_panel(seed=7, T=T),
                                   dtype=mspec.dtype)
                t_seq, v_seq = timed(jax.jit(jax.value_and_grad(
                    lambda p: _sd.get_loss(mspec, p, data))), mparam)
                t_tree, v_tree = timed(jax.jit(jax.value_and_grad(
                    lambda p: score_scan.get_loss(mspec, p, data))), mparam)
                agree = bool(np.isfinite(float(v_seq[0]))
                             and np.isclose(float(v_seq[0]),
                                            float(v_tree[0]), rtol=2e-2))
                parts.append(
                    f"msed T={T} grad[1-chain] seq {t_seq * 1e3:.0f} | tree "
                    f"{t_tree * 1e3:.0f} ms (agree={agree})")
                if T == max(Ts):
                    msed_ratio_at_max = t_seq / t_tree
            except Exception as e:
                parts.append(f"msed T={T} failed ({type(e).__name__})")

    plat = jax.devices()[0].platform
    return (f"longt-bench[AFNS5, {plat} x{n_dev}]: " + "; ".join(parts)
            + f"; assoc/seq 1-chain value+grad speedup @T={max(Ts)}: "
              f"{ratio_at_max:.2f}x"
            + f"; slr/seq tvl 1-chain value+grad speedup @T={max(Ts)}: "
              f"{tvl_ratio_at_max:.2f}x"
            + f"; score_tree/seq msed 1-chain value+grad speedup "
              f"@T={max(Ts)}: {msed_ratio_at_max:.2f}x")


def _longt_bench():
    """Subprocess mode for the CPU-fallback path (the caller exports
    JAX_PLATFORMS=cpu + the 8-virtual-device XLA flag before jax inits)."""
    print(_longt_line())
    return 0


def _newton_bench():
    """Subprocess mode (CPU, float64 — exported by the caller before jax
    inits): LBFGS-only vs the two-phase second-order cascade at matched
    ``g_tol`` on the config-2-shaped multi-start (AFNS5, T=360,
    ``BENCH_NEWTON_STARTS`` perturbed stationary starts).

    The LBFGS-only side gets the REAL first-order budget
    (``BENCH_NEWTON_ITERS``, default 400 — at matched ``g_tol`` it either
    converges or demonstrably stalls on the penalty surface, which is the
    workload the cascade replaces); the cascade side uses its own internal
    coarse budget (optimize._NEWTON_COARSE_ITERS) plus the polish.  With
    ``BENCH_NEWTON_REPS=1`` (the default) the two sides compare COLD —
    compile cost included on both, conservative for the cascade since it
    compiles strictly more programs; ``reps>1`` warms both once and
    reports p50 over interleaved warm rounds (1-core contention drifts
    into both equally).

    Filter-pass eval-equivalent convention: value pass = 1, value+grad =
    3 (forward + reverse ≈ 2 value passes), backtracking probe = 1 (so an
    L-BFGS iteration ≥ 4 — an undercount when the 80-probe backtracking
    budget is burning, which favors the baseline), and one dense
    trust-region attempt = 6: the P-direction curvature sweep rides ONE
    vectorized ``jax.linearize`` scan (measured ≈2 value-pass cost at
    P≈33 on this box — NOT P separate passes) + value+grad (3) + trial
    probe (1).  The acceptance figure is ISSUE 12's: >=2x fewer
    eval-equivalents or >=1.5x lower wall p50 at matched ``g_tol``, final
    best losses matching within 1e-6 or better on the cascade side."""
    import jax
    import numpy as np

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.estimation import optimize as opt

    import jax.numpy as jnp

    from yieldfactormodels_jl_tpu.models import api

    S = int(os.environ.get("BENCH_NEWTON_STARTS", "4"))
    reps = int(os.environ.get("BENCH_NEWTON_REPS", "1"))
    max_iters = int(os.environ.get("BENCH_NEWTON_ITERS", "400"))
    g_tol = float(os.environ.get("BENCH_NEWTON_GTOL", "1e-5"))
    spec, _ = create_model("AFNS5", tuple(MATURITIES), float_type="float64")
    batch = np.asarray(make_param_batch(spec, S), dtype=np.float64)
    # the panel is simulated FROM the model at the batch's base point: the
    # matched-tolerance comparison needs an optimum both optimizers can
    # actually approach (make_panel()'s DGP offset parks every start in
    # linesearch-death at useless points — measured)
    data = np.asarray(api.simulate(spec, jnp.asarray(batch[0]), T_MONTHS,
                                   jax.random.PRNGKey(9))["data"])
    # make_param_batch returns CONSTRAINED stationary draws (S, P) -> (P, S)
    starts = batch.T
    Pn = spec.n_params

    def run(second_order):
        _, ll, _, _ = opt.estimate(spec, data, starts, max_iters=max_iters,
                                   g_tol=g_tol, f_abstol=1e-8,
                                   second_order=second_order)
        return ll, opt.last_multistart_report()

    if reps > 1:  # warm/compile both paths once, then interleave timed reps
        run(False), run("fisher")
    w_base, w_so = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); ll_base, rep_base = run(False)
        w_base.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); ll_so, rep_so = run("fisher")
        w_so.append(time.perf_counter() - t0)
    p50_base = float(np.median(w_base))
    p50_so = float(np.median(w_so))
    # eval-equivalent accounting (convention in the docstring)
    evals_base = 4.0 * sum(rep_base["iters"])
    n = rep_so["newton"] or {"iters": [0] * S, "cg_iters": [0] * S}
    coarse_iters = sum(rep_so["iters"]) - sum(n["iters"])
    # cg_iters counts curvature sweeps: P per dense-TR attempt; the sweep
    # itself is ONE vectorized linearize scan (≈2 value passes), not P
    attempts = sum(n["cg_iters"]) / max(Pn, 1)
    evals_so = 4.0 * coarse_iters + attempts * (2.0 + 3 + 1)
    match = abs(ll_base - ll_so) <= 1e-6 or ll_so >= ll_base
    print(f"newton-bench[AFNS5 f64 S={S} T={T_MONTHS} g_tol={g_tol:g}]: "
          f"lbfgs-only {p50_base:.1f} s p50 ({sum(rep_base['iters'])} iters,"
          f" {evals_base:.0f} pass-eq) vs cascade {p50_so:.1f} s p50 "
          f"({coarse_iters} coarse + {sum(n['iters'])} newton iters, "
          f"{evals_so:.0f} pass-eq) -> wall {p50_base / p50_so:.2f}x, "
          f"evals {evals_base / max(evals_so, 1.0):.2f}x; "
          f"best ll lbfgs {ll_base:.6f} vs cascade {ll_so:.6f} "
          f"(match-or-better: {match}); conv "
          f"{sum(rep_base['converged'])}/{S} vs {sum(rep_so['converged'])}/{S}")
    return 0


def _amort_bench():
    """Subprocess mode (CPU, float64 — exported by the caller before jax
    inits): the amortized warm start (docs/DESIGN.md §20) vs the cold
    LBFGS-only multi-start at matched ``g_tol`` on the config-2-shaped
    workload (AFNS5, T=360, ``BENCH_AMORT_STARTS`` stationary starts).

    Protocol: the surrogate is trained ONCE (``BENCH_AMORT_ROUNDS`` ×
    ``BENCH_AMORT_BATCH`` simulated panels — the wall is reported as
    ``train_s``, honestly separated from the per-refit walls and amortized
    into ``breakeven_refits`` = train cost / per-refit saving); the panel is
    simulated from a PRIOR DRAW (truth ≠ the surrogate's base point, so the
    forward pass must actually generalize).  The cold side runs the REAL
    first-order budget (``BENCH_AMORT_ITERS``); the warm side runs the
    amortized point + jittered neighbors + anchor through the shortened
    coarse phase and the trust-region Newton polish to the same ``g_tol``
    (second_order resolved through the SAME env helper run_all config-2
    uses — ``estimation.optimize.resolve_estimation_env`` — defaulting to
    "fisher").  ``BENCH_AMORT_REPS=1`` (default) compares COLD, compile
    included on both sides (conservative for the warm side, which compiles
    strictly more programs); >1 warms both once then interleaves.

    The acceptance figure (ISSUE 15): ≥5× end-to-end wall reduction with
    the final best NLL no worse than cold within 1e-3 nats."""
    import jax
    import numpy as np

    import jax.numpy as jnp

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.estimation import amortize as amz
    from yieldfactormodels_jl_tpu.estimation import optimize as opt
    from yieldfactormodels_jl_tpu.models import api

    S = int(os.environ.get("BENCH_AMORT_STARTS", "4"))
    reps = int(os.environ.get("BENCH_AMORT_REPS", "1"))
    max_iters = int(os.environ.get("BENCH_AMORT_ITERS", "400"))
    g_tol = float(os.environ.get("BENCH_AMORT_GTOL", "1e-5"))
    rounds = int(os.environ.get("BENCH_AMORT_ROUNDS", "30"))
    tbatch = int(os.environ.get("BENCH_AMORT_BATCH", "128"))
    n_warm = int(os.environ.get("BENCH_AMORT_WARM", "2"))
    spec, _ = create_model("AFNS5", tuple(MATURITIES), float_type="float64")
    batch = np.asarray(make_param_batch(spec, max(S, 2)), dtype=np.float64)
    starts = batch[:S].T                               # (P, S) constrained

    # train-once (the amortization numerator).  n_warm=2 by default: the
    # amortized point + ONE structured neighbor (+ the anchor) — the whole
    # point of amortization is that the wide spray is unnecessary, and on
    # CPU the polish wall scales with the lane count
    t0 = time.perf_counter()
    am = amz.train_amortizer(
        spec, batch[0], T_MONTHS, n_rounds=rounds, batch=tbatch,
        steps_per_round=10, lr=1e-2, prior_scale=0.1,
        cfg=amz.AmortizerConfig(n_warm=n_warm))
    train_s = time.perf_counter() - t0

    # the EXACT _newton_bench panel (simulated at the batch's base point,
    # key 9): the workload where cold LBFGS-only demonstrably grinds
    # (BASELINE round 9: 1145 s at S=4) — measuring the warm side on the
    # same panel makes the three estimation benches' numbers composable
    data = np.asarray(api.simulate(spec, jnp.asarray(batch[0]), T_MONTHS,
                                   jax.random.PRNGKey(9))["data"])

    so = opt.resolve_estimation_env()["second_order"] or "fisher"

    def run(warm):
        _, ll, _, _ = opt.estimate(
            spec, data, starts, max_iters=max_iters, g_tol=g_tol,
            f_abstol=1e-8, warm_start=am if warm else False,
            second_order=so if warm else False)
        return ll

    if reps > 1:  # warm/compile both paths once, then interleave timed reps
        run(False), run(True)
    w_cold, w_warm = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); ll_cold = run(False)
        w_cold.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); ll_warm = run(True)
        w_warm.append(time.perf_counter() - t0)
    p50_cold = float(np.median(w_cold))
    p50_warm = float(np.median(w_warm))
    saving = max(p50_cold - p50_warm, 1e-9)
    plat = jax.devices()[0].platform
    rec = {
        "train_s": round(train_s, 3),
        "train_panels": rounds * tbatch,
        "cold_p50_s": round(p50_cold, 3),
        "warm_p50_s": round(p50_warm, 3),
        "speedup": round(p50_cold / p50_warm, 2),
        "nll_cold": round(-float(ll_cold), 6),
        "nll_warm": round(-float(ll_warm), 6),
        "warm_within_tol": bool(ll_warm >= ll_cold - 1e-3),
        "breakeven_refits": round(train_s / saving, 2),
        "device_fallback": plat != "tpu",
        "fallback_reason": "" if plat == "tpu" else os.environ.get(
            "BENCH_FALLBACK_REASON",
            "optimizer-convergence claim: always CPU-pinned f64 (same "
            "rationale as newton-bench)"),
    }
    print(f"amort-bench[AFNS5 f64 S={S} T={T_MONTHS} g_tol={g_tol:g}]: "
          + json.dumps(rec))
    return 0


def _serving_fixture_1c():
    """Shared fixture for the BENCH_LOAD subprocess modes: the 1C f64 spec
    at the tests' stable point (oracle.stable_1c_params) plus a 96-month
    stationary DNS panel matched to it, frozen to a serving snapshot at
    t = 64.  Returns ``(spec, data, snap)``."""
    import jax
    jax.config.update("jax_enable_x64", True)

    from yieldfactormodels_jl_tpu import create_model, serving

    spec, _ = create_model("1C", tuple(MATURITIES), float_type="float64")
    # the tests' stable 1C point (oracle.stable_1c_params): λ = 0.5, obs var
    # 4e-4, state chol 0.05 I, Φ = 0.9 I — a finite-loglik serving state
    p = np.zeros(spec.n_params)
    p[spec.layout["gamma"][0]] = math.log(0.5)
    p[spec.layout["obs_var"][0]] = 4e-4
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        p[a + k] = 0.05 if r == c else 0.0
    a, b = spec.layout["delta"]
    p[a:b] = [5.0, -1.0, 0.5]
    a, b = spec.layout["phi"]
    p[a:b] = np.diag([0.9, 0.9, 0.9]).reshape(-1)
    # stationary 3-factor DNS panel matched to those params (the tests'
    # simulate_dns_panel DGP — make_panel above is the 5-factor AFNS DGP)
    rng = np.random.default_rng(3)
    tau = 0.5 * MATURITIES
    Z = np.column_stack([np.ones_like(MATURITIES),
                         (1 - np.exp(-tau)) / tau,
                         (1 - np.exp(-tau)) / tau - np.exp(-tau)])
    Phi = np.diag([0.95, 0.9, 0.85])
    delta = np.array([0.3, -0.1, 0.05])
    beta = np.linalg.solve(np.eye(3) - Phi, delta)
    data = np.zeros((N_MATURITIES, 96))
    for t in range(96):
        beta = delta + Phi @ beta + 0.1 * rng.standard_normal(3)
        data[:, t] = Z @ beta + 0.02 * rng.standard_normal(N_MATURITIES)
    data += 5.0
    snap = serving.freeze_snapshot(spec, p, data, end=64)
    return spec, data, snap


def _load_mesh_bench():
    """Subprocess mode (CPU, 8 virtual devices — exported by the caller
    before jax inits): the BENCH_LOAD ``mesh_scaling`` line.  A sharded
    state store of FIXED total capacity (8192 live filter states) is swept
    across mesh sizes ``BENCH_LOAD_MESH`` (default 1,2,4,8); each size
    serves the same update traffic through a ShardedGateway and reports the
    unpaced max sustained QPS plus paced p50/p99 (robustness/loadgen.
    mesh_scaling, docs/DESIGN.md §16).  Fixed total capacity means a bigger
    mesh holds smaller shards — the production scaling shape; on this
    harness the win is the per-launch compute partition, on real chips the
    shards run concurrently too."""
    import dataclasses

    import jax

    from yieldfactormodels_jl_tpu import serving
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh
    from yieldfactormodels_jl_tpu.robustness import loadgen

    mesh_sizes = tuple(
        int(x) for x in
        os.environ.get("BENCH_LOAD_MESH", "1,2,4,8").split(",") if x)
    n_dev = len(jax.devices())
    mesh_sizes = tuple(m for m in mesh_sizes if m <= n_dev) or (1,)
    total = 8192
    spec, data, snap = _serving_fixture_1c()

    def factory(m):
        store = serving.ShardedStateStore(
            spec, mesh=pmesh.make_mesh(m), shard_capacity=total // m,
            lattice=serving.BucketLattice(update_batch_sizes=(1, 4, 16)))
        # mesh sizes that don't divide `total` (BENCH_LOAD_MESH=3,5,...)
        # get the largest registry that fits — m*(total//m) states
        keys = store.register_many(
            dataclasses.replace(snap,
                                meta=dataclasses.replace(snap.meta,
                                                         task_id=i))
            for i in range(store.capacity))
        store.warmup()
        gw = serving.ShardedGateway(store, queue_max=2048, queue_age_ms=0.0)
        return gw, keys

    out = loadgen.mesh_scaling(factory, data, mesh_sizes=mesh_sizes,
                               n=512, burst=128, duration_s=1.0)
    plat = jax.devices()[0].platform
    out["device_fallback"] = plat != "tpu"
    out["fallback_reason"] = "" if plat == "tpu" else os.environ.get(
        "BENCH_FALLBACK_REASON",
        f"mesh sweep on the {n_dev}-virtual-device {plat} harness (the "
        f"single-chip relay exposes no multi-device mesh)")
    print(f"load-mesh-bench[1C f64, {total} resident states]: "
          + json.dumps(out))
    return 0


def _load_tier_bench():
    """Subprocess mode (CPU, 8 virtual devices): the BENCH_LOAD WORKING-SET
    column — the tiered store's capacity ledger (docs/DESIGN.md §21).  A
    TieredStateStore with ``BENCH_LOAD_TIER_HOT`` HBM-hot slots (default
    1024) across the full visible mesh serves zipf(1.2)-skewed update
    traffic over working sets of ``BENCH_LOAD_WORKING_SET`` × hot capacity
    (default 1,2,4 — 1× is the fully-resident yardstick); each multiplier
    gets a FRESH store booted via ``register_many`` (head hot, tail frozen
    warm), then reports the unpaced capacity, paced p50/p99 at 0.8× of it,
    the tier ledger's hit rate, and the promotion-wave percentiles.
    Headline metric: ``states_per_chip_at_p99`` — the largest working set
    per chip whose paced p99 stays within 1.5× the fully-resident line."""
    import dataclasses

    import jax

    from yieldfactormodels_jl_tpu import serving
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh
    from yieldfactormodels_jl_tpu.robustness import loadgen

    n_dev = len(jax.devices())
    hot = int(os.environ.get("BENCH_LOAD_TIER_HOT", "1024"))
    hot = max(n_dev, hot - hot % n_dev)  # divisible by the mesh
    mults = tuple(
        int(x) for x in
        os.environ.get("BENCH_LOAD_WORKING_SET", "1,2,4").split(",")
        if x) or (1, 2)
    spec, data, snap = _serving_fixture_1c()

    recs = []
    for mult in sorted(set(mults)):
        ws = mult * hot
        # warm sized to exactly the overflow: steady-state churn spills the
        # coldest warm records to the cold registry, so all three tiers
        # exercise at every multiplier > 1
        store = serving.TieredStateStore(
            spec, mesh=pmesh.make_mesh(n_dev), shard_capacity=hot // n_dev,
            warm_capacity=max(ws - hot, 1),
            registry=serving.SnapshotRegistry(),
            lattice=serving.BucketLattice(update_batch_sizes=(1, 4, 16)))
        keys = store.register_many(
            dataclasses.replace(snap,
                                meta=dataclasses.replace(snap.meta,
                                                         task_id=i))
            for i in range(ws))
        store.warmup()
        gw = serving.ShardedGateway(store, queue_max=2048, queue_age_ms=0.0)
        # zipf rank order follows key order: the register_many head (hot at
        # boot) is also the popularity head — the steady-state layout
        w = loadgen.zipf_weights(ws, s=1.2)
        # priming pass (discarded): let the LRU converge on the zipf head
        # before the measured window, then zero the ledger/timers — the
        # published column is the steady state, not the boot transient
        loadgen.measure_capacity(gw, data, n=256, burst=128,
                                 mix=(1.0, 0.0, 0.0), keys=keys,
                                 key_weights=w)
        store.ledger = serving.TierLedger()
        store.timer.samples.pop("promote", None)
        cap = loadgen.measure_capacity(gw, data, n=512, burst=128,
                                       mix=(1.0, 0.0, 0.0), keys=keys,
                                       key_weights=w)
        rep = loadgen.run_load(gw, data, duration_s=1.0,
                               offered_qps=0.8 * cap, mix=(1.0, 0.0, 0.0),
                               burst=64, keys=keys, key_weights=w)
        t = store.tiers()
        recs.append({
            "multiplier": mult, "working_set": ws,
            "capacity_qps": round(cap, 2),
            "p50_ms": rep.p50_ms, "p99_ms": rep.p99_ms,
            "shed_rate": round(rep.shed_rate, 6),
            "degraded_rate": round(rep.degraded_rate, 6),
            "hit_rate": t["ledger"]["hit_rate"],
            "promotions": t["ledger"]["promotions"],
            "demotions": t["ledger"]["demotions"],
            "spills": t["ledger"]["spills"],
            "promote_waves": t["promote_waves"],
            "promote_p50_ms": t["promote_p50_ms"],
            "promote_p99_ms": t["promote_p99_ms"],
        })

    base = next((r for r in recs if r["multiplier"] == 1), recs[0])
    p99_budget = 1.5 * base["p99_ms"]
    fit = [r for r in recs
           if r is base or (base["p99_ms"] > 0
                            and r["p99_ms"] <= p99_budget)]
    out = {
        "hot_total": hot, "mesh": n_dev, "zipf_s": 1.2,
        "working_sets": recs,
        "p99_budget_ms": round(p99_budget, 3),
        "states_per_chip_at_p99": max(r["working_set"] for r in fit)
        // n_dev,
    }
    for r in recs:
        if r["multiplier"] == 2 and base["capacity_qps"]:
            out["qps_vs_resident_2x"] = round(
                r["capacity_qps"] / base["capacity_qps"], 3)
            out["hit_rate_2x"] = r["hit_rate"]
    plat = jax.devices()[0].platform
    out["device_fallback"] = plat != "tpu"
    out["fallback_reason"] = "" if plat == "tpu" else os.environ.get(
        "BENCH_FALLBACK_REASON",
        f"working-set sweep on the {n_dev}-virtual-device {plat} harness "
        f"(the single-chip relay exposes no multi-device mesh)")
    print(f"load-tier-bench[1C f64, hot {hot} on {n_dev} chips]: "
          + json.dumps(out))
    return 0


def _load_fan_bench():
    """Subprocess mode (CPU, 8 virtual devices): the BENCH_LOAD STREAMING
    column — the scenario-subscription hub's delta-refresh claim
    (docs/DESIGN.md §23).  ``BENCH_LOAD_FAN_SUBS`` standing subscriptions
    (default 24) ride one ``ScenarioStreamHub`` over a live
    ``YieldCurveService`` while ``BENCH_LOAD_FAN_UPDATES`` accepted online
    updates stream in (default 40); every update delta-refreshes ALL dirty
    fans in ONE donated wave and every subscription's answer is collected
    after each update.  The baseline is the same stream answered the
    pre-§23 way: one full ``stress_fan`` recompute per subscription per
    update.  Headline metric: ``delta_vs_full`` — sustained fan answers/sec
    of the delta refresh over the per-update full recompute (the ISSUE
    acceptance bar is ≥ 3×) at bounded answer-time staleness p99."""
    import jax

    from yieldfactormodels_jl_tpu import serving
    from yieldfactormodels_jl_tpu.robustness import loadgen
    from yieldfactormodels_jl_tpu.serving import streams  # noqa: F401

    subs = int(os.environ.get("BENCH_LOAD_FAN_SUBS", "24"))
    updates = int(os.environ.get("BENCH_LOAD_FAN_UPDATES", "40"))
    horizon = 8
    spec, data, snap = _serving_fixture_1c()
    live = data.shape[1] - 64   # post-origin curves; the stream cycles them
    dates = list(range(updates))
    curves = [data[:, 64 + (i % live)] for i in range(updates)]

    # ---- delta side: one hub, one donated wave per update ----
    svc = serving.YieldCurveService(snap)
    hub = serving.ScenarioStreamHub(svc, capacity=subs)
    for i in range(subs):
        hub.subscribe(f"sub{i}", horizon=horizon)
    # warm: one update + one answer sweep (compile both programs), discarded
    svc.update(-1, curves[0])
    for i in range(subs):
        hub.fan(f"sub{i}")
    rep = loadgen.run_fan_load(hub, svc, curves, dates)

    # ---- full side: the same stream, a stress_fan recompute per sub ----
    svc_full = serving.YieldCurveService(snap)
    svc_full.update(-1, curves[0])
    svc_full.stress_fan(h=horizon)   # warm, discarded
    full_lat = []
    t_start = time.perf_counter()
    for date, curve in zip(dates, curves):
        svc_full.update(date, curve)
        for _ in range(subs):
            t0 = time.perf_counter()
            svc_full.stress_fan(h=horizon)
            full_lat.append(time.perf_counter() - t0)
    full_wall = time.perf_counter() - t_start
    f50, f99, _ = loadgen._percentiles_ms(full_lat)
    full_fans_per_s = round(updates * subs / full_wall, 2) if full_wall \
        else 0.0

    out = {
        "subscriptions": subs, "updates": updates, "horizon": horizon,
        "shocks": len(sc_standard := hub.fan("sub0")["names"]),
        "shock_names": list(sc_standard),
        "delta": rep.to_dict(),
        "full": {"fans_per_s": full_fans_per_s, "wall_s": round(full_wall, 4),
                 "p50_ms": round(f50, 3), "p99_ms": round(f99, 3)},
        "delta_vs_full": round(rep.fans_per_s / full_fans_per_s, 2)
        if full_fans_per_s else float("nan"),
        "counters": hub.counters.to_dict(),
    }
    plat = jax.devices()[0].platform
    out["device_fallback"] = plat != "tpu"
    out["fallback_reason"] = "" if plat == "tpu" else os.environ.get(
        "BENCH_FALLBACK_REASON",
        f"streaming-fan sweep on the 8-virtual-device {plat} harness "
        f"(the single-chip relay exposes no multi-device mesh)")
    print(f"load-fan-bench[1C f64, {subs} subs x {updates} updates]: "
          + json.dumps(out))
    return 0


def _load_recovery_bench():
    """Subprocess mode (CPU, 8 virtual devices): the BENCH_LOAD RECOVERY
    column — shard-loss fault domains under sustained keyed updates
    (docs/DESIGN.md §24).  A full mesh of resident 1C states takes
    ``BENCH_LOAD_RECOVERY_ROUNDS`` rounds (default 30) of one update per
    key through a ShardedGateway while ``BENCH_LOAD_RECOVERY_KILLS`` shards
    die mid-stream (default 2: explicit ``mark_shard_lost`` operator kills
    plus one chaos-fired ``shard_lost`` dispatch loss) — each loss answers
    its in-flight requests DEGRADED from the banked last-good, then the
    rebuild wave re-registers the shard and replays journal suffixes.
    Headline metrics: detection→rebuilt MTTR p50/p99, the degraded-answer
    rate across the loss windows, and ``zero_lost_accepted`` — every
    ungapped key bit-identical to a fault-free twin fed exactly the
    accepted stream (the availability contract; no naive denominator —
    see BASELINE.md)."""
    import dataclasses

    import jax

    from yieldfactormodels_jl_tpu import serving
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh
    from yieldfactormodels_jl_tpu.robustness import loadgen

    n_dev = len(jax.devices())
    rounds = int(os.environ.get("BENCH_LOAD_RECOVERY_ROUNDS", "30"))
    kills = max(1, int(os.environ.get("BENCH_LOAD_RECOVERY_KILLS", "2")))
    spec, data, snap = _serving_fixture_1c()
    cap_per = 16
    n_keys = n_dev * cap_per // 2   # half-full: room for redistribution
    lat = serving.BucketLattice(update_batch_sizes=(1, 4, 16))

    def build():
        st = serving.ShardedStateStore(spec, mesh=pmesh.make_mesh(n_dev),
                                       shard_capacity=cap_per, lattice=lat)
        st.register_many(
            dataclasses.replace(snap,
                                meta=dataclasses.replace(snap.meta,
                                                         task_id=i))
            for i in range(n_keys))
        return st

    store, twin = build(), build()
    keys = store.keys()
    store.warmup()      # twin shares the process-wide compiled programs
    gw = serving.ShardedGateway(store, queue_max=4096, queue_age_ms=0.0)
    # kills - 1 explicit operator kills at evenly spaced rounds, round-robin
    # over the shards, plus ONE chaos-fired in-dispatch loss mid-run — both
    # detection paths (health-sweep verb and launch failure) exercise
    kill_at = [(max(1, (i + 1) * rounds // (kills + 1)), i % n_dev)
               for i in range(kills - 1)]
    rep = loadgen.run_recovery_load(
        gw, store, twin, data[:, 64:], keys, rounds=rounds, kill_at=kill_at,
        chaos_kill_rounds=[max(1, rounds // 2)])
    out = rep.to_dict()
    out.update({
        "keys": len(keys), "mesh": n_dev,
        "journal_cap": store.journal.capacity,
        "lost_shards": store.recovery.lost_shards,
        "rehomed_keys": store.recovery.rehomed_keys,
        "zero_lost_accepted": rep.lost_accepted == 0 and rep.errors == 0
        and rep.kills > 0,
    })
    plat = jax.devices()[0].platform
    out["device_fallback"] = plat != "tpu"
    out["fallback_reason"] = "" if plat == "tpu" else os.environ.get(
        "BENCH_FALLBACK_REASON",
        f"recovery sweep on the {n_dev}-virtual-device {plat} harness "
        f"(the single-chip relay exposes no multi-device mesh)")
    print(f"load-recovery-bench[1C f64, {len(keys)} keys on {n_dev} "
          f"chips, {rep.kills} kills]: " + json.dumps(out))
    return 0


def _orch_bench():
    """2-worker in-process orchestration bench (CPU-pinned subprocess mode):
    tasks/sec on a clean RW rolling run through the leased queue, plus the
    wall-clock overhead of a chaos-killed worker being stolen from and the
    run completing anyway (the recovery path priced, not just tested)."""
    import tempfile
    import numpy as np

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.orchestration import chaos
    from yieldfactormodels_jl_tpu.orchestration import supervisor as sup

    mats = tuple(MATURITIES[::4])
    T, in_end, h = 84, 61, 4  # 24 origins + 1 merge barrier
    rng = np.random.default_rng(0)
    data = np.cumsum(rng.standard_normal((len(mats), T)) * 0.1, axis=1) + 5.0
    n_tasks = T - in_end + 1

    def run_once(root, with_chaos):
        spec, _ = create_model("RW", mats, float_type="float64",
                               results_location=root + os.sep)
        init = np.zeros((spec.n_params, 1))
        # ttl balances spurious steals on a loaded 1-core box (too low)
        # against the dead-worker takeover wait priced into the resume wall
        kw = dict(window_type="expanding", lease_ttl=2.0, poll_interval=0.02,
                  reestimate=False)
        if with_chaos:
            # one worker dies at its 8th shard write; the survivor steals
            # the expired lease and finishes the whole run
            chaos.configure("shard_write:@8")
        t0 = time.perf_counter()
        stats = sup.run_orchestrated(spec, data, "1", in_end, 1, h, init,
                                     n_workers=2, **kw)
        wall = time.perf_counter() - t0
        chaos.reset()
        merged = os.path.join(root, "db",
                              "forecasts_expanding_merged.sqlite3")
        assert os.path.isfile(merged), "orchestrated run did not merge"
        assert with_chaos == any(s.died for s in stats)
        return wall

    with tempfile.TemporaryDirectory() as d:
        run_once(os.path.join(d, "warmup"), False)  # pay jit compiles once
        wall_clean = run_once(os.path.join(d, "clean"), False)
        wall_chaos = run_once(os.path.join(d, "resume"), True)
    print(f"orch-bench[RW, {n_tasks} tasks, 2 workers]: "
          f"{n_tasks / wall_clean:.2f} tasks/s (wall {wall_clean:.2f}s); "
          f"worker-death resume wall {wall_chaos:.2f}s -> overhead "
          f"{wall_chaos / wall_clean:.2f}x")
    return 0


def _wait_patient(proc, timeout_s, grace_s=600):
    """Wait for a subprocess with the relay-safe escalation: plain wait,
    then SIGTERM + bounded grace, then ABANDON UNKILLED.  Never SIGKILL — a
    client killed while holding the axon relay claim wedges the TPU for
    everyone (CLAUDE.md TPU access rules; the round-2 outage and 2026-07-31
    were both SIGKILL-during-backend-init).  Returns True when the process
    exited (its returncode is then valid), False when it was abandoned."""
    try:
        proc.wait(timeout=timeout_s)
        return True
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=grace_s)
            return True
        except subprocess.TimeoutExpired:
            return False


def _probe_device(timeout_s, retries):
    """Bounded backend probe: can a fresh process see the TPU at all?

    Each attempt imports jax in a subprocess and prints the default
    platform, with the SIGTERM-patient wait.  Backend flakes (timeout,
    nonzero exit — the relay's UNAVAILABLE-wedge signature) retry up to
    ``retries`` times; a clean non-TPU answer is final (retrying cannot grow
    a TPU).  Returns ``(on_tpu, reason)`` — ``reason`` feeds the BENCH
    JSON's ``fallback_reason`` so a fallback round can never silently pose
    as a device measurement (ROADMAP item 3)."""
    import tempfile

    code = "import jax, sys; sys.stdout.write(jax.devices()[0].platform)"
    reason = "probe never ran"
    for attempt in range(1, max(1, retries) + 1):
        with tempfile.NamedTemporaryFile("w+", suffix=".probe") as out_f:
            proc = subprocess.Popen([sys.executable, "-c", code],
                                    stdout=out_f, stderr=subprocess.DEVNULL,
                                    text=True)
            exited = _wait_patient(proc, timeout_s)
            if exited and proc.returncode == 0:
                out_f.seek(0)
                plat = out_f.read().strip()
                if plat == "tpu":
                    return True, ""
                return False, (f"backend probe saw platform={plat!r} "
                               f"(attempt {attempt})")
            what = (f"timed out after {timeout_s:.0f}s" if not exited
                    else f"exited rc={proc.returncode}")
            reason = f"backend probe {what} (attempt {attempt}/{retries})"
            sys.stderr.write(f"# {reason}\n")
    return False, reason


def _orchestrate():
    """Run main() in a watchdog subprocess; fall back to CPU on wedge.
    Returns the stdout that was emitted (the JSON line) so the caller can
    enforce ``--require-device``."""
    here = os.path.abspath(__file__)
    # default sized for the round-3 relay: remote compiles of the kernel set
    # (tile-rows sweep + fused grad + the 2nd-order-AD ssd section) took
    # >900 s cold in the first post-outage window; the wedge this watchdog
    # guards against manifests as a silent multi-HOUR hang, so 2400 s keeps
    # the guard meaningful without tripping on honest compiles
    timeout_s = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "2400"))
    fallback_reason = None
    # cheap bounded probe BEFORE committing the full watchdog budget: a
    # backend that cannot even enumerate a TPU in BENCH_PROBE_TIMEOUT s
    # (x BENCH_PROBE_RETRIES) will not produce a device measurement in
    # 2400 s either — skip straight to the honestly-labelled CPU round
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
    probe_retries = int(os.environ.get("BENCH_PROBE_RETRIES", "2"))
    on_tpu, probe_reason = _probe_device(probe_timeout, probe_retries)
    if not on_tpu:
        sys.stderr.write(f"# {probe_reason}; skipping the device attempt\n")
        fallback_reason = probe_reason
    if on_tpu:
        try:
            # NEVER SIGKILL the inner process (subprocess.run's timeout
            # does): a client killed while holding the relay claim wedges
            # the TPU for everyone — the round-2 outage, and again on
            # 2026-07-31 when this orchestrator's 900 s kill preceded hours
            # of UNAVAILABLE backend inits.  SIGTERM is catchable, lets the
            # claim release (_wait_patient; abandoned-unkilled as last
            # resort).
            # file-backed output, not PIPEs: an abandoned child must be able
            # to keep logging and exit on its own (a full unread pipe would
            # block its writes and pin the relay claim forever)
            import tempfile
            out_f = tempfile.NamedTemporaryFile("w+", suffix=".bench.out",
                                                delete=False)
            err_f = tempfile.NamedTemporaryFile("w+", suffix=".bench.err",
                                                delete=False)
            proc = subprocess.Popen([sys.executable, here, "--inner"],
                                    stdout=out_f, stderr=err_f, text=True)
            if not _wait_patient(proc, timeout_s):
                sys.stderr.write("# inner past the watchdog and ignored "
                                 "SIGTERM; abandoning it unkilled (relay "
                                 "claim safety) and falling back to CPU\n")
            out_f.flush()
            err_f.flush()
            out = open(out_f.name).read()
            err = open(err_f.name).read()
            if proc.returncode == 0 and out.strip():
                sys.stdout.write(out)
                sys.stderr.write(err[-2000:])
                return out
            fallback_reason = (f"device run failed rc={proc.returncode} "
                               f"after the probe saw a TPU")
            sys.stderr.write(f"# {fallback_reason}; "
                             f"stderr tail: {err[-500:]}\n")
        except Exception as e:
            fallback_reason = (f"device orchestration error "
                               f"({type(e).__name__}: {e})")
            sys.stderr.write(f"# {fallback_reason}; falling back to CPU\n")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable the TPU plugin hook
    # a persistent cache exported for the device attempt must not follow the
    # fallback onto CPU: XLA:CPU AOT executables are host-specific and a
    # cross-container cache hit risks SIGILL (see benchmarks/hw_verify.py)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the honest label: the inner stamps device_fallback/fallback_reason
    # into its JSON line from this env var (ROADMAP item 3 bench blindness)
    env["BENCH_FALLBACK_REASON"] = fallback_reason or "unknown fallback cause"
    proc = subprocess.run([sys.executable, here, "--inner"], env=env,
                          timeout=timeout_s, capture_output=True, text=True)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr[-2000:])
    return proc.stdout


def _require_device_rc(stdout_text) -> int:
    """Exit code for --require-device: 0 only when the emitted JSON line is
    a real device measurement (``device_fallback: false``); anything else —
    fallback, no output, unparseable output — is non-zero, so CI can refuse
    to let a CPU round pose as the TPU trajectory."""
    for line in reversed((stdout_text or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if rec.get("device_fallback") is False:
            return 0
        sys.stderr.write(f"# --require-device: refusing fallback round "
                         f"({rec.get('fallback_reason', 'unknown')!r})\n")
        return 2
    sys.stderr.write("# --require-device: no BENCH JSON line emitted\n")
    return 2


if __name__ == "__main__":
    if "--grad-parity" in sys.argv:
        sys.exit(_grad_parity())
    elif "--orch-bench" in sys.argv:
        sys.exit(_orch_bench())
    elif "--longt-bench" in sys.argv:
        sys.exit(_longt_bench())
    elif "--newton-bench" in sys.argv:
        sys.exit(_newton_bench())
    elif "--amort-bench" in sys.argv:
        sys.exit(_amort_bench())
    elif "--load-mesh-bench" in sys.argv:
        sys.exit(_load_mesh_bench())
    elif "--load-tier-bench" in sys.argv:
        sys.exit(_load_tier_bench())
    elif "--load-fan-bench" in sys.argv:
        sys.exit(_load_fan_bench())
    elif "--load-recovery-bench" in sys.argv:
        sys.exit(_load_recovery_bench())
    elif "--inner" in sys.argv:
        main()
    else:
        emitted = _orchestrate()
        if "--require-device" in sys.argv:
            sys.exit(_require_device_rc(emitted))
