"""Simulate yield panels from a fitted Kalman-family model.

Beyond-reference capability: the reference's simulation mode only READS
pre-simulated CSVs (`YieldFactorModels.jl:241-246` + `test.jl`); it has no
generator.  This module samples from the model the Kalman filters assume:

    β_t = δ + Φ β_{t−1} + C η_t,          η_t ~ N(0, I)   (C Cᵀ = Ω_state)
    y_t = Z(β_t) β_t + d + √(σ² e^{h_t}) ε_t,  ε_t ~ N(0, I_N)
    h_t = φ_h h_{t−1} + σ_h ξ_t            (SV extension; h ≡ 0 without it)

β₀ is drawn from the unconditional distribution (the same
``init_state`` moments the filters start from), so simulated panels are
stationary from the first column.  The TVλ EKF family rebuilds its loading
row from the state each step (same ``_tvl_measurement`` the filter
linearizes); constant-measurement families use ``measurement_setup``.  One
``lax.scan`` over time — jittable and vmappable over draws.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .kalman import init_state, measurement_setup, state_measurement
from .params import unpack_kalman
from .specs import ModelSpec


def simulate(spec: ModelSpec, params, T: int, key,
             sv_phi: float = 0.0, sv_sigma: float = 0.0,
             start_state=None):
    """Simulate a (N, T) panel plus its latent paths.

    Returns a dict: ``data`` (N, T), ``states`` (Ms, T) the sampled β path,
    ``h`` (T,) the log-volatility path (zeros unless ``sv_sigma > 0``).
    With ``sv_sigma = 0`` the DGP is exactly the homoskedastic model the
    Kalman loglik assumes; with SV it matches ``ops/particle.py``'s model
    (draw-then-observe order, h₀ = 0 before the first step).

    ``start_state``: optional ``(beta, P)`` moments to draw β₀ from instead
    of the unconditional distribution.  With the FILTERED moments
    (β_{t|t}, P_{t|t}) of a fitted model, the simulated panel is an exact
    draw from the h-step predictive distribution given the data — the
    scenario generator of the online serving layer (``serving/``).
    """
    if not spec.is_kalman:
        raise ValueError(
            f"simulate: generative state-space sampling needs a Kalman "
            f"family; {spec.family!r} is a prediction-error family with no "
            f"generative measurement model")
    kp = unpack_kalman(spec, jnp.asarray(params, dtype=spec.dtype))
    dtype = kp.Phi.dtype
    Ms, N = spec.state_dim, spec.N
    mats = spec.maturities_array
    Z_const, d_const = measurement_setup(spec, kp, dtype)
    mfn = state_measurement(spec)
    if Z_const is not None and d_const is None:
        d_const = jnp.zeros((N,), dtype=dtype)

    if start_state is None:
        st0 = init_state(spec, kp)
        beta_mean, P_start = st0.beta, st0.P
    else:
        beta_mean = jnp.asarray(start_state[0], dtype=dtype)
        P_start = jnp.asarray(start_state[1], dtype=dtype)
    P0 = 0.5 * (P_start + P_start.T) + 1e-9 * jnp.eye(Ms, dtype=dtype)
    S0 = jnp.linalg.cholesky(P0)
    Om = 0.5 * (kp.Omega_state + kp.Omega_state.T) \
        + 1e-12 * jnp.eye(Ms, dtype=dtype)
    C = jnp.linalg.cholesky(Om)
    sig = jnp.sqrt(kp.obs_var)

    key, k0 = jax.random.split(jnp.asarray(key))
    beta0 = beta_mean + S0 @ jax.random.normal(k0, (Ms,), dtype=dtype)

    def step(carry, k):
        beta, h = carry
        k_eta, k_xi, k_eps = jax.random.split(k, 3)
        beta = kp.delta + kp.Phi @ beta \
            + C @ jax.random.normal(k_eta, (Ms,), dtype=dtype)
        h = sv_phi * h + sv_sigma * jax.random.normal(k_xi, (), dtype=dtype)
        if mfn is not None:
            _, y_mean = mfn(beta, mats)
        else:
            y_mean = Z_const @ beta + d_const
        y = y_mean + sig * jnp.exp(0.5 * h) \
            * jax.random.normal(k_eps, (N,), dtype=dtype)
        return (beta, h), (y, beta, h)

    h0 = jnp.zeros((), dtype=dtype)
    _, (ys, betas, hs) = lax.scan(step, (beta0, h0),
                                  jax.random.split(key, T))
    return {"data": ys.T, "states": betas.T, "h": hs}
