from . import specs, loadings, params, registry, api, kalman, score_driven, static_model

__all__ = [
    "specs",
    "loadings",
    "params",
    "registry",
    "api",
    "kalman",
    "score_driven",
    "static_model",
]
