"""Arbitrage-free (generalized) Nelson–Siegel models — AFNS3 / AFNS5.

NEW capability relative to the reference (SURVEY.md §7 "stretch": the
BASELINE.json benchmark configs name a 5-factor AFNS on the Liu–Wu panel;
the reference itself has no AFNS).  Model of Christensen–Diebold–Rudebusch:

- AFNS3: factors (level, slope, curvature) with one decay λ₁ — DNS loadings
  plus an arbitrage-free *yield-adjustment* intercept.
- AFNS5 (AFGNS): (level, slope₁, curv₁, slope₂, curv₂) with decays λ₁, λ₂.

Measurement: y(τ) = Z(τ)·X + α(τ) + ε, where α(τ) = −A(τ)/τ and
A(τ) = ½∫₀^τ B(s)ᵀ Ω B(s) ds with B(s) the bond-price factor loadings.
Substituting s = uτ gives α(τ) = −½∫₀¹ B(uτ)ᵀ Ω B(uτ) du, evaluated here by
a fixed-grid trapezoid — one (N, Q, M) tensor contraction, jit/vmap-friendly
and exact to quadrature error instead of transcribing the long closed form.

Parameter layout (flat, following the kalman convention of specs.py):
[γ (n_lambda drivers, λᵢ = 1e-2 + exp γᵢ) | σ²_obs | chol(Ω_state) | δ | Φ_rowmajor].
"""

from __future__ import annotations

import jax.numpy as jnp

from .loadings import LAMBDA_FLOOR


def afns_lambdas(gamma):
    """λᵢ = 1e-2 + exp(γᵢ), same convention as dns.jl:55."""
    return LAMBDA_FLOOR + jnp.exp(gamma)


def afns_loadings(gamma, maturities, M: int):
    """(N, M) yield loading matrix; M ∈ {3, 5}."""
    lams = afns_lambdas(gamma)
    cols = [jnp.ones_like(maturities)]
    n_lam = (M - 1) // 2
    for i in range(n_lam):
        tau = lams[i] * maturities
        z = jnp.exp(-tau)
        slope = (1.0 - z) / tau
        cols.append(slope)
        cols.append(slope - z)
    return jnp.stack(cols, axis=-1)


def _price_loadings(s, lams, M: int):
    """B(s): bond-price factor loadings at time-to-maturity s (…, broadcast)."""
    cols = [-s]
    n_lam = (M - 1) // 2
    for i in range(n_lam):
        lam = lams[i]
        e = jnp.exp(-lam * s)
        b_slope = -(1.0 - e) / lam
        b_curv = s * e + b_slope
        cols.append(b_slope)
        cols.append(b_curv)
    return jnp.stack(cols, axis=-1)


def yield_adjustment(gamma, Omega_state, maturities, M: int, quad_points: int = 64):
    """α(τ) = −½ ∫₀¹ B(uτ)ᵀ Ω B(uτ) du per maturity, trapezoid in u."""
    lams = afns_lambdas(gamma)
    u = jnp.linspace(0.0, 1.0, quad_points + 1)
    s = maturities[:, None] * u[None, :]           # (N, Q+1)
    B = _price_loadings(s, lams, M)                # (N, Q+1, M)
    f = jnp.einsum("nqi,ij,nqj->nq", B, Omega_state, B)
    w = jnp.ones_like(u).at[0].set(0.5).at[-1].set(0.5) / quad_points
    integral = f @ w                               # (N,)
    return -0.5 * integral
