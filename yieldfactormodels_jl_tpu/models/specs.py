"""Immutable model specifications.

The reference represents a model as a mutable struct bundling dims, parameter
buffers, scratch arrays and transform function vectors
(/root/reference/src/models/kalman/kalmanbasemodel.jl:6-41,
 msedriven/msebasemodel.jl:8-104, static/staticbasemodel.jl:8-83).

TPU-native design: a model is a hashable, frozen :class:`ModelSpec` (static
under ``jit``) plus a flat parameter *vector* (a traced array).  All state the
reference mutates (β, γ, P, EWMA...) lives in the scan carry of the filter
kernels instead.

The flat parameter layout is byte-for-byte the reference's ``get_params``
ordering so parameter files and warm starts are interchangeable:

- kalman_dns   [γ_λ | σ²_obs | chol(Ω_state) | δ | vec_rowmajor(Φ)]   (20 for M=3)
  (kalman/paramoperations.jl:44-58 + :6-41)
- kalman_tvl   [σ²_obs | chol(Ω_state) | δ | vec_rowmajor(Φ)]          (31, Ms=M+1)
  (kalman/paramoperations.jl:61-68; tvλdns.jl:24)
- msed_*       [uniq A | uniq B (unless RW) | ω | δ | vec_colmajor(Φ)]
  (msedriven/paramteroperations.jl:3-22)
- static_* / random_walk  [γ | δ | vec_colmajor(Φ)]
  (static/paramteroperations.jl:3-16)
"""

from __future__ import annotations

import dataclasses
from functools import cached_property
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import transformations as tr

KALMAN_FAMILIES = ("kalman_dns", "kalman_tvl", "kalman_afns")
MSED_FAMILIES = ("msed_lambda", "msed_neural")
STATIC_FAMILIES = ("static_lambda", "static_neural", "random_walk")
ALL_FAMILIES = KALMAN_FAMILIES + MSED_FAMILIES + STATIC_FAMILIES


@jax.tree_util.register_static
@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Static description of a yield-factor model (hashable; safe under jit)."""

    family: str
    model_code: str
    maturities: Tuple[float, ...]
    M: int = 3
    L: int = 1
    dtype_name: str = "float32"

    # score-driven family options (msebasemodel.jl:73, :95-104)
    random_walk: bool = False
    scale_grad: bool = False
    forget_factor: float = 0.9
    dynamics: Optional[str] = None  # 'scalar' | 'block_diag' | 'diag'
    duplicator: Tuple[int, ...] = ()  # 0-based unique-parameter index per state

    # neural loading option: False = "-Anchored" codes (model_dictionary.jl:74-112)
    transform_bool: bool = True

    # EKF Jacobian: reference analytic formula (kalman/filter.jl:43) has a
    # quirk vs the true derivative; False reproduces the reference.
    exact_jacobian: bool = False

    # Score-driven inner score: the reference detaches β inside the inner
    # gradient (ForwardDiff.value., filter.jl:175), which also drops β's
    # sensitivity from the *outer* MLE gradient.  True reproduces that; False
    # gives the exact AD gradient of the loss (matches finite differences).
    detach_inner_beta: bool = True

    # persistence context (kalmanbasemodel.jl init_folder/results_folder)
    model_string: str = ""
    results_location: str = "results/"

    # ---- basic derived facts -------------------------------------------------

    def __post_init__(self):
        if self.family not in ALL_FAMILIES:
            raise ValueError(f"unknown family {self.family!r}")
        if not self.model_string:
            object.__setattr__(self, "model_string", self.model_code)

    @property
    def N(self) -> int:
        return len(self.maturities)

    @property
    def dtype(self):
        return jnp.dtype(self.dtype_name)

    @property
    def state_dim(self) -> int:
        """Kalman state dimension (M+1 for TVλ — tvλdns.jl:24)."""
        return self.M + 1 if self.family == "kalman_tvl" else self.M

    @property
    def n_lambdas(self) -> int:
        """Number of λ decay drivers (AFNS5/AFGNS has two)."""
        return (self.M - 1) // 2 if self.family == "kalman_afns" else 1

    @property
    def n_unique(self) -> int:
        return (max(self.duplicator) + 1) if self.duplicator else self.L

    @property
    def maturities_array(self) -> jnp.ndarray:
        return jnp.asarray(self.maturities, dtype=self.dtype)

    @property
    def is_kalman(self) -> bool:
        return self.family in KALMAN_FAMILIES

    @property
    def has_constant_measurement(self) -> bool:
        """Constant-Z Kalman family — THE applicability gate for the
        associative-scan engine and everything built on it (T-switch
        dispatch, ``objective="time_sharded"``, the ladder's assoc rung,
        serving ``refilter()`` — docs/DESIGN.md §13).  One property so the
        four call sites can never drift; TVλ's state-dependent Jacobian rows
        (and any future time-varying measurement) stay excluded here."""
        return self.family in ("kalman_dns", "kalman_afns")

    @property
    def is_msed(self) -> bool:
        return self.family in MSED_FAMILIES

    @property
    def supports_score_tree(self) -> bool:
        """Score-driven spec whose recursion the O(log T) tree engine
        (ops/score_scan.py) can carry — THE applicability gate for the
        score-tree engine and everything built on it (``config.engines_for``,
        the T-switch dispatch, ``objective="time_sharded"``, the ladder's
        score_tree rung — docs/DESIGN.md §19), the MSED twin of
        ``has_constant_measurement``.  Requires the plain gradient update
        γ ← γ + A⊙score: the ``scale_grad`` lineage carries an EWMA
        second-moment state whose Adam-style normalization is not a
        small-state affine recursion, so those specs keep the sequential
        scan (and return ``False`` here)."""
        return self.is_msed and not self.scale_grad

    @property
    def is_static(self) -> bool:
        return self.family in STATIC_FAMILIES

    # ---- flat parameter layout ----------------------------------------------

    @cached_property
    def layout(self) -> dict:
        """name -> (start, stop) slices into the flat parameter vector."""
        M, L, u = self.M, self.L, self.n_unique
        pos = 0
        lay = {}

        def put(name, size):
            nonlocal pos
            lay[name] = (pos, pos + size)
            pos += size

        if self.is_kalman:
            Ms = self.state_dim
            if self.family == "kalman_dns":
                put("gamma", 1)
            elif self.family == "kalman_afns":
                put("gamma", self.n_lambdas)
            put("obs_var", 1)
            put("chol", Ms * (Ms + 1) // 2)
            put("delta", Ms)
            put("phi", Ms * Ms)
        elif self.is_msed:
            put("A", u)
            if not self.random_walk:
                put("B", u)
            put("omega", L)
            put("delta", M)
            put("phi", M * M)
        else:
            put("gamma", L)
            put("delta", M)
            put("phi", M * M)
        lay["__total__"] = (0, pos)
        return lay

    @property
    def n_params(self) -> int:
        return self.layout["__total__"][1]

    def slice(self, params, name):
        a, b = self.layout[name]
        return params[..., a:b]

    # ---- transform codes -----------------------------------------------------

    @cached_property
    def transform_codes(self) -> Tuple[int, ...]:
        """Per-parameter bijection codes, ordered like the flat layout.

        Kalman list construction: kalmanbasemodel.jl:74-120; MSED/static:
        msebasemodel.jl:79-92 / staticbasemodel.jl:47-60; model-specific heads:
        dns.jl:21-22, mselambda.jl:17-24, mseneural.jl:33-51.
        """
        codes: list[int] = []
        M = self.M
        if self.is_kalman:
            Ms = self.state_dim
            if self.family == "kalman_dns":
                codes.append(tr.IDENTITY)  # λ driver γ
            elif self.family == "kalman_afns":
                codes.extend([tr.IDENTITY] * self.n_lambdas)
            codes.append(tr.R_TO_POS)  # observation variance
            for j in range(Ms):  # chol, column-by-column; diag positive
                for i in range(j + 1):
                    codes.append(tr.R_TO_POS if i == j else tr.IDENTITY)
            codes.extend([tr.IDENTITY] * Ms)  # delta
            for i in range(Ms):  # Phi row-major, diag in (-1,1)
                for j in range(Ms):
                    codes.append(tr.R_TO_11 if i == j else tr.IDENTITY)
        elif self.is_msed:
            u = self.n_unique
            codes.extend([tr.R_TO_POS] * u)  # step sizes A > 0
            if not self.random_walk:
                codes.extend([tr.R_TO_01] * u)  # persistence B in (0,1)
            codes.extend([tr.IDENTITY] * self.L)  # omega
            codes.extend([tr.IDENTITY] * M)  # delta
            for k in range(M * M):  # Phi col-major, diag in (-1,1)
                codes.append(tr.R_TO_11 if k % (M + 1) == 0 else tr.IDENTITY)
        else:
            codes.extend([tr.IDENTITY] * self.L)  # gamma
            codes.extend([tr.IDENTITY] * M)  # delta
            for k in range(M * M):
                codes.append(tr.R_TO_11 if k % (M + 1) == 0 else tr.IDENTITY)
        assert len(codes) == self.n_params
        return tuple(codes)

    @property
    def transform_codes_array(self) -> jnp.ndarray:
        return jnp.asarray(self.transform_codes, dtype=jnp.int32)

    # ---- default parameter groups (block-coordinate estimation) -------------

    def default_param_groups(self) -> Tuple[str, ...]:
        """kalman: all "1" (kalmanbasemodel.jl:150-159); msed/static: head "1",
        (δ, Φ) block "2" (msebasemodel.jl:153-162, staticbasemodel.jl:103-112)."""
        n = self.n_params
        if self.is_kalman:
            return tuple(["1"] * n)
        tail = self.M * (self.M + 1)
        return tuple(["1"] * (n - tail) + ["2"] * tail)

    # ---- initialization grids (mselambda.jl:26-27, mseneural.jl:53-54) ------

    @property
    def A_guesses(self) -> Tuple[float, ...]:
        if self.family == "msed_lambda":
            return (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1)
        if self.family == "msed_neural":
            return (1e-6, 1e-5, 1e-4, 1e-3)
        return ()

    @property
    def B_guesses(self) -> Tuple[float, ...]:
        if self.random_walk:
            return ()
        if self.family == "msed_lambda":
            return (0.9, 0.95, 0.98, 0.99, 0.999)
        if self.family == "msed_neural":
            return (0.97, 0.98, 0.99, 0.999)
        return ()

    # ---- chol index helpers --------------------------------------------------

    @cached_property
    def chol_indices(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, cols) positions of the upper-triangular Cholesky-like factor
        in flat fill order (kalman/paramoperations.jl:17-33: column by column)."""
        Ms = self.state_dim
        rows, cols = [], []
        for j in range(Ms):
            for i in range(j + 1):
                rows.append(i)
                cols.append(j)
        return np.asarray(rows), np.asarray(cols)


def make_duplicator(dynamics: str, L: int, net_size: int = 3) -> Tuple[int, ...]:
    """Parameter-sharing index (0-based) per γ-state (mseneural.jl:33-51)."""
    if dynamics == "scalar":
        half = L // 2
        return tuple([0] * half + [1] * half)
    if dynamics == "block_diag":
        return tuple(i // net_size for i in range(L))
    if dynamics == "diag":
        return tuple(range(L))
    raise ValueError("dynamics must be 'scalar', 'block_diag' or 'diag'")
