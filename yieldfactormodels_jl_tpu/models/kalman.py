"""Kalman / extended-Kalman filtering as `lax.scan` kernels.

Behavioural parity targets (cited for the judge):

- standard KF in predicted-state form — measurement update immediately followed
  by the state propagation β ← δ + Φ(β + Kv), P ← Φ(I−KZ)PΦᵀ + Ω_state
  (/root/reference/src/models/kalman/filter.jl:125-179),
- EKF for time-varying λ with the analytic Jacobian column
  (:12-80; the reference's dZ₂/dλ term (:43) uses e^{-λτ} where the true
  derivative has (1 - e^{-λτ}) — ``spec.exact_jacobian`` selects either),
- NaN observation ⇒ predict-only step (:126-140),
- Gaussian log-likelihood −½(log|F| + vᵀF⁻¹v + N log 2π) accumulated for
  t > 1 over t = 1..T−1 (:182-209),
- diffuse-free initialization β₀ = (I−Φ)⁻¹δ, vec(P₀) = (I−Φ⊗Φ)⁻¹vec(Ω_state)
  (:1-10).

TPU-native differences (documented, intentional):
- F is factorized once per step with Cholesky (solve + log-det) instead of the
  reference's explicit ``inv(F)`` (:150) — fewer flops, stable in f32;
- missing/invalid steps are branchless masks, not early returns, so the whole
  recursion jits into a single fused scan and vmaps over batch axes
  (windows, starts, draws).

The per-step mask convention: a step is *observed* iff no entry of y_t is NaN
and ``start <= t < end``.  Because β₀ and P₀ are the unconditional values,
transition-only steps are exact no-ops, so masking a prefix is *identical* to
truncating the sample — that is what makes rolling windows a pure vmap axis.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .afns import afns_loadings, yield_adjustment
from .loadings import LAMBDA_FLOOR, dns_lambda, dns_loadings, dns_slope_curvature
from ..robustness import taxonomy as tax
from .params import KalmanParams, unpack_kalman
from .specs import ModelSpec

_LOG_2PI = math.log(2.0 * math.pi)


class KalmanState(NamedTuple):
    beta: jnp.ndarray  # (Ms,) predicted state β_{t|t-1}
    P: jnp.ndarray     # (Ms, Ms) predicted covariance


def init_state(spec: ModelSpec, kp: KalmanParams) -> KalmanState:
    """Unconditional mean/covariance start (kalman/filter.jl:1-10)."""
    Ms = spec.state_dim
    I = jnp.eye(Ms, dtype=kp.Phi.dtype)
    beta0 = jnp.linalg.solve(I - kp.Phi, kp.delta)
    II = jnp.eye(Ms * Ms, dtype=kp.Phi.dtype)
    vecP = jnp.linalg.solve(II - jnp.kron(kp.Phi, kp.Phi), kp.Omega_state.reshape(-1))
    P0 = vecP.reshape(Ms, Ms)
    return KalmanState(beta0, P0)


def tvl_dz2_dlam(lam, ztau, maturities, exact: bool):
    """dZ₂/dλ for the TVλ EKF Jacobian — the single source of truth shared by
    this module and the Pallas kernel (ops/pallas_kf.py).  ``exact=False``
    reproduces the reference's formula (kalman/filter.jl:43), whose second
    term uses e^{-λτ} where the true derivative has (1 − e^{-λτ})."""
    if exact:
        return ztau / lam - (1.0 - ztau) / (lam * lam * maturities)
    return ztau / lam - ztau / (lam * lam * maturities)


def _tvl_measurement(spec: ModelSpec, beta, maturities):
    """Z (N×4) with the analytic EKF Jacobian in column 4, and ŷ = Z[:, :3]β[:3]
    (kalman/filter.jl:31-47, tvλdns.jl:53-64)."""
    lam = dns_lambda(beta[3])
    z2, z3 = dns_slope_curvature(lam, maturities)
    z = jnp.exp(-lam * maturities)
    dlam_db4 = lam - LAMBDA_FLOOR
    dz2_dlam = tvl_dz2_dlam(lam, z, maturities, spec.exact_jacobian)
    dz3_extra = maturities * z  # (kalman/filter.jl:44)
    jac = ((beta[1] + beta[2]) * dz2_dlam + beta[2] * dz3_extra) * dlam_db4
    ones = jnp.ones_like(z2)
    Z = jnp.stack([ones, z2, z3, jac], axis=-1)
    y_pred = Z[:, 0] * beta[0] + z2 * beta[1] + z3 * beta[2]
    return Z, y_pred


def _step(spec: ModelSpec, kp: KalmanParams, Z_const, d_const, state: KalmanState, y, observed):
    """One branchless KF/EKF step.  Returns (next_state, per-step outputs)."""
    beta, P = state
    Ms = spec.state_dim
    N = spec.N
    dtype = P.dtype
    maturities = spec.maturities_array

    mfn = state_measurement(spec)
    if mfn is not None:
        Z, y_pred = mfn(beta, maturities)
    else:
        Z = Z_const
        y_pred = Z @ beta
        if d_const is not None:  # AFNS yield-adjustment intercept
            y_pred = y_pred + d_const

    obs = observed & jnp.all(jnp.isfinite(y))
    obs_f = obs.astype(dtype)
    ysafe = jnp.where(jnp.isfinite(y), y, y_pred)
    v = (ysafe - y_pred) * obs_f

    F = Z @ P @ Z.T + kp.obs_var * jnp.eye(N, dtype=dtype)
    cho = jnp.linalg.cholesky(F)
    cho_ok = jnp.all(jnp.isfinite(cho))
    cho_safe = jnp.where(cho_ok, jnp.nan_to_num(cho), jnp.eye(N, dtype=dtype))

    # K = P Zᵀ F⁻¹  via two triangular solves of F X = Z P  (Kᵀ = F⁻¹ Z Pᵀ)
    Kt = jax.scipy.linalg.cho_solve((cho_safe, True), Z @ P)  # (N, Ms)
    Fi_v = jax.scipy.linalg.cho_solve((cho_safe, True), v)

    beta_upd = beta + Kt.T @ v * obs_f
    beta_next = kp.delta + kp.Phi @ beta_upd

    KZ = Kt.T @ Z * obs_f
    P_upd = (jnp.eye(Ms, dtype=dtype) - KZ) @ P
    P_next = kp.Phi @ P_upd @ kp.Phi.T + kp.Omega_state

    logdet_F = 2.0 * jnp.sum(jnp.log(jnp.diagonal(cho_safe)))
    ll = -0.5 * (logdet_F + v @ Fi_v + N * _LOG_2PI)
    # taxonomy bitmask beside the −Inf sentinel (robustness/taxonomy.py): a
    # failed innovation Cholesky is the joint form's non-PD failure; a
    # non-finite ll behind a *successful* factorization is a blown-up state
    code = tax.bit(obs & ~cho_ok, tax.CHOL_BREAKDOWN) \
        | tax.bit(obs & cho_ok & ~jnp.isfinite(ll), tax.STATE_EXPLODED)
    ll = jnp.where(obs & cho_ok, ll, jnp.where(obs, -jnp.inf, 0.0))

    outs = {
        "y_pred": y_pred,
        "v": v,
        "ll": ll,
        "obs": obs,
        "beta_after": beta_next,
        "Z2": Z[:, 1],
        "Z3": Z[:, 2],
        # filtering moments for the RTS backward pass (ops/smoother.py);
        # XLA dead-code-eliminates these from callers that don't use them
        "beta_pred": beta,
        "P_pred": P,
        "beta_upd": beta_upd,
        "P_upd": P_upd,
        # innovation covariance for the Fisher HVP recursion (ops/newton.py)
        # — DCE'd from plain loglik consumers like the moment stacks above
        "F": F,
        "code": code,
    }
    return KalmanState(beta_next, P_next), outs


def measurement_setup(spec: ModelSpec, kp: KalmanParams, dtype):
    """(Z_const, d_const) for the constant-measurement families; (None, None)
    when Z is state-dependent (TVλ, state-dependent programs).  Shared by the
    joint-form filter here, the univariate kernel (ops/univariate_kf.py) and
    the associative-scan filter so the likelihood kernels can never diverge
    on loadings setup.  Program-declared models (program/, docs/DESIGN.md
    §22) plug in HERE: their loadings/intercept callables feed the same
    kernels as the hand-ported families."""
    mats = spec.maturities_array
    prog = getattr(spec, "program", None)
    if prog is not None:
        if prog.measurement is not None:
            return None, None
        Z = prog.loadings(kp.gamma, mats).astype(dtype)
        if prog.intercept is None:
            return Z, None
        d = prog.intercept(kp.gamma, kp.Omega_state, mats)
        return Z, d.astype(dtype)
    if spec.family == "kalman_dns":
        return dns_loadings(kp.gamma, mats).astype(dtype), None
    if spec.family == "kalman_afns":
        Z = afns_loadings(kp.gamma, mats, spec.M).astype(dtype)
        d = yield_adjustment(kp.gamma, kp.Omega_state, mats, spec.M)
        return Z, d.astype(dtype)
    return None, None


def state_measurement(spec: ModelSpec):
    """The state-dependent measurement callable ``(beta, maturities) ->
    (Z, y_pred)`` for specs whose Z depends on the state — TVλ's
    EKF-Jacobian form (:func:`_tvl_measurement`) or a program-declared
    ``measurement`` — and ``None`` for the constant-measurement families.

    THE trace-time dispatch seam replacing the scattered
    ``spec.family == "kalman_tvl"`` string checks: the joint/univariate/
    sqrt/SLR kernels, the forecast scan, the simulator and the serving
    online filter all consult this one function, so a state-dependent
    program rides the full TVλ machinery with no per-kernel wiring
    (docs/DESIGN.md §22)."""
    prog = getattr(spec, "program", None)
    if prog is not None:
        return prog.measurement
    if spec.family == "kalman_tvl":
        return lambda beta, mats: _tvl_measurement(spec, beta, mats)
    return None


def loglik_contrib_mask(start, end, T):
    """The loss convention shared by every kalman loglik kernel: recursion over
    t = 1..T−1 skipping the first innovation ⇒ contributing steps are
    start+1 .. end−2 (0-based) — kalman/filter.jl:182-209."""
    t_idx = jnp.arange(T)
    return (t_idx >= start + 1) & (t_idx <= end - 2)


def _scan_filter(spec: ModelSpec, params, data, start, end, state0: KalmanState | None = None):
    """Run the filter over all T columns of ``data`` (N, T).  ``start``/``end``
    may be traced scalars; columns outside [start, end) are treated as missing."""
    kp = unpack_kalman(spec, params)
    Z_const, d_const = measurement_setup(spec, kp, params.dtype)
    if state0 is None:
        state0 = init_state(spec, kp)
    T = data.shape[1]
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)

    def body(state, inp):
        y, obs_t = inp
        return _step(spec, kp, Z_const, d_const, state, y, obs_t)

    state, outs = lax.scan(body, state0, (data.T, observed))
    return kp, Z_const, state, outs


def get_loss(spec: ModelSpec, params, data, start=0, end=None):
    """Gaussian log-likelihood (kalman/filter.jl:182-209): the recursion runs
    over t = 1..T−1 and the first step's innovation is skipped, so with masks
    the contributing steps are start+1 .. end−2 (0-based).

    Documented divergence: on an *interior* NaN column the reference's loop
    re-reads the stale F/v buffers from the last observed step and double
    counts that innovation (filter.jl:191-195 after the early return at
    :126-140).  Here a missing step simply contributes 0 — the reference never
    exercises interior NaNs in a loss call (NaN padding is applied only for
    post-sample forecasting, forecasting.jl:141)."""
    T = data.shape[1]
    if end is None:
        end = T
    _, _, _, outs = _scan_filter(spec, params, data, start, end)
    contrib = loglik_contrib_mask(start, end, T)
    loglik = jnp.sum(jnp.where(contrib, outs["ll"], 0.0))
    return jnp.where(jnp.isfinite(loglik), loglik, -jnp.inf)


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None):
    """``(loss, code)``: :func:`get_loss` plus the taxonomy bitmask the scan
    already carries (robustness/taxonomy.py) — same loss value; the code is
    dead-code-eliminated from plain ``get_loss`` consumers."""
    T = data.shape[1]
    if end is None:
        end = T
    _, _, _, outs = _scan_filter(spec, params, data, start, end)
    contrib = loglik_contrib_mask(start, end, T)
    loglik = jnp.sum(jnp.where(contrib, outs["ll"], 0.0))
    loss = jnp.where(jnp.isfinite(loglik), loglik, -jnp.inf)
    code = tax.params_code(params) \
        | tax.combine(jnp.where(contrib, outs["code"], jnp.int32(0))) \
        | tax.bit(~jnp.any(contrib & outs["obs"]), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code


def get_loss_array(spec: ModelSpec, params, data, start=0, end=None, K: int = 1):
    """Per-step one-step-ahead MSE diagnostics (kalman/filter.jl:211-247):
    mse[t] = −‖y_t − ŷ_{t|t−1}‖²/N for t = 2..T−1 (1-based), length T−1.

    K > 1 replays the filter pass accumulating contributions before the /K —
    for Kalman models set_params! touches neither β nor P
    (kalman/paramoperations.jl:6-58), so every extra pass continues from the
    previous end state, replicated by chaining the scan carry."""
    T = data.shape[1]
    if end is None:
        end = T
    contrib = loglik_contrib_mask(start, end, T)
    acc = jnp.zeros((T,), dtype=data.dtype)
    state = None
    for _ in range(K):
        _, _, state, outs = _scan_filter(spec, params, data, start, end, state)
        per_t = -jnp.sum(outs["v"] * outs["v"], axis=-1)
        acc = acc + jnp.where(contrib, per_t, 0.0)
    return (acc / spec.N / K)[: T - 1]


def predict(spec: ModelSpec, params, data):
    """Filter the full sample plus one trailing NaN step, returning the same
    artifact set as the reference (kalman/filter.jl:250-282): preds[:, k] is
    the one-step-ahead prediction of y_{k+1}; factors/states/loading columns
    are the post-propagation values.  NaN columns in ``data`` are predict-only
    steps, which is how multi-step forecasts are produced
    (forecasting.jl:141)."""
    T = data.shape[1]
    nan_col = jnp.full((data.shape[0], 1), jnp.nan, dtype=data.dtype)
    data_ext = jnp.concatenate([data, nan_col], axis=1)
    kp, _, _, outs = _scan_filter(spec, params, data_ext, 0, T + 1)
    # columns k = steps k+1 (the reference stores step-t values at t−1)
    preds = outs["y_pred"][1:].T
    factors = outs["beta_after"][1:].T
    fl1 = outs["Z2"][1:].T
    fl2 = outs["Z3"][1:].T
    if kp.gamma is not None:  # layout-driven: any spec with a γ head block
        states = jnp.broadcast_to(kp.gamma, (T, kp.gamma.shape[-1])).T
    else:
        # TVλ never writes its γ buffer (set_params! at kalman/paramoperations.jl:61-68)
        states = jnp.zeros((spec.L, T), dtype=params.dtype)
    return {
        "preds": preds,
        "factors": factors,
        "states": states,
        "factor_loadings_1": fl1,
        "factor_loadings_2": fl2,
    }
