"""Flat-parameter pack/unpack, sharing, transforms and initialization.

Pure-functional counterpart of the reference's mutating parameter operations
(/root/reference/src/models/{kalman/paramoperations.jl,
msedriven/paramteroperations.jl, static/paramteroperations.jl,
parameteroperations.jl}).  ``unpack`` builds the structured state-space
ingredients from a flat *constrained* parameter vector; nothing is mutated.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from ..utils.transformations import apply_transforms, apply_untransforms
from .specs import ModelSpec


# ---------------------------------------------------------------------------
# sharing utilities (parameteroperations.jl:4-18)
# ---------------------------------------------------------------------------

def expand_params(unique_params, duplicator):
    """unique (u,) -> full (L,) via 0-based duplicator index."""
    idx = jnp.asarray(duplicator, dtype=jnp.int32)
    return jnp.take(unique_params, idx, axis=-1)


def get_unique_params(full_params, duplicator):
    """full (L,) -> unique (u,), taking the first occurrence of each index."""
    dup = np.asarray(duplicator)
    n_unique = int(dup.max()) + 1
    first = np.asarray([int(np.argmax(dup == i)) for i in range(n_unique)])
    return jnp.take(full_params, jnp.asarray(first), axis=-1)


# ---------------------------------------------------------------------------
# transforms (parameteroperations.jl:22-60)
# ---------------------------------------------------------------------------

def transform_params(spec: ModelSpec, params):
    return apply_transforms(params, spec.transform_codes_array)


def untransform_params(spec: ModelSpec, params):
    return apply_untransforms(params, spec.transform_codes_array)


# ---------------------------------------------------------------------------
# structured views
# ---------------------------------------------------------------------------

class MSEDParams(NamedTuple):
    A: jnp.ndarray        # (L,) expanded step sizes
    B: Optional[jnp.ndarray]  # (L,) expanded persistence, None if random walk
    omega: jnp.ndarray    # (L,)
    delta: jnp.ndarray    # (M,)
    Phi: jnp.ndarray      # (M, M)
    mu: jnp.ndarray       # (M,)  = (I - Phi) δ
    nu: jnp.ndarray       # (L,)  = (1 - B) ⊙ ω  (0 if random walk)


class StaticParams(NamedTuple):
    gamma: jnp.ndarray    # (L,)
    delta: jnp.ndarray    # (M,)
    Phi: jnp.ndarray      # (M, M)
    mu: jnp.ndarray       # (M,)


class KalmanParams(NamedTuple):
    gamma: Optional[jnp.ndarray]  # (1,) λ driver (DNS only)
    obs_var: jnp.ndarray          # scalar measurement variance
    Omega_state: jnp.ndarray      # (Ms, Ms) = CᵀC
    delta: jnp.ndarray            # (Ms,)
    Phi: jnp.ndarray              # (Ms, Ms)


def unpack_msed(spec: ModelSpec, params) -> MSEDParams:
    """msedriven/paramteroperations.jl:25-65 semantics: β₀=δ, γ₀=ω, μ=(I−Φ)δ,
    ν=(1−B)⊙ω; Φ filled column-major."""
    M = spec.M
    A = expand_params(spec.slice(params, "A"), spec.duplicator)
    if spec.random_walk:
        B = None
    else:
        B = expand_params(spec.slice(params, "B"), spec.duplicator)
    omega = spec.slice(params, "omega")
    delta = spec.slice(params, "delta")
    Phi = spec.slice(params, "phi").reshape(params.shape[:-1] + (M, M))
    Phi = jnp.swapaxes(Phi, -1, -2)  # column-major vec -> matrix
    mu = delta - Phi @ delta
    nu = jnp.zeros_like(omega) if B is None else (1.0 - B) * omega
    return MSEDParams(A, B, omega, delta, Phi, mu, nu)


def unpack_static(spec: ModelSpec, params) -> StaticParams:
    M = spec.M
    gamma = spec.slice(params, "gamma")
    delta = spec.slice(params, "delta")
    Phi = spec.slice(params, "phi").reshape(params.shape[:-1] + (M, M))
    Phi = jnp.swapaxes(Phi, -1, -2)
    mu = delta - Phi @ delta
    return StaticParams(gamma, delta, Phi, mu)


def unpack_kalman(spec: ModelSpec, params) -> KalmanParams:
    """kalman/paramoperations.jl:6-58: Ω_obs = σ²I; Ω_state = CᵀC with C the
    upper-triangular factor filled column-by-column; Φ filled row-major."""
    Ms = spec.state_dim
    # layout-driven, not family-listed: program-compiled specs (program/)
    # carry a γ head exactly when their block table declares one
    gamma = (spec.slice(params, "gamma")
             if "gamma" in spec.layout else None)
    obs_var = spec.slice(params, "obs_var")[..., 0]
    chol_flat = spec.slice(params, "chol")
    rows, cols = spec.chol_indices
    C = jnp.zeros(params.shape[:-1] + (Ms, Ms), dtype=params.dtype)
    C = C.at[..., rows, cols].set(chol_flat)
    Omega_state = jnp.swapaxes(C, -1, -2) @ C
    delta = spec.slice(params, "delta")
    Phi = spec.slice(params, "phi").reshape(params.shape[:-1] + (Ms, Ms))
    return KalmanParams(gamma, obs_var, Omega_state, delta, Phi)


def unpack(spec: ModelSpec, params):
    if spec.is_kalman:
        return unpack_kalman(spec, params)
    if spec.is_msed:
        return unpack_msed(spec, params)
    return unpack_static(spec, params)


# ---------------------------------------------------------------------------
# initialization (get_new_initial_params / initialize_with_static_params)
# ---------------------------------------------------------------------------

def get_new_initial_params(spec: ModelSpec, params, trial: int, rng: np.random.Generator | None = None):
    """Trial-indexed initial parameter proposals.

    - MSED: enumerate the A×B guess grid (msedriven/paramteroperations.jl:132-187);
      returns None once the grid is exhausted.
    - static λ: jitter non-(δ,Φ) by U(-0.05, 0.05) (static/paramteroperations.jl:89-97)
    - static neural: structured randn/10 layer init (:99-114)
    - kalman: standard normal redraw (kalman/paramoperations.jl:92-97)

    ``trial`` is 1-based (Julia convention; the grid walk below depends on it).
    """
    if trial < 1:
        raise ValueError(f"trial is 1-based; got {trial}")
    params = np.asarray(params, dtype=np.float64).copy()
    if rng is None:
        rng = np.random.default_rng(trial)

    if spec.is_msed:
        num_A = len(spec.A_guesses)
        num_B = 0 if spec.random_walk else len(spec.B_guesses)
        u = spec.n_unique
        has_B = num_B > 0
        if u == 1:
            total = num_A * num_B if has_B else num_A
        else:
            total = (num_A ** 2) * (num_B ** 2) if has_B else num_A ** 2
        if trial > total:
            return None
        t = trial - 1
        if u == 1:
            if has_B:
                params[0] = spec.A_guesses[t // num_B]
                params[1] = spec.B_guesses[t % num_B]
            else:
                params[0] = spec.A_guesses[t]
        else:
            half = u // 2
            if has_B:
                a1 = t // (num_A * num_B ** 2)
                rem = t % (num_A * num_B ** 2)
                a2 = rem // (num_B ** 2)
                rem = rem % (num_B ** 2)
                b1 = rem // num_B
                b2 = rem % num_B
                params[0:half] = spec.A_guesses[a1]
                params[half:u] = spec.A_guesses[a2]
                params[u:u + half] = spec.B_guesses[b1]
                params[u + half:2 * u] = spec.B_guesses[b2]
            else:
                params[0:half] = spec.A_guesses[t // num_A]
                params[half:u] = spec.A_guesses[t % num_A]
        return params

    if spec.family == "static_neural":
        params[0:3] = rng.standard_normal(3) / 10
        params[3:6] = 0.0
        params[6:9] = rng.standard_normal(3) / 10
        params[9:12] = rng.standard_normal(3) / 10
        params[12:15] = 0.0
        params[15:18] = rng.standard_normal(3) / 10
        return params

    if spec.is_static:
        tail = spec.M * (spec.M + 1)
        head = params.shape[0] - tail
        params[:head] += rng.uniform(size=head) * 0.1 - 0.05
        return params

    # kalman
    return rng.standard_normal(params.shape[0])


def initialize_with_static_params(spec: ModelSpec, params, static_params):
    """Warm start from a simpler (static) model's fitted parameters.

    - MSED: overwrite the [ω; δ; Φ] tail (msedriven/paramteroperations.jl:124-128)
    - TVλ: index map from the "1C" fit (kalman/paramoperations.jl:78-89)
    - others: no-op
    """
    params = np.asarray(params, dtype=np.float64).copy()
    sp = np.asarray(static_params, dtype=np.float64).reshape(-1)
    if spec.is_msed:
        params[len(params) - len(sp):] = sp
        return params
    if spec.family == "kalman_tvl":
        params[0:1] = sp[1:2]
        params[1:7] = sp[-18:-12]
        params[11:14] = sp[-12:-9]
        params[15:18] = sp[-9:-6]
        params[19:22] = sp[-6:-3]
        params[23:26] = sp[-3:]
        return params
    return params
