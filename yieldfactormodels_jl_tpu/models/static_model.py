"""Static (fixed-loading) filters: OLS cross-sections and the random-walk
benchmark, as `lax.scan` kernels.

Parity targets: /root/reference/src/models/filter.jl:93-110 (static OLS +
transition), :112-120 (random walk), with the same get_loss/get_loss_array/
predict conventions as the score-driven family (:209-306).

Because γ is a *static* parameter here, Z is computed once outside the scan —
the reference recomputes nothing either (update_factor_loadings! only runs in
set_params!, static/paramteroperations.jl:42).
"""

from __future__ import annotations


import jax.numpy as jnp
from jax import lax

from ..ops.linalg import ols_solve
from ..robustness import taxonomy as tax
from .common import partial_nan_poison, window_contributions
from .loadings import dns_loadings, neural_loadings
from .params import StaticParams, unpack_static
from .specs import ModelSpec


def loadings_fn(spec: ModelSpec, gamma):
    mats = spec.maturities_array
    if spec.family == "static_lambda":
        return dns_loadings(gamma, mats)
    if spec.family == "static_neural":
        return neural_loadings(gamma, mats, spec.transform_bool)
    # random walk: loadings are the untouched all-ones Z (randomwalk.jl:46-49)
    return jnp.ones((spec.N, spec.M), dtype=gamma.dtype)


def _static_scan(spec: ModelSpec, sp: StaticParams, Z, data, start, end):
    T = data.shape[1]
    t_idx = jnp.arange(T)
    observed_mask = (t_idx >= start) & (t_idx < end)

    def body(beta, inp):
        y, obs_t = inp
        obs = obs_t & jnp.isfinite(y[0])
        ysafe = jnp.where(jnp.isfinite(y), y, 0.0)
        beta_ols = ols_solve(Z, ysafe)
        # partially-NaN observed column ⇒ NaN β, loss −Inf (reference parity)
        beta_obs = jnp.where(obs, beta_ols, beta) * partial_nan_poison(y, obs)
        beta_next = sp.mu + sp.Phi @ beta_obs
        pred = Z @ beta_next
        return beta_next, {"pred": pred, "beta": beta_next}

    beta0 = sp.delta  # set_params!: β = δ (static/paramteroperations.jl:40)
    _, outs = lax.scan(body, beta0, (data.T, observed_mask))
    return outs


def _rw_scan(spec: ModelSpec, data, start, end):
    T = data.shape[1]
    t_idx = jnp.arange(T)
    observed_mask = (t_idx >= start) & (t_idx < end)

    def body(last_y, inp):
        y, obs_t = inp
        obs = obs_t & jnp.isfinite(y[0])
        new_last = jnp.where(obs, jnp.where(jnp.isfinite(y), y, last_y), last_y)
        return new_last, {"pred": new_last}

    last0 = jnp.zeros((spec.N,), dtype=data.dtype)
    _, outs = lax.scan(body, last0, (data.T, observed_mask))
    return outs


def _run(spec: ModelSpec, params, data, start, end):
    if spec.family == "random_walk":
        return None, None, _rw_scan(spec, data, start, end)
    sp = unpack_static(spec, params)
    Z = loadings_fn(spec, sp.gamma)
    return sp, Z, _static_scan(spec, sp, Z, data, start, end)


def get_loss(spec: ModelSpec, params, data, start=0, end=None, K: int = 1):
    T = data.shape[1]
    if end is None:
        end = T
    nobs = end - start
    total = 0.0
    for _ in range(K):  # static filters have no cross-pass state
        _, _, outs = _run(spec, params, data, start, end)
        total = total + jnp.sum(window_contributions(outs["pred"], data, start, end))
    loss = total / spec.N / nobs / K
    return jnp.where(jnp.isfinite(loss), loss, -jnp.inf)


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None):
    """``(loss, code)``: :func:`get_loss` (K=1) plus the taxonomy bitmask
    (robustness/taxonomy.py) — STATE_EXPLODED for a non-finite trajectory on
    an observed step (incl. the reference-parity partial-NaN β poisoning),
    MISSING_ALL_OBS for a window with no observed columns."""
    T = data.shape[1]
    if end is None:
        end = T
    nobs = end - start
    _, _, outs = _run(spec, params, data, start, end)
    total = jnp.sum(window_contributions(outs["pred"], data, start, end))
    loss = total / spec.N / nobs
    loss = jnp.where(jnp.isfinite(loss), loss, -jnp.inf)
    t_idx = jnp.arange(T)
    in_win = (t_idx >= start) & (t_idx < end)
    observed = in_win & jnp.isfinite(data[0, :])  # filter.jl:95 convention
    bad_step = in_win & ~jnp.all(jnp.isfinite(outs["pred"]), axis=-1)
    code = tax.params_code(params) \
        | tax.bit(jnp.any(bad_step), tax.STATE_EXPLODED) \
        | tax.bit(~jnp.any(observed), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code


def get_loss_array(spec: ModelSpec, params, data, start=0, end=None, K: int = 1):
    T = data.shape[1]
    if end is None:
        end = T
    _, _, outs = _run(spec, params, data, start, end)
    return window_contributions(outs["pred"], data, start, end) * (1.0 / spec.N)


def predict(spec: ModelSpec, params, data):
    T = data.shape[1]
    if spec.family == "random_walk":
        outs = _rw_scan(spec, data, 0, T)
        zeros_M = jnp.zeros((spec.M, T), dtype=data.dtype)
        zeros_L = jnp.zeros((spec.L, T), dtype=data.dtype)
        ones_N = jnp.ones((spec.N, T), dtype=data.dtype)
        return {
            "preds": outs["pred"].T,
            "factors": zeros_M,     # RW never writes β/γ (randomwalk.jl:3-32)
            "states": zeros_L,
            "factor_loadings_1": ones_N,  # untouched all-ones Z columns
            "factor_loadings_2": ones_N,
        }
    sp, Z, outs = _run(spec, params, data, 0, T)
    gamma_states = jnp.broadcast_to(sp.gamma, (T, spec.L)).T
    fl1 = jnp.broadcast_to(Z[:, 1], (T, spec.N)).T
    fl2 = jnp.broadcast_to(Z[:, 2], (T, spec.N)).T
    return {
        "preds": outs["pred"].T,
        "factors": outs["beta"].T,
        "states": gamma_states,
        "factor_loadings_1": fl1,
        "factor_loadings_2": fl2,
    }
