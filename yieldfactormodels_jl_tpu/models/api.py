"""Family-dispatch façade: one callable surface over the three filter engines.

Mirrors the multiple-dispatch seams of the reference (`get_loss`, `predict`,
`get_loss_array`, `update_factor_loadings!` dispatch on the model's abstract
type).  All functions take (spec, constrained-params, data) and are pure — jit
and vmap them freely.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import kalman, score_driven, static_model
from .loadings import dns_loadings
from .params import unpack
from .specs import ModelSpec


def _engine(spec: ModelSpec):
    if spec.is_kalman:
        return kalman
    if spec.is_msed:
        return score_driven
    return static_model


def get_loss(spec: ModelSpec, params, data, start=0, end=None, K: int = 1,
             engine: str | None = None):
    if spec.is_kalman:
        # Default production path is the univariate (sequential-observation)
        # kernel: algebraically identical to the joint form for the diagonal
        # Ω_obs all models here use, but Cholesky-free — rank-1 FMAs that stay
        # in true f32 on TPU where the joint form's batched N×N Cholesky/
        # matmuls drop to bf16 MXU passes (≈33× faster AND more precise on
        # TPU; see ops/univariate_kf.py).  Alternatives (config.KALMAN_ENGINES)
        # are trace-time choices: "sqrt" (Potter, PSD-by-construction f32),
        # "joint" (textbook), "assoc" (parallel-in-time; constant-Z families)
        # and "slr" (parallel-in-time iterated SLR; every Kalman family incl.
        # the state-dependent-measurement ones).  WHICH engines apply to a
        # family is config.engines_for(spec) — the one introspection seam
        # (docs/DESIGN.md §19), consulted by the validation below, the error
        # message, and the T-switch dispatch alike.
        from .. import config
        from ..ops import univariate_kf

        name = engine or config.kalman_engine()
        if name not in config.KALMAN_ENGINES:
            raise ValueError(
                f"unknown kalman engine {name!r}; pick from {config.KALMAN_ENGINES}")
        valid = config.engines_for(spec)
        if engine is not None and engine not in valid:
            raise ValueError(
                f"engine {engine!r} is not applicable to family "
                f"{spec.family!r}; config.engines_for lists {valid}")
        if engine is None and name not in valid:
            # the process-wide default does not apply to this family (e.g.
            # set_kalman_engine("assoc") then a TVλ loss): fall back to the
            # family-universal sequential default rather than erroring a
            # call that never chose an engine itself
            name = "univariate"
        if (engine is None and name == "univariate"
                and 0 < config.loglik_t_switch() <= data.shape[1]):
            # engine-dispatch policy (YFM_LOGLIK_T_SWITCH, docs/DESIGN.md
            # §13/§19): long panels ride the family's O(log T) parallel-in-
            # time tree — "assoc" for the constant-Z families, "slr" for the
            # nonlinear ones — short panels keep the sequential default
            # whose constant factor wins.  Only the PRODUCTION DEFAULT is
            # upgraded — an explicit per-call engine or a deliberate
            # process-wide "sqrt"/"joint" choice is never overridden.  T is
            # static at trace time, so the dispatch costs nothing at run
            # time; the jitted-loss caches that bake the choice in are
            # invalidated by config.set_loglik_t_switch (the
            # @register_engine_cache contract).
            name = config.tree_engine_for(spec) or name
        if name == "sqrt":
            from ..ops import sqrt_kf

            return sqrt_kf.get_loss(spec, params, data, start, end)
        if name == "joint":
            return kalman.get_loss(spec, params, data, start, end)
        if name == "assoc":
            from ..ops import assoc_scan

            return assoc_scan.get_loss(spec, params, data, start, end)
        if name == "slr":
            from ..ops import slr_scan

            return slr_scan.get_loss(spec, params, data, start, end)
        return univariate_kf.get_loss(spec, params, data, start, end)
    if spec.is_msed:
        # The score-driven families carry the same engine seam
        # (config.MSED_ENGINES): "scan" is the sequential reference-parity
        # default, "score_tree" the O(log T) parallel-in-time engine for
        # the capable specs (spec.supports_score_tree — docs/DESIGN.md §19).
        from .. import config

        valid = config.engines_for(spec)
        if engine is not None and engine not in valid:
            raise ValueError(
                f"engine {engine!r} is not applicable to family "
                f"{spec.family!r}; config.engines_for lists {valid}")
        name = engine or "scan"
        if name == "score_tree" and K != 1:
            # the tree has no K-replay semantics (K >= 2 CONTINUES the
            # sequential filter from its end state — a second pass, not a
            # restart); keep the contract loud instead of approximating
            raise ValueError(
                "engine 'score_tree' supports K=1 only; use the sequential "
                "'scan' engine for K-replay losses")
        if (engine is None and K == 1
                and 0 < config.loglik_t_switch() <= data.shape[1]
                and config.tree_engine_for(spec) == "score_tree"):
            # the same YFM_LOGLIK_T_SWITCH policy as the Kalman branch:
            # long panels ride the family's tree engine, short panels keep
            # the sequential default; only the production default upgrades
            name = "score_tree"
        if name == "score_tree":
            from ..ops import score_scan

            return score_scan.get_loss(spec, params, data, start, end)
        return score_driven.get_loss(spec, params, data, start, end, K)
    if engine is not None:
        # static families are closed-form regressions with no state
        # recursion to parallelize — engines_for(spec) is () and an
        # explicit choice is a caller error, not a silent ignore
        from .. import config

        raise ValueError(
            f"engine {engine!r} is not applicable to family "
            f"{spec.family!r}; config.engines_for lists "
            f"{config.engines_for(spec)}")
    return _engine(spec).get_loss(spec, params, data, start, end, K)


def get_loss_array(spec: ModelSpec, params, data, start=0, end=None, K: int = 1):
    return _engine(spec).get_loss_array(spec, params, data, start, end, K)


def predict(spec: ModelSpec, params, data):
    return _engine(spec).predict(spec, params, data)


def forecast_density(spec: ModelSpec, params, data, horizon: int,
                     start=0, end=None, engine=None):
    """h-step-ahead Gaussian predictive densities (means + covariances) for
    the Kalman families — see ops/forecast.py.  The BASELINE north star's
    "multi-step predictive density" (api.predict gives the point-forecast
    artifact set; this gives the distributions)."""
    from ..ops.forecast import forecast_density as _fd

    return _fd(spec, params, data, horizon, start, end, engine=engine)


def simulate(spec: ModelSpec, params, T: int, key,
             sv_phi: float = 0.0, sv_sigma: float = 0.0):
    """Simulate a (N, T) yield panel (+ latent state/vol paths) from a
    Kalman-family model — see models/simulate.py (beyond-reference: the
    reference's simulation mode only reads pre-simulated CSVs)."""
    from .simulate import simulate as _sim

    return _sim(spec, params, T, key, sv_phi=sv_phi, sv_sigma=sv_sigma)


def smooth(spec: ModelSpec, params, data, start=0, end=None, engine=None):
    """Fixed-interval RTS smoothed moments β_{t|T}, P_{t|T} (Kalman families
    only — see ops/smoother.py; beyond-reference capability).

    Engine note: the forward pass honors ``engine`` /
    ``config.set_kalman_engine`` for the moment-emitting engines — "joint"
    (per-step Cholesky) and "univariate" (Cholesky-free, same posterior
    moments).  "sqrt"/"assoc" do not emit the RTS moment set and raise a
    clear error instead of silently substituting another engine.  A failed
    f32 forward factorization poisons the output with NaN; rerun in float64
    in that case."""
    from ..ops import smoother

    return smoother.smooth(spec, params, data, start, end, engine=engine)


def init_state(spec: ModelSpec, params):
    """The scan carry the filter starts from (β₀/γ₀/P₀...)."""
    up = unpack(spec, params)
    if spec.is_kalman:
        return kalman.init_state(spec, up)
    if spec.is_msed:
        return score_driven.init_state(spec, up)
    return up.delta


def update_factor_loadings(spec: ModelSpec, gamma):
    """Z(γ) for any family (reference: per-family update_factor_loadings!)."""
    if spec.is_kalman:
        prog = getattr(spec, "program", None)
        if prog is not None:
            if prog.measurement is not None:
                raise ValueError(
                    f"program {prog.name!r} loadings are state-dependent; "
                    f"see kalman.state_measurement")
            return prog.loadings(gamma, spec.maturities_array)
        if spec.family == "kalman_tvl":
            # TVλ builds Z from the 4th state at filter time
            raise ValueError("kalman_tvl loadings are state-dependent; see kalman._tvl_measurement")
        if spec.family == "kalman_afns":
            from .afns import afns_loadings

            return afns_loadings(gamma, spec.maturities_array, spec.M)
        return dns_loadings(gamma, spec.maturities_array)
    if spec.is_msed:
        return score_driven.loadings_fn(spec, gamma)
    return static_model.loadings_fn(spec, gamma)


def n_params(spec: ModelSpec) -> int:
    return spec.n_params


def get_params(spec: ModelSpec, params):
    """Identity view — the flat vector *is* the parameter representation."""
    return jnp.asarray(params)


def get_param_groups(spec: ModelSpec, param_groups=None):
    """kalmanbasemodel.jl:150-159 etc.: accept a caller-provided grouping only
    if its length matches; otherwise assign the family default."""
    if param_groups is not None and len(param_groups) == spec.n_params:
        return tuple(param_groups)
    return spec.default_param_groups()


def get_static_model_type(spec: ModelSpec) -> str:
    """Warm-start source model code (dns.jl:46-48, tvλdns.jl:48-50,
    mselambda.jl:58-60, mseneural.jl:118-123, staticneural.jl:80-85)."""
    if spec.family == "kalman_dns":
        return "DNS"
    if spec.family == "kalman_tvl":
        return "1C"
    if spec.family == "msed_lambda" or spec.family == "static_lambda":
        return "NS"
    if spec.family in ("msed_neural", "static_neural"):
        return "NNS" if spec.transform_bool else "NNS-Anchored"
    return ""  # random walk


def random_initial_params(spec: ModelSpec, seed: int = 0):
    """U(0,1) draw like load_initial_parameters! fallback
    (YieldFactorModels.jl:145-153)."""
    rng = np.random.default_rng(seed)
    return rng.uniform(size=spec.n_params).astype(spec.dtype_name)
