"""Factor-loading builders: Z(γ) for every model family.

DNS formula parity with /root/reference/src/models/kalman/dns.jl:51-65 (and the
identical copies in mselambda.jl:63-76, staticlambda.jl:46-60):

    λ = 1e-2 + exp(γ);  Z1 = 1;  Z2 = (1 - e^{-λτ})/(λτ);  Z3 = Z2 - e^{-λτ}

Neural loadings parity with /root/reference/src/models/msedriven/mseneural.jl:
two tiny MLPs maturity -> loading, ``Chain(Dense(1=>3, tanh), Dense(3=>1; no
bias))`` (:63-64), parameters packed as γ[0:9] / γ[9:18] in the layout of
``shapeγ`` (:120-133): W1 = γ[0:3] (3×1), b1 = γ[3:6], W2 = γ[6:9] (1×3).
Curves are then pinned to NS shape by the transforms in utils/nn_transform.py.

Everything is a pure function of (γ, maturities) returning a fresh (N, M)
loading matrix — the reference mutates a preallocated Z in place.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..utils.nn_transform import transform_net_1, transform_net_2

LAMBDA_FLOOR = 1e-2


def dns_lambda(gamma_scalar):
    """λ = 1e-2 + exp(γ) (dns.jl:55)."""
    return LAMBDA_FLOOR + jnp.exp(gamma_scalar)


def dns_slope_curvature(lam, maturities):
    """Columns 2 and 3 of the DNS loading matrix for decay rate(s) ``lam``."""
    tau = lam * maturities
    z = jnp.exp(-tau)
    z2 = (1.0 - z) / tau
    z3 = z2 - z
    return z2, z3


def dns_loadings(gamma, maturities):
    """(N, 3) DNS loading matrix from the scalar driver γ (level/slope/curv)."""
    lam = dns_lambda(jnp.reshape(gamma, ())[None])  # (1,)
    z2, z3 = dns_slope_curvature(lam, maturities)
    ones = jnp.ones_like(z2)
    return jnp.stack([ones, z2, z3], axis=-1)


def mlp_curve(p9, maturities):
    """Evaluate the 1->3(tanh)->1(no bias) loading net at each maturity.

    out[n] = Σ_j W2[j] * tanh(W1[j] * τ_n + b1[j]);  p9 packed as shapeγ
    (mseneural.jl:120-133).
    """
    w1 = p9[..., 0:3]
    b1 = p9[..., 3:6]
    w2 = p9[..., 6:9]
    h = jnp.tanh(maturities[..., :, None] * w1[..., None, :] + b1[..., None, :])
    return jnp.einsum("...nj,...j->...n", h, w2)


def neural_loadings(gamma18, maturities, transform_bool: bool):
    """(N, 3) neural NS loading matrix from the 18-dim γ state."""
    raw2 = mlp_curve(gamma18[..., 0:9], maturities)
    raw3 = mlp_curve(gamma18[..., 9:18], maturities)
    z2 = transform_net_1(raw2, maturities, transform_bool)
    z3 = transform_net_2(raw3, maturities, transform_bool)
    ones = jnp.ones_like(z2)
    return jnp.stack([ones, z2, z3], axis=-1)
