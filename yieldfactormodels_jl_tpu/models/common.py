"""Shared loss-window helpers for the prediction-error engines."""

from __future__ import annotations

import jax.numpy as jnp


def window_contributions(preds, data, start, end):
    """−‖y_{t+1} − ŷ_{t+1|t}‖² for contributing steps t (filter.jl:225-234).

    ``preds`` is (T, N) scan output; raw ``data`` (N, T) is used for the target
    so a NaN inside the window poisons the sum into the reference's −Inf
    sentinel.  Contributions run t = start .. end−2 (0-based).
    """
    T = data.shape[1]
    t_idx = jnp.arange(T - 1)
    contrib = (t_idx >= start) & (t_idx <= end - 2)
    v = data[:, 1:].T - preds[:-1]
    return jnp.where(contrib, -jnp.sum(v * v, axis=-1), 0.0)


def partial_nan_poison(y, obs):
    """Reference parity for partially-NaN observed columns.

    The score-driven/static engines treat a column as observed iff its *first*
    entry is finite (filter.jl:53,95); a NaN at any other maturity then flows
    through OLS and poisons β (and the loss → −Inf).  Returns a multiplicative
    scalar: 1.0 normally, NaN when an observed column is partially NaN.
    """
    bad = obs & ~jnp.all(jnp.isfinite(y))
    return jnp.where(bad, jnp.nan, 1.0)
