"""String-code → ModelSpec registry — the plugin boundary.

Full parity with /root/reference/src/model_dictionary.jl:7-128: all 34 model
codes and their numeric aliases, including the "-Anchored" variants
(transform_bool=False), the `pC`/`vanillaNN` placeholders (return None) and the
random-walk benchmark.  Unknown codes raise ValueError (:124).
"""

from __future__ import annotations

from typing import Optional, Tuple

from .specs import ModelSpec, make_duplicator

_NET_SIZE = 3
_NEURAL_L = 3 * _NET_SIZE * 2  # 18 (mseneural.jl:30)


def _msed_lambda(rw: bool, sg: bool):
    return dict(
        family="msed_lambda", L=1, duplicator=(0,), random_walk=rw,
        scale_grad=sg, forget_factor=0.98,  # mselambda.jl:15
    )


def _msed_neural(dynamics: str, rw: bool, sg: bool, anchored: bool):
    return dict(
        family="msed_neural", L=_NEURAL_L,
        duplicator=make_duplicator(dynamics, _NEURAL_L, _NET_SIZE),
        dynamics=dynamics, random_walk=rw, scale_grad=sg,
        forget_factor=0.9,  # mseneural.jl:28
        transform_bool=not anchored,
    )


def _build_table():
    t = {}

    def add(code, alias, **kw):
        t[code] = (code, kw)
        t[alias] = (code, kw)

    add("1C", "0", family="kalman_dns", L=1)
    add("TVλ", "1", family="kalman_tvl", L=1)
    add("NS", "2", family="static_lambda", L=1)
    add("NNS", "3", family="static_neural", L=_NEURAL_L)

    add("SD-NS", "4", **_msed_lambda(False, False))
    add("RWSD-NS", "5", **_msed_lambda(True, False))
    add("SSD-NS", "6", **_msed_lambda(False, True))
    add("SRWSD-NS", "7", **_msed_lambda(True, True))

    dyn = {"1": "scalar", "2": "block_diag", "3": "diag"}
    num = 8
    for sg in (False, True):
        for d in ("1", "2", "3"):
            for rw in (False, True):
                code = f"{d}{'S' if sg else ''}{'RW' if rw else ''}SD-NNS"
                add(code, str(num), **_msed_neural(dyn[d], rw, sg, anchored=False))
                num += 1
    assert num == 20

    add("NNS-Anchored", "20", family="static_neural", L=_NEURAL_L, transform_bool=False)
    num = 21
    for sg in (False, True):
        for d in ("1", "2", "3"):
            for rw in (False, True):
                code = f"{d}{'S' if sg else ''}{'RW' if rw else ''}SD-NNS-Anchored"
                add(code, str(num), **_msed_neural(dyn[d], rw, sg, anchored=True))
                num += 1
    assert num == 33

    t["pC"] = ("pC", None)
    t["1100"] = ("pC", None)
    t["vanillaNN"] = ("vanillaNN", None)
    t["a"] = ("vanillaNN", None)
    add("RW", "-1", family="random_walk", L=1)

    # Extensions beyond the reference (BASELINE.md benchmark configs):
    # arbitrage-free NS with yield-adjustment term; AFNS5 = AFGNS (two decays).
    add("AFNS3", "af3", family="kalman_afns", L=1, M_override=3)
    add("AFNS5", "af5", family="kalman_afns", L=2, M_override=5)
    return t


_TABLE = _build_table()
MODEL_CODES = sorted({canon for canon, _ in _TABLE.values()})


def valid_codes() -> Tuple[str, ...]:
    """Every code :func:`create_model` accepts right now: the zoo's canonical
    codes plus the registered program codes (program/registry.py) — the list
    the unknown-code ``ValueError`` names."""
    try:
        from ..program.registry import registered_codes
    except ImportError:  # program layer absent/partial: zoo codes only
        return tuple(MODEL_CODES)
    return tuple(sorted({*MODEL_CODES, *registered_codes()}))


def create_model(
    model_type: str,
    maturities,
    N: Optional[int] = None,
    M: int = 3,
    float_type="float32",
    results_location: str = "results/",
) -> Tuple[Optional[ModelSpec], str]:
    """model_dictionary.jl:7 equivalent.  Returns (spec | None, canonical code).

    Program codes (``program.register_program``) resolve here too — the
    compiled :class:`~..program.compile.ProgramSpec` comes back through the
    same factory seam as the hand-ported zoo (``M`` is ignored for programs;
    the declaration owns its factor count)."""
    if model_type not in _TABLE:
        # registered declarative programs share the factory seam; import
        # through the package so the shipped library registers first
        from .. import program as _program

        prog = _program.lookup(model_type)
        if prog is not None:
            spec = _program.build_spec(
                prog, maturities, N=N, float_type=float_type,
                results_location=results_location)
            return spec, prog.name
        raise ValueError(
            f"Invalid model type: {model_type!r}; valid codes (aliases "
            f"omitted): {valid_codes()}")
    canon, kw = _TABLE[model_type]
    if kw is None:  # pC / vanillaNN placeholders (model_dictionary.jl:114-119)
        return None, canon
    mats = tuple(float(m) for m in maturities)
    if N is not None and N != len(mats):
        raise ValueError(f"N={N} does not match len(maturities)={len(mats)}")
    kw = dict(kw)
    M = kw.pop("M_override", M)
    import numpy as _np

    dtype_name = _np.dtype(float_type).name
    if dtype_name == "float64":
        import jax as _jax

        if not _jax.config.jax_enable_x64:
            import warnings

            warnings.warn(
                "float_type=float64 requested but jax_enable_x64 is off — "
                "arrays will silently truncate to float32. Set JAX_ENABLE_X64=1 "
                "or jax.config.update('jax_enable_x64', True) first.",
                stacklevel=2,
            )
    spec = ModelSpec(
        model_code=canon,
        maturities=mats,
        M=M,
        dtype_name=dtype_name,
        model_string=model_type,
        results_location=results_location,
        **kw,
    )
    return spec, canon
