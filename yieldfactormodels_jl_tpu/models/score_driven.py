"""Score-driven (MSE-driven) filter as a `lax.scan` kernel — the hot path.

Per-step recursion parity with /root/reference/src/models/filter.jl:52-91:

1. β ← OLS(Z, y_t) with ridge fallback (:122-137)
2. score = ∇_γ −‖y_t − Z(γ)β̄‖² with β̄ *detached* — the reference evaluates
   ``ForwardDiff.value.(beta)`` inside the inner closure (:175), which here is
   ``stop_gradient`` so the outer MLE differentiates through the inner update
   exactly the way the reference's nested-dual setup does,
3. γ update — plain γ += A⊙score, or EWMA-scaled (Adam-like second-moment
   normalization with bias correction) when ``scale_grad`` (:29-50),
4. refresh Z(γ), re-OLS (:75-81),
5. transition γ ← ν + B⊙γ (skipped for random-walk dynamics where B is
   empty), β ← μ + Φβ; emit ŷ = Zβ (:84-90).

NaN observation ⇒ transition-only step (:53-60).  γ₀ = ω and β₀ = δ are fixed
points of the transition (set_params! at msedriven/paramteroperations.jl:55-63),
so masking a prefix of the sample is exactly equivalent to truncating it —
rolling windows batch as a vmap axis with no approximation.

The inner gradient inside the scan makes the whole loss a second-order AD
problem under the outer optimizer; JAX's grad-of-grad through scan handles it
without the reference's `Ref{Any}` dual-buffer machinery.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.linalg import ols_solve
from ..robustness import taxonomy as tax
from .common import partial_nan_poison, window_contributions
from .loadings import dns_loadings, neural_loadings
from .params import MSEDParams, unpack_msed
from .specs import ModelSpec


class MSEDState(NamedTuple):
    gamma: jnp.ndarray   # (L,)
    beta: jnp.ndarray    # (M,)
    ewma: jnp.ndarray    # (L,) second-moment EWMA (scale_grad)
    count: jnp.ndarray   # () int32 bias-correction counter


def loadings_fn(spec: ModelSpec, gamma):
    mats = spec.maturities_array
    prog = getattr(spec, "program", None)
    if prog is not None:
        # program-declared msed loadings; the score is AD through the
        # user callable (the same jax.grad path as the zoo families)
        return prog.loadings(gamma, mats)
    if spec.family == "msed_lambda":
        return dns_loadings(gamma, mats)
    return neural_loadings(gamma, mats, spec.transform_bool)


def init_state(spec: ModelSpec, mp: MSEDParams) -> MSEDState:
    """β₀ = δ, γ₀ = ω (paramteroperations.jl:55-57); EWMA state zeroed
    (filter.jl:19-26)."""
    return MSEDState(
        gamma=mp.omega,
        beta=mp.delta,
        ewma=jnp.zeros_like(mp.omega),
        count=jnp.zeros((), dtype=jnp.int32),
    )


def _score(spec: ModelSpec, gamma, beta_detached, y):
    """∇_γ of −‖y − Z(γ)β̄‖² (filter.jl:168-184)."""

    def neg_sq_err(g):
        Z = loadings_fn(spec, g)
        v = y - Z @ beta_detached
        return -jnp.dot(v, v)

    return jax.grad(neg_sq_err)(gamma)


def plain_gamma_update(spec: ModelSpec, mp: MSEDParams, gamma, ysafe, obs):
    """The non-``scale_grad`` γ measurement update — OLS β̄, analytic score,
    γ ← γ + A⊙score on observed steps — returned as ``(gamma_obs, Z)``.

    Single source shared by the sequential :func:`_step` and the score-tree
    engine (ops/score_scan.py), which linearizes exactly this map for its
    affine prefix elements and re-runs it exactly in the refinement sweeps
    (the ``spec.supports_score_tree`` capability is precisely "the γ update
    is THIS function")."""
    Z = loadings_fn(spec, gamma)
    beta_ols = ols_solve(Z, ysafe)
    beta_for_score = lax.stop_gradient(beta_ols) if spec.detach_inner_beta else beta_ols
    grad = _score(spec, gamma, beta_for_score, ysafe)
    return jnp.where(obs, gamma + grad * mp.A, gamma), Z


def plain_gamma_transition(mp: MSEDParams, gamma_obs):
    """γ ← ν + B⊙γ (identity for random-walk dynamics where B is empty) —
    the transition half of the γ recursion, shared with ops/score_scan.py
    for the same single-source reason as :func:`plain_gamma_update`."""
    if mp.B is None:
        return gamma_obs
    return mp.nu + mp.B * gamma_obs


def _step(spec: ModelSpec, mp: MSEDParams, state: MSEDState, y, observed):
    gamma, beta, ewma, count = state
    dtype = gamma.dtype
    obs = observed & jnp.isfinite(y[0])  # reference checks y[1] only (filter.jl:53)
    obs_f = obs.astype(dtype)
    ysafe = jnp.where(jnp.isfinite(y), y, 0.0)
    # A partially-NaN observed column poisons β in the reference (NaN through
    # OLS ⇒ loss −Inf); replicate by tainting the step's outputs with NaN.
    poison = partial_nan_poison(y, obs)

    # --- measurement update (computed unconditionally, masked in) ---
    if spec.scale_grad:
        Z = loadings_fn(spec, gamma)
        beta_ols = ols_solve(Z, ysafe)
        beta_for_score = lax.stop_gradient(beta_ols) if spec.detach_inner_beta else beta_ols
        grad = _score(spec, gamma, beta_for_score, ysafe)
        ff = jnp.asarray(spec.forget_factor, dtype)
        new_ewma = ff * ewma + (1.0 - ff) * grad * grad
        new_count = count + 1
        denom = 1.0 - ff ** new_count.astype(dtype)
        eps = jnp.asarray(jnp.finfo(dtype).eps, dtype)
        scaled = grad / (jnp.sqrt(new_ewma / denom) + eps)
        gamma_upd = gamma + scaled * mp.A
        ewma = jnp.where(obs, new_ewma, ewma)
        count = jnp.where(obs, new_count, count)
        gamma_obs = jnp.where(obs, gamma_upd, gamma)
    else:
        gamma_obs, Z = plain_gamma_update(spec, mp, gamma, ysafe, obs)

    Z_upd = loadings_fn(spec, gamma_obs)
    beta_reols = ols_solve(Z_upd, ysafe)
    beta_obs = jnp.where(obs, beta_reols, beta) * poison

    # --- transition (always applied; filter.jl:84-90 and the NaN branch :53-60) ---
    if mp.B is None:
        gamma_next = gamma_obs
        Z_next = jnp.where(obs, Z_upd, Z)  # no refresh on missing steps
    else:
        gamma_next = mp.nu + mp.B * gamma_obs
        Z_next = loadings_fn(spec, gamma_next)
    beta_next = mp.mu + mp.Phi @ beta_obs
    pred = Z_next @ beta_next

    out = {
        "pred": pred,
        "beta": beta_next,
        "gamma": gamma_next,
        "Z2": Z_next[:, 1],
        "Z3": Z_next[:, 2],
        # pre-transition measurement β (post-update) — on fully-observed
        # windows this is pure OLS, independent of (δ, Φ): the fact the
        # closed-form group-"2" solve in estimation/optimize.py exploits
        "beta_obs": beta_obs,
        # taxonomy bitmask beside the −Inf sentinel (robustness/taxonomy.py):
        # a non-finite trajectory on an observed step — overflowed γ update,
        # or the reference-parity partial-NaN β poisoning — is STATE_EXPLODED
        "code": tax.bit(obs & ~jnp.all(jnp.isfinite(pred)),
                        tax.STATE_EXPLODED),
    }
    return MSEDState(gamma_next, beta_next, ewma, count), out


def scan_filter(spec: ModelSpec, params, data, start, end, state: MSEDState | None = None):
    mp = unpack_msed(spec, params)
    if state is None:
        state = init_state(spec, mp)
    T = data.shape[1]
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)

    def body(st, inp):
        y, obs_t = inp
        return _step(spec, mp, st, y, obs_t)

    state, outs = lax.scan(body, state, (data.T, observed))
    return mp, state, outs


def get_loss(spec: ModelSpec, params, data, start=0, end=None, K: int = 1):
    """One-step-ahead forecast MSE, normalized by N·nobs·K (filter.jl:209-243).

    K > 1 replays the filter pass: the reference restores parameters caught at
    a checkpoint, but since the static parameter vector never changes during
    filtering this amounts to continuing from the end state (k = 1) or
    restarting from the unconditional state (k ≥ 2) — replicated faithfully.
    """
    T = data.shape[1]
    if end is None:
        end = T
    nobs = end - start
    mp = unpack_msed(spec, params)
    state = init_state(spec, mp)
    total = 0.0
    for k in range(K):
        if k >= 2:
            state = MSEDState(mp.omega, mp.delta, state.ewma, state.count)
        mp, state, outs = scan_filter(spec, params, data, start, end, state)
        total = total + jnp.sum(window_contributions(outs["pred"], data, start, end))
    loss = total / spec.N / nobs / K
    return jnp.where(jnp.isfinite(loss), loss, -jnp.inf)


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None):
    """``(loss, code)``: :func:`get_loss` (K=1) plus the taxonomy bitmask
    riding the scan outputs (robustness/taxonomy.py)."""
    T = data.shape[1]
    if end is None:
        end = T
    nobs = end - start
    mp = unpack_msed(spec, params)
    _, _, outs = scan_filter(spec, params, data, start, end, init_state(spec, mp))
    total = jnp.sum(window_contributions(outs["pred"], data, start, end))
    loss = total / spec.N / nobs
    loss = jnp.where(jnp.isfinite(loss), loss, -jnp.inf)
    t_idx = jnp.arange(T)
    in_win = (t_idx >= start) & (t_idx < end)
    observed = in_win & jnp.isfinite(data[0, :])  # filter.jl:53 convention
    code = tax.params_code(params) \
        | tax.combine(jnp.where(in_win, outs["code"], jnp.int32(0))) \
        | tax.bit(~jnp.any(observed), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code


def get_loss_array(spec: ModelSpec, params, data, start=0, end=None, K: int = 1):
    """Per-step loss vector of length T−1 (filter.jl:245-281)."""
    T = data.shape[1]
    if end is None:
        end = T
    mp = unpack_msed(spec, params)
    state = init_state(spec, mp)
    acc = jnp.zeros((T - 1,), dtype=data.dtype)
    for k in range(K):
        if k >= 2:
            state = MSEDState(mp.omega, mp.delta, state.ewma, state.count)
        mp, state, outs = scan_filter(spec, params, data, start, end, state)
        acc = acc + window_contributions(outs["pred"], data, start, end)
    return acc / spec.N / K


def predict(spec: ModelSpec, params, data):
    """Filter all T columns, recording post-transition values at column t
    (filter.jl:284-306).  NaN columns give multi-step forecasts."""
    _, _, outs = scan_filter(spec, params, data, 0, data.shape[1])
    return {
        "preds": outs["pred"].T,
        "factors": outs["beta"].T,
        "states": outs["gamma"].T,
        "factor_loadings_1": outs["Z2"].T,
        "factor_loadings_2": outs["Z3"].T,
    }
