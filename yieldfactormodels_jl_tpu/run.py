"""Top-level experiment driver.

Parity with /root/reference/src/YieldFactorModels.jl:221-347 ``run(...)``:
path setup, CSV data loading, model creation from a string code, initial
parameter loading (with random fallback written to disk), static warm-start
cascade, estimation (block-coordinate by default — ``get_param_groups`` always
assigns a non-empty grouping, so ``estimate_steps`` is the reference's live
path), in-sample save + out-of-sample loss quantile prints, and rolling
forecasts.  ``simulation=True`` forces no-window forecasting and disables
optimization/saving (:241-246).  M = 3 factors, seed default 43, Float32
default — all as the reference hard-codes (:262, :238, :227).
"""

from __future__ import annotations

import os
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from .estimation import optimize as opt
from .forecasting import run_rolling_forecasts
from .models import api
from .models.params import initialize_with_static_params
from .models.registry import create_model
from .persistence.io import save_results
from .utils.data_management import load_data


def setup_data_paths(model_type: str, simulation: bool, scratch_dir: str,
                     thread_id: str):
    """YieldFactorModels.jl:87-98."""
    if simulation:
        data_folder = os.path.join(scratch_dir, "YieldFactorModels.jl", "data_simulation") + os.sep
        results = os.path.join(scratch_dir, "YieldFactorModels.jl", "results_simulation",
                               f"thread_id__{thread_id}") + os.sep
    else:
        data_folder = os.path.join(scratch_dir, "YieldFactorModels.jl", "data") + os.sep
        results = os.path.join(scratch_dir, "YieldFactorModels.jl", "results",
                               f"thread_id__{thread_id}") + os.sep
    return data_folder, results


def _init_folder(model_string: str, scratch_dir: str = "") -> str:
    # reference keeps this relative to the working dir (kalmanbasemodel.jl:122)
    return os.path.join("YieldFactorModels.jl", "initializations", model_string) + os.sep


def load_initial_parameters(spec, model_type: str, float_type, simulation: bool = False):
    """CSV initial parameters with random-U(0,1) fallback written to disk
    (YieldFactorModels.jl:131-155)."""
    folder = _init_folder(spec.model_string)
    candidates = []
    if simulation:
        candidates.append(os.path.join(folder, f"init_params_{model_type}_simulation.csv"))
    candidates.append(os.path.join(folder, f"init_params_{model_type}.csv"))
    for path in candidates:
        if os.path.isfile(path):
            arr = np.loadtxt(path, delimiter=",")
            if arr.ndim == 1:
                arr = arr[:, None]
            return arr
    num_params = spec.n_params
    print(f"Initial parameters for {model_type} not found in {folder}. "
          f"Writing file with random initial parameters... ({num_params} params)")
    arr = np.random.default_rng().uniform(size=(num_params, 1))
    os.makedirs(folder, exist_ok=True)
    np.savetxt(os.path.join(folder, f"init_params_{model_type}.csv"), arr, delimiter=",")
    return arr


def load_static_parameters(spec, model_type: str, results_location: str,
                           thread_id: str, params: np.ndarray) -> np.ndarray:
    """Warm-start cascade from the simpler model's saved parameters
    (YieldFactorModels.jl:107-121)."""
    static_name = api.get_static_model_type(spec)
    if not static_name:
        return params
    path = os.path.join(results_location, static_name,
                        f"{static_name}__thread_id__{thread_id}__out_params.csv")
    if not os.path.isfile(path):
        print(f"Static parameters for {model_type} not found, using default initialization.")
        return params
    static_params = np.loadtxt(path, delimiter=",").reshape(-1, 1)
    return initialize_with_static_params(spec, params, static_params)


def run_estimation(spec, data, in_sample_end: int, all_params, param_groups,
                   max_group_iters: int, group_tol: float, printing: bool = True,
                   second_order=None):
    """YieldFactorModels.jl:162-186: grouped (block-coordinate) vs plain MLE."""
    if param_groups:
        assert np.asarray(all_params).shape[0] == len(param_groups)
        return opt.estimate_steps(
            spec, data, all_params, list(param_groups),
            max_group_iters=max_group_iters, tol=group_tol,
            start=0, end=in_sample_end, printing=printing,
            second_order=second_order)
    return opt.estimate(spec, data, all_params, start=0, end=in_sample_end,
                        printing=printing, second_order=second_order)


def run(
    thread_id: str = "1",
    in_sample_end: int = 100,
    forecast_horizon: int = 12,
    run_rolling: bool = True,
    model_type: str = "1C",
    float_type="float32",
    *,
    window_type: str = "both",
    in_sample_start: int = 1,
    param_groups: Sequence[str] = (),
    max_group_iters: int = 10,
    group_tol: float = 1e-8,
    run_optimization: bool = True,
    save_results_bool: bool = True,
    simulation: bool = False,
    reestimate: bool = True,
    scratch_dir: str = "",
    seed: int = 43,
    batched_windows: bool = False,
    orchestrated: bool = False,
    n_workers: int = 2,
    second_order=None,
):
    if simulation:  # :241-246
        window_type = "simulation"
        run_optimization = False
        run_rolling = True
        save_results_bool = False

    np.random.seed(seed)

    data_folder, results_location = setup_data_paths(model_type, simulation,
                                                     scratch_dir, thread_id)
    data, maturities = load_data(data_folder, thread_id)
    data = np.asarray(data, dtype=float_type)
    maturities = np.asarray(maturities, dtype=float_type)

    N = len(maturities)
    M = 3  # hard-coded in the reference (:262)
    spec, model_type = create_model(
        model_type, tuple(maturities), N, M, float_type,
        results_location=os.path.join(results_location, model_type) + os.sep)
    if spec is None:  # pC / vanillaNN placeholders
        return None

    param_groups = list(api.get_param_groups(spec, list(param_groups) or None))
    all_params = load_initial_parameters(spec, model_type, float_type,
                                         simulation=simulation)
    all_params = all_params.astype(np.float64)
    all_params[:, 0] = np.asarray(
        load_static_parameters(spec, model_type, results_location, thread_id,
                               all_params[:, 0])).reshape(-1)

    if run_optimization:
        print("The param groups are:", param_groups)
        init_params, loss, params, ir = run_estimation(
            spec, data, in_sample_end, all_params, param_groups,
            max_group_iters, group_tol, printing=True,
            second_order=second_order)
    else:
        init_params = all_params[:, 0]
        params = all_params[:, 0]
        loss = 0.0

    params_j = jnp.asarray(params, dtype=spec.dtype)
    data_j = jnp.asarray(data, dtype=spec.dtype)

    if save_results_bool:
        results = api.predict(spec, params_j, data_j[:, :in_sample_end])
        save_results(spec, results, loss, params, thread_id, "insample")
        loss = float(api.get_loss(spec, params_j, data_j[:, :in_sample_end]))
        print(f"In-sample loss: {loss}")

        results = api.predict(spec, params_j, data_j)
        save_results(spec, results, loss, params, thread_id, "outofsample")

        loss_array = np.asarray(api.get_loss_array(spec, params_j, data_j, K=1))
        oos = loss_array[in_sample_end:]
        for frac in (0.1, 0.25, 0.5, 0.75, 1.0):
            k = max(1, int(np.floor(frac * len(oos))))
            print(f"Out-of-sample loss array (first {int(frac * 100)}%): {np.mean(oos[:k])}")

    if run_rolling:
        print("Forecasting...")
        if orchestrated:
            # crash-tolerant path (docs/DESIGN.md §10): the same windows run
            # as leased queue tasks with checkpoint resume — expanding /
            # moving / both only (no_windowing has no task decomposition)
            from .orchestration.supervisor import run_orchestrated

            run_orchestrated(
                spec, data, thread_id, in_sample_end, in_sample_start,
                forecast_horizon, all_params, n_workers=n_workers,
                window_type=window_type, param_groups=param_groups,
                max_group_iters=max_group_iters, group_tol=group_tol,
                reestimate=reestimate)
        else:
            run_rolling_forecasts(
                spec, data, thread_id, in_sample_end, in_sample_start,
                forecast_horizon, all_params,
                window_type=window_type, param_groups=param_groups,
                max_group_iters=max_group_iters, group_tol=group_tol,
                reestimate=reestimate, batched=batched_windows,
                second_order=second_order)

    return spec, params
