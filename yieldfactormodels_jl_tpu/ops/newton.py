"""Second-order estimation engine: Hessian-vector recursions through the
filter, and a batched trust-region Newton-CG polish stage.

Multi-start MLE is the repo's dominant wall (BASELINE config 2: 649 s on one
core), and ``_run_lbfgs``/``batched_lbfgs`` are first-order methods grinding
a badly scaled penalty surface — the backtracking budget had to grow 25→80
just to escape plateaus (estimation/optimize.py).  The recursive Newton
method of Gustafsson–Schön (arXiv:2306.09148, PAPERS.md) computes Newton
directions *through the state-space recursion at filter cost*: the
curvature information a Kalman likelihood carries is already threaded
through the `lax.scan` carry, so a Hessian-VECTOR product never needs the
O(P²) Hessian — one tangent recursion rides the same scan.  Parallel-in-
time second-order smoothing (arXiv:2207.00426, already the PSD-floor
citation) shows the identical recursions compose on the assoc-scan tree for
long panels; this module keeps the sequential scan (the tree is engine
plumbing, not new math).

Two HVP engines, registered in ``config.NEWTON_ENGINES`` (every entry is
oracle-backed — graftlint YFM007, same contract as ``KALMAN_ENGINES``):

- ``"fisher"`` (the cheap default): the Gauss–Newton/Fisher curvature.  For
  the Gaussian filter NLL(θ) = Σ_t ½(log|F_t| + v_tᵀF_t⁻¹v_t) the expected
  (Fisher) information is

      I(θ)u = Σ_t [ J_vᵀ F⁻¹ (J_v u)  +  ½ J_Fᵀ (F⁻¹ (J_F u) F⁻¹) ]

  with J_v = ∂v_t/∂θ, J_F = ∂F_t/∂θ.  Hand-deriving WHICH curvature terms
  to keep is the approximation; evaluating it is one `jax.jvp` through the
  filter scan (tangents (dv_t, dF_t) threaded through the carry — the
  forward recursion), a per-step weighting (F⁻¹dv, ½F⁻¹dF F⁻¹ via the
  innovation Cholesky the filter already computes), and ONE `jax.vjp`
  pull-back (the §5b adjoint machinery — the same reverse-through-scan
  transpose the smoother/grad paths use).  ≈3 filter-pass cost per HVP,
  and the operator is PSD by construction whenever every contributing F_t
  factorizes — CG never sees negative curvature.

- ``"exact"``: the true Hessian-vector product as
  grad-of-directional-derivative, Hu = ∇(⟨∇NLL, u⟩) — REVERSE over the
  tangent recursion (jvp threads u through the scan carry, grad transposes
  it).  Family-generic (any ``api.get_loss`` family) and the parity anchor:
  pinned against the finite-difference NumPy Hessian oracle
  (tests/oracle.fd_hessian) AND against jvp-of-grad (the opposite
  differentiation order) in tests/test_newton.py.  Indefinite far from an
  optimum — the trust region is the damping.

The polish stage (:func:`batched_newton`) is ONE trust-region Newton-CG
loop whose iterate is the whole (S, P) start matrix, batch-last per the
lane rule like ``estimation/batched_lbfgs``: every objective/gradient/HVP
evaluation covers all S starts in one batched call, and the CG algebra is
per-start elementwise/reduction work along P.  Steihaug CG solves the
trust-region subproblem matrix-free; per-start `done` masks freeze
converged rows while the batch keeps iterating.

Sentinel discipline (CLAUDE.md §4) and the damping/fallback table
(docs/DESIGN.md §17):

    non-finite f at entry          start frozen on its first-order point
                                   (done, not converged) — stays on the
                                   LBFGS-phase result
    non-finite HVP (a contributing Hd discarded; direction falls back to
    F_t failed to factorize)       steepest descent clipped to Δ;
                                   NONPSD_HESSIAN taxonomy bit raised
    negative curvature in CG       Steihaug boundary step (the trust
    ("exact" mode)                 region IS the damping); bit raised
    trial f non-finite / penalty   step rejected, Δ ← Δ/4
    Δ underflow (< 1e-12)          start done (stuck), not converged

Failures never raise inside the jitted loop — a dead start keeps its entry
point and the driver's escalation ladder (robustness/ladder.py,
``YFM_ESCALATE=1``) picks it up exactly as it does for a dead LBFGS start.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .. import config
from ..models import api
from ..models import kalman as K
from ..models.params import transform_params
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax

#: objective clamp for trial values — the CANONICAL penalty/threshold pair:
#: the estimation layer aliases these (optimize._PENALTY_THRESH) so the
#: polish's entry-validity check and the LBFGS phase's plateau tests can
#: never drift apart.  THRESH sits just under the penalty because float32
#: rounds 1e12 down to 999_999_995_904 — an exact compare would never fire.
PENALTY = 1e12
PENALTY_THRESH = 0.999e12


def resolve_mode(spec: ModelSpec, mode: str) -> str:
    """Validate/resolve an HVP engine name for a family.  ``"fisher"`` needs
    the Kalman innovation structure (v_t, F_t); non-Kalman families fall
    back to the family-generic ``"exact"`` recursion (documented downgrade,
    not an error — the cascade must thread through MSED/static
    ``estimate_steps`` paths too)."""
    if mode not in config.NEWTON_ENGINES:
        raise ValueError(f"unknown newton engine {mode!r}; pick from "
                         f"{config.NEWTON_ENGINES}")
    if mode == "fisher" and not spec.is_kalman:
        return "exact"
    return mode


def _nll(spec: ModelSpec, raw, data, start, end):
    """Unclamped negative loglik at unconstrained parameters — the smooth
    objective the HVPs differentiate (the penalty clamp would zero the
    curvature exactly where the polish needs it)."""
    return -api.get_loss(spec, transform_params(spec, raw), data, start, end)


def _clamped_nll(spec: ModelSpec, raw, data, start, end):
    v = _nll(spec, raw, data, start, end)
    return jnp.where(jnp.isfinite(v), v, PENALTY)


def _innovations(spec: ModelSpec, raw, data, start, end):
    """(v (T, N), F (T, N, N)) — the per-step innovation and its covariance,
    the carriers of every curvature term the Fisher approximation keeps.

    Two providers, one contract (docs/DESIGN.md §17/§19):

    - sequential (the default): the joint-form scan — the joint form is
      used (not the univariate production default) because F_t is exactly
      the object being weighted; engine mixing is the tolerance-based
      regime the repo already documents for the SSD value/grad split
      (optimize._jitted_group_opt_ssd);
    - parallel-in-time: when the ``YFM_LOGLIK_T_SWITCH`` policy puts the
      panel on the tree (same gate as ``api.get_loss`` — constant-Z family,
      T at/above the switch), the innovations are assembled from the
      assoc-scan filter's composed moments instead.  ``jax.linearize``/
      ``jvp``/``vjp`` through THIS provider sweep the combine tree, so the
      Newton polish's tangent recursions run at O(log T) span on long
      panels — arXiv:2207.00426's parallel-in-time second-order form, with
      the cascade selection (``YFM_NEWTON``) unchanged.  (The nonlinear
      families get their tree automatically through ``exact_hvp``, whose
      ``api.get_loss`` dispatch upgrades TVλ to the iterated-SLR engine
      under the same policy.)
    """
    from .. import config

    if (spec.has_constant_measurement
            and 0 < config.loglik_t_switch() <= data.shape[1]):
        return _innovations_assoc(spec, raw, data, start, end)
    cons = transform_params(spec, raw)
    _, _, _, outs = K._scan_filter(spec, cons, data, start, end)
    return outs["v"], outs["F"]


def _innovations_assoc(spec: ModelSpec, raw, data, start, end):
    """(v, F) assembled from the associative-scan tree: the composed
    filtered moments (ops/assoc_scan.filter_means_covs) are shifted through
    the transition to predicted moments, and the innovation pair follows in
    closed form — v_t = y_t − Z m_{t|t−1} − d, F_t = Z P_{t|t−1} Zᵀ + R.
    Numerically the sequential provider's values (float association order
    aside — pinned in tests/test_slr_scan.py), but the program is the
    combine tree, so its linearization is a tree too.  Missing/out-of-window
    steps carry v = 0; their F is well-formed but excluded by the callers'
    ``contrib`` masks, exactly like the sequential outs."""
    cons = transform_params(spec, raw)
    from .assoc_scan import _bmm, filter_means_covs, predicted_moments

    m, P, (Z, d, kp, state0, obs) = filter_means_covs(spec, cons, data,
                                                      start, end)
    # the shift convention is assoc_scan's own (shared helper); the joint
    # innovation pair follows through _bmm — this provider exists to make
    # the long-panel tangent sweeps fast, so it must not re-enter the
    # batched dot_general path the combine tree just escaped
    mpred, Ppred = predicted_moments(m, P, kp, state0.beta, state0.P)
    ysafe = jnp.where(jnp.isfinite(data.T), data.T, 0.0)
    v = (ysafe - mpred @ Z.T - d[None]) * obs.astype(m.dtype)[:, None]
    N = spec.N
    F = _bmm(_bmm(Z, Ppred), Z.T) \
        + kp.obs_var * jnp.eye(N, dtype=m.dtype)[None]
    return v, F


def fisher_hvp(spec: ModelSpec, x, u, data, start, end):
    """Gauss–Newton/Fisher Hessian-vector product at one unconstrained point.

    One jvp threads the tangent ``u`` through the filter scan carry (the
    forward tangent recursion), per-step weights are formed from the
    innovation Cholesky, and one vjp pulls back — ≈3 filter passes, no
    O(P²) object anywhere.  Steps whose F fails to factorize contribute
    nothing (their weight rows are zeroed); the resulting operator is the
    Fisher matrix restricted to the healthy steps, still PSD.
    """
    T = data.shape[1]

    def inn(p):
        return _innovations(spec, p, data, start, end)

    (v, F), (dv, dF) = jax.jvp(inn, (x,), (u,))
    # contributing steps: the loss convention (start+1 .. end-2) ∩ observed
    contrib = K.loglik_contrib_mask(start, end, T) \
        & jnp.all(jnp.isfinite(data), axis=0)
    N = F.shape[-1]
    eye = jnp.eye(N, dtype=F.dtype)
    cho = jnp.linalg.cholesky(F)
    ok = jnp.all(jnp.isfinite(cho), axis=(-1, -2))
    cho_safe = jnp.where(ok[:, None, None], jnp.nan_to_num(cho), eye)
    solve = jax.vmap(lambda c, b: jax.scipy.linalg.cho_solve((c, True), b))
    w_v = solve(cho_safe, dv[:, :, None])[:, :, 0]          # F⁻¹ dv
    FiD = solve(cho_safe, dF)                               # F⁻¹ dF
    w_F = 0.5 * solve(cho_safe, FiD.swapaxes(-1, -2))       # ½ F⁻¹ dF F⁻¹
    keep = (contrib & ok)[:, None]
    w_v = jnp.where(keep, w_v, 0.0)
    w_F = jnp.where(keep[:, :, None], w_F, 0.0)
    _, pull = jax.vjp(inn, x)
    (hu,) = pull((w_v, w_F))
    return hu


def fisher_matrix(spec: ModelSpec, x, data, start, end):
    """The full (P, P) Gauss–Newton/Fisher matrix at one point, assembled
    from ONE ``jax.linearize`` of the innovation recursion: the primal
    filter runs once, the linearized scan is swept over the P basis
    tangents (vmapped — ~1 pass each instead of jvp+vjp's ~5), and the
    matrix is the GRAM of the whitened tangent stacks

        H = Σ_t [ Lᵥᵀ Lᵥ + ½ ⟨B_i, B_j⟩ ],  Lᵥ = L⁻¹ dv,  B = L⁻¹ dF L⁻ᵀ

    with L the per-step innovation Cholesky — symmetric PSD by
    construction even in floating point (the HVP composition loses that to
    rounding at κ(F)² scale).  This is the dense trust-region path's
    curvature source; the matrix-free :func:`fisher_hvp` serves the CG
    path at large P."""
    T = data.shape[1]
    Pn = x.shape[0]

    def inn(p):
        return _innovations(spec, p, data, start, end)

    (v, F), lin = jax.linearize(inn, x)
    contrib = K.loglik_contrib_mask(start, end, T) \
        & jnp.all(jnp.isfinite(data), axis=0)
    N = F.shape[-1]
    eye = jnp.eye(N, dtype=F.dtype)
    cho = jnp.linalg.cholesky(F)
    ok = jnp.all(jnp.isfinite(cho), axis=(-1, -2))
    cho_safe = jnp.where(ok[:, None, None], jnp.nan_to_num(cho), eye)
    keep = (contrib & ok).astype(F.dtype)

    dvs, dFs = jax.vmap(lin)(jnp.eye(Pn, dtype=x.dtype))  # (P,T,N), (P,T,N,N)
    tri = jax.scipy.linalg.solve_triangular
    Lv = jax.vmap(jax.vmap(lambda c, b: tri(c, b, lower=True)),
                  in_axes=(None, 0))(cho_safe, dvs)        # L⁻¹ dv
    Lv = jnp.where(jnp.isfinite(Lv), Lv, 0.0) * keep[None, :, None]

    def whiten_F(c, dF):  # B = L⁻¹ dF L⁻ᵀ per step
        Y = tri(c, dF, lower=True)
        return tri(c, Y.swapaxes(-1, -2), lower=True)

    B = jax.vmap(jax.vmap(whiten_F), in_axes=(None, 0))(cho_safe, dFs)
    B = jnp.where(jnp.isfinite(B), B, 0.0) * keep[None, :, None, None]
    H = jnp.einsum("ptn,qtn->pq", Lv, Lv) \
        + 0.5 * jnp.einsum("ptab,qtab->pq", B, B)
    return 0.5 * (H + H.T)


def exact_hvp(spec: ModelSpec, x, u, data, start, end):
    """Exact HVP as grad-of-directional-derivative (reverse over the forward
    tangent recursion): the jvp threads ``u`` through the scan carry, the
    outer grad transposes that tangent program.  Family-generic; the parity
    anchor against tests/oracle.fd_hessian and jvp-of-grad."""
    def dd(p):
        return jax.jvp(lambda q: _nll(spec, q, data, start, end),
                       (p,), (u,))[1]

    return jax.grad(dd)(x)


def hvp_fn(spec: ModelSpec, mode: str):
    """(x (P,), u (P,), data, start, end) → (P,) for a resolved engine."""
    mode = resolve_mode(spec, mode)
    if mode == "fisher":
        return lambda x, u, data, start, end: fisher_hvp(
            spec, x, u, data, start, end)
    return lambda x, u, data, start, end: exact_hvp(
        spec, x, u, data, start, end)


# ---------------------------------------------------------------------------
# batched trust-region Newton-CG
# ---------------------------------------------------------------------------

class BatchedNewtonResult(NamedTuple):
    x: jax.Array          # (S, P) final iterates
    f: jax.Array          # (S,) final (clamped) objective values
    iters: jax.Array      # (S,) outer Newton iterations actually applied
    converged: jax.Array  # (S,) bool: g_tol/f_abstol met on a valid row
    cg_iters: jax.Array   # (S,) total CG (HVP) iterations consumed
    code: jax.Array       # (S,) int32 taxonomy bits (NONPSD_HESSIAN, ...)


def _dot(a, b):
    return jnp.sum(a * b, axis=-1)  # (S,)


def _boundary_tau(p, d, delta):
    """Positive root of ‖p + τd‖ = Δ per start (Steihaug boundary exit)."""
    dd = jnp.maximum(_dot(d, d), 1e-30)
    pd = _dot(p, d)
    pp = _dot(p, p)
    disc = jnp.maximum(pd * pd + dd * (delta * delta - pp), 0.0)
    return (-pd + jnp.sqrt(disc)) / dd


def _cg_steihaug(hvp_b, X, G, delta, active, max_cg: int, cg_rtol):
    """Batched Steihaug CG on the trust-region subproblem min gᵀp + ½pᵀHp,
    ‖p‖ ≤ Δ.  Every HVP evaluation covers all S starts; per-start ``done``
    masks freeze finished rows.  Returns (p, curv_code) where curv_code
    raises NONPSD_HESSIAN for rows that hit negative curvature or a broken
    (non-finite) HVP."""
    S, Pn = X.shape
    dtype = X.dtype
    gnorm0 = jnp.sqrt(jnp.maximum(_dot(G, G), 1e-30))
    # steepest-descent fallback, clipped to the trust radius — used for rows
    # whose very first HVP comes back non-finite
    sd_scale = jnp.minimum(1.0, delta / gnorm0)
    p_sd = -G * sd_scale[:, None]

    class C(NamedTuple):
        p: jax.Array
        r: jax.Array
        d: jax.Array
        rr: jax.Array
        done: jax.Array
        broken: jax.Array   # negative curvature / non-finite HVP seen
        j: jax.Array

    def body(c: C) -> C:
        Hd = hvp_b(X, c.d)
        hd_ok = jnp.all(jnp.isfinite(Hd), axis=-1)
        dHd = _dot(c.d, Hd)
        neg = dHd <= 1e-16 * jnp.maximum(_dot(c.d, c.d), 1e-30)
        # broken HVP: fall back to clipped steepest descent when no CG
        # progress exists yet, else keep the partial CG iterate
        p_bad = jnp.where(c.j == 0, p_sd, c.p)
        take_bad = ~hd_ok & ~c.done
        # negative curvature (and trust-radius hits below): ride d to the
        # boundary — the Steihaug exits
        tau = _boundary_tau(c.p, c.d, delta)
        p_bound = c.p + tau[:, None] * c.d
        take_neg = hd_ok & neg & ~c.done
        # standard CG step
        alpha = c.rr / jnp.where(neg | ~hd_ok, 1.0, dHd)
        p_try = c.p + alpha[:, None] * c.d
        hit = jnp.sqrt(_dot(p_try, p_try)) >= delta
        take_hit = hd_ok & ~neg & hit & ~c.done
        r_new = c.r + alpha[:, None] * Hd
        rr_new = _dot(r_new, r_new)
        small = jnp.sqrt(rr_new) <= cg_rtol * gnorm0
        take_int = hd_ok & ~neg & ~hit & ~c.done
        p = jnp.where(take_bad[:, None], p_bad,
                      jnp.where((take_neg | take_hit)[:, None], p_bound,
                                jnp.where(take_int[:, None], p_try,
                                          c.p)))
        beta = rr_new / jnp.maximum(c.rr, 1e-30)
        d = jnp.where(take_int[:, None], -r_new + beta[:, None] * c.d, c.d)
        r = jnp.where(take_int[:, None], r_new, c.r)
        rr = jnp.where(take_int, rr_new, c.rr)
        done = c.done | take_bad | take_neg | take_hit | (take_int & small)
        broken = c.broken | take_bad | take_neg
        return C(p, r, d, rr, done, broken, c.j + 1)

    def cont(c: C):
        return (c.j < max_cg) & ~jnp.all(c.done)

    init = C(p=jnp.zeros((S, Pn), dtype=dtype), r=G, d=-G, rr=_dot(G, G),
             done=~active, broken=jnp.zeros((S,), bool),
             j=jnp.asarray(0, jnp.int32))
    out = jax.lax.while_loop(cont, body, init)
    code = tax.bit(out.broken & active, tax.NONPSD_HESSIAN).astype(jnp.int32)
    return out.p, code, out.j


def _full_hessian(hvp_b, X):
    """(S, P, P) model Hessian from P batched HVP sweeps — ONE vmapped
    program whose inner call covers all S starts (P · S HVPs in a single
    launch).  Affordable because the repo's parameter vectors are small
    (P ≤ ~50); above ``DENSE_P_MAX`` the matrix-free CG path takes over."""
    S, Pn = X.shape
    eye = jnp.eye(Pn, dtype=X.dtype)

    def col(e):  # e (P,) basis direction, broadcast across starts
        return hvp_b(X, jnp.broadcast_to(e, (S, Pn)))

    H = jax.vmap(col)(eye)              # (P, S, P)
    return jnp.swapaxes(H, 0, 1)        # (S, P, P)


def _tr_solve_dense(H, g, delta):
    """Exact trust-region subproblem per start from the eigendecomposition:
    p(λ) = −Q (Λ + λI)⁻¹ Qᵀg with the smallest λ ≥ max(0, −λ_min) putting
    ‖p‖ ≤ Δ (Moré–Sorensen secular equation, bisection — ~60 scalar
    iterations, vectorized over S).  Indefinite H is handled by the λ shift
    — the "damped fallback" of the §17 table; the hard case (g ⟂ the
    bottom eigenspace) degrades to an interior step shorter than Δ, which
    the ρ-test machinery simply treats as a cautious step.

    Returns (p, nonpd) — nonpd flags rows whose model Hessian needed a
    positive shift (reported as the NONPSD_HESSIAN taxonomy bit)."""
    S, Pn = g.shape
    w, Q = jnp.linalg.eigh(H)                       # (S, P), (S, P, P)
    gh = jnp.einsum("sij,si->sj", Q, g)             # Qᵀ g
    scale = jnp.maximum(jnp.abs(w).max(axis=-1), 1.0)
    lam_floor = jnp.maximum(0.0, -w[:, 0]) + 1e-12 * scale

    def pnorm(lam):  # ‖p(λ)‖ per start
        denom = w + lam[:, None]
        ph = gh / jnp.maximum(denom, 1e-300)
        return jnp.sqrt(jnp.sum(ph * ph, axis=-1))

    inside = pnorm(lam_floor) <= delta
    # bracket: grow hi until ‖p(hi)‖ ≤ Δ (‖p‖ is decreasing in λ)
    hi0 = lam_floor + scale

    def grow(carry):
        hi, k = carry
        return jnp.where(pnorm(hi) > delta, hi * 4.0, hi), k + 1

    hi, _ = jax.lax.while_loop(
        lambda c: (c[1] < 60) & jnp.any(pnorm(c[0]) > delta),
        grow, (hi0, 0))

    def bisect(_, lohi):
        lo, hi = lohi
        mid = 0.5 * (lo + hi)
        big = pnorm(mid) > delta
        return jnp.where(big, mid, lo), jnp.where(big, hi, mid)

    lo, hi = jax.lax.fori_loop(0, 60, bisect, (lam_floor, hi))
    lam = jnp.where(inside, lam_floor, hi)
    ph = gh / jnp.maximum(w + lam[:, None], 1e-300)
    p = -jnp.einsum("sij,sj->si", Q, ph)
    return p, w[:, 0] < 0


def batched_newton(value_and_grad: Callable[[jax.Array],
                                            Tuple[jax.Array, jax.Array]],
                   hvp_b: Callable[[jax.Array, jax.Array], jax.Array],
                   x0: jax.Array,
                   max_iters: int,
                   g_tol: float = 1e-6,
                   f_abstol: float = 1e-6,
                   max_cg: int = 20,
                   delta0: float = 1.0,
                   delta_max: float = 1e3,
                   eta: float = 1e-4,
                   invalid_above: float | None = None,
                   value_fn: Callable[[jax.Array], jax.Array] | None = None,
                   dense_tr: bool = True,
                   hess_b: Callable[[jax.Array], jax.Array] | None = None,
                   ) -> BatchedNewtonResult:
    """Minimize S objectives simultaneously by trust-region Newton.

    ``value_and_grad``: (S, P) → ((S,), (S, P)) finite-clamped batch
    objective (same contract as :func:`~..estimation.batched_lbfgs.
    batched_lbfgs`); ``hvp_b``: (X (S, P), U (S, P)) → (S, P) batched HVP at
    X along U; ``value_fn``: optional value-only objective for the trial
    probe (one value pass, no adjoint).  Rows whose entry value is
    non-finite or on the penalty plateau never move (done, not converged).

    ``dense_tr=True`` (the default at this repo's parameter counts) builds
    the full (S, P, P) model Hessian from P vmapped HVP sweeps and solves
    the trust-region subproblem EXACTLY (eigh + secular bisection) — the
    raw-parameter Hessian's conditioning spans ~9 orders (bijected
    variances vs Φ entries), which unpreconditioned CG cannot cut through
    (measured: Steihaug at max_cg=20 left gnorm bouncing at 1e1–1e4 after
    40 outer iterations; the dense solve converges).  ``dense_tr=False``
    is the matrix-free Steihaug-CG stage for parameter counts where P
    HVPs per iteration stop being cheap.
    """
    S, Pn = x0.shape
    if invalid_above is None:
        invalid_above = jnp.inf
    probe = value_fn if value_fn is not None else (
        lambda X: value_and_grad(X)[0])

    f0, g0 = value_and_grad(x0)

    def valid_row(f):
        return jnp.isfinite(f) & (f < invalid_above)

    class Carry(NamedTuple):
        x: jax.Array
        f: jax.Array
        g: jax.Array
        delta: jax.Array
        it: jax.Array
        iters: jax.Array
        cg: jax.Array
        done: jax.Array
        conv: jax.Array
        code: jax.Array

    def subproblem(c, active):
        """→ (p, curv_code, hvp_count, Hp)"""
        if dense_tr:
            H = hess_b(c.x) if hess_b is not None else _full_hessian(hvp_b,
                                                                     c.x)
            H = 0.5 * (H + H.swapaxes(-1, -2))
            h_ok = jnp.all(jnp.isfinite(H), axis=(-1, -2))
            gnorm = jnp.sqrt(jnp.maximum(_dot(c.g, c.g), 1e-30))
            p_sd = -c.g * jnp.minimum(1.0, c.delta / gnorm)[:, None]
            H_safe = jnp.where(h_ok[:, None, None], H,
                               jnp.eye(Pn, dtype=H.dtype))
            p, nonpd = _tr_solve_dense(H_safe, c.g, c.delta)
            p_ok = jnp.all(jnp.isfinite(p), axis=-1)
            use_sd = ~h_ok | ~p_ok
            p = jnp.where(use_sd[:, None], p_sd, p)
            Hp = jnp.einsum("sij,sj->si", H_safe, p)
            code = tax.bit(active & (use_sd | nonpd), tax.NONPSD_HESSIAN)
            return p, code.astype(jnp.int32), jnp.int32(Pn), Hp
        p, code, cg_j = _cg_steihaug(hvp_b, c.x, c.g, c.delta, active,
                                     max_cg, cg_rtol=0.1)
        Hp = hvp_b(c.x, p)
        return p, code, cg_j + 1, jnp.where(jnp.isfinite(Hp), Hp, 0.0)

    def step(c: Carry) -> Carry:
        active = ~c.done
        p, curv_code, cg_j, Hp = subproblem(c, active)
        pred = -(_dot(c.g, p) + 0.5 * _dot(p, Hp))  # model decrease, ≥ 0
        x_try = c.x + p
        f_try = probe(x_try)
        rho = (c.f - f_try) / jnp.maximum(pred, 1e-30)
        ok_try = valid_row(f_try) & (f_try < c.f) & (pred > 0)
        accept = active & ok_try & (rho > eta)
        x_new = jnp.where(accept[:, None], x_try, c.x)
        # the fresh gradient is only needed where a row moved — an
        # all-reject iteration (common during trust-radius shrink
        # sequences) skips the whole batched value+grad (~3 filter passes
        # per start) instead of computing and discarding it
        f_new2, g_new2 = jax.lax.cond(
            jnp.any(accept), value_and_grad, lambda X: (c.f, c.g), x_new)
        f_new = jnp.where(accept, f_new2, c.f)
        g_new = jnp.where(accept[:, None], g_new2, c.g)
        pnorm = jnp.sqrt(jnp.maximum(_dot(p, p), 1e-30))
        shrink = active & ((~accept) | (rho < 0.25))
        grow = accept & (rho > 0.75) & (pnorm >= 0.99 * c.delta)
        delta = jnp.where(shrink, 0.25 * pnorm,
                          jnp.where(grow, jnp.minimum(2.0 * c.delta,
                                                      delta_max), c.delta))
        gnorm = jnp.max(jnp.abs(g_new), axis=-1)
        df = jnp.abs(f_new - c.f)
        newly_conv = accept & ((gnorm <= g_tol) | (df <= f_abstol)) \
            & valid_row(f_new)
        stuck = active & (delta < 1e-12)
        at_tol = active & (gnorm <= g_tol) & valid_row(f_new)
        done = c.done | newly_conv | stuck | at_tol
        conv = c.conv | newly_conv | (at_tol & valid_row(f_new))
        return Carry(x_new, f_new, g_new, delta, c.it + 1,
                     c.iters + accept.astype(jnp.int32),
                     c.cg + jnp.where(active, cg_j, 0).astype(jnp.int32),
                     done, conv, c.code | jnp.where(active, curv_code, 0))

    def cont(c: Carry):
        return (c.it < max_iters) & ~jnp.all(c.done)

    at_opt0 = (jnp.max(jnp.abs(g0), axis=-1) <= g_tol) & valid_row(f0)
    init = Carry(
        x=x0, f=f0, g=g0,
        delta=jnp.full((S,), delta0, dtype=x0.dtype),
        it=jnp.asarray(0, jnp.int32),
        iters=jnp.zeros((S,), jnp.int32),
        cg=jnp.zeros((S,), jnp.int32),
        done=~valid_row(f0) | at_opt0,
        conv=at_opt0,
        code=jnp.zeros((S,), jnp.int32),
    )
    out = jax.lax.while_loop(cont, step, init)
    return BatchedNewtonResult(out.x, out.f, out.iters, out.conv, out.cg,
                               out.code)


# ---------------------------------------------------------------------------
# the polish entry the estimation layer jits
# ---------------------------------------------------------------------------

#: parameter-count threshold for the dense trust-region subproblem: below
#: it the full (S, P, P) Hessian costs P vmapped HVP sweeps per iteration
#: and the eigh-based solve is exact; above it the matrix-free Steihaug-CG
#: stage takes over
DENSE_P_MAX = 64


def polish(spec: ModelSpec, X0, data, start, end, *, max_iters: int = 25,
           g_tol: float = 1e-6, f_abstol: float = 1e-6, mode: str = "fisher",
           max_cg: int = 20) -> BatchedNewtonResult:
    """Trust-region Newton polish of an (S, P) unconstrained start matrix —
    the second phase of the ``estimate(..., second_order=True)`` cascade.

    Pure and jit/vmap-safe: the estimation layer wraps it in the standard
    ``@register_engine_cache`` + ``@lru_cache`` jitted-builder idiom
    (optimize._jitted_newton_polish)."""
    mode = resolve_mode(spec, mode)

    def single_val(p, dat, s, e):
        return _clamped_nll(spec, p, dat, s, e)

    def vag(X):
        vals, grads = jax.vmap(
            jax.value_and_grad(lambda p: single_val(p, data, start, end)))(X)
        return vals, jnp.where(jnp.isfinite(grads), grads, 0.0)

    def value_fn(X):
        return jax.vmap(lambda p: single_val(p, data, start, end))(X)

    hvp1 = hvp_fn(spec, mode)

    def hvp_b(X, U):
        return jax.vmap(lambda x, u: hvp1(x, u, data, start, end))(X, U)

    hess_b = None
    if mode == "fisher":
        # the dense path's cheap curvature: one linearize sweep per start
        # (~P passes) instead of P HVP compositions (~5P)
        def hess_b(X):
            return jax.vmap(
                lambda x: fisher_matrix(spec, x, data, start, end))(X)

    return batched_newton(vag, hvp_b, X0, max_iters, g_tol=g_tol,
                          f_abstol=f_abstol, max_cg=max_cg,
                          invalid_above=PENALTY_THRESH, value_fn=value_fn,
                          dense_tr=X0.shape[1] <= DENSE_P_MAX, hess_b=hess_b)
