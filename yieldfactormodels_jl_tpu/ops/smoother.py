"""Rauch–Tung–Striebel (fixed-interval) smoother for the Kalman families.

A capability beyond the reference (which only filters —
/root/reference/src/models/kalman/filter.jl has no backward pass): smoothed
state estimates β_{t|T} and covariances P_{t|T} for every t, as a forward
`lax.scan` (the existing filter, whose per-step filtering moments ride along
as scan outputs) followed by a reverse `lax.scan` over the standard RTS
recursion

    G_t   = P_{t|t} Φᵀ P_{t+1|t}⁻¹
    β_{t|T} = β_{t|t} + G_t (β_{t+1|T} − β_{t+1|t})
    P_{t|T} = P_{t|t} + G_t (P_{t+1|T} − P_{t+1|t}) G_tᵀ

The backward pass is measurement-free (only Φ and the filtering moments
enter), so it covers the constant-loading families AND the TVλ EKF with the
same code — the linearization only affected the forward pass.  Missing
columns (NaN) are handled by the filter's masked update (predicted == updated
on unobserved steps), so smoothing across data gaps needs no special casing.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax


def forward_moments(spec: ModelSpec, params, data, start, end, engine=None):
    """Engine-validated per-step filtering moments ``(kp, outs)`` — THE shared
    dispatch for every consumer of (β_pred, P_pred, β_upd, P_upd, ll)
    (``smooth`` here and ``ops/forecast.forecast_density``), so the engine
    contract — "joint" and "univariate" emit moments, "sqrt"/"assoc" raise —
    lives in exactly one place."""
    from .. import config
    from . import univariate_kf

    eng = engine or config.kalman_engine()
    if eng not in ("joint", "univariate"):
        raise ValueError(
            f"engine {eng!r} has no filtering-moments path — per-step "
            f"(β, P) moments are emitted by the 'joint' and 'univariate' "
            f"engines only.  Pass engine= explicitly or "
            f"config.set_kalman_engine('univariate').")
    if eng == "univariate":
        return univariate_kf.filter_moments(spec, params, data, start, end)
    kp, _, _, outs = K._scan_filter(spec, params, data, start, end)
    return kp, outs


def smooth(spec: ModelSpec, params, data, start=0, end=None, engine=None):
    """Smoothed moments for every t of the panel.

    Returns a dict:
      ``beta_smooth`` (Ms, T), ``P_smooth`` (T, Ms, Ms) — β_{t|T}, P_{t|T};
      ``beta_filt`` (Ms, T), ``P_filt`` (T, Ms, Ms) — the filtered β_{t|t},
      P_{t|t} for comparison (equal to the smoothed values at t = T−1).

    ``engine``: forward-pass engine for the filtering moments — ``None``
    reads ``config.kalman_engine()``.  Supported: ``"joint"`` (per-step
    Cholesky) and ``"univariate"`` (Cholesky-free sequential updates,
    algebraically the same posterior moments).  The ``"sqrt"``/``"assoc"``
    loglik engines do not emit the (β_{t|t}, P_{t|t}, β_{t+1|t}, P_{t+1|t})
    set the RTS backward pass consumes, so they raise here rather than
    silently running a different engine than the caller selected.
    """
    if not spec.is_kalman:
        raise ValueError(
            f"smooth: RTS smoothing needs a state-space covariance recursion; "
            f"family {spec.family!r} is not a Kalman family")
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    if end is None:
        end = T
    kp, outs = forward_moments(spec, params, data, start, end, engine)

    b_pred, P_pred = outs["beta_pred"], outs["P_pred"]    # (T, Ms), (T, Ms, Ms)
    b_upd, P_upd = outs["beta_upd"], outs["P_upd"]

    def backward(carry, inp):
        bs, Ps = carry
        b_u, P_u, b_p1, P_p1 = inp
        # G = P_upd Φᵀ P_pred₊₁⁻¹  via a PD solve: P_pred₊₁ X = Φ P_updᵀ
        P_p1s = 0.5 * (P_p1 + P_p1.swapaxes(-1, -2))
        G = jnp.linalg.solve(P_p1s, kp.Phi @ P_u.swapaxes(-1, -2)).swapaxes(-1, -2)
        b_new = b_u + G @ (bs - b_p1)
        P_new = P_u + G @ (Ps - P_p1) @ G.swapaxes(-1, -2)
        return (b_new, P_new), (b_new, P_new)

    # seed with the LAST filtered moments; sweep t = T−2 .. 0
    init = (b_upd[-1], P_upd[-1])
    (_, _), (bs_rev, Ps_rev) = lax.scan(
        backward, init,
        (b_upd[:-1], P_upd[:-1], b_pred[1:], P_pred[1:]),
        reverse=True)
    beta_smooth = jnp.concatenate([bs_rev, b_upd[-1:]], axis=0)
    P_smooth = jnp.concatenate([Ps_rev, P_upd[-1:]], axis=0)
    # sentinel convention: a failed forward Cholesky surfaces as ll = −Inf in
    # the filter (kalman._step); the moments it produced are meaningless, so
    # poison the whole output with NaN instead of returning finite garbage
    # (mirrors get_loss's −Inf and the particle filter's draw-level −Inf).
    # The taxonomy code rides along (robustness/taxonomy.py): the forward
    # pass's per-step bits say WHY the moments went NaN, and NAN_STATE marks
    # the poisoning itself — decoded only at the driver.
    ok = jnp.all(outs["ll"] > -jnp.inf)
    code = tax.combine(outs["code"]) | tax.bit(~ok, tax.NAN_STATE)
    nan = jnp.asarray(jnp.nan, dtype=beta_smooth.dtype)
    return {
        "beta_smooth": jnp.where(ok, beta_smooth.T, nan),
        "P_smooth": jnp.where(ok, P_smooth, nan),
        "beta_filt": jnp.where(ok, b_upd.T, nan),
        "P_filt": jnp.where(ok, P_upd, nan),
        "code": code,
    }
