"""Associative-scan (parallel-in-time) Kalman filter.

The reference's filters are strictly sequential ``for t`` loops
(SURVEY.md §5.7); on TPU the time recursion can instead run in O(log T) span
with `jax.lax.associative_scan` using the parallel Kalman formulation of
Särkkä & García-Fernández (temporal parallelization of Bayesian smoothers; cf.
PAPERS.md "Parallel square-root statistical linear regression").  This is the
framework's sequence-parallelism story: long panels (daily data, simulation
studies) stop being latency-bound on sequential steps, and the scan can be
sharded over the time axis of a mesh.

Each step is the 5-tuple element (A, b, C, J, η); composition is closed under
the filtering semigroup.  Missing observations (NaN columns) become pure
prediction elements, so multi-step forecasting composes the same way.
Applies to the time-invariant-measurement families (DNS, AFNS).

This module is the ESTIMATION engine behind ``api.get_loss(engine="assoc")``
and the ``YFM_LOGLIK_T_SWITCH`` dispatch policy (docs/DESIGN.md §13):

- differentiable end-to-end (every op here has a JAX adjoint — the combine
  tree, the batched solves, the Cholesky factors), so the multi-start L-BFGS
  cascade runs on it unchanged;
- optional square-root stabilization (``psd_floor``): the composed filtered
  covariances are PSD-*projected* through the same eigenvalue-clip square-root
  machinery as the escalation ladder's sqrt rung (ops/sqrt_kf.py
  ``_psd_sqrt_factor``, after Yaghoobi et al., arXiv:2207.00426) before the
  predicted innovation factorizations — the combine tree's f32 cancellations
  cannot poison the likelihood with a spuriously indefinite moment.  Like
  ``sqrt_kf.get_loss(init_psd_floor=...)`` this is the RECOVERY surface, not
  the parity path: leave it ``None`` for exact agreement with the sequential
  engines;
- failure taxonomy (``get_loss_coded``): the int32 bitmask channel every
  other engine carries (robustness/taxonomy.py), so an assoc-engine −Inf
  decodes into causes and the ``YFM_ESCALATE`` ladder can use this engine as
  a rescue rung for long panels (robustness/ladder.py).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax

_LOG_2PI = math.log(2.0 * math.pi)


class FilterElement(NamedTuple):
    A: jnp.ndarray
    b: jnp.ndarray
    C: jnp.ndarray
    J: jnp.ndarray
    eta: jnp.ndarray


def _mv(M, v):
    """Batched tiny matvec as broadcast-multiply-reduce (see :func:`_bmm`)."""
    return jnp.sum(M * v[..., None, :], axis=-1)


def _bmm(a, b):
    """Batched tiny-matrix product spelled as broadcast-multiply-reduce.

    XLA:CPU special-cases batched ``dot_general`` at 3×3 into straight-line
    vector code but dispatches 4×4/5×5 operands to a per-instance kernel
    call — ~10× the wall of the fused elementwise form at the combine
    tree's (T,)-batched shapes (measured: a 128-step scan of (157, Ms, Ms)
    products runs 13.6 ms as ``@`` vs 1.3 ms as mul+sum at Ms = 4, and the
    whole blocked prefix fell 126 → ~15 ms).  Ms ≤ 5 here, so the
    (…, M, M, M) broadcast intermediate is trivially small.  Broadcasting
    matches ``a @ b`` (either operand may be unbatched)."""
    return jnp.sum(a[..., :, :, None] * b[..., None, :, :], axis=-2)


def _solve_unrolled(D, B):
    """Pivot-free Gauss–Jordan solve of D X = B, unrolled over the (static,
    tiny) state dimension — pure broadcast arithmetic that vectorizes over
    the T-sized combine batch.  ``jnp.linalg.solve`` here lowers to batched
    LAPACK on CPU (per-matrix dispatch ate ~70% of the combine tree's wall)
    and to a lane-hostile loop on TPU; at Ms ≤ 5 the unrolled elimination is
    a handful of fused elementwise ops instead.  No pivoting by design: every
    system solved in :func:`_combine` is D = I + (PSD·PSD) — its spectrum
    sits at/above 1 and D ≈ I in the filter's operating regime, exactly the
    class where unpivoted elimination is stable (a genuinely degenerate
    point goes non-finite and lands in the −Inf sentinel + taxonomy channel
    like every other engine's breakdown)."""
    M = D.shape[-1]
    A = jnp.concatenate([D, B], axis=-1)          # (..., M, M+K)
    for i in range(M):
        piv = A[..., i:i + 1, :] / A[..., i:i + 1, i:i + 1]
        A = A - A[..., :, i:i + 1] * piv          # eliminate col i everywhere
        A = A.at[..., i, :].set(piv[..., 0, :])   # …then restore row i
    return A[..., :, M:]


def _combine(ei: FilterElement, ej: FilterElement) -> FilterElement:
    """Associative composition (element i happens before j).  All batched
    tiny-matrix products go through :func:`_bmm`/:func:`_mv` — the combine
    runs T-batched inside the prefix scan, exactly the shape class where
    XLA:CPU's batched ``dot_general`` path is ~10× the fused form."""
    I = jnp.eye(ei.A.shape[-1], dtype=ei.A.dtype)
    D = I + _bmm(ei.C, ej.J)
    rhs = jnp.concatenate(
        [ei.A, (ei.b + _mv(ei.C, ej.eta))[..., None], ei.C], axis=-1)
    sol = _solve_unrolled(D, rhs)                 # one elimination, 3 uses
    Ms = ei.A.shape[-1]
    Dinv_Ai = sol[..., :, :Ms]
    Dinv_bCe = sol[..., :, Ms]
    Dinv_Ci = sol[..., :, Ms + 1:]
    A = _bmm(ej.A, Dinv_Ai)
    b = _mv(ej.A, Dinv_bCe) + ej.b
    C = _bmm(_bmm(ej.A, Dinv_Ci), ej.A.swapaxes(-1, -2)) + ej.C
    E = I + _bmm(ej.J, ei.C)
    rhs_e = jnp.concatenate(
        [ej.J, (ej.eta - _mv(ej.J, ei.b))[..., None]], axis=-1)
    sol_e = _solve_unrolled(E, rhs_e)
    Einv_Jj = sol_e[..., :, :Ms]
    Ait = ei.A.swapaxes(-1, -2)
    eta = _mv(Ait, sol_e[..., :, Ms]) + ei.eta
    J = _bmm(_bmm(Ait, Einv_Jj), ei.A) + ei.J
    return FilterElement(A, b, C, J, eta)


def _elements(Z, d, Phi, delta, Q, R_diag, m0, P0, data, observed):
    """Build the per-step elements for all T steps at once (batched)."""
    N, Ms = Z.shape
    T = data.shape[1]
    I = jnp.eye(Ms, dtype=Z.dtype)
    y = jnp.where(jnp.isfinite(data.T), data.T, 0.0)  # (T, N)
    obs = observed & jnp.all(jnp.isfinite(data.T), axis=1)
    obs_f = obs.astype(Z.dtype)[:, None]

    R = jnp.diag(R_diag)
    # generic element (k >= 2): uses only local quantities
    S = Z @ Q @ Z.T + R
    S_cho = jnp.linalg.cholesky(S)
    Kg = jax.scipy.linalg.cho_solve((S_cho, True), Z @ Q.T).T  # Q Zᵀ S⁻¹
    A_g = (I - Kg @ Z) @ Phi
    C_g = (I - Kg @ Z) @ Q
    ZtSi = jax.scipy.linalg.cho_solve((S_cho, True), Z).T  # Zᵀ S⁻¹
    J_g = Phi.T @ ZtSi @ Z @ Phi

    resid = y - (Z @ delta + d)[None, :]  # y_k − Z c − d  (T, N)
    b_g = delta[None, :] + resid @ Kg.T
    eta_g = resid @ (Phi.T @ ZtSi).T

    # first element: exact update from the prior (m0, P0)
    mpred1 = Phi @ m0 + delta
    Ppred1 = Phi @ P0 @ Phi.T + Q
    S1 = Z @ Ppred1 @ Z.T + R
    S1_cho = jnp.linalg.cholesky(S1)
    K1 = jax.scipy.linalg.cho_solve((S1_cho, True), Z @ Ppred1.T).T
    b_1 = mpred1 + K1 @ (y[0] - Z @ mpred1 - d)
    C_1 = (I - K1 @ Z) @ Ppred1

    # assemble (T, ...) with missing steps as pure prediction elements
    A = jnp.where(obs_f[:, :, None], A_g[None], Phi[None])
    b = jnp.where(obs_f, b_g, delta[None, :])
    C = jnp.where(obs_f[:, :, None], C_g[None], Q[None])
    J = jnp.where(obs_f[:, :, None], J_g[None], jnp.zeros_like(J_g)[None])
    eta = jnp.where(obs_f, eta_g, jnp.zeros_like(eta_g))

    # overwrite k = 1 (prior-conditioned); A₁ = 0, J₁ = η₁ = 0
    A = A.at[0].set(jnp.where(obs[0], jnp.zeros_like(Phi), Phi))
    b = b.at[0].set(jnp.where(obs[0], b_1, mpred1))
    C = C.at[0].set(jnp.where(obs[0], C_1, Ppred1))
    J = J.at[0].set(jnp.zeros_like(J_g))
    eta = eta.at[0].set(jnp.zeros_like(eta_g[0]))
    return FilterElement(A, b, C, J, eta), obs


#: pass-1 scan length of the blocked prefix (:func:`_prefix_scan`): chunks of
#: this many steps ride the batch axis, so the within-chunk compose runs as
#: an L-step scan over (T/L)-wide element batches.  128 balances scan-step
#: dispatch (fewer iterations) against per-iteration working-set size.
_CHUNK = 128


def _identity_like(e: FilterElement) -> FilterElement:
    """The semigroup identity, batched like ``e``'s leading axes: A = I,
    everything else 0 (combine(id, x) = combine(x, id) = x — both directions
    verified by the parity tests through the padded tail)."""
    I = jnp.eye(e.A.shape[-1], dtype=e.A.dtype)
    return FilterElement(jnp.broadcast_to(I, e.A.shape).astype(e.A.dtype),
                         jnp.zeros_like(e.b), jnp.zeros_like(e.C),
                         jnp.zeros_like(e.J), jnp.zeros_like(e.eta))


def _prefix_scan(elems: FilterElement, T: int):
    """All-prefix composition of the T per-step elements: returns the
    filtered ``(b (T, Ms), C (T, Ms, Ms))`` trajectories — the same result
    as ``lax.associative_scan(_combine, elems)`` (up to float association
    order) restructured as the classic three-pass blocked prefix:

      1. within-chunk prefixes: an L-step ``lax.scan`` whose every combine
         is batched over all T/L chunks (wide fused elementwise work),
      2. exclusive prefix of the T/L chunk totals (a tiny combine tree),
      3. one T-batched *simplified* apply of each chunk's incoming prefix
         to its local prefixes.

    ``lax.associative_scan`` interleaves slice/update traffic at every one
    of its ~2·log₂T levels, which on CPU cost more than the whole
    sequential filter; the blocked form does the identical ~2T combines as
    two long-vectorized passes plus a negligible tree.  Pass 3 exploits
    that every chunk-incoming prefix from chunk 1 on CONTAINS step 1, whose
    element has A₁ = 0 — so the full composition collapses to one solve and
    two matmuls, and its J/η outputs (never consumed downstream) are not
    formed at all.
    """
    Ms = elems.A.shape[-1]
    L = min(_CHUNK, T)
    C = -(-T // L)
    pad = C * L - T
    if pad:
        ident = _identity_like(jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[:1], (pad,) + x.shape[1:]), elems))
        elems = jax.tree_util.tree_map(
            lambda x, p: jnp.concatenate([x, p], axis=0), elems, ident)
    # (C·L, ...) → (L, C, ...): scan over position-in-chunk, batch over chunks
    by_l = jax.tree_util.tree_map(
        lambda x: x.reshape((C, L) + x.shape[1:]).swapaxes(0, 1), elems)

    def body(carry, e_l):
        new = _combine(carry, e_l)  # carry (earlier steps) before e_l
        return new, new

    init = _identity_like(jax.tree_util.tree_map(lambda x: x[0], by_l))
    _, prefixes = lax.scan(body, init, by_l)      # (L, C, ...) local prefixes
    totals = jax.tree_util.tree_map(lambda x: x[-1], prefixes)      # (C, ...)
    incl = lax.associative_scan(_combine, totals)                    # tiny: C
    ident1 = _identity_like(jax.tree_util.tree_map(lambda x: x[:1], totals))
    prefix_in = jax.tree_util.tree_map(         # exclusive: identity for c=0
        lambda x, i: jnp.concatenate([i, x[:-1]], axis=0), incl, ident1)
    # pass 3 — one batched combine(prefix_in[c], prefixes[l, c]) reduced to
    # its (b, C) outputs, which depend on ei only through (b_i, C_i): one
    # solve + two matmuls per element, J/η (never consumed downstream) not
    # formed at all.  Exact for every chunk — chunk 0's identity prefix has
    # C_i = 0, so D = I and the apply collapses to the local prefix.
    Ci = prefix_in.C[None]                                # (1, C, Ms, Ms)
    bi = prefix_in.b[None]
    D = jnp.eye(Ms, dtype=Ci.dtype) + _bmm(Ci, prefixes.J)
    rhs = jnp.concatenate(
        [(bi + _mv(Ci, prefixes.eta))[..., None],
         jnp.broadcast_to(Ci, prefixes.C.shape)], axis=-1)
    sol = _solve_unrolled(D, rhs)
    b_full = _mv(prefixes.A, sol[..., :, 0]) + prefixes.b
    C_full = _bmm(_bmm(prefixes.A, sol[..., :, 1:]),
                  prefixes.A.swapaxes(-1, -2)) + prefixes.C
    # (L, C, ...) → (T, ...)
    b_out = b_full.swapaxes(0, 1).reshape((C * L, Ms))[:T]
    C_out = C_full.swapaxes(0, 1).reshape((C * L, Ms, Ms))[:T]
    return b_out, C_out


def _psd_project(P, floor):
    """Batched PSD projection of (…, Ms, Ms) symmetric matrices: eigenvalue
    clip at ``floor`` and reconstruct — the matrix form of ops/sqrt_kf.py's
    ``_psd_sqrt_factor`` (the escalation ladder's square-root rescue
    machinery), applied to the semigroup's composed moments instead of the
    initial ones.  Differentiable (eigh has a JAX adjoint; the stable points
    the optimizer visits have separated eigenvalues)."""
    sym = 0.5 * (P + P.swapaxes(-1, -2))
    w, V = jnp.linalg.eigh(sym)
    w = jnp.maximum(w, jnp.asarray(floor, dtype=P.dtype))
    return jnp.einsum("...ik,...k,...jk->...ij", V, w, V)


def filter_means_covs(spec: ModelSpec, params, data, start=0, end=None,
                      psd_floor=None, prefix: str = "blocked"):
    """Filtered means/covariances for every t via the parallel prefix.

    Returns (m (T, Ms) = E[x_t | y_{1:t}], P (T, Ms, Ms)).  ``psd_floor``
    (a float) PSD-projects the composed covariances through
    :func:`_psd_project` — the square-root-stabilized recovery mode; leave
    ``None`` for the parity path.  ``prefix`` picks the combine schedule:
    ``"blocked"`` (default — :func:`_prefix_scan`, the single-device fast
    path) or ``"interleaved"`` (``lax.associative_scan`` — the TIME-SHARDED
    path: its tree keeps block locality under SPMD where the blocked form's
    chunk reshape would cross shard boundaries; also sidesteps an XLA SPMD
    verifier fault in sharded scan-under-jvp).  Same math, float-level
    association-order differences only.
    """
    if prefix not in ("blocked", "interleaved"):
        raise ValueError(f"unknown prefix schedule {prefix!r}; pick from "
                         f"('blocked', 'interleaved')")
    kp = unpack_kalman(spec, params)
    Z, d = K.measurement_setup(spec, kp, params.dtype)
    if Z is None:
        raise ValueError("associative-scan filter requires a constant measurement matrix")
    if d is None:
        d = jnp.zeros((spec.N,), dtype=Z.dtype)
    state0 = K.init_state(spec, kp)
    T = data.shape[1]
    if end is None:
        end = T
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)
    R_diag = kp.obs_var * jnp.ones((spec.N,), dtype=Z.dtype)
    P0 = state0.P if psd_floor is None else _psd_project(
        jnp.where(jnp.isfinite(state0.P), state0.P, 0.0), psd_floor)
    elems, obs = _elements(Z, d, kp.Phi, kp.delta, kp.Omega_state, R_diag,
                           state0.beta, P0, data, observed)
    if prefix == "interleaved":
        out = lax.associative_scan(_combine, elems)
        m, covs = out.b, out.C
    else:
        m, covs = _prefix_scan(elems, T)
    if psd_floor is not None:
        covs = _psd_project(covs, psd_floor)
    return m, covs, (Z, d, kp, state0, obs)


def predicted_moments(m, P, kp, m0, P0):
    """(mpred (T, Ms), Ppred (T, Ms, Ms)): one-step-ahead predicted moments
    from filtered trajectories — filtered at t−1 shifted through the
    transition, with the prior (m0, P0) feeding step 0.  Shared by the loss
    pass below and the Newton tangent provider
    (ops/newton._innovations_assoc) so the shift convention cannot
    diverge."""
    m_prev = jnp.concatenate([m0[None], m[:-1]], axis=0)
    P_prev = jnp.concatenate([P0[None], P[:-1]], axis=0)
    mpred = m_prev @ kp.Phi.T + kp.delta[None]
    Ppred = _bmm(_bmm(kp.Phi, P_prev), kp.Phi.T) + kp.Omega_state[None]
    return mpred, Ppred


def _loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                psd_floor=None, prefix: str = "blocked"):
    """Shared parallel-filter loss pass.  Returns ``(loss, code, moments)``
    with ``moments = (m, P)`` the filtered trajectories — computed once so
    the serving re-filter (:func:`filter_and_loss`) and the loss consumers
    (:func:`get_loss`/:func:`get_loss_coded`) share one combine tree; XLA
    dead-code-eliminates the stacks from loss-only callers."""
    m, P, (Z, d, kp, state0, obs) = filter_means_covs(spec, params, data,
                                                      start, end, psd_floor,
                                                      prefix)
    T = data.shape[1]
    if end is None:
        end = T
    N = spec.N
    P0 = state0.P if psd_floor is None else _psd_project(
        jnp.where(jnp.isfinite(state0.P), state0.P, 0.0), psd_floor)
    mpred, Ppred = predicted_moments(m, P, kp, state0.beta, P0)
    ysafe = jnp.where(jnp.isfinite(data.T), data.T, 0.0)
    y_eff = ysafe - d[None]
    # per-step loglik by the univariate (sequential-observation) identity
    # (ops/univariate_kf.py): log|F| + vᵀF⁻¹v = Σ_i log f_i + v_i²/f_i, so a
    # scan over the N observations — each step a few ops VECTORIZED over all
    # T — replaces the (T, N, N) batched innovation Cholesky, which on CPU
    # cost more than the whole combine tree and on TPU is the classic
    # unmappable tiny-factorization case.  Same failure semantics as the
    # univariate engine: finite f ≤ 0 → NONPSD_INNOVATION, non-finite chain
    # → STATE_EXPLODED, either → −Inf through the ok gate.
    def obs_body(carry, zi_yi):
        b, Pm, ll, ok, code = carry                  # (T,Ms) (T,Ms,Ms) (T,)…
        z, y_i = zi_yi                               # (Ms,), (T,)
        zP = _mv(Pm, z)
        f = zP @ z + kp.obs_var
        f_fin = jnp.isfinite(f)
        ok = ok & (f > 0) & f_fin
        code = code | tax.bit(f_fin & (f <= 0), tax.NONPSD_INNOVATION) \
            | tax.bit(~f_fin, tax.STATE_EXPLODED)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y_i - b @ z
        Kg = zP / fsafe[:, None]
        b = b + Kg * v[:, None]
        Pm = Pm - Kg[:, :, None] * zP[:, None, :]
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b, Pm, ll, ok, code), None

    zeros_t = jnp.zeros((T,), dtype=Z.dtype)
    (_, _, ll_t, ok, codes), _ = lax.scan(
        obs_body,
        (mpred, Ppred, zeros_t, jnp.ones((T,), dtype=bool),
         jnp.zeros((T,), dtype=tax.CODE_DTYPE)),
        (Z, y_eff.T), length=N)
    t_idx = jnp.arange(T)
    contrib = (t_idx >= start + 1) & (t_idx <= end - 2) & obs
    total = jnp.sum(jnp.where(contrib, jnp.where(ok, ll_t, -jnp.inf), 0.0))
    loss = jnp.where(jnp.isfinite(total), total, -jnp.inf)
    # taxonomy bitmask beside the sentinel (robustness/taxonomy.py), same
    # decode vocabulary as the sequential engines
    code = tax.params_code(params) \
        | tax.combine(jnp.where(contrib, codes, jnp.int32(0))) \
        | tax.bit(~jnp.any(contrib), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code, (m, P)


def get_loss(spec: ModelSpec, params, data, start=0, end=None,
             psd_floor=None, prefix: str = "blocked"):
    """Gaussian loglik computed from the parallel filter — numerically matches
    the sequential kalman.get_loss (same skip-first convention) at O(log T)
    span, and differentiable end-to-end (the MLE cascade's assoc engine).
    ``psd_floor`` selects the square-root-stabilized recovery mode
    (:func:`_psd_project`); leave it ``None`` for the parity engine.
    ``prefix`` follows :func:`filter_means_covs` (time-sharded callers pass
    ``"interleaved"``)."""
    loss, _, _ = _loss_coded(spec, params, data, start, end, psd_floor,
                             prefix)
    return loss


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                   psd_floor=None, prefix: str = "blocked"):
    """``(loss, code)`` — :func:`get_loss` plus its taxonomy bitmask, the
    same self-describing failure channel every sequential engine carries."""
    loss, code, _ = _loss_coded(spec, params, data, start, end, psd_floor,
                                prefix)
    return loss, code


def filter_and_loss(spec: ModelSpec, params, data, start=0, end=None):
    """One combine tree, all three consumers: ``(m, P, loss, code)`` with
    ``(m[t], P[t])`` the filtered moments E[x_t | y_{1:t}] — the serving
    re-filter-from-scratch primitive (serving/online.py ``_jitted_refilter``):
    an exact O(log T)-span rebuild of the online state from raw history,
    replacing trust in thousands of accumulated O(1) recursive updates."""
    loss, code, (m, P) = _loss_coded(spec, params, data, start, end)
    return m, P, loss, code
