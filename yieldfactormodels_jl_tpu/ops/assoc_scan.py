"""Associative-scan (parallel-in-time) Kalman filter.

The reference's filters are strictly sequential ``for t`` loops
(SURVEY.md §5.7); on TPU the time recursion can instead run in O(log T) span
with `jax.lax.associative_scan` using the parallel Kalman formulation of
Särkkä & García-Fernández (temporal parallelization of Bayesian smoothers; cf.
PAPERS.md "Parallel square-root statistical linear regression").  This is the
framework's sequence-parallelism story: long panels (daily data, simulation
studies) stop being latency-bound on sequential steps, and the scan can be
sharded over the time axis of a mesh.

Each step is the 5-tuple element (A, b, C, J, η); composition is closed under
the filtering semigroup.  Missing observations (NaN columns) become pure
prediction elements, so multi-step forecasting composes the same way.
Applies to the time-invariant-measurement families (DNS, AFNS).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec

_LOG_2PI = math.log(2.0 * math.pi)


class FilterElement(NamedTuple):
    A: jnp.ndarray
    b: jnp.ndarray
    C: jnp.ndarray
    J: jnp.ndarray
    eta: jnp.ndarray


def _mv(M, v):
    return jnp.einsum("...ij,...j->...i", M, v)


def _combine(ei: FilterElement, ej: FilterElement) -> FilterElement:
    """Associative composition (element i happens before j)."""
    I = jnp.eye(ei.A.shape[-1], dtype=ei.A.dtype)
    D = I + ei.C @ ej.J
    Dinv_Ai = jnp.linalg.solve(D, ei.A)
    Dinv_bCe = jnp.linalg.solve(D, (ei.b + _mv(ei.C, ej.eta))[..., None])[..., 0]
    A = ej.A @ Dinv_Ai
    b = _mv(ej.A, Dinv_bCe) + ej.b
    C = ej.A @ jnp.linalg.solve(D, ei.C) @ ej.A.swapaxes(-1, -2) + ej.C
    E = I + ej.J @ ei.C
    Einv_Jj = jnp.linalg.solve(E, ej.J)
    Ait = ei.A.swapaxes(-1, -2)
    eta = _mv(Ait, jnp.linalg.solve(
        E, (ej.eta - _mv(ej.J, ei.b))[..., None])[..., 0]) + ei.eta
    J = Ait @ Einv_Jj @ ei.A + ei.J
    return FilterElement(A, b, C, J, eta)


def _elements(Z, d, Phi, delta, Q, R_diag, m0, P0, data, observed):
    """Build the per-step elements for all T steps at once (batched)."""
    N, Ms = Z.shape
    T = data.shape[1]
    I = jnp.eye(Ms, dtype=Z.dtype)
    y = jnp.where(jnp.isfinite(data.T), data.T, 0.0)  # (T, N)
    obs = observed & jnp.all(jnp.isfinite(data.T), axis=1)
    obs_f = obs.astype(Z.dtype)[:, None]

    R = jnp.diag(R_diag)
    # generic element (k >= 2): uses only local quantities
    S = Z @ Q @ Z.T + R
    S_cho = jnp.linalg.cholesky(S)
    Kg = jax.scipy.linalg.cho_solve((S_cho, True), Z @ Q.T).T  # Q Zᵀ S⁻¹
    A_g = (I - Kg @ Z) @ Phi
    C_g = (I - Kg @ Z) @ Q
    ZtSi = jax.scipy.linalg.cho_solve((S_cho, True), Z).T  # Zᵀ S⁻¹
    J_g = Phi.T @ ZtSi @ Z @ Phi

    resid = y - (Z @ delta + d)[None, :]  # y_k − Z c − d  (T, N)
    b_g = delta[None, :] + resid @ Kg.T
    eta_g = resid @ (Phi.T @ ZtSi).T

    # first element: exact update from the prior (m0, P0)
    mpred1 = Phi @ m0 + delta
    Ppred1 = Phi @ P0 @ Phi.T + Q
    S1 = Z @ Ppred1 @ Z.T + R
    S1_cho = jnp.linalg.cholesky(S1)
    K1 = jax.scipy.linalg.cho_solve((S1_cho, True), Z @ Ppred1.T).T
    b_1 = mpred1 + K1 @ (y[0] - Z @ mpred1 - d)
    C_1 = (I - K1 @ Z) @ Ppred1

    # assemble (T, ...) with missing steps as pure prediction elements
    A = jnp.where(obs_f[:, :, None], A_g[None], Phi[None])
    b = jnp.where(obs_f, b_g, delta[None, :])
    C = jnp.where(obs_f[:, :, None], C_g[None], Q[None])
    J = jnp.where(obs_f[:, :, None], J_g[None], jnp.zeros_like(J_g)[None])
    eta = jnp.where(obs_f, eta_g, jnp.zeros_like(eta_g))

    # overwrite k = 1 (prior-conditioned); A₁ = 0, J₁ = η₁ = 0
    A = A.at[0].set(jnp.where(obs[0], jnp.zeros_like(Phi), Phi))
    b = b.at[0].set(jnp.where(obs[0], b_1, mpred1))
    C = C.at[0].set(jnp.where(obs[0], C_1, Ppred1))
    J = J.at[0].set(jnp.zeros_like(J_g))
    eta = eta.at[0].set(jnp.zeros_like(eta_g[0]))
    return FilterElement(A, b, C, J, eta), obs


def filter_means_covs(spec: ModelSpec, params, data, start=0, end=None):
    """Filtered means/covariances for every t via `lax.associative_scan`.

    Returns (m (T, Ms) = E[x_t | y_{1:t}], P (T, Ms, Ms)).
    """
    kp = unpack_kalman(spec, params)
    Z, d = K.measurement_setup(spec, kp, params.dtype)
    if Z is None:
        raise ValueError("associative-scan filter requires a constant measurement matrix")
    if d is None:
        d = jnp.zeros((spec.N,), dtype=Z.dtype)
    state0 = K.init_state(spec, kp)
    T = data.shape[1]
    if end is None:
        end = T
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)
    R_diag = kp.obs_var * jnp.ones((spec.N,), dtype=Z.dtype)
    elems, obs = _elements(Z, d, kp.Phi, kp.delta, kp.Omega_state, R_diag,
                           state0.beta, state0.P, data, observed)
    out = lax.associative_scan(_combine, elems)
    return out.b, out.C, (Z, d, kp, state0, obs)


def get_loss(spec: ModelSpec, params, data, start=0, end=None):
    """Gaussian loglik computed from the parallel filter — numerically matches
    the sequential kalman.get_loss (same skip-first convention)."""
    m, P, (Z, d, kp, state0, obs) = filter_means_covs(spec, params, data, start, end)
    T = data.shape[1]
    if end is None:
        end = T
    N = spec.N
    R = kp.obs_var * jnp.eye(N, dtype=Z.dtype)
    # predicted moments at t from filtered at t−1
    m_prev = jnp.concatenate([state0.beta[None], m[:-1]], axis=0)
    P_prev = jnp.concatenate([state0.P[None], P[:-1]], axis=0)
    mpred = m_prev @ kp.Phi.T + kp.delta[None]
    Ppred = jnp.einsum("ij,tjk,lk->til", kp.Phi, P_prev, kp.Phi) + kp.Omega_state[None]
    ysafe = jnp.where(jnp.isfinite(data.T), data.T, 0.0)
    v = ysafe - (mpred @ Z.T + d[None])
    F = jnp.einsum("ij,tjk,lk->til", Z, Ppred, Z) + R[None]
    cho = jnp.linalg.cholesky(F)
    ok = jnp.all(jnp.isfinite(cho), axis=(1, 2))
    cho_safe = jnp.where(ok[:, None, None], jnp.nan_to_num(cho),
                         jnp.eye(N, dtype=Z.dtype)[None])
    Fi_v = jax.scipy.linalg.cho_solve((cho_safe, True), v[..., None])[..., 0]
    logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(cho_safe, axis1=1, axis2=2)), axis=1)
    ll_t = -0.5 * (logdet + jnp.sum(v * Fi_v, axis=1) + N * _LOG_2PI)
    t_idx = jnp.arange(T)
    contrib = (t_idx >= start + 1) & (t_idx <= end - 2) & obs
    total = jnp.sum(jnp.where(contrib, jnp.where(ok, ll_t, -jnp.inf), 0.0))
    return jnp.where(jnp.isfinite(total), total, -jnp.inf)
