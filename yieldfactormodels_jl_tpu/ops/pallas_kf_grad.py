"""Differentiable Pallas Kalman loglik: hand-derived adjoint kernel.

``pallas_kf.batched_loglik`` is evaluation-only (Pallas kernels have no
autodiff).  This module adds ``batched_loglik_diff`` — the same fused forward
for the constant-measurement Kalman families plus a *hand-derived reverse
(adjoint) kernel*, wired together with ``jax.custom_vjp`` so ``jax.grad``
through it works and MLE can run entirely on the fused kernels.

Memory strategy (the whole point of doing this by hand): reverse-mode through
a ``lax.scan`` stores every per-step primal; XLA spills them to HBM.  Here the
forward kernel saves only ``nC ≈ √T`` segment checkpoints of the (β, P)
carry, and the backward kernel re-computes each segment's per-step states into
VMEM scratch before running the per-step adjoints — classic binomial
checkpointing, all on-chip:

  forward : state₀ ─▶ … save state_{c·S} … ─▶ loglik
  backward: for c = nC−1 … 0:  recompute states in [c·S, (c+1)·S) into VMEM,
            then sweep the segment in reverse accumulating
            (∂Z, ∂d, ∂Φ, ∂δ, ∂Ω, ∂σ², ∂β₀, ∂P₀) and the carry adjoints.

Per-step adjoint of the univariate (rank-1) measurement update, derived from

    zP = P z,  f = z'zP + σ²,  v = y − d − z'b,  K = zP/f,
    b' = b + K v,  P' = P − K zP',  ll += −½(log f + v²/f + log 2π):

    K̄ = −P̄' zP + v b̄',          z̄P = −P̄'ᵀ K + K̄/f + f̄ z
    v̄ = K·b̄' − w v/f,           f̄ = −(K̄·K)/f − ½ w (1/f − v²/f²)
    b̄ += b̄' − fin·v̄·z,          P̄ += P̄' + z z̄Pᵀ
    z̄ += −fin·v̄·b + f̄·zP + P z̄P,  d̄ += −fin·v̄,  σ̄² += f̄

(w = cotangent × obs × contrib gate), and of the transition
β⁺ = δ + Φβ_m, P⁺ = ΦP_mΦᵀ + Ω:

    δ̄ += β̄⁺,  Ω̄ += P̄⁺,  Φ̄ += β̄⁺β_mᵀ + (P̄⁺ + P̄⁺ᵀ) Φ P_m,
    β̄_m = Φᵀβ̄⁺,  P̄_m = ΦᵀP̄⁺Φ.

Gradients are validated against ``jax.grad`` of ``univariate_kf.get_loss``
(identical algebra) in tests/test_pallas_grad.py.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.kalman import init_state, loglik_contrib_mask, measurement_setup
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from .pallas_kf import (_LANE, _SUB, TILE, CompilerParams, _lay, tvl_rows,
                        window_array, window_masks)

_LOG_2PI = math.log(2.0 * math.pi)


def _seg(T: int):
    """(segment length, #checkpoints) ≈ √T blocking."""
    S = max(1, int(math.ceil(math.sqrt(T))))
    return S, -(-T // S)


# ---------------------------------------------------------------------------
# shared per-step primal math (values in/out, fully unrolled)
# ---------------------------------------------------------------------------

def _inner_chain(N, Ms, Z, d, ovar, y_scal, b, Pm):
    """Run the N rank-1 updates; returns (b_u, P_u_unsym, P_u_sym, ll,
    fin_all, cache) where cache holds per-i (zP, fsafe, v, K, fin) for the
    adjoint.  Pre-update states are NOT stored — the adjoint reconstructs
    them by inverting each rank-1 update (P_pre = P_post + K zPᵀ,
    b_pre = b_post − K v), keeping the backward's live set ~5× smaller.
    ``Z``/``d`` are tuples of tiles; ``y_scal`` python list of data scalars.
    """
    cache = []
    ll = 0.0
    fin_all = True
    for i in range(N):
        z = Z[i]
        y_i = y_scal[i]
        fin_i = jnp.isfinite(y_i)
        fin_all = jnp.logical_and(fin_all, fin_i)
        zP = [sum(z[k] * Pm[k * Ms + m] for k in range(Ms)) for m in range(Ms)]
        f = sum(zP[m] * z[m] for m in range(Ms)) + ovar
        fsafe = jnp.where(f > 0, f, jnp.ones_like(f))
        predv = sum(z[m] * b[m] for m in range(Ms)) + d[i]
        v = jnp.where(fin_i, y_i - predv, jnp.zeros_like(predv))
        K = [zP[m] / fsafe for m in range(Ms)]
        b = [b[m] + K[m] * v for m in range(Ms)]
        Pm = [Pm[k * Ms + m] - K[k] * zP[m] for k in range(Ms) for m in range(Ms)]
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        cache.append((zP, fsafe, v, K, fin_i))
    P_unsym = list(Pm)
    Pm = [0.5 * (Pm[k * Ms + m] + Pm[m * Ms + k])
          for k in range(Ms) for m in range(Ms)]
    return b, P_unsym, Pm, ll, fin_all, cache


def _transition(Ms, phi, delta, om, b_m, P_m):
    b_next = [delta[m] + sum(phi[m * Ms + k] * b_m[k] for k in range(Ms))
              for m in range(Ms)]
    PA = [sum(phi[m * Ms + k] * P_m[k * Ms + n] for k in range(Ms))
          for m in range(Ms) for n in range(Ms)]
    P_next = [om[m * Ms + n]
              + sum(PA[m * Ms + k] * phi[n * Ms + k] for k in range(Ms))
              for m in range(Ms) for n in range(Ms)]
    return b_next, P_next


def _full_step(N, Ms, Z, d, phi, delta, om, ovar, y_scal, obs_s, beta, P):
    """One forward step on values; returns (β⁺, P⁺) with obs blending."""
    b_u, _, P_u, _, fin_all, _ = _inner_chain(N, Ms, Z, d, ovar, y_scal,
                                              list(beta), list(P))
    obs = jnp.logical_and(obs_s, fin_all)
    b_m = [jnp.where(obs, b_u[m], beta[m]) for m in range(Ms)]
    P_m = [jnp.where(obs, P_u[k], P[k]) for k in range(Ms * Ms)]
    return _transition(Ms, phi, delta, om, b_m, P_m), obs


# ---------------------------------------------------------------------------
# forward kernel: value + segment checkpoints
# ---------------------------------------------------------------------------

def _fwd_kernel(N, Ms, T, S, nC, windowed,
                Zr, dr, phir, deltar, omr, ovarr, b0r, p0r, datar, maskr,
                winr, outr, chkr):
    f32 = phir.dtype
    D = Ms + Ms * Ms
    ovar = ovarr[0]
    Z = tuple(tuple(Zr[i * Ms + m] for m in range(Ms)) for i in range(N))
    d = tuple(dr[i] for i in range(N))
    phi = tuple(phir[j] for j in range(Ms * Ms))
    delta = tuple(deltar[m] for m in range(Ms))
    om = tuple(omr[j] for j in range(Ms * Ms))

    beta0 = tuple(b0r[m] for m in range(Ms))
    P0 = tuple(p0r[k] for k in range(Ms * Ms))
    # zero tile derived from a loaded value: a broadcasted-constant zero gets
    # a replicated Mosaic layout that cannot be reconciled with the computed
    # (distributed) tiles the loop body produces
    ll0 = ovar * 0.0

    def step(t, carry):
        beta, P, ll = carry

        @pl.when(t % S == 0)
        def _save():
            c = t // S
            chkr[pl.ds(c * D, D)] = jnp.stack(list(beta) + list(P))

        obs_s, con_s = window_masks(windowed, f32, maskr, winr, t)
        y_scal = [datar[t, i] for i in range(N)]
        b_u, _, P_u, ll_step, fin_all, cache = _inner_chain(
            N, Ms, Z, d, ovar, y_scal, list(beta), list(P))
        ok = jnp.ones((_SUB, _LANE), dtype=jnp.bool_)
        for i, (zP, fsafe, v, K, fin_i) in enumerate(cache):
            z = Z[i]
            f = sum(zP[m] * z[m] for m in range(Ms)) + ovar
            ok = ok & (f > 0) & jnp.isfinite(f)
        obs = jnp.logical_and(obs_s, fin_all)
        b_m = [jnp.where(obs, b_u[m], beta[m]) for m in range(Ms)]
        P_m = [jnp.where(obs, P_u[k], P[k]) for k in range(Ms * Ms)]
        b_next, P_next = _transition(Ms, phi, delta, om, b_m, P_m)
        neg_inf = jnp.full((_SUB, _LANE), -jnp.inf, dtype=f32)
        zero = jnp.zeros((_SUB, _LANE), dtype=f32)
        ll_t = jnp.where(jnp.logical_and(obs, con_s),
                         jnp.where(ok, ll_step, neg_inf), zero)
        return tuple(b_next), tuple(P_next), ll + ll_t

    _, _, ll = jax.lax.fori_loop(0, T, step, (beta0, P0, ll0))
    outr[...] = jnp.where(jnp.isfinite(ll), ll, -jnp.inf)


# ---------------------------------------------------------------------------
# backward kernel: segment recompute + per-step adjoints
# ---------------------------------------------------------------------------

def _bwd_kernel(N, Ms, T, S, nC, windowed,
                Zr, dr, phir, deltar, omr, ovarr, datar, maskr, winr, chkr, gr,
                gZr, gdr, gphir, gdeltar, gomr, govarr, gb0r, gp0r, segr):
    f32 = phir.dtype
    D = Ms + Ms * Ms
    ovar = ovarr[0]
    Z = tuple(tuple(Zr[i * Ms + m] for m in range(Ms)) for i in range(N))
    d = tuple(dr[i] for i in range(N))
    phi = tuple(phir[j] for j in range(Ms * Ms))
    delta = tuple(deltar[m] for m in range(Ms))
    om = tuple(omr[j] for j in range(Ms * Ms))
    g = gr[...]  # cotangent per lane, already gated on finite ll

    # loaded-value-derived zero tile (see _fwd_kernel layout note)
    zt = ovar * 0.0

    def zeros(n):
        return tuple(zt for _ in range(n))

    def step_adjoint(t, beta, P, bbar_n, Pbar_n, acc):
        """Adjoint of one step given its incoming primal state (β, P)."""
        (gZ, gd, gphi, gdelta, gom, govar) = acc
        obs_s, con_s = window_masks(windowed, f32, maskr, winr, t)
        y_scal = [datar[t, i] for i in range(N)]
        b_u, P_u_unsym, P_u_sym, _, fin_all, cache = _inner_chain(
            N, Ms, Z, d, ovar, y_scal, list(beta), list(P))
        obs = jnp.logical_and(obs_s, fin_all)
        obs_f = obs.astype(f32)
        w = jnp.where(jnp.logical_and(obs, con_s), g, zt)

        b_m = [jnp.where(obs, b_u[m], beta[m]) for m in range(Ms)]
        P_m = [jnp.where(obs, P_u_sym[k], P[k]) for k in range(Ms * Ms)]

        # ---- transition backward ----
        gdelta = tuple(gdelta[m] + bbar_n[m] for m in range(Ms))
        gom = tuple(gom[j] + Pbar_n[j] for j in range(Ms * Ms))
        # Φ̄ += β̄⁺ β_mᵀ + (P̄⁺ + P̄⁺ᵀ) Φ P_m
        PbS = [Pbar_n[m * Ms + n] + Pbar_n[n * Ms + m]
               for m in range(Ms) for n in range(Ms)]
        PhiPm = [sum(phi[a * Ms + k] * P_m[k * Ms + bcol] for k in range(Ms))
                 for a in range(Ms) for bcol in range(Ms)]
        gphi = tuple(
            gphi[m * Ms + k]
            + bbar_n[m] * b_m[k]
            + sum(PbS[m * Ms + a] * PhiPm[a * Ms + k] for a in range(Ms))
            for m in range(Ms) for k in range(Ms))
        # β̄_m = Φᵀ β̄⁺ ;  P̄_m = Φᵀ P̄⁺ Φ
        bbar_m = [sum(phi[a * Ms + m] * bbar_n[a] for a in range(Ms))
                  for m in range(Ms)]
        PtPb = [sum(phi[a * Ms + m] * Pbar_n[a * Ms + bcol] for a in range(Ms))
                for m in range(Ms) for bcol in range(Ms)]
        Pbar_m = [sum(PtPb[m * Ms + a] * phi[a * Ms + n] for a in range(Ms))
                  for m in range(Ms) for n in range(Ms)]

        # ---- blend backward ----
        bbar_u = [obs_f * bbar_m[m] for m in range(Ms)]
        bbar_pre = [(1.0 - obs_f) * bbar_m[m] for m in range(Ms)]
        Pbar_u_sym = [obs_f * Pbar_m[k] for k in range(Ms * Ms)]
        Pbar_pre = [(1.0 - obs_f) * Pbar_m[k] for k in range(Ms * Ms)]
        # desymmetrize P_u = ½(P + Pᵀ)
        Pbar_u = [0.5 * (Pbar_u_sym[k * Ms + m] + Pbar_u_sym[m * Ms + k])
                  for k in range(Ms) for m in range(Ms)]

        # ---- inner updates backward (i = N−1 … 0) ----
        # primal (b_post, P_post) is walked backwards by INVERTING each
        # rank-1 update instead of storing every pre-state
        bbar = list(bbar_u)
        Pbar = list(Pbar_u)
        b_post = list(b_u)
        P_post = list(P_u_unsym)
        gZ, gd, govar = list(gZ), list(gd), list(govar)
        for i in reversed(range(N)):
            z = Z[i]
            (zP, fsafe, v, K, fin_i) = cache[i]
            # invert: P_pre = P_post + K zPᵀ,  b_pre = b_post − K v
            P_pre = [P_post[k * Ms + m] + K[k] * zP[m]
                     for k in range(Ms) for m in range(Ms)]
            b_pre = [b_post[m] - K[m] * v for m in range(Ms)]
            fin_f = jnp.where(fin_i, jnp.ones((), f32), jnp.zeros((), f32))
            inv_f = 1.0 / fsafe
            # K̄ = −P̄' zP + v b̄'
            Kbar = [-sum(Pbar[k * Ms + m] * zP[m] for m in range(Ms))
                    + v * bbar[k] for k in range(Ms)]
            # z̄P (from P' and K)
            zPbar = [-sum(Pbar[k * Ms + m] * K[k] for k in range(Ms))
                     + Kbar[m] * inv_f for m in range(Ms)]
            # v̄ = K·b̄' − w v/f
            vbar = sum(K[m] * bbar[m] for m in range(Ms)) - w * v * inv_f
            # f̄ = −(K̄·K)/f − ½ w (1/f − v²/f²)
            fbar = (-sum(Kbar[m] * K[m] for m in range(Ms)) * inv_f
                    - 0.5 * w * (inv_f - v * v * inv_f * inv_f))
            # f = z·zP + σ² contributions
            zPbar = [zPbar[m] + fbar * z[m] for m in range(Ms)]
            govar[0] = govar[0] + fbar
            # b̄ (into pre-update state) and parameter rows
            bbar = [bbar[m] - fin_f * vbar * z[m] for m in range(Ms)]
            gd[i] = gd[i] - fin_f * vbar
            # z̄ row i: −fin v̄ b + f̄ zP + Pᵀ z̄P (P pre-update, symmetric)
            for m in range(Ms):
                gZ[i * Ms + m] = (gZ[i * Ms + m]
                                  - fin_f * vbar * b_pre[m]
                                  + fbar * zP[m]
                                  + sum(P_pre[m * Ms + k] * zPbar[k]
                                        for k in range(Ms)))
            # P̄ (into pre-update state): direct + outer(z, z̄P)
            Pbar = [Pbar[k * Ms + m] + z[k] * zPbar[m]
                    for k in range(Ms) for m in range(Ms)]
            b_post, P_post = b_pre, P_pre

        bbar_out = [bbar[m] + bbar_pre[m] for m in range(Ms)]
        Pbar_out = [Pbar[k] + Pbar_pre[k] for k in range(Ms * Ms)]
        return (bbar_out, Pbar_out,
                (tuple(gZ), tuple(gd), gphi, gdelta, gom, tuple(govar)))

    def seg_body(ci, carry):
        c = nC - 1 - ci
        bbar, Pbar, acc = carry
        # load checkpoint state (start of segment)
        st = chkr[pl.ds(c * D, D)]
        st_b = [st[m] for m in range(Ms)]
        st_P = [st[Ms + k] for k in range(Ms * Ms)]

        # forward recompute: store each local step's incoming state
        def fwd_body(s, state):
            beta, P = state
            t = c * S + s
            valid = t < T
            segr[pl.ds(s * D, D)] = jnp.stack(list(beta) + list(P))
            y_scal = [datar[jnp.minimum(t, T - 1), i] for i in range(N)]
            obs_s, _ = window_masks(windowed, f32, maskr, winr,
                                     jnp.minimum(t, T - 1))
            (b_next, P_next), _ = _full_step(N, Ms, Z, d, phi, delta, om,
                                             ovar, y_scal, obs_s, beta, P)
            beta = tuple(jnp.where(valid, b_next[m], beta[m]) for m in range(Ms))
            P = tuple(jnp.where(valid, P_next[k], P[k]) for k in range(Ms * Ms))
            return beta, P

        jax.lax.fori_loop(0, S, fwd_body, (tuple(st_b), tuple(st_P)))

        # reverse sweep over the segment
        def bwd_body(s2, carry2):
            bbar, Pbar, acc = carry2
            s = S - 1 - s2
            t = c * S + s
            valid = t < T
            blk = segr[pl.ds(s * D, D)]
            beta = tuple(blk[m] for m in range(Ms))
            P = tuple(blk[Ms + k] for k in range(Ms * Ms))
            t_safe = jnp.minimum(t, T - 1)
            nb, nP, nacc = step_adjoint(t_safe, beta, P, bbar, Pbar, acc)
            bbar = tuple(jnp.where(valid, nb[m], bbar[m]) for m in range(Ms))
            Pbar = tuple(jnp.where(valid, nP[k], Pbar[k]) for k in range(Ms * Ms))
            acc = jax.tree.map(lambda new, old: jnp.where(valid, new, old),
                               nacc, acc)
            return bbar, Pbar, acc

        return jax.lax.fori_loop(0, S, bwd_body, (bbar, Pbar, acc))

    acc0 = (zeros(N * Ms), zeros(N), zeros(Ms * Ms), zeros(Ms),
            zeros(Ms * Ms), zeros(1))
    bbar0, Pbar0, acc = jax.lax.fori_loop(
        0, nC, seg_body, (zeros(Ms), zeros(Ms * Ms), acc0))
    (gZ, gd, gphi, gdelta, gom, govar) = acc
    for j in range(N * Ms):
        gZr[j] = gZ[j]
    for j in range(N):
        gdr[j] = gd[j]
    for j in range(Ms * Ms):
        gphir[j] = gphi[j]
        gomr[j] = gom[j]
        gp0r[j] = Pbar0[j]
    for m in range(Ms):
        gdeltar[m] = gdelta[m]
        gb0r[m] = bbar0[m]
    govarr[0] = govar[0]


# ---------------------------------------------------------------------------
# TVλ EKF: state-dependent measurement rows
# ---------------------------------------------------------------------------
#
# The TVλ family rebuilds its loading row per step from the predicted state
# (λ = 1e-2 + e^{β₄}; Jacobian column per kalman/filter.jl:38-46), so the
# measurement chain's adjoint needs SECOND derivatives of the loadings
# (d(dZ₂/dλ)/dλ through the Jacobian).  Rather than hand-deriving those, the
# backward kernel keeps the same √T-checkpoint structure and runs ``jax.vjp``
# over ONE step's value function (pallas_kf.tvl_rows + the rank-1 chain +
# blend + transition — all unrolled elementwise tile arithmetic, so the
# transpose lowers like the hand-written adjoints).  This guarantees the
# adjoint can never diverge from the forward build, including the
# ``exact_jacobian`` quirk flag.


def _tvl_chain_values(N, Ms, mats, exact, ovar, y_scal, beta, P):
    """TVλ inner chain on values.  Returns (b_u, P_u_sym, ll, fin_all, ok)."""
    trows = tvl_rows(beta, mats, exact)
    b = list(beta)
    Pm = list(P)
    ll = beta[0] * 0.0  # loaded-value-derived zero (Mosaic layout note above)
    ok = None
    fin_all = True
    for i in range(N):
        z, jb = trows[i]
        y_i = y_scal[i]
        fin_i = jnp.isfinite(y_i)
        fin_all = jnp.logical_and(fin_all, fin_i)
        zP = [sum(z[k] * Pm[k * Ms + m] for k in range(Ms)) for m in range(Ms)]
        f = sum(zP[m] * z[m] for m in range(Ms)) + ovar
        ok_i = (f > 0) & jnp.isfinite(f)
        ok = ok_i if ok is None else (ok & ok_i)
        fsafe = jnp.where(f > 0, f, jnp.ones_like(f))
        predv = sum(z[m] * b[m] for m in range(Ms))
        v = jnp.where(fin_i, y_i + jb - predv, jnp.zeros_like(predv))
        K = [zP[m] / fsafe for m in range(Ms)]
        b = [b[m] + K[m] * v for m in range(Ms)]
        Pm = [Pm[k * Ms + m] - K[k] * zP[m] for k in range(Ms) for m in range(Ms)]
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
    Pm = [0.5 * (Pm[k * Ms + m] + Pm[m * Ms + k])
          for k in range(Ms) for m in range(Ms)]
    return b, Pm, ll, fin_all, ok


def _tvl_full_step(N, Ms, mats, exact, phi, delta, om, ovar, y_scal, obs_s,
                   beta, P):
    """One TVλ forward step on values with obs blending (no ll)."""
    b_u, P_u, _, fin_all, _ = _tvl_chain_values(N, Ms, mats, exact, ovar,
                                                y_scal, beta, P)
    obs = jnp.logical_and(obs_s, fin_all)
    b_m = [jnp.where(obs, b_u[m], beta[m]) for m in range(Ms)]
    P_m = [jnp.where(obs, P_u[k], P[k]) for k in range(Ms * Ms)]
    return _transition(Ms, phi, delta, om, b_m, P_m), obs


def _fwd_kernel_tvl(N, Ms, T, S, nC, windowed, exact, mats,
                    phir, deltar, omr, ovarr, b0r, p0r, datar, maskr,
                    winr, outr, chkr):
    f32 = phir.dtype
    D = Ms + Ms * Ms
    ovar = ovarr[0]
    phi = tuple(phir[j] for j in range(Ms * Ms))
    delta = tuple(deltar[m] for m in range(Ms))
    om = tuple(omr[j] for j in range(Ms * Ms))
    beta0 = tuple(b0r[m] for m in range(Ms))
    P0 = tuple(p0r[k] for k in range(Ms * Ms))
    ll0 = ovar * 0.0

    def step(t, carry):
        beta, P, ll = carry

        @pl.when(t % S == 0)
        def _save():
            c = t // S
            chkr[pl.ds(c * D, D)] = jnp.stack(list(beta) + list(P))

        obs_s, con_s = window_masks(windowed, f32, maskr, winr, t)
        y_scal = [datar[t, i] for i in range(N)]
        b_u, P_u, ll_step, fin_all, ok = _tvl_chain_values(
            N, Ms, mats, exact, ovar, y_scal, beta, P)
        obs = jnp.logical_and(obs_s, fin_all)
        b_m = [jnp.where(obs, b_u[m], beta[m]) for m in range(Ms)]
        P_m = [jnp.where(obs, P_u[k], P[k]) for k in range(Ms * Ms)]
        b_next, P_next = _transition(Ms, phi, delta, om, b_m, P_m)
        neg_inf = ll0 - jnp.inf
        ll_t = jnp.where(jnp.logical_and(obs, con_s),
                         jnp.where(ok, ll_step, neg_inf), ll0)
        return tuple(b_next), tuple(P_next), ll + ll_t

    _, _, ll = jax.lax.fori_loop(0, T, step, (beta0, P0, ll0))
    outr[...] = jnp.where(jnp.isfinite(ll), ll, -jnp.inf)


def _bwd_kernel_tvl(N, Ms, T, S, nC, windowed, exact, mats,
                    phir, deltar, omr, ovarr, datar, maskr, winr, chkr, gr,
                    gphir, gdeltar, gomr, govarr, gb0r, gp0r, segr):
    f32 = phir.dtype
    D = Ms + Ms * Ms
    ovar = ovarr[0]
    phi = tuple(phir[j] for j in range(Ms * Ms))
    delta = tuple(deltar[m] for m in range(Ms))
    om = tuple(omr[j] for j in range(Ms * Ms))
    g = gr[...]  # cotangent per lane, already gated on finite ll
    zt = ovar * 0.0

    def zeros(n):
        return tuple(zt for _ in range(n))

    def step_adjoint(t, beta, P, bbar_n, Pbar_n, acc):
        """Adjoint of one TVλ step via jax.vjp of its value function: the AD
        transpose covers the loading build's state dependence (incl. the
        second-derivative terms through the Jacobian column) exactly."""
        (gphi, gdelta, gom, govar) = acc
        obs_s, con_s = window_masks(windowed, f32, maskr, winr, t)
        y_scal = [datar[t, i] for i in range(N)]
        fin_all = True
        for i in range(N):
            fin_all = jnp.logical_and(fin_all, jnp.isfinite(y_scal[i]))
        obs = jnp.logical_and(obs_s, fin_all)

        def f(beta_t, P_t, phi_t, delta_t, om_t, ovar_t):
            b_u, P_u, ll_step, _fin, _ok = _tvl_chain_values(
                N, Ms, mats, exact, ovar_t, y_scal, beta_t, P_t)
            b_m = tuple(jnp.where(obs, b_u[m], beta_t[m]) for m in range(Ms))
            P_m = tuple(jnp.where(obs, P_u[k], P_t[k]) for k in range(Ms * Ms))
            b_next, P_next = _transition(Ms, phi_t, delta_t, om_t, b_m, P_m)
            return tuple(b_next), tuple(P_next), ll_step

        # lanes whose total ll hit the −Inf sentinel have g = 0 already, so
        # the ok-gate needs no extra handling here
        w = jnp.where(jnp.logical_and(obs, con_s), g, zt)
        _, pullback = jax.vjp(f, tuple(beta), tuple(P), phi, delta, om,
                              (ovar,)[0])
        bbar, Pbar, gphi_d, gdelta_d, gom_d, govar_d = pullback(
            (tuple(bbar_n), tuple(Pbar_n), w))
        gphi = tuple(gphi[j] + gphi_d[j] for j in range(Ms * Ms))
        gdelta = tuple(gdelta[m] + gdelta_d[m] for m in range(Ms))
        gom = tuple(gom[j] + gom_d[j] for j in range(Ms * Ms))
        govar = (govar[0] + govar_d,)
        return list(bbar), list(Pbar), (gphi, gdelta, gom, govar)

    def seg_body(ci, carry):
        c = nC - 1 - ci
        bbar, Pbar, acc = carry
        st = chkr[pl.ds(c * D, D)]
        st_b = [st[m] for m in range(Ms)]
        st_P = [st[Ms + k] for k in range(Ms * Ms)]

        def fwd_body(s, state):
            beta, P = state
            t = c * S + s
            valid = t < T
            segr[pl.ds(s * D, D)] = jnp.stack(list(beta) + list(P))
            y_scal = [datar[jnp.minimum(t, T - 1), i] for i in range(N)]
            obs_s, _ = window_masks(windowed, f32, maskr, winr,
                                    jnp.minimum(t, T - 1))
            (b_next, P_next), _ = _tvl_full_step(N, Ms, mats, exact, phi,
                                                 delta, om, ovar, y_scal,
                                                 obs_s, beta, P)
            beta = tuple(jnp.where(valid, b_next[m], beta[m]) for m in range(Ms))
            P = tuple(jnp.where(valid, P_next[k], P[k]) for k in range(Ms * Ms))
            return beta, P

        jax.lax.fori_loop(0, S, fwd_body, (tuple(st_b), tuple(st_P)))

        def bwd_body(s2, carry2):
            bbar, Pbar, acc = carry2
            s = S - 1 - s2
            t = c * S + s
            valid = t < T
            blk = segr[pl.ds(s * D, D)]
            beta = tuple(blk[m] for m in range(Ms))
            P = tuple(blk[Ms + k] for k in range(Ms * Ms))
            t_safe = jnp.minimum(t, T - 1)
            nb, nP, nacc = step_adjoint(t_safe, beta, P, bbar, Pbar, acc)
            bbar = tuple(jnp.where(valid, nb[m], bbar[m]) for m in range(Ms))
            Pbar = tuple(jnp.where(valid, nP[k], Pbar[k]) for k in range(Ms * Ms))
            acc = jax.tree.map(lambda new, old: jnp.where(valid, new, old),
                               nacc, acc)
            return bbar, Pbar, acc

        return jax.lax.fori_loop(0, S, bwd_body, (bbar, Pbar, acc))

    acc0 = (zeros(Ms * Ms), zeros(Ms), zeros(Ms * Ms), zeros(1))
    bbar0, Pbar0, acc = jax.lax.fori_loop(
        0, nC, seg_body, (zeros(Ms), zeros(Ms * Ms), acc0))
    (gphi, gdelta, gom, govar) = acc
    for j in range(Ms * Ms):
        gphir[j] = gphi[j]
        gomr[j] = gom[j]
        gp0r[j] = Pbar0[j]
    for m in range(Ms):
        gdeltar[m] = gdelta[m]
        gb0r[m] = bbar0[m]
    govarr[0] = govar[0]


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _core_tvl(spec, interpret, windowed, Phi, delta, Om, ovar, beta0, P0,
              data, masks, win):
    out, _ = _core_tvl_fwd(spec, interpret, windowed, Phi, delta, Om, ovar,
                           beta0, P0, data, masks, win)
    return out


def _core_tvl_fwd(spec, interpret, windowed, Phi, delta, Om, ovar, beta0, P0,
                  data, masks, win):
    f32 = Phi.dtype
    B = Phi.shape[0]
    nb = -(-B // TILE)
    N, Ms = spec.N, spec.state_dim
    T = data.shape[1]
    S, nC = _seg(T)
    D = Ms + Ms * Ms
    mats = tuple(float(m) for m in spec.maturities)

    args = [_lay(Phi.astype(f32), B, nb), _lay(delta.astype(f32), B, nb),
            _lay(Om.astype(f32), B, nb), _lay(ovar.astype(f32), B, nb),
            _lay(beta0.astype(f32), B, nb), _lay(P0.astype(f32), B, nb),
            jnp.asarray(data, dtype=f32).T, masks.astype(f32),
            _lay(win.astype(f32), B, nb)]

    def tile_spec(Drows):
        return pl.BlockSpec((Drows, _SUB, _LANE), lambda gidx: (0, gidx, 0),
                            memory_space=pltpu.VMEM)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    out, chk = pl.pallas_call(
        partial(_fwd_kernel_tvl, N, Ms, T, S, nC, windowed,
                spec.exact_jacobian, mats),
        grid=(nb,),
        in_specs=[tile_spec(Ms * Ms), tile_spec(Ms), tile_spec(Ms * Ms),
                  tile_spec(1), tile_spec(Ms), tile_spec(Ms * Ms),
                  smem, smem, tile_spec(2)],
        out_specs=(pl.BlockSpec((_SUB, _LANE), lambda gidx: (gidx, 0),
                                memory_space=pltpu.VMEM),
                   tile_spec(nC * D)),
        out_shape=(jax.ShapeDtypeStruct((nb * _SUB, _LANE), f32),
                   jax.ShapeDtypeStruct((nC * D, nb * _SUB, _LANE), f32)),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    ll = out.reshape(-1)[:B]
    shapes = (Phi.shape, delta.shape, Om.shape, ovar.shape, beta0.shape,
              P0.shape, data.shape, masks.shape, win.shape)
    return ll, (args, chk, B, nb, ll, shapes)


def _core_tvl_bwd(spec, interpret, windowed, res, g):
    args, chk, B, nb, ll, shapes = res
    f32 = args[0].dtype
    N, Ms = spec.N, spec.state_dim
    T = args[6].shape[0]
    S, nC = _seg(T)
    D = Ms + Ms * Ms
    mats = tuple(float(m) for m in spec.maturities)

    g_lane = jnp.zeros((nb * TILE,), dtype=f32).at[:B].set(
        jnp.where(jnp.isfinite(ll), jnp.asarray(g, dtype=f32), 0.0))
    g_tile = g_lane.reshape(nb * _SUB, _LANE)

    def tile_spec(Drows):
        return pl.BlockSpec((Drows, _SUB, _LANE), lambda gidx: (0, gidx, 0),
                            memory_space=pltpu.VMEM)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    grads = pl.pallas_call(
        partial(_bwd_kernel_tvl, N, Ms, T, S, nC, windowed,
                spec.exact_jacobian, mats),
        grid=(nb,),
        in_specs=[tile_spec(Ms * Ms), tile_spec(Ms), tile_spec(Ms * Ms),
                  tile_spec(1), smem, smem, tile_spec(2), tile_spec(nC * D),
                  pl.BlockSpec((_SUB, _LANE), lambda gidx: (gidx, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=tuple(tile_spec(rows)
                        for rows in (Ms * Ms, Ms, Ms * Ms, 1, Ms, Ms * Ms)),
        out_shape=tuple(
            jax.ShapeDtypeStruct((rows, nb * _SUB, _LANE), f32)
            for rows in (Ms * Ms, Ms, Ms * Ms, 1, Ms, Ms * Ms)),
        scratch_shapes=[pltpu.VMEM((S * D, _SUB, _LANE), f32)],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(args[0], args[1], args[2], args[3], args[6], args[7], args[8], chk,
      g_tile)

    (psh, desh, osh, ovsh, b0sh, p0sh, datash, msh, wsh) = shapes
    return (_unlay(grads[0], B, psh[1:]), _unlay(grads[1], B, desh[1:]),
            _unlay(grads[2], B, osh[1:]), _unlay(grads[3], B, ovsh[1:]),
            _unlay(grads[4], B, b0sh[1:]), _unlay(grads[5], B, p0sh[1:]),
            jnp.zeros(datash, dtype=f32), jnp.zeros(msh, dtype=f32),
            jnp.zeros(wsh, dtype=f32))


_core_tvl.defvjp(_core_tvl_fwd, _core_tvl_bwd)


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------

def _unlay(flat, B, shape):
    """Inverse of pallas_kf._lay: (D, nb·8, 128) → (B, *shape)."""
    D = flat.shape[0]
    return flat.reshape(D, -1).T[:B].reshape((B,) + shape)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _core(spec, interpret, windowed, Z, d, Phi, delta, Om, ovar, beta0, P0,
          data, masks, win):
    out, _ = _core_fwd(spec, interpret, windowed, Z, d, Phi, delta, Om, ovar,
                       beta0, P0, data, masks, win)
    return out


def _call_fwd(spec, interpret, windowed, Z, d, Phi, delta, Om, ovar, beta0, P0,
              data, masks, win):
    f32 = Phi.dtype  # compute dtype (f32 on TPU; f64 allowed in interpret mode)
    B = Z.shape[0]
    nb = -(-B // TILE)
    N, Ms = spec.N, spec.state_dim
    T = data.shape[1]
    S, nC = _seg(T)
    D = Ms + Ms * Ms

    args = [_lay(Z.astype(f32), B, nb), _lay(d.astype(f32), B, nb),
            _lay(Phi.astype(f32), B, nb), _lay(delta.astype(f32), B, nb),
            _lay(Om.astype(f32), B, nb), _lay(ovar.astype(f32), B, nb),
            _lay(beta0.astype(f32), B, nb), _lay(P0.astype(f32), B, nb),
            jnp.asarray(data, dtype=f32).T, masks.astype(f32),
            _lay(win.astype(f32), B, nb)]

    def tile_spec(Drows):
        return pl.BlockSpec((Drows, _SUB, _LANE), lambda gidx: (0, gidx, 0),
                            memory_space=pltpu.VMEM)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    out, chk = pl.pallas_call(
        partial(_fwd_kernel, N, Ms, T, S, nC, windowed),
        grid=(nb,),
        in_specs=[tile_spec(N * Ms), tile_spec(N), tile_spec(Ms * Ms),
                  tile_spec(Ms), tile_spec(Ms * Ms), tile_spec(1),
                  tile_spec(Ms), tile_spec(Ms * Ms), smem, smem,
                  tile_spec(2)],
        out_specs=(pl.BlockSpec((_SUB, _LANE), lambda gidx: (gidx, 0),
                                memory_space=pltpu.VMEM),
                   tile_spec(nC * D)),
        out_shape=(jax.ShapeDtypeStruct((nb * _SUB, _LANE), f32),
                   jax.ShapeDtypeStruct((nC * D, nb * _SUB, _LANE), f32)),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    return out.reshape(-1)[:B], (args, chk, B, nb)


def _core_fwd(spec, interpret, windowed, Z, d, Phi, delta, Om, ovar, beta0, P0,
              data, masks, win):
    ll, (args, chk, B, nb) = _call_fwd(spec, interpret, windowed, Z, d, Phi,
                                       delta, Om, ovar, beta0, P0, data,
                                       masks, win)
    shapes = (Z.shape, d.shape, Phi.shape, delta.shape, Om.shape, ovar.shape,
              beta0.shape, P0.shape, data.shape, masks.shape, win.shape)
    return ll, (args, chk, B, nb, ll, shapes)


def _core_bwd(spec, interpret, windowed, res, g):
    args, chk, B, nb, ll, shapes = res
    f32 = args[2].dtype
    N, Ms = spec.N, spec.state_dim
    T = args[8].shape[0]
    S, nC = _seg(T)
    D = Ms + Ms * Ms

    # gate cotangent: where the forward hit the −Inf sentinel the loss is
    # where(finite, ll, −inf) whose ∂/∂ll is zero
    g_lane = jnp.zeros((nb * TILE,), dtype=f32).at[:B].set(
        jnp.where(jnp.isfinite(ll), jnp.asarray(g, dtype=f32), 0.0))
    g_tile = g_lane.reshape(nb * _SUB, _LANE)

    def tile_spec(Drows):
        return pl.BlockSpec((Drows, _SUB, _LANE), lambda gidx: (0, gidx, 0),
                            memory_space=pltpu.VMEM)

    out_tile = tile_spec
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    grads = pl.pallas_call(
        partial(_bwd_kernel, N, Ms, T, S, nC, windowed),
        grid=(nb,),
        in_specs=[tile_spec(N * Ms), tile_spec(N), tile_spec(Ms * Ms),
                  tile_spec(Ms), tile_spec(Ms * Ms), tile_spec(1),
                  smem, smem, tile_spec(2), tile_spec(nC * D),
                  pl.BlockSpec((_SUB, _LANE), lambda gidx: (gidx, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(out_tile(N * Ms), out_tile(N), out_tile(Ms * Ms),
                   out_tile(Ms), out_tile(Ms * Ms), out_tile(1),
                   out_tile(Ms), out_tile(Ms * Ms)),
        out_shape=tuple(
            jax.ShapeDtypeStruct((rows, nb * _SUB, _LANE), f32)
            for rows in (N * Ms, N, Ms * Ms, Ms, Ms * Ms, 1, Ms, Ms * Ms)),
        scratch_shapes=[pltpu.VMEM((S * D, _SUB, _LANE), f32)],
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(args[0], args[1], args[2], args[3], args[4], args[5], args[8], args[9],
      args[10], chk, g_tile)

    (zsh, dsh, psh, desh, osh, ovsh, b0sh, p0sh, datash, msh, wsh) = shapes
    gZ = _unlay(grads[0], B, zsh[1:])
    gd = _unlay(grads[1], B, dsh[1:])
    gPhi = _unlay(grads[2], B, psh[1:])
    gdelta = _unlay(grads[3], B, desh[1:])
    gOm = _unlay(grads[4], B, osh[1:])
    govar = _unlay(grads[5], B, ovsh[1:])
    gb0 = _unlay(grads[6], B, b0sh[1:])
    gP0 = _unlay(grads[7], B, p0sh[1:])
    return (gZ, gd, gPhi, gdelta, gOm, govar, gb0, gP0,
            jnp.zeros(datash, dtype=f32), jnp.zeros(msh, dtype=f32),
            jnp.zeros(wsh, dtype=f32))


_core.defvjp(_core_fwd, _core_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def batched_loglik_diff(spec: ModelSpec, params_batch, data, start=0, end=None,
                        interpret: bool | None = None, dtype=None,
                        starts=None, ends=None):
    """Differentiable fused-kernel loglik: (B, n_params) → (B,).

    ``jax.grad`` flows through the hand-derived adjoint kernel for the state-
    space tensors and through ordinary JAX AD for the parameter unpacking and
    loading construction.  All three Kalman families: constant-measurement
    DNS/AFNS take the hand-derived adjoint; the TVλ EKF takes the
    checkpointed per-step ``jax.vjp`` adjoint (its measurement rows are
    rebuilt from the state in-kernel, so there are no Z/d tensors to
    differentiate — the loading gradients flow into the state adjoint).
    ``dtype`` defaults to f32 (the TPU compute type); f64 is accepted in
    interpret mode for tight test comparisons against ``jax.grad`` of the
    algebraically identical ``univariate_kf.get_loss``.

    ``starts``/``ends``: optional (B,) per-draw estimation windows (see
    ``pallas_kf.batched_loglik``) — lets a whole rolling-window × multi-start
    batch share one differentiable program.  Scalar ``start``/``end`` are
    ignored when given.
    """
    if spec.family not in ("kalman_dns", "kalman_afns", "kalman_tvl"):
        raise ValueError(f"differentiable pallas kernel supports the kalman "
                         f"families, not {spec.family!r}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    f32 = jnp.float32 if dtype is None else jnp.dtype(dtype)
    params_batch = jnp.asarray(params_batch, dtype=f32)
    B = params_batch.shape[0]
    N = spec.N
    T = data.shape[1]
    if end is None:
        end = T

    tvl = spec.family == "kalman_tvl"

    def precompute(pb):
        kp = jax.vmap(partial(unpack_kalman, spec))(pb)
        state0 = jax.vmap(partial(init_state, spec))(kp)
        if tvl:  # Z/d are built in-kernel from the state
            return (kp.Phi, kp.delta, kp.Omega_state, kp.obs_var,
                    state0.beta, state0.P)
        Z, d = jax.vmap(lambda k: measurement_setup(spec, k, f32))(kp)
        if d is None:
            d = jnp.zeros((B, N), dtype=f32)
        return (Z, d, kp.Phi, kp.delta, kp.Omega_state, kp.obs_var,
                state0.beta, state0.P)

    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)
    contrib = loglik_contrib_mask(start, end, T)
    masks = jnp.stack([observed, contrib], axis=1).astype(f32)
    windowed = starts is not None
    win = window_array(starts, ends, B, f32)

    tensors = precompute(params_batch)
    core = _core_tvl if tvl else _core
    return core(spec, interpret, windowed, *tensors,
                jnp.asarray(data, dtype=f32), masks, win)
