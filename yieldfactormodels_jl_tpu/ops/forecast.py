"""Multi-step Gaussian predictive densities for the Kalman families.

The reference's forecasting pipeline produces POINT forecasts by filtering
NaN-padded panels (forecasting.jl:141 — reproduced by ``api.predict``).
The BASELINE north star names the "multi-step predictive density"; this
module supplies it analytically from the same filter: after the last
observed column the state predictive distribution iterates

    β_{T+k|T} = δ + Φ β_{T+k−1|T},     P_{T+k|T} = Φ P_{T+k−1|T} Φᵀ + Ω,

and each step's yield density is N(Z β + d,  Z P Zᵀ + σ² I) — for the TVλ
EKF the mean uses the exact nonlinear measurement h(β) and the covariance
its Jacobian linearization Z(β), the same linearization the filter uses.
One ``lax.scan`` over the horizon; engine-aware through
``univariate_kf.filter_moments`` (or the joint engine's moments).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.kalman import state_measurement
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax


def density_from_state(spec: ModelSpec, kp, beta, P, horizon: int):
    """The propagate-then-emit predictive-density scan, from a FILTERED state
    (β_{t|t}, P_{t|t}): step k emits the (k+1)-step-ahead yield density.  The
    single source of the density recursion, shared by ``forecast_density``
    (which filters to the origin first) and the online serving layer
    (``serving/batcher.py``), whose snapshots already hold the filtered state.
    No failure gating here — callers own the sentinel/poison policy."""
    dtype = kp.Phi.dtype
    mats = spec.maturities_array
    Z_const, d_const = K.measurement_setup(spec, kp, dtype)
    mfn = state_measurement(spec)
    if Z_const is not None and d_const is None:
        d_const = jnp.zeros((spec.N,), dtype=dtype)
    eyeN = jnp.eye(spec.N, dtype=dtype)

    def step(carry, _):
        b, Pm = carry
        b = kp.delta + kp.Phi @ b
        Pm = kp.Phi @ Pm @ kp.Phi.T + kp.Omega_state
        if mfn is not None:
            Z, y_mean = mfn(b, mats)
        else:
            Z = Z_const
            y_mean = Z @ b + d_const
        cov = Z @ Pm @ Z.T + kp.obs_var * eyeN
        return (b, Pm), (y_mean, cov, b, Pm)

    (_, _), (means, covs, sb, sP) = lax.scan(step, (beta, P), None,
                                             length=horizon)
    return {"means": means, "covs": covs, "state_means": sb, "state_covs": sP}


def density_fan(spec: ModelSpec, kp, beta, P, shifts, vol_scales,
                horizon: int):
    """Shock-axis batch of :func:`density_from_state`: for every scenario
    shock s the filtered state is displaced (β + ``shifts[s]``) and its
    covariance vol-scaled (P · ``vol_scales[s]²``), then the same
    propagate-then-emit recursion runs — so a whole stress fan (parallel
    shift, twist, vol regime) is ONE vmapped scan instead of S separate
    density programs.  ``shifts`` (S, Ms), ``vol_scales`` (S,); outputs gain
    a LEADING shock axis ((S, h, N) means etc — the per-cell (h, N[,N])
    blocks stay contiguous for host consumption).

    Unlike ``density_from_state`` this IS the sentinel boundary for the fan
    axis (DESIGN §11): a shock whose displaced start (β + shift, P·vs²) is
    non-finite, or whose recursion explodes, gets its whole fan row
    NaN-poisoned and a per-shock taxonomy code in ``codes`` (S,) int32 —
    never a silently propagated garbage density.  Finite rows are untouched,
    so one poisoned shock fails alone."""
    def one(sh, vs):
        b0 = beta + sh
        P0 = P * (vs * vs)
        out = density_from_state(spec, kp, b0, P0, horizon)
        start_ok = jnp.isfinite(b0).all() & jnp.isfinite(P0).all()
        code = (tax.bit(~jnp.isfinite(b0).all(), tax.NAN_STATE)
                | tax.bit(~jnp.isfinite(P0).all(), tax.NONPSD_COV)
                | tax.bit(start_ok & ~(jnp.isfinite(out["means"]).all()
                                       & jnp.isfinite(out["covs"]).all()),
                          tax.STATE_EXPLODED))
        bad = code != tax.OK
        nan = jnp.asarray(jnp.nan, dtype=kp.Phi.dtype)
        poisoned = {k: jnp.where(bad, nan, v) for k, v in out.items()}
        poisoned["codes"] = code
        return poisoned

    return jax.vmap(one)(shifts, vol_scales)


def forecast_density(spec: ModelSpec, params, data, horizon: int,
                     start=0, end=None, engine=None):
    """h-step-ahead predictive densities from the forecast ORIGIN ``end``.

    ``end`` (python int; default = T) is the origin: the filter conditions
    on columns ``start .. end−1`` ONLY (the panel is truncated there, so
    step k of the output is exactly the (k+1)-step-ahead density of column
    ``end−1+k+1`` — no silent transition-only drift through post-``end``
    columns).  Returns a dict of ``means`` (horizon, N), ``covs``
    (horizon, N, N) and the state path ``state_means`` (horizon, Ms) /
    ``state_covs`` (horizon, Ms, Ms).  A failed forward pass (−Inf filter
    ll) poisons the output with NaN, mirroring ``smooth``'s sentinel
    convention.

    ``engine``: "joint" or "univariate" forward moments (None reads
    ``config.kalman_engine()``) — same contract as ``api.smooth``
    (ops/smoother.forward_moments is the single shared dispatch).
    """
    if not spec.is_kalman:
        raise ValueError(
            f"forecast_density: analytic Gaussian predictive densities need "
            f"a Kalman family; {spec.family!r} has no predictive covariance "
            f"recursion (use api.predict for point forecasts)")
    from .smoother import forward_moments

    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    if end is None:
        end = T
    end = int(end)
    data = data[:, :end]  # the origin: condition on start..end-1 only
    params = jnp.asarray(params, dtype=spec.dtype)
    kp, outs = forward_moments(spec, params, data, start, end, engine)
    dens = density_from_state(spec, kp, outs["beta_upd"][-1],
                              outs["P_upd"][-1], horizon)
    ok = jnp.all(outs["ll"] > -jnp.inf)
    nan = jnp.asarray(jnp.nan, dtype=params.dtype)
    return {k: jnp.where(ok, v, nan) for k, v in dens.items()}
