"""Rao-Blackwellized particle filter for AFNS with stochastic-volatility
measurement errors (BASELINE.md config 3 — a capability beyond the reference).

Model extension of the Kalman families:

    y_t = Z x_t + α + ε_t,   ε_t ~ N(0, σ² e^{h_t} I_N)
    h_t = φ_h h_{t-1} + σ_h η_t                     (log-vol AR(1), h₀ = 0)
    x_t as in the linear state space (Φ, δ, Ω_state)

Conditional on the volatility path h the model is linear-Gaussian, so the
particle filter only samples h (1-dim!) and runs an exact Kalman step per
particle — the marginalized ("Rao-Blackwellized") design, which keeps 1,000
draws cheap and low-variance.  Everything is one `lax.scan` over time with the
particle axis vmapped inside each step; systematic resampling keeps the whole
kernel jittable (sorting-free, fixed shapes).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.afns import afns_loadings, yield_adjustment
from ..models.loadings import dns_loadings
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec

_LOG_2PI = math.log(2.0 * math.pi)


class PFState(NamedTuple):
    beta: jnp.ndarray   # (P, Ms) per-particle predicted state
    P: jnp.ndarray      # (P, Ms, Ms)
    h: jnp.ndarray      # (P,) log-vol
    logw: jnp.ndarray   # (P,) normalized log-weights (logsumexp == 0)
    key: jnp.ndarray


def _measurement(spec: ModelSpec, kp):
    mats = spec.maturities_array
    if spec.family == "kalman_afns":
        Z = afns_loadings(kp.gamma, mats, spec.M)
        d = yield_adjustment(kp.gamma, kp.Omega_state, mats, spec.M)
    else:
        Z = dns_loadings(kp.gamma, mats)
        d = jnp.zeros((spec.N,), dtype=Z.dtype)
    return Z, d


def _systematic_resample(key, weights, n):
    """Systematic resampling: fixed-shape, O(P), jit-safe."""
    positions = (jnp.arange(n) + jax.random.uniform(key)) / n
    cum = jnp.cumsum(weights)
    return jnp.searchsorted(cum, positions)


def _kf_particle_step(Z, d, Phi, delta, Omega_state, beta, P, y, r, obs):
    """Measurement+propagate Kalman step for ALL particles at once.

    ``beta (Pn, Ms)``, ``P (Pn, Ms, Ms)``, ``r (Pn,)`` the per-particle scalar
    observation variance σ²e^{h}.  Because Ω_obs = r·I is diagonal, the update
    runs as N sequential *scalar* innovations (the same univariate
    decomposition as ops/univariate_kf.py) — rank-1 FMAs over the particle
    axis, no per-particle N×N Cholesky.  Algebraically identical posterior and
    log-likelihood; a non-PD innovation variance yields −Inf for that particle
    (which logsumexp then zero-weights) instead of the silently-garbled value
    the factored form would produce."""
    N = Z.shape[0]
    ll = jnp.zeros(r.shape, dtype=P.dtype)
    ok = jnp.ones(r.shape, dtype=bool)
    b_u, P_u = beta, P
    for i in range(N):  # N is static; unrolled rank-1 updates
        z = Z[i]
        zP = P_u @ z                                  # (Pn, Ms)
        f = zP @ z + r                                # (Pn,)
        ok = ok & (f > 0) & jnp.isfinite(f)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y[i] - d[i] - b_u @ z                     # (Pn,)
        Kg = zP / fsafe[:, None]
        b_u = b_u + Kg * v[:, None]
        P_u = P_u - Kg[:, :, None] * zP[:, None, :]
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
    P_u = 0.5 * (P_u + jnp.swapaxes(P_u, -1, -2))     # symmetry insurance
    beta_m = beta + (b_u - beta) * obs
    P_m = P + (P_u - P) * obs
    beta_next = delta[None, :] + beta_m @ Phi.T
    P_next = jnp.einsum("ij,pjk,lk->pil", Phi, P_m, Phi) + Omega_state[None]
    return beta_next, P_next, jnp.where(ok, ll, -jnp.inf)


def particle_filter_loglik(
    spec: ModelSpec,
    params,
    data,
    key,
    n_particles: int = 1000,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    ess_threshold: float = 0.5,
):
    """Marginal log-likelihood estimate under SV measurement errors.

    Matches the reference's loglik conventions (skip the first innovation,
    recursion over t = 1..T−1 — kalman/filter.jl:190-195).  With
    ``sv_sigma → 0`` the estimate collapses to the exact Kalman loglik.
    Fully jittable; vmap over ``params`` for 1,000-draw MLE sweeps.
    """
    kp = unpack_kalman(spec, params)
    Z, d = _measurement(spec, kp)
    state0 = K.init_state(spec, kp)
    Pn = n_particles
    beta0 = jnp.broadcast_to(state0.beta, (Pn,) + state0.beta.shape)
    P0 = jnp.broadcast_to(state0.P, (Pn,) + state0.P.shape)
    h0 = jnp.zeros((Pn,), dtype=params.dtype)

    T = data.shape[1]
    log_uniform = -jnp.log(jnp.asarray(float(Pn), dtype=params.dtype))

    def body(st: PFState, inp):
        y, t_idx = inp
        key, k_prop, k_res = jax.random.split(st.key, 3)
        h_new = sv_phi * st.h + sv_sigma * jax.random.normal(k_prop, (Pn,), dtype=st.h.dtype)
        obs = jnp.all(jnp.isfinite(y))
        ysafe = jnp.where(jnp.isfinite(y), y, 0.0)
        r = kp.obs_var * jnp.exp(h_new)
        beta, P, ll = _kf_particle_step(Z, d, kp.Phi, kp.delta, kp.Omega_state,
                                        st.beta, st.P, ysafe, r,
                                        obs.astype(st.h.dtype))
        contributes = obs & (t_idx > 0)  # reference skips t == 1 (1-based)
        # accumulate onto the carried normalized log-weights: the step's
        # likelihood contribution is log Σ_i W_{t-1,i} exp(ll_i)
        logw_new = st.logw + jnp.where(contributes, ll, 0.0)
        step_ll = jax.scipy.special.logsumexp(logw_new)
        logw_norm = logw_new - step_ll
        step_ll = jnp.where(contributes, step_ll, 0.0)
        wn = jnp.exp(logw_norm)
        ess = 1.0 / jnp.sum(wn * wn)
        idx = _systematic_resample(k_res, wn, Pn)
        do_resample = contributes & (ess < ess_threshold * Pn)
        beta = jnp.where(do_resample, beta[idx], beta)
        P = jnp.where(do_resample, P[idx], P)
        h_new = jnp.where(do_resample, h_new[idx], h_new)
        logw_out = jnp.where(do_resample,
                             jnp.full_like(logw_norm, log_uniform), logw_norm)
        return PFState(beta, P, h_new, logw_out, key), step_ll

    t_idx = jnp.arange(T - 1)
    logw0 = jnp.full((Pn,), log_uniform, dtype=params.dtype)
    _, lls = lax.scan(body, PFState(beta0, P0, h0, logw0, key), (data.T[:-1], t_idx))
    total = jnp.sum(lls)
    return jnp.where(jnp.isfinite(total), total, -jnp.inf)
