"""Rao-Blackwellized particle filter for AFNS with stochastic-volatility
measurement errors (BASELINE.md config 3 — a capability beyond the reference).

Model extension of the Kalman families:

    y_t = Z x_t + α + ε_t,   ε_t ~ N(0, σ² e^{h_t} I_N)
    h_t = φ_h h_{t-1} + σ_h η_t                     (log-vol AR(1), h₀ = 0)
    x_t as in the linear state space (Φ, δ, Ω_state)

Conditional on the volatility path h the model is linear-Gaussian, so the
particle filter only samples h (1-dim!) and runs an exact Kalman step per
particle — the marginalized ("Rao-Blackwellized") design, which keeps 1,000
draws cheap and low-variance.  Everything is one `lax.scan` over time with the
particle axis vmapped inside each step; systematic resampling keeps the whole
kernel jittable (sorting-free, fixed shapes).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.afns import afns_loadings, yield_adjustment
from ..models.loadings import dns_loadings
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax

_LOG_2PI = math.log(2.0 * math.pi)


class PFState(NamedTuple):
    beta: jnp.ndarray   # (Ms, P) per-particle predicted state
    S: jnp.ndarray      # (Ms, Ms, P) lower square-root factor, P_cov = S Sᵀ
    h: jnp.ndarray      # (P,) log-vol
    logw: jnp.ndarray   # (P,) normalized log-weights (logsumexp == 0)
    key: jnp.ndarray


def _measurement(spec: ModelSpec, kp, dtype):
    """Loadings + intercept cast to the spec dtype — like kalman.
    measurement_setup; under jax_enable_x64 the quadrature inside
    yield_adjustment otherwise emits f64 into an f32 scan carry."""
    mats = spec.maturities_array
    prog = getattr(spec, "program", None)
    if prog is not None:
        if prog.measurement is not None:
            raise ValueError(
                "the SV particle filter marginalizes a LINEAR state space; "
                f"program {prog.name!r} has a state-dependent measurement")
        Z = prog.loadings(kp.gamma, mats)
        if prog.intercept is None:
            return Z.astype(dtype), jnp.zeros((spec.N,), dtype=dtype)
        d = prog.intercept(kp.gamma, kp.Omega_state, mats)
        return Z.astype(dtype), d.astype(dtype)
    if spec.family == "kalman_afns":
        Z = afns_loadings(kp.gamma, mats, spec.M)
        d = yield_adjustment(kp.gamma, kp.Omega_state, mats, spec.M)
        return Z.astype(dtype), d.astype(dtype)
    Z = dns_loadings(kp.gamma, mats)
    return Z.astype(dtype), jnp.zeros((spec.N,), dtype=dtype)


def factored_init(spec: ModelSpec, kp, dtype):
    """Initial state + factored covariances with the engine's jitter/fallback
    arithmetic — the ONE copy shared by the XLA engine below and the Pallas
    kernel's parameter packing (ops/pallas_pf._pack_params), so the
    elementwise common-noise parity contract between them cannot drift.
    Returns ``(state0, S0, chol_Om, fac_ok)``; a failed factorization is the
    draw-level −Inf sentinel (sqrt_kf.get_loss conventions)."""
    Ms = spec.state_dim
    state0 = K.init_state(spec, kp)
    P0s = 0.5 * (state0.P + state0.P.T) + 1e-9 * jnp.eye(Ms, dtype=dtype)
    S0 = jnp.linalg.cholesky(P0s)
    Om = 0.5 * (kp.Omega_state + kp.Omega_state.T) \
        + 1e-12 * jnp.eye(Ms, dtype=dtype)
    chol_Om = jnp.linalg.cholesky(Om)
    fac_ok = jnp.all(jnp.isfinite(S0)) & jnp.all(jnp.isfinite(chol_Om))
    S0 = jnp.where(jnp.isfinite(S0), S0, jnp.eye(Ms, dtype=dtype) * 1e-3)
    chol_Om = jnp.where(jnp.isfinite(chol_Om), chol_Om,
                        jnp.zeros_like(chol_Om))
    return state0, S0, chol_Om, fac_ok


def _systematic_resample(u, weights, n):
    """Systematic resampling from a single uniform offset ``u`` ∈ [0, 1):
    fixed-shape, O(P), jit-safe."""
    positions = (jnp.arange(n) + u) / n
    cum = jnp.cumsum(weights)
    return jnp.searchsorted(cum, positions)


def _propagate_cholesky(A, Om, Ms: int, floor: float = 1e-12):
    """Unrolled Cholesky–Banachiewicz of P = A Aᵀ + Ω for (Ms, Ms, particles)
    factors — pure elementwise VPU arithmetic over the trailing particle axis
    (no LAPACK batching, no data-dependent control flow).  The matrix dims
    LEAD so the big particle axis stays on the TPU lane dimension (a
    (P, 5, 5) layout leaves 123 of 128 lanes idle), and each needed entry of
    P is formed on demand as a K-term sum of (particles,) products — never as
    the (Ms, Ms, Ms, particles) broadcast a materialized A Aᵀ would cost.
    Diagonal pivots are floored so a rounding-level indefiniteness cannot
    emit NaN; inputs here are PSD-by-construction (S Sᵀ products plus a PD
    Ω), so the floor only ever absorbs last-ulp noise."""
    def P(i, j):
        s = Om[i, j]
        for k in range(Ms):
            s = s + A[i, k] * A[j, k]
        return s

    L = [[None] * Ms for _ in range(Ms)]
    for i in range(Ms):
        for j in range(i + 1):
            s = P(i, j)
            for k in range(j):
                s = s - L[i][k] * L[j][k]
            if i == j:
                L[i][i] = jnp.sqrt(jnp.maximum(s, floor))
            else:
                L[i][j] = s / L[j][j]
    zero = jnp.zeros_like(A[0, 0])
    rows = [jnp.stack([L[i][j] if j <= i else zero for j in range(Ms)], axis=0)
            for i in range(Ms)]
    return jnp.stack(rows, axis=0)


def _kf_particle_step(Z, d, Phi, delta, chol_Om, beta, S, y, r, obs):
    """Square-root measurement+propagate Kalman step for ALL particles.

    ``beta (Ms, Pn)``, ``S (Ms, Ms, Pn)`` the lower factor of the predicted
    covariance (P = S Sᵀ), ``r (Pn,)`` the per-particle scalar observation
    variance σ²e^{h}.  Because Ω_obs = r·I is diagonal, the update runs as N
    sequential *scalar* Potter square-root updates (the univariate
    decomposition of ops/sqrt_kf._potter_update, vectorized across the
    particle axis): φ = Sᵀz, f = φᵀφ + r, so the innovation variance is a sum
    of squares plus r — **strictly positive by construction**, which is what
    keeps every particle's likelihood finite in f32 where the plain
    P-propagating form loses ~18% of draws to rank-1 downdate drift
    (VERDICT round 1, item 3).  The time update re-factors
    Φ S_m (Φ S_m)ᵀ + Ω with an unrolled elementwise Cholesky.

    Layout: the particle axis is LAST everywhere so it rides the 128-wide TPU
    lane dimension; the Ms-sized contractions are written as broadcast
    multiplies + leading-axis sums (pure elementwise VPU work), never as
    dot_generals over a 5-long axis."""
    sqrt_r = jnp.sqrt(jnp.maximum(r, 0.0))

    def obs_update(carry, zy):
        b_u, S_u, ll, ok = carry
        z, y_i, d_i = zy                              # z (Ms,)
        phi = jnp.sum(S_u * z[:, None, None], axis=0)  # Sᵀz → (Ms, Pn)
        f = jnp.sum(phi * phi, axis=0) + r            # (Pn,) > 0 when r > 0
        fsafe = jnp.where(f > 0, f, 1.0)
        # f ≤ 0 is reachable only from invalid inputs (σ² < 0 passed directly
        # in constrained space); kill the draw like the Kalman engines do
        # rather than silently filtering with fsafe = 1
        ok = ok & jnp.isfinite(f) & (f > 0)
        v = y_i - d_i - jnp.sum(b_u * z[:, None], axis=0)   # (Pn,)
        Sphi = jnp.sum(S_u * phi[None, :, :], axis=1)       # = P z → (Ms, Pn)
        b_u = b_u + Sphi * (v / fsafe)[None, :]
        alpha = 1.0 / (fsafe + sqrt_r * jnp.sqrt(fsafe))
        S_u = S_u - alpha[None, None, :] * (Sphi[:, None, :] * phi[None, :, :])
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b_u, S_u, ll, ok), None

    # scan (not unroll) over the N observations: 20x smaller XLA graph, which
    # keeps device compile times sane inside the outer T-step scan
    (b_u, S_u, ll, ok), _ = jax.lax.scan(
        obs_update,
        (beta, S, jnp.zeros(r.shape, dtype=S.dtype), jnp.isfinite(r)),
        (Z, y, d))
    beta_m = beta + (b_u - beta) * obs
    S_m = S + (S_u - S) * obs
    beta_next = delta[:, None] + jnp.sum(Phi[:, :, None] * beta_m[None, :, :],
                                         axis=1)
    # A = Φ S_m entry-by-entry: Ms³ scalar×(Pn,) multiply-adds, never the
    # (Ms, Ms, Ms, Pn) broadcast a materialized product would cost
    Ms = Phi.shape[0]
    A = jnp.stack([
        jnp.stack([sum(Phi[i, j] * S_m[j, k] for j in range(Ms))
                   for k in range(Ms)], axis=0)
        for i in range(Ms)], axis=0)
    S_next = _propagate_cholesky(A, chol_Om @ chol_Om.T, Ms)
    return beta_next, S_next, jnp.where(ok, ll, -jnp.inf)


def particle_filter_loglik(
    spec: ModelSpec,
    params,
    data,
    key=None,
    n_particles: int = 1000,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    ess_threshold: float = 0.5,
    noise=None,
    with_code: bool = False,
):
    """Marginal log-likelihood estimate under SV measurement errors.

    Matches the reference's loglik conventions (skip the first innovation,
    recursion over t = 1..T−1 — kalman/filter.jl:190-195).  With
    ``sv_sigma → 0`` the estimate collapses to the exact Kalman loglik.
    Fully jittable; vmap over ``params`` for 1,000-draw MLE sweeps.

    ``noise``: optional ``(normals, uniforms)`` with shapes ``(T-1,
    n_particles)`` / ``(T-1,)`` — the common-noise mode.  The filter then
    consumes exactly these draws (normals drive the log-vol proposal,
    uniforms the systematic-resampling offset) instead of splitting ``key``,
    so two engines fed the same arrays follow the same particle trajectories:
    this is the deterministic contract the Pallas kernel
    (``ops/pallas_pf.py``) is parity-tested against, and what common-random-
    number estimation drivers pass.

    ``with_code=True`` additionally returns the taxonomy bitmask
    (robustness/taxonomy.py) beside the loss — the loss value itself is
    unchanged, and the default single-return signature is preserved for
    every existing caller.
    """
    kp = unpack_kalman(spec, params)
    Pn = n_particles
    Ms = spec.state_dim
    dtype = params.dtype
    Z, d = _measurement(spec, kp, dtype)
    state0, S0, chol_Om, fac_ok = factored_init(spec, kp, dtype)
    beta0 = jnp.broadcast_to(state0.beta[:, None], (Ms, Pn))
    S0b = jnp.broadcast_to(S0[:, :, None], (Ms, Ms, Pn))
    h0 = jnp.zeros((Pn,), dtype=dtype)

    T = data.shape[1]
    log_uniform = -jnp.log(jnp.asarray(float(Pn), dtype=params.dtype))

    def body(st: PFState, inp):
        if noise is None:
            y, t_idx = inp
            key, k_prop, k_res = jax.random.split(st.key, 3)
            z_row = jax.random.normal(k_prop, (Pn,), dtype=st.h.dtype)
            u_res = jax.random.uniform(k_res)
        else:
            y, t_idx, z_row, u_res = inp
            key = st.key
        h_new = sv_phi * st.h + sv_sigma * z_row
        obs = jnp.all(jnp.isfinite(y))
        ysafe = jnp.where(jnp.isfinite(y), y, 0.0)
        r = kp.obs_var * jnp.exp(h_new)
        beta, S, ll = _kf_particle_step(Z, d, kp.Phi, kp.delta, chol_Om,
                                        st.beta, st.S, ysafe, r,
                                        obs.astype(st.h.dtype))
        contributes = obs & (t_idx > 0)  # reference skips t == 1 (1-based)
        # accumulate onto the carried normalized log-weights: the step's
        # likelihood contribution is log Σ_i W_{t-1,i} exp(ll_i)
        logw_new = st.logw + jnp.where(contributes, ll, 0.0)
        step_ll = jax.scipy.special.logsumexp(logw_new)
        logw_norm = logw_new - step_ll
        step_ll = jnp.where(contributes, step_ll, 0.0)
        wn = jnp.exp(logw_norm)
        ess = 1.0 / jnp.sum(wn * wn)
        idx = _systematic_resample(u_res, wn, Pn)
        do_resample = contributes & (ess < ess_threshold * Pn)
        beta = jnp.where(do_resample, beta[:, idx], beta)
        S = jnp.where(do_resample, S[:, :, idx], S)
        h_new = jnp.where(do_resample, h_new[idx], h_new)
        logw_out = jnp.where(do_resample,
                             jnp.full_like(logw_norm, log_uniform), logw_norm)
        # taxonomy channel beside the −Inf sentinel: a contributing step whose
        # mixture weight collapsed (every draw's Kalman step died — non-PD
        # innovation under an invalid σ², or an overflowed e^h) — decoded
        # only at the driver (robustness/taxonomy.py)
        dead = contributes & ~jnp.isfinite(step_ll)
        return PFState(beta, S, h_new, logw_out, key), (step_ll, dead)

    t_idx = jnp.arange(T - 1)
    logw0 = jnp.full((Pn,), log_uniform, dtype=params.dtype)
    if noise is None:
        if key is None:
            raise ValueError("particle_filter_loglik needs a PRNG key or "
                             "a (normals, uniforms) noise pair")
        xs = (data.T[:-1], t_idx)
    else:
        normals, uniforms = noise
        if normals.shape != (T - 1, Pn) or uniforms.shape != (T - 1,):
            raise ValueError(
                f"common-noise shapes must be ({T - 1}, {Pn}) / ({T - 1},); "
                f"got {normals.shape} / {uniforms.shape}")
        key = jax.random.PRNGKey(0) if key is None else key  # unused carry
        xs = (data.T[:-1], t_idx, normals.astype(dtype), uniforms.astype(dtype))
    _, (lls, dead) = lax.scan(body, PFState(beta0, S0b, h0, logw0, key), xs)
    total = jnp.sum(lls)
    loss = jnp.where(fac_ok & jnp.isfinite(total), total, -jnp.inf)
    code = tax.params_code(params) \
        | tax.bit(~fac_ok, tax.CHOL_BREAKDOWN) \
        | tax.bit(jnp.any(dead), tax.NONPSD_INNOVATION)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    if with_code:
        return loss, code
    return loss


def draw_noise(T: int, n_particles: int, key, dtype):
    """The shared CRN noise pair for a draw sweep: ``(normals (T-1, Pn),
    uniforms (T-1,))`` from one key split — THE derivation
    ``draw_loglik_core`` consumes, exposed so parity tests and external
    callers can reproduce the exact streams."""
    kz, ku = jax.random.split(jnp.asarray(key))
    return (jax.random.normal(kz, (T - 1, n_particles), dtype=dtype),
            jax.random.uniform(ku, (T - 1,), dtype=dtype))


def draw_loglik_core(spec: ModelSpec, n_particles: int, sv_phi: float,
                     sv_sigma: float):
    """Batch plumbing for the SV-draw lattice axis: a PLAIN callable
    ``(draws (D, P), data (N, T), key) -> (D,)`` vmapping the filter over
    the draw axis on ONE shared common-noise pair (``draw_noise``): the
    log-vol proposals and resampling offsets are generated ONCE and reused
    by every draw — the streamed-noise CRN contract of the fused
    ``estimate_sv`` objective (``ops/pallas_pf``), which both pins the
    fixed-surface property (the sweep is deterministic in the parameters)
    and deletes the per-draw RNG recomputation a key-splitting vmap would
    pay D times.  A different (but equally valid) noise realization than
    the key-splitting scan search, same as the Pallas path (see
    ``estimate_sv``'s docstring).  Un-jitted on purpose:
    ``estimation/sv.pf_draw_logliks`` jits it for standalone sweeps and the
    fused scenario lattice (estimation/scenario.py) inlines it into ITS
    program.  The per-draw filters keep the particle axis on the lane
    dimension (module docstring); the draw axis vmaps outside them."""
    def batch(draws, data, key):
        noise = draw_noise(data.shape[1], n_particles, key, data.dtype)
        return jax.vmap(
            lambda p: particle_filter_loglik(
                spec, p, data, noise=noise, n_particles=n_particles,
                sv_phi=sv_phi, sv_sigma=sv_sigma))(draws)

    return batch
