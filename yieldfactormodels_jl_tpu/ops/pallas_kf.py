"""Pallas TPU kernel: fused batched univariate Kalman log-likelihood.

This is the hand-scheduled version of ``ops/univariate_kf.get_loss`` for the
Kalman families — constant-measurement (``kalman_dns``, ``kalman_afns``) and
the TVλ EKF, whose state-dependent loading row is recomputed lane-locally
inside the kernel — the SURVEY.md §7 stretch goal ("Pallas kernel for the
fused filter step").  The
XLA path is already fast; what Pallas adds is *layout control*: the batch axis
is laid out across the full (8 sublanes × 128 lanes) VPU tile, and every
per-draw quantity (Z, Φ, δ, Ω, β, P) lives in VMEM as a stack of such tiles,
so the whole T-step recursion runs register-resident elementwise arithmetic
with zero HBM traffic between steps and no cross-lane shuffles at all:

  - batch draw  b  ↔  (sublane, lane) position — 1024 draws per grid program,
  - state/obs dims (Ms ≤ 5, N ≈ 20) are unrolled Python loops over tiles,
  - the shared data panel (T × N) and the window masks sit in SMEM and are
    read as scalars by the scalar core while the VPU does the tile math.

Semantics are identical to ``univariate_kf.get_loss`` (same windows / NaN /
−Inf conventions, same symmetrization): the test suite checks agreement in
interpret mode, and ``bench.py`` cross-checks on hardware.

The kernel is evaluation-only (no custom VJP): it serves the value-only bulk
paths — A/B-grid initialization search, bootstrap/draw evaluation, model
selection — while gradient-based MLE keeps the ``lax.scan`` kernels that JAX
differentiates.  (The reference has no analogue; its every loss call is a
sequential per-step CPU loop, /root/reference/src/models/kalman/filter.jl.)
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.kalman import (init_state, loglik_contrib_mask,
                             measurement_setup, tvl_dz2_dlam)
from ..models.loadings import LAMBDA_FLOOR as _FLOOR, dns_slope_curvature
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec

# jax ≥ 0.6 renamed pltpu.TPUCompilerParams → pltpu.CompilerParams; resolve
# whichever this install has (shared by every Pallas kernel module here)
CompilerParams = getattr(pltpu, "CompilerParams",
                         getattr(pltpu, "TPUCompilerParams", None))

_LOG_2PI = math.log(2.0 * math.pi)

_SUB, _LANE = 8, 128
TILE = _SUB * _LANE  # draws per grid program


def tvl_rows(beta, mats, exact):
    """TVλ measurement rows from the predicted state — the single source of
    truth shared by the value kernel here and the adjoint kernels
    (pallas_kf_grad, which differentiate THROUGH this build with jax.vjp).

    Returns per maturity ``((1, z2, z3, jac), jb)`` where ``jac`` is the EKF
    Jacobian column (kalman/filter.jl:38-46, quirk behind ``exact``) and
    ``jb = jac·β₄`` is the fixed-linearization y_eff offset
    (ops/univariate_kf.py derivation).  All tiles are derived from ``beta``
    so Mosaic never sees a replicated-constant layout.
    """
    lam = _FLOOR + jnp.exp(beta[3])
    dlam = lam - _FLOOR
    one = beta[3] * 0.0 + 1.0
    rows = []
    for tau in mats:  # static python floats
        z2, z3 = dns_slope_curvature(lam, tau)
        ztau = z2 - z3  # e^{-λτ} via the DNS identity Z₃ = Z₂ − e^{-λτ}
        dz2 = tvl_dz2_dlam(lam, ztau, tau, exact)
        jac = ((beta[1] + beta[2]) * dz2 + beta[2] * tau * ztau) * dlam
        rows.append(((one, z2, z3, jac), jac * beta[3]))
    return rows


def window_masks(windowed, f32, maskr, winr, t):
    """Per-step (in-window, loglik-contributing) masks — the single source of
    truth shared by the value kernel and the adjoint kernels (pallas_kf_grad):
    scalar SMEM rows for a shared window, or per-lane tiles computed from the
    loop index when each draw carries its own [start, end).  The contributing
    convention start+1 .. end−2 mirrors models.kalman.loglik_contrib_mask."""
    if windowed:
        ts = jnp.asarray(t, dtype=f32)
        w_lo, w_hi = winr[0], winr[1]
        return (ts >= w_lo) & (ts < w_hi), (ts >= w_lo + 1) & (ts <= w_hi - 2)
    return maskr[t, 0] > 0.5, maskr[t, 1] > 0.5


def window_array(starts, ends, B, f32):
    """(B, 2) per-draw [start, end) tile input; zeros when not windowed."""
    if starts is None:
        return jnp.zeros((B, 2), dtype=f32)
    return jnp.stack([jnp.asarray(starts, dtype=f32).reshape(B),
                      jnp.asarray(ends, dtype=f32).reshape(B)], axis=1)


def _kernel(N: int, Ms: int, T: int, tvl: bool, exact_jac: bool,
            windowed: bool, mats, rows,
            Zr, dr, phir, deltar, omr, ovarr, b0r, p0r, datar, maskr, winr,
            outr):
    """One grid program = TILE draws.  Tile-stacked refs, scalar data/masks.

    ``tvl`` switches to the EKF for the TVλ family: the loading row z_i is
    recomputed per step from the lane-local predicted state (λ = 1e-2 +
    e^{β₄}, Jacobian column as kalman/filter.jl:38-46), and the fixed-
    linearization effective observation y_eff = y + jac·β₄ replaces y
    (ops/univariate_kf.py derivation).  ``mats`` are the static maturities.

    ``windowed``: per-LANE estimation windows — ``winr`` holds (start, end)
    tiles and the in-window/contributing masks are computed per draw from the
    loop index, so a whole batch of rolling-window origins (each its own
    [start, end)) runs as one fused program.  Otherwise the shared scalar
    masks in SMEM apply to every lane.
    """
    f32 = phir.dtype
    ovar = ovarr[0]

    beta0 = tuple(b0r[m] for m in range(Ms))
    P0 = tuple(p0r[k] for k in range(Ms * Ms))
    ll0 = jnp.zeros((rows, _LANE), dtype=f32)

    def step(t, carry):
        beta, P, ll = carry

        obs_s, con_s = window_masks(windowed, f32, maskr, winr, t)

        if tvl:  # lane-local rows + y_eff offsets from β_pred (shared build)
            trows = tvl_rows(beta, mats, exact_jac)

        # ---- N sequential scalar measurement updates (rank-1, lane-local) --
        b = list(beta)
        Pm = list(P)
        ll_step = jnp.zeros((rows, _LANE), dtype=f32)
        ok = jnp.ones((rows, _LANE), dtype=jnp.bool_)
        finite_s = True
        for i in range(N):
            y_i = datar[t, i]
            fin_i = jnp.isfinite(y_i)
            finite_s = jnp.logical_and(finite_s, fin_i)
            if tvl:
                z, jb = trows[i]
                # y_eff = y − h(β_pred) + z·β_pred = y + jac·β₄_pred
                y_eff = y_i + jb
                d_i = jnp.zeros((), f32)
            else:
                z = tuple(Zr[i * Ms + m] for m in range(Ms))
                y_eff = y_i
                d_i = dr[i]
            zP = [sum(z[k] * Pm[k * Ms + m] for k in range(Ms)) for m in range(Ms)]
            f = sum(zP[m] * z[m] for m in range(Ms)) + ovar
            ok = ok & (f > 0) & jnp.isfinite(f)
            fsafe = jnp.where(f > 0, f, jnp.ones((), f32))
            pred = sum(z[m] * b[m] for m in range(Ms)) + d_i
            # NaN y_i ⇒ whole column is treated missing (blended out below);
            # a zero innovation keeps the discarded arithmetic finite.
            v = jnp.where(fin_i, y_eff - pred, jnp.zeros_like(pred))
            K = [zP[m] / fsafe for m in range(Ms)]
            b = [b[m] + K[m] * v for m in range(Ms)]
            Pm = [Pm[k * Ms + m] - K[k] * zP[m]
                  for k in range(Ms) for m in range(Ms)]
            ll_step = ll_step - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)

        # symmetrize (univariate_kf.py drift insurance)
        Pm = [0.5 * (Pm[k * Ms + m] + Pm[m * Ms + k])
              for k in range(Ms) for m in range(Ms)]

        # ---- blend update vs predict-only, then propagate -----------------
        obs = jnp.logical_and(obs_s, finite_s)  # scalar
        b = [jnp.where(obs, b[m], beta[m]) for m in range(Ms)]
        Pm = [jnp.where(obs, Pm[k], P[k]) for k in range(Ms * Ms)]

        beta_next = tuple(
            deltar[m] + sum(phir[m * Ms + k] * b[k] for k in range(Ms))
            for m in range(Ms))
        PA = [sum(phir[m * Ms + k] * Pm[k * Ms + n] for k in range(Ms))
              for m in range(Ms) for n in range(Ms)]
        P_next = tuple(
            omr[m * Ms + n]
            + sum(PA[m * Ms + k] * phir[n * Ms + k] for k in range(Ms))
            for m in range(Ms) for n in range(Ms))

        neg_inf = jnp.full((rows, _LANE), -jnp.inf, dtype=f32)
        zero = jnp.zeros((rows, _LANE), dtype=f32)
        ll_t = jnp.where(jnp.logical_and(obs, con_s),
                         jnp.where(ok, ll_step, neg_inf), zero)
        return beta_next, P_next, ll + ll_t

    _, _, ll = jax.lax.fori_loop(0, T, step, (beta0, P0, ll0))
    outr[...] = jnp.where(jnp.isfinite(ll), ll, -jnp.inf)


def _lay(x, B, nb, rows=_SUB):
    """(B, ...) draw-major → (D, nb·rows, 128) tile-stacked, edge-padded."""
    D = int(x.size) // B
    x2 = x.reshape(B, D).T
    pad = nb * rows * _LANE - B
    if pad:
        x2 = jnp.concatenate([x2, jnp.broadcast_to(x2[:, -1:], (D, pad))], axis=1)
    return x2.reshape(D, nb * rows, _LANE)


def batched_loglik(spec: ModelSpec, params_batch, data, start=0, end=None,
                   interpret: bool | None = None, starts=None, ends=None,
                   tile_rows: int = _SUB):
    """Gaussian loglik for a batch of parameter draws — Pallas fused kernel.

    Numerically equivalent to ``vmap(univariate_kf.get_loss)`` for every
    Kalman family (constant-measurement DNS/AFNS and the TVλ EKF, whose
    loading row is recomputed in-kernel).  ``interpret`` defaults to True off
    TPU so tests run on CPU; on TPU the kernel compiles to Mosaic.

    ``starts``/``ends``: optional (B,) per-draw estimation windows — each draw
    gets its own [start, end) mask computed in-kernel, so a whole batch of
    rolling-window origins runs as one fused program (the reference's
    per-origin process farm, forecasting.jl:120-199, collapsed into one
    launch).  When given, the scalar ``start``/``end`` are ignored.

    ``tile_rows``: sublane rows per grid program (multiple of 8).  The
    recursion is serially dependent along T and the observation chain, so the
    kernel is latency-bound; wider tiles (16/32) give each vector op 2–4
    independent vregs of work to pipeline through the same dependency chain.
    """
    if spec.family not in ("kalman_dns", "kalman_afns", "kalman_tvl"):
        raise ValueError(f"pallas kernel supports the kalman families, "
                         f"not {spec.family!r}")
    if tile_rows <= 0 or tile_rows % _SUB:
        raise ValueError(f"tile_rows must be a positive multiple of {_SUB}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    tvl = spec.family == "kalman_tvl"
    f32 = jnp.float32
    params_batch = jnp.asarray(params_batch, dtype=f32)
    B = params_batch.shape[0]
    rows = tile_rows
    nb = -(-B // (rows * _LANE))
    N, Ms = spec.N, spec.state_dim
    T = data.shape[1]
    if end is None:
        end = T
    windowed = starts is not None

    kp = jax.vmap(partial(unpack_kalman, spec))(params_batch)
    if tvl:  # state-dependent measurement: Z/d are built inside the kernel
        Z = jnp.zeros((B, 1), dtype=f32)
        d = jnp.zeros((B, 1), dtype=f32)
    else:
        Z, d = jax.vmap(lambda k: measurement_setup(spec, k, f32))(kp)
        if d is None:
            d = jnp.zeros((B, N), dtype=f32)
    state0 = jax.vmap(partial(init_state, spec))(kp)

    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)
    contrib = loglik_contrib_mask(start, end, T)
    masks = jnp.stack([observed, contrib], axis=1).astype(f32)
    win = window_array(starts, ends, B, f32)

    args = [
        _lay(Z.astype(f32), B, nb, rows),              # (N·Ms, nb·rows, 128); (1, ...) TVλ dummy
        _lay(d.astype(f32), B, nb, rows),              # (N, ...); (1, ...) TVλ dummy
        _lay(kp.Phi.astype(f32), B, nb, rows),         # (Ms·Ms, ...)
        _lay(kp.delta.astype(f32), B, nb, rows),       # (Ms, ...)
        _lay(kp.Omega_state.astype(f32), B, nb, rows), # (Ms·Ms, ...)
        _lay(kp.obs_var.astype(f32), B, nb, rows),     # (1, ...)
        _lay(state0.beta.astype(f32), B, nb, rows),    # (Ms, ...)
        _lay(state0.P.astype(f32), B, nb, rows),       # (Ms·Ms, ...)
        jnp.asarray(data, dtype=f32).T,                # (T, N) shared
        masks,                                         # (T, 2) shared
        _lay(win, B, nb, rows),                        # (2, ...) per-lane window
    ]

    def tile_spec(D):
        return pl.BlockSpec((D, rows, _LANE), lambda g: (0, g, 0),
                            memory_space=pltpu.VMEM)

    z_rows = 1 if tvl else N * Ms
    d_rows = 1 if tvl else N
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    out = pl.pallas_call(
        partial(_kernel, N, Ms, T, tvl, spec.exact_jacobian, windowed,
                tuple(float(m) for m in spec.maturities), rows),
        grid=(nb,),
        in_specs=[tile_spec(z_rows), tile_spec(d_rows), tile_spec(Ms * Ms),
                  tile_spec(Ms), tile_spec(Ms * Ms), tile_spec(1),
                  tile_spec(Ms), tile_spec(Ms * Ms), smem, smem,
                  tile_spec(2)],
        out_specs=pl.BlockSpec((rows, _LANE), lambda g: (g, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * rows, _LANE), f32),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    return out.reshape(-1)[:B]
