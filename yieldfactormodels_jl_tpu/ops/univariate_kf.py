"""Univariate (sequential-observation) Kalman loglik — the TPU fast path.

The joint-form filter step (models/kalman.py) factorizes the N×N innovation
covariance F with a Cholesky every step (the reference inverts it outright,
/root/reference/src/models/kalman/filter.jl:150).  On TPU a batched 20×20
Cholesky inside a scan is the worst-case op: tiny, sequential, and unmappable
to the MXU.

Because the measurement error is diagonal in every model of this framework
(Ω_obs = σ²I — kalman/paramoperations.jl:13), the innovations decomposition
lets the N-dimensional update be processed as N *scalar* updates per time
step (the Koopman–Durbin "univariate treatment of multivariate series"):

    for i = 1..N:   f_i = z_i' P z_i + σ²,   v_i = y_i^eff − z_i'β
                    K = P z_i / f_i,   β += K v_i,   P −= K (z_i'P)
    loglik_t = −½ Σ_i (log f_i + v_i²/f_i + log 2π)

which is *algebraically identical* to the joint update — same posterior, same
log-likelihood (log|F| + v'F⁻¹v = Σ log f_i + v_i²/f_i) — but contains only
rank-1 elementwise arithmetic that XLA fuses and vmaps into pure VPU work.

Nonlinear measurements (the TVλ EKF) are handled by the standard fixed-
linearization trick: with y_i^eff = y_i − h_i(β_pred) + z_i'β_pred the scalar
recursion reproduces the joint EKF update exactly.

Semantics match models/kalman.py bit-for-bit in structure: NaN columns and
out-of-window steps are transition-only, the first innovation is skipped, and
a non-PD innovation variance yields −Inf (the joint form's failed-Cholesky
sentinel, filter.jl:182-209).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ..models.kalman import (
    KalmanState,
    init_state,
    loglik_contrib_mask,
    measurement_setup,
    state_measurement,
)
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax

_LOG_2PI = math.log(2.0 * math.pi)


def _sequential_update(Z, y_eff, beta, P, obs_var):
    """N scalar measurement updates.  Returns (β⁺, P⁺, loglik, ok, code) —
    ``code`` is the taxonomy bitmask riding the same carry as ``ok``
    (robustness/taxonomy.py): NONPSD_INNOVATION for a finite f ≤ 0,
    STATE_EXPLODED for a non-finite innovation chain."""
    N = Z.shape[0]

    def body(carry, zi_yi):
        b, Pm, ll, ok, code = carry
        z, y_i = zi_yi
        zP = z @ Pm                     # (Ms,)
        f = zP @ z + obs_var
        f_fin = jnp.isfinite(f)
        ok = ok & (f > 0) & f_fin
        code = code | tax.bit(f_fin & (f <= 0), tax.NONPSD_INNOVATION) \
            | tax.bit(~f_fin, tax.STATE_EXPLODED)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y_i - z @ b
        K = zP / fsafe
        b = b + K * v
        Pm = Pm - jnp.outer(K, zP)
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b, Pm, ll, ok, code), None

    zero = jnp.zeros((), dtype=P.dtype)
    (beta_u, P_u, ll, ok, code), _ = lax.scan(
        body, (beta, P, zero, jnp.bool_(True), tax.zero_code()),
        (Z, y_eff), length=N)
    # symmetrize: the rank-1 downdates drift asymmetric in f32 over hundreds
    # of steps, which the joint form's (I−KZ)P also suffers — cheap insurance
    P_u = 0.5 * (P_u + P_u.T)
    return beta_u, P_u, ll, ok, code


def _filter_scan(spec: ModelSpec, params, data, start, end):
    """THE sequential-update forward pass — single source of the engine's
    NaN-column/window/failure semantics, shared by ``get_loss`` and
    ``filter_moments`` so the loglik and the moments the smoother/sandwich
    ride can never diverge.  Returns ``(kp, outs)``; ``outs['ll']`` follows
    the joint form's per-step convention (0 unobserved, −Inf on a failed
    innovation-variance chain, NOT contribution-masked)."""
    kp = unpack_kalman(spec, params)
    dtype = kp.Phi.dtype
    mats = spec.maturities_array
    Z_const, d_const = measurement_setup(spec, kp, dtype)
    mfn = state_measurement(spec)
    if Z_const is not None and d_const is None:
        d_const = jnp.zeros((spec.N,), dtype=dtype)

    state0 = init_state(spec, kp)
    T = data.shape[1]
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)

    def body(state, inp):
        y, obs_t = inp
        beta, P = state
        if mfn is not None:
            # fixed-linearization effective observation for the EKF: with
            # y_eff = y − h(β_pred) + Z β_pred the scalar recursion
            # v_i = y_eff_i − z_i'b reproduces the joint EKF update exactly
            # (Z carries the Jacobian column that h(β_pred) does not).
            Z, y_pred0 = mfn(beta, mats)
            ysafe = jnp.where(jnp.isfinite(y), y, y_pred0)
            y_eff = ysafe - y_pred0 + Z @ beta
        else:
            # linear measurement: the round-trip above cancels to y − d
            Z = Z_const
            ysafe = jnp.where(jnp.isfinite(y), y, Z @ beta + d_const)
            y_eff = ysafe - d_const
        obs = obs_t & jnp.all(jnp.isfinite(y))
        beta_u, P_u, ll, ok, code = _sequential_update(Z, y_eff, beta, P,
                                                       kp.obs_var)
        obs_f = obs.astype(dtype)
        beta_m = beta + (beta_u - beta) * obs_f
        P_m = P + (P_u - P) * obs_f
        beta_next = kp.delta + kp.Phi @ beta_m
        P_next = kp.Phi @ P_m @ kp.Phi.T + kp.Omega_state
        ll_out = jnp.where(obs & ok, ll, jnp.where(obs, -jnp.inf, 0.0))
        code_out = jnp.where(obs, code, jnp.int32(0))
        return (KalmanState(beta_next, P_next),
                (beta, P, beta_m, P_m, ll_out, obs, code_out))

    _, (b_pred, P_pred, b_upd, P_upd, lls, obs_steps, codes) = lax.scan(
        body, state0, (data.T, observed))
    return kp, {"beta_pred": b_pred, "P_pred": P_pred,
                "beta_upd": b_upd, "P_upd": P_upd, "ll": lls,
                "obs": obs_steps, "code": codes}


def get_loss(spec: ModelSpec, params, data, start=0, end=None):
    """Gaussian loglik via sequential scalar updates — numerically equal to
    ``models.kalman.get_loss`` (same windows/NaN/−Inf conventions), but with
    no Cholesky/triangular solves: the per-step work is rank-1 FMAs that vmap
    across draw/start/window batches as pure elementwise lanes.  (The moment
    stacks the shared scan also emits are dead code here; jit/scan DCE prunes
    them — same mechanism the joint engine's `_step` outputs rely on.)"""
    T = data.shape[1]
    if end is None:
        end = T
    _, outs = _filter_scan(spec, params, data, start, end)
    contrib = loglik_contrib_mask(start, end, T)
    # per-step joint convention → loss gating: where(obs & contrib,
    # where(ok, ll, −Inf), 0) ≡ where(contrib, ll_out, 0) since ll_out is
    # already 0 on unobserved steps and −Inf on failed observed ones
    total = jnp.sum(jnp.where(contrib, outs["ll"], 0.0))
    return jnp.where(jnp.isfinite(total), total, -jnp.inf)


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None):
    """``(loss, code)``: :func:`get_loss` plus its taxonomy bitmask
    (robustness/taxonomy.py).  Identical loss value — the code rides the scan
    carry the kernel already threads, so callers that ignore it (every
    ``get_loss`` consumer) have it dead-code-eliminated by XLA."""
    T = data.shape[1]
    if end is None:
        end = T
    _, outs = _filter_scan(spec, params, data, start, end)
    contrib = loglik_contrib_mask(start, end, T)
    total = jnp.sum(jnp.where(contrib, outs["ll"], 0.0))
    loss = jnp.where(jnp.isfinite(total), total, -jnp.inf)
    code = tax.params_code(params) \
        | tax.combine(jnp.where(contrib, outs["code"], jnp.int32(0))) \
        | tax.bit(~jnp.any(contrib & outs["obs"]), tax.MISSING_ALL_OBS)
    # a −Inf loss must never decode as OK: non-finite total without a more
    # specific cause (e.g. NaN data inside the window) is a blown-up state
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code


def filter_moments(spec: ModelSpec, params, data, start=0, end=None):
    """Per-step filtering moments via the sequential-update engine.

    Returns ``(kp, outs)`` with ``outs`` matching the joint form's moment
    outputs (models/kalman.py `_step`): ``beta_pred``/``P_pred`` are the
    incoming predicted moments, ``beta_upd``/``P_upd`` the obs-blended
    posterior moments, and ``ll`` the per-step loglik in the joint
    convention — 0 on unobserved steps, −Inf where the innovation variance
    chain failed (the joint form's failed-Cholesky sentinel), NOT
    contribution-masked.  The posterior moments are algebraically identical
    to the joint update's (Koopman–Durbin), so the RTS smoother
    (ops/smoother.py) and the sandwich score decomposition
    (estimation/inference.py) can ride this Cholesky-free engine.
    """
    T = data.shape[1]
    if end is None:
        end = T
    return _filter_scan(spec, params, data, start, end)
