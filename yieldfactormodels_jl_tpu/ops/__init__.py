from . import linalg

__all__ = ["linalg"]
