from . import linalg

__all__ = ["linalg", "assoc_scan", "particle", "pallas_kf", "pallas_pf",
           "pallas_ssd", "score_scan", "slr_scan", "smoother", "sqrt_kf",
           "univariate_kf"]


def __getattr__(name):
    # lazy: pallas/associative-scan/particle modules import jax.experimental
    # machinery that should not load unless used
    if name in __all__:
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
