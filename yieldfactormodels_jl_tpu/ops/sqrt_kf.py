"""Square-root (Potter) Kalman log-likelihood — f32-robust covariance path.

The covariance recursions in the joint (models/kalman.py) and univariate
(ops/univariate_kf.py) filters propagate P itself; over hundreds of f32 steps
the rank-1 downdates can push P slightly indefinite, which surfaces as a
spurious non-PD innovation variance (−Inf loss) near poorly-conditioned
optima.  This kernel propagates a Cholesky-like factor S with P = S Sᵀ
instead, so P is positive semi-definite *by construction* at every step:

  - measurement update: Potter's rank-1 square-root update per scalar
    observation (the univariate/sequential decomposition of ops/univariate_kf,
    valid because Ω_obs = σ²I in every model of this framework):
        φ = Sᵀz,  f = φᵀφ + σ²,  α = 1/(f + √(σ²·f)),
        β ← β + (Sφ) v / f,   S ← S − α (Sφ) φᵀ
  - time update: QR re-factorization  qr([Sᵀ Φᵀ; C]) → R,  S_pred = Rᵀ
    with Ω_state = CᵀC — one small QR per step instead of a Cholesky, which
    XLA batches fine at these sizes (Ms ≤ 5).

Log-likelihood, window masks, NaN handling and the −Inf sentinel follow the
same conventions as every other Kalman kernel here (kalman/filter.jl:182-209
semantics); agreement with the univariate path is tested in f64 and the f32
robustness property (finite where the plain path may fail) in tests.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ..models.kalman import (
    init_state,
    loglik_contrib_mask,
    measurement_setup,
    state_measurement,
)
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax

_LOG_2PI = math.log(2.0 * math.pi)


def _potter_update(Z, y_eff, beta, S, obs_var):
    """N sequential Potter square-root updates.  Returns (β⁺, S⁺, ll, ok,
    code) — ``code`` is the taxonomy bitmask beside ``ok``
    (robustness/taxonomy.py)."""
    N = Z.shape[0]

    def body(carry, zi_yi):
        b, Sm, ll, ok, code = carry
        z, y_i = zi_yi
        phi = Sm.T @ z                    # (Ms,)
        f = phi @ phi + obs_var
        f_fin = jnp.isfinite(f)
        ok = ok & (f > 0) & f_fin
        code = code | tax.bit(f_fin & (f <= 0), tax.NONPSD_INNOVATION) \
            | tax.bit(~f_fin, tax.STATE_EXPLODED)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y_i - z @ b
        Sphi = Sm @ phi                   # = P z
        b = b + Sphi * (v / fsafe)
        alpha = 1.0 / (fsafe + jnp.sqrt(jnp.maximum(obs_var, 0.0) * fsafe))
        Sm = Sm - alpha * jnp.outer(Sphi, phi)
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b, Sm, ll, ok, code), None

    zero = jnp.zeros((), dtype=S.dtype)
    (beta_u, S_u, ll, ok, code), _ = lax.scan(
        body, (beta, S, zero, jnp.bool_(True), tax.zero_code()),
        (Z, y_eff), length=N)
    return beta_u, S_u, ll, ok, code


def _psd_sqrt_factor(M, floor, dtype):
    """A (possibly non-triangular) square root of the PSD *projection* of a
    symmetric matrix: eigendecompose, clip eigenvalues at ``floor``, return
    ``V·diag(√w̃)`` so the product is the nearest-PSD reconstruction.  The
    Potter/QR recursions only need S Sᵀ = P, not triangularity.  This is the
    escalation ladder's square-root rescue (robustness/ladder.py, after
    Yaghoobi et al., arXiv:2207.00426): breakdown-prone covariances re-enter
    the filter through a factorization that cannot go indefinite."""
    w, V = jnp.linalg.eigh(0.5 * (M + M.T))
    w = jnp.maximum(w, jnp.asarray(floor, dtype=dtype))
    return V * jnp.sqrt(w)[None, :]


def _loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                init_psd_floor=None):
    """Shared square-root forward pass.  Returns ``(loss, code)``.

    ``init_psd_floor=None`` is the production engine: a failed initial
    factorization (indefinite P₀, invalid Ω) is the −Inf sentinel, bit-exact
    with the historical ``get_loss``.  With a float floor, P₀ and Ω_state are
    PSD-*projected* (eigenvalue clip at the floor) before factoring instead
    of poisoning — the ladder's recovery mode, NOT the parity path: at a
    degenerate parameter point the exact likelihood does not exist, and the
    projected filter is the numerically-safe surrogate the escalation ladder
    evaluates (its acceptance is decided at the driver, never silently).
    """
    kp = unpack_kalman(spec, params)
    dtype = kp.Phi.dtype
    Ms = spec.state_dim
    mats = spec.maturities_array
    Z_const, d_const = measurement_setup(spec, kp, dtype)
    mfn = state_measurement(spec)
    if Z_const is not None and d_const is None:
        d_const = jnp.zeros((spec.N,), dtype=dtype)

    state0 = init_state(spec, kp)
    if init_psd_floor is None:
        # factor P0 (symmetrized + jitter: the kron solve is only
        # approximately symmetric in f32) and Ω_state once
        P0 = 0.5 * (state0.P + state0.P.T) + 1e-9 * jnp.eye(Ms, dtype=dtype)
        S0 = jnp.linalg.cholesky(P0)
        Om = 0.5 * (kp.Omega_state + kp.Omega_state.T) \
            + 1e-12 * jnp.eye(Ms, dtype=dtype)
        C = jnp.linalg.cholesky(Om).T      # upper factor: Ω = CᵀC
        # a failed factorization (indefinite P0 from a non-stationary Φ draw,
        # or invalid Ω) is the −Inf sentinel, like every other engine's
        # failed Cholesky — substitute finite placeholders only to keep the
        # scan arithmetic NaN-free, and poison the total at the end
        fac_ok = jnp.all(jnp.isfinite(S0)) & jnp.all(jnp.isfinite(C))
        S0 = jnp.where(jnp.isfinite(S0), S0, jnp.eye(Ms, dtype=dtype) * 1e-3)
        C = jnp.where(jnp.isfinite(C), C, jnp.zeros_like(C))
    else:
        # ladder recovery mode: PSD-project instead of poisoning; only
        # non-finite inputs (TRANSFORM_OVERFLOW class) still fail
        S0 = _psd_sqrt_factor(jnp.where(jnp.isfinite(state0.P), state0.P, 0.0),
                              init_psd_floor, dtype)
        Cl = _psd_sqrt_factor(jnp.where(jnp.isfinite(kp.Omega_state),
                                        kp.Omega_state, 0.0),
                              init_psd_floor, dtype)
        C = Cl.T                           # Ω̃ = CᵀC
        fac_ok = jnp.all(jnp.isfinite(S0)) & jnp.all(jnp.isfinite(C))

    T = data.shape[1]
    if end is None:
        end = T
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)
    contrib = loglik_contrib_mask(start, end, T)

    def body(state, inp):
        y, obs_t, con_t = inp
        beta, S = state
        if mfn is not None:
            Z, y_pred0 = mfn(beta, mats)
            ysafe = jnp.where(jnp.isfinite(y), y, y_pred0)
            y_eff = ysafe - y_pred0 + Z @ beta
        else:
            Z = Z_const
            ysafe = jnp.where(jnp.isfinite(y), y, Z @ beta + d_const)
            y_eff = ysafe - d_const
        obs = obs_t & jnp.all(jnp.isfinite(y))
        beta_u, S_u, ll, ok, code = _potter_update(Z, y_eff, beta, S,
                                                   kp.obs_var)
        obs_f = obs.astype(dtype)
        beta_m = beta + (beta_u - beta) * obs_f
        S_m = S + (S_u - S) * obs_f
        beta_next = kp.delta + kp.Phi @ beta_m
        # time update: qr([S_mᵀ Φᵀ; C]) — R is (Ms, Ms) upper, S_pred = Rᵀ
        pre = jnp.concatenate([S_m.T @ kp.Phi.T, C], axis=0)  # (2Ms, Ms)
        R = jnp.linalg.qr(pre, mode="r")
        S_next = R.T
        ll_t = jnp.where(obs & con_t,
                         jnp.where(ok, ll, -jnp.inf),
                         0.0)
        code_t = jnp.where(obs & con_t, code, jnp.int32(0))
        return (beta_next, S_next), (ll_t, code_t, obs & con_t)

    _, (lls, codes, obs_c) = lax.scan(body, (state0.beta, S0),
                                      (data.T, observed, contrib))
    total = jnp.sum(lls)
    loss = jnp.where(fac_ok & jnp.isfinite(total), total, -jnp.inf)
    code = tax.params_code(params) | tax.combine(codes) \
        | tax.bit(~fac_ok, tax.CHOL_BREAKDOWN) \
        | tax.bit(~jnp.any(obs_c), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code


def get_loss(spec: ModelSpec, params, data, start=0, end=None,
             init_psd_floor=None):
    """Gaussian loglik with square-root covariance propagation.

    Same value as ``univariate_kf.get_loss`` in exact arithmetic; in f32 it
    trades ~2 small QRs worth of work per step for a guaranteed-PSD P.
    ``init_psd_floor`` selects the ladder's PSD-projected recovery mode
    (see :func:`_loss_coded`); leave it ``None`` for the parity engine.
    """
    loss, _ = _loss_coded(spec, params, data, start, end, init_psd_floor)
    return loss


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                   init_psd_floor=None):
    """``(loss, code)`` — :func:`get_loss` plus its taxonomy bitmask."""
    return _loss_coded(spec, params, data, start, end, init_psd_floor)
