"""Pallas TPU kernel: fused Rao-Blackwellized particle filter (config 3).

Hand-scheduled version of ``ops/particle.particle_filter_loglik`` in its
common-noise mode — the VERDICT r2 #2 kernel push for the SV workload.  The
XLA path dispatches ~T×N small fused ops per draw with the (Ms, Ms, P) state
round-tripping HBM between scan steps; here ONE grid program owns ONE draw
and keeps the entire particle system VMEM-resident across the whole T-step
recursion:

  - particle p ↔ lane position: every per-particle quantity is a (1, P) row
    (P = 1024 default → 8 lane-tiles per vector op), state/obs dims are
    unrolled Python loops over rows — pure VPU arithmetic, zero HBM traffic
    between steps;
  - systematic resampling runs entirely on-chip: the cumulative weights come
    from one (1, P)·(P, P) lower-triangular MXU matmul, the slot→particle
    selection matrix M[i, j] = 1[cum_{i−1} < pos_j ≤ cum_i] is built from
    ``broadcasted_iota`` comparisons (row→column transposes via a
    broadcast–diag-mask–lane-reduce, no cross-lane shuffles), and the gather
    ``state[:, idx]`` becomes one (R, P)·(P, P) MXU matmul over the stacked
    31-row state — the "fuse resampling gathers" item;
  - the log-vol proposal noise and resampling offsets are STREAMED IN
    (common-noise contract), so the kernel is deterministic and elementwise
    parity-testable against ``particle_filter_loglik(..., noise=...)`` —
    float64 in interpret mode (tests/test_pallas_pf.py), statistically on
    hardware where f32 boundary flips at resampling de-synchronize
    trajectories (same criterion family as benchmarks/common.py).

Semantics mirror the XLA path exactly: Potter square-root updates (strictly
positive innovation variance), predict-only NaN columns, the reference's
skip-first-innovation convention (kalman/filter.jl:190-195), ESS-gated
systematic resampling with searchsorted-left boundary/clamp behavior, and the
−Inf draw sentinel.  (The reference has no SV model at all — this is the
beyond-reference capability benchmarked as BASELINE.md config 3.)
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from .pallas_kf import CompilerParams
from .particle import _measurement, factored_init

_LOG_2PI = math.log(2.0 * math.pi)
_LANE = 128


def _kernel(N: int, Ms: int, T: int, P: int, n_eff: int, th: float, ft,
            parr, datar, unifr, noiser, outr):
    """One grid program = one draw; particles on the lane axis.

    ``n_eff`` ≤ P live particles; lanes n_eff..P−1 are DEAD padding (weight
    −Inf, never resampled into a live slot, zero loglik contribution), so the
    kernel runs the exact n_eff-particle workload of the XLA engine while
    every vector op stays full-lane-width.

    ``parr`` (1, npar) SMEM per-draw parameter row (packing in
    ``_pack_params``), ``datar`` (T, N) SMEM shared panel, ``unifr``
    (1, T−1) SMEM resampling offsets, ``noiser`` (1, T−1, P) VMEM log-vol
    proposal normals, ``outr`` (1, 128) VMEM output tile (loglik broadcast).
    """
    o_z, o_d = 0, N * Ms
    o_phi = o_d + N
    o_del = o_phi + Ms * Ms
    o_om = o_del + Ms
    o_ov = o_om + Ms * Ms
    o_b0 = o_ov + 1
    o_s0 = o_b0 + Ms
    o_svp = o_s0 + Ms * Ms
    o_svs = o_svp + 1

    def pr(i):
        return parr[0, i]

    ovar = pr(o_ov)
    svphi, svsig = pr(o_svp), pr(o_svs)
    log_uniform = jnp.asarray(-math.log(float(n_eff)), dtype=ft)
    live = lax.broadcasted_iota(jnp.int32, (1, P), 1) < n_eff
    logw_reset = jnp.where(live, jnp.full((1, P), log_uniform, dtype=ft),
                           jnp.full((1, P), -jnp.inf, dtype=ft))

    beta0 = tuple(jnp.full((1, P), pr(o_b0 + m), dtype=ft) for m in range(Ms))
    S0 = tuple(jnp.full((1, P), pr(o_s0 + k), dtype=ft) for k in range(Ms * Ms))
    h0 = jnp.zeros((1, P), dtype=ft)
    logw0 = logw_reset
    ll0 = jnp.zeros((1, 1), dtype=ft)

    def step(t, carry):
        beta, S, h, logw, ll_tot = carry

        # ---- log-vol proposal from the streamed normals ------------------
        z_row = noiser[0, pl.ds(t, 1), :]                       # (1, P)
        h_new = svphi * h + svsig * z_row
        r = ovar * jnp.exp(h_new)
        sqrt_r = jnp.sqrt(jnp.maximum(r, 0.0))

        # ---- N sequential Potter square-root measurement updates ---------
        b_u = list(beta)
        S_u = list(S)
        llp = jnp.zeros((1, P), dtype=ft)
        ok = jnp.isfinite(r)
        finite_s = True
        for i in range(N):
            y_i = datar[t, i]
            fin_i = jnp.isfinite(y_i)
            finite_s = jnp.logical_and(finite_s, fin_i)
            ysafe = jnp.where(fin_i, y_i, jnp.zeros((), ft))
            z = tuple(pr(o_z + i * Ms + m) for m in range(Ms))
            d_i = pr(o_d + i)
            phi = [sum(S_u[k * Ms + m] * z[k] for k in range(Ms))
                   for m in range(Ms)]                            # Sᵀz
            f = sum(phi[m] * phi[m] for m in range(Ms)) + r       # > 0 if r > 0
            fsafe = jnp.where(f > 0, f, jnp.ones((), ft))
            ok = ok & jnp.isfinite(f) & (f > 0)                   # σ²<0 sentinel
            v = ysafe - d_i - sum(b_u[m] * z[m] for m in range(Ms))
            Sphi = [sum(S_u[k * Ms + m] * phi[m] for m in range(Ms))
                    for k in range(Ms)]                           # P z
            vf = v / fsafe
            b_u = [b_u[m] + Sphi[m] * vf for m in range(Ms)]
            alpha = 1.0 / (fsafe + sqrt_r * jnp.sqrt(fsafe))
            S_u = [S_u[k * Ms + m] - alpha * Sphi[k] * phi[m]
                   for k in range(Ms) for m in range(Ms)]
            llp = llp - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)

        # ---- blend update vs predict-only (float blend, XLA-identical) ---
        obs_f = jnp.where(finite_s, jnp.ones((), ft), jnp.zeros((), ft))
        beta_m = [beta[m] + (b_u[m] - beta[m]) * obs_f for m in range(Ms)]
        S_m = [S[k] + (S_u[k] - S[k]) * obs_f for k in range(Ms * Ms)]

        # ---- propagate: β' = δ + Φβ, S' = chol(ΦS(ΦS)ᵀ + Ω) --------------
        beta_next = [pr(o_del + m)
                     + sum(pr(o_phi + m * Ms + k) * beta_m[k]
                           for k in range(Ms)) for m in range(Ms)]
        A = [sum(pr(o_phi + i * Ms + j) * S_m[j * Ms + k] for j in range(Ms))
             for i in range(Ms) for k in range(Ms)]

        # unrolled Cholesky–Banachiewicz of P = A Aᵀ + Ω (particle.
        # _propagate_cholesky, identical op order/floor)
        L = [None] * (Ms * Ms)
        for i in range(Ms):
            for j in range(i + 1):
                s = pr(o_om + i * Ms + j)
                for k in range(Ms):
                    s = s + A[i * Ms + k] * A[j * Ms + k]
                for k in range(j):
                    s = s - L[i * Ms + k] * L[j * Ms + k]
                if i == j:
                    L[i * Ms + i] = jnp.sqrt(jnp.maximum(s, 1e-12))
                else:
                    L[i * Ms + j] = s / L[j * Ms + j]
        zero_row = jnp.zeros((1, P), dtype=ft)
        S_next = [L[i * Ms + j] if j <= i else zero_row
                  for i in range(Ms) for j in range(Ms)]

        # ---- weights / loglik accumulation -------------------------------
        ll_step = jnp.where(ok, llp, -jnp.inf)
        contrib = jnp.logical_and(finite_s, t > 0)
        logw_new = logw + jnp.where(contrib, ll_step, zero_row)
        m_w = jnp.max(logw_new, axis=1, keepdims=True)            # (1, 1)
        m_safe = jnp.where(m_w > -jnp.inf, m_w, jnp.zeros((), ft))
        sum_e = jnp.sum(jnp.exp(logw_new - m_safe), axis=1, keepdims=True)
        step_ll = m_safe + jnp.log(sum_e)                         # (1, 1)
        logw_norm = logw_new - step_ll
        ll_tot = ll_tot + jnp.where(contrib, step_ll, jnp.zeros((1, 1), ft))

        # ---- ESS-gated systematic resampling (always computed, selected) -
        wn = jnp.exp(logw_norm)
        ess = 1.0 / jnp.sum(wn * wn, axis=1, keepdims=True)       # (1, 1)
        do_res = jnp.logical_and(contrib, ess < th)               # (1, 1)

        ii = lax.broadcasted_iota(jnp.int32, (P, P), 0)
        jj = lax.broadcasted_iota(jnp.int32, (P, P), 1)
        lt = (ii <= jj).astype(ft)
        cum_row = jnp.dot(wn, lt, preferred_element_type=ft)      # (1, P)
        diag = (ii == jj).astype(ft)
        cum_col = jnp.sum(jnp.broadcast_to(cum_row, (P, P)) * diag,
                          axis=1, keepdims=True)                  # (P, 1)
        wn_col = jnp.sum(jnp.broadcast_to(wn, (P, P)) * diag,
                         axis=1, keepdims=True)
        prev_col = cum_col - wn_col
        row_id = lax.broadcasted_iota(jnp.int32, (P, 1), 0)
        # row 0's lower bound is cum_{-1} = −∞, not 0: searchsorted-left
        # clones particle 0 for pos = 0 exactly (the u = 0 draw), whereas
        # `0 < pos` would leave slot 0 matching NO row and the matmul would
        # silently zero its state
        prev_col = jnp.where(row_id == 0,
                             jnp.full((P, 1), -1.0, dtype=ft), prev_col)
        # clamp: slots past cum (f32 rounding) pick the LAST LIVE particle
        # (gather-clamp parity with the XLA engine's index n_eff−1)
        cum_hi = jnp.where(row_id == n_eff - 1,
                           jnp.full((P, 1), 2.0, dtype=ft), cum_col)
        u_t = unifr[0, t]
        jrow = lax.broadcasted_iota(jnp.int32, (1, P), 1)
        # dead slots (j ≥ n_eff) get pos = 2 > every cum ⇒ they copy the
        # clamp row's state but their weight stays −Inf below
        pos = jnp.where(live,
                        (jrow.astype(ft) + u_t)
                        / jnp.asarray(float(n_eff), dtype=ft),
                        jnp.full((1, P), 2.0, dtype=ft))
        sel = jnp.logical_and(prev_col < pos, pos <= cum_hi).astype(ft)
        old = jnp.concatenate(
            [beta_next[m] for m in range(Ms)]
            + [S_next[k] for k in range(Ms * Ms)] + [h_new], axis=0)
        new = jnp.dot(old, sel, preferred_element_type=ft)        # (R, P)

        beta_out = tuple(jnp.where(do_res, new[m:m + 1, :], beta_next[m])
                         for m in range(Ms))
        S_out = tuple(jnp.where(do_res, new[Ms + k:Ms + k + 1, :], S_next[k])
                      for k in range(Ms * Ms))
        R = Ms + Ms * Ms
        h_out = jnp.where(do_res, new[R:R + 1, :], h_new)
        logw_out = jnp.where(do_res, logw_reset, logw_norm)
        return beta_out, S_out, h_out, logw_out, ll_tot

    _, _, _, _, ll = lax.fori_loop(0, T - 1, step,
                                   (beta0, S0, h0, logw0, ll0))
    val = jnp.where(jnp.isfinite(ll), ll, -jnp.inf)
    outr[...] = jnp.broadcast_to(val, (1, _LANE))


def _pack_params(spec: ModelSpec, params, ft):
    """Per-draw scalar row + fac_ok flag.  The initial-moment factorization
    (jitters, NaN fallbacks, sentinel) comes from the ONE shared helper
    ``particle.factored_init`` so the elementwise parity contract with the
    XLA engine cannot drift."""
    kp = unpack_kalman(spec, params)
    dtype = params.dtype
    Z, d = _measurement(spec, kp, dtype)
    state0, S0, chol_Om, fac_ok = factored_init(spec, kp, dtype)
    Omq = chol_Om @ chol_Om.T  # the XLA path propagates with this product
    row = jnp.concatenate([
        Z.reshape(-1), d.reshape(-1), kp.Phi.reshape(-1), kp.delta.reshape(-1),
        Omq.reshape(-1), kp.obs_var.reshape(1), state0.beta.reshape(-1),
        S0.reshape(-1),
    ]).astype(ft)
    return row, fac_ok


def pf_loglik_batch(
    spec: ModelSpec,
    params_batch,
    data,
    normals,
    uniforms,
    n_particles: int | None = None,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    ess_threshold: float = 0.5,
    interpret: bool | None = None,
):
    """SV marginal loglik for a batch of draws — fused Pallas PF kernel.

    ``normals`` (D, T−1, P) / ``uniforms`` (D, T−1) are the common-noise
    arrays (P a multiple of 128; 1024 = the full-lane default).  Numerically
    equivalent to ``vmap(particle_filter_loglik)`` fed the same noise; the
    −Inf sentinel covers failed factorizations and non-finite paths exactly
    as there.

    ``n_particles``: live particle count ≤ P (default P).  Lanes beyond it
    are dead padding, so e.g. the BASELINE config-3 workload of exactly
    1,000 particles runs in 1,024 lanes and matches a 1,000-particle XLA
    run fed ``normals[..., :1000]``.
    """
    if spec.family not in ("kalman_dns", "kalman_afns"):
        raise ValueError(f"pallas PF supports the constant-measurement "
                         f"kalman families, not {spec.family!r}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    ft = params_batch.dtype if interpret else jnp.float32
    params_batch = jnp.asarray(params_batch, dtype=ft)
    D = params_batch.shape[0]
    N, Ms = spec.N, spec.state_dim
    T = data.shape[1]
    P = normals.shape[-1]
    if P % _LANE:
        raise ValueError(f"particle count must be a multiple of {_LANE}")
    if normals.shape != (D, T - 1, P) or uniforms.shape != (D, T - 1):
        raise ValueError(
            f"noise shapes must be ({D}, {T - 1}, {P}) / ({D}, {T - 1}); "
            f"got {normals.shape} / {uniforms.shape}")
    n_eff = P if n_particles is None else int(n_particles)
    if not 0 < n_eff <= P:
        raise ValueError(f"n_particles must be in (0, {P}]; got {n_eff}")

    rows, fac_ok = jax.vmap(partial(_pack_params, spec, ft=ft))(params_batch)
    # sv_phi / sv_sigma: shared scalars or per-draw (D,) vectors (the SV-MLE
    # search gives every candidate its own volatility dynamics)
    sv = jnp.stack([jnp.broadcast_to(jnp.asarray(sv_phi, dtype=ft), (D,)),
                    jnp.broadcast_to(jnp.asarray(sv_sigma, dtype=ft), (D,))],
                   axis=1)
    rows = jnp.concatenate([rows, sv], axis=1)

    out = pl.pallas_call(
        partial(_kernel, N, Ms, T, P, n_eff, float(ess_threshold) * n_eff, ft),
        grid=(D,),
        in_specs=[
            pl.BlockSpec((1, rows.shape[1]), lambda g: (g, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T - 1), lambda g: (g, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T - 1, P), lambda g: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, _LANE), lambda g: (g, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((D, _LANE), ft),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(rows, jnp.asarray(data, dtype=ft).T,
      jnp.asarray(uniforms, dtype=ft), jnp.asarray(normals, dtype=ft))
    total = out[:, 0]
    return jnp.where(fac_ok & jnp.isfinite(total), total, -jnp.inf)
