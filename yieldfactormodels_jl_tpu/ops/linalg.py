"""Small dense linear-algebra helpers shared by the filter kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp

RIDGE = 1e-3


def ols_solve(Z, y):
    """β = (ZᵀZ)⁻¹Zᵀy via Cholesky, with the reference's ridge fallback.

    The reference tries a plain Cholesky of ZᵀZ and, on failure, retries with
    +1e-3 on the diagonal (/root/reference/src/models/filter.jl:122-137).
    Branchlessly: factor both and select — a 3×3 Cholesky is free next to the
    surrounding matmuls, and the select keeps the kernel jit/vmap-safe.
    """
    M = Z.shape[-1]
    G = Z.T @ Z
    b = Z.T @ y
    cho = jnp.linalg.cholesky(G)
    ok = jnp.all(jnp.isfinite(cho))
    cho_ridge = jnp.linalg.cholesky(G + RIDGE * jnp.eye(M, dtype=G.dtype))
    cho_sel = jnp.where(ok, jnp.nan_to_num(cho), cho_ridge)
    return jax.scipy.linalg.cho_solve((cho_sel, True), b)
