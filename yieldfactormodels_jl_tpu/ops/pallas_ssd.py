"""Pallas TPU kernel: fused batched score-driven (MSED) loss — value only.

The reference's OWN flagship hot loop (`/root/reference/src/models/filter.jl:
52-91`, driven by test.jl's 1SSD-NNS) is a per-step recursion whose score is
an inner gradient of the neural measurement loss.  The XLA scan version
(models/score_driven.py) is faithful and differentiable, but at batch 1 on a
single chip its per-step graph (~hundreds of small fused ops: two MLP builds,
shape transforms, an inner AD sweep, two OLS solves) executes at device
latency — the round-3 window-1 measurement put one T=360 pass at ~131 ms,
8× SLOWER than one CPU core (BASELINE.md config 6).

This kernel runs the ENTIRE pass as one grid program per draw-tile:

  - draws on the (rows × 128) VPU tile like ops/pallas_kf.py; maturity and
    factor dimensions are unrolled static Python loops,
  - the inner score is the HAND-DERIVED reverse sweep through the loading
    build — MLP chain rule plus the shape-transform adjoints (rescale/pin
    for the slope curve, detrend/normalize for the curvature curve,
    including their global-scalar terms) — validated against the engine's
    `jax.grad` inner score and tests/oracle.py's finite-difference scores,
  - OLS runs as unrolled 3×3 normal equations with the reference's
    plain-then-ridge Cholesky select (ops/linalg.ols_solve semantics),
  - EWMA gradient scaling (scale_grad), random-walk dynamics (B absent:
    the carried Z provably equals loadings(γ), so recompute is exact), the
    partial-NaN poison and the skip-last-innovation window conventions all
    mirror models/score_driven.py elementwise.

Value-only by design: it serves the pure-evaluation bulk paths — the
reference-semantics A/B init grid (optimization.jl:73-114) and the
Nelder–Mead block of block-coordinate estimation — while gradient-based
blocks keep the differentiable scan.  (Same division of labor as
ops/pallas_kf.py before its adjoint existed.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.params import unpack_msed
from ..models.specs import ModelSpec
from .pallas_kf import CompilerParams, _lay

_SUB, _LANE = 8, 128
_EPS = 1e-7        # nn_transform._EPS
_SCALE = 0.9610    # nn_transform._SCALE
_RIDGE = 1e-3      # linalg.RIDGE


def _mlp(p9, tau):
    """Forward of the 1→3(tanh)→1 loading net per maturity (loadings.mlp_curve).

    ``p9``: list of 9 tiles [w1(3), b1(3), w2(3)]; returns (raw list of N
    tiles, h[n][j] tanh activations kept for the reverse sweep)."""
    raw, hs = [], []
    for t in tau:
        h = [jnp.tanh(p9[j] * t + p9[3 + j]) for j in range(3)]
        hs.append(h)
        raw.append(sum(p9[6 + j] * h[j] for j in range(3)))
    return raw, hs


def _mlp_rev(p9, tau, hs, obar):
    """Reverse of ``_mlp``: per-parameter cotangents from per-maturity ō."""
    g = [None] * 9
    for j in range(3):
        w2 = p9[6 + j]
        gw1 = gb1 = gw2 = 0.0
        for n, t in enumerate(tau):
            h = hs[n][j]
            pre = obar[n] * w2 * (1.0 - h * h)
            gw1 = gw1 + pre * t
            gb1 = gb1 + pre
            gw2 = gw2 + obar[n] * h
        g[j], g[3 + j], g[6 + j] = gw1, gb1, gw2
    return g


def _t1_fwd(raw, n, transformed):
    """transform_net_1 forward (nn_transform.py:27-43).  Returns (out, aux)."""
    if transformed:
        rl = raw[n - 2]
        c = 1.0 / (raw[0] - rl + _EPS)
        t = [(raw[i] - rl) * c for i in range(n)]
        sq = [t[i] * t[i] for i in range(n)]
        aux = (t, c)
    else:
        sq = [raw[i] * raw[i] for i in range(n)]
        aux = None
    out = []
    for i in range(n):  # interior = 1..n−3; 0 / n−2 / n−1 are pinned
        if i == 0:
            out.append(jnp.ones_like(raw[0]))
        elif i >= n - 2:
            out.append(jnp.zeros_like(raw[0]))
        else:
            out.append(sq[i])
    return out, aux


def _t1_rev(raw, aux, obar, n, transformed):
    """Reverse of ``_t1_fwd``: ∂out/∂raw applied to cotangents ō (pinned
    entries 0, n−2, n−1 have zero derivative)."""
    rbar = [0.0] * n
    if transformed:
        t, c = aux
        s_tc = 0.0   # Σ ō 2 t c        (interior)
        s_t2c = 0.0  # Σ ō 2 t² c       (interior)
        for i in range(1, n - 2):
            rbar[i] = obar[i] * 2.0 * t[i] * c
            s_tc = s_tc + rbar[i]
            s_t2c = s_t2c + obar[i] * 2.0 * t[i] * t[i] * c
        rbar[0] = -s_t2c          # via c = 1/(raw_0 − raw_{n−2} + ε)
        rbar[n - 2] = s_t2c - s_tc  # −Σ2ōtc (shift) + Σ2ōt²c (via c)
    else:
        for i in range(1, n - 2):
            rbar[i] = obar[i] * 2.0 * raw[i]
    return rbar


def _t2_fwd(raw, mats, n, transformed):
    """transform_net_2 forward (nn_transform.py:46-69).  Returns (out, aux)."""
    if transformed:
        x1, xN = mats[0], mats[n - 1]
        slope = (raw[n - 1] - raw[0]) / (xN - x1)
        intercept = raw[0] - slope * x1
        r = [raw[i] - (slope * mats[i] - intercept) for i in range(n)]
    else:
        r = raw
    r2 = [r[i] * r[i] if 1 <= i <= n - 2 else jnp.zeros_like(r[0])
          for i in range(n)]
    sum_sq = sum(r2[i] * r2[i] for i in range(n))
    if transformed:
        denom = jnp.sqrt(sum_sq) / _SCALE + _EPS
        out = [r2[i] / denom for i in range(n)]
        aux = (r, r2, sum_sq, denom)
    else:
        denom_inv = _SCALE / jnp.sqrt(sum_sq) + _EPS
        out = [r2[i] * denom_inv for i in range(n)]
        aux = (r, r2, sum_sq, denom_inv)
    return out, aux


def _t2_rev(aux, obar, mats, n, transformed):
    """Reverse of ``_t2_fwd`` including the global normalizer and (for the
    transformed variant) the endpoint-detrend line terms."""
    if transformed:
        r, r2, sum_sq, denom = aux
        dot = sum(obar[i] * r2[i] for i in range(n))
        sqrt_s = jnp.sqrt(sum_sq)
        # r2_bar_i = ō_i/denom − dot · r2_i / (√S · SCALE · denom²)
        coef = dot / (sqrt_s * _SCALE * denom * denom)
        rbar = [0.0] * n
        s_rbar = 0.0     # Σ r_bar_i
        s_rbarx = 0.0    # Σ r_bar_i · x_i
        for i in range(1, n - 1):
            r2b = obar[i] / denom - coef * r2[i]
            rb = 2.0 * r[i] * r2b
            rbar[i] = rb
            s_rbar = s_rbar + rb
            s_rbarx = s_rbarx + rb * mats[i]
        # r_i = raw_i + raw_0 − slope·(x_i + x_1), slope=(raw_{n−1}−raw_0)/(x_N−x_1)
        x1, xN = mats[0], mats[n - 1]
        w = 1.0 / (xN - x1)
        slope_bar = -(s_rbarx + s_rbar * x1)   # Σ r_bar_i · (−(x_i + x_1))
        rbar[0] = rbar[0] + s_rbar - slope_bar * w
        rbar[n - 1] = rbar[n - 1] + slope_bar * w
        return rbar
    r, r2, sum_sq, denom_inv = aux
    dot = sum(obar[i] * r2[i] for i in range(n))
    # denom_inv = SCALE/√S + ε ⇒ d denom_inv/d r2_i = −SCALE · r2_i / S^{3/2}
    coef = dot * _SCALE / (sum_sq * jnp.sqrt(sum_sq))
    rbar = [0.0] * n
    for i in range(1, n - 1):
        r2b = obar[i] * denom_inv - coef * r2[i]
        rbar[i] = 2.0 * r[i] * r2b
    return rbar


def _chol3_solve(G, b):
    """β = G⁻¹ b via unrolled 3×3 Cholesky with ols_solve's plain-then-ridge
    select (NaN pivots from a non-PD G mirror jnp.linalg.cholesky)."""
    def chol(g11, g21, g22, g31, g32, g33):
        l11 = jnp.sqrt(g11)
        l21 = g21 / l11
        l31 = g31 / l11
        l22 = jnp.sqrt(g22 - l21 * l21)
        l32 = (g32 - l31 * l21) / l22
        l33 = jnp.sqrt(g33 - l31 * l31 - l32 * l32)
        return l11, l21, l22, l31, l32, l33

    g11, g21, g22, g31, g32, g33 = G
    plain = chol(g11, g21, g22, g31, g32, g33)
    ok = jnp.ones_like(g11, dtype=jnp.bool_)
    for l in plain:
        ok = ok & jnp.isfinite(l)
    ridge = chol(g11 + _RIDGE, g21, g22 + _RIDGE, g31, g32, g33 + _RIDGE)
    L = [jnp.where(ok, jnp.nan_to_num(p), q) for p, q in zip(plain, ridge)]
    l11, l21, l22, l31, l32, l33 = L
    b1, b2, b3 = b
    z1 = b1 / l11
    z2 = (b2 - l21 * z1) / l22
    z3 = (b3 - l31 * z1 - l32 * z2) / l33
    x3 = z3 / l33
    x2 = (z2 - l32 * x3) / l22
    x1 = (z1 - l21 * x2 - l31 * x3) / l11
    return x1, x2, x3


def _kernel(spec_tuple, T: int, rows: int,
            Ar, Br, nur, omr, deltar, mur, phir, datar, maskr, outr):
    """One grid program = ``rows``×128 draws; full T-pass per program."""
    (N, L, family, transformed, scale_grad, has_B, ff, mats) = spec_tuple
    ft = phir.dtype
    n = N
    neural = family == "msed_neural"

    def build_Z(g):
        """Z columns 2 and 3 (lists of N tiles) + aux for the reverse sweep."""
        if neural:
            raw2, h2 = _mlp([g[j] for j in range(9)], mats)
            raw3, h3 = _mlp([g[9 + j] for j in range(9)], mats)
            z2, aux1 = _t1_fwd(raw2, n, transformed)
            z3, aux2 = _t2_fwd(raw3, mats, n, transformed)
            return z2, z3, (raw2, h2, aux1, raw3, h3, aux2)
        # msed_lambda: γ scalar drives λ = 1e-2 + e^γ (loadings.dns_lambda)
        lam = 1e-2 + jnp.exp(g[0])
        z2, z3, zs = [], [], []
        for t in mats:
            zt = jnp.exp(-lam * t)
            c2 = (1.0 - zt) / (lam * t)
            z2.append(c2)
            z3.append(c2 - zt)
            zs.append(zt)
        return z2, z3, (lam, zs)

    def score(g, z2, z3, aux, beta, ysafe):
        """Hand-derived ∇_γ −‖y − Zβ̄‖² (score_driven._score semantics)."""
        v = [ysafe[i] - (beta[0] + beta[1] * z2[i] + beta[2] * z3[i])
             for i in range(n)]
        if neural:
            raw2, h2, aux1, raw3, h3, aux2 = aux
            ob2 = [2.0 * beta[1] * v[i] for i in range(n)]
            ob3 = [2.0 * beta[2] * v[i] for i in range(n)]
            rb2 = _t1_rev(raw2, aux1, ob2, n, transformed)
            rb3 = _t2_rev(aux2, ob3, mats, n, transformed)
            g2 = _mlp_rev([g[j] for j in range(9)], mats, h2, rb2)
            g3 = _mlp_rev([g[9 + j] for j in range(9)], mats, h3, rb3)
            return g2 + g3
        lam, zs = aux
        dlam = lam - 1e-2           # dλ/dγ = e^γ
        acc = 0.0
        for i, t in enumerate(mats):
            zt = zs[i]
            # dz2/dλ = (z τ λτ − (1−z)τ)/(λτ)² ;  dz3/dλ = dz2/dλ + τ z
            lt = lam * t
            dz2 = (zt * t * lt - (1.0 - zt) * t) / (lt * lt)
            dz3 = dz2 + t * zt
            acc = acc + 2.0 * v[i] * (beta[1] * dz2 + beta[2] * dz3)
        return [acc * dlam]

    def ols(z2, z3, ysafe, y_sums):
        sy, s1 = y_sums  # Σ y_i (scalar), N (float)
        g21 = sum(z2)
        g31 = sum(z3)
        g22 = sum(z2[i] * z2[i] for i in range(n))
        g32 = sum(z3[i] * z2[i] for i in range(n))
        g33 = sum(z3[i] * z3[i] for i in range(n))
        b2 = sum(z2[i] * ysafe[i] for i in range(n))
        b3 = sum(z3[i] * ysafe[i] for i in range(n))
        ones = jnp.ones_like(g22)
        return _chol3_solve((s1 * ones, g21, g22, g31, g32, g33),
                            (sy * ones, b2, b3))

    A = [Ar[k] for k in range(L)]
    B = [Br[k] for k in range(L)] if has_B else None
    nu = [nur[k] for k in range(L)]
    gamma0 = [omr[k] for k in range(L)]
    beta0 = [deltar[m] for m in range(3)]
    mu = [mur[m] for m in range(3)]
    zero = jnp.zeros((rows, _LANE), dtype=ft)

    def step(t, carry):
        gamma, beta, ewma, count, loss = carry
        obs_s = maskr[t, 0] > 0.5
        con_s = maskr[t, 1] > 0.5
        y = [datar[t, i] for i in range(n)]
        fin0 = jnp.isfinite(y[0])
        all_fin = fin0
        for i in range(1, n):
            all_fin = jnp.logical_and(all_fin, jnp.isfinite(y[i]))
        obs = jnp.logical_and(obs_s, fin0)   # reference checks y[1] only
        ysafe = [jnp.where(jnp.isfinite(y[i]), y[i], 0.0) for i in range(n)]
        sy = sum(ysafe)
        poison = jnp.where(jnp.logical_and(obs, jnp.logical_not(all_fin)),
                           jnp.full((), jnp.nan, dtype=ft),
                           jnp.ones((), dtype=ft))
        y_sums = (sy, jnp.asarray(float(n), dtype=ft))

        z2, z3, aux = build_Z(gamma)
        b_ols = ols(z2, z3, ysafe, y_sums)
        grad = score(gamma, z2, z3, aux, b_ols, ysafe)

        if scale_grad:
            ffc = jnp.asarray(ff, dtype=ft)
            new_count = count + 1.0
            denom = 1.0 - jnp.power(ffc, new_count)
            eps = jnp.asarray(jnp.finfo(ft).eps, dtype=ft)
            new_ewma = [ffc * ewma[k] + (1.0 - ffc) * grad[k] * grad[k]
                        for k in range(L)]
            upd = [gamma[k] + grad[k] / (jnp.sqrt(new_ewma[k] / denom) + eps)
                   * A[k] for k in range(L)]
            ewma = [jnp.where(obs, new_ewma[k], ewma[k]) for k in range(L)]
            count = jnp.where(obs, new_count, count)
        else:
            upd = [gamma[k] + grad[k] * A[k] for k in range(L)]
        gamma_obs = [jnp.where(obs, upd[k], gamma[k]) for k in range(L)]

        z2u, z3u, _ = build_Z(gamma_obs)
        b_re = ols(z2u, z3u, ysafe, y_sums)
        beta_obs = [jnp.where(obs, b_re[m], beta[m]) * poison for m in range(3)]

        if has_B:
            gamma_next = [nu[k] + B[k] * gamma_obs[k] for k in range(L)]
            z2n, z3n, _ = build_Z(gamma_next)
        else:
            gamma_next = gamma_obs
            z2n, z3n = z2u, z3u  # == loadings(γ_next); exact (see module doc)
            # on missing steps γ is unchanged so the rebuild equals the carry
            z2n = [jnp.where(obs, z2u[i], z2[i]) for i in range(n)]
            z3n = [jnp.where(obs, z3u[i], z3[i]) for i in range(n)]
        beta_next = [mu[m] + sum(phir[m * 3 + k] * beta_obs[k]
                                 for k in range(3)) for m in range(3)]

        # contribution at t: −‖y_{t+1} − ŷ_t‖² (window_contributions)
        sq = zero
        for i in range(n):
            y_nx = datar[t + 1, i]
            pv = y_nx - (beta_next[0] + beta_next[1] * z2n[i]
                         + beta_next[2] * z3n[i])
            sq = sq + pv * pv
        loss = loss + jnp.where(con_s, -sq, zero)
        return gamma_next, beta_next, ewma, count, loss

    ewma0 = [zero] * L if scale_grad else [zero]
    init = (gamma0, beta0, ewma0, jnp.zeros((), dtype=ft), zero)
    _, _, _, _, loss = jax.lax.fori_loop(0, T - 1, step, init)
    outr[...] = loss


def batched_loss(spec: ModelSpec, params_batch, data, start=0, end=None,
                 interpret: bool | None = None, tile_rows: int = _SUB):
    """Score-driven loss for a batch of draws — fused Pallas kernel.

    Numerically equivalent to ``vmap(score_driven.get_loss)`` (K = 1) for the
    MSED families: ``msed_lambda`` and ``msed_neural`` (both transform
    variants), plain and EWMA-scaled updates, AR(1) and random-walk γ
    dynamics.  Loss = mean one-step-ahead −MSE over the window, −Inf
    sentinel on non-finite paths, exactly as there.
    """
    if spec.family not in ("msed_lambda", "msed_neural"):
        raise ValueError(f"pallas ssd kernel supports the MSED families, "
                         f"not {spec.family!r}")
    if not spec.detach_inner_beta:
        # the hand-derived score treats β̄ as a constant — exactly the
        # reference's ForwardDiff.value detach.  The exact-AD variant
        # (detach_inner_beta=False) differentiates through β(γ) and is a
        # DIFFERENT recursion; refuse rather than silently compute it wrong.
        raise ValueError("pallas ssd kernel implements the detached-β̄ score "
                         "(reference semantics); use the scan engine for "
                         "detach_inner_beta=False specs")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    ft = params_batch.dtype if interpret else jnp.float32
    params_batch = jnp.asarray(params_batch, dtype=ft)
    B = params_batch.shape[0]
    rows = tile_rows
    nb = -(-B // (rows * _LANE))
    N = spec.N
    T = data.shape[1]
    if end is None:
        end = T
    nobs = end - start

    mp = jax.vmap(partial(unpack_msed, spec))(params_batch)
    L = mp.omega.shape[1]
    has_B = mp.B is not None

    t_idx = jnp.arange(T)
    observed = ((t_idx >= start) & (t_idx < end)).astype(ft)
    contrib = ((t_idx >= start) & (t_idx <= end - 2)).astype(ft)
    masks = jnp.stack([observed, contrib], axis=1)  # (T, 2)

    Bv = mp.B if has_B else jnp.zeros_like(mp.omega)
    args = [
        _lay(mp.A.astype(ft), B, nb, rows),        # (L, ...)
        _lay(Bv.astype(ft), B, nb, rows),          # (L, ...)
        _lay(mp.nu.astype(ft) if mp.nu is not None else
             jnp.zeros_like(Bv).astype(ft), B, nb, rows),
        _lay(mp.omega.astype(ft), B, nb, rows),
        _lay(mp.delta.astype(ft), B, nb, rows),
        _lay(mp.mu.astype(ft), B, nb, rows),
        _lay(mp.Phi.astype(ft), B, nb, rows),      # (9, ...)
        jnp.asarray(data, dtype=ft).T,             # (T, N) shared
        masks,                                     # (T, 2) shared
    ]

    def tile_spec(D):
        return pl.BlockSpec((D, rows, _LANE), lambda g: (0, g, 0),
                            memory_space=pltpu.VMEM)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    spec_tuple = (N, L, spec.family, bool(spec.transform_bool),
                  bool(spec.scale_grad), has_B,
                  float(spec.forget_factor or 0.0),
                  tuple(float(m) for m in spec.maturities))
    out = pl.pallas_call(
        partial(_kernel, spec_tuple, T, rows),
        grid=(nb,),
        in_specs=[tile_spec(L), tile_spec(L), tile_spec(L), tile_spec(L),
                  tile_spec(3), tile_spec(3), tile_spec(9), smem, smem],
        out_specs=pl.BlockSpec((rows, _LANE), lambda g: (g, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * rows, _LANE), ft),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    total = out.reshape(-1)[:B]
    loss = total / N / nobs
    return jnp.where(jnp.isfinite(loss), loss, -jnp.inf)
