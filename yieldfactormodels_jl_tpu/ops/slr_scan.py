"""Iterated square-root SLR (posterior-linearization) filter on the tree.

The associative-scan engine (ops/assoc_scan.py, docs/DESIGN.md §13) covers
the constant-Z Kalman families only — a state-dependent measurement breaks
the per-step element construction, so the nonlinear half of the model zoo
(the TVλ EKF lineage) stayed latency-bound on sequential ``lax.scan`` steps.
"Parallel square-root statistical linear regression for inference in
nonlinear state space models" (Yaghoobi et al., arXiv:2207.00426 — already
the engine's PSD-floor citation) gives the frame used here: freeze an affine
surrogate of the measurement around a *reference trajectory*, run the
now-linear filter as the same O(log T) associative combine, and iterate —
each sweep re-linearizes around the trajectory the previous sweep produced
(posterior linearization).  This module is that engine (docs/DESIGN.md §19),
as one tree pass plus K chunk-refinement sweeps:

- **Pass A — global coupling on the tree (once per evaluation).**
  :func:`_linearize_trajectory` turns the prediction-only reference
  trajectory (the constant unconditional-mean path — the stationary
  initialization is the transition's fixed point) into per-step affine
  measurements ``y_t ≈ Z_t x_t + d_t`` (first-order Taylor —
  ``kalman._tvl_measurement`` for TVλ; the ``config.SLR_ENGINES`` registry
  names the linearization rules, ``"ekf"`` first).  :func:`_tv_elements`
  builds all T per-step filtering elements at once — each step gets its own
  element, assembled WITHOUT any (T, N, N) innovation factorization: because
  Ω_obs is diagonal (σ²I, every model here), the Woodbury push-through
  Zᵀ(ZQZᵀ + R)⁻¹ = (I + ZᵀR⁻¹Z·Q)⁻¹ZᵀR⁻¹ reduces an element to ONE
  pivot-free Ms×Ms elimination (``assoc_scan._solve_unrolled`` — the same
  D = I + PSD·PSD class) plus batched tiny products, keeping the factored
  (I − QW)-forms where the textbook gain subtraction cancels.  The elements
  compose with the EXISTING machinery — ``assoc_scan._combine`` under the
  blocked prefix, or ``lax.associative_scan`` (the time-sharded
  ``"interleaved"`` schedule) — with the same ``psd_floor`` square-root
  stabilization surface.  One O(log T) pass conditions every chunk-entry
  state on ALL data before it.

- **Pass B — K sweeps of local exactness on the lanes.**  The composed
  moments are read at the T/L chunk boundaries only, and every chunk
  re-runs the TRUE nonlinear recursion — predict, linearize at the chunk's
  OWN predicted mean, sequential-observation update (the
  ``ops/univariate_kf.py`` algebra) — as an L-step scan whose every step is
  batched over all chunks (the exact shape of the blocked prefix's pass 1).
  Inside a chunk there is no surrogate error at all; the only error is the
  entry state, which the filter's own forgetting contracts by ρ^L ≈ 1e−4
  per sweep (ρ ≈ the per-step posterior memory).  Sweep k ≥ 2 takes its
  entries from sweep k−1's chunk-exit moments (Jacobi relaxation — chunk 0
  keeps the exact prior); the final sweep emits the exact per-step
  innovations (the loss) and filtered moments.

The sequential EKF is the fixed point of this map — it linearizes every
step at its own predicted mean — and the two-scale split is what makes a
STATIC K = 2 sweeps enough, where a pure whole-trajectory Picard iteration
needs O(1/(1−ρ)) sweeps (measured: the plain affine-sweep map contracts at
≈ρ per sweep through the weakly-identified λ channel; the chunked
refinement contracts boundary errors at ρ^L per sweep).  For T ≤ L one
chunk covers the panel and the refinement reproduces the sequential EKF to
float rounding in one sweep.  With K ≥ 2 the tree's entry states are
``stop_gradient``-ed: their influence on the output is ρ^((K−1)L)-damped,
so the adjoint of the (reverse-expensive) combine tree contributes below
engine tolerance — the single biggest lever in the engine's 8.5× T=20k
TVλ value+grad win (BASELINE round 10; the tree's reverse pass measured
~6× its forward wall; grad parity vs the sequential EKF is pinned at
~2e−7 for K = 2 and ~1e−11 for K = 3 in tests/test_slr_scan.py).
tests/test_slr_scan.py also pins the K-sweep gap
shrinking monotonically at an adversarially small chunk size and the
default engine at parity tolerance against
tests/oracle.iterated_slr_filter.

Everything else matches the assoc engine contract: differentiable
end-to-end, −Inf sentinels with the taxonomy bitmask channel
(:func:`get_loss_coded`), the skip-first loss convention, whole-column NaN =
pure prediction element, and :func:`filter_and_loss` as the serving
re-filter primitive for TVλ snapshots (serving/online.py
``_jitted_refilter``).  Constant-measurement families collapse to one sweep
(the linearization cannot move), making this engine a strict superset of
the assoc construction.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..models import kalman as K
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax
from .assoc_scan import (
    _CHUNK,
    FilterElement,
    _bmm,
    _combine,
    _mv,
    _prefix_scan,
    _psd_project,
    _solve_unrolled,
)

_LOG_2PI = math.log(2.0 * math.pi)

#: the standard no-recompile regression idiom (config.make_trace_counter):
#: ``_note_trace`` runs once per (re)trace of the sweep stack, so the tests
#: can pin that repeated same-shape calls reuse one program and that each
#: distinct (sweeps, chunk, prefix) traces its own
from .. import config as _config  # noqa: E402  (after the jax imports above)

trace_counts, _note_trace, reset_trace_counts = _config.make_trace_counter()

#: default refinement sweep count K.  Sweep 1 refines every chunk exactly
#: from the tree's globally-coupled entry states; sweep 2 repeats from
#: sweep 1's chunk exits.  Boundary errors contract at ρ^L per sweep (the
#: filter's own L-step forgetting), so two sweeps sit at parity tolerance
#: against the sequential EKF on the oracle points (loss ≈ 2e−7, grad
#: ≈ 2e−7 relative; K = 3 reaches ≈ 1e−11) — raise per call to tighten the
#: fixed point (K is static; each value traces its own program).
DEFAULT_SWEEPS = 2


def _resolve_linearization(name: str | None) -> str:
    """Validate an SLR linearization-rule name against the registry
    (``config.SLR_ENGINES`` — oracle-backed like every engine registry,
    graftlint YFM007)."""
    from .. import config

    name = name or config.SLR_ENGINES[0]
    if name not in config.SLR_ENGINES:
        raise ValueError(f"unknown SLR linearization {name!r}; pick from "
                         f"{config.SLR_ENGINES}")
    return name


def _chol_unrolled(P):
    """Lower Cholesky factor of (…, Ms, Ms) SPD matrices, unrolled over the
    static tiny state dimension — the factorization twin of
    ``assoc_scan._solve_unrolled``: pure broadcast arithmetic that vectorizes
    over the chunk batch, where ``jnp.linalg.cholesky`` would lower to
    per-matrix LAPACK dispatch on CPU and a lane-hostile loop on TPU.  A
    non-PD input goes NaN through the sqrt and lands in the engine's −Inf
    sentinel + STATE_EXPLODED taxonomy like every other breakdown (the
    ``psd_floor`` recovery surface projects entry moments before they get
    here)."""
    Ms = P.shape[-1]
    rows: list = [[None] * Ms for _ in range(Ms)]
    for j in range(Ms):
        s = P[..., j, j]
        for k in range(j):
            s = s - rows[j][k] * rows[j][k]
        diag = jnp.sqrt(s)
        rows[j][j] = diag
        for i in range(j + 1, Ms):
            t = P[..., i, j]
            for k in range(j):
                t = t - rows[i][k] * rows[j][k]
            rows[i][j] = t / diag
    zero = jnp.zeros_like(P[..., 0, 0])
    return jnp.stack(
        [jnp.stack([rows[i][j] if j <= i else zero for j in range(Ms)],
                   axis=-1) for i in range(Ms)], axis=-2)


def _tri_solve_right_unrolled(B, Lc):
    """Solve X·L = B for lower-triangular ``Lc`` (…, Ms, Ms) and
    B (…, N, Ms) by unrolled back-substitution over the static columns —
    same no-dispatch rationale as :func:`_chol_unrolled` (the sigma-point
    regression slope needs ``D_hᵀ L⁻¹``, never an explicit inverse)."""
    Ms = Lc.shape[-1]
    cols: list = [None] * Ms
    for j in range(Ms - 1, -1, -1):
        t = B[..., j]
        for k in range(j + 1, Ms):
            t = t - cols[k] * Lc[..., k, j][..., None]
        cols[j] = t / Lc[..., j, j][..., None]
    return jnp.stack(cols, axis=-1)


def _tvl_h_lanes(spec: ModelSpec, chi, mats):
    """TVλ measurement h(β) evaluated at sigma points ``chi`` (…, Ms, S)
    with the point axis TRAILING (the lane rule: S = 2·Ms+1 rides the TPU
    lane dimension) → ŷ (…, N, S).  Restates ``kalman._tvl_measurement``'s
    ŷ half (kalman/filter.jl:31-47) through the shared loadings helpers
    (``dns_lambda``/``dns_slope_curvature``) so the decay-floor and NS
    shapes cannot drift; no Jacobian — the sigma-point rule replaces it."""
    from ..models.loadings import dns_lambda, dns_slope_curvature

    lam = dns_lambda(chi[..., 3, :])                        # (…, S)
    z2, z3 = dns_slope_curvature(lam[..., None, :], mats[:, None])
    return (chi[..., 0:1, :] + z2 * chi[..., 1:2, :]
            + z3 * chi[..., 2:3, :])                        # (…, N, S)


def _sigma_linearize(spec: ModelSpec, m, P, mats):
    """Statistical (sigma-point) linearization of the TVλ measurement at
    (m (…, Ms), P (…, Ms, Ms)): the ``"ukf"`` rule of ``config.SLR_ENGINES``.

    Unscented cubature with κ = 1 (c = Ms+1): χ₀ = m,
    χᵢ± = m ± √c·L·eᵢ with P = LLᵀ, weights w₀ = 1/c, wᵢ = 1/(2c) — all
    positive for every Ms here (the classic κ = 3−Ms goes negative at
    Ms ≥ 4, which would break the PSD reading of the SLR moments).  The SLR
    regression slope collapses to a triangular solve:
    Ψ = Σ wᵢ (χᵢ−m)(h(χᵢ)−μ)ᵀ = L·(√c·wᵢ·D_h) with D_h rows h(χᵢ⁺)−h(χᵢ⁻),
    so Z = Ψᵀ P⁻¹ = D_hᵀ L⁻¹ / (2√c) and d = μ − Z m.  DELIBERATE
    divergence from the full sigma-point filter: the SLR residual
    covariance Ω = E[(h−Zx−d)(·)ᵀ] is OMITTED from the observation noise —
    keeping R diagonal is what lets the Woodbury element assembly and the
    sequential-observation update stay pivot-free (the module contract);
    the oracle (tests/oracle.py sigma-point loops) defines the identical
    semantics, so the sequential fixed point is the statistically
    linearized filter with unmodified R.  Returns (Z (…, N, Ms),
    d (…, N), μ (…, N))."""
    Ms = m.shape[-1]
    c = float(Ms + 1)
    scale = math.sqrt(c)
    Lc = _chol_unrolled(P)
    offs = jnp.concatenate(
        [jnp.zeros_like(Lc[..., :, :1]), scale * Lc, -scale * Lc], axis=-1)
    chi = m[..., :, None] + offs                            # (…, Ms, S)
    h = _tvl_h_lanes(spec, chi, mats)                       # (…, N, S)
    h0 = h[..., 0]
    hp = h[..., 1:Ms + 1]
    hm = h[..., Ms + 1:]
    mu = h0 / c + jnp.sum(hp + hm, axis=-1) / (2.0 * c)
    Z = _tri_solve_right_unrolled((hp - hm) / (2.0 * scale), Lc)
    d = mu - _mv(Z, m)
    return Z, d, mu


def _resolve_sweeps(spec: ModelSpec, sweeps: int | None) -> int:
    """K for a family: constant-measurement families are their own fixed
    point after one sweep (the linearization cannot move), so extra sweeps
    would re-compose identical elements."""
    K_sweeps = DEFAULT_SWEEPS if sweeps is None else int(sweeps)
    if K_sweeps < 1:
        raise ValueError(f"sweeps must be >= 1, got {K_sweeps}")
    return 1 if spec.has_constant_measurement else K_sweeps


def _linearize_trajectory(spec: ModelSpec, kp, beta_bar, dtype,
                          rule: str = "ekf", P_bar=None):
    """(Z_all (T, N, Ms), d_all (T, N)) — the affine measurement surrogate
    y_t ≈ Z_t x_t + d_t linearized at the reference trajectory ``beta_bar``
    (T, Ms).  For the TVλ EKF family the ``rule`` (``config.SLR_ENGINES``)
    picks the surrogate: ``"ekf"`` takes the analytic Jacobian at β̄_t
    (``kalman._tvl_measurement`` — the single source of truth the sequential
    engines use) with d_t = h(β̄_t) − Z_t β̄_t; ``"ukf"`` statistically
    linearizes at (β̄, ``P_bar``) — the stationary predicted covariance,
    constant like the mean reference, so one sigma-point regression
    broadcasts over T (:func:`_sigma_linearize`).  Constant-Z families
    broadcast their loadings (the reference point is ignored; an affine h
    is its own statistical linearization, so the rule is moot there)."""
    T = beta_bar.shape[0]
    mfn = K.state_measurement(spec)
    if mfn is not None:
        mats = spec.maturities_array
        if rule == "ukf":
            if spec.family != "kalman_tvl":
                # the sigma-point lanes (_tvl_h_lanes) are hand-laid for the
                # TVλ h; state-dependent program measurements linearize by AD
                raise ValueError(
                    "the 'ukf' linearization rule is TVλ-specific; "
                    f"family {spec.family!r} uses 'ekf'")
            Z1, d1, _ = _sigma_linearize(spec, beta_bar[0], P_bar, mats)
            return (jnp.broadcast_to(Z1, (T,) + Z1.shape),
                    jnp.broadcast_to(d1, (T,) + d1.shape))
        Z_all, y_pred = jax.vmap(lambda b: mfn(b, mats))(beta_bar)
        d_all = y_pred - _mv(Z_all, beta_bar)
        return Z_all, d_all
    Z, d = K.measurement_setup(spec, kp, dtype)
    if Z is None:
        raise ValueError(
            f"family {spec.family!r} has no SLR measurement linearization")
    if d is None:
        d = jnp.zeros((spec.N,), dtype=dtype)
    return (jnp.broadcast_to(Z, (T,) + Z.shape),
            jnp.broadcast_to(d, (T,) + d.shape))


def _tv_elements(Z_all, d_all, Phi, delta, Q, obs_var, m0, P0, data,
                 observed):
    """Per-step filtering elements for a TIME-VARYING affine measurement.

    The constant-Z construction (``assoc_scan._elements``) builds one
    generic element and broadcasts; here each step owns a (Z_t, d_t) pair,
    and every per-step quantity is assembled through the diagonal-R Woodbury
    push-through  Zᵀ S⁻¹ = (I + Λ Q)⁻¹ ZᵀR⁻¹  with Λ = ZᵀR⁻¹Z, S = ZQZᵀ+R:

        W_t = (I + Λ_t Q)⁻¹ Λ_t        (= Zᵀ S⁻¹ Z)
        w_t = (I + Λ_t Q)⁻¹ ι_t        (= Zᵀ S⁻¹ resid_t),  ι = ZᵀR⁻¹resid

        A_t = (I − Q W_t) Φ            b_t = δ + Q w_t
        C_t = (I − Q W_t) Q            J_t = Φᵀ W_t Φ       η_t = Φᵀ w_t

    — one pivot-free Ms×Ms elimination per step (batched over all T) and
    tiny-matmul assembly, never an (N, N) factorization.  Steps with any NaN
    element become pure prediction elements; step 0 is the exact update from
    the prior (m0, P0) with A₀ = 0 (same overwrite as the constant-Z form).
    """
    T, N, Ms = Z_all.shape
    dtype = Z_all.dtype
    I = jnp.eye(Ms, dtype=dtype)
    y = jnp.where(jnp.isfinite(data.T), data.T, 0.0)          # (T, N)
    obs = observed & jnp.all(jnp.isfinite(data.T), axis=1)
    obs_f = obs.astype(dtype)[:, None]

    resid = y - (_mv(Z_all, delta) + d_all)
    Zt = Z_all.swapaxes(-1, -2)                               # (T, Ms, N)
    Lam = _bmm(Zt, Z_all) / obs_var                           # ZᵀR⁻¹Z
    iota = _mv(Zt, resid) / obs_var                           # ZᵀR⁻¹resid
    D = I + _bmm(Lam, Q)
    sol = _solve_unrolled(D, jnp.concatenate([Lam, iota[..., None]], axis=-1))
    W = sol[..., :, :Ms]                                      # Zᵀ S⁻¹ Z
    w = sol[..., :, Ms]                                       # Zᵀ S⁻¹ resid
    IQW = I - _bmm(Q, W)                                      # (T, Ms, Ms)
    A_g = _bmm(IQW, Phi)
    C_g = _bmm(IQW, Q)
    C_g = 0.5 * (C_g + C_g.swapaxes(-1, -2))
    b_g = delta[None, :] + _mv(Q, w)
    J_g = _bmm(_bmm(Phi.T, W), Phi)                           # Φᵀ W Φ
    eta_g = _mv(Phi.T, w)                                     # Φᵀ w

    # first element: exact update from the prior (m0, P0), A₁ = 0
    mpred1 = Phi @ m0 + delta
    Ppred1 = Phi @ P0 @ Phi.T + Q
    resid1 = y[0] - (Z_all[0] @ mpred1 + d_all[0])
    Lam1 = Z_all[0].T @ Z_all[0] / obs_var
    iota1 = Z_all[0].T @ resid1 / obs_var
    sol1 = _solve_unrolled(
        I + Lam1 @ Ppred1,
        jnp.concatenate([Lam1, iota1[:, None]], axis=-1))
    b_1 = mpred1 + Ppred1 @ sol1[:, Ms]
    C_1 = (I - Ppred1 @ sol1[:, :Ms]) @ Ppred1
    C_1 = 0.5 * (C_1 + C_1.T)

    # assemble (T, ...) with missing steps as pure prediction elements
    A = jnp.where(obs_f[:, :, None], A_g, Phi[None])
    b = jnp.where(obs_f, b_g, delta[None, :])
    C = jnp.where(obs_f[:, :, None], C_g, Q[None])
    J = jnp.where(obs_f[:, :, None], J_g, jnp.zeros_like(J_g))
    eta = jnp.where(obs_f, eta_g, jnp.zeros_like(eta_g))

    A = A.at[0].set(jnp.where(obs[0], jnp.zeros_like(Phi), Phi))
    b = b.at[0].set(jnp.where(obs[0], b_1, mpred1))
    C = C.at[0].set(jnp.where(obs[0], C_1, Ppred1))
    J = J.at[0].set(jnp.zeros_like(J_g[0]))
    eta = eta.at[0].set(jnp.zeros_like(eta_g[0]))
    return FilterElement(A, b, C, J, eta), obs


def _sweep_filter(elems, T: int, prefix: str):
    """Pass A's composition: (b (T, Ms), C (T, Ms, Ms)) filtered
    trajectories of the affine surrogate through the chosen combine
    schedule (same two schedules, same semantics as
    ``assoc_scan.filter_means_covs``)."""
    if prefix == "interleaved":
        out = lax.associative_scan(_combine, elems)
        return out.b, out.C
    return _prefix_scan(elems, T)


def _seq_update_batched(spec: ModelSpec, Z, y_eff, beta, P, obs_var):
    """Sequential-observation measurement update batched over the chunk
    axis: the ``univariate_kf._sequential_update`` algebra with a leading
    (C,) batch and per-chunk measurement rows.  Returns
    (β⁺ (C, Ms), P⁺ (C, Ms, Ms), ll (C,), ok (C,), code (C,))."""
    N = spec.N

    def body(carry, zi_yi):
        b, Pm, ll, ok, code = carry
        z, y_i = zi_yi                               # (C, Ms), (C,)
        zP = _mv(Pm, z)
        f = jnp.sum(zP * z, axis=-1) + obs_var
        f_fin = jnp.isfinite(f)
        ok = ok & (f > 0) & f_fin
        code = code | tax.bit(f_fin & (f <= 0), tax.NONPSD_INNOVATION) \
            | tax.bit(~f_fin, tax.STATE_EXPLODED)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y_i - jnp.sum(z * b, axis=-1)
        Kg = zP / fsafe[:, None]
        b = b + Kg * v[:, None]
        Pm = Pm - Kg[:, :, None] * zP[:, None, :]
        ll = ll - 0.5 * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b, Pm, ll, ok, code), None

    Cb = beta.shape[0]
    zero = jnp.zeros((Cb,), dtype=P.dtype)
    (beta_u, P_u, ll, ok, code), _ = lax.scan(
        body,
        (beta, P, zero, jnp.ones((Cb,), bool),
         jnp.zeros((Cb,), dtype=tax.CODE_DTYPE)),
        (Z.swapaxes(0, 1), y_eff.T), length=N)
    P_u = 0.5 * (P_u + P_u.swapaxes(-1, -2))
    return beta_u, P_u, ll, ok, code


def _chunked_refine(spec: ModelSpec, kp, data_p, observed_p, entry_m,
                    entry_P, L: int, Cn: int, rule: str = "ekf"):
    """Pass B: exact nonlinear re-propagation within chunks, batched over
    the chunk axis.

    ``entry_m`` (C, Ms) / ``entry_P`` (C, Ms, Ms) are each chunk's FILTERED
    moments at the last pre-chunk step (chunk 0 gets the stationary prior,
    for which predict is a no-op — identical to the sequential engines'
    start).  Every scan step predicts, linearizes at the chunk's own
    predicted moments — ``rule`` "ekf": first-order at the predicted mean
    (``kalman._tvl_measurement``, the exact EKF recursion); "ukf":
    sigma-point statistical linearization at the predicted (mean,
    covariance) pair (:func:`_sigma_linearize`, the exact statistically
    linearized recursion) — and applies the sequential-observation update;
    all C chunks advance in lanes.  Returns per-step ``(beta_pred, m_filt,
    P_filt, ll, obs, code)`` stacked back to (C·L, ...) time order —
    ``ll`` in the per-step joint convention (0 unobserved, −Inf on a failed
    innovation chain).
    """
    dtype = entry_m.dtype
    N = spec.N
    mats = spec.maturities_array
    Z_const, d_const = K.measurement_setup(spec, kp, dtype)
    mfn = K.state_measurement(spec)
    if Z_const is not None and d_const is None:
        d_const = jnp.zeros((N,), dtype=dtype)
    y_cl = data_p.T.reshape(Cn, L, N).swapaxes(0, 1)          # (L, C, N)
    obs_cl = observed_p.reshape(Cn, L).swapaxes(0, 1)         # (L, C)

    def step(carry, inp):
        b, P = carry                                          # filtered t−1
        y, obs_t = inp
        b = kp.delta[None] + b @ kp.Phi.T                     # predict
        P = _bmm(_bmm(kp.Phi, P), kp.Phi.T) + kp.Omega_state
        if spec.family == "kalman_tvl" and rule == "ukf":
            Z, d_sig, mu_h = _sigma_linearize(spec, b, P, mats)
            # same fixed-linearization effective-observation trick as the
            # EKF branch: v_i = y_eff_i − z_iᵀb = y_i − μ_i, the innovation
            # against the sigma-point predicted measurement mean
            ysafe = jnp.where(jnp.isfinite(y), y, mu_h)
            y_eff = ysafe - d_sig
        elif mfn is not None:
            Z, y_hat = jax.vmap(lambda bb: mfn(bb, mats))(b)
            # fixed-linearization effective observation (the univariate
            # engine's EKF trick): v_i = y_eff_i − z_iᵀb reproduces the
            # joint EKF update with Z carrying the Jacobian column
            ysafe = jnp.where(jnp.isfinite(y), y, y_hat)
            y_eff = ysafe - y_hat + _mv(Z, b)
        else:
            Z = jnp.broadcast_to(Z_const, (b.shape[0],) + Z_const.shape)
            ysafe = jnp.where(jnp.isfinite(y), y,
                              b @ Z_const.T + d_const[None])
            y_eff = ysafe - d_const[None]
        obs = obs_t & jnp.all(jnp.isfinite(y), axis=-1)       # (C,)
        b_u, P_u, ll, ok, code = _seq_update_batched(spec, Z, y_eff, b, P,
                                                     kp.obs_var)
        obs_f = obs.astype(dtype)
        b_m = b + (b_u - b) * obs_f[:, None]
        P_m = P + (P_u - P) * obs_f[:, None, None]
        ll_out = jnp.where(obs & ok, ll, jnp.where(obs, -jnp.inf, 0.0))
        code_out = jnp.where(obs, code, jnp.int32(0))
        return (b_m, P_m), (b, b_m, P_m, ll_out, obs, code_out)

    _, outs = lax.scan(step, (entry_m, entry_P), (y_cl, obs_cl))
    # (L, C, ...) → (C·L, ...) time order
    return tuple(
        jnp.swapaxes(o, 0, 1).reshape((Cn * L,) + o.shape[2:]) for o in outs)


def _filter_sweeps(spec: ModelSpec, params, data, start, end, psd_floor,
                   prefix: str, sweeps: int | None,
                   linearization: str | None, chunk: int | None):
    """The iterated two-pass forward sweep shared by every consumer.

    Returns ``(m, P, ll_t, obs, codes, kp)`` with ``(m, P)`` the final
    sweep's exact-chunk filtered trajectories (length T) and ``ll_t`` the
    exact per-step loglik contributions in the joint convention — at the
    fixed point the sequential EKF's, step for step.
    """
    if prefix not in ("blocked", "interleaved"):
        raise ValueError(f"unknown prefix schedule {prefix!r}; pick from "
                         f"('blocked', 'interleaved')")
    if not spec.is_kalman:
        from .. import config

        raise ValueError(
            f"the slr engine needs a Kalman family; "
            f"config.engines_for({spec.family!r}) = {config.engines_for(spec)}")
    rule = _resolve_linearization(linearization)
    _note_trace("slr_filter")
    K_sweeps = _resolve_sweeps(spec, sweeps)
    kp = unpack_kalman(spec, params)
    dtype = kp.Phi.dtype
    state0 = K.init_state(spec, kp)
    T = data.shape[1]
    if end is None:
        end = T
    t_idx = jnp.arange(T)
    observed = (t_idx >= start) & (t_idx < end)
    P0 = state0.P if psd_floor is None else _psd_project(
        jnp.where(jnp.isfinite(state0.P), state0.P, 0.0), psd_floor)

    L = min(_CHUNK if chunk is None else int(chunk), T)
    if L < 1:
        raise ValueError(f"chunk must be >= 1, got {L}")
    Cn = -(-T // L)
    pad = Cn * L - T
    data_p = data if not pad else jnp.concatenate(
        [data, jnp.full(data.shape[:1] + (pad,), jnp.nan, dtype=data.dtype)],
        axis=1)
    observed_p = observed if not pad else jnp.concatenate(
        [observed, jnp.zeros((pad,), bool)])
    bidx = jnp.arange(1, Cn) * L - 1      # chunk-entry steps (filtered at)

    # pass A (once per evaluation) — the prediction-only reference.  The
    # stationary initialization is the transition's fixed point, so the
    # reference is the constant unconditional-mean path: no sequential walk
    # anywhere.  The composed tree conditions every chunk-entry state on ALL
    # data before it in one O(log T) pass — the global coupling that a pure
    # chunk relaxation lacks (information would otherwise cross one chunk
    # boundary per sweep, which stalls exactly where the filter forgets
    # slowly: long missing stretches, near-unit persistence).
    mpred1 = kp.Phi @ state0.beta + kp.delta
    Ppred1 = _bmm(_bmm(kp.Phi, P0), kp.Phi.T) + kp.Omega_state
    beta_bar = jnp.broadcast_to(mpred1, (T,) + mpred1.shape)
    Z_all, d_all = _linearize_trajectory(spec, kp, beta_bar, dtype,
                                         rule=rule, P_bar=Ppred1)
    elems, _ = _tv_elements(Z_all, d_all, kp.Phi, kp.delta,
                            kp.Omega_state, kp.obs_var, state0.beta,
                            P0, data, observed)
    m_aff, P_aff = _sweep_filter(elems, T, prefix)
    if psd_floor is not None:
        P_aff = _psd_project(P_aff, psd_floor)
    entry_m = jnp.concatenate([state0.beta[None], m_aff[bidx]], axis=0)
    entry_P = jnp.concatenate([P0[None], P_aff[bidx]], axis=0)
    if K_sweeps > 1:
        # With two or more refinement sweeps the tree only seeds entry
        # states whose influence on the output is ρ^((K−1)·L)-damped (each
        # sweep's in-chunk forgetting), so its adjoint contributes below
        # engine tolerance — cutting it here removes the single most
        # expensive reverse pass (measured ~6× the tree's forward wall)
        # while the value path keeps the full composition.  K = 1 (the
        # constant-Z collapse) keeps the tree differentiated: its entries
        # feed the output directly.  Grad parity vs the sequential EKF is
        # pinned in tests/test_slr_scan.py.
        entry_m = lax.stop_gradient(entry_m)
        entry_P = lax.stop_gradient(entry_P)

    m = P = ll_t = obs = codes = None
    exit_idx = jnp.arange(Cn) * L + (L - 1)
    for k in range(K_sweeps):
        if k > 0:
            # Jacobi relaxation: this sweep's entries are the PREVIOUS
            # sweep's chunk-exit filtered moments, shifted one chunk right
            # (chunk 0 keeps the exact prior).  Each sweep contracts the
            # remaining boundary error by the chunk's own forgetting ρ^L.
            entry_m = jnp.concatenate(
                [state0.beta[None], m[exit_idx[:-1]]], axis=0)
            entry_P = jnp.concatenate([P0[None], P[exit_idx[:-1]]], axis=0)
            if psd_floor is not None:
                entry_P = _psd_project(entry_P, psd_floor)
        # pass B — exact within-chunk re-propagation: predict, linearize at
        # the chunk's own predicted mean, sequential-observation update
        _, m, P, ll_t, obs, codes = _chunked_refine(
            spec, kp, data_p, observed_p, entry_m, entry_P, L, Cn, rule)
    return m[:T], P[:T], ll_t[:T], obs[:T], codes[:T], kp


def filter_means_covs(spec: ModelSpec, params, data, start=0, end=None,
                      psd_floor=None, prefix: str = "blocked",
                      sweeps: int | None = None,
                      linearization: str | None = None,
                      chunk: int | None = None):
    """Filtered means/covariances for every t via the iterated two-pass
    sweep: (m (T, Ms) = E[x_t | y_{1:t}], P (T, Ms, Ms)) — the sequential
    EKF's filtered moments at the fixed point.  ``psd_floor`` selects the
    square-root-stabilized recovery surface (entry moments PSD-projected
    through the same machinery as the assoc engine); ``prefix`` picks pass
    A's combine schedule (time-sharded callers pass ``"interleaved"``)."""
    m, P, _, _, _, _ = _filter_sweeps(spec, params, data, start, end,
                                      psd_floor, prefix, sweeps,
                                      linearization, chunk)
    return m, P


def _loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                psd_floor=None, prefix: str = "blocked",
                sweeps: int | None = None, linearization: str | None = None,
                chunk: int | None = None):
    """Shared loss pass: ``(loss, code, (m, P))`` from the final sweep's
    exact per-step innovations — same contribution mask, sentinel gating and
    taxonomy channel as every sequential engine."""
    m, P, ll_t, obs, codes, _ = _filter_sweeps(
        spec, params, data, start, end, psd_floor, prefix, sweeps,
        linearization, chunk)
    T = data.shape[1]
    if end is None:
        end = T
    contrib = K.loglik_contrib_mask(start, end, T)
    total = jnp.sum(jnp.where(contrib, ll_t, 0.0))
    loss = jnp.where(jnp.isfinite(total), total, -jnp.inf)
    code = tax.params_code(params) \
        | tax.combine(jnp.where(contrib, codes, jnp.int32(0))) \
        | tax.bit(~jnp.any(contrib & obs), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code, (m, P)


def get_loss(spec: ModelSpec, params, data, start=0, end=None,
             psd_floor=None, prefix: str = "blocked",
             sweeps: int | None = None, linearization: str | None = None,
             chunk: int | None = None):
    """Gaussian loglik of the K-sweep iterated-SLR filter at O(log T) span —
    converges to the sequential EKF likelihood (same skip-first convention)
    at ρ^L per sweep, differentiable end-to-end (the MLE cascade's
    nonlinear-tree engine).  ``psd_floor`` selects the stabilized recovery
    surface; leave ``None`` for the parity path."""
    loss, _, _ = _loss_coded(spec, params, data, start, end, psd_floor,
                             prefix, sweeps, linearization, chunk)
    return loss


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                   psd_floor=None, prefix: str = "blocked",
                   sweeps: int | None = None,
                   linearization: str | None = None,
                   chunk: int | None = None):
    """``(loss, code)`` — :func:`get_loss` plus its taxonomy bitmask, the
    same self-describing failure channel every other engine carries."""
    loss, code, _ = _loss_coded(spec, params, data, start, end, psd_floor,
                                prefix, sweeps, linearization, chunk)
    return loss, code


def filter_and_loss(spec: ModelSpec, params, data, start=0, end=None,
                    sweeps: int | None = None):
    """One iterated sweep stack, all three consumers: ``(m, P, loss, code)``
    with ``(m[t], P[t])`` the filtered moments — the serving re-filter
    primitive for TVλ snapshots (serving/online.py ``_jitted_refilter``),
    mirroring ``assoc_scan.filter_and_loss`` for the constant-Z families."""
    loss, code, (m, P) = _loss_coded(spec, params, data, start, end,
                                     sweeps=sweeps)
    return m, P, loss, code
