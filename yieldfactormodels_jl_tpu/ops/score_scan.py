"""Score-driven (MSED) recursion on the parallel-in-time tree.

The score-driven filter (models/score_driven.py, filter.jl:52-91) was the
last `MODEL_CODES` lineage pinned to a sequential ``lax.scan``: its state
update is a gradient recursion, not a Kalman step, so neither the
associative-scan elements (ops/assoc_scan.py) nor the SLR Woodbury elements
(ops/slr_scan.py) apply.  Statistical/posterior linearization is more
general than either: ANY state recursion x_t = f_t(x_{t−1}) admits a
per-step affine surrogate x_t ≈ J_t x_{t−1} + b_t, and affine maps compose
associatively — (J₂, b₂)∘(J₁, b₁) = (J₂J₁, J₂b₁ + b₂) — so the same
two-scale design that carried TVλ (arXiv:2207.00426 idea; docs/DESIGN.md
§19) carries the score recursion:

- **pass A** (once): linearize the TRUE per-step γ map — measurement update
  ``score_driven.plain_gamma_update`` (OLS β̄, analytic score, γ += A⊙score)
  composed with the transition γ ← ν + B⊙γ — around the STATIONARY reference
  ω (γ₀ = ω is the transition's fixed point, exactly like the SLR engine's
  unconditional-mean reference), one ``jacfwd`` vmapped over T.  Missing
  steps are exactly affine (diag(B), ν).  The composed prefix of these
  elements is the surrogate γ trajectory at O(log T) span.  β needs no
  surrogate at all: on observed steps the reference recursion fully RESETS
  β to the OLS fit (β_obs is independent of β_{t−1} — the same structural
  fact the closed-form (δ, Φ) solve in estimation/optimize.py exploits), so
  given the γ path the β recursion is EXACTLY affine per step — a second
  composed prefix, no approximation.
- **pass B** (K sweeps): re-run the TRUE recursion (``score_driven._step``,
  vmapped over the chunk axis) within length-L chunks seeded from the
  composed entry states, Jacobi-shifting entries to the previous sweep's
  chunk exits.  Boundary errors contract at ≈∏B per step (the recursion's
  own forgetting), so K = 2 sits at parity tolerance against the sequential
  scan; the final sweep's predictions feed the exact reference loss.

Applicability is ``spec.supports_score_tree`` (the plain γ update only —
the ``scale_grad`` EWMA lineage is not a small-state affine recursion), the
registry entry is ``config.MSED_ENGINES["score_tree"]``, and the engine
matrix seam is ``config.engines_for`` / ``tree_engine_for`` like every
other tree engine.  Same conventions as the siblings: −Inf sentinel +
taxonomy codes, trace-counter no-recompile pins, ``prefix="interleaved"``
for the time-sharded layout with the refinement chunk pinned to the shard
length (parallel/time_parallel.py), oracle parity against the independent
NumPy loops in tests/oracle.py (linearized_score_filter — never
JAX-vs-JAX).

One deliberate divergence from ops/slr_scan.py: the tree entries are NOT
``stop_gradient``-cut at K ≥ 2.  The SLR cut was a measured-cost call (the
Kalman combine tree's reverse pass dominated, and ρ^L forgetting makes the
cut adjoint negligible); here the tree is an L-dimensional affine compose
(L = 1 for msed_lambda) whose reverse pass is cheap, while the recursion's
forgetting ≈B^L is WEAK at realistic B → 1 — cutting would cost real
gradient accuracy for no measurable wall.  Grad parity vs the sequential
scan is pinned in tests/test_score_scan.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..models import score_driven as SD
from ..models.common import partial_nan_poison, window_contributions
from ..models.params import unpack_msed
from ..models.specs import ModelSpec
from ..ops.linalg import ols_solve
from ..robustness import taxonomy as tax
from .assoc_scan import _CHUNK, _bmm, _mv

from .. import config as _config  # noqa: E402  (after the jax imports above)

trace_counts, _note_trace, reset_trace_counts = _config.make_trace_counter()

#: default refinement sweep count K — same two-scale rationale as
#: slr_scan.DEFAULT_SWEEPS: sweep 1 refines every chunk exactly from the
#: tree's globally-coupled entries, sweep 2 repeats from sweep 1's exits,
#: and the remaining boundary error contracts at the recursion's own ≈B^L
#: per-chunk forgetting (K is static; each value traces its own program).
DEFAULT_SWEEPS = 2

#: default refinement chunk length L.  Larger than assoc/slr's ``_CHUNK``
#: (128) on purpose: the score recursion's per-chunk contraction is its own
#: forgetting ≈∏B ≈ B^L with B → 1 in practice (0.97^128 ≈ 0.02 but
#: 0.97^256 ≈ 4e-4), and the refinement step is tiny (OLS + analytic score,
#: no covariance algebra), so a longer chunk buys both accuracy AND wall —
#: measured on the 20k single-chain value+grad workload the L = 256 sweep
#: beats both L = 128 and L = 512 (the latter pays scan-length dispatch).
DEFAULT_CHUNK = 256


def _affine_combine(e1, e2):
    """Associative composition of affine maps applied in time order —
    ``e2 ∘ e1`` for elements (J, b) meaning x ↦ Jx + b: (J₂J₁, J₂b₁ + b₂).
    Broadcast-multiply-reduce matmuls (assoc_scan's ``_bmm``/``_mv``) so the
    combine vectorizes over any leading batch/tree layout."""
    J1, b1 = e1
    J2, b2 = e2
    return _bmm(J2, J1), _mv(J2, b1) + b2


def _affine_prefix(J, b, T: int, prefix: str):
    """Composed prefix STATES of the affine chain x_t = J_t x_{t−1} + b_t
    whose start state was absorbed into element 0 (J₀ = 0, b₀ = f₀(x₋₁)):
    every prefix then has zero slope, so the states are just the composed
    offsets — returns b(P_t) of shape (T, n).

    ``"blocked"`` mirrors ``assoc_scan._prefix_scan``'s three-pass schedule
    (chunk-local scan → associative scan over chunk totals → one batched
    apply that, like the assoc engine's, only needs the offset outputs);
    ``"interleaved"`` is one ``lax.associative_scan`` over time — the
    block-local schedule the time-sharded layout needs."""
    if prefix == "interleaved":
        _, states = lax.associative_scan(_affine_combine, (J, b), axis=0)
        return states
    n = J.shape[-1]
    L = min(_CHUNK, T)
    Cn = -(-T // L)
    pad = Cn * L - T
    if pad:  # identity elements: padding cannot move any real prefix
        eye = jnp.broadcast_to(jnp.eye(n, dtype=J.dtype),
                               (pad,) + J.shape[1:])
        J = jnp.concatenate([J, eye], axis=0)
        b = jnp.concatenate(
            [b, jnp.zeros((pad,) + b.shape[1:], dtype=b.dtype)], axis=0)
    Jc = J.reshape(Cn, L, n, n).swapaxes(0, 1)            # (L, C, n, n)
    bc = b.reshape(Cn, L, n).swapaxes(0, 1)               # (L, C, n)
    eyeC = jnp.broadcast_to(jnp.eye(n, dtype=J.dtype), (Cn, n, n))
    zeroC = jnp.zeros((Cn, n), dtype=b.dtype)

    def local(carry, e):
        out = _affine_combine(carry, e)
        return out, out

    (Jt, bt), (Jl, bl) = lax.scan(local, (eyeC, zeroC), (Jc, bc))
    # exclusive prefix over the chunk totals = each chunk's entry map
    Jg, bg = lax.associative_scan(_affine_combine, (Jt, bt), axis=0)
    bg = jnp.concatenate([zeroC[:1], bg[:-1]], axis=0)
    # apply: b(local ∘ entry) = J_local·b_entry + b_local (J never needed —
    # chunk 0's entry offset is the absorbed start state itself, 0 here)
    states = _mv(Jl, bg[None]) + bl                       # (L, C, n)
    return states.swapaxes(0, 1).reshape(Cn * L, n)[:T]


def _gamma_elements(spec: ModelSpec, mp, ysafe_T, obs):
    """Per-step affine surrogate (J_t (T, L, L), b_t (T, L)) of the TRUE
    post-transition γ map, linearized at the stationary reference ω — one
    vmapped ``jacfwd`` of exactly the recursion pass B re-runs
    (``plain_gamma_update`` + ``plain_gamma_transition``), so the surrogate
    and the refinement can never drift.  Missing steps come out EXACTLY
    affine (the map is ν + B⊙γ already); a non-finite score at a broken
    parameter point lands in the engine's −Inf sentinel downstream."""

    def fmap(g, y, o):
        g_obs, _ = SD.plain_gamma_update(spec, mp, g, y, o)
        return SD.plain_gamma_transition(mp, g_obs)

    def elem(y, o):
        J = jax.jacfwd(fmap)(mp.omega, y, o)
        return J, fmap(mp.omega, y, o) - _mv(J, mp.omega)

    return jax.vmap(elem)(ysafe_T, obs)


def _beta_elements(spec: ModelSpec, mp, gprev, data_T, obs):
    """Per-step EXACT affine elements (A_t (T, M, M), b_t (T, M)) of the β
    recursion given the composed γ path ``gprev`` (the pre-step states):
    observed steps reset β to the re-OLS fit — β_next = μ + Φ·(OLS·poison),
    slope 0 — and missing steps are the bare transition (Φ, μ).  The
    reference-parity partial-NaN poison taints exactly like the sequential
    step (NaN elements compose into NaN states → −Inf loss)."""
    dtype = gprev.dtype

    def elem(g, yraw, o):
        ysafe = jnp.where(jnp.isfinite(yraw), yraw, 0.0)
        poison = partial_nan_poison(yraw, o)
        g_obs, _ = SD.plain_gamma_update(spec, mp, g, ysafe, o)
        beta_reols = ols_solve(SD.loadings_fn(spec, g_obs), ysafe)
        of = o.astype(dtype)
        A = ((1.0 - of) * poison) * mp.Phi
        bvec = mp.mu + (of * poison) * (mp.Phi @ beta_reols)
        return A, bvec

    return jax.vmap(elem)(gprev, data_T, obs)


def _absorb_start(J, b, x0):
    """Fold the start state into element 0: b₀ ← J₀x₀ + b₀, J₀ ← 0 — after
    which every composed prefix offset IS the state (see _affine_prefix)."""
    b = b.at[0].set(b[0] + _mv(J[0], x0))
    return J.at[0].set(0.0), b


def _chunked_refine(spec: ModelSpec, mp, data_p, observed_p, entry_g,
                    entry_b, L: int, Cn: int):
    """Pass B: the TRUE score recursion (``score_driven._step`` — the
    sequential engine's own step, vmapped over the chunk axis) re-run within
    chunks from the composed entry states.  EWMA state enters zeroed — the
    ``supports_score_tree`` gate guarantees it is never read.  Returns
    per-step (pred, γ_next, β_next, code) stacked back to (C·L, ...) time
    order."""
    N = spec.N
    y_cl = data_p.T.reshape(Cn, L, N).swapaxes(0, 1)      # (L, C, N)
    obs_cl = observed_p.reshape(Cn, L).swapaxes(0, 1)     # (L, C)
    step_v = jax.vmap(lambda st, y, o: SD._step(spec, mp, st, y, o))
    st0 = SD.MSEDState(entry_g, entry_b, jnp.zeros_like(entry_g),
                       jnp.zeros((Cn,), dtype=jnp.int32))

    def body(st, inp):
        y, o = inp
        st2, out = step_v(st, y, o)
        return st2, (out["pred"], out["gamma"], out["beta"], out["code"])

    _, outs = lax.scan(body, st0, (y_cl, obs_cl))
    return tuple(
        jnp.swapaxes(o, 0, 1).reshape((Cn * L,) + o.shape[2:]) for o in outs)


def _resolve_sweeps(sweeps: int | None) -> int:
    K_sweeps = DEFAULT_SWEEPS if sweeps is None else int(sweeps)
    if K_sweeps < 1:
        raise ValueError(f"sweeps must be >= 1, got {K_sweeps}")
    return K_sweeps


def _filter_sweeps(spec: ModelSpec, params, data, start, end,
                   prefix: str, sweeps: int | None, chunk: int | None):
    """The iterated two-pass forward sweep: composed affine prefixes seed
    the chunk entries, K true-recursion sweeps refine.  Returns
    ``(preds, gammas, betas, codes)`` (each length T, time order) — at the
    fixed point the sequential scan's outputs, step for step."""
    if prefix not in ("blocked", "interleaved"):
        raise ValueError(f"unknown prefix schedule {prefix!r}; pick from "
                         f"('blocked', 'interleaved')")
    if not getattr(spec, "supports_score_tree", False):
        raise ValueError(
            f"the score_tree engine needs a plain-gradient score-driven "
            f"family (spec.supports_score_tree); "
            f"config.engines_for({spec.family!r}) = {_config.engines_for(spec)}")
    K_sweeps = _resolve_sweeps(sweeps)
    _note_trace("score_filter")
    mp = unpack_msed(spec, params)
    T = data.shape[1]
    if end is None:
        end = T
    t_idx = jnp.arange(T)
    in_win = (t_idx >= start) & (t_idx < end)
    obs = in_win & jnp.isfinite(data[0, :])   # filter.jl:53 convention
    data_T = data.T                                        # (T, N)
    ysafe_T = jnp.where(jnp.isfinite(data_T), data_T, 0.0)

    # pass A — composed affine surrogates (γ linearized at ω; β exact
    # given the γ path), both at O(log T) span
    Jg, bg = _gamma_elements(spec, mp, ysafe_T, obs)
    Jg, bg = _absorb_start(Jg, bg, mp.omega)
    gs = _affine_prefix(Jg, bg, T, prefix)                 # (T, L) post-step
    gprev = jnp.concatenate([mp.omega[None], gs[:-1]], axis=0)
    Jb, bb = _beta_elements(spec, mp, gprev, data_T, obs)
    Jb, bb = _absorb_start(Jb, bb, mp.delta)
    bs = _affine_prefix(Jb, bb, T, prefix)                 # (T, M) post-step

    L = min(DEFAULT_CHUNK if chunk is None else int(chunk), T)
    if L < 1:
        raise ValueError(f"chunk must be >= 1, got {L}")
    Cn = -(-T // L)
    pad = Cn * L - T
    data_p = data if not pad else jnp.concatenate(
        [data, jnp.full(data.shape[:1] + (pad,), jnp.nan, dtype=data.dtype)],
        axis=1)
    observed_p = in_win if not pad else jnp.concatenate(
        [in_win, jnp.zeros((pad,), bool)])
    bidx = jnp.arange(1, Cn) * L - 1       # chunk-entry steps (post-step at)
    entry_g = jnp.concatenate([mp.omega[None], gs[bidx]], axis=0)
    entry_b = jnp.concatenate([mp.delta[None], bs[bidx]], axis=0)

    preds = gammas = betas = codes = None
    exit_idx = jnp.arange(Cn) * L + (L - 1)
    for k in range(K_sweeps):
        if k > 0:
            # Jacobi relaxation, same schedule as the SLR engine: entries
            # are the previous sweep's chunk exits, shifted one chunk right
            # (chunk 0 keeps the exact start state); each sweep contracts
            # boundary error by the chunk's own ≈B^L forgetting
            entry_g = jnp.concatenate(
                [mp.omega[None], gammas[exit_idx[:-1]]], axis=0)
            entry_b = jnp.concatenate(
                [mp.delta[None], betas[exit_idx[:-1]]], axis=0)
        preds, gammas, betas, codes = _chunked_refine(
            spec, mp, data_p, observed_p, entry_g, entry_b, L, Cn)
    return preds[:T], gammas[:T], betas[:T], codes[:T]


def _loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                prefix: str = "blocked", sweeps: int | None = None,
                chunk: int | None = None):
    """Shared loss pass ``(loss, code, (gammas, betas))`` — the exact
    reference loss (one-step-ahead forecast MSE over the contribution
    window, normalized by N·nobs) on the final sweep's predictions, with
    the same −Inf sentinel and taxonomy channel as the sequential engine
    (``score_driven.get_loss_coded``)."""
    preds, gammas, betas, codes = _filter_sweeps(spec, params, data, start,
                                                 end, prefix, sweeps, chunk)
    T = data.shape[1]
    if end is None:
        end = T
    nobs = end - start
    total = jnp.sum(window_contributions(preds, data, start, end))
    loss = total / spec.N / nobs
    loss = jnp.where(jnp.isfinite(loss), loss, -jnp.inf)
    t_idx = jnp.arange(T)
    in_win = (t_idx >= start) & (t_idx < end)
    observed = in_win & jnp.isfinite(data[0, :])
    code = tax.params_code(params) \
        | tax.combine(jnp.where(in_win, codes, jnp.int32(0))) \
        | tax.bit(~jnp.any(observed), tax.MISSING_ALL_OBS)
    code = code | tax.bit(~jnp.isfinite(loss) & (code == 0),
                          tax.STATE_EXPLODED)
    return loss, code, (gammas, betas)


def get_loss(spec: ModelSpec, params, data, start=0, end=None,
             prefix: str = "blocked", sweeps: int | None = None,
             chunk: int | None = None):
    """The score-driven loss at O(log T) span — converges to the sequential
    ``score_driven.get_loss`` (K = 1 replay) at ≈B^L per sweep,
    differentiable end-to-end (tree included — see the module docstring on
    the deliberate no-cut divergence from the SLR engine)."""
    loss, _, _ = _loss_coded(spec, params, data, start, end, prefix, sweeps,
                             chunk)
    return loss


def get_loss_coded(spec: ModelSpec, params, data, start=0, end=None,
                   prefix: str = "blocked", sweeps: int | None = None,
                   chunk: int | None = None):
    """``(loss, code)`` — :func:`get_loss` plus its taxonomy bitmask, the
    self-describing failure channel every engine carries (the ladder's
    score_tree rescue rung reads this)."""
    loss, code, _ = _loss_coded(spec, params, data, start, end, prefix,
                                sweeps, chunk)
    return loss, code


def filter_states(spec: ModelSpec, params, data, start=0, end=None,
                  prefix: str = "blocked", sweeps: int | None = None,
                  chunk: int | None = None):
    """Post-transition state trajectories ``(gammas (T, L), betas (T, M))``
    from the final refinement sweep — the tree twin of reading
    ``scan_filter``'s outs (the parity surface tests/test_score_scan.py
    pins element-wise against the sequential scan and the NumPy oracle)."""
    _, _, (gammas, betas) = _loss_coded(spec, params, data, start, end,
                                        prefix, sweeps, chunk)
    return gammas, betas
