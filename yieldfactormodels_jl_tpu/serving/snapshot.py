"""Model-snapshot registry: fitted params → frozen serving state.

A :class:`ServingSnapshot` is the unit of deployment for the online layer:
the fitted flat parameter vector (loaded from the merged SQLite DBs the
rolling-forecast pipeline writes — persistence/database.py), the filtered
state moments (β_{t|t}, P_{t|t}) from ONE offline filter pass over the
conditioning sample, and version-stamped metadata.  After the freeze, serving
never touches the history again: a new observation advances the state through
``serving/online.py``'s O(1) recursive update, and forecasts/scenarios read
the state directly (amortized posterior-update inference — PAPERS.md,
arxiv 2210.07154).

Snapshots are registered pytrees (params/β/P are leaves, spec + meta are
static aux data), so they pass through ``jit``/``vmap`` boundaries unchanged
and stack naturally into the micro-batcher's padded batches.

Driver-layer error policy (CLAUDE.md): a freeze that fails structurally — no
params in the DB, a −Inf filter pass — raises :class:`ServingError` loudly;
inside the jitted kernels the same failures stay sentinels.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import lru_cache
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import register_engine_cache
from ..models.specs import ModelSpec
from ..persistence.database import read_all_task_params, read_task_params


class ServingError(RuntimeError):
    """Structured serving failure, raised only at the driver layer.  Carries
    ``stage`` (``"snapshot" | "update" | "forecast" | "scenarios"``) and a
    ``context`` dict (date, task_id, version, ...) for the caller's logs."""

    def __init__(self, stage: str, detail: str, **context):
        self.stage = stage
        self.detail = detail
        self.context = dict(context)
        ctx = f" [{', '.join(f'{k}={v}' for k, v in self.context.items())}]" \
            if self.context else ""
        super().__init__(f"{stage}: {detail}{ctx}")


@dataclasses.dataclass(frozen=True)
class SnapshotMeta:
    """Version-stamped provenance (hashable: rides the static side of the
    pytree).  ``version`` bumps on every accepted online update;
    ``n_updates`` counts updates since the freeze (``n_obs`` columns were
    conditioned on at freeze time)."""

    model_string: str = ""
    window_type: str = "expanding"
    task_id: int = -1
    n_obs: int = 0
    version: int = 0
    n_updates: int = 0

    def bump(self, n: int = 1) -> "SnapshotMeta":
        return dataclasses.replace(self, version=self.version + n,
                                   n_updates=self.n_updates + n)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class ServingSnapshot:
    """Frozen serving state: params + filtered (β_{t|t}, P_{t|t}) + meta."""

    spec: ModelSpec
    params: jnp.ndarray   # (n_params,) constrained flat vector
    beta: jnp.ndarray     # (Ms,)
    P: jnp.ndarray        # (Ms, Ms)
    meta: SnapshotMeta = SnapshotMeta()

    def tree_flatten(self):
        return (self.params, self.beta, self.P), (self.spec, self.meta)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        spec, meta = aux
        params, beta, P = leaves
        return cls(spec, params, beta, P, meta)

    def advanced(self, beta, P, n: int = 1) -> "ServingSnapshot":
        """The snapshot after ``n`` accepted online updates (version bump
        of ``n`` — one per observation, O(1) regardless of n)."""
        return dataclasses.replace(self, beta=beta, P=P,
                                   meta=self.meta.bump(n))


def freeze_snapshot(spec: ModelSpec, params, data, start: int = 0,
                    end: Optional[int] = None, engine=None,
                    meta: Optional[SnapshotMeta] = None) -> ServingSnapshot:
    """Run the filter once over ``data[:, start:end]`` and freeze the final
    filtered moments.  ``engine`` follows the ``forward_moments`` contract
    ("univariate"/"joint" emit moments; None reads the process engine, with a
    fallback to "univariate" when the process engine has no moments path).

    Raises :class:`ServingError` on a failed filter pass (−Inf loglik) —
    first-iteration structural failures are loud at the driver layer.
    """
    from .. import config
    from ..ops.smoother import forward_moments

    if not spec.is_kalman:
        raise ServingError(
            "snapshot", f"online serving needs a Kalman family with a state "
            f"posterior; {spec.family!r} has no filtered covariance",
            model=spec.model_string)
    if engine is None and config.kalman_engine() not in ("joint", "univariate"):
        engine = "univariate"  # loglik-only engines have no moments path
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    end = T if end is None else min(int(end), T)
    data = data[:, :end]  # condition on start..end-1 only (forecast origin)
    params = jnp.asarray(params, dtype=spec.dtype).reshape(-1)
    _, outs = forward_moments(spec, params, data, start, end, engine)
    if not bool(jnp.all(outs["ll"] > -jnp.inf)):
        raise ServingError(
            "snapshot", "filter pass failed (−Inf loglik sentinel) — params "
            "invalid for this panel", model=spec.model_string, end=end)
    if meta is None:
        meta = SnapshotMeta(model_string=spec.model_string)
    meta = dataclasses.replace(meta, n_obs=end - start)
    return ServingSnapshot(spec, params, outs["beta_upd"][-1],
                           outs["P_upd"][-1], meta)


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_freeze_batch(spec: ModelSpec, T: int, engine: str, B: int):
    """One vmapped warm-boot freeze program: (params (B, P), data (N, T),
    ends (B,)) → per-task final filtered (β, P) moments at each task's OWN
    conditioning end, plus the per-task ok flag.

    The trick that lets tasks with DIFFERENT window ends share one program:
    the Kalman recursion is causal, so the filtered state after step e−1 of
    a T-long pass equals the final state of an e-long pass — every task runs
    the same full-length filter and gathers its own (β_{e−1|e−1},
    P_{e−1|e−1}) in-program.  One trace replaces the serial boot's
    one-compile-per-distinct-end loop (the warm-boot wall measured in
    tests/test_serving.py)."""
    from ..ops.smoother import forward_moments

    def one(params, data, e):
        _, outs = forward_moments(spec, params, data, 0, T, engine)
        beta = outs["beta_upd"][e - 1]
        P = outs["P_upd"][e - 1]
        conditioned = jnp.arange(T) < e
        ok = jnp.all(jnp.where(conditioned, outs["ll"], 0.0) > -jnp.inf) \
            & jnp.all(jnp.isfinite(beta)) & jnp.all(jnp.isfinite(P))
        return beta, P, ok

    return jax.jit(jax.vmap(one, in_axes=(0, None, 0)))


def freeze_snapshots_batch(spec: ModelSpec, params_by_task: Dict[int, object],
                           data, window_type: str = "expanding",
                           engine=None):
    """Freeze one snapshot per task through ONE vmapped filter pass —
    the warm-boot batch path behind :meth:`SnapshotRegistry.load_all`.

    Returns ``(snapshots, errors)``: malformed rows (wrong params length,
    empty conditioning window) and tasks whose filter pass failed (−Inf
    sentinel) are quarantined into ``errors`` with a structural
    :class:`ServingError`, never taking the healthy tasks down — the
    serial-loop semantics, minus the per-task compile."""
    if not spec.is_kalman:
        raise ServingError(
            "snapshot", f"online serving needs a Kalman family with a state "
            f"posterior; {spec.family!r} has no filtered covariance",
            model=spec.model_string)
    from .. import config

    if engine is None and config.kalman_engine() not in ("joint",
                                                         "univariate"):
        engine = "univariate"  # loglik-only engines have no moments path
    data = jnp.asarray(data, dtype=spec.dtype)
    T = int(data.shape[1])
    errors: Dict[int, Exception] = {}
    staged = []
    for task_id in sorted(params_by_task):
        end = min(int(task_id), T)
        p = np.asarray(params_by_task[task_id], dtype=np.float64).reshape(-1)
        if p.shape[0] != spec.n_params:
            errors[int(task_id)] = ServingError(
                "snapshot", f"params row has {p.shape[0]} entries, spec "
                f"needs {spec.n_params}", task_id=int(task_id))
            continue
        if end < 1:
            errors[int(task_id)] = ServingError(
                "snapshot", f"empty conditioning window (end={end})",
                task_id=int(task_id))
            continue
        staged.append((int(task_id), end, p))
    snapshots = []
    if staged:
        t_max = max(e for _, e, _ in staged)
        runner = _jitted_freeze_batch(spec, t_max, engine, len(staged))
        betas, Ps, oks = runner(
            jnp.asarray(np.stack([p for _, _, p in staged]),
                        dtype=spec.dtype),
            data[:, :t_max],
            jnp.asarray([e for _, e, _ in staged], dtype=jnp.int32))
        oks = np.asarray(oks)
        for i, (task_id, end, p) in enumerate(staged):
            if not oks[i]:
                errors[task_id] = ServingError(
                    "snapshot", "filter pass failed (−Inf loglik sentinel) — "
                    "params invalid for this panel",
                    model=spec.model_string, end=end)
                continue
            meta = SnapshotMeta(model_string=spec.model_string,
                                window_type=window_type, task_id=task_id,
                                n_obs=end)
            snapshots.append(ServingSnapshot(
                spec, jnp.asarray(p, dtype=spec.dtype), betas[i], Ps[i],
                meta))
    return snapshots, errors


def load_snapshot(db_path: str, spec: ModelSpec, task_id: int, data,
                  window_type: str = "expanding", engine=None
                  ) -> ServingSnapshot:
    """Read task ``task_id``'s fitted params from a merged forecast DB
    (persistence/database.py contract) and freeze a snapshot conditioned on
    ``data[:, :task_id]`` (the task's estimation sample)."""
    params = read_task_params(db_path, task_id)
    if params is None:
        raise ServingError("snapshot", f"no fitted params for task {task_id}",
                           db_path=db_path, task_id=task_id)
    meta = SnapshotMeta(model_string=spec.model_string,
                        window_type=window_type, task_id=int(task_id))
    return freeze_snapshot(spec, params, data, end=int(task_id),
                           engine=engine, meta=meta)


class SnapshotRegistry:
    """In-process registry of live snapshots, keyed (model_string, task_id).

    ``load_all`` bulk-loads every task in a merged DB with ONE query
    (``read_all_task_params``) and ONE vmapped filter freeze across the
    tasks (:func:`freeze_snapshots_batch`) — the serving warm-boot path: no
    per-task SELECT loop, no per-task compile.

    Thread-safe: ``put``/``get``/``load_all`` are called concurrently from
    the gateway worker thread and the health-rebuild path
    (service._rebuild_source), so every map access holds a lock — a
    half-registered snapshot must never be observable."""

    def __init__(self):
        self._snaps: Dict[Tuple[str, int], ServingSnapshot] = {}
        self.last_errors: Dict[int, Exception] = {}
        self._lock = threading.RLock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)

    def __contains__(self, key: Tuple[str, int]) -> bool:
        with self._lock:
            return key in self._snaps

    def keys(self):
        with self._lock:
            return sorted(self._snaps)

    def pop(self, key: Tuple[str, int]) -> Optional[ServingSnapshot]:
        """Remove and return one snapshot (None when absent) — the tiered
        store's cold-tier consume path (serving/tiers.py)."""
        with self._lock:
            return self._snaps.pop(key, None)

    def put(self, snap: ServingSnapshot) -> Tuple[str, int]:
        key = (snap.meta.model_string, snap.meta.task_id)
        with self._lock:
            self._snaps[key] = snap
        return key

    def get(self, model_string: str, task_id: int = -1) -> ServingSnapshot:
        key = (model_string, task_id)
        with self._lock:
            if key not in self._snaps:
                raise ServingError("snapshot",
                                   f"no snapshot registered for {key}",
                                   known=sorted(self._snaps))
            return self._snaps[key]

    def load_all(self, db_path: str, spec: ModelSpec, data,
                 window_type: str = "expanding", engine=None,
                 batch: bool = True):
        """Freeze one snapshot per task found in ``db_path``; returns the
        registered keys.  Tasks whose freeze fails are skipped with their
        errors collected on ``self.last_errors`` (a dead task must not take
        the whole registry down).  ``batch=True`` (default) runs ONE vmapped
        freeze across every well-formed row (one compile per boot instead of
        one per distinct window end); ``batch=False`` keeps the serial
        per-task loop — the reference path the batch is pinned against in
        tests/test_serving.py."""
        all_params = read_all_task_params(db_path)
        keys, errors = [], {}
        if batch and spec.is_kalman:
            snaps, errors = freeze_snapshots_batch(
                spec, all_params, data, window_type=window_type,
                engine=engine)
            keys = [self.put(s) for s in snaps]
            self.last_errors = errors
            return keys
        for task_id in sorted(all_params):
            meta = SnapshotMeta(model_string=spec.model_string,
                                window_type=window_type, task_id=int(task_id))
            try:
                snap = freeze_snapshot(spec, all_params[task_id], data,
                                       end=int(task_id), engine=engine,
                                       meta=meta)
            except Exception as e:  # noqa: BLE001 — quarantine the row
                errors[int(task_id)] = e
                continue
            keys.append(self.put(snap))
        self.last_errors = errors
        return keys
