"""Jitted recursive filter updates — the O(1)-per-observation serving core.

The reference design is a filter: a new daily curve advances the state with
ONE Kalman step (SURVEY.md §1), which is exactly the primitive an online
service needs — no refit, no re-filter of history.  This module provides that
step as precompiled fixed-shape programs (amortized-update inference in the
spirit of arxiv 2210.07154 / 2207.00426: trace once, serve forever):

- ``update``   one predict-then-update recursion from the FILTERED state
  (β_{t|t}, P_{t|t} — what a :class:`~.snapshot.ServingSnapshot` freezes),
- ``update_k`` the k-step batch of the same recursion as one ``lax.scan``
  (catch-up after an ingest gap),
- ``scenario_paths``  n sampled h-step paths from the current predictive
  distribution (``models/simulate.py`` seeded at the filtered state).

Two engines, same algebra as the offline filters they reuse pieces of:
``"univariate"`` propagates P itself (sequential scalar updates,
ops/univariate_kf.py); ``"sqrt"`` propagates a square-root factor S with
P = S Sᵀ (Potter updates + QR time update, ops/sqrt_kf.py) for f32-robust
long-horizon serving.

Beyond the offline filters: the measurement update is NaN-masked PER ELEMENT,
so a partially-observed curve (late auction, stale tenor) updates the state
from the quoted maturities only — the offline kernels drop any column with a
NaN entirely (/root/reference/src/models/kalman/filter.jl:126-140 semantics),
which wastes real quotes in a live feed.  The sequential-observation decomposition makes the partial
update exact, not approximate: each scalar observation conditions the state
independently (Koopman–Durbin), so skipping the missing ones IS the correct
posterior given the observed subset.

Sentinel convention (CLAUDE.md): a failed innovation-variance chain inside
the jitted kernel poisons the state to NaN and lowers ``ok``; only the driver
layer (serving/service.py) converts that into a structured error.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import make_trace_counter, register_engine_cache
from ..models.kalman import measurement_setup, state_measurement
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..robustness import taxonomy as tax

_LOG_2PI = math.log(2.0 * math.pi)

#: online-update engines (subset of config.KALMAN_ENGINES: the joint/assoc
#: forms bring nothing to a single-step update — the univariate form IS the
#: joint posterior, and assoc is a parallel-in-time reformulation)
ONLINE_ENGINES = ("univariate", "sqrt")

# trace counters (config.make_trace_counter) — note_trace at the top of a
# traced body runs once per (re)compilation; the no-recompile serving tests
# pin their sum against the bucket-lattice bound (tests/test_serving.py)
trace_counts, note_trace, reset_trace_counts = make_trace_counter()


class OnlineState(NamedTuple):
    """The serving scan carry: filtered mean + covariance representation —
    ``cov`` holds P_{t|t} for the univariate engine, its square-root factor S
    (P = S Sᵀ) for the sqrt engine."""

    beta: jnp.ndarray   # (Ms,)
    cov: jnp.ndarray    # (Ms, Ms)


# ---------------------------------------------------------------------------
# element-masked measurement updates
# ---------------------------------------------------------------------------

def _masked_sequential_update(Z, y_eff, mask, beta, P, obs_var):
    """N scalar updates skipping masked elements (ops/univariate_kf.py's
    ``_sequential_update`` with a per-observation mask; identical arithmetic
    on fully-observed curves — the mask factor is an exact 1.0 multiply)."""

    def body(carry, inp):
        b, Pm, ll, ok, code = carry
        z, y_i, m = inp
        mf = m.astype(P.dtype)
        zP = z @ Pm                     # (Ms,)
        f = zP @ z + obs_var
        f_fin = jnp.isfinite(f)
        ok = ok & (~m | ((f > 0) & f_fin))
        code = code | tax.bit(m & f_fin & (f <= 0), tax.NONPSD_INNOVATION) \
            | tax.bit(m & ~f_fin, tax.STATE_EXPLODED)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y_i - z @ b
        K = zP / fsafe
        b = b + K * (v * mf)
        Pm = Pm - mf * jnp.outer(K, zP)
        ll = ll - 0.5 * mf * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b, Pm, ll, ok, code), None

    zero = jnp.zeros((), dtype=P.dtype)
    (beta_u, P_u, ll, ok, code), _ = lax.scan(
        body, (beta, P, zero, jnp.bool_(True), tax.zero_code()),
        (Z, y_eff, mask), length=Z.shape[0])
    # same drift insurance as the offline kernel
    P_u = 0.5 * (P_u + P_u.T)
    return beta_u, P_u, ll, ok, code


def _masked_potter_update(Z, y_eff, mask, beta, S, obs_var):
    """Element-masked Potter square-root updates (ops/sqrt_kf.py's
    ``_potter_update`` + the per-observation mask)."""

    def body(carry, inp):
        b, Sm, ll, ok, code = carry
        z, y_i, m = inp
        mf = m.astype(S.dtype)
        phi = Sm.T @ z                    # (Ms,)
        f = phi @ phi + obs_var
        f_fin = jnp.isfinite(f)
        ok = ok & (~m | ((f > 0) & f_fin))
        code = code | tax.bit(m & f_fin & (f <= 0), tax.NONPSD_INNOVATION) \
            | tax.bit(m & ~f_fin, tax.STATE_EXPLODED)
        fsafe = jnp.where(f > 0, f, 1.0)
        v = y_i - z @ b
        Sphi = Sm @ phi                   # = P z
        b = b + Sphi * (v * mf / fsafe)
        alpha = 1.0 / (fsafe + jnp.sqrt(jnp.maximum(obs_var, 0.0) * fsafe))
        Sm = Sm - (alpha * mf) * jnp.outer(Sphi, phi)
        ll = ll - 0.5 * mf * (jnp.log(fsafe) + v * v / fsafe + _LOG_2PI)
        return (b, Sm, ll, ok, code), None

    zero = jnp.zeros((), dtype=S.dtype)
    (beta_u, S_u, ll, ok, code), _ = lax.scan(
        body, (beta, S, zero, jnp.bool_(True), tax.zero_code()),
        (Z, y_eff, mask), length=Z.shape[0])
    return beta_u, S_u, ll, ok, code


# ---------------------------------------------------------------------------
# one recursion step (predict → element-masked update)
# ---------------------------------------------------------------------------

def _omega_sqrt_factor(kp, Ms, dtype):
    """Upper factor C with Ω_state = CᵀC and its validity flag
    (ops/sqrt_kf.py's jittered form + its ``fac_ok`` gate: a failed
    factorization must poison the step, never silently serve with Ω = 0)."""
    Om = 0.5 * (kp.Omega_state + kp.Omega_state.T) \
        + 1e-12 * jnp.eye(Ms, dtype=dtype)
    C = jnp.linalg.cholesky(Om).T
    fac_ok = jnp.all(jnp.isfinite(C))
    return jnp.where(jnp.isfinite(C), C, jnp.zeros_like(C)), fac_ok


def filter_step(spec: ModelSpec, kp, state: OnlineState, y, engine: str):
    """Advance the filtered state by one observation.

    Predict-then-update: the snapshot holds β_{t|t}, so the transition runs
    FIRST, then the element-masked measurement update with ``y`` (N,) — the
    exact continuation of the offline filter's update-then-propagate scan.
    Returns ``(OnlineState, ll, ok, code)``; on failure (``ok`` false) the
    state is poisoned to NaN (sentinel), never raised here — ``code`` is the
    taxonomy bitmask saying why (robustness/taxonomy.py), decoded only by
    the driver (serving/service.py).
    """
    dtype = kp.Phi.dtype
    Ms = spec.state_dim
    mats = spec.maturities_array
    beta, cov = state

    beta_pred = kp.delta + kp.Phi @ beta
    fac_ok = jnp.bool_(True)
    if engine == "sqrt":
        C, fac_ok = _omega_sqrt_factor(kp, Ms, dtype)
        pre = jnp.concatenate([cov.T @ kp.Phi.T, C], axis=0)  # (2Ms, Ms)
        cov_pred = jnp.linalg.qr(pre, mode="r").T
    else:
        cov_pred = kp.Phi @ cov @ kp.Phi.T + kp.Omega_state

    mask = jnp.isfinite(y)
    ysafe = jnp.where(mask, y, 0.0)  # masked elements never reach the update
    mfn = state_measurement(spec)
    if mfn is not None:
        # fixed-linearization effective observation (ops/univariate_kf.py)
        Z, y_pred0 = mfn(beta_pred, mats)
        y_eff = ysafe - y_pred0 + Z @ beta_pred
    else:
        Z, d_const = measurement_setup(spec, kp, dtype)
        if d_const is None:
            d_const = jnp.zeros((spec.N,), dtype=dtype)
        y_eff = ysafe - d_const

    if engine == "sqrt":
        beta_u, cov_u, ll, ok, code = _masked_potter_update(
            Z, y_eff, mask, beta_pred, cov_pred, kp.obs_var)
    else:
        beta_u, cov_u, ll, ok, code = _masked_sequential_update(
            Z, y_eff, mask, beta_pred, cov_pred, kp.obs_var)
    ok = ok & fac_ok
    code = code | tax.bit(~fac_ok, tax.CHOL_BREAKDOWN)

    nan = jnp.asarray(jnp.nan, dtype=dtype)
    beta_u = jnp.where(ok, beta_u, nan)   # bad update → NaN state (sentinel)
    cov_u = jnp.where(ok, cov_u, nan)
    code = code | tax.bit(~ok, tax.NAN_STATE)
    return OnlineState(beta_u, cov_u), ll, ok, code


# ---------------------------------------------------------------------------
# jitted fixed-shape programs (trace-time builders: engine-cache registered)
# ---------------------------------------------------------------------------

def _check_engine(engine: str) -> None:
    if engine not in ONLINE_ENGINES:
        raise ValueError(
            f"unknown online engine {engine!r}; pick from {ONLINE_ENGINES}")


def factor_cov(P, engine: str, dtype):
    """The engine's covariance REPRESENTATION of filtered moments P:
    P itself for the univariate engine (copied — the donated update kernels
    consume the live buffer, so it must never alias a frozen record), the
    lower Cholesky factor S with P = S Sᵀ for the sqrt engine.  Raises
    ``ValueError`` (trace-time validation class) on a non-PSD P under the
    sqrt factorization — the driver layers (service/store) convert that into
    their structured error."""
    cov = jnp.asarray(P, dtype=dtype)
    if engine == "sqrt":
        Ms = cov.shape[0]
        sym = 0.5 * (cov + cov.T) + 1e-12 * jnp.eye(Ms, dtype=cov.dtype)
        cov = jnp.linalg.cholesky(sym)
        if not bool(jnp.all(jnp.isfinite(cov))):
            raise ValueError("filtered covariance is not PSD — cannot start "
                             "the sqrt engine")
        return cov
    return jnp.array(cov, copy=True)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_update(spec: ModelSpec, engine: str, donate: bool = False):
    """One-step update program: (params, β, cov, y) →
    (β′, cov′, ll, ok, code).

    ``donate=True`` donates the state arguments (β, cov): the launch CONSUMES
    the caller's buffers and reuses their memory for the identically-shaped
    updated-state outputs — the O(1) serving hot loop then allocates nothing
    per update (docs/DESIGN.md §14).  Callers owning long-lived references
    to the passed state (the service's snapshot/last-good bookkeeping) must
    hold independent copies; :class:`~.service.YieldCurveService` keeps them
    host-side."""
    _check_engine(engine)

    def one(params, beta, cov, y):
        note_trace("update")
        kp = unpack_kalman(spec, params)
        st, ll, ok, code = filter_step(spec, kp, OnlineState(beta, cov), y,
                                       engine)
        return st.beta, st.cov, ll, ok, code

    return jax.jit(one, donate_argnums=(1, 2) if donate else ())


#: catch-up length buckets: like the batcher's lattice, distinct gap lengths
#: must not mean distinct compiled programs on the hot path (DESIGN.md §9)
K_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def _k_bucket(k: int) -> int:
    for v in K_BUCKETS:
        if k <= v:
            return v
    return k  # beyond the lattice: one exact-size program (rare giant gap)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_update_k(spec: ModelSpec, engine: str, kb: int,
                     donate: bool = False):
    """Padded k-step catch-up program: (params, β, cov, Y (N, kb),
    valid (kb,)) → (β′, cov′, lls (kb,), oks (kb,)) — one scan, params
    unpacked once.  Steps with ``valid`` false are EXACT no-ops (the carry
    passes through unchanged — NaN-padding alone would still apply the
    transition), so any k ≤ kb runs through this one program.  ``donate``
    follows the ``_jitted_update`` contract: (β, cov) consumed, their memory
    reused for the updated state."""
    _check_engine(engine)

    def many(params, beta, cov, Y, valid):
        note_trace("update_k")
        kp = unpack_kalman(spec, params)

        def body(carry, inp):
            y, v = inp
            b0, c0 = carry
            st, ll, ok, code = filter_step(spec, kp, OnlineState(b0, c0), y,
                                           engine)
            b = jnp.where(v, st.beta, b0)
            c = jnp.where(v, st.cov, c0)
            return (b, c), (jnp.where(v, ll, 0.0), ok | ~v,
                            jnp.where(v, code, jnp.int32(0)))

        (b, c), (lls, oks, codes) = lax.scan(body, (beta, cov), (Y.T, valid),
                                             length=kb)
        return b, c, lls, oks, codes

    return jax.jit(many, donate_argnums=(1, 2) if donate else ())


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_shard_update(spec: ModelSpec, engine: str, capacity: int,
                         bucket: int, donate: bool = True):
    """ONE shard's micro-batch update program (docs/DESIGN.md §16): the
    shard's mesh-resident state — ``params`` (P, C), ``beta`` (Ms, C),
    ``cov`` (Ms, Ms, C), ``version`` (C,), slot axis LAST per the lane rule
    — plus a padded request batch ``Y`` (N, B), ``slots`` (B,), ``valid``
    (B,) → the updated resident state and the per-REQUEST curve outputs
    (ll, ok, code, version, β′, cov′ at the requested slots).

    Requests are scattered onto the slot axis (padding rows scatter out of
    bounds and are DROPPED — they can never clobber a live slot), then every
    slot advances through :func:`filter_step` in lanes, masked: unselected
    slots are exact pass-throughs, and a selected slot whose step FAILED
    (``ok`` false) also keeps its resident state — "keep the last good
    version" happens in-program, no host restore dance.  Failures stay
    sentinels riding the batch (NaN candidate state, taxonomy bits); the
    driver (serving/store.py) decodes the per-request codes.

    ``donate=True`` donates all four state buffers; each is carried to an
    identically-shaped output (params passes through as the first output —
    the §14 aliasing invariant), so the resident store allocates nothing per
    micro-batch and the only host traffic is O(batch), never O(capacity).
    One compiled program per (engine, capacity, bucket): mesh size never
    appears in the key, so a 1→2→4→8 device sweep at fixed shard capacity
    reuses one trace (pinned in tests/test_store.py)."""
    _check_engine(engine)

    def many(params, beta, cov, ver, Y, slots, valid):
        note_trace("store_update")
        # padding rows target slot `capacity` (out of bounds): mode="drop"
        # discards them, so a duplicated padding index can never mask or
        # NaN-out a live slot's scattered curve
        safe = jnp.where(valid, slots, capacity)
        sel = jnp.zeros((capacity,), dtype=bool).at[safe].set(
            True, mode="drop")
        Yfull = jnp.full((spec.N, capacity), jnp.nan, dtype=beta.dtype)
        Yfull = Yfull.at[:, safe].set(Y, mode="drop")

        def one(p, b, c, y):
            kp = unpack_kalman(spec, p)
            st, ll, ok, code = filter_step(spec, kp, OnlineState(b, c), y,
                                           engine)
            return st.beta, st.cov, ll, ok, code

        nb, nc, ll, ok, code = jax.vmap(
            one, in_axes=(-1, -1, -1, -1),
            out_axes=(-1, -1, -1, -1, -1))(params, beta, cov, Yfull)
        accept = sel & ok
        beta_o = jnp.where(accept[None, :], nb, beta)
        cov_o = jnp.where(accept[None, None, :], nc, cov)
        ver_o = ver + accept.astype(ver.dtype)
        # per-request gathers — the ONLY outputs that cross to host
        gs = jnp.minimum(slots, capacity - 1)
        return (params, beta_o, cov_o, ver_o,
                jnp.where(valid, ll[gs], 0.0),
                ok[gs] | ~valid,
                jnp.where(valid, code[gs], jnp.int32(0)),
                ver_o[gs],
                beta_o[:, gs], cov_o[:, :, gs])

    return jax.jit(many, donate_argnums=(0, 1, 2, 3) if donate else ())


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_slot_write(spec: ModelSpec, capacity: int, donate: bool = True):
    """Single-slot rewrite program: scatter (p, β, cov-rep, version) into one
    slot of a shard's resident arrays WITHOUT gathering the shard — the
    register/evict/heal path (docs/DESIGN.md §16 slot lifecycle).  All four
    state buffers are donated and carried to identically-shaped outputs, so
    a rebuild touches O(slot) memory, not O(capacity)."""
    del spec  # shapes ride the arguments; the key keeps specs apart

    def write(params, beta, cov, ver, slot, p, b, c, v):
        note_trace("slot_write")
        return (params.at[:, slot].set(p),
                beta.at[:, slot].set(b),
                cov.at[:, :, slot].set(c),
                ver.at[slot].set(v))

    return jax.jit(write, donate_argnums=(0, 1, 2, 3) if donate else ())


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_slot_write_many(spec: ModelSpec, capacity: int, bucket: int,
                            donate: bool = True):
    """Multi-slot rewrite program: scatter up to ``bucket`` slots' worth of
    (p, β, cov-rep, version) into a shard's resident arrays in ONE donated
    launch — the batched promotion / bulk-registration path (docs/DESIGN.md
    §21): a burst of tier misses costs one device dispatch per shard, not
    one per user.  Padding rows target slot ``capacity`` (out of bounds) and
    are DROPPED exactly as in ``_jitted_shard_update`` — they can never
    clobber a live slot.  Callers guarantee the valid slots are UNIQUE
    within one launch (duplicate scatter order is undefined); the router
    (``serving.tiers``) enforces it by construction.  One compiled program
    per (capacity, bucket): mesh size never appears in the key, so a
    1→2→4→8 sweep at fixed shard capacity reuses one trace (pinned in
    tests/test_tiers.py)."""
    del spec, bucket  # shapes ride the arguments; the key keeps them apart

    def write(params, beta, cov, ver, slots, valid, p, b, c, v):
        note_trace("slot_write_many")
        safe = jnp.where(valid, slots, capacity)
        return (params.at[:, safe].set(p, mode="drop"),
                beta.at[:, safe].set(b, mode="drop"),
                cov.at[:, :, safe].set(c, mode="drop"),
                ver.at[safe].set(v, mode="drop"))

    return jax.jit(write, donate_argnums=(0, 1, 2, 3) if donate else ())


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_refilter(spec: ModelSpec, T: int):
    """Re-filter-from-scratch program (docs/DESIGN.md §13/§19): the
    O(log T)-span parallel-in-time filter over a full (N, T) history → the
    final filtered (β, P), the total loglik, and the ok/taxonomy pair.
    Constant-Z families ride ``assoc_scan.filter_and_loss``; the
    state-dependent-measurement ones (TVλ) the iterated-SLR twin
    (``slr_scan.filter_and_loss``) — the applicability gate is
    ``config.tree_engine_for``, validated at the driver
    (serving/service.py).  The dispatch is EXPLICIT on the moment-emitting
    tree engines: "score_tree" (the score-driven tree, no filtered (β, P)
    moment set) and tree-less families raise here instead of silently
    falling into the assoc path.  This is the exact rebuild that replaces
    "trust k accumulated O(1) updates".  Sentinel discipline as everywhere:
    a failed pass NaN-poisons the returned state and lowers ``ok``; the
    driver decodes ``code`` into the structured error."""
    from .. import config as _config

    eng = _config.tree_engine_for(spec)
    if eng == "slr":
        from ..ops import slr_scan as _tree
    elif eng == "assoc":
        from ..ops import assoc_scan as _tree
    else:
        raise ValueError(
            f"refilter needs a moment-emitting parallel-in-time engine "
            f"('assoc' or 'slr'); config.tree_engine_for({spec.family!r}) "
            f"is {eng!r}")

    def refit(params, data):
        note_trace("refilter")
        m, P, ll, code = _tree.filter_and_loss(spec, params, data, 0, T)
        beta = m[-1]
        cov = 0.5 * (P[-1] + P[-1].T)
        ok = jnp.all(jnp.isfinite(beta)) & jnp.all(jnp.isfinite(cov)) \
            & (code == 0)
        nan = jnp.asarray(jnp.nan, dtype=beta.dtype)
        beta = jnp.where(ok, beta, nan)
        cov = jnp.where(ok, cov, nan)
        code = code | tax.bit(~ok, tax.NAN_STATE)
        return beta, cov, ll, ok, code

    return jax.jit(refit)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_scenarios(spec: ModelSpec, horizon: int, n: int):
    """n sampled h-step yield paths from the filtered state: (params, β, P,
    keys (n, ·)) → (N, horizon, n) — draws ride the trailing (lane) axis."""
    from ..models.simulate import simulate

    def paths(params, beta, P, keys):
        note_trace("scenarios")
        return jax.vmap(
            lambda k: simulate(spec, params, horizon, k,
                               start_state=(beta, P))["data"],
            out_axes=-1)(keys)

    return jax.jit(paths)


# ---------------------------------------------------------------------------
# public (still sentinel-level: drivers own the error policy)
# ---------------------------------------------------------------------------

def update(spec: ModelSpec, params, state: OnlineState, y,
           engine: str = "univariate", with_code: bool = False,
           donate: bool = False):
    """One recursive update.  Returns ``(OnlineState, ll, ok)`` — all traced
    outputs; the caller decides whether NaN state is an error.
    ``with_code=True`` appends the taxonomy bitmask (same program — the code
    always rides the kernel outputs).  ``donate=True`` consumes ``state``
    (its buffers are reused for the returned state — the alloc-free serving
    hot loop); default off so existing callers' states stay valid."""
    runner = _jitted_update(spec, engine, donate)
    b, c, ll, ok, code = runner(params, state.beta, state.cov, jnp.asarray(y))
    if with_code:
        return OnlineState(b, c), ll, ok, code
    return OnlineState(b, c), ll, ok


def update_k(spec: ModelSpec, params, state: OnlineState, Y,
             engine: str = "univariate", with_code: bool = False,
             donate: bool = False):
    """k-step catch-up over the columns of ``Y`` (N, k).  Returns
    ``(OnlineState, lls (k,), oks (k,))`` (+ per-step codes with
    ``with_code=True``).  ``k`` is rounded up onto ``K_BUCKETS`` (padded
    steps are exact no-ops), so varying gap lengths share a handful of
    compiled programs.  ``donate`` follows :func:`update`'s contract."""
    Y = jnp.asarray(Y)
    k = int(Y.shape[1])
    kb = _k_bucket(k)
    if kb > k:
        pad = jnp.full(Y.shape[:1] + (kb - k,), jnp.nan, dtype=Y.dtype)
        Y = jnp.concatenate([Y, pad], axis=1)
    valid = jnp.arange(kb) < k
    runner = _jitted_update_k(spec, engine, kb, donate)
    b, c, lls, oks, codes = runner(params, state.beta, state.cov, Y, valid)
    if with_code:
        return OnlineState(b, c), lls[:k], oks[:k], codes[:k]
    return OnlineState(b, c), lls[:k], oks[:k]


def scenario_paths(spec: ModelSpec, params, beta, P, horizon: int, n: int,
                   key):
    """n h-step scenario paths (N, horizon, n) from filtered moments (β, P)."""
    runner = _jitted_scenarios(spec, int(horizon), int(n))
    keys = jax.random.split(jnp.asarray(key), n)
    return runner(params, beta, P, keys)
