"""Resilient request pipeline in front of the serving layer (DESIGN §12).

`YieldCurveService` answers one request at a time and blocks its caller for
exactly as long as the kernels take; under offered load above capacity that
is a recipe for unbounded queues and collapsing tail latency.  The gateway
puts the production request path in front of it:

- **Backpressure.**  Requests land in a BOUNDED deque (``queue_max``,
  ``YFM_SERVE_QUEUE_MAX``) — memory per gateway is O(queue_max), full stop.
- **Admission control / load shedding.**  A submit against a full queue, or
  against a queue whose HEAD has waited longer than ``queue_age_ms``
  (``YFM_SERVE_QUEUE_AGE_MS`` — a stalled worker means admitting more work
  is pure harm), is shed with a structured ``ServingError(stage="admission")``
  carrying ``retry_after_ms`` — the client's backoff hint, not a timeout.
- **Per-request deadlines.**  Every request can carry a deadline
  (``deadline_ms=`` per call, ``YFM_SERVE_DEADLINE_MS`` as the default); the
  remaining budget propagates into batch formation: a request that cannot
  make its deadline given the measured flush cost is answered IMMEDIATELY
  from the service's last-good snapshot (β, P, version, ``stale``/
  ``degraded`` flags) instead of blocking the batch — degraded beats late,
  and the square-root refresh machinery (DESIGN §11) keeps that snapshot a
  principled answer, not a hack.
- **Worker isolation.**  The pump collects every ticket under its own
  try/except and the micro-batcher isolates chunk failures per ticket, so
  one poisoned request fails alone — never its whole bucket chunk, never
  the worker loop.

Request-path chaos seams (orchestration/chaos.py): ``slow_update`` injects
latency before the update dispatch, ``queue_stall`` makes a pump cycle
process nothing (the queue ages → admission sheds).  The closed-loop
sustained-load harness (robustness/loadgen.py, ``BENCH_LOAD=1``) drives
mixed traffic through exactly this machinery with chaos armed and reports
p50/p99/p999, max sustained QPS, shed rate and degraded rate.

Threading: ``submit_*``/``result`` are safe from any thread; the pump runs
either inline (call :meth:`pump` yourself — deterministic, what the tests
and the load harness do) or on the background worker started by
:meth:`start` (event-paced — the request-path convention bans bare
``time.sleep``, enforced by tests/test_conventions.py).  Outcome counters
live on ``service.counters`` (:class:`~.service.RequestCounters`) so
``service.health()`` / ``latency_summary()`` stay the one operator report.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..orchestration import chaos
from .batcher import ForecastRequest, ScenarioRequest
from .service import YieldCurveService
from .snapshot import ServingError


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


@dataclasses.dataclass(frozen=True)
class _Pending:
    """One admitted request waiting in the bounded queue."""

    ticket: int
    kind: str                   # "update" | "forecast" | "scenarios"
    payload: object             # (date, yields) | ForecastRequest | ScenarioRequest
    enqueued: float             # gateway-clock time at admission
    deadline: Optional[float]   # absolute gateway-clock deadline (None = none)


class ServingGateway:
    """Bounded, deadline-aware, load-shedding front end for one service.

    ``queue_max`` / ``queue_age_ms`` / ``deadline_ms`` default from the
    ``YFM_SERVE_QUEUE_MAX`` / ``YFM_SERVE_QUEUE_AGE_MS`` /
    ``YFM_SERVE_DEADLINE_MS`` env knobs (CLAUDE.md); constructor arguments
    win.  ``deadline_ms=0`` means no default deadline; ``queue_age_ms=0``
    disables the head-age shed (depth shedding is never disabled — the
    queue bound IS the memory bound).

    ``clock`` is injectable (monotonic seconds) so the age/deadline machinery
    is testable without wall-clock sleeps; ``slow_update_s``/``queue_stall_s``
    size the chaos seams' injected latency (0 = trigger without sleeping).
    """

    def __init__(self, service: YieldCurveService,
                 queue_max: Optional[int] = None,
                 queue_age_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 max_banked: int = 4096,
                 clock=time.monotonic,
                 slow_update_s: float = 0.05,
                 queue_stall_s: float = 0.05):
        self.service = service
        self.queue_max = int(queue_max if queue_max is not None
                             else _env_float("YFM_SERVE_QUEUE_MAX", 256))
        if self.queue_max < 1:
            raise ValueError(f"queue_max must be >= 1, got {self.queue_max}")
        self.queue_age_ms = float(
            queue_age_ms if queue_age_ms is not None
            else _env_float("YFM_SERVE_QUEUE_AGE_MS", 500.0))
        self.deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else _env_float("YFM_SERVE_DEADLINE_MS", 0.0))
        self.max_banked = int(max_banked)
        self.slow_update_s = float(slow_update_s)
        self.queue_stall_s = float(queue_stall_s)
        self._clock = clock
        self._queue: Deque[_Pending] = deque()
        self._inflight: set = set()
        self._lock = threading.Lock()
        self._pump_lock = threading.Lock()
        self._cv = threading.Condition()
        self._results: Dict[int, dict] = {}
        self._next_ticket = 0
        self._flush_cost = 0.0      # EWMA seconds of one pump's batched flush
        self._refit_cost = 0.0      # EWMA seconds of one amortized refit
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None

    # ---- admission control ------------------------------------------------

    def __len__(self) -> int:
        """Current queue depth (admitted, not yet drained by a pump)."""
        with self._lock:
            return len(self._queue)

    @property
    def counters(self):
        """The request-path outcome counters (live on the service so
        ``health()``/``latency_summary()`` report them)."""
        return self.service.counters

    def _shed(self, kind: str, detail: str, depth: int):
        self.counters.shed += 1
        # backoff hint: roughly the time the worker needs to drain what is
        # already queued (measured flush cost, floor 1 ms)
        retry_ms = max(1.0, (depth + 1) * max(self._flush_cost, 1e-3) * 1e3)
        raise ServingError(
            "admission", f"load shed: {detail} — retry after "
            f"~{retry_ms:.0f} ms", retry_after_ms=round(retry_ms, 3),
            kind=kind, depth=depth)

    def _admit(self, kind: str, payload,
               deadline_ms: Optional[float]) -> int:
        now = self._clock()
        with self._lock:
            depth = len(self._queue)
            if depth >= self.queue_max:
                self._shed(kind, f"queue full ({depth}/{self.queue_max})",
                           depth)
            if self.queue_age_ms and self._queue:
                age_ms = (now - self._queue[0].enqueued) * 1e3
                if age_ms > self.queue_age_ms:
                    self._shed(
                        kind, f"queue stalled (head age {age_ms:.0f} ms > "
                        f"{self.queue_age_ms:.0f} ms)", depth)
            dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
            ticket = self._next_ticket
            self._next_ticket += 1
            self._queue.append(_Pending(ticket, kind, payload, now,
                                        now + dl / 1e3 if dl else None))
            self.counters.admitted += 1
        self._wake.set()
        return ticket

    def submit_update(self, date, yields,
                      deadline_ms: Optional[float] = None) -> int:
        """Queue one observed-curve update; returns the result ticket."""
        y = np.asarray(yields)
        return self._admit("update", (date, y), deadline_ms)

    def submit_forecast(self, h: int,
                        quantiles: Optional[Tuple[float, ...]] = None,
                        deadline_ms: Optional[float] = None) -> int:
        """Queue an h-step predictive-density request."""
        req = ForecastRequest(int(h), tuple(quantiles) if quantiles else None)
        return self._admit("forecast", req, deadline_ms)

    def submit_scenarios(self, n: int, h: int, seed: int = 0,
                         deadline_ms: Optional[float] = None) -> int:
        """Queue an n-path scenario-fan request."""
        return self._admit("scenarios",
                           ScenarioRequest(int(n), int(h), int(seed)),
                           deadline_ms)

    # ---- results ----------------------------------------------------------

    def _finish(self, ticket: int, resp: dict) -> None:
        with self._cv:
            self._inflight.discard(ticket)
            self._results[ticket] = resp
            while len(self._results) > self.max_banked:
                self._results.pop(min(self._results))  # oldest ticket first
            self._cv.notify_all()

    def poll(self, ticket: int) -> Optional[dict]:
        """Non-blocking collect: the response dict if the ticket finished,
        ``None`` if it is still queued/in flight.  An errored ticket raises
        its structured failure (to THIS caller only)."""
        with self._cv:
            if ticket not in self._results:
                return None
            resp = self._results.pop(ticket)
        if "error" in resp:
            raise resp["error"]
        return resp

    def result(self, ticket: int, timeout: Optional[float] = None) -> dict:
        """Blocking collect.  Without a background worker the wait cannot
        make progress, so an un-pumped ticket raises immediately instead of
        deadlocking; with one, waits up to ``timeout`` (None = forever)."""
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                if ticket in self._results:
                    resp = self._results.pop(ticket)
                    break
                with self._lock:
                    pending = ticket in self._inflight or any(
                        r.ticket == ticket for r in self._queue)
                if not pending:
                    raise ServingError(
                        "gateway", f"ticket {ticket} has no banked result — "
                        "never admitted, or evicted uncollected")
                if not (self._worker and self._worker.is_alive()):
                    raise ServingError(
                        "gateway", f"ticket {ticket} is still queued and no "
                        "worker is running — call pump() or start()")
                remaining = None if t_end is None \
                    else t_end - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise ServingError(
                        "gateway", f"ticket {ticket} not answered within "
                        f"{timeout}s", ticket=ticket)
                self._cv.wait(0.05 if remaining is None
                              else min(0.05, remaining))
        if "error" in resp:
            raise resp["error"]
        return resp

    # ---- the worker loop --------------------------------------------------

    def pump(self, max_requests: Optional[int] = None) -> int:
        """One worker-loop cycle: drain up to ``max_requests`` admitted
        requests, degrade the deadline-expired ones from the last-good
        snapshot, dispatch updates in arrival order, then run every batched
        read through ONE micro-batcher flush.  Returns requests answered.

        Never raises for a request's failure — every outcome lands in that
        ticket's banked response (worker isolation).  Concurrent pump callers
        (a background worker plus an inline driver) serialize on a dedicated
        lock: the micro-batcher underneath is deliberately lock-free, so two
        interleaved flushes could strand each other's tickets."""
        with self._pump_lock:
            return self._pump_locked(max_requests)

    def _pump_locked(self, max_requests: Optional[int] = None) -> int:
        if chaos.maybe_delay("queue_stall", self.queue_stall_s):
            return 0  # a stalled worker cycle: the queue ages, nothing drains
        with self._lock:
            k = len(self._queue) if max_requests is None \
                else min(max_requests, len(self._queue))
            batch = [self._queue.popleft() for _ in range(k)]
            self._inflight.update(r.ticket for r in batch)
        if not batch:
            return 0
        now = self._clock()
        est = self._flush_cost
        run_updates: List[_Pending] = []
        run_batched: List[_Pending] = []
        est_degraded = 0
        for req in batch:
            remaining = None if req.deadline is None else req.deadline - now
            if remaining is not None and remaining <= est:
                # can't make its deadline (already expired, or the measured
                # flush cost says it will be) — degraded beats late, and
                # beats stalling the whole batch
                if remaining > 0:
                    est_degraded += 1
                self.counters.deadline += 1
                self._finish(req.ticket, self._degraded_answer(
                    req, "deadline expired before flush" if remaining <= 0
                    else "deadline unmeetable at measured flush cost"))
            elif req.kind == "update":
                run_updates.append(req)
            else:
                run_batched.append(req)
        self._prepare_batch(run_updates, run_batched)
        self._dispatch_updates(run_updates)
        if run_batched:
            self._dispatch_batched(run_batched)
        elif est_degraded:
            # the ESTIMATE degraded live requests but no flush ran to refresh
            # it: decay it, or one outlier flush (a compile, a GC pause)
            # locks the gateway into permanent degradation — a closed loop
            # must be able to find its way back to serving fresh answers
            self._flush_cost = 0.5 * self._flush_cost
        return len(batch)

    def _prepare_batch(self, run_updates: List[_Pending],
                       run_batched: List[_Pending]) -> None:
        """Hook between batch formation and dispatch: the sharded gateway
        pre-promotes the drained READ keys in one wave when its store is
        tiered (serving/tiers.py — update keys promote inside
        ``store.update_batch`` itself).  Runs AFTER deadline triage so
        already-expired requests never trigger device work; pure routing —
        no host transfer here (YFM008)."""

    def _degraded_answer(self, req: _Pending, reason: str) -> dict:
        """The degraded answer: the service's last-good snapshot state —
        version-stamped (β, P) the client can propagate itself, PSD by the
        health watch's construction, stale-flagged per DESIGN §11."""
        snap = self.service.last_good_snapshot
        self.counters.degraded += 1
        return {"kind": req.kind, "degraded": True, "stale": True,
                "reason": reason, "version": snap.meta.version,
                "beta": np.asarray(snap.beta), "P": np.asarray(snap.P)}

    def _dispatch_updates(self, reqs: List[_Pending]) -> None:
        """Answer the drained update requests (arrival order).  Hook: the
        sharded gateway overrides this to route the whole batch through the
        state store's per-shard programs instead of one-by-one dispatch."""
        for req in reqs:
            self._finish(req.ticket, self._dispatch_update(req))

    def _submit_read(self, req: _Pending) -> int:
        """Submit one batched-read request to the micro-batcher; returns the
        batcher ticket.  Hook: the sharded gateway resolves the request's
        KEY to its mesh-resident state here (device slices — no host
        gather on the routing path, YFM008)."""
        svc = self.service
        return svc.batcher.submit(svc.snapshot, req.payload)

    def _dispatch_update(self, req: _Pending) -> dict:
        chaos.maybe_delay("slow_update", self.slow_update_s)
        date, y = req.payload
        svc = self.service
        try:
            ll = svc.update(date, y)
        except ServingError as e:
            self.counters.errors += 1
            return {"error": e}
        except Exception as e:  # noqa: BLE001 — isolation: fail alone
            self.counters.errors += 1
            return {"error": ServingError(
                "update", f"unexpected failure: {e!r}", ticket=req.ticket)}
        if np.isfinite(ll):
            self.counters.completed += 1
            return {"kind": "update", "ll": float(ll),
                    "version": svc.version, "stale": svc.stale}
        # self-heal degrade inside the service: state rebuilt, NaN returned
        self.counters.degraded += 1
        return {"kind": "update", "ll": float(ll), "degraded": True,
                "stale": True, "version": svc.version}

    def _dispatch_batched(self, reqs: List[_Pending]) -> None:
        """Submit every still-live read to the micro-batcher, flush ONCE,
        collect per ticket (isolation: a poisoned ticket fails alone — the
        batcher already quarantines per ticket, DESIGN §12)."""
        svc = self.service
        t0 = self._clock()
        tickets: Dict[int, int] = {}
        for req in reqs:
            try:
                tickets[req.ticket] = self._submit_read(req)
            except ServingError as e:   # lattice rejection: fails at submit
                self.counters.errors += 1
                self._finish(req.ticket, {"error": e})
        with svc.timer.stage("flush"):
            svc.batcher.flush()         # exception-safe per ticket
        for req in reqs:
            if req.ticket not in tickets:
                continue
            try:
                out = svc.batcher.result(tickets[req.ticket])
            except ServingError as e:
                self.counters.errors += 1
                self._finish(req.ticket, {"error": e})
                continue
            if out.get("degraded"):
                # per-element poison (or chaos): relay the last-good answer
                self._finish(req.ticket, self._degraded_answer(
                    req, out.get("stage", req.kind) + " result degraded"))
            else:
                self.counters.completed += 1
                self._finish(req.ticket, {"kind": req.kind, **out})
        elapsed = self._clock() - t0
        self._flush_cost = elapsed if self._flush_cost == 0.0 \
            else 0.8 * self._flush_cost + 0.2 * elapsed

    # ---- amortized refit (docs/DESIGN.md §20) ------------------------------

    def _refit_within_deadline(self, kind, deadline_ms, degraded_fn, run_fn):
        """Deadline budget for the refit verb, same machinery as batch
        formation (DESIGN §12): the measured EWMA cost of past refits is
        checked against the caller's budget BEFORE the work starts — an
        unmeetable refit is answered immediately from the last-good state,
        stale-flagged, instead of blowing the deadline; the estimate decays
        (×0.5) on every degraded answer so one compile outlier cannot lock
        permanent degradation."""
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        # the whole verb runs under the PUMP lock: the service/store has no
        # internal locks — every other state-mutating verb is serialized
        # through the queue + _pump_locked, and an unserialized refit racing
        # a flushing update would tear the (snapshot, state, bank) triple.
        # The lock wait itself counts against the measured cost (honest: a
        # busy gateway's refits ARE that slow), and the EWMA read-modify-
        # write rides the same lock.
        with self._pump_lock:
            if dl and self._refit_cost and self._refit_cost * 1e3 > dl:
                self._refit_cost = 0.5 * self._refit_cost
                return degraded_fn(
                    f"refit cost ~{self._refit_cost * 2e3:.0f} ms exceeds "
                    f"the {dl:.0f} ms deadline")
            t0 = self._clock()
            out = run_fn()
            elapsed = self._clock() - t0
            self._refit_cost = elapsed if self._refit_cost == 0.0 \
                else 0.8 * self._refit_cost + 0.2 * elapsed
            return out

    def refit(self, history, deadline_ms: Optional[float] = None, *,
              amortizer=None, polish_iters: int = 1, date=None) -> dict:
        """Request-path re-estimation: the amortized surrogate's forward
        pass + one Newton polish step + state rebuild, inside the deadline
        budget (``YieldCurveService.refit`` does the work; this wrapper owns
        the §12 deadline/degrade accounting).  Returns the update-shaped
        response dict — ``{"ll", "version", "stale"}`` fresh, or the
        degraded last-good answer when the measured refit cost cannot make
        the deadline."""
        def run():
            try:
                ll = self.service.refit(history, amortizer=amortizer,
                                        polish_iters=polish_iters, date=date)
            except ServingError as e:
                self.counters.errors += 1
                return {"error": e}
            if np.isfinite(ll):
                self.counters.completed += 1
                return {"kind": "refit", "ll": float(ll),
                        "version": self.service.version,
                        "stale": self.service.stale}
            self.counters.degraded += 1
            return {"kind": "refit", "ll": float(ll), "degraded": True,
                    "stale": True, "version": self.service.version}

        req = _Pending(-1, "refit", None, self._clock(), None)
        return self._refit_within_deadline(
            "refit", deadline_ms,
            lambda reason: self._degraded_answer(req, reason), run)

    # ---- background worker -------------------------------------------------

    def start(self, poll_s: float = 0.005) -> "ServingGateway":
        """Run the pump on a daemon thread (event-paced, no bare sleeps)."""
        if self._worker and self._worker.is_alive():
            return self
        self._stop.clear()

        def _run():
            while not self._stop.is_set():
                if self.pump() == 0:
                    self._wake.wait(poll_s)
                    self._wake.clear()

        self._worker = threading.Thread(target=_run, daemon=True,
                                        name="yfm-serving-gateway")
        self._worker.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._worker is not None:
            self._worker.join(timeout)
            self._worker = None


class ShardedGateway(ServingGateway):
    """The gateway in front of a :class:`~.store.ShardedStateStore` — same
    admission control / deadlines / shedding machinery, but every request
    names a KEY (``(model_string, task_id)``) and the pump routes work to
    the mesh shard that owns that key's state (DESIGN §16):

    - updates drain into ONE ``store.update_batch`` call — grouped by owning
      shard, padded onto the lattice's update buckets, one donated SPMD
      program per (shard, bucket), O(batch) host traffic;
    - forecasts/scenarios resolve their key to DEVICE slices
      (``store.snapshot_of``) and ride the shared micro-batcher exactly as
      before — host transfer happens only in the batcher's response path;
    - a deadline-degraded request answers from that KEY's banked last-good
      state (``store.last_good_snapshot_of``), stale-flagged as ever.

    The store duck-types the service surface the base gateway reads
    (``counters``/``timer``/``batcher``), so health and latency stay ONE
    operator report.
    """

    def __init__(self, store, **kwargs):
        super().__init__(store, **kwargs)
        self.store = store
        self._hub = None

    def attach_hub(self, hub) -> None:
        """Wire a :class:`~.streams.ScenarioStreamHub` into the pump: every
        cycle's ACCEPTED update keys are reported through
        ``hub.notify_updated`` (one delta-refresh wave per touched fan
        block) and a published refit through ``hub.notify_refit`` (full
        recompute — the delta chain is not honest across a parameter
        change).  ``ScenarioStreamHub(gateway)`` calls this itself.

        Blast-radius wiring (DESIGN §24): a shard-loss rebuild wave also
        breaks the affected keys' delta chains — the rebuilt state is
        bit-identical for ungapped keys, but a gapped key's standing fan
        would otherwise keep delta-refreshing off silently-wrong state, so
        every affected key gets a full recompute."""
        self._hub = hub
        add = getattr(self.store, "add_rebuild_listener", None)
        if add is not None:
            add(hub.notify_refit)

    # ---- key-addressed admission -----------------------------------------

    def submit_update(self, date, yields, deadline_ms=None, *,
                      key=None) -> int:
        if key is None:
            raise ServingError("admission", "sharded updates need key= (the "
                               "(model_string, task_id) state address)")
        return self._admit("update", (key, date, np.asarray(yields)),
                           deadline_ms)

    def submit_forecast(self, h, quantiles=None, deadline_ms=None, *,
                        key=None) -> int:
        if key is None:
            raise ServingError("admission", "sharded forecasts need key=")
        req = ForecastRequest(int(h), tuple(quantiles) if quantiles else None)
        return self._admit("forecast", (key, req), deadline_ms)

    def submit_scenarios(self, n, h, seed=0, deadline_ms=None, *,
                         key=None) -> int:
        if key is None:
            raise ServingError("admission", "sharded scenarios need key=")
        return self._admit("scenarios",
                           (key, ScenarioRequest(int(n), int(h), int(seed))),
                           deadline_ms)

    # ---- shard-routed dispatch -------------------------------------------

    def _dispatch_updates(self, reqs: List[_Pending]) -> None:
        if not reqs:
            return
        chaos.maybe_delay("slow_update", self.slow_update_s)
        store = self.store
        with store.timer.stage("update"):
            outs = store.update_batch(
                [(r.payload[0], r.payload[2]) for r in reqs],
                dates=[r.payload[1] for r in reqs])
        accepted = []
        for req, out in zip(reqs, outs):
            if "error" in out:
                self.counters.errors += 1
                self._finish(req.ticket, out)
            elif out.get("degraded"):
                self.counters.degraded += 1
                self._finish(req.ticket, {"kind": "update", **out})
            else:
                self.counters.completed += 1
                self._finish(req.ticket, {"kind": "update", **out})
                accepted.append(req.payload[0])
        if self._hub is not None and accepted:
            # one delta-refresh wave per touched fan block (streams.py) —
            # key routing + a donated device launch, no host transfer here
            self._hub.notify_updated(accepted)

    def _prepare_batch(self, run_updates: List[_Pending],
                       run_batched: List[_Pending]) -> None:
        """Batch-promote the cycle's READ keys before any per-request
        ``snapshot_of`` resolution: a tiered store (or fleet) thaws every
        warm/cold read key of this wave in one batched promotion, so a read
        burst against demoted state costs one device dispatch per shard —
        never one per request.  Update keys are handled inside
        ``store.update_batch``; stores without a tier seam have no
        ``prepare_reads`` and skip.  Pure key routing (YFM008).

        Recovery ordering (DESIGN §24): a store left with LOST shards (an
        explicit ``mark_shard_lost`` between pumps — update-path losses
        rebuild inside ``update_batch`` itself) is rebuilt HERE, before any
        read resolves ``snapshot_of`` against a dead shard — the batched
        rebuild wave is the read path's promotion analogue."""
        if getattr(self.store, "rebuilding", False):
            recover = getattr(self.store, "recover_lost_shards", None)
            if recover is not None:
                recover()
        prepare = getattr(self.store, "prepare_reads", None)
        if prepare is None or not run_batched:
            return
        prepare([r.payload[0] for r in run_batched])

    def _submit_read(self, req: _Pending) -> int:
        key, payload = req.payload
        return self.store.batcher.submit(self.store.snapshot_of(key), payload)

    def refit(self, history, deadline_ms=None, *, key=None, amortizer=None,
              polish_iters: int = 1, date=None) -> dict:
        """Key-addressed amortized refit: surrogate forward pass + one
        polish step (``estimation.amortize.amortized_refit``), published
        STRAIGHT into the key's live slot through
        ``store.publish_refit`` (ROADMAP 2c) — the state stays mesh-resident
        and continuously servable.  Deadline semantics as the base gateway:
        an unmeetable refit answers from THIS key's banked last-good
        state."""
        if key is None:
            raise ServingError("refit", "sharded refits need key= (the "
                               "(model_string, task_id) state address)")
        store = self.store
        # fleet stores have no single .spec — resolve per key
        spec = store.spec_for(key) if hasattr(store, "spec_for") \
            else store.spec

        def run():
            from ..estimation import amortize as _amortize

            try:
                raw, ll = _amortize.amortized_refit(
                    spec, history, amortizer=amortizer,
                    polish_iters=polish_iters)
            except ValueError as e:  # no trained amortizer registered
                self.counters.errors += 1
                return {"error": ServingError("refit", str(e), key=key)}
            if raw is None:
                # surrogate sentinel: keep the slot, answer degraded
                return self._degraded_answer(
                    req, "surrogate prediction is non-finite")
            from ..models.params import transform_params
            import jax.numpy as _jnp

            params = np.asarray(transform_params(
                spec, _jnp.asarray(raw, dtype=spec.dtype)))
            try:
                out = store.publish_refit(key, params, history=history,
                                          beta=None, P=None)
            except ServingError as e:
                self.counters.errors += 1
                return {"error": e}
            self.counters.completed += 1
            if self._hub is not None:
                # the key's params moved: its standing fan must recompute
                # from scratch (delta refresh is not honest across a refit)
                self._hub.notify_refit([key])
            return {"kind": "refit", "key": key, "ll": float(ll), **out}

        req = _Pending(-1, "refit", (key, None), self._clock(), None)
        return self._refit_within_deadline(
            "refit", deadline_ms,
            lambda reason: self._degraded_answer(req, reason), run)

    def _degraded_answer(self, req: _Pending, reason: str) -> dict:
        key = req.payload[0]
        try:
            snap = self.store.last_good_snapshot_of(key)
        except ServingError as e:
            # unknown/evicted key: the degraded answer itself must never
            # raise out of the pump (worker-isolation contract — a raise
            # here would strand the batch's tickets and kill the worker
            # thread); THIS ticket gets the structured error instead
            self.counters.errors += 1
            return {"error": e}
        self.counters.degraded += 1
        return {"kind": req.kind, "key": key, "degraded": True, "stale": True,
                "reason": reason, "version": snap.meta.version,
                "beta": np.asarray(snap.beta), "P": np.asarray(snap.P)}
