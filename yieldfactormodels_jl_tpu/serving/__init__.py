"""Online serving layer (docs/DESIGN.md §9; QUICKSTART "Serving").

Turns the batch reproduction into the serving stack the ROADMAP asks for:
snapshot registry over the merged SQLite DBs (``snapshot``), O(1) jitted
recursive filter updates (``online``), shape-bucketed micro-batching onto a
small lattice of precompiled programs (``batcher``), and the
``YieldCurveService`` driver with per-stage latency accounting (``service``).
"""

from .batcher import (BucketLattice, DEFAULT_LATTICE, ForecastRequest,
                      MicroBatcher, ScenarioRequest)
from .online import (ONLINE_ENGINES, OnlineState, reset_trace_counts,
                     scenario_paths, trace_counts, update, update_k)
from .service import YieldCurveService
from .snapshot import (ServingError, ServingSnapshot, SnapshotMeta,
                       SnapshotRegistry, freeze_snapshot, load_snapshot)

__all__ = [
    "BucketLattice",
    "DEFAULT_LATTICE",
    "ForecastRequest",
    "MicroBatcher",
    "ScenarioRequest",
    "ONLINE_ENGINES",
    "OnlineState",
    "reset_trace_counts",
    "scenario_paths",
    "trace_counts",
    "update",
    "update_k",
    "YieldCurveService",
    "ServingError",
    "ServingSnapshot",
    "SnapshotMeta",
    "SnapshotRegistry",
    "freeze_snapshot",
    "load_snapshot",
]
