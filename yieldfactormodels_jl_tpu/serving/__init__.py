"""Online serving layer (docs/DESIGN.md §9, §12; QUICKSTART "Serving").

Turns the batch reproduction into the serving stack the ROADMAP asks for:
snapshot registry over the merged SQLite DBs (``snapshot``), O(1) jitted
recursive filter updates (``online``), shape-bucketed micro-batching onto a
small lattice of precompiled programs (``batcher``), the
``YieldCurveService`` driver with per-stage latency accounting (``service``),
and the resilient request pipeline in front of it all — bounded queue,
admission control/load shedding, per-request deadlines with degraded
last-good answers (``gateway``) — and the device-scale half: mesh-resident
per-user filter states sharded across the device mesh with shard-routed
donated micro-batch updates (``store``, ``ShardedGateway``;
docs/DESIGN.md §16) — extended past HBM by the tiered residency hierarchy:
hot device slots / packed warm host records / cold snapshot registry with
LRU promotion-on-miss, batched promotion waves, a capacity ledger, and the
multi-store fleet seam (``tiers``; docs/DESIGN.md §21) — and the streaming
subscription layer on top: standing per-user stress-fan subscriptions,
device-resident next to the filter state, delta-refreshed in one donated
wave per accepted update (``streams``; docs/DESIGN.md §23) — all of it
treating shard loss as a recoverable fault domain: a bounded per-shard ring
journal of accepted updates with watermark gap detection (``journal``),
degraded last-good answers while lost, and failover rebuild waves that
replay each key's journal suffix to bit-identical post-replay state
(docs/DESIGN.md §24).
"""

from .batcher import (BucketLattice, DEFAULT_LATTICE, ForecastRequest,
                      MicroBatcher, ScenarioRequest)
from .gateway import ServingGateway, ShardedGateway
from .online import (ONLINE_ENGINES, OnlineState, reset_trace_counts,
                     scenario_paths, trace_counts, update, update_k)
from .service import RequestCounters, YieldCurveService
from .snapshot import (ServingError, ServingSnapshot, SnapshotMeta,
                       SnapshotRegistry, freeze_snapshot,
                       freeze_snapshots_batch, load_snapshot)
from .journal import JournalRecord, UpdateJournal
from .store import RecoveryLedger, ShardedStateStore
from .streams import FanCounters, ScenarioStreamHub
from .tiers import StoreFleet, TieredStateStore, TierLedger, WarmTier

__all__ = [
    "BucketLattice",
    "FanCounters",
    "JournalRecord",
    "RecoveryLedger",
    "UpdateJournal",
    "ScenarioStreamHub",
    "ShardedGateway",
    "ShardedStateStore",
    "StoreFleet",
    "TieredStateStore",
    "TierLedger",
    "WarmTier",
    "DEFAULT_LATTICE",
    "ForecastRequest",
    "MicroBatcher",
    "RequestCounters",
    "ScenarioRequest",
    "ServingGateway",
    "ONLINE_ENGINES",
    "OnlineState",
    "reset_trace_counts",
    "scenario_paths",
    "trace_counts",
    "update",
    "update_k",
    "YieldCurveService",
    "ServingError",
    "ServingSnapshot",
    "SnapshotMeta",
    "SnapshotRegistry",
    "freeze_snapshot",
    "freeze_snapshots_batch",
    "load_snapshot",
]
