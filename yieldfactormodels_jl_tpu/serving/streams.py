"""Streaming scenario subscriptions: resident stress fans with delta
refresh (docs/DESIGN.md §23).

Every stress fan before this module was recomputed from scratch per request
(``YieldCurveService.stress_fan`` → one ``scenario._jitted_fan`` launch per
call).  A :class:`ScenarioStreamHub` turns the fan into a STANDING product:
``subscribe(key, shocks=...)`` allocates a fan slot whose density fan lives
device-resident next to the filter state, and every ACCEPTED online update
triggers a **delta refresh** — one donated, compile-once
:func:`_jitted_fan_refresh` launch that re-runs the
``ops/forecast.density_fan`` recursion from the NEW posterior for ALL of a
block's dirty fans at once, the subscription (lane) axis riding the TPU lane
dimension.  Refit/rebuild/version breaks fall back to a full
``scenario.stress_fan`` recompute per subscription (the honest path when the
parameters themselves moved).

Fan-slot lifecycle (one ``_FanBlock`` per (spec, shocks, horizon) shape
bucket, slot machinery generalized from ``serving/store.py``/``tiers.py``):

    subscribe → slot allocated (free-list pop), lane marked DIRTY
    update    → dirty lanes refreshed in ONE donated wave; each refreshed
                lane records a PENDING (version, time) attempt
    answer    → the pending attempt settles host-side: the kernel's
                ``refreshed`` flag promotes it to the GOOD stamp, or parks
                the lane DEGRADED (the kernel kept the old fan — in-kernel
                degrade-from-last-fan, which is also what makes the donated
                buffers aliasable); answers past the ``YFM_FAN_STALE_MS``
                budget are stale-flagged and counted degraded instead of
                ever blocking the update path (§12 discipline)
    unsubscribe → slot back on the free list (buffer rows are inert)

Donation table (the §14 value-use rule — every donated buffer's values flow
into the same-shaped output that aliases it):

    means (S, h, N, C)    → kept-or-refreshed means   (donated)
    covs  (S, h, N, N, C) → kept-or-refreshed covs    (donated)
    codes (S, C) / refreshed (C,) are small and NOT donated.

Chaos seams (orchestration/chaos.py): ``refresh_storm`` drops one whole
refresh wave — its lanes stay dirty and answer degraded until the next
update heals them; ``fan_stale`` forces one answer to be served degraded
from the last promoted fan.  Both are exercised by tests/test_streams.py
and the ``load-fan-bench`` harness.

Threading: ONE hub lock guards all slot metadata AND every device launch /
answer materialization — the donated wave consumes the fan buffers, so an
answer's slice must never race a wave's donation.  The hub subscribes to
``YieldCurveService.add_update_listener`` (service mode) or is attached to
a :class:`~.gateway.ShardedGateway` (``attach_hub`` — store mode, per-key
dirty marking through :meth:`notify_updated`).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from functools import lru_cache
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import make_trace_counter, register_engine_cache
from ..models.specs import ModelSpec
from ..orchestration import chaos
from ..robustness import taxonomy as tax
from .snapshot import ServingError

# trace counters (config.make_trace_counter): incremented INSIDE traced
# bodies — the no-recompile tests pin trace_counts["fan_refresh"] == 1
# across whole subscribe/update/answer lifecycles
trace_counts, note_trace, reset_trace_counts = make_trace_counter()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    return float(raw) if raw else default


# ---------------------------------------------------------------------------
# the delta-refresh program
# ---------------------------------------------------------------------------

def refresh_signature(spec: ModelSpec, n_shocks: int, horizon: int,
                      capacity: int, shared: bool = False) -> Dict[str, tuple]:
    """The (shape, dtype) staging signature of one :func:`_jitted_fan_refresh`
    launch — the SINGLE source both the hub's buffer allocation and the
    IR-audit manifest avals build from (staging parity: a second shape
    recipe is how warmup/live retrace mismatches are born).  ``shared`` is
    the service-mode variant: every lane refreshes from the SAME posterior,
    so params/beta/P stage unbatched and the lane broadcast lives inside
    the kernel (zero staging dispatches on the per-update hot path)."""
    dt = jnp.dtype(spec.dtype)
    lane = () if shared else (capacity,)
    return {
        "params": ((spec.n_params,) + lane, dt),
        "beta": ((spec.state_dim,) + lane, dt),
        "P": ((spec.state_dim, spec.state_dim) + lane, dt),
        "active": ((capacity,), jnp.dtype(bool)),
        "means": ((n_shocks, horizon, spec.N, capacity), dt),
        "covs": ((n_shocks, horizon, spec.N, spec.N, capacity), dt),
        "codes": ((n_shocks, capacity), jnp.dtype(tax.CODE_DTYPE)),
        "refreshed": ((capacity,), jnp.dtype(bool)),
    }


@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_fan_refresh(spec: ModelSpec, shocks: tuple, horizon: int,
                        capacity: int, shared: bool = False):
    """ONE donated delta-refresh program for a whole fan block:

        (params (P, C), beta (Ms, C), P (Ms, Ms, C), active (C,),
         means (S, h, N, C) DONATED, covs (S, h, N, N, C) DONATED,
         codes (S, C) int32, refreshed (C,) bool)
            → (means', covs', codes', refreshed')

    Per ACTIVE lane the ``density_fan`` recursion re-runs from that lane's
    new posterior; a lane whose fan comes back poisoned (non-zero combined
    taxonomy code) KEEPS its previous fan values in-kernel — the
    degrade-from-last-fan policy is part of the program, which is exactly
    what lets the big buffers be donated (kept-old values flow through to
    the aliased outputs).  Inactive lanes pass everything through untouched.
    ``refreshed`` reports, per lane, whether THIS wave's values were taken.
    The subscription axis C rides the TPU lanes (batch-last rule).

    ``shared=True`` is the service-mode program: ONE live posterior feeds
    every lane, so params (P,) / beta (Ms,) / P (Ms, Ms) arrive unbatched
    (zero staging dispatches per update — the service's snapshot leaves go
    straight in) and the fan computes ONCE, broadcast across the lane axis
    in-kernel."""
    from ..estimation.scenario import _shock_arrays
    from ..models.params import unpack_kalman
    from ..ops.forecast import density_fan

    def one_fan(params, beta, P):
        kp = unpack_kalman(spec, params)
        shifts, vols, _, _ = _shock_arrays(shocks, spec.state_dim,
                                           beta.dtype)
        return density_fan(spec, kp, beta, P, shifts, vols, horizon)

    if shared:
        def refresh(params, beta, P, active, means, covs, codes, refreshed):
            note_trace("fan_refresh")
            out = one_fan(params, beta, P)
            use = active & (tax.combine(out["codes"]) == tax.OK)   # (C,)
            m = jnp.where(use, out["means"][..., None], means)
            c = jnp.where(use, out["covs"][..., None], covs)
            new_codes = jnp.where(active, out["codes"][:, None], codes)
            refr = jnp.where(active, use, refreshed)
            return m, c, new_codes, refr

        return jax.jit(refresh, donate_argnums=(4, 5))

    def lane(params, beta, P, act, m_old, c_old, code_old, refr_old):
        out = one_fan(params, beta, P)
        use = act & (tax.combine(out["codes"]) == tax.OK)
        m = jnp.where(use, out["means"], m_old)
        c = jnp.where(use, out["covs"], c_old)
        codes = jnp.where(act, out["codes"], code_old)
        refr = jnp.where(act, use, refr_old)
        return m, c, codes, refr

    over_lanes = jax.vmap(lane, in_axes=(-1, -1, -1, 0, -1, -1, -1, 0),
                          out_axes=(-1, -1, -1, 0))

    def refresh(params, beta, P, active, means, covs, codes, refreshed):
        note_trace("fan_refresh")
        return over_lanes(params, beta, P, active, means, covs, codes,
                          refreshed)

    return jax.jit(refresh, donate_argnums=(4, 5))


# ---------------------------------------------------------------------------
# fan blocks: slot-addressed resident fan state
# ---------------------------------------------------------------------------

class _FanBlock:
    """One (spec, shocks, horizon) shape bucket of resident fan slots —
    device buffers in the refresh program's staging layout plus per-lane
    host metadata.  All access runs under the hub lock."""

    def __init__(self, spec: ModelSpec, shocks: tuple, horizon: int,
                 capacity: int):
        self.spec, self.shocks, self.horizon = spec, shocks, horizon
        self.names = tuple(s.name for s in shocks)
        self.capacity = 0
        self.keys: List[object] = []
        self.slot_of: Dict[object, int] = {}
        self.free: List[int] = []
        self.dirty: List[bool] = []
        self.pending: List[Optional[tuple]] = []   # (version, attempt_time)
        self.good: List[Optional[tuple]] = []      # (version, computed_at)
        self.degraded: List[bool] = []
        sig = refresh_signature(spec, len(shocks), horizon, capacity)
        self.means = jnp.zeros(*sig["means"])
        self.covs = jnp.zeros(*sig["covs"])
        self.codes = jnp.zeros(*sig["codes"])
        self.refreshed = jnp.zeros(*sig["refreshed"])
        # host-side answer cache: ONE bulk materialization per wave (lazy,
        # at the first answer — the response boundary), then every
        # subscriber's answer is a NumPy slice.  None = invalidated by the
        # last wave/recompute/grow.
        self.host: Optional[dict] = None
        # active-mask cache: the wave's (C,) lane mask is keyed on the
        # dirty-lane tuple (usually "all subscribed"), so steady-state
        # waves stage it with zero device dispatches
        self._masks: Dict[tuple, object] = {}
        self._grow_meta(capacity)

    def _grow_meta(self, new_capacity: int) -> None:
        pad = new_capacity - self.capacity
        self.free.extend(reversed(range(self.capacity, new_capacity)))
        self.keys.extend([None] * pad)
        self.dirty.extend([False] * pad)
        self.pending.extend([None] * pad)
        self.good.extend([None] * pad)
        self.degraded.extend([False] * pad)
        self.capacity = new_capacity

    def grow(self) -> None:
        """Double the lane capacity: zero-pad every buffer on the lane axis.
        The refresh program is keyed on capacity, so the NEXT wave retraces
        once at the new width (documented cost of an overflowing block —
        size the initial ``capacity`` at the expected subscriber count)."""
        new_capacity = max(1, self.capacity) * 2
        pad = new_capacity - self.capacity

        def widen(buf):
            return jnp.concatenate(
                [buf, jnp.zeros(buf.shape[:-1] + (pad,), dtype=buf.dtype)],
                axis=-1)

        self.means = widen(self.means)
        self.covs = widen(self.covs)
        self.codes = widen(self.codes)
        self.refreshed = widen(self.refreshed)
        self.host = None
        self._masks.clear()
        self._grow_meta(new_capacity)

    def active_dirty(self) -> List[int]:
        return [i for i in range(self.capacity)
                if self.keys[i] is not None and self.dirty[i]]


@dataclasses.dataclass
class FanCounters:
    """Subscription-path outcome counters, surfaced by ``hub.health()``
    (the §12 one-operator-report convention).  ``refreshes`` counts LANES
    delta-refreshed (a wave of k dirty fans is one launch, k refreshes);
    ``dropped_waves`` counts ``refresh_storm`` hits — their lanes answer
    degraded until the next update heals them."""

    subscribed: int = 0
    waves: int = 0
    refreshes: int = 0
    full_recomputes: int = 0
    dropped_waves: int = 0
    answers: int = 0
    degraded_answers: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# the hub
# ---------------------------------------------------------------------------

class ScenarioStreamHub:
    """Standing per-user scenario subscriptions over one serving source.

    ``source`` is either a :class:`~.service.YieldCurveService` (the hub
    registers itself as an update listener: every accepted update delta-
    refreshes every subscription; re-filter/refit events trigger the full
    recompute path) or a :class:`~.gateway.ShardedGateway` /
    :class:`~.store.ShardedStateStore` (per-key dirty marking through
    :meth:`notify_updated`, wired by ``ShardedGateway.attach_hub``).

    ``stale_ms`` is the fan staleness budget (``YFM_FAN_STALE_MS`` when
    None; 0 = no budget): an answer whose promoted fan is older is served
    anyway — stale-flagged and counted degraded — never recomputed inline
    on the answer path.  ``capacity`` sizes each fan block's initial lane
    count (blocks double on overflow, one retrace per doubling).  ``clock``
    is injectable (monotonic seconds) so staleness is testable without
    wall-clock sleeps."""

    def __init__(self, source, *, stale_ms: Optional[float] = None,
                 capacity: int = 8, clock=time.monotonic):
        self.stale_ms = float(
            stale_ms if stale_ms is not None
            else _env_float("YFM_FAN_STALE_MS", 0.0))
        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")
        self.clock = clock
        self.counters = FanCounters()
        self._lock = threading.Lock()
        self._blocks: Dict[tuple, _FanBlock] = {}
        self._sub_block: Dict[object, tuple] = {}   # key → block key
        self.service = None
        self.store = None
        if hasattr(source, "add_update_listener"):
            self.service = source
            source.add_update_listener(self._on_service_event)
        elif hasattr(source, "attach_hub"):
            self.store = source.store
            source.attach_hub(self)
        elif hasattr(source, "snapshot_of"):
            self.store = source
            # blast-radius wiring (DESIGN §24): a rebuild wave breaks the
            # affected keys' delta chains — full recompute from the rebuilt
            # state (the gateway path wires this through attach_hub)
            add = getattr(source, "add_rebuild_listener", None)
            if add is not None:
                add(self.notify_refit)
        else:
            raise ServingError(
                "streams", f"unsupported subscription source "
                f"{type(source).__name__} — need a YieldCurveService, a "
                f"ShardedGateway or a sharded state store")

    # ---- subscription lifecycle ------------------------------------------

    def subscribe(self, key, shocks="standard", horizon: int = 12):
        """Open a standing fan subscription for ``key``: allocate a lane in
        the (spec, shocks, horizon) block and fill it with an initial
        refresh wave (the same compile-once program every later delta
        refresh uses).  ``shocks`` is ``"standard"``, a tuple of
        :class:`~..estimation.scenario.ShockSpec` (including
        ``replay_episodes`` output), or a tuple of
        :class:`~..program.shocks.ShockRule` grammar rules (compiled via
        ``program.shocks.compile_shocks``).  Returns ``key``."""
        from ..estimation.scenario import ShockSpec, standard_fan

        with self._lock:
            if key in self._sub_block:
                raise ServingError("streams", f"key {key!r} already has a "
                                   f"subscription — unsubscribe first",
                                   key=key)
            spec = self._spec_for(key)
            if isinstance(shocks, str):
                if shocks != "standard":
                    raise ServingError(
                        "streams", f"unknown shock fan {shocks!r} — pass "
                        f"'standard', ShockSpec tuples or ShockRule "
                        f"grammar rules", key=key)
                shocks = standard_fan(spec)
            shocks = tuple(shocks)
            if shocks and not all(isinstance(s, ShockSpec) for s in shocks):
                from ..program.shocks import ShockRule, compile_shocks

                if all(isinstance(s, ShockRule) for s in shocks):
                    shocks = compile_shocks(shocks, spec)
                else:
                    raise ServingError(
                        "streams", "shocks must be ShockSpec instances or "
                        "ShockRule grammar rules (not a mix)", key=key)
            if not shocks:
                raise ServingError("streams", "a subscription needs at "
                                   "least one shock", key=key)
            if int(horizon) < 1:
                raise ServingError("streams",
                                   f"horizon must be >= 1, got {horizon}",
                                   key=key)
            bkey = (spec, shocks, int(horizon))
            block = self._blocks.get(bkey)
            if block is None:
                block = _FanBlock(spec, shocks, int(horizon), self.capacity)
                self._blocks[bkey] = block
            if not block.free:
                block.grow()
            slot = block.free.pop()
            block.keys[slot] = key
            block.slot_of[key] = slot
            block.dirty[slot] = True
            block.pending[slot] = None
            block.good[slot] = None
            block.degraded[slot] = False
            self._sub_block[key] = bkey
            self.counters.subscribed += 1
            self._refresh_wave(block)   # initial fill, same program
        return key

    def unsubscribe(self, key) -> None:
        with self._lock:
            bkey = self._sub_block.pop(key, None)
            if bkey is None:
                raise ServingError("streams", f"no subscription for {key!r}",
                                   key=key)
            block = self._blocks[bkey]
            slot = block.slot_of.pop(key)
            block.keys[slot] = None
            block.dirty[slot] = False
            block.pending[slot] = None
            block.good[slot] = None
            block.degraded[slot] = False
            block.free.append(slot)   # buffer rows are inert until reuse
            self.counters.subscribed -= 1

    def subscriptions(self) -> tuple:
        with self._lock:
            return tuple(self._sub_block)

    # ---- source plumbing --------------------------------------------------

    def _spec_for(self, key) -> ModelSpec:
        if self.service is not None:
            return self.service.snapshot.spec
        if hasattr(self.store, "spec_for"):
            return self.store.spec_for(key)
        return self.store.spec

    def _snapshot_for(self, key):
        """The key's CURRENT posterior — device leaves for the store path
        (``snapshot_of``), the service's live snapshot otherwise."""
        if self.service is not None:
            return self.service.snapshot
        return self.store.snapshot_of(key)

    def _on_service_event(self, event: str) -> None:
        """Service-mode listener: accepted updates delta-refresh every
        subscription; rebuild/refit events invalidate the delta chain and
        fall back to the full ``stress_fan`` recompute."""
        with self._lock:
            if event == "update":
                for block in self._blocks.values():
                    self._mark_dirty_block(block)
                    self._refresh_wave(block)
            else:   # "rebuild" | "refit": the base state/params moved
                for block in self._blocks.values():
                    lanes = [i for i in range(block.capacity)
                             if block.keys[i] is not None]
                    self._full_recompute(block, lanes)

    def notify_updated(self, keys) -> None:
        """Store-mode dirty marking: the gateway pump reports this cycle's
        ACCEPTED update keys; their fans delta-refresh in one wave per
        touched block.  Pure key routing + device launches — no host
        transfer on this path (YFM008)."""
        with self._lock:
            touched = self._mark_dirty(keys)
            for block in touched:
                self._refresh_wave(block)

    def notify_refit(self, keys) -> None:
        """Store-mode refit/version-break notification: the named keys'
        fans recompute from scratch (delta refresh is not an honest answer
        when the parameters themselves moved)."""
        with self._lock:
            for key in keys:
                bkey = self._sub_block.get(key)
                if bkey is None:
                    continue
                block = self._blocks[bkey]
                self._full_recompute(block, [block.slot_of[key]])

    def _mark_dirty(self, keys) -> List[_FanBlock]:
        touched: List[_FanBlock] = []
        for key in keys:
            bkey = self._sub_block.get(key)
            if bkey is None:
                continue
            block = self._blocks[bkey]
            block.dirty[block.slot_of[key]] = True
            if block not in touched:
                touched.append(block)
        return touched

    def _mark_dirty_block(self, block: _FanBlock) -> None:
        for i in range(block.capacity):
            if block.keys[i] is not None:
                block.dirty[i] = True

    # ---- the refresh state machine ----------------------------------------

    def _refresh_wave(self, block: _FanBlock) -> int:
        """Delta-refresh every dirty lane of ``block`` in ONE donated
        launch.  Runs under the hub lock; device-side only (the pending →
        good promotion reads device flags at ANSWER time, never here —
        YFM008 routing hygiene).  A ``refresh_storm`` chaos hit drops the
        whole wave: its lanes stay dirty and answer degraded until the
        next update retries them."""
        lanes = block.active_dirty()
        if not lanes:
            return 0
        if chaos.should_inject("refresh_storm"):
            self.counters.dropped_waves += 1
            return 0
        params, beta, P, active, versions = self._stage_wave(block, lanes)
        fn = _jitted_fan_refresh(block.spec, block.shocks, block.horizon,
                                 block.capacity,
                                 shared=self.service is not None)
        block.means, block.covs, block.codes, block.refreshed = fn(
            params, beta, P, active, block.means, block.covs, block.codes,
            block.refreshed)
        block.host = None   # answers re-materialize at the next fan()
        now = self.clock()
        for i, v in zip(lanes, versions):
            block.dirty[i] = False
            block.pending[i] = (v, now)
        self.counters.waves += 1
        self.counters.refreshes += len(lanes)
        return len(lanes)

    def _stage_wave(self, block: _FanBlock, lanes: List[int]):
        """Stage one wave's posterior inputs in the refresh program's
        layout (``refresh_signature`` — lane axis LAST).  Device-side:
        service mode hands the one live posterior's leaves straight to the
        ``shared`` program (zero staging dispatches); store mode stacks
        each key's mesh-resident ``snapshot_of`` leaves (device slices, no
        host gather — YFM008)."""
        C = block.capacity
        active = block._masks.get(tuple(lanes))
        if active is None:
            mask = np.zeros((C,), dtype=bool)
            mask[lanes] = True
            active = block._masks[tuple(lanes)] = jnp.asarray(mask)
        dt = block.spec.dtype
        if self.service is not None:
            # shared-posterior program: the snapshot's leaves go straight
            # in, unbatched — the lane broadcast happens in-kernel
            snap = self.service.snapshot
            params = jnp.asarray(snap.params, dtype=dt)
            beta = jnp.asarray(snap.beta, dtype=dt)
            P = jnp.asarray(snap.P, dtype=dt)
            versions = [snap.meta.version] * len(lanes)
            return params, beta, P, active, versions
        snaps = {i: self.store.snapshot_of(block.keys[i]) for i in lanes}
        fill = snaps[lanes[0]]
        cols = [snaps.get(i, fill) for i in range(C)]
        # the store's snapshots are committed to their shard's device;
        # re-pin the staged wave next to the block buffers (a device-side
        # copy, not a host gather) so the donated launch sees one device
        dev = next(iter(block.refreshed.devices()))
        params = jax.device_put(
            jnp.stack([jnp.asarray(s.params, dtype=dt) for s in cols],
                      axis=-1), dev)
        beta = jax.device_put(
            jnp.stack([jnp.asarray(s.beta, dtype=dt) for s in cols],
                      axis=-1), dev)
        P = jax.device_put(
            jnp.stack([jnp.asarray(s.P, dtype=dt) for s in cols], axis=-1),
            dev)
        versions = [snaps[i].meta.version for i in lanes]
        return params, beta, P, active, versions

    def _full_recompute(self, block: _FanBlock, lanes: List[int]) -> int:
        """The fallback when the delta chain breaks (refit, §11 rebuild,
        version break): a from-scratch ``scenario.stress_fan`` per lane,
        written back into the block's resident buffers.  Deliberately the
        expensive path — one driver launch per subscription — which is
        exactly what the delta refresh exists to avoid on the per-update
        hot path (the ``load-fan-bench`` ratio)."""
        from ..estimation.scenario import stress_fan

        done = 0
        for i in lanes:
            key = block.keys[i]
            if key is None:
                continue
            snap = self._snapshot_for(key)
            out = stress_fan(block.spec, snap.params, snap.beta, snap.P,
                             block.shocks, block.horizon, 0)
            codes = np.asarray(out["codes"])
            ok = int(np.bitwise_or.reduce(codes)) == tax.OK
            block.host = None
            block.dirty[i] = False
            block.pending[i] = None
            if ok:
                block.means = block.means.at[..., i].set(out["means"])
                block.covs = block.covs.at[..., i].set(out["covs"])
                block.codes = block.codes.at[:, i].set(out["codes"])
                block.refreshed = block.refreshed.at[i].set(True)
                block.good[i] = (snap.meta.version, self.clock())
                block.degraded[i] = False
            else:
                # poisoned recompute: keep the last fan, answer degraded
                block.codes = block.codes.at[:, i].set(out["codes"])
                block.degraded[i] = True
            done += 1
        self.counters.full_recomputes += done
        return done

    # ---- answers ----------------------------------------------------------

    def _materialize(self, block: _FanBlock) -> dict:
        """The block's host-side answer cache: ONE bulk device→host
        materialization per wave, built lazily at the first answer after the
        wave invalidated it (this is the response boundary — the routing
        functions above never transfer).  Every subscriber's answer then
        costs a NumPy slice, not a device dispatch."""
        if block.host is None:
            block.host = {
                "means": np.asarray(block.means),
                "covs": np.asarray(block.covs),
                "codes": np.asarray(block.codes),
                "refreshed": np.asarray(block.refreshed),
            }
        return block.host

    def fan(self, key) -> dict:
        """The subscription's current fan answer: per-shock predictive
        densities (``means`` (S, h, N), ``covs`` (S, h, N, N)), shock
        ``names``, per-shock taxonomy ``codes``, and the coherence stamps —
        ``version`` (the source snapshot the fan was computed from),
        ``computed_at``/``age_ms``, ``stale`` (past the ``YFM_FAN_STALE_MS``
        budget) and ``degraded`` (served from the last promoted fan: a
        dropped/failed refresh, a poisoned recompute, or a ``fan_stale``
        chaos hit).  This is the response boundary: the pending refresh
        attempt settles here against the materialized ``refreshed`` flags,
        and the whole block's buffers come host-side in ONE lazy bulk
        transfer per wave (:meth:`_materialize`, under the hub lock so it
        can never race a donating wave) — each answer is then a NumPy
        slice, not a device dispatch."""
        with self._lock:
            bkey = self._sub_block.get(key)
            if bkey is None:
                raise ServingError("streams",
                                   f"no subscription for {key!r}", key=key)
            block = self._blocks[bkey]
            slot = block.slot_of[key]
            host = self._materialize(block)
            if block.pending[slot] is not None:
                if bool(host["refreshed"][slot]):
                    block.good[slot] = block.pending[slot]
                    block.degraded[slot] = False
                else:
                    # the wave ran but the kernel kept the old fan
                    # (poisoned posterior) — degrade-from-last-fan
                    block.degraded[slot] = True
                block.pending[slot] = None
            degraded = block.degraded[slot] or block.dirty[slot]
            if chaos.should_inject("fan_stale"):
                degraded = True
            good = block.good[slot]
            version, computed_at = good if good is not None else (-1, None)
            age_ms = None if computed_at is None \
                else (self.clock() - computed_at) * 1e3
            stale = bool(self.stale_ms and age_ms is not None
                         and age_ms > self.stale_ms)
            out = {
                "key": key,
                "names": block.names,
                "means": host["means"][..., slot].copy(),
                "covs": host["covs"][..., slot].copy(),
                "codes": host["codes"][:, slot].copy(),
                "version": version,
                "computed_at": computed_at,
                "age_ms": age_ms,
                "stale": stale,
                "degraded": bool(degraded or stale),
            }
            self.counters.answers += 1
            if out["degraded"]:
                self.counters.degraded_answers += 1
            return out

    # ---- observability ----------------------------------------------------

    def health(self) -> dict:
        """The subscription-layer health report: outcome counters plus
        per-block occupancy — one report next to ``service.health()``."""
        with self._lock:
            blocks = [{
                "shocks": b.names,
                "horizon": b.horizon,
                "capacity": b.capacity,
                "subscribed": len(b.slot_of),
                "dirty": sum(1 for i in range(b.capacity)
                             if b.keys[i] is not None and b.dirty[i]),
            } for b in self._blocks.values()]
            return {"stale_ms": self.stale_ms,
                    "counters": self.counters.to_dict(),
                    "blocks": blocks}
