"""Per-shard journal of accepted online updates (docs/DESIGN.md §24).

The resident mesh (serving/store.py) is the fast copy of every live filter
state; a lost device shard — relay wedge, killed backend, a poisoned
whole-shard launch — takes every state on it down at once.  The recovery
contract is replay determinism: rebuild each key from its best surviving
host-side source (last-good bank, warm record, cold registry snapshot) and
re-drive the ACCEPTED updates it is missing through the exact same donated
``_jitted_shard_update`` program, so the post-replay resident state is
bit-identical to the never-lost run.  This module is the record of those
accepted updates:

- **Appends are free.**  Every update request already crosses the host
  O(batch) on its way in (the curve arrives as a host buffer), so journaling
  the accepted ones — ``(key, date, curve, post-update version)`` — adds one
  host copy per accept and zero device traffic.
- **Bounded ring per shard.**  Each shard keeps a ``deque(maxlen=capacity)``
  of records (``YFM_JOURNAL_CAP``, constructor wins over env).  Eviction is
  deliberate memory bounding: a replay suffix that has aged out of the ring
  is reported as a GAP — the key is stale-flagged, never silently replayed
  short.
- **Watermarks detect gaps.**  The journal keeps a per-key high-water
  version (scalar — survives ring eviction) and a per-shard append sequence.
  An append whose version is not exactly ``last + 1`` marks the key GAPPED
  (a dropped append — the ``journal_gap`` chaos seam simulates exactly
  this); so does a rebuild-time suffix whose versions are not contiguous
  from the source to the expected version.  A gapped key is *detected* as
  unreplayable, which is the whole safety story: degrade loudly instead of
  serving silently-wrong state.
- **Optional spill for cross-process recovery.**  ``spill()`` publishes the
  full journal state atomically (tmp + ``os.replace`` — the YFM005
  discipline) so a successor process can ``load()`` it and replay on top of
  the cold registry.

Threading: one lock guards all tables (append/watermark/suffix/spill); the
store appends from its response boundary while a health/ops thread may be
snapshotting — the lock keeps every reader consistent (graftlint YFM010
covers the class like the rest of the threaded host layer).
"""

from __future__ import annotations

import os
import pickle
import threading
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

Key = Tuple[str, int]


class JournalRecord(NamedTuple):
    """One accepted update as it crossed the host: everything the donated
    shard-update program needs to reproduce the accept bit-for-bit."""
    key: Key
    date: Optional[object]
    curve: np.ndarray          # (N,) float64 host copy of the observed yields
    version: int               # POST-update version (meta/resident agree)


def _env_capacity() -> int:
    """``YFM_JOURNAL_CAP`` (per-shard ring capacity in records; default
    1024 — at one accept per key per pump cycle that is many full rebuild
    windows of history for a 64-slot shard)."""
    raw = os.environ.get("YFM_JOURNAL_CAP", "")
    if not raw:
        return 1024
    cap = int(raw)
    if cap < 1:
        raise ValueError(f"YFM_JOURNAL_CAP must be >= 1, got {cap}")
    return cap


class UpdateJournal:
    """Bounded per-shard ring journal of accepted updates + gap detector."""

    def __init__(self, n_shards: int, capacity: Optional[int] = None):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.capacity = int(capacity) if capacity is not None \
            else _env_capacity()
        if self.capacity < 1:
            raise ValueError(f"journal capacity must be >= 1, "
                             f"got {self.capacity}")
        self.n_shards = int(n_shards)
        self._lock = threading.Lock()
        self._rings: List[deque] = [deque(maxlen=self.capacity)
                                    for _ in range(self.n_shards)]
        self._seq: List[int] = [0] * self.n_shards      # per-shard watermark
        self._last_ver: Dict[Key, int] = {}             # per-key watermark
        self._gapped: set = set()

    # ---- write side -------------------------------------------------------

    def note_base(self, key: Key, version: int) -> None:
        """Seed a key's version watermark at registration/refit time (no
        record — registration is not an update).  Without the base, a
        dropped FIRST append would leave the gap detector blind."""
        with self._lock:
            self._last_ver[key] = int(version)
            self._gapped.discard(key)

    def append(self, shard: int, key: Key, date, curve,
               version: int) -> None:
        """Journal one ACCEPTED update.  Detects a version jump against the
        key's watermark (a silently dropped earlier append — the
        ``journal_gap`` failure) and marks the key gapped; the append itself
        is still recorded so later contiguous suffixes stay usable after a
        re-base."""
        rec = JournalRecord(key, date,
                            np.asarray(curve, dtype=np.float64).copy(),
                            int(version))
        with self._lock:
            last = self._last_ver.get(key)
            if last is not None and rec.version != last + 1:
                self._gapped.add(key)
            self._last_ver[key] = rec.version
            self._rings[shard].append(rec)
            self._seq[shard] += 1

    def forget(self, key: Key) -> None:
        """Drop a key's watermark/gap state (eviction); its ring records
        become inert (a replay never consults a forgotten key)."""
        with self._lock:
            self._last_ver.pop(key, None)
            self._gapped.discard(key)

    # ---- read side --------------------------------------------------------

    def watermark(self, key: Key) -> Optional[int]:
        """The key's high-water journaled version (survives ring eviction);
        ``None`` for a key the journal has never seen."""
        with self._lock:
            return self._last_ver.get(key)

    def shard_seq(self, shard: int) -> int:
        """Total appends ever made to ``shard``'s ring (the per-shard
        watermark — monotonic, unaffected by ring eviction)."""
        with self._lock:
            return self._seq[shard]

    def is_gapped(self, key: Key) -> bool:
        with self._lock:
            return key in self._gapped

    def suffix(self, key: Key, after_version: int,
               upto_version: int) -> Tuple[List[JournalRecord], bool]:
        """The key's replay suffix: records with ``after_version < version
        <= upto_version`` in version order, plus an ``ok`` verdict.  ``ok``
        is False — a GAP — when the key was marked gapped by the append
        detector, when its watermark is behind ``upto_version`` (the
        dropped append was the last one), or when the ring has evicted part
        of the needed range; an empty needed range with an intact watermark
        is trivially ok.  A gapped suffix must NOT be replayed — the caller
        stale-flags the key instead."""
        with self._lock:
            if key in self._gapped:
                return [], False
            last = self._last_ver.get(key)
            if last is None or last < upto_version:
                return [], upto_version <= after_version
            need = {}
            for ring in self._rings:
                for rec in ring:
                    if rec.key == key and \
                            after_version < rec.version <= upto_version:
                        need[rec.version] = rec
            want = list(range(after_version + 1, upto_version + 1))
            if sorted(need) != want:
                return [], False        # ring evicted part of the suffix
            return [need[v] for v in want], True

    def snapshot(self) -> Dict[str, object]:
        """A consistent host copy of the whole journal state (records,
        watermarks, gap set) — what ``spill`` publishes and what the
        append-vs-snapshot hammer test races against."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "n_shards": self.n_shards,
                "rings": [list(ring) for ring in self._rings],
                "seq": list(self._seq),
                "last_ver": dict(self._last_ver),
                "gapped": set(self._gapped),
            }

    # ---- cross-process spill ---------------------------------------------

    def spill(self, path: str) -> None:
        """Publish the journal atomically for cross-process recovery: write
        a tmp sibling, then ``os.replace`` — a crashed spill leaves the
        previous file intact, never a torn one (YFM005)."""
        payload = self.snapshot()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            pickle.dump(payload, fh)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "UpdateJournal":
        """Rehydrate a spilled journal (the successor process's replay
        source on top of the cold registry)."""
        with open(path, "rb") as fh:
            payload = pickle.load(fh)
        j = cls(payload["n_shards"], capacity=payload["capacity"])
        with j._lock:
            for s, recs in enumerate(payload["rings"]):
                j._rings[s].extend(JournalRecord(*r) for r in recs)
            j._seq = list(payload["seq"])
            j._last_ver = dict(payload["last_ver"])
            j._gapped = set(payload["gapped"])
        return j
