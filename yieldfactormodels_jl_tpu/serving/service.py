"""`YieldCurveService` — the online serving driver.

Wraps one :class:`~.snapshot.ServingSnapshot` with the three serving verbs:

- ``update(date, yields)``   advance the filtered state by one curve (O(1),
  precompiled; partial curves OK — NaN entries are masked per element),
- ``forecast(h, quantiles)`` h-step predictive densities through the
  shape-bucketed micro-batcher (ops/forecast.py's density recursion),
- ``scenarios(n, h, seed)``  n sampled paths from the predictive
  distribution (models/simulate.py seeded at the filtered state),
- ``refilter(history)``      EXACT rebuild of the state from raw history via
  the O(log T) associative-scan filter (ops/assoc_scan; docs/DESIGN.md §13)
  — the freshness escape hatch after thousands of accumulated O(1) updates.

Driver-layer responsibilities (CLAUDE.md conventions): the jitted kernels
only emit sentinels (NaN state / −Inf ll) plus a taxonomy bitmask
(robustness/taxonomy.py); THIS layer decodes them into structured
:class:`~.snapshot.ServingError`s, keeps the last good snapshot on a failed
update (no silent NaN propagation into later requests), stamps versions, and
records per-stage latency through ``utils/profiling.StageTimer`` so p50/p99
land in the BENCH ledger (``latency_summary()`` → ``StageTimer.summary()``).

Self-healing (docs/DESIGN.md §11): every accepted update passes a host-side
health watch (finiteness + min-eigenvalue of the covariance,
robustness/health.py), and every ``YFM_SERVE_REFRESH`` updates the covariance
is scrubbed through a square-root refresh.  A state that fails the watch —
drift, a poisoned update, or a ``YFM_CHAOS`` ``nan_curve``/``nonpsd_cov``
numeric fault — is rebuilt from the last-good snapshot (falling back to the
boot snapshot / a :class:`~.snapshot.SnapshotRegistry` entry) and the service
keeps answering from that state with a ``stale`` flag; with
``self_heal=True`` a degraded update returns NaN instead of raising, and
``health()`` reports the whole story (status, cov condition,
updates-since-refresh, rebuild count, last decoded failure).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..orchestration import chaos
from ..robustness import health as rh
from ..robustness import taxonomy as tax
from ..utils.profiling import StageTimer
from .batcher import (BucketLattice, ForecastRequest, MicroBatcher,
                      ScenarioRequest)
from .online import (OnlineState, _check_engine, _jitted_refilter,
                     _jitted_update, factor_cov, update_k)
from .snapshot import ServingError, ServingSnapshot, SnapshotRegistry


@dataclasses.dataclass
class RequestCounters:
    """Request-path outcome counters (docs/DESIGN.md §12).  Maintained by the
    :class:`~.gateway.ServingGateway` in front of this service, reported here
    (``health()`` / ``latency_summary()``) so the load harness and operators
    read ONE report.  Invariant the reconciliation test pins
    (tests/test_gateway.py): every offered request lands in exactly one of
    ``shed`` (never admitted), ``completed`` (fresh answer), ``degraded``
    (stale/last-good answer — ``deadline`` counts the deadline-expired
    subset), or ``errors`` (structured per-request failure)."""

    admitted: int = 0
    completed: int = 0
    shed: int = 0
    deadline: int = 0
    degraded: int = 0
    errors: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class YieldCurveService:
    """One curve family, served online.

    ``engine`` picks the recursive-update kernel: ``"univariate"``
    (propagates P) or ``"sqrt"`` (propagates a square-root factor —
    f32-robust over long serving horizons).  Forecasts/scenarios always read
    the (β, P) moments, so the engine choice is invisible downstream.

    By default each service owns its batcher.  A shared
    :class:`MicroBatcher` (``batcher=``) lets requests micro-batch ACROSS
    services; ``forecast``/``scenarios`` here flush whatever is pending and
    collect their own ticket — other submitters' results stay banked on the
    batcher until they collect them (``MicroBatcher.result``).

    Robustness knobs: ``self_heal=True`` turns failed/poisoned updates into
    graceful degradation (state rebuilt, ``stale`` flag, NaN return) instead
    of a raised :class:`ServingError`; ``registry`` provides the rebuild
    source of last resort (the frozen snapshot this service booted from is
    always available); ``refresh_every`` overrides ``YFM_SERVE_REFRESH``
    (0 = no periodic refresh).
    """

    def __init__(self, snapshot: ServingSnapshot,
                 lattice: Optional[BucketLattice] = None,
                 engine: str = "univariate",
                 timer: Optional[StageTimer] = None,
                 batcher: Optional[MicroBatcher] = None,
                 registry: Optional[SnapshotRegistry] = None,
                 self_heal: bool = False,
                 refresh_every: Optional[int] = None,
                 donate: bool = True):
        _check_engine(engine)
        self.engine = engine
        self.timer = timer if timer is not None else StageTimer()
        # `is not None`, not `or`: an EMPTY shared batcher is falsy (__len__)
        self.batcher = batcher if batcher is not None else MicroBatcher(lattice)
        self.registry = registry
        self.self_heal = bool(self_heal)
        # donate=True (default) runs the O(1) update kernels with the state
        # buffers DONATED — alloc-free per update; all long-lived references
        # (snapshot, last-good) are kept as host copies so nothing else can
        # alias a consumed buffer (docs/DESIGN.md §14)
        self._donate = bool(donate)
        self.stale = False
        self.rebuilds = 0
        self.counters = RequestCounters()
        self._refresh_every = rh.serve_refresh_every(refresh_every)
        self._updates_since_refresh = 0
        self._last_code = 0
        self._boot_snapshot = snapshot
        self._set_snapshot(snapshot)
        self._bank_last_good()
        self.last_update = None  # date of the last accepted update
        # update-event listeners (serving/streams.py subscribes here): each
        # accepted update / rebuild / refit fires every registered callback
        self._listeners = []

    # ---- update-event listeners (docs/DESIGN.md §23) ----------------------

    def add_update_listener(self, fn) -> None:
        """Register ``fn(event: str)`` to fire after every state change:
        ``"update"`` (accepted online update — the delta-refresh trigger),
        ``"rebuild"`` (re-filter or §11 heal — the state moved without a
        parameter change) or ``"refit"`` (new parameters; standing consumers
        must recompute from scratch).  The scenario stream hub
        (:class:`~.streams.ScenarioStreamHub`) is the first consumer."""
        self._listeners.append(fn)

    def _notify(self, event: str) -> None:
        """Fire the registered listeners; a listener failure must NEVER
        break the update path (worker-isolation contract, DESIGN §12) — the
        exception is swallowed, the listener's own health machinery owns
        reporting it."""
        for fn in self._listeners:
            try:
                fn(event)
            except Exception:  # noqa: BLE001 — isolation: fail alone
                pass

    # ---- state plumbing ---------------------------------------------------

    def _set_snapshot(self, snapshot: ServingSnapshot) -> None:
        self.snapshot = snapshot
        dtype = snapshot.spec.dtype
        try:
            # factor once per (re)load (sqrt engine: afterwards the kernel
            # propagates the factor itself and P is re-formed only for the
            # snapshot record); either representation is a fresh buffer — the
            # LIVE state must never alias the snapshot record, because the
            # donated update kernels consume the state buffers and a shared
            # buffer would take the frozen snapshot down with them
            cov = factor_cov(snapshot.P, self.engine, dtype)
        except ValueError:
            raise ServingError("snapshot", "filtered covariance is not "
                               "PSD — cannot start the sqrt engine",
                               version=snapshot.meta.version)
        self._state = OnlineState(
            jnp.array(jnp.asarray(snapshot.beta, dtype=dtype), copy=True),
            cov)

    def _bank_last_good(self, beta=None, cov=None) -> None:
        """Freeze the current (snapshot, state) as the degrade/heal source —
        HOST copies, so no later donated launch can consume them.  Callers
        that already materialized the state host-side (the accept paths'
        snapshot bookkeeping) pass it in so each accepted update pays ONE
        device-to-host fetch, not two."""
        self._last_good = (self.snapshot, OnlineState(
            np.asarray(self._state.beta) if beta is None else beta,
            np.asarray(self._state.cov) if cov is None else cov))

    def _restore_last_good(self) -> None:
        """Put the last-good pair back as the live state (fresh device
        buffers from the banked host copies).  NOT a rebuild — the callers
        are the rejected-update paths, where 'keep the state' under donation
        means restoring what the launch consumed."""
        snap, st = self._last_good
        dtype = snap.spec.dtype
        self.snapshot = snap
        self._state = OnlineState(jnp.asarray(st.beta, dtype=dtype),
                                  jnp.asarray(st.cov, dtype=dtype))

    def _bank_alive(self) -> bool:
        """Whether the banked last-good state is readable.  ``_bank_last_good``
        always stores host copies, but operators/tests may plant device
        arrays there — which a donated launch can consume out from under the
        bank; a dead bank reads as poisoned (rebuild-from-source), never as
        a crash."""
        _, st = self._last_good
        return not any(getattr(a, "is_deleted", lambda: False)()
                       for a in (st.beta, st.cov))

    def _keep_state_on_reject(self, fallback_state: OnlineState) -> None:
        """A rejected update 'keeps the last good state'.  Under donation the
        launch consumed the pre-update buffers, so keeping means restoring
        the banked copies — or, when the bank itself is unreadable/poisoned,
        parking the launch's NaN-sentinel outputs so the health watch below
        drives the full §11 rebuild ladder."""
        if self._bank_alive():
            self._restore_last_good()
        else:
            self._state = fallback_state

    @property
    def version(self) -> int:
        return self.snapshot.meta.version

    @property
    def last_good_snapshot(self) -> ServingSnapshot:
        """The snapshot as of the last accepted-and-healthy update — the
        state every degraded answer is served from (docs/DESIGN.md §12)."""
        return self._last_good[0]

    # ---- self-healing machinery (docs/DESIGN.md §11) ----------------------

    def _rebuild_source(self) -> ServingSnapshot:
        """Last-resort rebuild snapshot: the registry's frozen entry for this
        model/task if one is registered, else the snapshot the service booted
        from."""
        if self.registry is not None:
            try:
                return self.registry.get(self._boot_snapshot.meta.model_string,
                                         self._boot_snapshot.meta.task_id)
            except ServingError:
                pass
        return self._boot_snapshot

    def _heal_state(self, force: bool = False) -> bool:
        """Ensure the in-memory state is healthy; returns True if it had to
        be rebuilt (last-good snapshot first, frozen rebuild source if even
        that is poisoned).  A healthy-LOOKING state is left untouched — a
        *rejected* update is not a rebuild — unless ``force``: a corruption
        the watch cannot see (e.g. a finite-but-wrong sqrt factor, whose
        S Sᵀ is PSD for ANY finite S) must still be restored when the caller
        KNOWS the state is bad (a fired chaos seam)."""
        h = rh.state_health(self._state.beta, self._state.cov, self.engine)
        if h["code"] == tax.OK and not force:
            return False
        _, st = self._last_good
        if self._bank_alive() and rh.state_health(
                st.beta, st.cov, self.engine)["code"] == tax.OK:
            self._restore_last_good()
        else:
            self._set_snapshot(self._rebuild_source())
            self._bank_last_good()
        self.rebuilds += 1
        return True

    def _degrade(self, stage: str, code: int, detail: str,
                 force_restore: bool = False, **context):
        """Common failure tail: heal the state, flag stale, then either
        return (self-heal mode) or raise the structured error."""
        with self.timer.stage("rebuild"):
            self._heal_state(force=force_restore)
        self.stale = True
        self._last_code = int(code)
        # the state may have been rebuilt under the heal — standing consumers
        # (stream hub fans) must not keep serving deltas off a moved base
        self._notify("rebuild")
        if self.self_heal:
            return
        raise ServingError(stage, detail, code=tax.describe(code), **context)

    def _maybe_refresh(self, n: int = 1) -> None:
        """Periodic square-root scrub of the covariance (YFM_SERVE_REFRESH);
        ``n`` = accepted updates to credit (k for a catch-up batch)."""
        self._updates_since_refresh += n
        if not self._refresh_every \
                or self._updates_since_refresh < self._refresh_every:
            return
        with self.timer.stage("refresh"):
            cov = rh.refresh_state(self._state.beta, self._state.cov,
                                   self.engine)
            cov = jnp.asarray(cov, dtype=self.snapshot.spec.dtype)
            self._state = OnlineState(self._state.beta, cov)
            # snapshot record = HOST copy (never aliases the live state —
            # the next donated update consumes the state buffers)
            c_h = np.asarray(cov)
            P = c_h @ c_h.T if self.engine == "sqrt" else c_h
            self.snapshot = dataclasses.replace(self.snapshot, P=P)
        self._updates_since_refresh = 0

    def health(self) -> dict:
        """The serving health report: ``status`` (``"ok"``/``"stale"``), the
        covariance watch numbers, refresh cadence position, rebuild count and
        the last decoded failure — everything an operator needs to decide
        between "leave it" and "re-freeze a snapshot"."""
        h = rh.state_health(self._state.beta, self._state.cov, self.engine)
        return {
            "status": "stale" if self.stale else "ok",
            "version": self.version,
            "engine": self.engine,
            "cov_min_eig": h["min_eig"],
            "cov_cond": h["cond"],
            "updates_since_refresh": self._updates_since_refresh,
            "refresh_every": self._refresh_every,
            "rebuilds": self.rebuilds,
            "last_code": self._last_code,
            "last_code_names": tax.decode(self._last_code),
            "requests": self.counters.to_dict(),
            # chaos observability: which armed seams fired ({} when
            # disarmed) — a chaos run's health report shows the faults it
            # actually injected, not just their consequences
            "chaos": chaos.observe(),
        }

    # ---- the serving verbs ------------------------------------------------

    def update(self, date, yields) -> float:
        """Advance the state with one observed curve (N,).  NaN entries are
        treated as unquoted maturities (masked per element; an all-NaN curve
        is a pure transition step).  Returns the update's loglik contribution.

        A failed innovation chain (or a state that fails the post-update
        health watch) keeps the last good snapshot: raises
        :class:`ServingError` by default, or — with ``self_heal=True`` —
        degrades (``stale`` flag, rebuild, NaN return) and recovers to
        ``ok`` on the next healthy update."""
        y = jnp.asarray(yields, dtype=self.snapshot.spec.dtype).reshape(-1)
        if y.shape[0] != self.snapshot.spec.N:
            raise ServingError("update", f"curve has {y.shape[0]} maturities, "
                               f"spec has {self.snapshot.spec.N}", date=date)
        with self.timer.stage("update"):
            runner = _jitted_update(self.snapshot.spec, self.engine,
                                    self._donate)
            b, c, ll, ok, code = runner(self.snapshot.params,
                                        self._state.beta, self._state.cov, y)
            ok = bool(ok)  # device sync: the driver decides, not the kernel
            code = int(code)
        if ok:
            # tentative accept; the health watch below owns the final word.
            # Snapshot bookkeeping holds HOST copies: the donated kernel owns
            # the device state buffers and will consume them next update.
            self._state = OnlineState(b, c)
            b_h, c_h = np.asarray(b), np.asarray(c)
            P = c_h @ c_h.T if self.engine == "sqrt" else c_h
            self.snapshot = self.snapshot.advanced(b_h, P)
        elif self._donate:
            # the launch consumed the pre-update state; "keep the last good
            # version" now means restoring the banked copies (not a rebuild)
            self._keep_state_on_reject(OnlineState(b, c))
        # numeric chaos seams (orchestration/chaos.py, docs/DESIGN.md §11):
        # simulate a poison that made it INTO the accepted state — the class
        # of fault the health watch + rebuild path exist for.  ``injected``
        # forces the restore: a corrupted sqrt FACTOR is invisible to the
        # min-eig watch (S Sᵀ is PSD for any finite S), but a fired seam
        # knows the state is bad.
        injected = False
        if chaos.should_inject("nan_curve"):
            nanst = jnp.full_like(self._state.beta, jnp.nan)
            self._state = OnlineState(nanst,
                                      jnp.full_like(self._state.cov, jnp.nan))
            ok, injected = False, True
            code |= tax.NAN_STATE
        if chaos.should_inject("nonpsd_cov"):
            eye = jnp.eye(self._state.cov.shape[0],
                          dtype=self._state.cov.dtype)
            self._state = OnlineState(self._state.beta,
                                      self._state.cov - 2.0 * eye)
            ok, injected = False, True
            code |= tax.NONPSD_COV
        h = rh.state_health(self._state.beta, self._state.cov, self.engine)
        code |= h["code"]
        if not ok or h["code"] != tax.OK:
            self._degrade(
                "update",
                code,
                f"update failed ({tax.describe(code)}) — state kept at the "
                f"last good version",
                force_restore=injected,
                date=date, version=self.version)
            return float("nan")
        self._bank_last_good(beta=b_h, cov=c_h)
        self.stale = False
        self._last_code = code
        self.last_update = date
        self._maybe_refresh()
        self._notify("update")
        return float(ll)

    def update_many(self, date, curves) -> np.ndarray:
        """k-step catch-up over the columns of ``curves`` (N, k) — one scan
        program.  All-or-nothing: a failed step anywhere rolls back (and
        degrades instead of raising under ``self_heal``)."""
        Y = jnp.asarray(curves, dtype=self.snapshot.spec.dtype)
        with self.timer.stage("update"):
            st, lls, oks, codes = update_k(self.snapshot.spec,
                                           self.snapshot.params,
                                           self._state, Y, engine=self.engine,
                                           with_code=True,
                                           donate=self._donate)
            oks = np.asarray(oks)
        if self._donate:
            # all-or-nothing semantics, donated flavor: the scan consumed the
            # pre-batch state either way; park the returned state (possibly
            # NaN) and let the failure paths below restore the banked copies
            self._state = st
        if not oks.all():
            j = int(np.argmin(oks))
            code = int(np.asarray(codes)[j])
            if self._donate:
                self._keep_state_on_reject(st)
            self._degrade(
                "update",
                code,
                f"step {j} of {Y.shape[1]} failed ({tax.describe(code)})",
                date=date, version=self.version)
            return np.full(int(Y.shape[1]), np.nan)
        h = rh.state_health(st.beta, st.cov, self.engine)
        if h["code"] != tax.OK:
            if self._donate:
                self._keep_state_on_reject(st)
            self._degrade("update", h["code"],
                          f"catch-up state failed the health watch "
                          f"({tax.describe(h['code'])})",
                          date=date, version=self.version)
            return np.full(int(Y.shape[1]), np.nan)
        self._state = st
        b_h, c_h = np.asarray(st.beta), np.asarray(st.cov)
        P = c_h @ c_h.T if self.engine == "sqrt" else c_h
        self.snapshot = self.snapshot.advanced(b_h, P, n=int(Y.shape[1]))
        self._bank_last_good(beta=b_h, cov=c_h)
        self.stale = False
        self.last_update = date
        self._maybe_refresh(int(Y.shape[1]))  # k accepted steps count too
        self._notify("update")
        return np.asarray(lls)

    def refilter(self, history, date=None) -> float:
        """Rebuild the serving state EXACTLY from raw history — the O(log T)
        associative-scan re-filter (docs/DESIGN.md §13; ops/assoc_scan).

        ``history`` is the full (N, T) conditioning panel: the columns the
        snapshot was frozen on followed by every curve fed through
        ``update``/``update_many`` since.  One parallel-in-time program
        replaces "trust k accumulated O(1) recursive updates" with the exact
        filtered posterior — the freshness escape hatch for long-lived
        services (drift from thousands of f32 rank-1 downdates) and the
        strongest form of the §11 self-healing ladder's rebuild.

        Semantics notes: whole columns with any NaN are treated as unobserved
        (pure prediction steps — the OFFLINE filter convention), unlike the
        per-element masking of the online ``update`` path; feed fully-quoted
        history for bit-tight agreement.  Kalman families with a
        parallel-in-time engine (``config.engines_for``): DNS/AFNS rebuild
        on the assoc tree, TVλ on the iterated-SLR engine (docs/DESIGN.md
        §19) — the SLR fixed point is the sequential EKF, so the rebuilt
        state agrees with the accumulated EKF recursion at engine
        tolerance.

        On success the rebuilt state becomes the new last-good snapshot
        (version bumped, refresh cadence reset — an exact rebuild is the
        strongest refresh) and the total history loglik is returned.  On a
        failed pass or a rebuilt state that fails the §11 health watch, the
        current state is KEPT and the standard degrade path runs (structured
        :class:`ServingError`, or stale-flag + NaN under ``self_heal``).
        """
        spec = self.snapshot.spec
        from .. import config as _config

        if _config.tree_engine_for(spec) is None:
            raise ServingError(
                "refilter", f"re-filter needs a Kalman family with a "
                f"parallel-in-time engine (config.engines_for"
                f"({spec.family!r}) = {_config.engines_for(spec)} has "
                f"neither 'assoc' nor 'slr')", model=spec.model_string)
        Y = jnp.asarray(history, dtype=spec.dtype)
        if Y.ndim != 2 or Y.shape[0] != spec.N:
            raise ServingError(
                "refilter", f"history has shape {tuple(Y.shape)}, expected "
                f"({spec.N}, T)", date=date)
        with self.timer.stage("refilter"):
            runner = _jitted_refilter(spec, int(Y.shape[1]))
            b, c, ll, ok, code = runner(self.snapshot.params, Y)
            ok = bool(ok)  # device sync: the driver decides, not the kernel
            code = int(code)
        if not ok:
            self._degrade(
                "refilter", code,
                f"re-filter pass failed ({tax.describe(code)}) — state kept "
                f"at the last good version",
                date=date, version=self.version)
            return float("nan")
        h = rh.state_health(b, c, "univariate")  # (β, P) moments form
        if h["code"] != tax.OK:
            self._degrade(
                "refilter", h["code"],
                f"rebuilt state failed the health watch "
                f"({tax.describe(h['code'])}) — state kept",
                date=date, version=self.version)
            return float("nan")
        snap = self.snapshot.advanced(b, c)
        prev = (self.snapshot, self._state)
        try:
            self._set_snapshot(snap)  # sqrt engine re-factors P here
        except ServingError:
            # _set_snapshot assigns self.snapshot before factoring — restore
            # the consistent (snapshot, state) pair before degrading
            self.snapshot, self._state = prev
            self._degrade("refilter", tax.NONPSD_COV,
                          "rebuilt covariance is not PSD under the serving "
                          "engine's factorization — state kept",
                          date=date, version=self.version)
            return float("nan")
        self._bank_last_good()
        self.stale = False
        self._last_code = code
        if date is not None:
            self.last_update = date
        self._updates_since_refresh = 0
        self._notify("rebuild")
        return float(ll)

    def refit(self, history, *, amortizer=None, polish_iters: int = 1,
              date=None) -> float:
        """Amortized re-ESTIMATION from raw history (docs/DESIGN.md §20):
        one surrogate forward pass proposes fresh model parameters, one
        trust-region Newton polish step (``ops/newton.py``) fine-tunes them,
        and the O(log T) re-filter rebuilds the serving state UNDER THE NEW
        PARAMETERS — "re-estimate this user's curve model" as a request-path
        operation instead of a batch job.

        ``history`` is the full (N, T) conditioning panel (the
        :meth:`refilter` contract: whole columns with any NaN are treated as
        unobserved).  ``amortizer`` defaults to the process-wide registry
        entry for this spec (``estimation.amortize.register_amortizer``);
        no registered surrogate is a structural error.  ``polish_iters=0``
        serves the raw surrogate point (the absolute-latency floor).

        On success the refit parameters AND the rebuilt state become the new
        snapshot (version bumped, refresh cadence reset); the total history
        loglik under the new parameters is returned.  A non-finite surrogate
        prediction, a failed re-filter pass, or a rebuilt state that fails
        the §11 health watch KEEPS the current parameters/state and runs the
        standard degrade path (structured :class:`ServingError`, or
        stale-flag + NaN under ``self_heal``)."""
        spec = self.snapshot.spec
        from .. import config as _config
        from ..estimation import amortize as _amortize
        from ..models.params import transform_params

        am = amortizer if amortizer is not None \
            else _amortize.get_amortizer(spec)
        if am is None:
            raise ServingError(
                "refit", f"no trained amortizer registered for "
                f"{spec.model_string!r} — train one "
                f"(estimation.amortize.train_amortizer) and "
                f"register_amortizer() it, or pass amortizer=",
                model=spec.model_string)
        if _config.tree_engine_for(spec) is None:
            raise ServingError(
                "refit", f"refit needs a Kalman family with a "
                f"parallel-in-time engine (config.engines_for"
                f"({spec.family!r}) = {_config.engines_for(spec)})",
                model=spec.model_string)
        Y = jnp.asarray(history, dtype=spec.dtype)
        if Y.ndim != 2 or Y.shape[0] != spec.N:
            raise ServingError(
                "refit", f"history has shape {tuple(Y.shape)}, expected "
                f"({spec.N}, T)", date=date)
        with self.timer.stage("refit"):
            raw, _ = _amortize.amortized_refit(spec, Y, amortizer=am,
                                               polish_iters=polish_iters)
            if raw is None:
                self._degrade(
                    "refit", tax.NAN_STATE,
                    "surrogate prediction is non-finite — parameters kept "
                    "at the last good version", date=date,
                    version=self.version)
                return float("nan")
            new_params = jnp.asarray(np.asarray(transform_params(
                spec, jnp.asarray(raw, dtype=spec.dtype))), dtype=spec.dtype)
            runner = _jitted_refilter(spec, int(Y.shape[1]))
            b, c, ll, ok, code = runner(new_params, Y)
            ok = bool(ok)  # device sync: the driver decides, not the kernel
            code = int(code)
        if not ok:
            self._degrade(
                "refit", code,
                f"re-filter under the refit parameters failed "
                f"({tax.describe(code)}) — parameters kept at the last good "
                f"version", date=date, version=self.version)
            return float("nan")
        h = rh.state_health(b, c, "univariate")  # (β, P) moments form
        if h["code"] != tax.OK:
            self._degrade(
                "refit", h["code"],
                f"refit state failed the health watch "
                f"({tax.describe(h['code'])}) — parameters kept",
                date=date, version=self.version)
            return float("nan")
        snap = dataclasses.replace(
            self.snapshot, params=np.asarray(new_params)).advanced(b, c)
        prev = (self.snapshot, self._state)
        try:
            self._set_snapshot(snap)  # sqrt engine re-factors P here
        except ServingError:
            self.snapshot, self._state = prev
            self._degrade("refit", tax.NONPSD_COV,
                          "refit covariance is not PSD under the serving "
                          "engine's factorization — parameters kept",
                          date=date, version=self.version)
            return float("nan")
        self._bank_last_good()
        self.stale = False
        self._last_code = code
        if date is not None:
            self.last_update = date
        self._updates_since_refresh = 0
        self._notify("refit")
        return float(ll)

    def forecast(self, h: int, quantiles: Optional[Tuple[float, ...]] = None
                 ) -> dict:
        """h-step predictive density from the current state: ``means``
        (h, N), ``covs`` (h, N, N), state paths, optional ``quantiles``
        {q: (h, N)}.  Runs through the micro-batcher, so it shares bucket
        programs with every other service on the same spec."""
        with self.timer.stage("forecast"):
            ticket = self.batcher.submit(
                self.snapshot, ForecastRequest(int(h), tuple(quantiles)
                                               if quantiles else None))
            self.batcher.flush()
            out = self.batcher.result(ticket)
        out = self._finite_or_heal(
            "forecast", out, "means",
            lambda: self._run_again(ForecastRequest(int(h), tuple(quantiles)
                                                    if quantiles else None)))
        return out

    def scenarios(self, n: Optional[int] = None, h: int = 12, seed: int = 0,
                  shocks=None) -> dict:
        """n sampled h-step yield paths: ``paths`` (N, h, n), draws on the
        trailing (lane) axis.  With ``shocks`` (a tuple of
        :class:`~..estimation.scenario.ShockSpec`, or ``"standard"`` for the
        canonical six-scenario fan) the request routes through the fused
        scenario lattice's fan program instead: the WHOLE stress fan —
        parallel shift, twist, vol regime, n draws each plus the per-shock
        predictive densities — is ONE device launch (docs/DESIGN.md §14),
        returned with a leading shock axis (``names`` (S,), ``paths``
        (S, N, h, n), ``means`` (S, h, N), ``covs`` (S, h, N, N)).  On the
        fan path ``n`` defaults to 0 (densities only, no sampled paths), so
        ``scenarios(shocks="standard")`` is a complete request; the plain
        path needs an explicit draw count."""
        if shocks is not None:
            return self.stress_fan(shocks, n=0 if n is None else n, h=h,
                                   seed=seed)
        if n is None:
            raise ServingError("scenarios", "n (the number of sampled "
                               "paths) is required without a shock fan",
                               version=self.version)
        with self.timer.stage("scenarios"):
            ticket = self.batcher.submit(
                self.snapshot, ScenarioRequest(int(n), int(h), int(seed)))
            self.batcher.flush()
            out = self.batcher.result(ticket)
        out = self._finite_or_heal(
            "scenarios", out, "paths",
            lambda: self._run_again(ScenarioRequest(int(n), int(h),
                                                    int(seed))))
        # cache-coherence metadata (DESIGN §23): which snapshot answered,
        # and when — the stream hub's staleness stamps build on these
        out["version"] = self.version
        out["computed_at"] = time.time()
        return out

    def stress_fan(self, shocks="standard", n: int = 0, h: int = 12,
                   seed: int = 0) -> dict:
        """One-launch stress fan from the current filtered state (the
        serving half of the fused scenario lattice).  The fan always carries
        the per-shock h-step predictive densities; ``n > 0`` adds sampled
        paths.  Answers come from the snapshot's (β, P) moments, so the
        engine choice stays invisible; a non-finite fan heals the state and
        retries once under ``self_heal`` (the ``_finite_or_heal``
        contract)."""
        from ..estimation.scenario import ShockSpec, standard_fan, stress_fan

        spec = self.snapshot.spec
        if isinstance(shocks, str):
            if shocks != "standard":
                raise ServingError("scenarios", f"unknown shock fan "
                                   f"{shocks!r} — pass 'standard' or a tuple "
                                   f"of ShockSpec", version=self.version)
            shocks = standard_fan(spec)
        shocks = tuple(shocks)
        if not all(isinstance(s, ShockSpec) for s in shocks):
            raise ServingError("scenarios", "shocks must be ShockSpec "
                               "instances", version=self.version)

        def run_fan():
            import jax as _jax

            out = stress_fan(spec, self.snapshot.params, self.snapshot.beta,
                             self.snapshot.P, shocks, int(h), int(n),
                             key=_jax.random.PRNGKey(int(seed)))
            res = {k: np.asarray(v) for k, v in out.items()}
            res["names"] = tuple(s.name for s in shocks)
            res["version"] = self.version
            res["computed_at"] = time.time()
            return res

        with self.timer.stage("scenarios"):
            out = run_fan()
        out = self._finite_or_heal("scenarios", out, "means", run_fan)
        return out

    def _run_again(self, request) -> dict:
        """Re-run one request from the (healed) current snapshot."""
        ticket = self.batcher.submit(self.snapshot, request)
        self.batcher.flush()
        return self.batcher.result(ticket)

    def _finite_or_heal(self, stage: str, out: dict, key: str, retry) -> dict:
        """The request-path guard, fixed to never leave a poisoned in-memory
        state behind: on a non-finite result the state is healed (rolled back
        to the last good snapshot / rebuilt) BEFORE the error surfaces; under
        ``self_heal`` a successful heal gets one retry from the restored
        state so the caller still receives a (stale) answer."""
        if np.all(np.isfinite(out[key])):
            return out
        healed = self._heal_state()
        self.stale = self.stale or healed
        self._last_code = tax.NAN_STATE
        if self.self_heal and healed:
            out = retry()
            if np.all(np.isfinite(out[key])):
                return out
        raise ServingError(stage, "non-finite output (NaN sentinel from "
                           "the kernels)"
                           + (", state rebuilt from the last good snapshot"
                              if healed else ""),
                           version=self.version,
                           code=tax.describe(tax.NAN_STATE))

    # ---- warmup / observability ------------------------------------------

    def warmup(self, horizons: Optional[Tuple[int, ...]] = None,
               batch_sizes: Tuple[int, ...] = (1,),
               scenario_counts: Tuple[int, ...] = ()) -> int:
        """Pre-trace the update kernel and the bucket-lattice programs so the
        first live request pays no compile.  Returns programs touched."""
        spec = self.snapshot.spec
        with self.timer.stage("warmup"):
            runner = _jitted_update(spec, self.engine, self._donate)
            nan_curve = jnp.full((spec.N,), jnp.nan, dtype=spec.dtype)
            # all-NaN warmup curve: a pure transition step, real params/state
            # — passed as COPIES: the donated program consumes its state args
            runner(self.snapshot.params,
                   jnp.array(self._state.beta, copy=True),
                   jnp.array(self._state.cov, copy=True), nan_curve)
            n = 1 + self.batcher.warmup(self.snapshot, horizons=horizons,
                                        batch_sizes=batch_sizes,
                                        scenario_counts=scenario_counts)
        return n

    def latency_summary(self) -> dict:
        """Per-stage latency percentiles (StageTimer.summary()) plus the
        request-path outcome counters — one report for the load harness and
        operators, not three (``"counters"`` rides beside the stage dicts)."""
        return {**self.timer.summary(), "counters": self.counters.to_dict()}
