"""`YieldCurveService` — the online serving driver.

Wraps one :class:`~.snapshot.ServingSnapshot` with the three serving verbs:

- ``update(date, yields)``   advance the filtered state by one curve (O(1),
  precompiled; partial curves OK — NaN entries are masked per element),
- ``forecast(h, quantiles)`` h-step predictive densities through the
  shape-bucketed micro-batcher (ops/forecast.py's density recursion),
- ``scenarios(n, h, seed)``  n sampled paths from the predictive
  distribution (models/simulate.py seeded at the filtered state).

Driver-layer responsibilities (CLAUDE.md conventions): the jitted kernels
only emit sentinels (NaN state / −Inf ll); THIS layer turns them into
structured :class:`~.snapshot.ServingError`s, keeps the last good snapshot on
a failed update (no silent NaN propagation into later requests), stamps
versions, and records per-stage latency through
``utils/profiling.StageTimer`` so p50/p99 land in the BENCH ledger
(``latency_summary()`` → ``StageTimer.summary()``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..utils.profiling import StageTimer
from .batcher import (BucketLattice, ForecastRequest, MicroBatcher,
                      ScenarioRequest)
from .online import OnlineState, _check_engine, _jitted_update, update_k
from .snapshot import ServingError, ServingSnapshot


class YieldCurveService:
    """One curve family, served online.

    ``engine`` picks the recursive-update kernel: ``"univariate"``
    (propagates P) or ``"sqrt"`` (propagates a square-root factor —
    f32-robust over long serving horizons).  Forecasts/scenarios always read
    the (β, P) moments, so the engine choice is invisible downstream.

    By default each service owns its batcher.  A shared
    :class:`MicroBatcher` (``batcher=``) lets requests micro-batch ACROSS
    services; ``forecast``/``scenarios`` here flush whatever is pending and
    collect their own ticket — other submitters' results stay banked on the
    batcher until they collect them (``MicroBatcher.result``).
    """

    def __init__(self, snapshot: ServingSnapshot,
                 lattice: Optional[BucketLattice] = None,
                 engine: str = "univariate",
                 timer: Optional[StageTimer] = None,
                 batcher: Optional[MicroBatcher] = None):
        _check_engine(engine)
        self.engine = engine
        self.timer = timer if timer is not None else StageTimer()
        # `is not None`, not `or`: an EMPTY shared batcher is falsy (__len__)
        self.batcher = batcher if batcher is not None else MicroBatcher(lattice)
        self._set_snapshot(snapshot)
        self.last_update = None  # date of the last accepted update

    # ---- state plumbing ---------------------------------------------------

    def _set_snapshot(self, snapshot: ServingSnapshot) -> None:
        self.snapshot = snapshot
        cov = snapshot.P
        if self.engine == "sqrt":
            # factor once per (re)load; afterwards the sqrt kernel propagates
            # the factor itself and P is re-formed only for the snapshot record
            Ms = cov.shape[0]
            sym = 0.5 * (cov + cov.T) + 1e-12 * jnp.eye(Ms, dtype=cov.dtype)
            cov = jnp.linalg.cholesky(sym)
            if not bool(jnp.all(jnp.isfinite(cov))):
                raise ServingError("snapshot", "filtered covariance is not "
                                   "PSD — cannot start the sqrt engine",
                                   version=snapshot.meta.version)
        self._state = OnlineState(snapshot.beta, cov)

    @property
    def version(self) -> int:
        return self.snapshot.meta.version

    # ---- the serving verbs ------------------------------------------------

    def update(self, date, yields) -> float:
        """Advance the state with one observed curve (N,).  NaN entries are
        treated as unquoted maturities (masked per element; an all-NaN curve
        is a pure transition step).  Returns the update's loglik contribution.

        Raises :class:`ServingError` on a failed innovation chain; the
        service keeps the last good snapshot (version unchanged)."""
        y = jnp.asarray(yields, dtype=self.snapshot.spec.dtype).reshape(-1)
        if y.shape[0] != self.snapshot.spec.N:
            raise ServingError("update", f"curve has {y.shape[0]} maturities, "
                               f"spec has {self.snapshot.spec.N}", date=date)
        with self.timer.stage("update"):
            runner = _jitted_update(self.snapshot.spec, self.engine)
            b, c, ll, ok = runner(self.snapshot.params, self._state.beta,
                                  self._state.cov, y)
            ok = bool(ok)  # device sync: the driver decides, not the kernel
        if not ok:
            raise ServingError(
                "update", "non-PD innovation variance — state poisoned to "
                "NaN by the kernel; snapshot left at the last good version",
                date=date, version=self.version)
        self._state = OnlineState(b, c)
        P = c @ c.T if self.engine == "sqrt" else c
        self.snapshot = self.snapshot.advanced(b, P)
        self.last_update = date
        return float(ll)

    def update_many(self, date, curves) -> np.ndarray:
        """k-step catch-up over the columns of ``curves`` (N, k) — one scan
        program.  All-or-nothing: a failed step anywhere rolls back."""
        Y = jnp.asarray(curves, dtype=self.snapshot.spec.dtype)
        with self.timer.stage("update"):
            st, lls, oks = update_k(self.snapshot.spec, self.snapshot.params,
                                    self._state, Y, engine=self.engine)
            oks = np.asarray(oks)
        if not oks.all():
            raise ServingError(
                "update", f"step {int(np.argmin(oks))} of {Y.shape[1]} failed "
                "(non-PD innovation variance)", date=date,
                version=self.version)
        self._state = st
        P = st.cov @ st.cov.T if self.engine == "sqrt" else st.cov
        self.snapshot = self.snapshot.advanced(st.beta, P, n=int(Y.shape[1]))
        self.last_update = date
        return np.asarray(lls)

    def forecast(self, h: int, quantiles: Optional[Tuple[float, ...]] = None
                 ) -> dict:
        """h-step predictive density from the current state: ``means``
        (h, N), ``covs`` (h, N, N), state paths, optional ``quantiles``
        {q: (h, N)}.  Runs through the micro-batcher, so it shares bucket
        programs with every other service on the same spec."""
        with self.timer.stage("forecast"):
            ticket = self.batcher.submit(
                self.snapshot, ForecastRequest(int(h), tuple(quantiles)
                                               if quantiles else None))
            self.batcher.flush()
            out = self.batcher.result(ticket)
        self._check_finite("forecast", out["means"])
        return out

    def scenarios(self, n: int, h: int, seed: int = 0) -> dict:
        """n sampled h-step yield paths: ``paths`` (N, h, n), draws on the
        trailing (lane) axis."""
        with self.timer.stage("scenarios"):
            ticket = self.batcher.submit(
                self.snapshot, ScenarioRequest(int(n), int(h), int(seed)))
            self.batcher.flush()
            out = self.batcher.result(ticket)
        self._check_finite("scenarios", out["paths"])
        return out

    def _check_finite(self, stage: str, arr) -> None:
        if not np.all(np.isfinite(arr)):
            raise ServingError(stage, "non-finite output (NaN sentinel from "
                               "the kernels)", version=self.version)

    # ---- warmup / observability ------------------------------------------

    def warmup(self, horizons: Optional[Tuple[int, ...]] = None,
               batch_sizes: Tuple[int, ...] = (1,),
               scenario_counts: Tuple[int, ...] = ()) -> int:
        """Pre-trace the update kernel and the bucket-lattice programs so the
        first live request pays no compile.  Returns programs touched."""
        spec = self.snapshot.spec
        with self.timer.stage("warmup"):
            runner = _jitted_update(spec, self.engine)
            nan_curve = jnp.full((spec.N,), jnp.nan, dtype=spec.dtype)
            # all-NaN warmup curve: a pure transition step, real params/state
            runner(self.snapshot.params, self._state.beta, self._state.cov,
                   nan_curve)
            n = 1 + self.batcher.warmup(self.snapshot, horizons=horizons,
                                        batch_sizes=batch_sizes,
                                        scenario_counts=scenario_counts)
        return n

    def latency_summary(self) -> dict:
        """Per-stage latency percentiles (StageTimer.summary())."""
        return self.timer.summary()
