"""`YieldCurveService` — the online serving driver.

Wraps one :class:`~.snapshot.ServingSnapshot` with the three serving verbs:

- ``update(date, yields)``   advance the filtered state by one curve (O(1),
  precompiled; partial curves OK — NaN entries are masked per element),
- ``forecast(h, quantiles)`` h-step predictive densities through the
  shape-bucketed micro-batcher (ops/forecast.py's density recursion),
- ``scenarios(n, h, seed)``  n sampled paths from the predictive
  distribution (models/simulate.py seeded at the filtered state),
- ``refilter(history)``      EXACT rebuild of the state from raw history via
  the O(log T) associative-scan filter (ops/assoc_scan; docs/DESIGN.md §13)
  — the freshness escape hatch after thousands of accumulated O(1) updates.

Driver-layer responsibilities (CLAUDE.md conventions): the jitted kernels
only emit sentinels (NaN state / −Inf ll) plus a taxonomy bitmask
(robustness/taxonomy.py); THIS layer decodes them into structured
:class:`~.snapshot.ServingError`s, keeps the last good snapshot on a failed
update (no silent NaN propagation into later requests), stamps versions, and
records per-stage latency through ``utils/profiling.StageTimer`` so p50/p99
land in the BENCH ledger (``latency_summary()`` → ``StageTimer.summary()``).

Self-healing (docs/DESIGN.md §11): every accepted update passes a host-side
health watch (finiteness + min-eigenvalue of the covariance,
robustness/health.py), and every ``YFM_SERVE_REFRESH`` updates the covariance
is scrubbed through a square-root refresh.  A state that fails the watch —
drift, a poisoned update, or a ``YFM_CHAOS`` ``nan_curve``/``nonpsd_cov``
numeric fault — is rebuilt from the last-good snapshot (falling back to the
boot snapshot / a :class:`~.snapshot.SnapshotRegistry` entry) and the service
keeps answering from that state with a ``stale`` flag; with
``self_heal=True`` a degraded update returns NaN instead of raising, and
``health()`` reports the whole story (status, cov condition,
updates-since-refresh, rebuild count, last decoded failure).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..orchestration import chaos
from ..robustness import health as rh
from ..robustness import taxonomy as tax
from ..utils.profiling import StageTimer
from .batcher import (BucketLattice, ForecastRequest, MicroBatcher,
                      ScenarioRequest)
from .online import (OnlineState, _check_engine, _jitted_refilter,
                     _jitted_update, update_k)
from .snapshot import ServingError, ServingSnapshot, SnapshotRegistry


@dataclasses.dataclass
class RequestCounters:
    """Request-path outcome counters (docs/DESIGN.md §12).  Maintained by the
    :class:`~.gateway.ServingGateway` in front of this service, reported here
    (``health()`` / ``latency_summary()``) so the load harness and operators
    read ONE report.  Invariant the reconciliation test pins
    (tests/test_gateway.py): every offered request lands in exactly one of
    ``shed`` (never admitted), ``completed`` (fresh answer), ``degraded``
    (stale/last-good answer — ``deadline`` counts the deadline-expired
    subset), or ``errors`` (structured per-request failure)."""

    admitted: int = 0
    completed: int = 0
    shed: int = 0
    deadline: int = 0
    degraded: int = 0
    errors: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class YieldCurveService:
    """One curve family, served online.

    ``engine`` picks the recursive-update kernel: ``"univariate"``
    (propagates P) or ``"sqrt"`` (propagates a square-root factor —
    f32-robust over long serving horizons).  Forecasts/scenarios always read
    the (β, P) moments, so the engine choice is invisible downstream.

    By default each service owns its batcher.  A shared
    :class:`MicroBatcher` (``batcher=``) lets requests micro-batch ACROSS
    services; ``forecast``/``scenarios`` here flush whatever is pending and
    collect their own ticket — other submitters' results stay banked on the
    batcher until they collect them (``MicroBatcher.result``).

    Robustness knobs: ``self_heal=True`` turns failed/poisoned updates into
    graceful degradation (state rebuilt, ``stale`` flag, NaN return) instead
    of a raised :class:`ServingError`; ``registry`` provides the rebuild
    source of last resort (the frozen snapshot this service booted from is
    always available); ``refresh_every`` overrides ``YFM_SERVE_REFRESH``
    (0 = no periodic refresh).
    """

    def __init__(self, snapshot: ServingSnapshot,
                 lattice: Optional[BucketLattice] = None,
                 engine: str = "univariate",
                 timer: Optional[StageTimer] = None,
                 batcher: Optional[MicroBatcher] = None,
                 registry: Optional[SnapshotRegistry] = None,
                 self_heal: bool = False,
                 refresh_every: Optional[int] = None):
        _check_engine(engine)
        self.engine = engine
        self.timer = timer if timer is not None else StageTimer()
        # `is not None`, not `or`: an EMPTY shared batcher is falsy (__len__)
        self.batcher = batcher if batcher is not None else MicroBatcher(lattice)
        self.registry = registry
        self.self_heal = bool(self_heal)
        self.stale = False
        self.rebuilds = 0
        self.counters = RequestCounters()
        self._refresh_every = rh.serve_refresh_every(refresh_every)
        self._updates_since_refresh = 0
        self._last_code = 0
        self._boot_snapshot = snapshot
        self._set_snapshot(snapshot)
        self._last_good = (self.snapshot, self._state)
        self.last_update = None  # date of the last accepted update

    # ---- state plumbing ---------------------------------------------------

    def _set_snapshot(self, snapshot: ServingSnapshot) -> None:
        self.snapshot = snapshot
        cov = snapshot.P
        if self.engine == "sqrt":
            # factor once per (re)load; afterwards the sqrt kernel propagates
            # the factor itself and P is re-formed only for the snapshot record
            Ms = cov.shape[0]
            sym = 0.5 * (cov + cov.T) + 1e-12 * jnp.eye(Ms, dtype=cov.dtype)
            cov = jnp.linalg.cholesky(sym)
            if not bool(jnp.all(jnp.isfinite(cov))):
                raise ServingError("snapshot", "filtered covariance is not "
                                   "PSD — cannot start the sqrt engine",
                                   version=snapshot.meta.version)
        self._state = OnlineState(snapshot.beta, cov)

    @property
    def version(self) -> int:
        return self.snapshot.meta.version

    @property
    def last_good_snapshot(self) -> ServingSnapshot:
        """The snapshot as of the last accepted-and-healthy update — the
        state every degraded answer is served from (docs/DESIGN.md §12)."""
        return self._last_good[0]

    # ---- self-healing machinery (docs/DESIGN.md §11) ----------------------

    def _rebuild_source(self) -> ServingSnapshot:
        """Last-resort rebuild snapshot: the registry's frozen entry for this
        model/task if one is registered, else the snapshot the service booted
        from."""
        if self.registry is not None:
            try:
                return self.registry.get(self._boot_snapshot.meta.model_string,
                                         self._boot_snapshot.meta.task_id)
            except ServingError:
                pass
        return self._boot_snapshot

    def _heal_state(self, force: bool = False) -> bool:
        """Ensure the in-memory state is healthy; returns True if it had to
        be rebuilt (last-good snapshot first, frozen rebuild source if even
        that is poisoned).  A healthy-LOOKING state is left untouched — a
        *rejected* update is not a rebuild — unless ``force``: a corruption
        the watch cannot see (e.g. a finite-but-wrong sqrt factor, whose
        S Sᵀ is PSD for ANY finite S) must still be restored when the caller
        KNOWS the state is bad (a fired chaos seam)."""
        h = rh.state_health(self._state.beta, self._state.cov, self.engine)
        if h["code"] == tax.OK and not force:
            return False
        snap, st = self._last_good
        if rh.state_health(st.beta, st.cov, self.engine)["code"] == tax.OK:
            self.snapshot, self._state = snap, st
        else:
            self._set_snapshot(self._rebuild_source())
            self._last_good = (self.snapshot, self._state)
        self.rebuilds += 1
        return True

    def _degrade(self, stage: str, code: int, detail: str,
                 force_restore: bool = False, **context):
        """Common failure tail: heal the state, flag stale, then either
        return (self-heal mode) or raise the structured error."""
        with self.timer.stage("rebuild"):
            self._heal_state(force=force_restore)
        self.stale = True
        self._last_code = int(code)
        if self.self_heal:
            return
        raise ServingError(stage, detail, code=tax.describe(code), **context)

    def _maybe_refresh(self, n: int = 1) -> None:
        """Periodic square-root scrub of the covariance (YFM_SERVE_REFRESH);
        ``n`` = accepted updates to credit (k for a catch-up batch)."""
        self._updates_since_refresh += n
        if not self._refresh_every \
                or self._updates_since_refresh < self._refresh_every:
            return
        with self.timer.stage("refresh"):
            cov = rh.refresh_state(self._state.beta, self._state.cov,
                                   self.engine)
            cov = jnp.asarray(cov, dtype=self.snapshot.spec.dtype)
            self._state = OnlineState(self._state.beta, cov)
            P = cov @ cov.T if self.engine == "sqrt" else cov
            self.snapshot = dataclasses.replace(self.snapshot, P=P)
        self._updates_since_refresh = 0

    def health(self) -> dict:
        """The serving health report: ``status`` (``"ok"``/``"stale"``), the
        covariance watch numbers, refresh cadence position, rebuild count and
        the last decoded failure — everything an operator needs to decide
        between "leave it" and "re-freeze a snapshot"."""
        h = rh.state_health(self._state.beta, self._state.cov, self.engine)
        return {
            "status": "stale" if self.stale else "ok",
            "version": self.version,
            "engine": self.engine,
            "cov_min_eig": h["min_eig"],
            "cov_cond": h["cond"],
            "updates_since_refresh": self._updates_since_refresh,
            "refresh_every": self._refresh_every,
            "rebuilds": self.rebuilds,
            "last_code": self._last_code,
            "last_code_names": tax.decode(self._last_code),
            "requests": self.counters.to_dict(),
        }

    # ---- the serving verbs ------------------------------------------------

    def update(self, date, yields) -> float:
        """Advance the state with one observed curve (N,).  NaN entries are
        treated as unquoted maturities (masked per element; an all-NaN curve
        is a pure transition step).  Returns the update's loglik contribution.

        A failed innovation chain (or a state that fails the post-update
        health watch) keeps the last good snapshot: raises
        :class:`ServingError` by default, or — with ``self_heal=True`` —
        degrades (``stale`` flag, rebuild, NaN return) and recovers to
        ``ok`` on the next healthy update."""
        y = jnp.asarray(yields, dtype=self.snapshot.spec.dtype).reshape(-1)
        if y.shape[0] != self.snapshot.spec.N:
            raise ServingError("update", f"curve has {y.shape[0]} maturities, "
                               f"spec has {self.snapshot.spec.N}", date=date)
        with self.timer.stage("update"):
            runner = _jitted_update(self.snapshot.spec, self.engine)
            b, c, ll, ok, code = runner(self.snapshot.params,
                                        self._state.beta, self._state.cov, y)
            ok = bool(ok)  # device sync: the driver decides, not the kernel
            code = int(code)
        if ok:
            # tentative accept; the health watch below owns the final word
            self._state = OnlineState(b, c)
            P = c @ c.T if self.engine == "sqrt" else c
            self.snapshot = self.snapshot.advanced(b, P)
        # numeric chaos seams (orchestration/chaos.py, docs/DESIGN.md §11):
        # simulate a poison that made it INTO the accepted state — the class
        # of fault the health watch + rebuild path exist for.  ``injected``
        # forces the restore: a corrupted sqrt FACTOR is invisible to the
        # min-eig watch (S Sᵀ is PSD for any finite S), but a fired seam
        # knows the state is bad.
        injected = False
        if chaos.should_inject("nan_curve"):
            nanst = jnp.full_like(self._state.beta, jnp.nan)
            self._state = OnlineState(nanst,
                                      jnp.full_like(self._state.cov, jnp.nan))
            ok, injected = False, True
            code |= tax.NAN_STATE
        if chaos.should_inject("nonpsd_cov"):
            eye = jnp.eye(self._state.cov.shape[0],
                          dtype=self._state.cov.dtype)
            self._state = OnlineState(self._state.beta,
                                      self._state.cov - 2.0 * eye)
            ok, injected = False, True
            code |= tax.NONPSD_COV
        h = rh.state_health(self._state.beta, self._state.cov, self.engine)
        code |= h["code"]
        if not ok or h["code"] != tax.OK:
            self._degrade(
                "update",
                code,
                f"update failed ({tax.describe(code)}) — state kept at the "
                f"last good version",
                force_restore=injected,
                date=date, version=self.version)
            return float("nan")
        self._last_good = (self.snapshot, self._state)
        self.stale = False
        self._last_code = code
        self.last_update = date
        self._maybe_refresh()
        return float(ll)

    def update_many(self, date, curves) -> np.ndarray:
        """k-step catch-up over the columns of ``curves`` (N, k) — one scan
        program.  All-or-nothing: a failed step anywhere rolls back (and
        degrades instead of raising under ``self_heal``)."""
        Y = jnp.asarray(curves, dtype=self.snapshot.spec.dtype)
        with self.timer.stage("update"):
            st, lls, oks, codes = update_k(self.snapshot.spec,
                                           self.snapshot.params,
                                           self._state, Y, engine=self.engine,
                                           with_code=True)
            oks = np.asarray(oks)
        if not oks.all():
            j = int(np.argmin(oks))
            code = int(np.asarray(codes)[j])
            self._degrade(
                "update",
                code,
                f"step {j} of {Y.shape[1]} failed ({tax.describe(code)})",
                date=date, version=self.version)
            return np.full(int(Y.shape[1]), np.nan)
        h = rh.state_health(st.beta, st.cov, self.engine)
        if h["code"] != tax.OK:
            self._degrade("update", h["code"],
                          f"catch-up state failed the health watch "
                          f"({tax.describe(h['code'])})",
                          date=date, version=self.version)
            return np.full(int(Y.shape[1]), np.nan)
        self._state = st
        P = st.cov @ st.cov.T if self.engine == "sqrt" else st.cov
        self.snapshot = self.snapshot.advanced(st.beta, P, n=int(Y.shape[1]))
        self._last_good = (self.snapshot, self._state)
        self.stale = False
        self.last_update = date
        self._maybe_refresh(int(Y.shape[1]))  # k accepted steps count too
        return np.asarray(lls)

    def refilter(self, history, date=None) -> float:
        """Rebuild the serving state EXACTLY from raw history — the O(log T)
        associative-scan re-filter (docs/DESIGN.md §13; ops/assoc_scan).

        ``history`` is the full (N, T) conditioning panel: the columns the
        snapshot was frozen on followed by every curve fed through
        ``update``/``update_many`` since.  One parallel-in-time program
        replaces "trust k accumulated O(1) recursive updates" with the exact
        filtered posterior — the freshness escape hatch for long-lived
        services (drift from thousands of f32 rank-1 downdates) and the
        strongest form of the §11 self-healing ladder's rebuild.

        Semantics notes: whole columns with any NaN are treated as unobserved
        (pure prediction steps — the OFFLINE filter convention), unlike the
        per-element masking of the online ``update`` path; feed fully-quoted
        history for bit-tight agreement.  Constant-measurement Kalman
        families only (DNS/AFNS — the associative form needs a constant Z).

        On success the rebuilt state becomes the new last-good snapshot
        (version bumped, refresh cadence reset — an exact rebuild is the
        strongest refresh) and the total history loglik is returned.  On a
        failed pass or a rebuilt state that fails the §11 health watch, the
        current state is KEPT and the standard degrade path runs (structured
        :class:`ServingError`, or stale-flag + NaN under ``self_heal``).
        """
        spec = self.snapshot.spec
        if not spec.has_constant_measurement:
            raise ServingError(
                "refilter", f"re-filter needs a constant-measurement Kalman "
                f"family (the associative-scan engine); "
                f"{spec.family!r} is not one", model=spec.model_string)
        Y = jnp.asarray(history, dtype=spec.dtype)
        if Y.ndim != 2 or Y.shape[0] != spec.N:
            raise ServingError(
                "refilter", f"history has shape {tuple(Y.shape)}, expected "
                f"({spec.N}, T)", date=date)
        with self.timer.stage("refilter"):
            runner = _jitted_refilter(spec, int(Y.shape[1]))
            b, c, ll, ok, code = runner(self.snapshot.params, Y)
            ok = bool(ok)  # device sync: the driver decides, not the kernel
            code = int(code)
        if not ok:
            self._degrade(
                "refilter", code,
                f"re-filter pass failed ({tax.describe(code)}) — state kept "
                f"at the last good version",
                date=date, version=self.version)
            return float("nan")
        h = rh.state_health(b, c, "univariate")  # (β, P) moments form
        if h["code"] != tax.OK:
            self._degrade(
                "refilter", h["code"],
                f"rebuilt state failed the health watch "
                f"({tax.describe(h['code'])}) — state kept",
                date=date, version=self.version)
            return float("nan")
        snap = self.snapshot.advanced(b, c)
        prev = (self.snapshot, self._state)
        try:
            self._set_snapshot(snap)  # sqrt engine re-factors P here
        except ServingError:
            # _set_snapshot assigns self.snapshot before factoring — restore
            # the consistent (snapshot, state) pair before degrading
            self.snapshot, self._state = prev
            self._degrade("refilter", tax.NONPSD_COV,
                          "rebuilt covariance is not PSD under the serving "
                          "engine's factorization — state kept",
                          date=date, version=self.version)
            return float("nan")
        self._last_good = (self.snapshot, self._state)
        self.stale = False
        self._last_code = code
        if date is not None:
            self.last_update = date
        self._updates_since_refresh = 0
        return float(ll)

    def forecast(self, h: int, quantiles: Optional[Tuple[float, ...]] = None
                 ) -> dict:
        """h-step predictive density from the current state: ``means``
        (h, N), ``covs`` (h, N, N), state paths, optional ``quantiles``
        {q: (h, N)}.  Runs through the micro-batcher, so it shares bucket
        programs with every other service on the same spec."""
        with self.timer.stage("forecast"):
            ticket = self.batcher.submit(
                self.snapshot, ForecastRequest(int(h), tuple(quantiles)
                                               if quantiles else None))
            self.batcher.flush()
            out = self.batcher.result(ticket)
        out = self._finite_or_heal(
            "forecast", out, "means",
            lambda: self._run_again(ForecastRequest(int(h), tuple(quantiles)
                                                    if quantiles else None)))
        return out

    def scenarios(self, n: int, h: int, seed: int = 0) -> dict:
        """n sampled h-step yield paths: ``paths`` (N, h, n), draws on the
        trailing (lane) axis."""
        with self.timer.stage("scenarios"):
            ticket = self.batcher.submit(
                self.snapshot, ScenarioRequest(int(n), int(h), int(seed)))
            self.batcher.flush()
            out = self.batcher.result(ticket)
        out = self._finite_or_heal(
            "scenarios", out, "paths",
            lambda: self._run_again(ScenarioRequest(int(n), int(h),
                                                    int(seed))))
        return out

    def _run_again(self, request) -> dict:
        """Re-run one request from the (healed) current snapshot."""
        ticket = self.batcher.submit(self.snapshot, request)
        self.batcher.flush()
        return self.batcher.result(ticket)

    def _finite_or_heal(self, stage: str, out: dict, key: str, retry) -> dict:
        """The request-path guard, fixed to never leave a poisoned in-memory
        state behind: on a non-finite result the state is healed (rolled back
        to the last good snapshot / rebuilt) BEFORE the error surfaces; under
        ``self_heal`` a successful heal gets one retry from the restored
        state so the caller still receives a (stale) answer."""
        if np.all(np.isfinite(out[key])):
            return out
        healed = self._heal_state()
        self.stale = self.stale or healed
        self._last_code = tax.NAN_STATE
        if self.self_heal and healed:
            out = retry()
            if np.all(np.isfinite(out[key])):
                return out
        raise ServingError(stage, "non-finite output (NaN sentinel from "
                           "the kernels)"
                           + (", state rebuilt from the last good snapshot"
                              if healed else ""),
                           version=self.version,
                           code=tax.describe(tax.NAN_STATE))

    # ---- warmup / observability ------------------------------------------

    def warmup(self, horizons: Optional[Tuple[int, ...]] = None,
               batch_sizes: Tuple[int, ...] = (1,),
               scenario_counts: Tuple[int, ...] = ()) -> int:
        """Pre-trace the update kernel and the bucket-lattice programs so the
        first live request pays no compile.  Returns programs touched."""
        spec = self.snapshot.spec
        with self.timer.stage("warmup"):
            runner = _jitted_update(spec, self.engine)
            nan_curve = jnp.full((spec.N,), jnp.nan, dtype=spec.dtype)
            # all-NaN warmup curve: a pure transition step, real params/state
            runner(self.snapshot.params, self._state.beta, self._state.cov,
                   nan_curve)
            n = 1 + self.batcher.warmup(self.snapshot, horizons=horizons,
                                        batch_sizes=batch_sizes,
                                        scenario_counts=scenario_counts)
        return n

    def latency_summary(self) -> dict:
        """Per-stage latency percentiles (StageTimer.summary()) plus the
        request-path outcome counters — one report for the load harness and
        operators, not three (``"counters"`` rides beside the stage dicts)."""
        return {**self.timer.summary(), "counters": self.counters.to_dict()}
