"""Shape-bucketed micro-batching: arbitrary request mixes, fixed programs.

A serving mix is heterogeneous — forecast horizons, scenario counts, and the
number of curves asking at once all vary per request — but XLA programs are
shape-monomorphic: every new shape is a retrace + recompile on the hot path.
The front end here rounds every request onto a small LATTICE of padded fixed
shapes (docs/DESIGN.md §9):

- forecast requests bucket on (horizon, batch): requests against snapshots of
  the same spec stack into one padded batch with the BATCH AXIS LAST —
  params (n_params, B), β (Ms, B), P (Ms, Ms, B) — per the lane-dim rule
  (docs/DESIGN.md §2): B rides the 128-wide lane axis, the small state dims
  sit on sublanes.
- scenario requests bucket on (horizon, n_draws): the draws axis IS the
  batch and already rides last ((N, h, n) outputs).

Every trace-time builder is ``@register_engine_cache`` + ``@lru_cache`` (in
that order — config.py), so an engine switch invalidates serving programs
exactly like the estimation caches, and the total number of distinct
compilations is bounded by ``BucketLattice.n_programs`` regardless of the
request mix (pinned in tests/test_serving.py via the trace counters in
serving/online.py).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import register_engine_cache
from ..models.params import unpack_kalman
from ..models.specs import ModelSpec
from ..orchestration import chaos
from .online import note_trace, scenario_paths
from .snapshot import ServingError, ServingSnapshot


@dataclasses.dataclass(frozen=True)
class BucketLattice:
    """The padded-shape lattice.  Small on purpose: its product bounds the
    number of live compiled programs (and the warmup cost)."""

    horizons: Tuple[int, ...] = (4, 8, 16, 32, 64)
    batch_sizes: Tuple[int, ...] = (1, 4, 16)
    scenario_counts: Tuple[int, ...] = (8, 32, 128)
    #: padded per-shard update-batch shapes for the sharded state store
    #: (serving/store.py): a shard's micro-batch of online updates rounds up
    #: onto these, so arbitrary request mixes share ``len(update_batch_sizes)``
    #: compiled shard-update programs per (engine, capacity)
    update_batch_sizes: Tuple[int, ...] = (1, 4, 16)

    def __post_init__(self):
        for name in ("horizons", "batch_sizes", "scenario_counts",
                     "update_batch_sizes"):
            vals = getattr(self, name)
            if not vals or list(vals) != sorted(set(vals)) or min(vals) < 1:
                raise ValueError(f"{name} must be strictly increasing ≥ 1, "
                                 f"got {vals}")

    @property
    def n_programs(self) -> int:
        """Upper bound on distinct compiled read-path (forecast/scenario)
        serving programs."""
        return (len(self.horizons) * len(self.batch_sizes)
                + len(self.horizons) * len(self.scenario_counts))

    @property
    def n_update_programs(self) -> int:
        """Upper bound on distinct compiled shard-update programs per
        (engine, shard capacity) — the store-side twin of ``n_programs``."""
        return len(self.update_batch_sizes)

    @staticmethod
    def _round_up(value: int, axis: Tuple[int, ...], name: str) -> int:
        stage = {"horizons": "forecast",
                 "update_batch_sizes": "update"}.get(name, "scenarios")
        if value < 1:
            # a non-positive size would otherwise round UP to the first
            # bucket and come back silently truncated to an empty/short array
            raise ServingError(stage, f"request {name[:-1]}={value} must be "
                               "≥ 1", lattice=axis)
        for v in axis:
            if value <= v:
                return v
        raise ServingError(stage,
                           f"request {name[:-1]}={value} exceeds the lattice "
                           f"maximum {axis[-1]} — widen the BucketLattice",
                           lattice=axis)

    def horizon_bucket(self, h: int) -> int:
        return self._round_up(int(h), self.horizons, "horizons")

    def batch_bucket(self, b: int) -> int:
        return self._round_up(int(b), self.batch_sizes, "batch_sizes")

    def scenario_bucket(self, n: int) -> int:
        return self._round_up(int(n), self.scenario_counts, "scenario_counts")

    def update_bucket(self, b: int) -> int:
        return self._round_up(int(b), self.update_batch_sizes,
                              "update_batch_sizes")


DEFAULT_LATTICE = BucketLattice()


@dataclasses.dataclass(frozen=True)
class ForecastRequest:
    """h-step predictive density; optional per-maturity Gaussian quantiles."""

    horizon: int
    quantiles: Optional[Tuple[float, ...]] = None


@dataclasses.dataclass(frozen=True)
class ScenarioRequest:
    """n sampled h-step paths from the current predictive distribution."""

    n: int
    horizon: int
    seed: int = 0


# ---------------------------------------------------------------------------
# jitted bucket programs
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=128)
def _jitted_forecast_bucket(spec: ModelSpec, horizon: int, batch: int):
    """One padded forecast program: (params (P, B), β (Ms, B), P (Ms, Ms, B))
    → density dict with the batch on the trailing axis of every output."""
    from ..ops.forecast import density_from_state

    def one(params, beta, P):
        note_trace("forecast")
        kp = unpack_kalman(spec, params)
        return density_from_state(spec, kp, beta, P, horizon)

    del batch  # shape is carried by the (padded) arguments; key keeps programs apart
    return jax.jit(jax.vmap(one, in_axes=-1, out_axes=-1))


def _stack_last(arrs) -> jnp.ndarray:
    """Stack equal-shape arrays on a NEW TRAILING axis (the lane-dim rule)."""
    return jnp.stack([jnp.asarray(a) for a in arrs], axis=-1)


def _normal_quantiles(means: np.ndarray, covs: np.ndarray,
                      qs: Tuple[float, ...]) -> Dict[float, np.ndarray]:
    """Per-maturity Gaussian quantile curves from an (h, N) mean path and
    (h, N, N) covariance path (driver-side NumPy; tiny)."""
    from scipy.special import ndtri

    sd = np.sqrt(np.maximum(np.diagonal(covs, axis1=-2, axis2=-1), 0.0))
    return {float(q): means + ndtri(q) * sd for q in qs}


# ---------------------------------------------------------------------------
# the micro-batcher
# ---------------------------------------------------------------------------

class MicroBatcher:
    """Collects (snapshot, request) pairs, groups them onto the lattice, runs
    one padded program per occupied bucket, and hands results back in
    submission order.

    Usage::

        t0 = batcher.submit(snap_a, ForecastRequest(12))
        t1 = batcher.submit(snap_b, ForecastRequest(9, quantiles=(0.05, 0.95)))
        results = batcher.flush()          # {t0: {...}, t1: {...}}

    Forecast requests whose snapshots share a ``ModelSpec`` batch together
    even across different snapshots (different tasks/curves) — that is the
    point: one program serves every curve of a model family.

    Tickets are stable monotonic ids, never reused; ``flush()`` also banks
    every completed result so a submitter whose requests were flushed by
    ANOTHER caller (shared batcher) can still ``result(ticket)`` them —
    collect promptly: only the ``max_banked`` most recent uncollected
    results are retained (a dead submitter's orphaned tickets must not grow
    the long-lived serving process without bound), oldest evicted first.
    """

    def __init__(self, lattice: Optional[BucketLattice] = None,
                 max_banked: int = 4096):
        self.lattice = lattice or DEFAULT_LATTICE
        self.max_banked = int(max_banked)
        self._pending: List[Tuple[int, ServingSnapshot, object]] = []
        self._done: Dict[int, dict] = {}
        self._next_ticket = 0

    def __len__(self) -> int:
        return len(self._pending)

    def submit(self, snapshot: ServingSnapshot, request) -> int:
        """Queue a request; returns its ticket (key into flush()'s dict and
        ``result()``)."""
        if not isinstance(request, (ForecastRequest, ScenarioRequest)):
            raise ServingError("forecast", f"unknown request type "
                               f"{type(request).__name__}")
        # validate the bucket eagerly so a too-large request fails at submit
        self.lattice.horizon_bucket(request.horizon)
        if isinstance(request, ScenarioRequest):
            self.lattice.scenario_bucket(request.n)
        ticket = self._next_ticket
        self._next_ticket += 1
        self._pending.append((ticket, snapshot, request))
        return ticket

    def result(self, ticket: int) -> dict:
        """Collect (and release) one flushed result by ticket.  A ticket
        whose bucket program failed re-raises that failure (structured) to
        ITS submitter only — other tickets are unaffected."""
        if ticket not in self._done:
            raise ServingError("forecast", f"ticket {ticket} has no banked "
                               "result — not flushed yet, or already "
                               "collected")
        out = self._done.pop(ticket)
        if "error" in out:
            raise ServingError(out["stage"], f"bucket program failed: "
                               f"{out['error']!r}", ticket=ticket)
        return out

    def flush(self) -> Dict[int, dict]:
        """Run every pending request through its bucket program.  Returns
        {ticket: result} for the requests flushed by THIS call (all of them
        are also banked for ``result()``).

        Failure isolation is PER TICKET, not per chunk (docs/DESIGN.md §12):
        a request that makes its padded program raise (e.g. a hand-built
        snapshot with malformed params) is re-run alone so only ITS ticket
        banks an ``{"error": exc}`` entry — the other tickets in the same
        bucket chunk still return normally; and a ticket whose per-element
        result is non-finite (or whose ``poison_ticket`` chaos seam fired)
        banks a per-ticket DEGRADED result (``"degraded": True``) instead of
        failing anything."""
        pending, self._pending = self._pending, []
        results: Dict[int, dict] = {}

        # ---- forecasts: group by (spec, horizon bucket), pad batch --------
        groups = defaultdict(list)
        for ticket, snap, req in pending:
            if isinstance(req, ForecastRequest):
                hb = self.lattice.horizon_bucket(req.horizon)
                groups[(snap.spec, hb)].append((ticket, snap, req))
        for (spec, hb), items in groups.items():
            # chunk oversized groups at the largest batch bucket
            bmax = self.lattice.batch_sizes[-1]
            for lo in range(0, len(items), bmax):
                chunk = items[lo:lo + bmax]
                try:
                    results.update(self._run_forecast_chunk(spec, hb, chunk))
                except Exception:  # noqa: BLE001 — isolate, then quarantine
                    # one poisoned request must fail ALONE: re-run each ticket
                    # as its own batch-1 program so only the offender errors
                    for item in chunk:
                        try:
                            results.update(
                                self._run_forecast_chunk(spec, hb, [item]))
                        except Exception as e1:  # noqa: BLE001
                            results[item[0]] = {"error": e1,
                                                "stage": "forecast"}

        # ---- scenarios: bucket on (horizon, n), draws axis is the batch ---
        for ticket, snap, req in pending:
            if not isinstance(req, ScenarioRequest):
                continue
            try:
                hb = self.lattice.horizon_bucket(req.horizon)
                nb = self.lattice.scenario_bucket(req.n)
                paths = scenario_paths(snap.spec, snap.params, snap.beta,
                                       snap.P, hb, nb,
                                       jax.random.PRNGKey(req.seed))
                res = {
                    "paths": np.asarray(paths)[:, :req.horizon, :req.n],
                    "version": snap.meta.version,
                }
                results[ticket] = self._maybe_degrade(res, "paths",
                                                      "scenarios")
            except Exception as e:  # noqa: BLE001
                results[ticket] = {"error": e, "stage": "scenarios"}
        self._done.update(results)  # bank for result() — shared-batcher safe
        while len(self._done) > self.max_banked:  # evict oldest (ticket order;
            self._done.pop(min(self._done))       # NOT insertion order — one
            # flush banks forecasts before scenarios, so insertion order can
            # put a newer ticket in front of an older one)
        return results

    def _run_forecast_chunk(self, spec, hb: int, chunk) -> Dict[int, dict]:
        """One padded bucket program over ≤ max-batch forecast requests."""
        bb = self.lattice.batch_bucket(len(chunk))
        pad = bb - len(chunk)
        snaps = [s for _, s, _ in chunk] + [chunk[-1][1]] * pad
        runner = _jitted_forecast_bucket(spec, hb, bb)
        dens = runner(_stack_last([s.params for s in snaps]),
                      _stack_last([s.beta for s in snaps]),
                      _stack_last([s.P for s in snaps]))
        means = np.asarray(dens["means"])   # (hb, N, bb)
        covs = np.asarray(dens["covs"])     # (hb, N, N, bb)
        smeans = np.asarray(dens["state_means"])
        scovs = np.asarray(dens["state_covs"])
        out: Dict[int, dict] = {}
        for i, (ticket, snap, req) in enumerate(chunk):
            h = req.horizon
            res = {
                "means": means[:h, :, i],
                "covs": covs[:h, :, :, i],
                "state_means": smeans[:h, :, i],
                "state_covs": scovs[:h, :, :, i],
                "version": snap.meta.version,
            }
            if req.quantiles:
                res["quantiles"] = _normal_quantiles(
                    res["means"], res["covs"], req.quantiles)
            out[ticket] = self._maybe_degrade(res, "means", "forecast")
        return out

    @staticmethod
    def _maybe_degrade(res: dict, key: str, stage: str) -> dict:
        """Per-ticket degradation mark: a non-finite per-element result (a
        NaN-sentinel snapshot riding an otherwise healthy chunk) or a fired
        ``poison_ticket`` chaos seam flags THIS ticket ``degraded`` — it is
        still returned (``result()`` raises only on ``"error"``), so the
        other tickets in the chunk are untouched and the driver decides the
        degradation policy (serving/service.py heals, the gateway answers
        from the last-good snapshot)."""
        if chaos.should_inject("poison_ticket") \
                or not np.all(np.isfinite(res[key])):
            return {**res, "degraded": True, "stage": stage}
        return res

    # ---- warmup -----------------------------------------------------------

    def warmup(self, snapshot: ServingSnapshot,
               horizons: Optional[Tuple[int, ...]] = None,
               batch_sizes: Optional[Tuple[int, ...]] = None,
               scenario_counts: Tuple[int, ...] = ()) -> int:
        """Pre-trace the bucket programs with real params/state so first
        requests hit compiled code.  Returns the number of programs touched
        (already-cached programs are free)."""
        n = 0
        # `is not None`, not `or`: an explicit EMPTY tuple means "none of
        # these", not "all of them" (same falsy-container trap as service.py)
        if horizons is None:
            horizons = self.lattice.horizons
        if batch_sizes is None:
            batch_sizes = self.lattice.batch_sizes
        for hb in horizons:
            hb = self.lattice.horizon_bucket(hb)
            for bb in batch_sizes:
                bb = self.lattice.batch_bucket(bb)
                runner = _jitted_forecast_bucket(snapshot.spec, hb, bb)
                runner(_stack_last([snapshot.params] * bb),
                       _stack_last([snapshot.beta] * bb),
                       _stack_last([snapshot.P] * bb))
                n += 1
            for nb in scenario_counts:
                nb = self.lattice.scenario_bucket(nb)
                scenario_paths(snapshot.spec, snapshot.params, snapshot.beta,
                               snapshot.P, hb, nb, jax.random.PRNGKey(0))
                n += 1
        return n
