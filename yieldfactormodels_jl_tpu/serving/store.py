"""Mesh-resident serving state: the sharded snapshot registry (DESIGN §16).

The single-service stack keeps every live filter state host-side — a dict of
per-task pytrees (`SnapshotRegistry`), gathered to host and re-staged to
device on every online update.  That is O(registry) host traffic per request
and caps serving at one state per `YieldCurveService`.  This module is the
device-scale replacement the ROADMAP's millions-of-users north star needs:

- **State lives on the mesh.**  A :class:`ShardedStateStore` holds the live
  per-user filter states — params, β, the covariance representation (P, or
  its square-root factor for the sqrt engine), and version counters — as
  device-RESIDENT arrays with the slot axis LAST (the lane rule), one shard
  per mesh device (`parallel/mesh.make_mesh`).  ``global_view()`` assembles
  the shards into batch-last ``NamedSharding`` global arrays
  (`parallel/mesh.batch_last_sharding`) — the store IS the
  ``P(None, batch)``-sharded registry, realized as per-device resident
  slices so a micro-batch launches on exactly the shard that owns it.
- **Slot management stays host-side and plain.**  A free-list per shard plus
  a ``(model_string, task_id) → (shard, slot)`` map; registering writes one
  slot through a donated scatter program (`online._jitted_slot_write`),
  never touching the rest of the shard.  Eviction and the health-rebuild
  path rewrite slots the same way — O(slot), not O(capacity).
- **Updates are shard-routed micro-batches.**  ``update_batch`` groups
  requests by owning shard, pads each group onto the lattice's
  ``update_batch_sizes`` buckets, and runs ONE donated, compile-once SPMD
  program per (shard, bucket) — `online._jitted_shard_update`, the
  ``filter_step`` core in lanes over the whole shard with scatter-selected
  slots.  A failed step keeps its resident slot in-program (sentinel NaN
  candidate + taxonomy bits ride the batch); only the per-request curve
  outputs return to host — O(batch) transfer, never O(registry).
- **Snapshot banking keeps the host-copy last-good semantics.**  Every
  accepted-and-healthy update banks host copies (β, cov-rep) per key; the
  health watch (robustness/health.py) checks each accepted update's
  returned moments, and a watch failure (or a fired ``nan_curve``/
  ``nonpsd_cov`` chaos seam) rebuilds the slot from the bank — the §11
  self-heal ladder at per-slot granularity.
- **Shard loss is a recoverable fault domain (DESIGN §24).**  Every
  accepted update is journaled host-side (`serving/journal.py`); a failed
  shard launch (or an explicit :meth:`mark_shard_lost` from a health
  sweep) marks the whole shard LOST — its keys answer degraded from the
  banked last-good while the end-of-batch rebuild wave re-homes fresh
  arrays on the reset device, re-registers every slot from its best
  surviving host source and REPLAYS each key's journal suffix through the
  same donated update program, so the post-replay resident state is
  bit-identical to the never-lost run.  A journal gap stale-flags the key
  instead of ever replaying to silently-wrong state; the ``shard_lost``
  and ``journal_gap`` chaos seams drill both paths deterministically.

Driver-layer error policy (CLAUDE.md): the kernels only sentinel; THIS
module decodes per-request taxonomy codes, and raises structured
:class:`~.snapshot.ServingError` only for structural failures (unknown key,
capacity exhausted, bad curve shape) — per-request numeric failures come
back as degraded result dicts so one poisoned curve never fails its batch.

Threading: the slot tables are lock-protected (the gateway worker thread
and a health/ops thread may both mutate them); the device arrays themselves
are single-writer — route all updates through one
:class:`~.gateway.ShardedGateway` pump (which serializes), or serialize
``update_batch`` calls yourself.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..orchestration import chaos
from ..parallel import mesh as pmesh
from ..robustness import health as rh
from ..robustness import taxonomy as tax
from ..utils.profiling import StageTimer, _nearest_rank
from .batcher import BucketLattice, MicroBatcher
from .journal import UpdateJournal
from .online import (_check_engine, _jitted_shard_update, _jitted_slot_write,
                     _jitted_slot_write_many, factor_cov)
from .service import RequestCounters
from .snapshot import (ServingError, ServingSnapshot, SnapshotMeta,
                       SnapshotRegistry)

Key = Tuple[str, int]


@dataclasses.dataclass
class RecoveryLedger:
    """Shard-loss recovery accounting (DESIGN §24) — what the failure
    domain cost and how it was repaid.  MTTR percentiles come from the
    store timer's ``recover`` samples (one per rebuilt shard, detection →
    rebuild complete); this ledger carries the counts."""
    lost_shards: int = 0        # shards marked LOST (launch failure / sweep)
    rebuilt_shards: int = 0     # rebuild waves completed
    rehomed_keys: int = 0       # keys re-registered on the reset device
    redistributed_keys: int = 0  # keys moved to surviving shards
    parked_keys: int = 0        # overflow keys parked off-mesh (warm/cold)
    replayed_updates: int = 0   # journal records re-driven through the mesh
    gapped_keys: int = 0        # keys stale-flagged by the gap detector
    degraded_answers: int = 0   # requests answered degraded during the window
    listener_errors: int = 0    # rebuild-listener callbacks that raised

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def stage_request_arrays(spec, bucket: int):
    """The ONE staging recipe for a shard-update request bucket: all-padding
    ``(Y, slots, valid)`` host buffers at the program's input signature.
    Both launch paths — ``_launch_chunk`` (hot) and ``warmup`` — build their
    request arrays HERE, so they cannot drift apart and silently double the
    per-(device, bucket) compile matrix (the PR-8 staging-mismatch bug); the
    IR-audit manifest (``analysis/manifest.py``) derives its
    ``_jitted_shard_update`` staging-parity variants from this same helper,
    pinning the recipe against the resident-state avals at lowering time."""
    Y = np.full((spec.N, bucket), np.nan, dtype=spec.dtype)
    slots = np.zeros((bucket,), dtype=np.int32)
    valid = np.zeros((bucket,), dtype=bool)
    return Y, slots, valid


def stage_slot_write_arrays(spec, bucket: int):
    """The ONE staging recipe for a batched slot-write bucket
    (``online._jitted_slot_write_many``): all-padding ``(slots, valid, p, b,
    c, v)`` host buffers at the program's input signature.  Same contract as
    :func:`stage_request_arrays` — every launch path (bulk registration,
    tier promotion/demotion, warm-up) builds its write arrays HERE, and the
    IR-audit manifest derives the program's staging-parity variants from
    this helper, so the paths cannot drift into a second compile per
    (device, bucket)."""
    dtype = spec.dtype
    slots = np.zeros((bucket,), dtype=np.int32)
    valid = np.zeros((bucket,), dtype=bool)
    p = np.zeros((spec.n_params, bucket), dtype=dtype)
    b = np.zeros((spec.state_dim, bucket), dtype=dtype)
    c = np.zeros((spec.state_dim, spec.state_dim, bucket), dtype=dtype)
    v = np.zeros((bucket,), dtype=np.int32)
    return slots, valid, p, b, c, v


def _route_waves(items, slot_map) -> List[Dict[int, list]]:
    """Group an update micro-batch by OWNING SHARD — the routing step of the
    request path (DESIGN §16 state machine), pure host dict/list work: no
    device transfer may happen here (enforced by graftlint YFM008's
    routing-path scan).  Returns a list of WAVES; each wave maps
    ``shard → [(position, slot), ...]`` with at most one request per slot
    (two updates for the same key in one batch commute through successive
    waves, never through one scatter whose duplicate order is undefined).
    Unknown keys land in pseudo-shard ``-1`` of the first wave."""
    waves: List[Dict[int, list]] = []
    remaining = list(enumerate(items))
    first = True
    while remaining:
        seen, now, later = set(), {}, []
        for pos, (key, y) in remaining:
            loc = slot_map.get(key)
            if loc is None:
                if first:
                    now.setdefault(-1, []).append((pos, -1))
            elif key in seen:
                later.append((pos, (key, y)))
            else:
                seen.add(key)
                now.setdefault(loc[0], []).append((pos, loc[1]))
        waves.append(now)
        remaining, first = later, False
    return waves


class ShardedStateStore:
    """Mesh-resident registry of live per-user filter states.

    ``shard_capacity`` is PER SHARD (total capacity = shards × capacity), so
    a mesh sweep at fixed shard capacity reuses one compiled program per
    update bucket — mesh size never enters a program key.  ``engine`` picks
    the per-slot recursion exactly as in :class:`~.service.YieldCurveService`
    (``"univariate"`` propagates P, ``"sqrt"`` a square-root factor).

    The store exposes the same operator surface as a service — ``counters``
    / ``timer`` / ``batcher`` / ``health()`` / ``latency_summary()`` — so a
    :class:`~.gateway.ShardedGateway` can sit in front of it unchanged and
    the load harness reads ONE report (DESIGN §12 discipline).
    """

    def __init__(self, spec, *, mesh=None, n_shards: Optional[int] = None,
                 shard_capacity: int = 64, engine: str = "univariate",
                 lattice: Optional[BucketLattice] = None,
                 registry: Optional[SnapshotRegistry] = None,
                 donate: bool = True, timer: Optional[StageTimer] = None,
                 axis_name: str = "batch",
                 journal_capacity: Optional[int] = None):
        _check_engine(engine)
        if shard_capacity < 1:
            raise ValueError(f"shard_capacity must be >= 1, "
                             f"got {shard_capacity}")
        self.spec = spec
        self.engine = engine
        self.mesh = mesh if mesh is not None \
            else pmesh.make_mesh(n_shards, axis_name=axis_name)
        self._axis_name = axis_name
        self._devices = pmesh.shard_devices(self.mesh)
        self.n_shards = len(self._devices)
        self.shard_capacity = int(shard_capacity)
        self.lattice = lattice if lattice is not None else BucketLattice()
        self.registry = registry
        self._donate = bool(donate)
        self.timer = timer if timer is not None else StageTimer()
        self.counters = RequestCounters()
        self.batcher = MicroBatcher(self.lattice)
        self.rebuilds = 0
        self.last_update = None
        self._last_code = 0
        self._lock = threading.Lock()
        self._slot: Dict[Key, Tuple[int, int]] = {}
        self._free: List[List[int]] = [list(range(self.shard_capacity))
                                       for _ in range(self.n_shards)]
        self._meta: Dict[Key, SnapshotMeta] = {}
        self._bank: Dict[Key, Tuple[np.ndarray, np.ndarray]] = {}
        self._stale: set = set()
        # shard-loss fault domain (DESIGN §24): the accepted-update journal,
        # per-key bank versions/params for rebuild sources, lost-shard table
        # (shard → detection timestamp), keys stale-flagged by a journal
        # gap (they stay stale until a refit re-bases them), the recovery
        # ledger, and the blast-radius listeners a rebuild must notify
        self.journal = UpdateJournal(self.n_shards,
                                     capacity=journal_capacity)
        self.recovery = RecoveryLedger()
        self._bank_ver: Dict[Key, int] = {}
        self._bank_params: Dict[Key, np.ndarray] = {}
        self._lost: Dict[int, Tuple[float, str]] = {}
        self._gapped_keys: set = set()
        self._rebuild_listeners: list = []
        self._rebuilding = False
        dtype = spec.dtype
        Pn, Ms, Cs = spec.n_params, spec.state_dim, self.shard_capacity
        self._shards = []
        for d in self._devices:
            self._shards.append({
                "params": jax.device_put(jnp.zeros((Pn, Cs), dtype=dtype), d),
                "beta": jax.device_put(jnp.zeros((Ms, Cs), dtype=dtype), d),
                "cov": jax.device_put(jnp.zeros((Ms, Ms, Cs), dtype=dtype),
                                      d),
                "ver": jax.device_put(jnp.zeros((Cs,), dtype=jnp.int32), d),
            })

    # ---- introspection ----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._slot)

    def __contains__(self, key: Key) -> bool:
        with self._lock:
            return key in self._slot

    @property
    def capacity(self) -> int:
        return self.n_shards * self.shard_capacity

    def keys(self):
        with self._lock:
            return sorted(self._slot)

    def shard_of(self, key: Key) -> int:
        with self._lock:
            if key not in self._slot:
                raise ServingError("store", f"no state registered for {key}")
            return self._slot[key][0]

    def global_view(self) -> dict:
        """The store as batch-last mesh-sharded GLOBAL arrays — zero-copy
        assembly of the per-device resident shards under
        ``NamedSharding(mesh, P(None, ..., axis))``.  Introspection/export
        only: mutation goes through the per-shard donated programs."""
        out = {}
        for name, nd in (("params", 2), ("beta", 2), ("cov", 3), ("ver", 1)):
            shards = [self._shards[s][name] for s in range(self.n_shards)]
            gshape = tuple(shards[0].shape[:-1]) + (self.capacity,)
            sharding = pmesh.batch_last_sharding(self.mesh, nd,
                                                 self._axis_name)
            out[name] = jax.make_array_from_single_device_arrays(
                gshape, sharding, shards)
        return out

    # ---- slot lifecycle ---------------------------------------------------

    def _write_state(self, s: int, sl: int, beta, cov, ver: int,
                     params=None) -> None:
        """Rewrite ONE slot of shard ``s`` through the donated scatter
        program — O(slot) work, the shard is never gathered."""
        sh = self._shards[s]
        dtype = self.spec.dtype
        p = sh["params"][:, sl] if params is None \
            else jnp.asarray(params, dtype=dtype).reshape(-1)
        writer = _jitted_slot_write(self.spec, self.shard_capacity,
                                    self._donate)
        sh["params"], sh["beta"], sh["cov"], sh["ver"] = writer(
            sh["params"], sh["beta"], sh["cov"], sh["ver"],
            jnp.asarray(sl, dtype=jnp.int32), p,
            jnp.asarray(beta, dtype=dtype),
            jnp.asarray(cov, dtype=dtype),
            jnp.asarray(ver, dtype=jnp.int32))

    def _write_state_many(self, s: int, entries) -> None:
        """Rewrite MANY slots of shard ``s`` in one donated scatter per
        lattice bucket — the batched sibling of :meth:`_write_state`
        (``online._jitted_slot_write_many``): a bulk registration or a tier
        promotion/demotion wave costs one device dispatch per (shard,
        bucket-chunk), not one per slot.  ``entries`` is ``[(slot, params,
        beta, cov, ver), ...]`` with UNIQUE slots (scatter duplicate order
        is undefined — callers route one write per slot per wave)."""
        if not entries:
            return
        sh = self._shards[s]
        bmax = self.lattice.update_batch_sizes[-1]
        for lo in range(0, len(entries), bmax):
            chunk = entries[lo:lo + bmax]
            bb = self.lattice.update_bucket(len(chunk))
            slots, valid, p, b, c, v = stage_slot_write_arrays(self.spec, bb)
            for j, (sl, pj, bj, cj, vj) in enumerate(chunk):
                slots[j], valid[j] = sl, True
                p[:, j] = np.asarray(pj).reshape(-1)
                b[:, j] = bj
                c[:, :, j] = cj
                v[j] = vj
            writer = _jitted_slot_write_many(self.spec, self.shard_capacity,
                                             bb, self._donate)
            sh["params"], sh["beta"], sh["cov"], sh["ver"] = writer(
                sh["params"], sh["beta"], sh["cov"], sh["ver"],
                slots, valid, p, b, c, v)

    def spec_for(self, key: Key):
        """The spec serving ``key`` — one spec per store here; the fleet
        seam (``tiers.StoreFleet``) routes per-key."""
        del key
        return self.spec

    def register(self, snapshot: ServingSnapshot) -> Key:
        """Admit one frozen snapshot: allocate a slot on the least-loaded
        shard, factor the covariance into the engine representation, write
        the slot (donated scatter), bank the host-copy last-good."""
        key = (snapshot.meta.model_string, snapshot.meta.task_id)
        try:
            cov = factor_cov(snapshot.P, self.engine, self.spec.dtype)
        except ValueError:
            raise ServingError("store", "filtered covariance is not PSD — "
                               "cannot start the sqrt engine", key=key)
        with self._lock:
            if key in self._slot:
                raise ServingError("store", f"key {key} already registered — "
                                   "evict it first", key=key)
            frees = [len(f) for f in self._free]
            s = int(np.argmax(frees))
            if frees[s] == 0:
                raise ServingError(
                    "store", f"capacity exhausted ({self.capacity} slots on "
                    f"{self.n_shards} shards) — widen shard_capacity or the "
                    f"mesh", key=key)
            sl = self._free[s].pop()
            self._write_state(s, sl, snapshot.beta, cov,
                              snapshot.meta.version, params=snapshot.params)
            self._slot[key] = (s, sl)
            self._meta[key] = snapshot.meta
            self._bank[key] = (np.asarray(snapshot.beta, dtype=np.float64),
                               np.asarray(cov, dtype=np.float64))
            self._bank_ver[key] = snapshot.meta.version
            self._bank_params[key] = np.asarray(
                snapshot.params, dtype=np.float64).reshape(-1)
            self._gapped_keys.discard(key)
        self.journal.note_base(key, snapshot.meta.version)
        return key

    def register_many(self, snapshots) -> List[Key]:
        """Bulk registration.  On an EMPTY store the shards are assembled
        host-side and shipped with ONE placement per shard array (no
        per-slot programs — the warm-boot path must not pay thousands of
        scatter launches); on a non-empty store the validated batch rides
        the batched slot-write program (:meth:`_write_state_many` —
        ``online._jitted_slot_write_many``), one donated dispatch per
        (shard, bucket-chunk), so resident state is never gathered and the
        cost is O(batch) launches, not O(batch) scatters.  Both branches are
        all-or-nothing: a mid-list failure leaves the store untouched."""
        snapshots = list(snapshots)
        dtype = self.spec.dtype
        # validate + factor EVERYTHING before touching any table or shard:
        # a mid-list failure must leave the store exactly as it was, never
        # half-registered (review finding: a partial bulk boot would alias
        # later tenants onto zero-state slots)
        if len(snapshots) > self.capacity:
            raise ServingError(
                "store", f"{len(snapshots)} snapshots exceed capacity "
                f"{self.capacity} ({self.n_shards} shards × "
                f"{self.shard_capacity})")
        staged = []
        seen = set()
        for snap in snapshots:
            key = (snap.meta.model_string, snap.meta.task_id)
            if key in seen:
                raise ServingError("store", f"key {key} appears twice in "
                                   "the bulk registration", key=key)
            seen.add(key)
            try:
                cov = np.asarray(factor_cov(snap.P, self.engine, dtype))
            except ValueError:
                raise ServingError("store", "filtered covariance is not "
                                   "PSD — cannot start the sqrt engine",
                                   key=key)
            staged.append((key, snap, cov))
        with self._lock:
            if self._slot:
                empty = False
            else:
                empty = True
                Pn, Ms, Cs = self.spec.n_params, self.spec.state_dim, \
                    self.shard_capacity
                staging = [{"params": np.zeros((Pn, Cs)),
                            "beta": np.zeros((Ms, Cs)),
                            "cov": np.zeros((Ms, Ms, Cs)),
                            "ver": np.zeros((Cs,), dtype=np.int32)}
                           for _ in range(self.n_shards)]
                keys = []
                for i, (key, snap, cov) in enumerate(staged):
                    s, sl = i % self.n_shards, i // self.n_shards
                    st = staging[s]
                    st["params"][:, sl] = np.asarray(snap.params).reshape(-1)
                    st["beta"][:, sl] = np.asarray(snap.beta)
                    st["cov"][:, :, sl] = cov
                    st["ver"][sl] = snap.meta.version
                    self._slot[key] = (s, sl)
                    self._meta[key] = snap.meta
                    self._bank[key] = (
                        np.asarray(snap.beta, dtype=np.float64),
                        np.asarray(cov, dtype=np.float64))
                    self._bank_ver[key] = snap.meta.version
                    self._bank_params[key] = np.asarray(
                        snap.params, dtype=np.float64).reshape(-1)
                    keys.append(key)
                for s, (st, d) in enumerate(zip(staging, self._devices)):
                    taken = {sl for (sh, sl) in self._slot.values()
                             if sh == s}
                    self._free[s] = [sl for sl in range(Cs)
                                     if sl not in taken]
                    self._shards[s] = {
                        name: jax.device_put(
                            jnp.asarray(st[name], dtype=dtype)
                            if name != "ver" else jnp.asarray(st[name]), d)
                        for name in ("params", "beta", "cov", "ver")}
        if not empty:
            # non-empty store: batched slot writes into the free slots
            # (resident state never gathered, and nothing was mutated above
            # beyond the validation pass — re-checked all-or-nothing here)
            with self._lock:
                clash = [k for k, _, _ in staged if k in self._slot]
                if clash:
                    raise ServingError(
                        "store", f"key {clash[0]} already registered — "
                        "evict it first", key=clash[0])
                if len(staged) > sum(len(f) for f in self._free):
                    raise ServingError(
                        "store", f"{len(staged)} snapshots exceed the "
                        f"{sum(len(f) for f in self._free)} free slots — "
                        "widen shard_capacity or the mesh")
                keys = []
                per_shard: Dict[int, list] = {}
                for key, snap, cov in staged:
                    s = int(np.argmax([len(f) for f in self._free]))
                    sl = self._free[s].pop()
                    per_shard.setdefault(s, []).append(
                        (sl, snap.params, snap.beta, cov,
                         snap.meta.version))
                    self._slot[key] = (s, sl)
                    self._meta[key] = snap.meta
                    self._bank[key] = (
                        np.asarray(snap.beta, dtype=np.float64),
                        np.asarray(cov, dtype=np.float64))
                    self._bank_ver[key] = snap.meta.version
                    self._bank_params[key] = np.asarray(
                        snap.params, dtype=np.float64).reshape(-1)
                    keys.append(key)
                for s in sorted(per_shard):
                    self._write_state_many(s, per_shard[s])
        for key in keys:
            self.journal.note_base(key, self._meta[key].version)
        return keys

    def evict(self, key: Key) -> None:
        """Free a key's slot (zeroed through the scatter program so a stale
        state can never be read back by a later tenant)."""
        with self._lock:
            if key not in self._slot:
                raise ServingError("store", f"no state registered for {key}")
            s, sl = self._slot.pop(key)
            Ms = self.spec.state_dim
            self._write_state(s, sl, np.zeros(Ms), np.zeros((Ms, Ms)), 0,
                              params=np.zeros(self.spec.n_params))
            self._free[s].append(sl)
            self._meta.pop(key, None)
            self._bank.pop(key, None)
            self._bank_ver.pop(key, None)
            self._bank_params.pop(key, None)
            self._stale.discard(key)
            self._gapped_keys.discard(key)
        self.journal.forget(key)

    def publish_refit(self, key: Key, params, history=None, beta=None,
                      P=None) -> dict:
        """Publish an estimate-side refit STRAIGHT into the live slot
        (ROADMAP 2c — the old path was evict → freeze → re-register): new
        model parameters, optionally fresh filtered moments, one donated
        ``_jitted_slot_write`` scatter — O(slot), the shard never gathered,
        the key stays continuously servable (readers between the decision
        and the write see the previous consistent state).

        Moment source, in order: ``history`` (an (N, T) panel — the state is
        rebuilt under the NEW params via the freeze filter, the
        amortized-refit flow of docs/DESIGN.md §20), explicit ``(beta, P)``
        (a caller who already filtered), or neither (the slot keeps its
        resident moments — a pure parameter swap).  Structural failures
        (unknown key, failed filter pass, non-PSD covariance) raise
        :class:`ServingError` with the slot UNTOUCHED."""
        with self._lock:
            if key not in self._slot:
                raise ServingError("store",
                                   f"no state registered for {key}", key=key)
            s, sl = self._slot[key]
        p = np.asarray(params, dtype=np.float64).reshape(-1)
        if p.shape[0] != self.spec.n_params:
            raise ServingError(
                "store", f"refit params have {p.shape[0]} entries, spec has "
                f"{self.spec.n_params}", key=key)
        cov = None
        if history is not None:
            from .snapshot import freeze_snapshot

            snap = freeze_snapshot(self.spec, p, history)
            beta, P = snap.beta, snap.P
        if beta is not None:
            # expensive work (filter pass, factorization) stays OUTSIDE the
            # lock; the refit's history/moments are authoritative over any
            # update that lands meanwhile (refit semantics)
            try:
                cov = np.asarray(factor_cov(P, self.engine, self.spec.dtype),
                                 dtype=np.float64)
            except ValueError:
                raise ServingError("store", "refit covariance is not PSD — "
                                   "cannot start the sqrt engine", key=key)
            beta = np.asarray(beta, dtype=np.float64)
        with self.timer.stage("refit_publish"):
            with self._lock:
                if self._slot.get(key) != (s, sl):  # evicted mid-flight
                    raise ServingError(
                        "store", f"{key} was evicted during the refit",
                        key=key)
                if beta is None:
                    # pure parameter swap: the slot keeps its resident
                    # moments — read UNDER the lock (an unlocked read could
                    # tear against a concurrent update's slot write and pair
                    # β from one version with cov from another), and reuse
                    # the resident ENGINE representation as-is
                    sh = self._shards[s]
                    beta = np.asarray(sh["beta"][:, sl], dtype=np.float64)
                    cov = np.asarray(sh["cov"][:, :, sl], dtype=np.float64)
                meta = self._meta[key].bump()
                self._write_state(s, sl, beta, cov, meta.version, params=p)
                self._meta[key] = meta
                self._bank[key] = (beta, cov)
                self._bank_ver[key] = meta.version
                self._bank_params[key] = p
                self._stale.discard(key)
                # a refit is a fresh authoritative state: it re-bases the
                # journal watermark and heals a gap-stale key
                self._gapped_keys.discard(key)
        self.journal.note_base(key, meta.version)
        return {"key": key, "version": meta.version, "stale": False}

    def _rebuild_slot(self, key: Key, s: int, sl: int) -> None:
        """The §11 heal path at slot granularity: rewrite the slot from the
        banked last-good host copies, falling back to the frozen registry
        entry when even the bank fails the watch.  Never gathers the shard."""
        beta, cov = self._bank[key]
        if rh.state_health(beta, cov, self.engine)["code"] != tax.OK \
                and self.registry is not None:
            try:
                snap = self.registry.get(*key)
                cov = np.asarray(factor_cov(snap.P, self.engine,
                                            self.spec.dtype))
                beta = np.asarray(snap.beta, dtype=np.float64)
                self._bank[key] = (beta, cov)
            except (ServingError, ValueError):
                pass  # bank is still the best available source
        self._write_state(s, sl, beta, cov, self._meta[key].version)
        self.rebuilds += 1

    # ---- the update path --------------------------------------------------

    def update_batch(self, items, dates=None) -> List[dict]:
        """Advance many keys' states by one observed curve each, routed to
        the shards that own them.  ``items`` is ``[(key, yields), ...]``;
        returns one result dict per item IN ORDER: ``{"ll", "version",
        "stale"}`` on success, ``{"ll": nan, "degraded": True, ...}`` on a
        per-request numeric failure (state kept / rebuilt per §11), or
        ``{"error": ServingError}`` for structural failures — one poisoned
        request never fails its batch (worker-isolation contract)."""
        res: List[Optional[dict]] = [None] * len(items)
        staged = []
        N = self.spec.N
        for pos, (key, y) in enumerate(items):
            y = np.asarray(y, dtype=np.float64).reshape(-1)
            if y.shape[0] != N:
                res[pos] = {"error": ServingError(
                    "update", f"curve has {y.shape[0]} maturities, spec has "
                    f"{N}", key=key)}
                continue
            staged.append((pos, key, y))
        routed = [(k, y) for _, k, y in staged]
        with self._lock:
            waves = _route_waves(routed, self._slot)
        bmax = self.lattice.update_batch_sizes[-1]
        for wave in waves:
            for s, group in sorted(wave.items()):
                if s < 0:
                    for gpos, _ in group:
                        pos, key, _ = staged[gpos]
                        res[pos] = {"error": ServingError(
                            "update", f"no state registered for {key}",
                            key=key)}
                    continue
                for lo in range(0, len(group), bmax):
                    self._launch_chunk(s, group[lo:lo + bmax], staged, dates,
                                       res)
        if self._lost:
            # the rebuild wave runs at the batch boundary: the failing
            # batch's requests were already answered degraded from the
            # bank; the NEXT batch meets a healthy mesh (DESIGN §24)
            self.recover_lost_shards()
        return res  # every position filled: staged ∪ shape-rejected

    def _launch_chunk(self, s: int, chunk, staged, dates, res) -> None:
        """One (shard, bucket) donated launch + host-side collection.  The
        padded request arrays go in as plain host buffers (jit stages them
        onto the owning shard's device alongside the committed resident
        state — no per-input device_put dispatches on the hot path).

        Shard-loss seam (DESIGN §24): a chunk routed to an already-LOST
        shard answers degraded from the bank without launching; a fired
        ``shard_lost`` chaos seam drops the shard's resident arrays right
        here (the simulated whole-shard device loss), and ANY launch
        failure marks the shard lost instead of raising out of the batch —
        the worker-isolation contract holds at shard granularity too."""
        if s in self._lost:
            self._answer_lost(s, chunk, staged, res)
            return
        bb = self.lattice.update_bucket(len(chunk))
        Y, slots, valid = stage_request_arrays(self.spec, bb)
        for j, (gpos, sl) in enumerate(chunk):
            Y[:, j] = staged[gpos][2]
            slots[j], valid[j] = sl, True
        if chaos.should_inject("shard_lost"):
            with self._lock:
                self._shards[s] = None   # resident arrays genuinely gone
        sh = self._shards[s]
        runner = _jitted_shard_update(self.spec, self.engine,
                                      self.shard_capacity, bb, self._donate)
        try:
            if sh is None:
                raise RuntimeError(f"shard {s} resident arrays lost")
            outs = runner(sh["params"], sh["beta"], sh["cov"], sh["ver"],
                          Y, slots, valid)
        except Exception as e:  # launch failure = the whole fault domain
            self._note_lost(s, repr(e))
            self._answer_lost(s, chunk, staged, res)
            return
        sh["params"], sh["beta"], sh["cov"], sh["ver"] = outs[:4]
        self._collect(s, chunk, staged, dates, outs[4:], res)

    def _collect(self, s: int, chunk, staged, dates, curve_outs, res) -> None:
        """The RESPONSE BOUNDARY: the per-request curve outputs (O(batch))
        come to host here — one fetch — and nowhere earlier on the update
        path; then each request gets the driver-layer verdict: taxonomy
        decode, batched health watch, chaos seams, slot rebuild, last-good
        banking."""
        lls, oks, codes, vers, betas, covs = jax.device_get(curve_outs)
        watch = rh.state_health_batch(betas, covs, self.engine)
        for j, (gpos, sl) in enumerate(chunk):
            pos, key, _ = staged[gpos]
            ok, code = bool(oks[j]), int(codes[j])
            b_h = np.asarray(betas[:, j], dtype=np.float64)
            c_h = np.asarray(covs[:, :, j], dtype=np.float64)
            injected = False
            if ok and chaos.should_inject("nan_curve"):
                # numeric chaos (DESIGN §11): poison that made it INTO the
                # accepted resident slot — written to device so the rebuild
                # genuinely repairs corrupted mesh state, not a host mirage
                b_h = np.full_like(b_h, np.nan)
                c_h = np.full_like(c_h, np.nan)
                self._write_state(s, sl, b_h, c_h, int(vers[j]))
                code |= tax.NAN_STATE
                injected = True
            if ok and chaos.should_inject("nonpsd_cov"):
                c_h = c_h - 2.0 * np.eye(c_h.shape[0])
                self._write_state(s, sl, b_h, c_h, int(vers[j]))
                code |= tax.NONPSD_COV
                injected = True
            if ok and not injected:
                code |= int(watch[j])
            if ok and not injected and code == 0:
                # accepted and healthy: bank host copies, sync the meta,
                # journal the accept (the replay source a lost shard is
                # rebuilt from — the journal_gap seam drops one append,
                # which the journal's watermark detector must catch)
                with self._lock:
                    self._meta[key] = self._meta[key].bump()
                    self._bank[key] = (b_h, c_h)
                    self._bank_ver[key] = int(vers[j])
                    # a gap-stale key keeps its stale flag through later
                    # accepts: its state diverged from the never-lost run
                    # and only a refit re-bases it (DESIGN §24)
                    gap_stale = key in self._gapped_keys
                    if gap_stale:
                        self._stale.add(key)
                    else:
                        self._stale.discard(key)
                if not chaos.should_inject("journal_gap"):
                    self.journal.append(
                        s, key, dates[pos] if dates is not None else None,
                        staged[gpos][2], int(vers[j]))
                if dates is not None:
                    self.last_update = dates[pos]
                res[pos] = {"ll": float(lls[j]),
                            "version": int(vers[j]), "stale": gap_stale}
                continue
            # degraded: kernel reject (state untouched in-program) needs no
            # rebuild; an accepted-then-unhealthy/chaos-corrupted slot does
            if ok:
                with self.timer.stage("rebuild"):
                    with self._lock:
                        self._rebuild_slot(key, s, sl)
            with self._lock:
                self._stale.add(key)
            self._last_code = code
            res[pos] = {"ll": float("nan"), "degraded": True, "stale": True,
                        "version": self._meta[key].version,
                        "code": tax.describe(code)}

    # ---- shard-loss fault domain (DESIGN §24) -----------------------------

    def _note_lost(self, s: int, reason: str) -> None:
        """Transition shard ``s`` to LOST: drop its resident arrays, stamp
        the detection time (the MTTR clock starts here) and ledger it.
        Idempotent — a second detection of the same loss is a no-op."""
        with self._lock:
            if s in self._lost:
                return
            self._lost[s] = (time.perf_counter(), reason)
            self._shards[s] = None
            self.recovery.lost_shards += 1

    def mark_shard_lost(self, s: int,
                        reason: str = "whole-shard health sweep") -> None:
        """Operator verb: declare shard ``s`` LOST (a failed whole-shard
        health sweep, a wedged relay, an ops decision).  Its keys answer
        degraded from the banked last-good until :meth:`recover_lost_shards`
        — which the next ``update_batch`` runs automatically — rebuilds
        it."""
        if not 0 <= s < self.n_shards:
            raise ServingError("store", f"no shard {s} on a "
                               f"{self.n_shards}-shard mesh")
        self._note_lost(s, reason)

    def _answer_lost(self, s: int, chunk, staged, res) -> None:
        """Degraded answers for a chunk routed to a LOST shard: the banked
        last-good version is what the caller can still read
        (``last_good_snapshot_of``), the update itself is NOT applied — it
        was never accepted, so the zero-lost-ACCEPTED-updates invariant is
        untouched."""
        del s
        for gpos, _sl in chunk:
            pos, key, _ = staged[gpos]
            with self._lock:
                self._stale.add(key)
                self.recovery.degraded_answers += 1
                ver = self._meta[key].version
            res[pos] = {"ll": float("nan"), "degraded": True, "stale": True,
                        "version": ver,
                        "reason": "shard lost — serving last-good until "
                                  "the rebuild wave lands"}

    @property
    def rebuilding(self) -> bool:
        """True while a shard is LOST or a rebuild wave is in flight — the
        fleet seam (``tiers.StoreFleet``) routes around a rebuilding
        member."""
        with self._lock:
            return bool(self._lost) or self._rebuilding

    def add_rebuild_listener(self, fn) -> None:
        """Blast-radius hook: ``fn(keys)`` is called after a rebuild wave
        with every affected key — the streaming hub breaks those keys'
        delta chains (full ``stress_fan`` recompute, serving/streams.py)."""
        self._rebuild_listeners.append(fn)

    def _rebuild_plan(self, s: int):
        """Which keys lived on the LOST shard and what each needs: slot,
        expected (meta) version, and the bank's version — the replay
        window.  Pure host dict/list routing (graftlint YFM008's
        routing-path scan): no host transfer may happen while planning;
        the array work lives in :meth:`_rebuild_shard`'s flush."""
        with self._lock:
            keys = sorted(k for k, loc in self._slot.items() if loc[0] == s)
            return [(k, self._slot[k][1], self._meta[k].version,
                     self._bank_ver.get(k, self._meta[k].version))
                    for k in keys]

    def _rebuild_source(self, key: Key):
        """Best surviving host-side source for a key's rebuild: the banked
        last-good (freshest), falling back to the frozen registry entry
        when the bank fails the health watch — the §11 ladder applied at
        rebuild scope.  Returns ``(params, beta, cov, version, healthy)``;
        the tiered store interposes its warm records (serving/tiers.py)."""
        with self._lock:
            banked = self._bank.get(key)
            ver = self._bank_ver.get(key, self._meta[key].version)
            params = self._bank_params.get(key)
        if banked is not None and params is not None:
            beta, cov = banked
            if rh.state_health(beta, cov, self.engine)["code"] == tax.OK:
                return params, beta, cov, ver, True
        else:
            beta = cov = None
        if self.registry is not None:
            try:
                snap = self.registry.get(*key)
                cov2 = np.asarray(factor_cov(snap.P, self.engine,
                                             self.spec.dtype),
                                  dtype=np.float64)
                beta2 = np.asarray(snap.beta, dtype=np.float64)
                p2 = np.asarray(snap.params, dtype=np.float64).reshape(-1)
                return p2, beta2, cov2, int(snap.meta.version), True
            except (ServingError, ValueError):
                pass  # bank is still the best available source
        if beta is None:
            raise ServingError(
                "store", f"no surviving rebuild source for {key} — no bank, "
                "no registry entry", key=key)
        return params, beta, cov, ver, False

    def _rebuild_overflow(self, key: Key, params, beta, cov, ver: int,
                          stale: bool) -> bool:
        """Absorb a key that found no free slot during a redistributing
        rebuild.  The base store has no off-mesh tier, so it cannot — the
        caller falls back to re-homing the key on the reset device.  The
        tiered store overrides this to park the key warm (DESIGN §21
        spill discipline)."""
        del key, params, beta, cov, ver, stale
        return False

    def recover_lost_shards(self, redistribute: bool = False) -> List[int]:
        """The failover rebuild wave (DESIGN §24) for every LOST shard:
        fresh resident arrays, every affected slot re-registered from its
        best surviving host source, each key's journal suffix replayed in
        version order through the same donated update program — post-replay
        state bit-identical to the never-lost run for every ungapped key;
        a journal gap stale-flags the key instead.  ``redistribute=True``
        spreads the keys over the SURVIVING shards' free slots (overflow
        handled by :meth:`_rebuild_overflow`) instead of re-homing on the
        reset device.  Returns the rebuilt shard ids; one MTTR sample per
        shard (detection → rebuilt) lands in the timer's ``recover``
        stage."""
        with self._lock:
            lost = sorted(self._lost)
            if not lost:
                return []
            self._rebuilding = True
        affected: List[Key] = []
        try:
            for s in lost:
                with self.timer.stage("rebuild_wave"):
                    affected.extend(self._rebuild_shard(s, redistribute))
                with self._lock:
                    t0, _reason = self._lost.pop(s)
                    self.recovery.rebuilt_shards += 1
                self.timer.record("recover", time.perf_counter() - t0)
        finally:
            with self._lock:
                self._rebuilding = False
        if affected:
            self._notify_rebuilt(affected)
        return lost

    def _rebuild_shard(self, s: int, redistribute: bool) -> List[Key]:
        """One shard's rebuild flush: allocate fresh arrays on the reset
        device, route every affected key to its rebuild slot, write the
        source states in batched donated scatters, then replay the journal
        suffixes.  Returns the affected keys (the blast radius)."""
        plan = self._rebuild_plan(s)
        dtype = self.spec.dtype
        Pn, Ms, Cs = self.spec.n_params, self.spec.state_dim, \
            self.shard_capacity
        d = self._devices[s]
        fresh = {
            "params": jax.device_put(jnp.zeros((Pn, Cs), dtype=dtype), d),
            "beta": jax.device_put(jnp.zeros((Ms, Cs), dtype=dtype), d),
            "cov": jax.device_put(jnp.zeros((Ms, Ms, Cs), dtype=dtype), d),
            "ver": jax.device_put(jnp.zeros((Cs,), dtype=jnp.int32), d),
        }
        with self._lock:
            self._shards[s] = fresh
            if redistribute:
                for key, _sl, _exp, _bv in plan:
                    self._slot.pop(key, None)
                self._free[s] = list(range(Cs))
        entries: Dict[int, list] = {}           # shard → slot-write entries
        replay: Dict[int, list] = {}            # shard → (key, slot, recs)
        for key, sl, expected, _bank_hint in plan:
            try:
                params, beta, cov, src_ver, healthy = \
                    self._rebuild_source(key)
            except ServingError:
                # nothing survives anywhere for this key: drop it from
                # residency (a later update meets the structural unknown-key
                # error — loud, not silently-wrong) and ledger the loss
                with self._lock:
                    self._slot.pop(key, None)
                    if not redistribute:
                        self._free[s].append(sl)
                    self._stale.add(key)
                    self.recovery.gapped_keys += 1
                continue
            recs, ok = self.journal.suffix(key, src_ver, expected)
            target = None
            if redistribute:
                with self._lock:
                    frees = [len(f) if t != s and t not in self._lost else -1
                             for t, f in enumerate(self._free)]
                    t_best = int(np.argmax(frees))
                    if frees[t_best] > 0:
                        target = (t_best, self._free[t_best].pop())
                        self._slot[key] = target
                        self.recovery.redistributed_keys += 1
                if target is None:
                    # a parked key never replays: if its suffix is gapped OR
                    # non-empty, the parked record is behind the accepted
                    # stream — park it stale, never silently regressed
                    if self._rebuild_overflow(key, params, beta, cov,
                                              src_ver,
                                              stale=(not ok) or bool(recs)):
                        with self._lock:
                            self.recovery.parked_keys += 1
                        continue
            if target is None:      # re-home on the reset device
                with self._lock:
                    if redistribute:
                        sl = self._free[s].pop()
                    self._slot[key] = (s, sl)
                    self.recovery.rehomed_keys += 1
                target = (s, sl)
            entries.setdefault(target[0], []).append(
                (target[1], params, beta, cov, src_ver))
            with self._lock:
                self._bank[key] = (np.asarray(beta, dtype=np.float64),
                                   np.asarray(cov, dtype=np.float64))
                self._bank_ver[key] = src_ver
                if not ok:
                    # gap detector verdict: the suffix cannot be trusted —
                    # stale-flag forever (until a refit re-bases), never
                    # replay to silently-wrong state
                    self._stale.add(key)
                    self._gapped_keys.add(key)
                    self.recovery.gapped_keys += 1
                elif not healthy:
                    self._stale.add(key)
            if ok and recs:
                replay.setdefault(target[0], []).append(
                    (key, target[1], recs))
        for t in sorted(entries):
            self._write_state_many(t, entries[t])
        for t in sorted(replay):
            self._replay_suffixes(t, replay[t])
        return [key for key, _sl, _exp, _bv in plan]

    def _replay_suffixes(self, s: int, items) -> int:
        """Re-drive journal records through the SAME donated shard-update
        program the live path uses, in version order per key — a
        deterministic program on identical inputs gives bit-identical
        post-replay state (each slot's recursion sees only its own state
        and curve; the padding-invariance pin in tests/test_store.py is the
        same property).  ``items`` is ``[(key, slot, records), ...]`` on
        shard ``s``; one wave per record rank keeps one write per slot per
        launch.  A replayed accept that fails to re-accept (impossible
        unless the journal lied) stale-flags the key."""
        bmax = self.lattice.update_batch_sizes[-1]
        rank, replayed = 0, 0
        dead: set = set()
        while True:
            wave = [(key, sl, recs[rank]) for key, sl, recs in items
                    if rank < len(recs) and key not in dead]
            if not wave:
                break
            for lo in range(0, len(wave), bmax):
                chunk = wave[lo:lo + bmax]
                bb = self.lattice.update_bucket(len(chunk))
                Y, slots, valid = stage_request_arrays(self.spec, bb)
                for j, (_key, sl, rec) in enumerate(chunk):
                    Y[:, j] = rec.curve
                    slots[j], valid[j] = sl, True
                sh = self._shards[s]
                runner = _jitted_shard_update(self.spec, self.engine,
                                              self.shard_capacity, bb,
                                              self._donate)
                outs = runner(sh["params"], sh["beta"], sh["cov"],
                              sh["ver"], Y, slots, valid)
                sh["params"], sh["beta"], sh["cov"], sh["ver"] = outs[:4]
                _lls, oks, _codes, vers, betas, covs = \
                    jax.device_get(outs[4:])
                for j, (key, _sl, rec) in enumerate(chunk):
                    if bool(oks[j]) and int(vers[j]) == rec.version:
                        with self._lock:
                            self._bank[key] = (
                                np.asarray(betas[:, j], dtype=np.float64),
                                np.asarray(covs[:, :, j], dtype=np.float64))
                            self._bank_ver[key] = rec.version
                            self.recovery.replayed_updates += 1
                        replayed += 1
                    else:
                        dead.add(key)
                        with self._lock:
                            self._stale.add(key)
                            self._gapped_keys.add(key)
                            self.recovery.gapped_keys += 1
            rank += 1
        return replayed

    def _notify_rebuilt(self, keys: List[Key]) -> None:
        """Blast-radius fan-out after a rebuild wave: standing scenario
        fans over the affected keys must break their delta chains (the hub
        recomputes them from the rebuilt state).  A listener failure never
        breaks the store — it is ledgered instead."""
        for fn in list(self._rebuild_listeners):
            try:
                fn(list(keys))
            except Exception:
                with self._lock:
                    self.recovery.listener_errors += 1

    # ---- read-side snapshots ---------------------------------------------

    def _snapshot_of_locked(self, key: Key) -> ServingSnapshot:
        """:meth:`snapshot_of` body with ``self._lock`` HELD by the caller —
        the tiered store resolves the hot tier and builds the device slices
        under one acquisition so a concurrent demotion wave can't invalidate
        the slot between check and slice (serving/tiers.py)."""
        s, sl = self._slot[key]
        meta = self._meta[key]
        sh = self._shards[s]
        if sh is None:
            raise ServingError(
                "store", f"shard {s} is LOST — rebuild pending "
                f"(recover_lost_shards()); serve last_good_snapshot_of",
                key=key)
        c = sh["cov"][:, :, sl]
        P = c @ c.T if self.engine == "sqrt" else c
        return ServingSnapshot(self.spec, sh["params"][:, sl],
                               sh["beta"][:, sl], P, meta)

    def snapshot_of(self, key: Key) -> ServingSnapshot:
        """The key's LIVE state as a snapshot with DEVICE leaves (params, β,
        P) — slot-sized device slices, no host transfer: forecast/scenario
        requests ride these through the shared micro-batcher and only the
        batcher's outputs cross to host (the response boundary)."""
        with self._lock:
            if key not in self._slot:
                raise ServingError("store", f"no state registered for {key}")
            return self._snapshot_of_locked(key)

    def _last_good_locked(self, key: Key) -> ServingSnapshot:
        """:meth:`last_good_snapshot_of` body with ``self._lock`` held by
        the caller (same single-acquisition rationale as
        :meth:`_snapshot_of_locked`)."""
        beta, cov = self._bank[key]
        meta = self._meta[key]
        P = cov @ cov.T if self.engine == "sqrt" else cov
        return ServingSnapshot(self.spec, None, beta, P, meta)

    def last_good_snapshot_of(self, key: Key) -> ServingSnapshot:
        """The banked last-good state (host copies) as a snapshot — what a
        deadline-degraded answer is served from (DESIGN §12)."""
        with self._lock:
            if key not in self._bank:
                raise ServingError("store", f"no state registered for {key}")
            return self._last_good_locked(key)

    # ---- observability / warmup ------------------------------------------

    def health(self) -> dict:
        with self._lock:
            live, stale = len(self._slot), len(self._stale)
            free = sum(len(f) for f in self._free)
            lost_now = {s: reason for s, (_t, reason) in self._lost.items()}
            recovery = self.recovery.to_dict()
        mttr = sorted(self.timer.samples.get("recover", ()))
        recovery.update({
            "lost_now": lost_now,
            "mttr_p50_s": _nearest_rank(mttr, 0.50) if mttr else 0.0,
            "mttr_p99_s": _nearest_rank(mttr, 0.99) if mttr else 0.0,
        })
        status = "rebuilding" if lost_now else \
            ("stale" if stale else "ok")
        return {
            "status": status,
            "engine": self.engine,
            "shards": self.n_shards,
            "shard_capacity": self.shard_capacity,
            "live": live,
            "free": free,
            "stale_keys": stale,
            "rebuilds": self.rebuilds,
            "last_code": self._last_code,
            "last_code_names": tax.decode(self._last_code),
            "requests": self.counters.to_dict(),
            "recovery": recovery,
            "chaos": chaos.observe(),
        }

    def latency_summary(self) -> dict:
        return {**self.timer.summary(), "counters": self.counters.to_dict()}

    def warmup(self, horizons=None, batch_sizes=(1,),
               scenario_counts=()) -> int:
        """Pre-trace every shard-update bucket program ON EVERY SHARD (an
        all-padding launch is an exact no-op: ``valid`` all false, every slot
        passes through) plus the read-path bucket programs for one registered
        snapshot.  Returns programs touched."""
        n = 0
        with self.timer.stage("warmup"):
            for bb in self.lattice.update_batch_sizes:
                runner = _jitted_shard_update(self.spec, self.engine,
                                              self.shard_capacity, bb,
                                              self._donate)
                # request arrays staged EXACTLY like _launch_chunk's: both
                # paths build them in stage_request_arrays — a different
                # staging signature here would compile a second executable
                # per (device, bucket) and the first live request would pay
                # it on the hot path
                Y, slots, valid = stage_request_arrays(self.spec, bb)
                for sh in self._shards:
                    if sh is None:      # LOST shard awaiting its rebuild
                        continue
                    outs = runner(sh["params"], sh["beta"], sh["cov"],
                                  sh["ver"], Y, slots, valid)
                    sh["params"], sh["beta"], sh["cov"], sh["ver"] = outs[:4]
                    n += 1
            keys = self.keys()
            if keys:
                n += self.batcher.warmup(self.snapshot_of(keys[0]),
                                         horizons=horizons,
                                         batch_sizes=batch_sizes,
                                         scenario_counts=scenario_counts)
        return n
