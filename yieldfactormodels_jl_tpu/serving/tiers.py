"""Tiered state residency for the sharded store (docs/DESIGN.md §21).

PR 8 made per-user filter states device-resident (``store.ShardedStateStore``)
— and capped serving at what fits in HBM.  This module adds the memory
hierarchy behind those hot slots, the serving-side analogue of KV-cache
paging in LLM inference stacks:

- **Hot tier** — the mesh-resident slots, unchanged: donated shard-update
  programs, O(batch) host traffic, the only tier that serves live updates.
- **Warm tier** (:class:`WarmTier`) — evicted slots frozen to PACKED host-RAM
  arrays holding the exact ENGINE representation (params, β, cov-rep,
  version) plus meta/stale bits.  Because the engine representation itself
  is frozen (never re-factored), a demote → promote round trip restores the
  hot slot **bit-for-bit** (pinned in tests/test_tiers.py) — the freeze/thaw
  parity invariant.
- **Cold tier** — the :class:`~.snapshot.SnapshotRegistry` behind the warm
  tier: warm overflow spills there as moment-space snapshots (β, P).  Cold →
  hot re-factors the covariance (``factor_cov``), so warm↔hot is bit-exact
  while cold↔hot is moment-exact — the sqrt engine's factor is not unique,
  and the §11 health watch guards the re-factorization.

**Policy** (:class:`TieredStateStore`): an LRU access clock (one integer per
key, bumped on every accounted request), promotion on miss, demotion of the
coldest resident keys under pressure.  Promotions and demotions move in
WAVES: one gathered fetch per shard on the way out, one donated
``online._jitted_slot_write_many`` scatter per (shard, bucket-chunk) on the
way in — a burst of misses costs one device dispatch per shard, not one per
user, and the steady-state hot path adds ZERO retraces (the write program's
key never mentions mesh size or wave content).

**Request flow**: ``update_batch`` accounts each request against the ledger
(hit / warm miss / cold miss), promotes the missed keys in one wave, then
delegates to the base shard-routed launch.  A key whose promotion cannot
land this wave (the ``promote_stall`` chaos seam, a health-watch rejection
with no cold fallback, or genuine capacity starvation) is answered with a
DEGRADED stale result — never an error, never a blocked batch (the §12
degrade machinery).  Reads are tier-transparent: ``snapshot_of`` serves
warm/cold keys from their host records directly; the
:class:`~.gateway.ShardedGateway` pump pre-promotes the read keys of each
drained batch (``prepare_reads``) so read bursts ride the same batched
promotion wave.

**Chaos seams** (orchestration/chaos.py, ``YFM_CHAOS`` grammar):
``evict_corrupt`` poisons one frozen warm record at demotion time — the
promotion-side health watch must catch it and rebuild from the cold tier
(§11 ladder); ``promote_stall`` drops one whole promotion wave — the
affected requests degrade and the next wave retries.

**Capacity ledger** (:class:`TierLedger`): hits, per-tier misses,
promotions/demotions/spills/drops and stall counts, plus per-wave promotion
latency percentiles through the store timer — the honest numbers behind the
``BENCH_LOAD=1`` working-set column and BASELINE round 13's
states-per-chip-at-fixed-p99 metric.

Threading follows store.py: tier tables ride the store lock, the packed
warm arrays their own lock (always acquired store → warm, never reverse);
the device arrays stay single-writer — route updates through ONE gateway
pump.  Without a cold registry the tier stack is LOSSY past hot+warm
capacity: the coldest warm record is dropped (counted in
``ledger.dropped``) — give the store a registry when state must survive
arbitrary working sets.
"""

from __future__ import annotations

import dataclasses
import heapq
import os
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..orchestration import chaos
from ..robustness import health as rh
from ..robustness import taxonomy as tax
from ..utils.profiling import StageTimer, _nearest_rank
from .batcher import MicroBatcher
from .online import _jitted_slot_write_many, factor_cov
from .snapshot import ServingError, ServingSnapshot, SnapshotMeta
from .store import Key, ShardedStateStore, stage_slot_write_arrays
from .service import RequestCounters


class WarmRecord(NamedTuple):
    """One frozen slot: the exact engine representation plus its identity.
    ``params``/``beta``/``cov`` are host copies at the store dtype — the
    bits that went cold are the bits that come back hot."""

    params: np.ndarray
    beta: np.ndarray
    cov: np.ndarray
    ver: int
    meta: SnapshotMeta
    stale: bool
    stamp: int


class WarmTier:
    """Packed host-RAM columns of frozen slots (docs/DESIGN.md §21).

    One preallocated array per state field with the slot axis LAST (same
    layout discipline as the device shards, so freeze/thaw is a column copy,
    not a transpose), a free-list, and a ``key → column`` map.  Bounded:
    ``capacity`` columns, full stop — the warm tier is a memory bound, not a
    cache that grows.  Thread-safe: every map/array access holds the tier
    lock (the store mutates under its own lock from the pump thread while
    health/ops threads read)."""

    def __init__(self, spec, capacity: int):
        if capacity < 1:
            raise ValueError(f"warm capacity must be >= 1, got {capacity}")
        self.spec = spec
        self.capacity = int(capacity)
        Pn, Ms, W = spec.n_params, spec.state_dim, self.capacity
        self._lock = threading.Lock()
        self._idx: Dict[Key, int] = {}
        self._free: List[int] = list(range(W))
        self._params = np.zeros((Pn, W), dtype=spec.dtype)
        self._beta = np.zeros((Ms, W), dtype=spec.dtype)
        self._cov = np.zeros((Ms, Ms, W), dtype=spec.dtype)
        self._ver = np.zeros((W,), dtype=np.int32)
        self._meta: Dict[Key, SnapshotMeta] = {}
        self._stale: set = set()
        self._stamp: Dict[Key, int] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._idx)

    def __contains__(self, key: Key) -> bool:
        with self._lock:
            return key in self._idx

    def keys(self):
        with self._lock:
            return sorted(self._idx)

    def free(self) -> int:
        with self._lock:
            return len(self._free)

    def coldest(self) -> Optional[Key]:
        """The least-recently-used warm key (the spill candidate)."""
        with self._lock:
            if not self._idx:
                return None
            return min(self._idx, key=lambda k: (self._stamp.get(k, 0), k))

    def put(self, key: Key, params, beta, cov, ver: int, meta: SnapshotMeta,
            stale: bool, stamp: int) -> None:
        """Freeze one record into a packed column.  Raises when full — the
        CALLER owns the spill policy (``TieredStateStore`` spills the
        coldest record to the cold registry first)."""
        with self._lock:
            i = self._idx.get(key)
            if i is None:
                if not self._free:
                    raise ServingError(
                        "store", f"warm tier exhausted ({self.capacity} "
                        "records) — spill to the cold registry first",
                        key=key)
                i = self._free.pop()
                self._idx[key] = i
            self._params[:, i] = np.asarray(params).reshape(-1)
            self._beta[:, i] = beta
            self._cov[:, :, i] = cov
            self._ver[i] = ver
            self._meta[key] = meta
            self._stamp[key] = int(stamp)
            if stale:
                self._stale.add(key)
            else:
                self._stale.discard(key)

    def _record_locked(self, key: Key) -> WarmRecord:
        i = self._idx[key]
        return WarmRecord(self._params[:, i].copy(), self._beta[:, i].copy(),
                          self._cov[:, :, i].copy(), int(self._ver[i]),
                          self._meta[key], key in self._stale,
                          self._stamp.get(key, 0))

    def peek(self, key: Key) -> Optional[WarmRecord]:
        """Copy one record without thawing it (degraded answers, reads)."""
        with self._lock:
            if key not in self._idx:
                return None
            return self._record_locked(key)

    def pop(self, key: Key) -> Optional[WarmRecord]:
        """Thaw one record: copy it out and free its column."""
        with self._lock:
            if key not in self._idx:
                return None
            rec = self._record_locked(key)
            self._free.append(self._idx.pop(key))
            self._meta.pop(key, None)
            self._stale.discard(key)
            self._stamp.pop(key, None)
            return rec

    def discard(self, key: Key) -> bool:
        """Drop a record without reading it; True when one existed."""
        with self._lock:
            if key not in self._idx:
                return False
            self._free.append(self._idx.pop(key))
            self._meta.pop(key, None)
            self._stale.discard(key)
            self._stamp.pop(key, None)
            return True


@dataclasses.dataclass
class TierLedger:
    """Request-path tier accounting (docs/DESIGN.md §21).  ``hits`` counts
    requests whose key was hot at accounting time; ``misses_*`` the tier the
    key was found in instead; promotion/demotion/spill/drop/stall counters
    track the waves those misses triggered.  ``dropped`` > 0 means state was
    LOST (warm overflow with no cold registry) — the lossy-mode tell."""

    hits: int = 0
    misses_warm: int = 0
    misses_cold: int = 0
    promotions: int = 0
    demotions: int = 0
    spills: int = 0
    dropped: int = 0
    promote_stalls: int = 0
    corrupt_rebuilds: int = 0

    @property
    def accounted(self) -> int:
        return self.hits + self.misses_warm + self.misses_cold

    @property
    def hit_rate(self) -> float:
        n = self.accounted
        return self.hits / n if n else 1.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["hit_rate"] = round(self.hit_rate, 6)
        return d


def _env_warm_cap(hot_capacity: int) -> int:
    raw = os.environ.get("YFM_STORE_WARM_CAP", "")
    return int(raw) if raw else 4 * hot_capacity


class TieredStateStore(ShardedStateStore):
    """A :class:`~.store.ShardedStateStore` with hot/warm/cold residency
    tiers and an LRU promotion/demotion policy (module docstring; lifecycle
    state machine in docs/DESIGN.md §21).

    ``warm_capacity`` bounds the packed host tier (default
    ``YFM_STORE_WARM_CAP``, else 4× the hot capacity); ``registry`` — the
    base class's rebuild source — doubles as the cold tier: warm overflow
    spills there, and promotion falls back to it when a warm record fails
    the health watch.  All other knobs as the base store.  The operator
    surface grows ``tiers()`` (occupancy + :class:`TierLedger` + promotion
    latency percentiles), ``demote()`` / ``ensure_resident()`` /
    ``prepare_reads()`` verbs, and ``health()['tiers']``.
    """

    def __init__(self, spec, *, warm_capacity: Optional[int] = None,
                 **kwargs):
        super().__init__(spec, **kwargs)
        if warm_capacity is None:
            warm_capacity = _env_warm_cap(self.capacity)
        self.warm = WarmTier(spec, warm_capacity)
        self.ledger = TierLedger()
        self._tick = 0
        self._access: Dict[Key, int] = {}

    # ---- introspection ----------------------------------------------------

    def __contains__(self, key: Key) -> bool:
        if super().__contains__(key) or key in self.warm:
            return True
        return self.registry is not None and key in self.registry

    def tiers(self) -> dict:
        """Occupancy + ledger + per-wave promotion latency (ms) — the
        capacity-ledger record the BENCH_LOAD working-set column reads."""
        with self._lock:
            hot = len(self._slot)
            hot_free = sum(len(f) for f in self._free)
        out = {
            "hot": hot, "hot_capacity": self.capacity, "hot_free": hot_free,
            "warm": len(self.warm), "warm_capacity": self.warm.capacity,
            "cold": len(self.registry) if self.registry is not None else 0,
            "ledger": self.ledger.to_dict(),
        }
        promo = sorted(self.timer.samples.get("promote", ()))
        out["promote_waves"] = len(promo)
        out["promote_p50_ms"] = round(
            1e3 * _nearest_rank(promo, 0.50), 3) if promo else 0.0
        out["promote_p99_ms"] = round(
            1e3 * _nearest_rank(promo, 0.99), 3) if promo else 0.0
        return out

    def health(self) -> dict:
        out = super().health()
        out["tiers"] = self.tiers()
        return out

    def _touch_locked(self, key: Key) -> None:
        self._tick += 1
        self._access[key] = self._tick

    def _tier_version(self, key: Key) -> int:
        rec = self.warm.peek(key)
        if rec is not None:
            return rec.meta.version
        if self.registry is not None and key in self.registry:
            return self.registry.get(*key).meta.version
        return 0

    # ---- registration across tiers ----------------------------------------

    def register(self, snapshot: ServingSnapshot) -> Key:
        key = (snapshot.meta.model_string, snapshot.meta.task_id)
        if key in self.warm:
            raise ServingError("store", f"key {key} is already warm-"
                               "resident — evict it first", key=key)
        with self._lock:
            if key not in self._slot \
                    and not any(len(f) for f in self._free):
                victims = self._demote_plan(1, exclude={key})
                if not victims:
                    raise ServingError(
                        "store", f"capacity exhausted ({self.capacity} hot "
                        "slots) and nothing demotable", key=key)
                with self.timer.stage("demote"):
                    self._demote_locked(victims)
        k = super().register(snapshot)
        with self._lock:
            self._touch_locked(k)
        return k

    def register_many(self, snapshots) -> List[Key]:
        """Bulk boot across tiers: the first ``hot_free`` snapshots take hot
        slots (the base store's batched paths), the remainder freeze
        STRAIGHT into the warm tier (no device work) — how a working set
        larger than residency boots.  All-or-nothing like the base: the
        whole list is validated (duplicates, warm clashes, PSD, warm fit)
        before anything mutates."""
        snapshots = list(snapshots)
        seen = set()
        for snap in snapshots:
            key = (snap.meta.model_string, snap.meta.task_id)
            if key in seen:
                raise ServingError("store", f"key {key} appears twice in "
                                   "the bulk registration", key=key)
            seen.add(key)
            if key in self.warm:
                raise ServingError("store", f"key {key} is already warm-"
                                   "resident — evict it first", key=key)
        with self._lock:
            hot_free = sum(len(f) for f in self._free)
        head, tail = snapshots[:hot_free], snapshots[hot_free:]
        staged_tail = []
        for snap in tail:
            key = (snap.meta.model_string, snap.meta.task_id)
            try:
                cov = np.asarray(factor_cov(snap.P, self.engine,
                                            self.spec.dtype))
            except ValueError:
                raise ServingError("store", "filtered covariance is not "
                                   "PSD — cannot start the sqrt engine",
                                   key=key)
            staged_tail.append((key, snap, cov))
        if staged_tail and self.registry is None \
                and len(staged_tail) > self.warm.free():
            raise ServingError(
                "store", f"{len(staged_tail)} overflow snapshots exceed the "
                f"{self.warm.free()} free warm records and no cold registry "
                "is attached — widen YFM_STORE_WARM_CAP or attach one")
        keys = list(super().register_many(head)) if head else []
        with self._lock:
            for k in keys:
                self._touch_locked(k)
        for key, snap, cov in staged_tail:
            self._warm_put_with_spill(
                key, np.asarray(snap.params), np.asarray(snap.beta), cov,
                snap.meta.version, snap.meta, stale=False, stamp=0)
            keys.append(key)
        return keys

    def evict(self, key: Key) -> None:
        """Drop a key from the hot or warm tier (the cold registry is the
        durable archive — its entries outlive an eviction, exactly as they
        do for the base store's rebuild path)."""
        with self._lock:
            hot = key in self._slot
        if hot:
            super().evict(key)
            with self._lock:
                self._access.pop(key, None)
            return
        if not self.warm.discard(key):
            raise ServingError("store", f"no state registered for {key}")
        self.journal.forget(key)

    # ---- demotion (hot → warm → cold) --------------------------------------

    def _demote_plan(self, n: int, exclude) -> List[Key]:
        """Pick the ``n`` coldest demotable resident keys (LRU by access
        clock) — pure host routing work, lock held by the caller; no device
        transfer may happen here (graftlint YFM008)."""
        return heapq.nsmallest(
            n, (k for k in self._slot if k not in exclude),
            key=lambda k: (self._access.get(k, 0), k))

    def _warm_put_with_spill(self, key: Key, params, beta, cov, ver, meta,
                             stale: bool, stamp: int) -> None:
        """Freeze one record, spilling the coldest warm record to the cold
        registry (moment-space snapshot) when the packed tier is full —
        or DROPPING it (``ledger.dropped``) when no registry is attached."""
        while key not in self.warm and self.warm.free() == 0:
            victim = self.warm.coldest()
            rec = self.warm.pop(victim)
            if rec is None:
                break
            if self.registry is not None:
                P = rec.cov @ rec.cov.T if self.engine == "sqrt" else rec.cov
                self.registry.put(ServingSnapshot(
                    self.spec, rec.params, rec.beta, P, rec.meta))
                self.ledger.spills += 1
            else:
                self.ledger.dropped += 1
        self.warm.put(key, params, beta, cov, ver, meta, stale, stamp)

    def _demote_locked(self, victims: List[Key]) -> None:
        """Freeze the victims' slots to the warm tier: per owning shard, ONE
        gathered fetch per lattice bucket-chunk, indices PADDED to the
        bucket size so the gather executables are as fixed-shape as the
        slot-write programs (``warmup`` primes both — a live wave never pays
        a compile).  The freed slots keep their last bits: they are
        unreachable (every read path resolves through the slot table) and
        the next promotion wave's donated scatter overwrites them, so
        demotion ships O(wave) host traffic and zero scatters of its own.
        The ``evict_corrupt`` chaos seam fires per frozen record (a poisoned
        freeze the promotion-side health watch must catch).  Store lock held
        by the caller."""
        groups: Dict[int, list] = {}
        for key in victims:
            if key not in self._slot:
                continue
            s, sl = self._slot[key]
            groups.setdefault(s, []).append((key, sl))
        bmax = self.lattice.update_batch_sizes[-1]
        for s in sorted(groups):
            sh = self._shards[s]
            for lo in range(0, len(groups[s]), bmax):
                chunk = groups[s][lo:lo + bmax]
                bb = self.lattice.update_bucket(len(chunk))
                sls = np.full(bb, chunk[-1][1], dtype=np.int32)
                sls[:len(chunk)] = [sl for _, sl in chunk]
                p_h, b_h, c_h, v_h = jax.device_get(
                    (sh["params"][:, sls], sh["beta"][:, sls],
                     sh["cov"][:, :, sls], sh["ver"][sls]))
                for j, (key, sl) in enumerate(chunk):
                    beta_j, cov_j = b_h[:, j].copy(), c_h[:, :, j].copy()
                    if chaos.should_inject("evict_corrupt"):
                        beta_j = np.full_like(beta_j, np.nan)
                        cov_j = np.full_like(cov_j, np.nan)
                    self._warm_put_with_spill(
                        key, p_h[:, j].copy(), beta_j, cov_j, int(v_h[j]),
                        self._meta[key], stale=key in self._stale,
                        stamp=self._access.get(key, 0))
                    self._slot.pop(key)
                    self._free[s].append(sl)
                    self._meta.pop(key, None)
                    self._bank.pop(key, None)
                    self._bank_ver.pop(key, None)
                    self._bank_params.pop(key, None)
                    self._stale.discard(key)
                    self._access.pop(key, None)
                    self.ledger.demotions += 1

    def demote(self, keys) -> None:
        """Explicitly freeze resident keys to the warm tier (operator verb;
        the pressure path calls the same machinery)."""
        keys = list(dict.fromkeys(keys))
        with self.timer.stage("demote"):
            with self._lock:
                missing = [k for k in keys if k not in self._slot]
                if missing:
                    raise ServingError(
                        "store", f"no state registered for {missing[0]}",
                        key=missing[0])
                self._demote_locked(keys)

    # ---- promotion (warm/cold → hot) ---------------------------------------

    def _account(self, keys) -> None:
        """Classify each requested key against the tiers (hit / warm miss /
        cold miss) and touch the hot ones' access clocks — the ONE
        accounting point per request (update path here, read path through
        the gateway's ``prepare_reads``); pure host routing work
        (graftlint YFM008)."""
        with self._lock:
            for k in keys:
                if k in self._slot:
                    self.ledger.hits += 1
                    self._touch_locked(k)
                elif k in self.warm:
                    self.ledger.misses_warm += 1
                elif self.registry is not None and k in self.registry:
                    self.ledger.misses_cold += 1

    def _promote_plan(self, keys) -> Optional[dict]:
        """Decide the promotion wave: which keys thaw, which resident keys
        demote to make room, which overflow (more misses than demotable
        slots) — pure host routing work, lock held by the caller; no device
        transfer may happen here (graftlint YFM008)."""
        want, seen = [], set()
        for k in keys:
            if k in seen or k in self._slot:
                continue
            seen.add(k)
            if k in self.warm or (self.registry is not None
                                  and k in self.registry):
                want.append(k)
        if not want:
            return None
        free_total = sum(len(f) for f in self._free)
        shortfall = len(want) - free_total
        victims: List[Key] = []
        overflow: List[Key] = []
        if shortfall > 0:
            victims = self._demote_plan(shortfall, exclude=seen)
            fit = free_total + len(victims)
            want, overflow = want[:fit], want[fit:]
        return {"want": want, "victims": victims, "overflow": overflow}

    def ensure_resident(self, keys) -> Tuple[List[Key], List[Key]]:
        """Make the warm/cold keys among ``keys`` hot in ONE batched wave
        (demote-for-room → thaw → health watch → batched slot writes).
        Returns ``(promoted, unpromoted)`` — an unpromoted key (stalled
        wave, failed watch with no fallback, capacity starvation) stays
        servable from its tier record; its updates degrade."""
        with self._lock:
            plan = self._promote_plan(keys)
        if plan is None:
            return [], []
        with self.timer.stage("promote"):
            with self._lock:
                promoted, unpromoted = self._promote_flush_locked(plan)
        return promoted, unpromoted + plan["overflow"]

    def prepare_reads(self, keys) -> None:
        """The gateway pump's read-side pre-promotion hook: account the
        drained read keys and promote their misses in one wave (so a read
        burst costs one dispatch per shard, exactly like an update burst)."""
        self._account(keys)
        self.ensure_resident(keys)

    def _promote_flush_locked(self, plan) -> Tuple[List[Key], List[Key]]:
        """Execute one promotion wave (store lock held): demote victims,
        thaw the wanted records (warm first, cold fallback), run the §11
        health watch over the whole wave in one batch, then write the
        survivors through the batched slot-write program — one donated
        dispatch per (shard, bucket-chunk)."""
        if plan["victims"]:
            self._demote_locked(plan["victims"])
        want = plan["want"]
        if chaos.should_inject("promote_stall"):
            self.ledger.promote_stalls += len(want)
            return [], list(want)
        thawed, unpromoted = [], []
        for key in want:
            rec = self.warm.pop(key)
            src = "warm"
            if rec is None:
                rec = self._cold_record(key)
                src = "cold"
            if rec is None:
                unpromoted.append(key)
                continue
            thawed.append((key, rec, src))
        if thawed:
            betas = np.stack([r.beta for _, r, _ in thawed], axis=-1)
            covs = np.stack([r.cov for _, r, _ in thawed], axis=-1)
            codes = np.asarray(rh.state_health_batch(betas, covs,
                                                     self.engine))
        good = []
        for j, (key, rec, src) in enumerate(thawed):
            if int(codes[j]) != tax.OK:
                fallback = self._cold_record(key) if src == "warm" else None
                if fallback is not None and rh.state_health(
                        fallback.beta, fallback.cov,
                        self.engine)["code"] == tax.OK:
                    rec = fallback
                    self.rebuilds += 1
                    self.ledger.corrupt_rebuilds += 1
                else:
                    # unpromotable: park the poisoned record back in the
                    # warm tier, stale-flagged — visible, never silently
                    # dropped; its requests degrade until an operator refit
                    self._warm_put_with_spill(
                        key, rec.params, rec.beta, rec.cov, rec.ver,
                        rec.meta, stale=True, stamp=rec.stamp)
                    unpromoted.append(key)
                    continue
            good.append((key, rec))
        per_shard: Dict[int, list] = {}
        for key, rec in good:
            s = int(np.argmax([len(f) for f in self._free]))
            sl = self._free[s].pop()
            per_shard.setdefault(s, []).append(
                (sl, rec.params, rec.beta, rec.cov, rec.ver))
            self._slot[key] = (s, sl)
            self._meta[key] = rec.meta
            self._bank[key] = (np.asarray(rec.beta, dtype=np.float64),
                               np.asarray(rec.cov, dtype=np.float64))
            self._bank_ver[key] = int(rec.ver)
            self._bank_params[key] = np.asarray(
                rec.params, dtype=np.float64).reshape(-1)
            # promotion RE-BASES the key's journal: replay determinism is
            # measured from the freshly installed record (a cold promote is
            # moment-exact — the pre-demotion journaled history no longer
            # applies to this base)
            self.journal.note_base(key, int(rec.ver))
            if rec.stale:
                self._stale.add(key)
            else:
                self._stale.discard(key)
            self._touch_locked(key)
            self.ledger.promotions += 1
        for s in sorted(per_shard):
            self._write_state_many(s, per_shard[s])
        return [k for k, _ in good], unpromoted

    def _cold_record(self, key: Key) -> Optional[WarmRecord]:
        """A cold-tier snapshot as a thawable record (engine re-factored —
        the moment-exact leg of the hierarchy)."""
        if self.registry is None or key not in self.registry:
            return None
        snap = self.registry.get(*key)
        try:
            cov = np.asarray(factor_cov(snap.P, self.engine,
                                        self.spec.dtype))
        except ValueError:
            return None
        params = snap.params if snap.params is not None \
            else np.zeros(self.spec.n_params)
        return WarmRecord(np.asarray(params), np.asarray(snap.beta), cov,
                          snap.meta.version, snap.meta, False, 0)

    # ---- shard-loss recovery across tiers (DESIGN §24) ---------------------

    def _rebuild_source(self, key: Key):
        """The base rebuild ladder (bank → cold registry) with the WARM
        tier interposed: a frozen warm record is engine-exact (bit-for-bit,
        the §21 freeze/thaw invariant) where the cold snapshot is only
        moment-exact, so a healthy warm record outranks both an unhealthy
        bank and the registry as the rebuild source."""
        try:
            src = super()._rebuild_source(key)
            if src[4]:
                return src
        except ServingError:
            src = None
        rec = self.warm.peek(key)
        if rec is not None and rh.state_health(
                rec.beta, rec.cov, self.engine)["code"] == tax.OK:
            return (np.asarray(rec.params, dtype=np.float64).reshape(-1),
                    np.asarray(rec.beta, dtype=np.float64),
                    np.asarray(rec.cov, dtype=np.float64),
                    int(rec.ver), True)
        if src is None:
            raise ServingError(
                "store", f"no surviving rebuild source for {key} — no "
                "bank, no warm record, no registry entry", key=key)
        return src

    def _rebuild_overflow(self, key: Key, params, beta, cov, ver: int,
                          stale: bool) -> bool:
        """Park a key that found no hot slot during a redistributing
        rebuild into the warm tier (the §21 spill discipline): servable
        immediately from its host record, promoted back on its next miss.
        ``stale`` means the parked record is BEHIND the accepted stream
        (gapped or unreplayed suffix) — it parks stale-flagged and the key
        joins the gap set so only a refit/re-register heals it; the meta
        version is rolled back to the parked record so the served version
        is never a lie."""
        with self._lock:
            meta = self._meta.get(key)
            stamp = self._access.get(key, 0)
        if meta is None:
            return False
        meta = dataclasses.replace(meta, version=int(ver))
        dt = self.spec.dtype
        self._warm_put_with_spill(
            key, np.asarray(params, dtype=dt).reshape(-1),
            np.asarray(beta, dtype=dt), np.asarray(cov, dtype=dt),
            int(ver), meta, stale=stale, stamp=stamp)
        with self._lock:
            self._meta.pop(key, None)
            self._bank.pop(key, None)
            self._bank_ver.pop(key, None)
            self._bank_params.pop(key, None)
            self._stale.discard(key)
            self._access.pop(key, None)
            if stale:
                self._gapped_keys.add(key)
                self.recovery.gapped_keys += 1
        self.journal.note_base(key, int(ver))
        return True

    # ---- the tier-aware request path ---------------------------------------

    def update_batch(self, items, dates=None) -> List[dict]:
        """The base shard-routed update path with miss handling in front:
        account every request, promote the missed keys in one wave, answer
        the unpromotable ones DEGRADED from their tier record (never an
        error, never a blocked batch), and delegate the resident rest."""
        keys = [k for k, _ in items]
        self._account(keys)
        _, unpromoted = self.ensure_resident(keys)
        un = set(unpromoted)
        if not un:
            return super().update_batch(items, dates=dates)
        res: List[Optional[dict]] = [None] * len(items)
        sub, mapping = [], []
        for pos, (key, y) in enumerate(items):
            if key in un:
                res[pos] = {"ll": float("nan"), "degraded": True,
                            "stale": True,
                            "version": self._tier_version(key),
                            "reason": "promotion did not land this wave"}
            else:
                mapping.append(pos)
                sub.append((key, y))
        outs = super().update_batch(
            sub, dates=[dates[p] for p in mapping] if dates is not None
            else None)
        for pos, out in zip(mapping, outs):
            res[pos] = out
        return res

    # ---- tier-transparent reads --------------------------------------------

    def snapshot_of(self, key: Key) -> ServingSnapshot:
        """Hot keys serve device slices exactly as the base store (resolved
        and sliced under ONE lock acquisition — a concurrent demotion wave
        can't invalidate the slot between check and slice); warm and cold
        keys serve their HOST record directly (no promotion, no device work
        — reads are tier-transparent; the gateway pump batch-promotes read
        keys via :meth:`prepare_reads` before it gets here).  The tier walk
        re-runs once on a complete miss: a key mid-promotion is briefly in
        neither table (warm.pop → slot write, store lock held throughout),
        and the second walk's hot check blocks on that lock until the wave
        lands."""
        for _ in range(2):
            with self._lock:
                if key in self._slot:
                    self._touch_locked(key)
                    return self._snapshot_of_locked(key)
            rec = self.warm.peek(key)
            if rec is not None:
                P = rec.cov @ rec.cov.T if self.engine == "sqrt" else rec.cov
                return ServingSnapshot(self.spec, rec.params, rec.beta, P,
                                       rec.meta)
            if self.registry is not None and key in self.registry:
                return self.registry.get(*key)
        raise ServingError("store", f"no state registered for {key}")

    def last_good_snapshot_of(self, key: Key) -> ServingSnapshot:
        for _ in range(2):  # same mid-promotion re-walk as snapshot_of
            with self._lock:
                if key in self._bank:
                    return self._last_good_locked(key)
            rec = self.warm.peek(key)
            if rec is not None:
                P = rec.cov @ rec.cov.T if self.engine == "sqrt" else rec.cov
                return ServingSnapshot(self.spec, None, rec.beta, P,
                                       rec.meta)
            if self.registry is not None and key in self.registry:
                return self.registry.get(*key)
        raise ServingError("store", f"no state registered for {key}")

    # ---- warmup -------------------------------------------------------------

    def warmup(self, horizons=None, batch_sizes=(1,),
               scenario_counts=()) -> int:
        """Base warmup plus both halves of a promotion/demotion wave, per
        (shard, bucket): the batched slot-write programs via an all-padding
        wave (an exact no-op — ``valid`` all false, every scatter drops),
        staged through the same ``stage_slot_write_arrays`` recipe as the
        live waves, and the demote-side gather executables via a slot-0
        fetch at each bucket shape — a first live miss burst must not pay a
        compile on the hot path."""
        n = super().warmup(horizons=horizons, batch_sizes=batch_sizes,
                           scenario_counts=scenario_counts)
        with self.timer.stage("warmup"):
            for bb in self.lattice.update_batch_sizes:
                writer = _jitted_slot_write_many(
                    self.spec, self.shard_capacity, bb, self._donate)
                args = stage_slot_write_arrays(self.spec, bb)
                idx = np.zeros(bb, dtype=np.int32)
                for sh in self._shards:
                    outs = writer(sh["params"], sh["beta"], sh["cov"],
                                  sh["ver"], *args)
                    sh["params"], sh["beta"], sh["cov"], sh["ver"] = outs
                    jax.device_get((sh["params"][:, idx], sh["beta"][:, idx],
                                    sh["cov"][:, :, idx], sh["ver"][idx]))
                    n += 1
        return n


class StoreFleet:
    """One gateway, MANY stores — the multi-model fleet seam
    (docs/DESIGN.md §21): requests are routed to the store serving their
    key's ``model_string``, and the fleet duck-types the full service
    surface a :class:`~.gateway.ShardedGateway` reads (``counters`` /
    ``timer`` / ``batcher`` / ``update_batch`` / ``snapshot_of`` / …), so
    one pump, one bounded queue, one operator report serve a whole fleet of
    model families on one mesh.  Reads from every member micro-batch
    through ONE shared :class:`~.batcher.MicroBatcher` (it already groups
    per spec).  The routing table is immutable after construction — the
    fleet itself needs no lock; each member store keeps its own."""

    def __init__(self, stores, timer: Optional[StageTimer] = None):
        stores = list(stores)
        if not stores:
            raise ServingError("fleet", "a fleet needs at least one store")
        self._stores: Dict[str, ShardedStateStore] = {}
        for st in stores:
            ms = st.spec.model_string
            if ms in self._stores:
                raise ServingError(
                    "fleet", f"two stores serve model {ms!r} — one store "
                    "per model_string", model=ms)
            self._stores[ms] = st
        self.timer = timer if timer is not None else StageTimer()
        self.counters = RequestCounters()
        self.batcher = MicroBatcher(stores[0].lattice)

    # ---- routing -----------------------------------------------------------

    def stores(self) -> dict:
        return dict(self._stores)

    def _route(self, key: Key) -> ShardedStateStore:
        st = self._stores.get(key[0])
        if st is None:
            raise ServingError(
                "fleet", f"no store serves model {key[0]!r}", key=key,
                known=sorted(self._stores))
        return st

    def spec_for(self, key: Key):
        return self._route(key).spec

    def __contains__(self, key: Key) -> bool:
        st = self._stores.get(key[0])
        return st is not None and key in st

    def __len__(self) -> int:
        return sum(len(st) for st in self._stores.values())

    def keys(self):
        out = []
        for st in self._stores.values():
            out.extend(st.keys())
        return sorted(out)

    # ---- the service surface the gateway reads ------------------------------

    def update_batch(self, items, dates=None) -> List[dict]:
        """Partition the batch by owning store (pure host routing), delegate
        each group in one call, merge the results back IN ORDER — an
        unroutable key gets a structured error result, never fails its
        batch."""
        res: List[Optional[dict]] = [None] * len(items)
        groups: Dict[str, list] = {}
        for pos, (key, y) in enumerate(items):
            if key[0] in self._stores:
                groups.setdefault(key[0], []).append(pos)
            else:
                res[pos] = {"error": ServingError(
                    "fleet", f"no store serves model {key[0]!r}", key=key)}
        for ms in sorted(groups):
            poss = groups[ms]
            outs = self._stores[ms].update_batch(
                [items[p] for p in poss],
                dates=[dates[p] for p in poss] if dates is not None
                else None)
            for p, o in zip(poss, outs):
                res[p] = o
        return res

    def prepare_reads(self, keys) -> None:
        groups: Dict[str, list] = {}
        for k in keys:
            if k[0] in self._stores:
                groups.setdefault(k[0], []).append(k)
        for ms in sorted(groups):
            prep = getattr(self._stores[ms], "prepare_reads", None)
            if prep is not None:
                prep(groups[ms])

    def snapshot_of(self, key: Key) -> ServingSnapshot:
        """Tier-transparent member read — ROUTING AROUND a rebuilding
        member (DESIGN §24): a read that lands on a LOST shard answers from
        the member's banked last-good instead of failing, so one member's
        fault domain never takes the fleet's read path down."""
        st = self._route(key)
        try:
            return st.snapshot_of(key)
        except ServingError:
            if getattr(st, "rebuilding", False):
                return st.last_good_snapshot_of(key)
            raise

    def last_good_snapshot_of(self, key: Key) -> ServingSnapshot:
        return self._route(key).last_good_snapshot_of(key)

    # ---- shard-loss fault domains across members (DESIGN §24) ---------------

    @property
    def rebuilding(self) -> bool:
        """True while ANY member has a lost shard or a rebuild in flight —
        the gateway pump's pre-batch recovery hook reads this through the
        same duck-typed surface as a single store."""
        return any(getattr(st, "rebuilding", False)
                   for st in self._stores.values())

    def recover_lost_shards(self, redistribute: bool = False) -> dict:
        """Run every member's rebuild wave; returns
        ``{model_string: [rebuilt shard ids]}`` for the members that had
        losses (empty dict when none did)."""
        out = {}
        for ms in sorted(self._stores):
            recover = getattr(self._stores[ms], "recover_lost_shards", None)
            if recover is None:
                continue
            rebuilt = recover(redistribute=redistribute)
            if rebuilt:
                out[ms] = rebuilt
        return out

    def add_rebuild_listener(self, fn) -> None:
        """Fan the blast-radius hook out to every member (the streaming hub
        attaches once and hears every member's rebuild waves)."""
        for ms in sorted(self._stores):
            add = getattr(self._stores[ms], "add_rebuild_listener", None)
            if add is not None:
                add(fn)

    def publish_refit(self, key: Key, params, history=None, beta=None,
                      P=None) -> dict:
        return self._route(key).publish_refit(key, params, history=history,
                                              beta=beta, P=P)

    # ---- observability / warmup --------------------------------------------

    def health(self) -> dict:
        members = {ms: st.health() for ms, st in self._stores.items()}
        if any(h["status"] == "rebuilding" for h in members.values()):
            status = "rebuilding"
        elif any(h["status"] != "ok" for h in members.values()):
            status = "stale"
        else:
            status = "ok"
        return {"status": status, "models": sorted(self._stores),
                "stores": members, "requests": self.counters.to_dict()}

    def latency_summary(self) -> dict:
        return {**self.timer.summary(), "counters": self.counters.to_dict(),
                "stores": {ms: st.latency_summary()
                           for ms, st in self._stores.items()}}

    def warmup(self, horizons=None, batch_sizes=(1,),
               scenario_counts=()) -> int:
        """Warm every member store, then the FLEET batcher (the one the
        gateway reads) with one snapshot per member."""
        n = 0
        for ms in sorted(self._stores):
            st = self._stores[ms]
            n += st.warmup(horizons=horizons, batch_sizes=batch_sizes,
                           scenario_counts=scenario_counts)
            keys = st.keys()
            if keys:
                n += self.batcher.warmup(st.snapshot_of(keys[0]),
                                         horizons=horizons,
                                         batch_sizes=batch_sizes,
                                         scenario_counts=scenario_counts)
        return n
