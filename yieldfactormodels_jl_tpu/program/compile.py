"""Compile a :class:`~.spec.ModelProgram` into an engine-ready spec.

:class:`ProgramSpec` is a synthetic :class:`~..models.specs.ModelSpec`: it
subclasses the hand-ported spec class and overrides exactly the DERIVED
surfaces — the capability properties ``config.engines_for`` and the kernels
read (``is_kalman``/``is_msed``/``has_constant_measurement``/
``supports_score_tree``/``state_dim``) and the flat-parameter compilation
(``layout``/``transform_codes``, built from the program's block table).
Everything downstream — ``api.get_loss`` dispatch, the estimation entry
points (``estimate``/``estimate_steps``/``estimate_windows``), the Newton
cascade, the escalation ladder, serving (refilter/freeze/store slots), the
scenario lattice, ``YFM_AMORT`` eligibility — is property- or layout-driven
and takes the compiled spec UNCHANGED (docs/DESIGN.md §22 has the lowering
table).

The Kalman measurement seams the kernels consult
(``models.kalman.measurement_setup`` for constant-Z,
``models.kalman.state_measurement`` for state-dependent Z,
``models.score_driven.loadings_fn`` for the score-driven kind) each carry a
program branch, so a compiled program flows through the SAME kernels as the
hand-ported families — never a parallel filter implementation that could
drift.
"""

from __future__ import annotations

import dataclasses
from functools import cached_property
from typing import Optional, Tuple

import jax

from ..models.specs import ModelSpec
from ..utils import transformations as tr
from .spec import ModelProgram

#: synthetic family strings — NEVER members of models.specs.ALL_FAMILIES, so
#: every ``spec.family == "kalman_*"`` string check in the kernels is False
#: for a program and dispatch flows through the property seams instead
PROGRAM_KALMAN = "program_kalman"
PROGRAM_MSED = "program_msed"


@jax.tree_util.register_static
@dataclasses.dataclass(frozen=True)
class ProgramSpec(ModelSpec):
    """A compiled model program (hashable/static under jit, like its base).

    ``program`` is the declarative source; the base-class fields are filled
    by :func:`compile_program` so the msed kind can reuse the hand-ported
    layout/transform machinery verbatim."""

    program: Optional[ModelProgram] = None

    def __post_init__(self):
        # replaces (does not extend) the base family validation: the family
        # is synthesized and deliberately outside the closed zoo list
        if self.program is None:
            raise ValueError("ProgramSpec requires a compiled program; use "
                             "program.compile_program(...)")
        if self.family not in (PROGRAM_KALMAN, PROGRAM_MSED):
            raise ValueError(
                f"ProgramSpec family must be {PROGRAM_KALMAN!r} or "
                f"{PROGRAM_MSED!r}, got {self.family!r}")
        if not self.model_string:
            object.__setattr__(self, "model_string", self.model_code)

    # ---- capability properties (the engines_for inputs) ------------------

    @property
    def is_kalman(self) -> bool:
        return self.program.kind == "kalman"

    @property
    def is_msed(self) -> bool:
        return self.program.kind == "msed"

    @property
    def is_static(self) -> bool:
        return False

    @property
    def has_constant_measurement(self) -> bool:
        return self.program.has_constant_measurement

    @property
    def supports_score_tree(self) -> bool:
        return self.program.supports_score_tree

    @property
    def state_dim(self) -> int:
        return self.program.resolved_state_dim if self.is_kalman else self.M

    @property
    def n_lambdas(self) -> int:
        # the decay-driver count is a zoo-family notion; a program's head is
        # its block table — expose the head size so generic consumers that
        # broadcast gamma (kalman.predict) see the right width
        return max(self.program.head_size, 1)

    # ---- flat parameter compilation --------------------------------------

    @cached_property
    def layout(self) -> dict:
        prog = self.program
        if prog.kind == "msed":
            # the msed layout/codes are exactly the hand-ported family's —
            # reuse the base implementation (it branches on is_msed)
            return ModelSpec.layout.func(self)
        pos = 0
        lay: dict = {}

        def put(name, size):
            nonlocal pos
            lay[name] = (pos, pos + size)
            pos += size

        for b in prog.blocks:
            put(b.name, b.size)
        head = pos
        Ms = self.state_dim
        put("obs_var", 1)
        put("chol", Ms * (Ms + 1) // 2)
        put("delta", Ms)
        put("phi", Ms * Ms)
        if head and "gamma" not in lay:
            # the concatenated head IS gamma: what the measurement callables
            # receive and what params.unpack_kalman slices by name
            lay["gamma"] = (0, head)
        lay["__total__"] = (0, pos)
        return lay

    @cached_property
    def transform_codes(self) -> Tuple[int, ...]:
        prog = self.program
        if prog.kind == "msed":
            return ModelSpec.transform_codes.func(self)
        codes: list[int] = []
        for b in prog.blocks:            # the declared head transform table
            codes.extend(b.transforms)
        Ms = self.state_dim              # standard state tail (specs.py)
        codes.append(tr.R_TO_POS)        # observation variance
        for j in range(Ms):              # chol column-by-column, diag > 0
            for i in range(j + 1):
                codes.append(tr.R_TO_POS if i == j else tr.IDENTITY)
        codes.extend([tr.IDENTITY] * Ms)           # delta
        for i in range(Ms):              # Phi row-major, diag in (-1, 1)
            for j in range(Ms):
                codes.append(tr.R_TO_11 if i == j else tr.IDENTITY)
        assert len(codes) == self.n_params
        return tuple(codes)

    # a program has no hand-tuned initialization grids — estimation's
    # multi-start spray / amortized warm start own the starts
    @property
    def A_guesses(self) -> Tuple[float, ...]:
        return ()

    @property
    def B_guesses(self) -> Tuple[float, ...]:
        return ()


def compile_program(
    program: ModelProgram,
    maturities,
    N: Optional[int] = None,
    float_type="float32",
    results_location: str = "results/",
) -> ProgramSpec:
    """Lower a declarative program onto a concrete maturity grid/dtype.

    The compiled spec is what every engine consumes; ``register_program``
    (program/registry.py) additionally publishes the program's name as a
    ``models.registry.create_model`` code so this call happens behind the
    same factory as the zoo models."""
    import numpy as np

    mats = tuple(float(m) for m in maturities)
    if N is not None and N != len(mats):
        raise ValueError(f"N={N} does not match len(maturities)={len(mats)}")
    dtype_name = np.dtype(float_type).name
    if program.kind == "kalman":
        return ProgramSpec(
            family=PROGRAM_KALMAN,
            model_code=program.name,
            maturities=mats,
            M=program.factors,
            L=max(program.head_size, 1),
            dtype_name=dtype_name,
            results_location=results_location,
            program=program,
        )
    return ProgramSpec(
        family=PROGRAM_MSED,
        model_code=program.name,
        maturities=mats,
        M=program.factors,
        L=program.gamma_dim,
        dtype_name=dtype_name,
        duplicator=program.duplicator or tuple(range(program.gamma_dim)),
        random_walk=program.random_walk,
        scale_grad=program.scale_grad,
        forget_factor=program.forget_factor,
        results_location=results_location,
        program=program,
    )
