"""User-defined shock grammars, compiled onto the scenario engine.

The PR-15 program layer (docs/DESIGN.md §22) lets users declare MODELS as
data; this module gives SHOCKS the same treatment (DESIGN §23): a
:class:`ShockRule` names a displacement in grammar terms — "level up 50bp",
"this literal factor vector", "double the vol", "the sum of those two" —
and :func:`compile_shocks` resolves the rules against a concrete
:class:`~..models.specs.ModelSpec` into the frozen
:class:`~..estimation.scenario.ShockSpec` tuples every fan engine
(``scenario.stress_fan``, the fused lattice, the stream hub's delta
refresh) already consumes.  Validation is loud and trace-free: a rule that
names a factor the state doesn't have, or composes an unknown rule, is a
``ValueError`` at compile time — never a silently zero-padded shock.

Rule kinds:

- ``factor``: displace ONE state factor by ``size`` (``factor`` is an index
  or one of the DNS-ordering aliases ``"level"``/``"slope"``/``"curvature"``).
- ``vector``: an explicit per-factor displacement (``vector``, length ≤
  state dim; validated, then zero-padded).
- ``vol``: pure covariance regime — ``vol_scale`` (with optional
  ``sv_phi``/``sv_sigma`` for sampled-path SV, as in ``standard_fan``'s
  vol_regime member).
- ``combo``: the scaled sum of previously declared rules (``of`` =
  ``((name, scale), ...)``); shifts add, vol scales multiply through their
  scale exponents — "taper tantrum twist plus half a parallel shift" as one
  declared scenario.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from ..models.specs import ModelSpec

#: DNS/AFNS factor-ordering aliases (models/specs.py state layout)
_FACTOR_ALIASES = {"level": 0, "slope": 1, "curvature": 2}


@dataclasses.dataclass(frozen=True)
class ShockRule:
    """One declared scenario (frozen + hashable, like
    :class:`~..estimation.scenario.ShockSpec` — rule tuples can key static
    caches).  Fields are kind-specific; :func:`compile_shocks` rejects
    mismatched ones loudly."""

    name: str
    kind: str = "factor"                      # factor | vector | vol | combo
    factor: object = 0                        # index or alias (kind=factor)
    size: float = 0.0                         # displacement (kind=factor)
    vector: Tuple[float, ...] = ()            # displacement (kind=vector)
    vol_scale: float = 1.0
    sv_phi: float = 0.0
    sv_sigma: float = 0.0
    of: Tuple[Tuple[str, float], ...] = ()    # (rule name, scale) (combo)


def _resolve_factor(rule: ShockRule, Ms: int) -> int:
    f = rule.factor
    if isinstance(f, str):
        if f not in _FACTOR_ALIASES:
            raise ValueError(
                f"shock rule {rule.name!r}: unknown factor alias {f!r} — "
                f"use {sorted(_FACTOR_ALIASES)} or an integer index")
        f = _FACTOR_ALIASES[f]
    f = int(f)
    if not 0 <= f < Ms:
        raise ValueError(
            f"shock rule {rule.name!r}: factor {f} out of range for a "
            f"{Ms}-factor state")
    return f


def compile_shocks(rules, spec: ModelSpec):
    """Resolve a tuple of :class:`ShockRule` against ``spec`` into
    :class:`~..estimation.scenario.ShockSpec` tuples (same order).  Combos
    may only reference rules declared EARLIER in the tuple (no cycles by
    construction); duplicate names are rejected."""
    from ..estimation.scenario import ShockSpec

    Ms = spec.state_dim
    compiled = {}
    out = []
    for rule in rules:
        if not isinstance(rule, ShockRule):
            raise ValueError(f"compile_shocks needs ShockRule instances, "
                             f"got {type(rule).__name__}")
        if rule.name in compiled:
            raise ValueError(f"duplicate shock rule name {rule.name!r}")
        shift = np.zeros(Ms)
        vol, phi, sig = float(rule.vol_scale), float(rule.sv_phi), \
            float(rule.sv_sigma)
        if rule.kind == "factor":
            shift[_resolve_factor(rule, Ms)] = float(rule.size)
        elif rule.kind == "vector":
            vec = np.asarray(rule.vector, dtype=np.float64).reshape(-1)
            if vec.shape[0] > Ms:
                raise ValueError(
                    f"shock rule {rule.name!r}: vector has {vec.shape[0]} "
                    f"entries but the state has {Ms} factors")
            shift[:vec.shape[0]] = vec
        elif rule.kind == "vol":
            if vol <= 0.0:
                raise ValueError(f"shock rule {rule.name!r}: vol_scale must "
                                 f"be > 0, got {vol}")
        elif rule.kind == "combo":
            if not rule.of:
                raise ValueError(f"shock rule {rule.name!r}: a combo needs "
                                 f"of=((name, scale), ...)")
            vol = 1.0
            for ref, scale in rule.of:
                if ref not in compiled:
                    raise ValueError(
                        f"shock rule {rule.name!r}: combo references "
                        f"{ref!r}, which is not declared earlier in the "
                        f"tuple (known: {sorted(compiled)})")
                base = compiled[ref]
                shift += float(scale) * np.asarray(
                    tuple(base.beta_shift) + (0.0,) * Ms)[:Ms]
                vol *= float(base.vol_scale) ** float(scale)
                phi = max(phi, float(base.sv_phi))
                sig = max(sig, float(base.sv_sigma))
        else:
            raise ValueError(
                f"shock rule {rule.name!r}: unknown kind {rule.kind!r} — "
                f"use 'factor', 'vector', 'vol' or 'combo'")
        shock = ShockSpec(rule.name,
                          beta_shift=tuple(float(v) for v in shift),
                          vol_scale=vol, sv_phi=phi, sv_sigma=sig)
        compiled[rule.name] = shock
        out.append(shock)
    if not out:
        raise ValueError("compile_shocks: no rules given")
    return tuple(out)
