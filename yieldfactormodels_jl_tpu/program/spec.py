"""Declarative model programs: the user-facing half of the program layer.

A :class:`ModelProgram` is a small declarative description of a state-space
model — user-declared transition/observation callables, a block-structured
parameter-transform table (reusing ``utils/transformations`` codes), and
capability flags derived from WHAT was declared (constant-Z vs
state-dependent-Z vs score-driven) — that ``program/compile.py`` lowers onto
the existing engine matrix (docs/DESIGN.md §22).  The design twin of
arXiv:2505.23302's state-space model programming idea: the model is data,
the inference engines are interchangeable.

Two program kinds cover the filtered families:

- ``kind="kalman"``: linear-Gaussian transition β ← δ + Φβ + η (the shared
  Kalman machinery owns it) with EITHER a constant measurement declared as
  ``loadings(gamma, maturities) -> Z (N, M)`` (+ optional
  ``intercept(gamma, Omega_state, maturities) -> d (N,)``) OR a
  state-dependent measurement declared as ``measurement(beta, maturities)
  -> (Z (N, state_dim), y_pred (N,))`` with Z carrying the Jacobian /
  linearization columns — exactly ``kalman._tvl_measurement``'s contract.
  Constant-Z programs get the FULL engine set including the associative
  scan; state-dependent ones ride the TVλ machinery (sequential EKF trick
  + the iterated-SLR tree, EKF rule).
- ``kind="msed"``: a score-driven observation ``loadings(gamma, maturities)
  -> Z (N, M)`` — the inner score is AD through the user callable
  (``score_driven._score``), so declaring Z is declaring the whole filter.
  ``supports_score_tree`` holds unless the program opts into the EWMA
  ``scale_grad`` lineage (same rule as the hand-ported specs).

Capability flags are PROPERTIES of the declaration, never free-floating
booleans a user could set inconsistently — a program that declares a
``measurement`` callable IS state-dependent, one that declares ``loadings``
IS constant-Z, and ``config.engines_for`` reads the compiled spec's
properties unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

from ..utils import transformations as tr

#: transform codes a block may use (utils/transformations.py — the same
#: integer codes the hand-ported specs compile to)
_VALID_CODES = (tr.IDENTITY, tr.R_TO_POS, tr.R_TO_11, tr.R_TO_01)

PROGRAM_KINDS = ("kalman", "msed")

#: tail block names the compiler appends to a Kalman program's layout —
#: head blocks must not collide with them (models/params.unpack_kalman
#: slices these by name)
RESERVED_BLOCK_NAMES = ("obs_var", "chol", "delta", "phi", "gamma",
                        "__total__")


@dataclasses.dataclass(frozen=True)
class ParamBlock:
    """One named block of the program's HEAD parameters with its per-slot
    bijection codes — the block-structured transform table.  Head blocks sit
    in front of the standard state blocks (obs_var | chol | δ | Φ for the
    Kalman kind) and are what the measurement callables receive,
    concatenated, as ``gamma``."""

    name: str
    size: int
    transforms: Tuple[int, ...]

    def __post_init__(self):
        if not self.name or not self.name.isidentifier():
            raise ValueError(f"block name {self.name!r} must be a Python "
                             f"identifier")
        if self.name in RESERVED_BLOCK_NAMES and self.name != "gamma":
            raise ValueError(
                f"block name {self.name!r} collides with a reserved state "
                f"block ({RESERVED_BLOCK_NAMES}) — pick another name")
        if self.size < 1:
            raise ValueError(f"block {self.name!r}: size must be >= 1, "
                             f"got {self.size}")
        if len(self.transforms) != self.size:
            raise ValueError(
                f"block {self.name!r}: {len(self.transforms)} transform "
                f"code(s) for size {self.size} — one code per slot")
        bad = [c for c in self.transforms if c not in _VALID_CODES]
        if bad:
            raise ValueError(
                f"block {self.name!r}: unknown transform code(s) {bad}; "
                f"pick from utils.transformations "
                f"(IDENTITY/R_TO_POS/R_TO_11/R_TO_01)")


@dataclasses.dataclass(frozen=True)
class ModelProgram:
    """A declarative state-space model (module docstring has the contract).

    Frozen and hashable — the compiled :class:`~.compile.ProgramSpec`
    carries the program as a static field, so it keys the same trace-time
    ``lru_cache``/``@register_engine_cache`` machinery as the hand-ported
    specs (callables hash by identity; declare programs at module level so
    the identity is stable for the life of the process)."""

    name: str
    kind: str                                   # "kalman" | "msed"
    factors: int                                # M (observation factors)
    blocks: Tuple[ParamBlock, ...] = ()         # head transform table
    loadings: Optional[Callable] = None         # (gamma, mats) -> Z (N, M)
    intercept: Optional[Callable] = None        # (gamma, Om, mats) -> d (N,)
    measurement: Optional[Callable] = None      # (beta, mats) -> (Z, y_pred)
    state_dim: Optional[int] = None             # kalman only; default M
    # score-driven (kind="msed") passthrough — same options as the
    # hand-ported MSED specs (models/specs.py)
    gamma_dim: int = 1                          # L
    duplicator: Tuple[int, ...] = ()
    random_walk: bool = False
    scale_grad: bool = False
    forget_factor: float = 0.9
    description: str = ""

    def __post_init__(self):
        if not self.name or not all(
                c.isalnum() or c in "-_." for c in self.name):
            raise ValueError(
                f"program name {self.name!r} must be non-empty and use only "
                f"[A-Za-z0-9._-] (it becomes a registry model code)")
        if self.kind not in PROGRAM_KINDS:
            raise ValueError(f"unknown program kind {self.kind!r}; pick "
                             f"from {PROGRAM_KINDS}")
        if self.factors < 1:
            raise ValueError(f"factors must be >= 1, got {self.factors}")
        names = [b.name for b in self.blocks]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate head block names {names}")
        if self.kind == "kalman":
            if (self.loadings is None) == (self.measurement is None):
                raise ValueError(
                    "a kalman program declares EXACTLY ONE measurement: "
                    "loadings= (constant-Z) or measurement= "
                    "(state-dependent-Z)")
            if self.measurement is not None and self.blocks:
                raise ValueError(
                    "a state-dependent kalman program keeps its measurement "
                    "drivers in the STATE (TVλ-style) — head parameter "
                    "blocks are for constant-Z loadings; drop blocks= or "
                    "declare loadings= instead")
            if self.measurement is not None and self.intercept is not None:
                raise ValueError(
                    "intercept= is part of the constant-Z contract; a "
                    "state-dependent measurement returns y_pred directly")
            sd = self.state_dim if self.state_dim is not None else self.factors
            if sd < self.factors:
                raise ValueError(
                    f"state_dim={sd} < factors={self.factors}: the state "
                    f"must carry at least the observation factors")
        else:  # msed
            if self.loadings is None or self.measurement is not None \
                    or self.intercept is not None:
                raise ValueError(
                    "an msed program declares loadings= only (the score "
                    "recursion is AD through it); measurement=/intercept= "
                    "belong to the kalman kind")
            if self.state_dim is not None:
                raise ValueError("state_dim is a kalman-kind field; msed "
                                 "programs size their state by factors/"
                                 "gamma_dim")
            if self.gamma_dim < 1:
                raise ValueError(f"gamma_dim must be >= 1, "
                                 f"got {self.gamma_dim}")
            if self.duplicator and (len(self.duplicator) != self.gamma_dim
                                    or min(self.duplicator) < 0):
                raise ValueError(
                    f"duplicator must map each of the {self.gamma_dim} "
                    f"γ-states to a 0-based unique index")

    # ---- derived capability flags (the lowering table's inputs) ----------

    @property
    def head_size(self) -> int:
        return sum(b.size for b in self.blocks)

    @property
    def resolved_state_dim(self) -> int:
        return self.state_dim if self.state_dim is not None else self.factors

    @property
    def has_constant_measurement(self) -> bool:
        """Constant-Z kalman program — grants the "assoc" engine and
        everything built on it (the same gate as
        ``ModelSpec.has_constant_measurement``)."""
        return self.kind == "kalman" and self.measurement is None

    @property
    def is_state_dependent(self) -> bool:
        return self.measurement is not None

    @property
    def supports_score_tree(self) -> bool:
        """Score-driven program on the plain-gradient recursion — grants the
        O(log T) score-tree engine (same rule as the hand-ported specs:
        the EWMA ``scale_grad`` lineage keeps the sequential scan)."""
        return self.kind == "msed" and not self.scale_grad
