"""The shipped program library — the two proving declarations.

- ``DNS_PROGRAM`` (code ``"prog-dns"``): the hand-ported ``kalman_dns``
  family re-declared through the program layer.  Its loadings callable IS
  ``models.loadings.dns_loadings`` and its compiled layout/transforms are
  slot-for-slot the family's, so every engine ``config.engines_for`` grants
  is pinned BIT-IDENTICAL (loss + grad + filter moments) to the hand-ported
  path — the correctness anchor of the whole layer
  (tests/test_program.py).
- ``SVENSSON4_PROGRAM`` (code ``"svensson4"``): a genuinely new model the
  zoo lacks — a 4-factor Svensson/second-curvature extension of DNS
  (Svensson 1994): columns [1, slope(λ₁), curv(λ₁), curv(λ₂)].  The decay
  head shows the block transform table doing real work: γ₁ is the usual
  unconstrained DNS driver (λ₁ = floor + exp γ₁ inside the loadings), and
  the second block carries its OWN transform — ``R_TO_POS`` maps the raw
  slot to a strictly positive gap g, with λ₂ = λ₁ + g, so λ₂ > λ₁ is
  enforced by the parameter transform (the classic Svensson identification
  constraint) rather than by a penalty.  Estimated, tree-dispatched,
  served and scenario-fanned end to end against an independent NumPy
  oracle (tests/oracle.py ``svensson_loadings``).

Both are registered at import (``program/__init__.py`` imports this
module), so ``create_model("svensson4", maturities)`` works out of the box
and graftlint tier 2 audits their compiled programs via the auto-generated
manifest cases.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..models.loadings import dns_lambda, dns_loadings, dns_slope_curvature
from ..utils import transformations as tr
from .registry import register_program
from .spec import ModelProgram, ParamBlock


def svensson_loadings(gamma, maturities):
    """(N, 4) Svensson loadings [1, slope(λ₁), curv(λ₁), curv(λ₂)] from the
    constrained head ``gamma = (γ₁, g)``: λ₁ = floor + exp(γ₁) (the DNS
    driver convention, models/loadings.dns_lambda), λ₂ = λ₁ + g with g > 0
    guaranteed by the head block's ``R_TO_POS`` transform.  Oracle twin:
    tests/oracle.py ``svensson_loadings`` (independent NumPy)."""
    lam1 = dns_lambda(gamma[..., 0])
    lam2 = lam1 + gamma[..., 1]
    z2, z3 = dns_slope_curvature(lam1, maturities)
    _, z4 = dns_slope_curvature(lam2, maturities)
    return jnp.stack([jnp.ones_like(z2), z2, z3, z4], axis=-1)


DNS_PROGRAM = ModelProgram(
    name="prog-dns",
    kind="kalman",
    factors=3,
    blocks=(ParamBlock("gamma", 1, (tr.IDENTITY,)),),
    loadings=dns_loadings,
    description="kalman_dns re-declared through the program layer — the "
                "bit-identity proving case",
)

SVENSSON4_PROGRAM = ModelProgram(
    name="svensson4",
    kind="kalman",
    factors=4,
    blocks=(ParamBlock("lambda1", 1, (tr.IDENTITY,)),
            ParamBlock("lambda2_gap", 1, (tr.R_TO_POS,))),
    loadings=svensson_loadings,
    description="4-factor Svensson/second-curvature DNS extension with a "
                "transform-enforced λ₂ > λ₁ gap",
)

register_program(DNS_PROGRAM)
register_program(SVENSSON4_PROGRAM)
