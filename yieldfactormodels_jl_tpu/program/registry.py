"""One-motion program registration (docs/DESIGN.md §22 state machine).

``register_program(prog)`` publishes a declared :class:`~.spec.ModelProgram`
everywhere the framework looks, atomically from the caller's point of view:

1. **models/registry**: the program's name becomes a ``create_model`` code
   (collisions with zoo codes and other programs are rejected up front), so
   drivers, services and scripts build it through the same factory as the
   hand-ported models.
2. **engine dispatch**: nothing to register — ``config.engines_for`` reads
   the compiled spec's capability properties, so the engine grant (assoc
   for constant-Z, slr for state-dependent-Z, score_tree where the flag
   holds) follows from the declaration itself.  Same for the estimation
   entry points, the Newton cascade, the escalation ladder, serving and the
   scenario lattice: all property-/layout-driven.
3. **``YFM_AMORT`` eligibility**: the amortizer registry
   (``estimation.amortize.register_amortizer``) keys on the compiled spec;
   a program spec is a valid key like any other, so training a surrogate
   for it makes the warm start available with no extra wiring.
4. **IR-audit coverage**: an auto-generated manifest ``Case`` per audited
   builder (label ``program:<name>``) so graftlint tier 2
   (``analysis/ir.py``, YFM101–YFM105) lowers and audits the COMPILED
   program like any hand-written case, and the runtime census (YFM011)
   cross-checks registered programs ↔ program-labeled cases in both
   directions.

Registration is process-global and import-time idempotent in spirit:
re-registering the SAME program object under its name is a no-op;
registering a DIFFERENT program under a taken name raises unless
``replace=True`` (tests use replace + ``unregister_program``).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .compile import ProgramSpec, compile_program
from .spec import ModelProgram

#: name → registered program (process-global, like the engine caches)
_PROGRAMS: Dict[str, ModelProgram] = {}

#: the engine-cache builders every registered program is audited through —
#: the estimation loss path and the serving refilter path, the two compiled
#: surfaces a program must keep clean (donation/dtype/host/lane/retrace)
_AUDIT_BUILDERS: Tuple[str, ...] = ("estimation.optimize._jitted_loss",
                                    "serving.online._jitted_refilter")


def registered_programs() -> Tuple[ModelProgram, ...]:
    """The registered programs, name-sorted (the IR census input)."""
    return tuple(_PROGRAMS[k] for k in sorted(_PROGRAMS))


def registered_codes() -> Tuple[str, ...]:
    return tuple(sorted(_PROGRAMS))


def lookup(name: str) -> Optional[ModelProgram]:
    return _PROGRAMS.get(name)


def _case_label(program: ModelProgram) -> str:
    return f"program:{program.name}"


def _register_manifest_cases(program: ModelProgram) -> None:
    """Auto-generate the tier-2 manifest cases for one program.

    Cases attach to EXISTING builder keys (the program flows through the
    same engine-cache builders as the zoo families), so the AST-side YFM011
    key census is untouched; the runtime census in ``analysis/ir.py`` is
    what pins registered programs ↔ program-labeled cases."""
    from ..analysis import manifest as mf

    label = _case_label(program)

    def loss_make(prog=program):
        from ..estimation.optimize import _jitted_loss

        sp = compile_program(prog, mf.MATS, float_type="float64")
        return _jitted_loss(sp, mf.T), [(mf.f64(sp.n_params),
                                         mf.f64(mf.N, mf.T),
                                         mf.i64(), mf.i64())]

    def refilter_make(prog=program):
        from ..serving.online import _jitted_refilter

        sp = compile_program(prog, mf.MATS, float_type="float64")
        return _jitted_refilter(sp, mf.T), [(mf.f64(sp.n_params),
                                            mf.f64(mf.N, mf.T))]

    makes = {"estimation.optimize._jitted_loss": loss_make,
             "serving.online._jitted_refilter": refilter_make}
    for key in _AUDIT_BUILDERS:
        cases = mf.MANIFEST.setdefault(key, [])
        if any(c.label == label for c in cases):
            continue
        cases.append(mf.Case(key, label, makes[key]))


def _drop_manifest_cases(name: str) -> None:
    from ..analysis import manifest as mf

    label = f"program:{name}"
    for key in _AUDIT_BUILDERS:
        cases = mf.MANIFEST.get(key)
        if cases:
            cases[:] = [c for c in cases if c.label != label]


def register_program(program: ModelProgram, replace: bool = False) -> None:
    """Publish ``program`` (module docstring has the four-surface motion)."""
    if not isinstance(program, ModelProgram):
        raise TypeError(f"register_program expects a ModelProgram, "
                        f"got {type(program).__name__}")
    from ..models import registry as model_registry

    if program.name in model_registry._TABLE:
        raise ValueError(
            f"program name {program.name!r} collides with a built-in model "
            f"code — pick another name (models/registry.py owns the zoo)")
    existing = _PROGRAMS.get(program.name)
    if existing is program:
        return  # idempotent re-registration of the same declaration
    if existing is not None and not replace:
        raise ValueError(
            f"program {program.name!r} is already registered; pass "
            f"replace=True to swap it (or unregister_program first)")
    _PROGRAMS[program.name] = program
    _register_manifest_cases(program)


def unregister_program(name: str) -> None:
    """Remove a registered program (tests/tooling; unknown names are a
    no-op so teardown paths stay simple)."""
    if _PROGRAMS.pop(name, None) is not None:
        _drop_manifest_cases(name)


def build_spec(
    name_or_program,
    maturities,
    N: Optional[int] = None,
    float_type="float32",
    results_location: str = "results/",
) -> ProgramSpec:
    """Compile a registered program (by name) or a program object onto a
    maturity grid — the hook ``models.registry.create_model`` calls for
    program codes."""
    if isinstance(name_or_program, ModelProgram):
        program = name_or_program
    else:
        program = _PROGRAMS.get(name_or_program)
        if program is None:
            raise ValueError(
                f"no registered program named {name_or_program!r}; "
                f"registered: {registered_codes()}")
    return compile_program(program, maturities, N=N, float_type=float_type,
                           results_location=results_location)
