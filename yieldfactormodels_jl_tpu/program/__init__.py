"""Declarative model programs (docs/DESIGN.md §22, QUICKSTART §12).

Declare a state-space model as data (:class:`ModelProgram`: measurement
callables + a block-structured parameter-transform table), compile it onto
the engine matrix (:func:`compile_program` → :class:`ProgramSpec`), and
publish it framework-wide in one motion (:func:`register_program`: registry
code, engine dispatch, estimation/serving/scenario surfaces, IR-audit
coverage).  ``library`` ships the proving declarations (``prog-dns``,
``svensson4``), registered at import.
"""

from .compile import ProgramSpec, compile_program
from .registry import (build_spec, lookup, register_program,
                       registered_codes, registered_programs,
                       unregister_program)
from .shocks import ShockRule, compile_shocks
from .spec import ModelProgram, ParamBlock

from . import library  # noqa: E402,F401 — registers the shipped programs

__all__ = [
    "ModelProgram", "ParamBlock", "ProgramSpec", "ShockRule",
    "compile_program", "compile_shocks",
    "register_program", "unregister_program", "registered_programs",
    "registered_codes", "lookup", "build_spec", "library",
]
