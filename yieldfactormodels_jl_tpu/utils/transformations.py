"""Scalar parameter bijections, vectorized for TPU.

Semantics match the reference's per-parameter transform functions
(/root/reference/src/utils/transformations.jl):

- ``R -> pos``:    exp(x)            (inverse log)
- ``R -> (-1,1)``: 2*sigmoid(x) - 1  (== tanh(x/2); inverse log1p(x)-log1p(-x))
- ``R -> (0,1)``:  sigmoid(x)        (inverse logit)

The reference stores a ``Vector{Function}`` per model and applies it
element-wise in a loop (/root/reference/src/models/parameteroperations.jl:22-60).
That is hostile to XLA, so here each model spec carries an integer *code* per
parameter and the whole vector is transformed branchlessly in one shot.  The
"double-where" idiom keeps gradients NaN-free when e.g. ``exp`` would overflow
on a parameter that belongs to a different code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Transform codes (stored per-parameter in ModelSpec.transform_codes).
IDENTITY = 0
R_TO_POS = 1  # exp       — variances, EWMA step sizes A
R_TO_11 = 2   # 2σ(x)-1   — Phi diagonals
R_TO_01 = 3   # σ(x)      — persistence B


def from_R_to_pos(x):
    return jnp.exp(x)


def from_pos_to_R(x):
    return jnp.log(x)


def from_R_to_11(x):
    # 2*exp(x)/(1+exp(x)) - 1 in the reference; tanh(x/2) is the same map,
    # numerically stable on both tails.
    return jnp.tanh(x / 2.0)


def from_11_to_R(x):
    return jnp.log1p(x) - jnp.log1p(-x)


def from_R_to_01(x):
    return jax.nn.sigmoid(x)


def from_01_to_R(x):
    return jnp.log(x) - jnp.log1p(-x)


def _masked(x, mask, fn, neutral):
    """Apply ``fn`` only where ``mask``; double-where so the un-taken branch
    never sees an input that could poison gradients (inf * 0 = NaN)."""
    safe = jnp.where(mask, x, neutral)
    return jnp.where(mask, fn(safe), x)


def apply_transforms(params, codes):
    """unconstrained -> constrained, elementwise by integer code."""
    params = jnp.asarray(params)
    codes = jnp.asarray(codes)
    out = params
    out = _masked(out, codes == R_TO_POS, from_R_to_pos, 0.0)
    out = _masked(out, codes == R_TO_11, from_R_to_11, 0.0)
    out = _masked(out, codes == R_TO_01, from_R_to_01, 0.0)
    return out


def apply_untransforms(params, codes):
    """constrained -> unconstrained, elementwise by integer code."""
    params = jnp.asarray(params)
    codes = jnp.asarray(codes)
    out = params
    out = _masked(out, codes == R_TO_POS, from_pos_to_R, 1.0)
    out = _masked(out, codes == R_TO_11, from_11_to_R, 0.0)
    out = _masked(out, codes == R_TO_01, from_01_to_R, 0.5)
    return out
