"""CSV data loading (parity with /root/reference/src/utils/data_management.jl).

``load_data(folder, thread_id)`` reads ``thread_id__<id>__data.csv`` (N×T panel
of yields) and ``thread_id__<id>__maturities.csv``.
"""

from __future__ import annotations

import os

import numpy as np


def load_data(data_folder: str, thread_id: str):
    data = np.loadtxt(os.path.join(data_folder, f"thread_id__{thread_id}__data.csv"), delimiter=",")
    maturities = np.loadtxt(
        os.path.join(data_folder, f"thread_id__{thread_id}__maturities.csv"), delimiter=","
    ).reshape(-1)
    return data, maturities


def extend_data(data, extension_horizon: int):
    """NaN-pad ``extension_horizon`` columns on the right (data_management.jl:7-14)."""
    data = np.asarray(data)
    pad = np.full((data.shape[0], extension_horizon), np.nan, dtype=data.dtype)
    return np.concatenate([data, pad], axis=1)
