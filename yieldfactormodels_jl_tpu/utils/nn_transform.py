"""Shape transforms that pin learned loading curves to Nelson–Siegel form.

Pure-functional equivalents of the in-place kernels in
/root/reference/src/utils/neural_network_transform.jl:

- ``transform_net_1`` (slope-type curve): 1 at the short end, 0 at the long
  end, squared for positivity.  "Transformed" variant (:6-24) rescales by the
  first/last raw gap first; "anchored" variant (:61-...) just squares.
- ``transform_net_2`` (curvature/hump): 0 at both ends, squared, normalized by
  ``sqrt(sum(r^4))/scale``.  The transformed variant (:27-59) first removes the
  straight line through the endpoint raw values.  Note the reference computes
  the line as ``slope*x - intercept`` (sign quirk, :44) — replicated here for
  behavioural parity.

All variants are branchless index-mask expressions over the full vector so they
vmap/jit cleanly (the reference mutates `dest` in @simd loops).
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-7
_SCALE = 0.9610


def transform_net_1(raw, maturities, transformed: bool):
    """Slope-type loading curve. ``raw``: (..., N) net output, returns (..., N)."""
    n = raw.shape[-1]
    idx = jnp.arange(n)
    interior = (idx >= 1) & (idx <= n - 3)  # reference: 2:n-2 (1-based)
    if transformed:
        raw_first = raw[..., 0:1]
        raw_last = raw[..., n - 2:n - 1]
        t = (raw - raw_last) / (raw_first - raw_last + _EPS)
        sq = t * t
    else:
        sq = raw * raw
    out = jnp.where(interior, sq, raw)
    out = out.at[..., 0].set(1.0)
    out = out.at[..., n - 2].set(0.0)
    out = out.at[..., n - 1].set(0.0)
    return out


def transform_net_2(raw, maturities, transformed: bool, scale: float = _SCALE):
    """Curvature-type loading curve. ``raw``: (..., N), ``maturities``: (N,)."""
    n = raw.shape[-1]
    idx = jnp.arange(n)
    interior = (idx >= 1) & (idx <= n - 2)  # reference: 2:n-1 (1-based)
    if transformed:
        x1 = maturities[0]
        xN = maturities[n - 1]
        raw1 = raw[..., 0:1]
        rawN = raw[..., n - 1:n]
        slope = (rawN - raw1) / (xN - x1)
        intercept = raw1 - slope * x1
        # Reference evaluates the detrend line as slope*x - intercept (:44).
        r = raw - (slope * maturities - intercept)
        r2 = jnp.where(interior, r * r, 0.0)
        sum_sq = jnp.sum(r2 * r2, axis=-1, keepdims=True)
        denom = jnp.sqrt(sum_sq) / scale + _EPS
        return r2 / denom
    else:
        r2 = jnp.where(interior, raw * raw, 0.0)
        sum_sq = jnp.sum(r2 * r2, axis=-1, keepdims=True)
        # Anchored variant: multiplier is scale/sqrt(sum_sq) + eps (:96).
        denom_inv = scale / jnp.sqrt(sum_sq) + _EPS
        return r2 * denom_inv
