"""Out-of-sample forecast evaluation: Diebold–Mariano + Gaussian CRPS.

Companion to the rolling-forecast pipeline (forecasting.py exports per-origin
forecasts; the reference leaves accuracy comparison entirely to external
tooling).  ``diebold_mariano`` tests H₀: equal expected loss between two
forecast-error series, with a Bartlett-kernel HAC variance (h-step forecasts
⇒ MA(h−1) differential autocorrelation) and the Harvey–Leybourne–Newbold
small-sample correction.  ``crps_gaussian`` scores the predictive DENSITIES
``api.forecast_density`` produces (closed form for N(μ, σ²); Gneiting &
Raftery 2007, eq. 21) — proper scoring, lower is better; CRPS series from
two models feed straight back into ``diebold_mariano``.

Pure NumPy — this is post-processing of exported forecasts, not device work.
"""

from __future__ import annotations

import math

import numpy as np


def crps_gaussian(mean, sd, y):
    """Continuous ranked probability score of N(mean, sd²) against outcome
    ``y`` (elementwise over any broadcastable shapes; lower is better):

        CRPS = σ [ z(2Φ(z) − 1) + 2φ(z) − 1/√π ],   z = (y − μ)/σ.

    A proper score for the predictive densities ``api.forecast_density``
    returns; NaNs propagate (missing outcomes score NaN), ``sd <= 0`` is
    invalid and returns NaN rather than a degenerate 0/∞.
    """
    from scipy.special import ndtr  # scipy is already a dependency (t below)

    mean = np.asarray(mean, dtype=np.float64)
    sd = np.asarray(sd, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        z = (y - mean) / sd
        phi = np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        out = sd * (z * (2.0 * ndtr(z) - 1.0) + 2.0 * phi
                    - 1.0 / math.sqrt(math.pi))
    return np.where(sd > 0, out, np.nan)


def diebold_mariano(err1, err2, h: int = 1, loss: str = "squared",
                    harvey_correction: bool = True):
    """DM statistic and two-sided p-value for equal predictive accuracy.

    ``err1``/``err2``: forecast-error series of the two competing models on
    the SAME targets, shape (T,) or (T, N) (multivariate errors are reduced
    to a per-period aggregate loss over the last axis).  ``h`` is the
    forecast horizon (HAC truncation lag = h − 1).  Negative statistic ⇒
    model 1 has the lower loss.

    Returns ``(stat, pvalue)``; NaN when the loss differential is constant
    (zero HAC variance) or fewer than 2 usable periods remain.
    """
    e1 = np.asarray(err1, dtype=np.float64)
    e2 = np.asarray(err2, dtype=np.float64)
    if e1.shape != e2.shape:
        raise ValueError(f"error series shapes differ: {e1.shape} vs {e2.shape}")
    if loss == "squared":
        l1, l2 = e1 ** 2, e2 ** 2
    elif loss == "absolute":
        l1, l2 = np.abs(e1), np.abs(e2)
    else:
        raise ValueError(f"loss must be 'squared' or 'absolute', got {loss!r}")
    if l1.ndim > 1:
        l1 = l1.mean(axis=tuple(range(1, l1.ndim)))
        l2 = l2.mean(axis=tuple(range(1, l2.ndim)))
    d = l1 - l2
    # keep TIME ALIGNMENT through missing periods (failed windows etc.):
    # compacting NaNs out would pair observations k+gap periods apart in the
    # HAC lags below, mis-estimating the MA(h−1) long-run variance
    finite = np.isfinite(d)
    T = int(finite.sum())
    if T < 2:
        return float("nan"), float("nan")
    dbar = d[finite].mean()
    dc = np.where(finite, d - dbar, 0.0)
    # Bartlett/Newey–West long-run variance with h−1 lags; lag-k products are
    # counted only where BOTH endpoints are observed
    lrv = float(dc @ dc) / T
    for k in range(1, min(h, d.shape[0])):
        w = 1.0 - k / h
        lrv += 2.0 * w * float(dc[k:] @ dc[:-k]) / T
    if lrv <= 0:
        return float("nan"), float("nan")
    stat = dbar / math.sqrt(lrv / T)
    if harvey_correction:
        # Harvey–Leybourne–Newbold (1997): small-sample scaling paired with
        # Student-t(T−1) critical values, not the normal.  Applied at every
        # h — at h=1 the factor (T−1)/T and the t(T−1) reference still differ
        # from the plain normal test (ADVICE r2).
        c = (T + 1 - 2 * h + h * (h - 1) / T) / T
        if c <= 0:
            return float("nan"), float("nan")
        stat *= math.sqrt(c)
        from scipy.stats import t as _t

        p = 2.0 * float(_t.sf(abs(stat), df=T - 1))
    else:
        p = math.erfc(abs(stat) / math.sqrt(2.0))
    return float(stat), float(p)
