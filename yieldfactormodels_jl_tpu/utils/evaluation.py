"""Out-of-sample forecast evaluation: Diebold–Mariano, CRPS, log scores.

Companion to the rolling-forecast pipeline (forecasting.py exports per-origin
forecasts; the reference leaves accuracy comparison entirely to external
tooling).  ``diebold_mariano`` tests H₀: equal expected loss between two
forecast-error series, with a Bartlett-kernel HAC variance (h-step forecasts
⇒ MA(h−1) differential autocorrelation) and the Harvey–Leybourne–Newbold
small-sample correction.  ``crps_gaussian`` scores the predictive DENSITIES
``api.forecast_density`` produces (closed form for N(μ, σ²); Gneiting &
Raftery 2007, eq. 21) — proper scoring, lower is better; CRPS series from
two models feed straight back into ``diebold_mariano``.

Scenario-lattice scoring (docs/DESIGN.md §14): ``log_predictive_score`` is
the joint multivariate Gaussian log predictive density of an outcome curve
under the lattice/fan ``(means, covs)`` output — the metric the treasury
VAR density-forecasting literature reports (arXiv:2108.06553's log
predictive likelihoods), higher is better, so the fused fan can be scored
head-to-head against external frequentist/Bayesian VAR baselines;
``crps_sample`` is the ensemble (empirical) CRPS for SAMPLED scenario paths
— the score for the fan's ``paths`` face, where SV regimes make the
predictive non-Gaussian and the closed form does not apply.

Pure NumPy — this is post-processing of exported forecasts, not device work.
"""

from __future__ import annotations

import math

import numpy as np


def crps_gaussian(mean, sd, y):
    """Continuous ranked probability score of N(mean, sd²) against outcome
    ``y`` (elementwise over any broadcastable shapes; lower is better):

        CRPS = σ [ z(2Φ(z) − 1) + 2φ(z) − 1/√π ],   z = (y − μ)/σ.

    A proper score for the predictive densities ``api.forecast_density``
    returns; NaNs propagate (missing outcomes score NaN), ``sd <= 0`` is
    invalid and returns NaN rather than a degenerate 0/∞.
    """
    from scipy.special import ndtr  # scipy is already a dependency (t below)

    mean = np.asarray(mean, dtype=np.float64)
    sd = np.asarray(sd, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        z = (y - mean) / sd
        phi = np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        out = sd * (z * (2.0 * ndtr(z) - 1.0) + 2.0 * phi
                    - 1.0 / math.sqrt(math.pi))
    return np.where(sd > 0, out, np.nan)


def log_predictive_score(means, covs, y):
    """Joint Gaussian log predictive density log N(y; μ, Σ) — HIGHER is
    better (the log predictive likelihood of the VAR density-forecasting
    literature, arXiv:2108.06553).

    ``means`` (..., N), ``covs`` (..., N, N), ``y`` broadcastable to
    (..., N); returns (...) scores.  Scores the scenario lattice / stress
    fan's analytic density face against realized curves: e.g. fan ``means``
    (S, h, N) + ``covs`` (S, h, N, N) against a realized (h, N) future gives
    an (S, h) score table.  A non-PSD or non-finite covariance (or a
    non-finite outcome/mean entry) scores NaN — degradation stays visible,
    never raises.
    """
    means = np.asarray(means, dtype=np.float64)
    covs = np.asarray(covs, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    N = means.shape[-1]
    v = y - means                                          # (..., N) broadcast
    covs = np.broadcast_to(covs, v.shape + (N,))
    flat_v = v.reshape(-1, N)
    flat_c = covs.reshape(-1, N, N)
    out = np.full(flat_v.shape[0], np.nan)
    for i in range(flat_v.shape[0]):
        vi, ci = flat_v[i], flat_c[i]
        if not (np.all(np.isfinite(vi)) and np.all(np.isfinite(ci))):
            continue
        try:
            L = np.linalg.cholesky(0.5 * (ci + ci.T))
        except np.linalg.LinAlgError:
            continue  # non-PSD → NaN score
        z = np.linalg.solve(L, vi)
        logdet = 2.0 * np.sum(np.log(np.diag(L)))
        out[i] = -0.5 * (N * math.log(2.0 * math.pi) + logdet + z @ z)
    return out.reshape(v.shape[:-1])


def crps_sample(samples, y, axis=-1):
    """Ensemble CRPS from sampled scenario draws — lower is better:

        CRPS = (1/m) Σᵢ |xᵢ − y|  −  (1/2m²) Σᵢⱼ |xᵢ − xⱼ|

    (the fair empirical form of Gneiting & Raftery 2007, eq. 20 — exact for
    the empirical predictive CDF, no distributional assumption, which is the
    point for SV-regime fans whose paths are non-Gaussian).  ``samples``
    carries the draw axis at ``axis`` (default last — the lane-dim draws
    axis of ``scenarios``/fan ``paths``); ``y`` broadcastable to the
    remaining shape.  NaNs in any draw of an element propagate to that
    element's score.
    """
    x = np.asarray(samples, dtype=np.float64)
    x = np.moveaxis(x, axis, -1)                           # (..., m)
    y = np.broadcast_to(np.asarray(y, dtype=np.float64), x.shape[:-1])
    m = x.shape[-1]
    term1 = np.mean(np.abs(x - y[..., None]), axis=-1)
    # pairwise |xᵢ − xⱼ| via sorted-spacings identity: Σᵢⱼ|xᵢ−xⱼ| =
    # 2 Σₖ (2k − m + 1) x₍ₖ₎ (O(m log m), no (..., m, m) broadcast)
    xs = np.sort(x, axis=-1)
    k = np.arange(m, dtype=np.float64)
    term2 = np.sum((2.0 * k - m + 1.0) * xs, axis=-1) / (m * m)
    return term1 - term2


def diebold_mariano(err1, err2, h: int = 1, loss: str = "squared",
                    harvey_correction: bool = True):
    """DM statistic and two-sided p-value for equal predictive accuracy.

    ``err1``/``err2``: forecast-error series of the two competing models on
    the SAME targets, shape (T,) or (T, N) (multivariate errors are reduced
    to a per-period aggregate loss over the last axis).  ``h`` is the
    forecast horizon (HAC truncation lag = h − 1).  Negative statistic ⇒
    model 1 has the lower loss.

    Returns ``(stat, pvalue)``; NaN when the loss differential is constant
    (zero HAC variance) or fewer than 2 usable periods remain.
    """
    e1 = np.asarray(err1, dtype=np.float64)
    e2 = np.asarray(err2, dtype=np.float64)
    if e1.shape != e2.shape:
        raise ValueError(f"error series shapes differ: {e1.shape} vs {e2.shape}")
    if loss == "squared":
        l1, l2 = e1 ** 2, e2 ** 2
    elif loss == "absolute":
        l1, l2 = np.abs(e1), np.abs(e2)
    else:
        raise ValueError(f"loss must be 'squared' or 'absolute', got {loss!r}")
    if l1.ndim > 1:
        l1 = l1.mean(axis=tuple(range(1, l1.ndim)))
        l2 = l2.mean(axis=tuple(range(1, l2.ndim)))
    d = l1 - l2
    # keep TIME ALIGNMENT through missing periods (failed windows etc.):
    # compacting NaNs out would pair observations k+gap periods apart in the
    # HAC lags below, mis-estimating the MA(h−1) long-run variance
    finite = np.isfinite(d)
    T = int(finite.sum())
    if T < 2:
        return float("nan"), float("nan")
    dbar = d[finite].mean()
    dc = np.where(finite, d - dbar, 0.0)
    # Bartlett/Newey–West long-run variance with h−1 lags; lag-k products are
    # counted only where BOTH endpoints are observed
    lrv = float(dc @ dc) / T
    for k in range(1, min(h, d.shape[0])):
        w = 1.0 - k / h
        lrv += 2.0 * w * float(dc[k:] @ dc[:-k]) / T
    if lrv <= 0:
        return float("nan"), float("nan")
    stat = dbar / math.sqrt(lrv / T)
    if harvey_correction:
        # Harvey–Leybourne–Newbold (1997): small-sample scaling paired with
        # Student-t(T−1) critical values, not the normal.  Applied at every
        # h — at h=1 the factor (T−1)/T and the t(T−1) reference still differ
        # from the plain normal test (ADVICE r2).
        c = (T + 1 - 2 * h + h * (h - 1) / T) / T
        if c <= 0:
            return float("nan"), float("nan")
        stat *= math.sqrt(c)
        from scipy.stats import t as _t

        p = 2.0 * float(_t.sf(abs(stat), df=T - 1))
    else:
        p = math.erfc(abs(stat) / math.sqrt(2.0))
    return float(stat), float(p)
