"""Profiling / stage-timing utilities (SURVEY.md §5.1).

The reference's only timing signal is ``@elapsed`` around per-window
re-estimation with a printed running mean (forecasting.jl:144-149,188-192).
Here that becomes a reusable stage timer plus an optional wrapper over
``jax.profiler`` for real device traces (viewable in TensorBoard/Perfetto).
"""

from __future__ import annotations

import contextlib
import json
import math
import time
from collections import defaultdict, deque
from typing import Dict, Iterator, List, Optional


def _nearest_rank(sorted_samples: List[float], q: float) -> float:
    """Nearest-rank percentile (no interpolation): the ⌈q·n⌉-th smallest.
    Deterministic and dependency-free — the BENCH ledger's p50/p99
    convention for serving latency."""
    n = len(sorted_samples)
    return sorted_samples[min(n - 1, max(0, math.ceil(q * n) - 1))]


class StageTimer:
    """Accumulates wall-clock per named stage; prints reference-style running
    means.  Thread-compatible with the forecasting loop's usage pattern.
    Durations are also kept in ``samples`` (a bounded sliding window of the
    most recent ``max_samples`` per stage) so ``summary()`` can report
    latency percentiles (p50/p99) for the BENCH ledger, not just means —
    bounded because the serving layer records one sample per request in a
    long-lived process; ``totals``/``counts``/``mean`` stay exact over the
    full history."""

    def __init__(self, max_samples: int = 65536):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.maxima: Dict[str, float] = defaultdict(float)
        self.samples: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=max_samples))

    def record(self, name: str, seconds: float) -> None:
        """Record one duration directly (what ``stage`` does on exit)."""
        self.totals[name] += seconds
        self.counts[name] += 1
        self.maxima[name] = max(self.maxima[name], seconds)
        self.samples[name].append(seconds)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def mean(self, name: str) -> float:
        c = self.counts[name]
        return self.totals[name] / c if c else 0.0

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-stage dict: count / total / mean / p50 / p99 / max (seconds;
        nearest-rank percentiles over the retained sample window; count /
        total / mean / max over the FULL history — a worst-case spike must
        not age out of the ledger)."""
        out: Dict[str, Dict[str, float]] = {}
        for name in sorted(self.totals):
            s = sorted(self.samples[name])
            out[name] = {
                "count": self.counts[name],
                "total": self.totals[name],
                "mean": self.mean(name),
                "p50": _nearest_rank(s, 0.50) if s else 0.0,
                "p99": _nearest_rank(s, 0.99) if s else 0.0,
                "max": self.maxima[name],
            }
        return out

    def to_json(self, **extra) -> str:
        """``summary()`` as one JSON line (ledger-ready); ``extra`` keys are
        merged at the top level (e.g. config labels)."""
        return json.dumps({**extra, "stages": self.summary()}, sort_keys=True)

    def report(self) -> str:
        lines = [f"{name}: {self.totals[name]:.3f}s total, "
                 f"{self.mean(name):.3f}s avg over {self.counts[name]}"
                 for name in sorted(self.totals)]
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(logdir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` scope when ``logdir`` is given, no-op otherwise —
    so call sites can thread a flag through without branching."""
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region in a device trace (``jax.profiler.TraceAnnotation``)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
