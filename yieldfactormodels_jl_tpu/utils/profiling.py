"""Profiling / stage-timing utilities (SURVEY.md §5.1).

The reference's only timing signal is ``@elapsed`` around per-window
re-estimation with a printed running mean (forecasting.jl:144-149,188-192).
Here that becomes a reusable stage timer plus an optional wrapper over
``jax.profiler`` for real device traces (viewable in TensorBoard/Perfetto).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional


class StageTimer:
    """Accumulates wall-clock per named stage; prints reference-style running
    means.  Thread-compatible with the forecasting loop's usage pattern."""

    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def mean(self, name: str) -> float:
        c = self.counts[name]
        return self.totals[name] / c if c else 0.0

    def report(self) -> str:
        lines = [f"{name}: {self.totals[name]:.3f}s total, "
                 f"{self.mean(name):.3f}s avg over {self.counts[name]}"
                 for name in sorted(self.totals)]
        return "\n".join(lines)


@contextlib.contextmanager
def device_trace(logdir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` scope when ``logdir`` is given, no-op otherwise —
    so call sites can thread a flag through without branching."""
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region in a device trace (``jax.profiler.TraceAnnotation``)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
