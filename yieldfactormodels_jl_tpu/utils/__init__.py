from . import transformations, nn_transform, data_management

__all__ = ["transformations", "nn_transform", "data_management"]
