from . import transformations, nn_transform, data_management, evaluation

__all__ = ["transformations", "nn_transform", "data_management", "evaluation"]
