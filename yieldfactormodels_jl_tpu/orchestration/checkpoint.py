"""Per-window multi-start estimation checkpoints (preemption resume).

A rolling-window task's expensive part is the block-coordinate multi-start
cascade (``estimation/optimize.estimate_steps``).  The reference's crash-only
protocol loses ALL of that progress on a worker death — the shard file is
only written at the very end.  ``WindowCheckpoint`` persists the cascade's
full lockstep state (start batch, per-start LLs, convergence flags) after
every group iteration, atomically (tmp + ``os.replace``), so a successor
worker resumes the remaining iterations bit-for-bit instead of refitting
from scratch: the saved arrays keep their native dtype, and each iteration
is a deterministic function of the restored state, so an interrupted +
resumed run produces byte-identical results to an uninterrupted one
(pinned by tests/test_orchestration.py).

A checkpoint is only trusted when its *signature* (model string, data length,
window bounds, grouping, start-batch shape) matches the live call — a stale
or foreign file is ignored, never half-applied.  The driver clears the
checkpoint after the task's shard is durably written; a crash in between
just replays the (cheap) final iterations from the last saved state.
"""

from __future__ import annotations

import os
import uuid
from typing import Dict, Optional, Tuple

import numpy as np

#: Process-wide ledger of group iterations actually *executed* per
#: (window_type, task_id) — recovery tests assert a resumed run skips
#: already-completed multi-start work via these recorded call counts.
ITERS_EXECUTED: Dict[Tuple[str, int], int] = {}

_FORMAT_VERSION = 1


class WindowCheckpoint:
    """Atomic npz-backed checkpoint for one (window_type, task_id) cascade."""

    def __init__(self, root: str, window_type: str, task_id: int):
        self.window_type = window_type
        self.task_id = int(task_id)
        self.path = os.path.join(root, window_type,
                                 f"task_{int(task_id)}.ckpt.npz")
        #: group iterations run by THIS process (excludes resumed ones)
        self.executed_iters = 0
        #: group iterations skipped thanks to a predecessor's checkpoint
        self.resumed_iters = 0

    # -- signature ----------------------------------------------------------

    @staticmethod
    def _sig_arrays(signature: dict) -> dict:
        return {f"sig_{k}": np.asarray(str(v))
                for k, v in dict(signature, _v=_FORMAT_VERSION).items()}

    def load(self, signature: dict) -> Optional[dict]:
        """Return the saved state dict, or None if absent/stale/corrupt."""
        if not os.path.isfile(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                blob = {k: z[k] for k in z.files}
        except Exception:  # truncated/corrupt file: refit, don't crash
            return None
        want = self._sig_arrays(signature)
        if set(k for k in blob if k.startswith("sig_")) != set(want):
            return None
        if any(str(blob[k]) != str(v) for k, v in want.items()):
            return None
        state = {k: blob[k] for k in blob if not k.startswith("sig_")}
        self.resumed_iters = int(state["next_it"])
        return state

    def save(self, signature: dict, state: dict) -> None:
        """Atomic write: a reader sees the old state or the new, never a
        torn file — writer-unique tmp + ``os.replace``, the same discipline
        as the shard DBs (a stalled worker whose lease was stolen and the
        thief may both checkpoint this window; a shared tmp name would let
        them interleave in one inode)."""
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = f"{self.path}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as fh:
            np.savez(fh, **self._sig_arrays(signature),
                     **{k: np.asarray(v) for k, v in state.items()})
        os.replace(tmp, self.path)

    def clear(self) -> None:
        """Remove the checkpoint (task durably finished)."""
        try:
            os.remove(self.path)
        except OSError:
            pass

    # -- call-count ledger --------------------------------------------------

    def record_executed(self) -> None:
        self.executed_iters += 1
        key = (self.window_type, self.task_id)
        ITERS_EXECUTED[key] = ITERS_EXECUTED.get(key, 0) + 1
