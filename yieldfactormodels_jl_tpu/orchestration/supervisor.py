"""Worker loop driving rolling-forecast tasks off the leased queue.

One task = one (window_type, task_id) origin of ``run_rolling_forecasts``;
each window type additionally gets one ``merge:<wt>`` task gated on every
shard existing.  The loop per claim:

    claim → heartbeat thread → estimate (checkpointed) → shard write
          → complete

with failures routed through ``retry``: ordinary exceptions and sentinel
losses (−Inf at the driver boundary) send the task back to pending with
exponential backoff, and after ``RetryPolicy.max_attempts`` the task is
quarantined with its failure cause on record.  A :class:`chaos.ChaosInjected`
is handled as a simulated worker DEATH — the worker stops heartbeating and
exits without touching the queue, so the lease expires by TTL and a
surviving/restarted worker steals it and resumes from the window checkpoint
(the crash-recovery contract pinned by tests/test_orchestration.py).

``run_orchestrated`` runs N workers as in-process threads (tests, the
``BENCH_ORCH=1`` bench, single-host fills); on a real fleet each host just
calls ``run_worker`` against the shared queue path.  ``status()`` renders
the queue journal into a progress/straggler report without touching any
worker.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import List, NamedTuple, Optional, Sequence

import numpy as np

import contextlib

from . import chaos
from .checkpoint import WindowCheckpoint  # noqa: F401  (re-export for callers)
from .queue import Lease, LeaseLost, TaskQueue, default_lease_ttl
from .retry import RetryPolicy, backoff_delay, should_quarantine


def _ignore_lease_lost():
    """A stolen lease makes the loser's queue transition moot (idempotent
    effects; the thief drives the task now)."""
    return contextlib.suppress(LeaseLost)


class WorkerStats(NamedTuple):
    worker_id: str
    completed: int
    failed: int
    stolen: int          # claims that took over an expired lease
    died: bool           # exited via an injected (or real) preemption signal
    merged: List[str]    # window types whose merge+export this worker ran


def default_queue_path(spec) -> str:
    return os.path.join(spec.results_location, "db", "queue.sqlite3")


def _window_types(window_type: str) -> List[str]:
    if window_type == "both":
        return ["expanding", "moving"]
    if window_type in ("expanding", "moving"):
        return [window_type]
    raise ValueError(f"orchestrated runs support expanding/moving/both, "
                     f"not {window_type!r}")


def task_keys(window_type: str, in_sample_end: int, T: int) -> List[str]:
    """Deterministic task enumeration: every origin of every window type,
    then one merge barrier per window type."""
    keys = []
    for wt in _window_types(window_type):
        keys += [f"{wt}:{tid}" for tid in range(in_sample_end, T + 1)]
    keys += [f"merge:{wt}" for wt in _window_types(window_type)]
    return keys


class _Heartbeat(threading.Thread):
    """Extends the lease every ``interval`` until stopped; a lost lease
    (stolen after a stall) just stops the beat — the queue's token guard
    rejects the loser's terminal write later."""

    def __init__(self, q: TaskQueue, lease: Lease, ttl: float, interval: float):
        super().__init__(daemon=True)
        self.q, self.lease, self.ttl = q, lease, ttl
        self.interval = interval
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(self.interval):
            if not self.q.heartbeat(self.lease, self.ttl):
                return

    def stop(self):
        self._stop.set()


class _MergeNotReady(RuntimeError):
    """Merge claimed before all sibling shards exist — release, no attempt."""


def run_worker(
    spec, data, thread_id: str, in_sample_end: int, in_sample_start: int,
    forecast_horizon: int, init_params, *,
    window_type: str = "expanding",
    worker_id: Optional[str] = None,
    queue_path: Optional[str] = None,
    lease_ttl: Optional[float] = None,
    heartbeat_interval: Optional[float] = None,
    poll_interval: float = 0.2,
    retry: RetryPolicy = RetryPolicy(),
    param_groups: Sequence[str] = (),
    max_group_iters: int = 10,
    group_tol: float = 1e-8,
    reestimate: bool = True,
    checkpoint_root: Optional[str] = None,
    wait_for_drain: bool = True,
    max_tasks: Optional[int] = None,
) -> WorkerStats:
    """Run one worker against the (shared) queue until the run is terminal.

    Safe to call from any number of processes/threads with the same
    arguments: enqueue is idempotent, claims are exclusive, effects are
    idempotent shards.  Returns this worker's :class:`WorkerStats`.
    """
    from .. import forecasting as fc

    data = np.asarray(data, dtype=np.float64)
    T = data.shape[1]
    wid = worker_id or f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
    ttl = default_lease_ttl() if lease_ttl is None else float(lease_ttl)
    hb_every = heartbeat_interval if heartbeat_interval is not None else ttl / 3.0
    ckroot = checkpoint_root or fc.default_checkpoint_root(spec)

    all_params = np.asarray(init_params, dtype=np.float64)
    if all_params.ndim == 1:
        all_params = all_params[:, None]

    q = TaskQueue(queue_path or default_queue_path(spec),
                  fallback_lockroot=os.path.join(fc._lockroot(spec), "queue"))
    keys = task_keys(window_type, in_sample_end, T)
    q.enqueue(keys)
    window_tasks = {wt: list(range(in_sample_end, T + 1))
                    for wt in _window_types(window_type)}

    def execute(key: str) -> None:
        kind, _, rest = key.partition(":")
        if kind == "merge":
            wt = rest
            from ..persistence import database as pdb

            if os.path.isfile(fc._merged_path(spec, wt)):
                # a predecessor already merged; re-run only the (idempotent,
                # merged-DB-sourced) CSV export, in case it died in between
                pdb.export_all_csv(spec, thread_id, window_tasks[wt],
                                   window_type=wt)
                return
            base = fc._forecast_db_base(spec, wt)
            # barrier = queue state, not shard-file existence: every sibling
            # window task must be terminal before folding (a leased task may
            # still be (re)writing its shard)
            st = q.statuses([f"{wt}:{t}" for t in window_tasks[wt]])
            open_tasks = [k for k, s in st.items()
                          if s not in ("done", "quarantined")]
            if open_tasks:
                raise _MergeNotReady(f"{len(open_tasks)} window tasks "
                                     f"not terminal")
            missing = [t for t in window_tasks[wt]
                       if not os.path.isfile(pdb.forecast_path(base, t))]
            if missing:
                if all(st[f"{wt}:{t}"] == "quarantined" for t in missing):
                    raise RuntimeError(
                        f"cannot merge {wt}: {len(missing)} window tasks "
                        f"quarantined ({sorted(missing)[:8]}...)")
                raise _MergeNotReady(f"{len(missing)} shards outstanding")
            fc.merge_and_export(spec, thread_id, window_tasks[wt], wt)
            stats["merged"].append(wt)
            return
        wt, tid = kind, int(rest)
        from ..persistence import database as pdb

        base = fc._forecast_db_base(spec, wt)
        if os.path.isfile(fc._merged_path(spec, wt)) or \
                os.path.isfile(pdb.forecast_path(base, tid)):
            return  # idempotent: effect already durable
        fc.run_single_window_task(
            spec, data, thread_id, tid, wt, in_sample_end, in_sample_start,
            forecast_horizon, all_params, param_groups=param_groups,
            max_group_iters=max_group_iters, group_tol=group_tol,
            reestimate=reestimate, checkpoint_root=ckroot,
            sentinel_policy="retry")

    stats = dict(completed=0, failed=0, stolen=0, died=False, merged=[])
    while True:
        if max_tasks is not None and stats["completed"] >= max_tasks:
            break
        lease = q.claim(wid, ttl)
        if lease is None:
            if q.all_terminal() or not wait_for_drain:
                break
            time.sleep(poll_interval)  # someone else holds live leases
            continue
        if lease.attempts > 1:
            stats["stolen"] += 1  # expired-lease takeover or post-fail retry
        hb = _Heartbeat(q, lease, ttl, hb_every)
        hb.start()
        try:
            execute(lease.key)
        except chaos.ChaosInjected:
            # simulated preemption: stop beating, abandon the lease AS-IS —
            # recovery must come from TTL expiry + steal, like a real death
            hb.stop()
            stats["died"] = True
            break
        except _MergeNotReady:
            hb.stop()
            with _ignore_lease_lost():
                q.release(lease, retry_in=poll_interval)
            time.sleep(poll_interval)
        except Exception as e:  # noqa: BLE001  — every failure is recorded
            hb.stop()
            stats["failed"] += 1
            err = f"{type(e).__name__}: {e}"
            with _ignore_lease_lost():
                if should_quarantine(retry, lease.attempts):
                    q.fail(lease, err, quarantine=True)
                else:
                    q.fail(lease, err,
                           retry_in=backoff_delay(retry, lease.attempts))
        else:
            hb.stop()
            try:
                q.complete(lease)
                stats["completed"] += 1
            except LeaseLost:
                # stalled past our TTL and got stolen mid-task: the effect
                # (shard) is idempotent and durable, the thief owns the
                # queue transition now — a benign lost race, not a failure
                pass
    return WorkerStats(wid, stats["completed"], stats["failed"],
                       stats["stolen"], stats["died"], stats["merged"])


def run_orchestrated(spec, data, thread_id: str, in_sample_end: int,
                     in_sample_start: int, forecast_horizon: int, init_params,
                     *, n_workers: int = 2, **worker_kw) -> List[WorkerStats]:
    """N in-process workers (threads) against one queue; returns their stats.

    In-process threads share the jit caches, so this is also the cheapest
    way to fill a single host; cross-host fleets run one ``run_worker`` per
    process against the same ``queue_path`` on the shared filesystem.
    """
    out: List[Optional[WorkerStats]] = [None] * n_workers
    errs: List[BaseException] = []
    wid_prefix = worker_kw.pop("worker_id", None) or "w"

    def go(i: int) -> None:
        try:
            out[i] = run_worker(spec, data, thread_id, in_sample_end,
                                in_sample_start, forecast_horizon, init_params,
                                worker_id=f"{wid_prefix}{i}", **worker_kw)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=go, args=(i,), daemon=True)
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]
    return [s for s in out if s is not None]


def status(queue_path: str, straggler_after: Optional[float] = None) -> dict:
    """Progress/straggler report from the queue journal alone (read-only).

    ``stragglers``: leased tasks first claimed more than ``straggler_after``
    seconds ago (default 3× their lease TTL) — live-but-slow workers, or
    tasks cycling through steals."""
    if not os.path.isfile(queue_path):
        # read-only means read-only: connecting through TaskQueue would
        # CREATE an empty journal at a mistyped path and report 0/0 progress
        raise FileNotFoundError(f"no queue journal at {queue_path!r}")
    q = TaskQueue(queue_path)
    now = time.time()
    snap = q.snapshot()
    counts = q.counts()
    running, stragglers, quarantined = [], [], []
    for r in snap:
        if r["status"] == "leased":
            age = now - (r["first_leased"] or now)
            entry = dict(task=r["task_key"], owner=r["owner"],
                         age_s=round(age, 3), attempts=r["attempts"],
                         lease_remaining_s=round(
                             (r["lease_expires"] or now) - now, 3))
            running.append(entry)
            limit = straggler_after if straggler_after is not None \
                else 3.0 * (r["lease_ttl"] or default_lease_ttl())
            if age > limit:
                stragglers.append(entry)
        elif r["status"] == "quarantined":
            quarantined.append(dict(task=r["task_key"],
                                    attempts=r["attempts"],
                                    error=r["last_error"]))
    total = max(1, len(snap))
    return dict(counts=counts, total=len(snap),
                progress=counts.get("done", 0) / total,
                running=running, stragglers=stragglers,
                quarantined=quarantined, degraded=q.degraded)


def format_status(queue_path: str, **kw) -> str:
    """One human line per concern — the ``status()`` dict, rendered."""
    s = status(queue_path, **kw)
    c = s["counts"]
    lines = [f"progress {100 * s['progress']:.1f}%  "
             f"(done {c['done']}/{s['total']}, pending {c['pending']}, "
             f"leased {c['leased']}, quarantined {c['quarantined']})"
             + ("  [DEGRADED: mkdir fallback]" if s["degraded"] else "")]
    for r in s["running"]:
        tag = "STRAGGLER " if r in s["stragglers"] else ""
        lines.append(f"  {tag}{r['task']} @{r['owner']} "
                     f"age {r['age_s']:.1f}s attempts {r['attempts']}")
    for r in s["quarantined"]:
        lines.append(f"  QUARANTINED {r['task']} after {r['attempts']} "
                     f"attempts: {r['error']}")
    return "\n".join(lines)
