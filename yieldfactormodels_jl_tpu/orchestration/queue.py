"""SQLite-journaled task queue with heartbeat leases and TTL lease steal.

The reference's coordination layer (atomic ``mkdir`` locks + idempotent
shards) cannot distinguish "worker is computing" from "worker is dead" — a
SIGKILLed worker's lock starves its task forever (SURVEY §5.3).  This queue
makes liveness explicit: a claim takes a *lease* with a TTL, the worker
heartbeats it while computing, and any worker may atomically steal a lease
whose TTL expired.  Work state is journaled in one SQLite file (WAL,
``busy_timeout``, IMMEDIATE transactions — the same discipline as the shard
DBs in ``persistence/database.py``), so ``status()`` reports and retry /
quarantine bookkeeping survive every process involved dying.

Lease integrity: each claim issues a random token; ``heartbeat`` /
``complete`` / ``fail`` are conditional updates on (owner, token), so a
stolen worker's late writes are rejected instead of corrupting the new
owner's lease.  Task *effects* (shard files) are idempotent regardless —
the token guard protects queue state, the artifact contract protects data.

Degraded mode: when the journal DB is unreachable (``sqlite3.Error`` on
connect — e.g. the shared filesystem dropped), the queue falls back to the
reference's mkdir-lock protocol under ``fallback_lockroot``: claims are
``mkdir``, heartbeats are ``utime`` on the lock dir, TTL steal is
``break_stale_lock`` (persistence/locks.py).  Completion tracking is
process-local in that mode; cross-process dedup degrades to the shard
existence checks, exactly the reference's semantics.

``YFM_LEASE_TTL`` sets the default lease TTL in seconds (default 60).
"""

from __future__ import annotations

import os
import re
import secrets
import sqlite3
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

from ..persistence.locks import break_stale_lock

_SCHEMA = """
    CREATE TABLE IF NOT EXISTS tasks(
        task_key     TEXT PRIMARY KEY,
        status       TEXT NOT NULL DEFAULT 'pending',
        owner        TEXT,
        token        TEXT,
        lease_ttl    REAL,
        lease_expires REAL,
        first_leased REAL,
        not_before   REAL NOT NULL DEFAULT 0,
        attempts     INTEGER NOT NULL DEFAULT 0,
        last_error   TEXT,
        enqueued_at  REAL,
        done_at      REAL
    );
"""

#: queue task states: pending -> leased -> done | pending (retry w/ backoff)
#:                                      -> quarantined (poison, attempts spent)
STATUSES = ("pending", "leased", "done", "quarantined")


def default_lease_ttl() -> float:
    """``YFM_LEASE_TTL`` (seconds), default 60 — read per call so tests and
    workers can retune without re-importing."""
    return float(os.environ.get("YFM_LEASE_TTL", "60"))


class Lease(NamedTuple):
    key: str
    owner: str
    token: str
    attempts: int


class LeaseLost(RuntimeError):
    """The lease was stolen (TTL expiry) before this write landed."""


def _sanitize(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", key)


class TaskQueue:
    """One queue = one SQLite file; any number of workers/processes."""

    def __init__(self, path: str, fallback_lockroot: Optional[str] = None):
        self.path = path
        self.fallback_lockroot = fallback_lockroot or path + ".locks"
        self.degraded = False
        # in-memory mirrors for degraded mode (and for claim iteration order)
        self._keys: List[str] = []
        self._done: set = set()
        self._quarantined: Dict[str, str] = {}
        self._attempts: Dict[str, int] = {}
        try:
            self._with_db(lambda db: None)
        except sqlite3.Error:
            self.degraded = True

    # -- journal plumbing ---------------------------------------------------

    def _connect(self) -> sqlite3.Connection:
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        except OSError as e:
            # unreachable journal location (e.g. parent is a file, or the
            # shared filesystem dropped) — same degraded-mode trigger as a
            # failed connect
            raise sqlite3.OperationalError(f"queue dir unavailable: {e}")
        from ..persistence.database import open_wal_db

        db = open_wal_db(self.path)
        db.execute(_SCHEMA)
        return db

    def _with_db(self, fn):
        """Run ``fn(db)`` in one IMMEDIATE transaction; sticky-degrade on
        an unreachable journal (the mkdir fallback takes over)."""
        if self.degraded:
            raise sqlite3.OperationalError("queue journal degraded")
        db = self._connect()
        try:
            db.execute("BEGIN IMMEDIATE;")
            out = fn(db)
            db.commit()
            return out
        except BaseException:
            try:
                db.rollback()
            except sqlite3.Error:
                pass
            raise
        finally:
            db.close()

    def _call(self, fn, fallback):
        try:
            return self._with_db(fn)
        except sqlite3.Error:
            self.degraded = True
            return fallback()

    # -- enqueue ------------------------------------------------------------

    def enqueue(self, keys: Sequence[str]) -> int:
        """Idempotent: INSERT OR IGNORE; returns number of NEW tasks."""
        keys = list(keys)
        for k in keys:
            if k not in self._keys:
                self._keys.append(k)
        now = time.time()

        def ins(db):
            n = 0
            for k in keys:
                cur = db.execute(
                    "INSERT OR IGNORE INTO tasks(task_key, enqueued_at) "
                    "VALUES(?, ?)", (k, now))
                n += cur.rowcount
            return n

        return self._call(ins, lambda: len(keys))

    # -- claim / heartbeat / terminal transitions ---------------------------

    def claim(self, owner: str, ttl: Optional[float] = None) -> Optional[Lease]:
        """Claim a runnable task: pending past its backoff, or leased with an
        EXPIRED lease (atomic steal of a dead worker's task)."""
        ttl = default_lease_ttl() if ttl is None else float(ttl)
        now = time.time()
        token = secrets.token_hex(8)

        def pick(db):
            row = db.execute(
                "SELECT task_key, attempts FROM tasks WHERE "
                "(status='pending' AND not_before<=?) OR "
                "(status='leased' AND lease_expires<?) "
                "ORDER BY enqueued_at, task_key LIMIT 1", (now, now)).fetchone()
            if row is None:
                return None
            key, attempts = row
            db.execute(
                "UPDATE tasks SET status='leased', owner=?, token=?, "
                "lease_ttl=?, lease_expires=?, "
                "first_leased=COALESCE(first_leased, ?), attempts=attempts+1 "
                "WHERE task_key=?", (owner, token, ttl, now + ttl, now, key))
            return Lease(key, owner, token, attempts + 1)

        return self._call(pick, lambda: self._claim_fallback(owner, ttl))

    def heartbeat(self, lease: Lease, ttl: Optional[float] = None) -> bool:
        """Extend the lease; False (not an exception) when it was stolen —
        the heartbeat thread polls this and must not kill the worker."""
        ttl = default_lease_ttl() if ttl is None else float(ttl)

        def beat(db):
            cur = db.execute(
                "UPDATE tasks SET lease_expires=? "
                "WHERE task_key=? AND owner=? AND token=? AND status='leased'",
                (time.time() + ttl, lease.key, lease.owner, lease.token))
            return cur.rowcount == 1

        return self._call(beat, lambda: self._heartbeat_fallback(lease))

    def _guarded(self, lease: Lease, sql: str, args: tuple, fallback) -> None:
        """Conditional lease-holder update; LeaseLost if stolen; degraded
        fallback if the journal went away mid-run."""
        def upd(db):
            cur = db.execute(sql, args + (lease.key, lease.owner, lease.token))
            if cur.rowcount != 1:
                raise LeaseLost(f"lease on {lease.key!r} no longer held by "
                                f"{lease.owner!r}")

        try:
            self._with_db(upd)
        except sqlite3.Error:
            self.degraded = True
            fallback()

    def complete(self, lease: Lease) -> None:
        self._guarded(
            lease,
            "UPDATE tasks SET status='done', done_at=?, owner=NULL, "
            "token=NULL WHERE task_key=? AND owner=? AND token=?",
            (time.time(),),
            lambda: self._complete_fallback(lease))

    def fail(self, lease: Lease, error: str, retry_in: float = 0.0,
             quarantine: bool = False) -> None:
        """Record a failure: back to pending after ``retry_in`` seconds, or
        straight to quarantined (poison task) with the cause on record."""
        status = "quarantined" if quarantine else "pending"
        self._guarded(
            lease,
            "UPDATE tasks SET status=?, last_error=?, not_before=?, "
            "owner=NULL, token=NULL WHERE task_key=? AND owner=? AND token=?",
            (status, str(error)[:2000], time.time() + max(0.0, retry_in)),
            lambda: self._fail_fallback(lease, error, quarantine))

    def release(self, lease: Lease, retry_in: float = 0.0) -> None:
        """Give a claim back WITHOUT burning an attempt (e.g. a merge task
        claimed before its precondition — all shards present — holds)."""
        def fb():
            self._attempts[lease.key] = max(
                0, self._attempts.get(lease.key, 1) - 1)
            self._release_lock(lease.key)

        self._guarded(
            lease,
            "UPDATE tasks SET status='pending', not_before=?, "
            "attempts=attempts-1, owner=NULL, token=NULL "
            "WHERE task_key=? AND owner=? AND token=?",
            (time.time() + max(0.0, retry_in),), fb)

    # -- introspection ------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        def cnt(db):
            rows = db.execute(
                "SELECT status, COUNT(*) FROM tasks GROUP BY status").fetchall()
            return {s: 0 for s in STATUSES} | dict(rows)

        def cnt_fallback():
            out = {s: 0 for s in STATUSES}
            for k in self._keys:
                if k in self._done:
                    out["done"] += 1
                elif k in self._quarantined:
                    out["quarantined"] += 1
                else:
                    out["pending"] += 1
            return out

        return self._call(cnt, cnt_fallback)

    def snapshot(self) -> List[dict]:
        """Every task's row as a dict (the ``status()`` report's raw feed)."""
        def rows(db):
            cols = ("task_key", "status", "owner", "lease_ttl",
                    "lease_expires", "first_leased", "not_before", "attempts",
                    "last_error", "enqueued_at", "done_at")
            got = db.execute(
                f"SELECT {', '.join(cols)} FROM tasks "
                "ORDER BY enqueued_at, task_key").fetchall()
            return [dict(zip(cols, r)) for r in got]

        def rows_fallback():
            return [dict(task_key=k,
                         status=("done" if k in self._done else
                                 "quarantined" if k in self._quarantined else
                                 "pending"),
                         owner=None, lease_ttl=None, lease_expires=None,
                         first_leased=None, not_before=0,
                         attempts=self._attempts.get(k, 0),
                         last_error=self._quarantined.get(k),
                         enqueued_at=None, done_at=None)
                    for k in self._keys]

        return self._call(rows, rows_fallback)

    def all_terminal(self) -> bool:
        """No task is pending or leased (everything done or quarantined)."""
        c = self.counts()
        return c["pending"] == 0 and c["leased"] == 0

    def statuses(self, keys: Sequence[str]) -> Dict[str, str]:
        snap = {r["task_key"]: r["status"] for r in self.snapshot()}
        return {k: snap.get(k, "unknown") for k in keys}

    # -- degraded mode: the reference's mkdir protocol ----------------------

    def _lockdir(self, key: str) -> str:
        return os.path.join(self.fallback_lockroot, _sanitize(key) + ".lock")

    def _release_lock(self, key: str) -> None:
        try:
            os.rmdir(self._lockdir(key))
        except OSError:
            pass

    def _claim_fallback(self, owner: str, ttl: float) -> Optional[Lease]:
        os.makedirs(self.fallback_lockroot, exist_ok=True)
        for key in self._keys:
            if key in self._done or key in self._quarantined:
                continue
            lockdir = self._lockdir(key)
            try:
                os.mkdir(lockdir)
            except FileExistsError:
                # dead-worker recovery, mkdir edition: steal on stale mtime
                if not break_stale_lock(lockdir, ttl):
                    continue
                try:
                    os.mkdir(lockdir)
                except FileExistsError:
                    continue
            self._attempts[key] = self._attempts.get(key, 0) + 1
            return Lease(key, owner, "mkdir", self._attempts[key])
        return None

    def _heartbeat_fallback(self, lease: Lease) -> bool:
        lockdir = self._lockdir(lease.key)
        if not os.path.isdir(lockdir):
            return False
        now = time.time()
        try:
            os.utime(lockdir, (now, now))
            return True
        except OSError:
            return False

    def _complete_fallback(self, lease: Lease) -> None:
        self._done.add(lease.key)
        self._release_lock(lease.key)

    def _fail_fallback(self, lease: Lease, error: str,
                       quarantine: bool) -> None:
        if quarantine:
            self._quarantined[lease.key] = str(error)[:2000]
        self._release_lock(lease.key)
