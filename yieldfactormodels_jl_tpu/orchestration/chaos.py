"""Deterministic fault injection at the orchestration seams.

Recovery paths (lease steal, checkpoint resume, idempotent re-merge) are only
trustworthy if they are exercised, and SIGKILLing pytest workers is neither
portable nor deterministic.  Instead the drivers call :func:`maybe_fail` at
three seams — ``estimate`` (once per checkpointed group iteration,
estimation/optimize.py), ``shard_write`` (before a task's shard insert) and
``merge`` (before the shard merge), both in forecasting.py — and an armed
seam raises :class:`ChaosInjected`.  The supervisor treats that exception as
a simulated worker death: stop heartbeating, abandon the lease, exit.  The
lease then expires by TTL and a surviving worker steals + resumes, exactly
the path a real preemption takes.

Arming is env-gated and off by default:

- ``YFM_CHAOS``: comma-separated ``seam:trigger`` specs.  A trigger is either
  ``@N`` (raise on the N-th hit of that seam — fully deterministic) or a
  probability in (0, 1] drawn from a seeded RNG, e.g.
  ``YFM_CHAOS="estimate:@3,shard_write:0.05"``.
- ``YFM_CHAOS_SEED``: seed for probability triggers (default ``0``) so chaos
  runs replay bit-for-bit.

Beyond the worker-death seams, NUMERIC seams share the same grammar but
corrupt data instead of raising (:func:`should_inject` returns the trigger
decision and the call site applies the fault): ``nan_curve`` and
``nonpsd_cov`` poison the online serving state (serving/service.py) to
exercise the health-watch → rebuild → stale-flag path end-to-end
(docs/DESIGN.md §11), and the TIER-BOUNDARY seams (serving/tiers.py,
docs/DESIGN.md §21) drill the residency hierarchy the same way:
``evict_corrupt`` poisons one frozen warm record at demotion time (the
promotion-side health watch must catch it and rebuild from the cold
registry) and ``promote_stall`` drops one whole promotion wave (the
affected requests answer degraded from their tier records and the next
wave retries).

REQUEST-PATH seams (docs/DESIGN.md §12) drill the serving gateway's
degradation machinery instead of the numerics: ``slow_update`` injects
latency in front of the gateway's update dispatch (:func:`maybe_delay` —
the tail the sustained-load harness must survive), ``queue_stall`` makes
one gateway pump cycle process nothing (the queue ages, admission control
sheds), and ``poison_ticket`` marks one micro-batcher ticket degraded so
the partial-failure isolation path is exercised without crafting NaN
snapshots (serving/batcher.py).

SUBSCRIPTION seams (serving/streams.py, docs/DESIGN.md §23) drill the
streaming fan hub's refresh state machine: ``refresh_storm`` drops one
whole delta-refresh wave — its fan lanes stay dirty and answer degraded
from the last promoted fan until the next accepted update heals them —
and ``fan_stale`` forces one fan answer to be served degraded, exercising
the degrade-from-last-fan path without aging a real ``YFM_FAN_STALE_MS``
budget.

SHARD-LOSS seams (serving/store.py + serving/journal.py,
docs/DESIGN.md §24) drill the failure-domain recovery layer:
``shard_lost`` drops one whole shard's resident device arrays at update
dispatch — the loss-detection → degraded-from-bank → rebuild-wave →
journal-replay path must bring every ungapped key back bit-identical to
the never-lost run — and ``journal_gap`` drops one accepted-update
journal append, which the journal's watermark gap detector must catch so
the affected key is stale-flagged at rebuild instead of ever replaying to
silently-wrong state.

Armed seam names are validated against :data:`KNOWN_SEAMS` at configure
time — a typo'd seam would otherwise arm nothing and silently never fire,
which defeats the whole point of a chaos run.

Tests and benchmarks arm programmatically via :func:`configure` /
:func:`reset` (reset also re-reads the environment on the next hit).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional, Tuple


class ChaosInjected(RuntimeError):
    """Simulated worker death injected at an orchestration seam."""


#: every seam a driver actually calls into — armed specs must name one of
#: these (a typo'd seam would arm nothing and the chaos run would silently
#: test nothing).  Grouped as in the module docstring.
KNOWN_SEAMS = frozenset({
    # worker-death seams (orchestration drivers)
    "estimate", "shard_write", "merge",
    # numeric seams (serving/service.py, serving/store.py)
    "nan_curve", "nonpsd_cov",
    # request-path seams (serving/gateway.py, serving/batcher.py)
    "slow_update", "queue_stall", "poison_ticket",
    # tier-boundary seams (serving/tiers.py)
    "evict_corrupt", "promote_stall",
    # subscription seams (serving/streams.py)
    "refresh_storm", "fan_stale",
    # shard-loss seams (serving/store.py, serving/journal.py)
    "shard_lost", "journal_gap",
})


class _Config:
    def __init__(self, spec: str, seed: int):
        #: seam -> ("count", N) | ("prob", p)
        self.arms: Dict[str, Tuple[str, float]] = {}
        #: seam -> the raw trigger text, for observability reports
        self.raw: Dict[str, str] = {}
        for tok in filter(None, (t.strip() for t in spec.split(","))):
            seam, _, trig = tok.partition(":")
            if not trig:
                raise ValueError(f"YFM_CHAOS entry {tok!r} lacks a trigger "
                                 f"(want 'seam:@N' or 'seam:prob')")
            if seam not in KNOWN_SEAMS:
                raise ValueError(
                    f"YFM_CHAOS entry {tok!r} names unknown seam {seam!r} "
                    f"(want one of: {', '.join(sorted(KNOWN_SEAMS))})")
            if trig.startswith("@"):
                self.arms[seam] = ("count", int(trig[1:]))
            else:
                p = float(trig)
                if not 0.0 < p <= 1.0:
                    raise ValueError(f"YFM_CHAOS probability {p} not in (0, 1]")
                self.arms[seam] = ("prob", p)
            self.raw[seam] = trig
        self.rng = random.Random(seed)


_lock = threading.Lock()
_config: Optional[_Config] = None
_env_checked = False
_hits: Dict[str, int] = {}
_fired: Dict[str, int] = {}


def configure(spec: Optional[str], seed: int = 0) -> None:
    """Arm chaos programmatically (``spec`` as in ``YFM_CHAOS``; ``None``
    disarms).  Validates seam names against :data:`KNOWN_SEAMS` and resets
    the hit/fired counters."""
    global _config, _env_checked
    with _lock:
        _config = _Config(spec, seed) if spec else None
        _env_checked = True  # programmatic config overrides the environment
        _hits.clear()
        _fired.clear()


def reset() -> None:
    """Disarm and forget counters; the environment is re-read on next hit."""
    global _config, _env_checked
    with _lock:
        _config = None
        _env_checked = False
        _hits.clear()
        _fired.clear()


def hits(seam: str) -> int:
    """How many times ``seam`` was reached since the last configure/reset."""
    with _lock:
        return _hits.get(seam, 0)


def fired(seam: str) -> int:
    """How many times ``seam`` actually FIRED (trigger decision true) since
    the last configure/reset — ``hits`` counts the seam being reached,
    ``fired`` the faults injected."""
    with _lock:
        return _fired.get(seam, 0)


def observe() -> Dict[str, Dict[str, object]]:
    """Per-ARMED-seam observability snapshot for health reports:
    ``{seam: {"trigger", "hits", "fired"}}`` — empty when chaos is
    disarmed, so a serving ``health()`` can always include it and a
    chaos-armed run shows which seams actually fired."""
    with _lock:
        if _config is None:
            return {}
        return {seam: {"trigger": _config.raw.get(seam, ""),
                       "hits": _hits.get(seam, 0),
                       "fired": _fired.get(seam, 0)}
                for seam in sorted(_config.arms)}


def _fires(seam: str) -> bool:
    """Shared trigger machinery: count the hit and decide whether the armed
    seam fires (holding the lock; deterministic for ``@N``, seeded-RNG for
    probability triggers)."""
    global _config, _env_checked
    with _lock:
        if not _env_checked:
            spec = os.environ.get("YFM_CHAOS", "")
            seed = int(os.environ.get("YFM_CHAOS_SEED", "0"))
            _config = _Config(spec, seed) if spec else None
            _env_checked = True
        _hits[seam] = _hits.get(seam, 0) + 1
        if _config is None:
            return False
        arm = _config.arms.get(seam)
        if arm is None:
            return False
        kind, val = arm
        decision = (_hits[seam] == val) if kind == "count" \
            else (_config.rng.random() < val)
        if decision:
            _fired[seam] = _fired.get(seam, 0) + 1
        return decision


def maybe_fail(seam: str) -> None:
    """Raise :class:`ChaosInjected` if ``seam`` is armed and triggers.

    No-op (one dict lookup) when chaos is disarmed — safe on hot driver
    paths.  Thread-safe: concurrent in-process workers share the counters,
    so ``@N`` kills whichever worker reaches the seam N-th, like a real
    preemption would.
    """
    if _fires(seam):
        raise ChaosInjected(f"chaos: injected fault at seam {seam!r} "
                            f"(hit {hits(seam)})")


def should_inject(seam: str) -> bool:
    """Non-raising trigger for NUMERIC seams: same arming/counters/specs as
    :func:`maybe_fail`, but the caller applies the fault itself (e.g. the
    serving layer's ``nan_curve``/``nonpsd_cov`` state corruptions,
    docs/DESIGN.md §11) instead of simulating a worker death.  A numeric
    seam must corrupt *data*, never raise — the whole point is exercising
    the silent-poison recovery paths, not the exception paths."""
    return _fires(seam)


def maybe_delay(seam: str, seconds: float) -> bool:
    """Latency-injection trigger for request-path seams (``slow_update``,
    ``queue_stall``): same arming/counters/specs as :func:`maybe_fail`, but a
    fired seam SLEEPS for ``seconds`` instead of raising — the fault a real
    service meets as a slow downstream call or a descheduled worker.  Returns
    whether it fired so the call site can also apply a non-temporal effect
    (e.g. the gateway skipping its pump cycle).  ``seconds <= 0`` keeps the
    trigger decision but skips the sleep (deterministic tests)."""
    fired = _fires(seam)
    if fired and seconds > 0:
        time.sleep(seconds)
    return fired
