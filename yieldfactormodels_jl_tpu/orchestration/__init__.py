"""Fault-tolerant work distribution for rolling-window estimation.

Supersedes the reference's bare ``mkdir`` task locks (forecasting.jl:53-79,
kept in ``persistence/locks.py`` as the degraded fallback) with a
crash-tolerant queue/lease/checkpoint stack for preemptible fleets
(docs/DESIGN.md §10):

- ``queue``      — SQLite-journaled task queue: heartbeat leases, TTL expiry,
  atomic lease steal of dead workers, mkdir-lock degraded mode.
- ``checkpoint`` — per-window multi-start estimation progress persisted after
  every block-coordinate group iteration, so a preempted worker's successor
  resumes the cascade instead of refitting from scratch.
- ``retry``      — exponential backoff with jitter, bounded attempts,
  poison-task quarantine with recorded failure cause.
- ``supervisor`` — the worker loop (claim → heartbeat → estimate →
  shard-write → complete) plus a ``status()`` progress/straggler report.
- ``chaos``      — env-gated deterministic fault injection (``YFM_CHAOS``)
  at the estimation / shard-write / merge seams.

Submodules are exposed lazily (PEP 562): ``supervisor`` imports the
forecasting driver, which itself imports ``chaos``/``checkpoint`` — a light
package ``__init__`` keeps that loop open.
"""

from __future__ import annotations

_SUBMODULES = ("chaos", "checkpoint", "queue", "retry", "supervisor")

_EXPORTS = {
    "ChaosInjected": "chaos",
    "TaskQueue": "queue",
    "Lease": "queue",
    "WindowCheckpoint": "checkpoint",
    "RetryPolicy": "retry",
    "SentinelFailure": "retry",
    "backoff_delay": "retry",
    "run_worker": "supervisor",
    "run_orchestrated": "supervisor",
    "status": "supervisor",
    "format_status": "supervisor",
}

__all__ = list(_SUBMODULES) + list(_EXPORTS)


def __getattr__(name):
    import importlib

    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    if name in _EXPORTS:
        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
