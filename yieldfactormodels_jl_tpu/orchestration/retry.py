"""Retry policy: exponential backoff with jitter, bounded attempts.

The sentinel convention (docs/DESIGN.md §4) keeps failures silent inside
jitted code — losses go to −Inf, moments to NaN — and loud only at the
driver.  The orchestration layer adds the third tier: at the TASK boundary a
sentinel (or a driver-layer exception) becomes a *retriable task failure*
with exponential backoff, and after ``max_attempts`` the task is quarantined
in the queue with its recorded failure cause instead of poisoning the worker
loop forever.
"""

from __future__ import annotations

import random
from typing import NamedTuple, Optional


class SentinelFailure(RuntimeError):
    """A sentinel value (−Inf loss / NaN moments) surfaced at the task
    boundary — retriable, since transient numeric blowups can depend on the
    warm-start cascade's state at claim time.

    Carries the ``seam`` it surfaced at and the taxonomy ``code``
    (robustness/taxonomy.py) diagnosing WHY the sentinel fired, so the
    queue's quarantine rows (which persist ``str(exception)``) are
    actionable instead of a bare "non-finite loss"."""

    def __init__(self, message: str, seam: Optional[str] = None,
                 code: int = 0):
        self.seam = seam
        self.code = int(code)
        detail = message
        if seam:
            detail += f" [seam={seam}]"
        if self.code:
            from ..robustness import taxonomy as _tax  # lazy: keep retry light

            detail += f" [cause={_tax.describe(self.code)}]"
        super().__init__(detail)


class RetryPolicy(NamedTuple):
    """Bounded exponential backoff with multiplicative jitter.

    Delay for attempt ``k`` (1-based) is
    ``min(max_delay, base_delay * factor**(k-1)) * (1 + U(0, jitter))`` —
    jitter decorrelates a fleet of workers retrying the same poisoned task.
    """
    max_attempts: int = 3
    base_delay: float = 0.5
    factor: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.5


def backoff_delay(policy: RetryPolicy, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
    """Delay before re-running a task that just failed its ``attempt``-th try."""
    base = min(policy.max_delay,
               policy.base_delay * policy.factor ** max(0, attempt - 1))
    u = (rng or random).random()
    return base * (1.0 + policy.jitter * u)


def should_quarantine(policy: RetryPolicy, attempts: int) -> bool:
    """True once a task has burned its attempt budget (poison task)."""
    return attempts >= policy.max_attempts
