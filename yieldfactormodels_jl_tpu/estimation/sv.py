"""MLE for the stochastic-volatility measurement-error extension.

The SV model (ops/particle.py) has no closed-form likelihood; the particle
filter provides a Monte-Carlo estimate.  Estimation here is simulated maximum
likelihood with **common random numbers**: one fixed PRNG key is reused for
every objective evaluation, making the estimated likelihood surface a
deterministic function of the parameters, so the gradient-free Nelder–Mead
simplex (estimation/neldermead.py — resampling makes the PF loglik piecewise
constant in places, and AD through systematic resampling is biased) descends
a fixed surface instead of chasing Monte-Carlo noise.

Multi-start: the whole simplex search is vmapped over the start axis — every
(start × simplex-vertex) particle filter runs in one device program, the same
batching thesis as estimation/optimize.py.  Beyond-reference capability
(the reference has no SV model); conventions follow kalman/filter.jl:190-195
via particle_filter_loglik.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..config import register_engine_cache
from ..models.params import transform_params
from ..models.specs import ModelSpec
from ..ops.particle import particle_filter_loglik
from ..utils.transformations import (from_11_to_R, from_pos_to_R,
                                     from_R_to_11, from_R_to_pos)
from .neldermead import nelder_mead, nelder_mead_batched

_PENALTY = 1e12


def _pf_kernel_enabled() -> bool:
    """Whether the fused Pallas PF kernel (ops/pallas_pf) evaluates the CRN
    objective.  Same switch semantics as optimize._ssd_kernel_enabled:
    ``YFM_PF_PALLAS`` "0" disables, "force" enables off-TPU (interpret, the
    test hook), default = TPU only."""
    import os

    flag = os.environ.get("YFM_PF_PALLAS", "auto")
    if flag == "0":
        return False
    if flag == "force":
        return True
    return jax.devices()[0].platform == "tpu"


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_sv_search_pallas(spec: ModelSpec, T: int, n_particles: int,
                             sv_phi, sv_sigma, max_iters: int, f_tol: float,
                             full: bool):
    """Kernel-backed twin of the two searches below: the whole multi-start
    simplex advances in lockstep (nelder_mead_batched) and EVERY candidate
    evaluation across (starts × vertices) is ONE fused PF kernel launch.
    Common random numbers become common noise ARRAYS (the kernel's streamed-
    noise contract) shared by every candidate — the same fixed-surface
    property, one launch instead of S vmapped per-step scans.  ``full``
    appends (φ_h, σ_h) to the search vector via their bijections, per draw."""
    from ..ops.pallas_pf import pf_loglik_batch

    P_pad = -(-n_particles // 128) * 128

    def run(raw0, data, key):  # raw0 (S, n)
        kz, ku = jax.random.split(key)
        nz = jax.random.normal(kz, (T - 1, P_pad), dtype=data.dtype)
        us = jax.random.uniform(ku, (T - 1,), dtype=data.dtype)

        def batch_fun(X):  # (S, K, n) -> (S, K)
            S_, K, n = X.shape
            flat = X.reshape(S_ * K, n)
            if full:
                C = jax.vmap(lambda r: transform_params(spec, r[:-2]))(flat)
                phis = from_R_to_11(flat[:, -2])
                sigs = from_R_to_pos(flat[:, -1])
            else:
                C = jax.vmap(lambda r: transform_params(spec, r))(flat)
                phis = jnp.asarray(sv_phi, dtype=data.dtype)
                sigs = jnp.asarray(sv_sigma, dtype=data.dtype)
            D = S_ * K
            ll = pf_loglik_batch(
                spec, C, data,
                jnp.broadcast_to(nz[None], (D, T - 1, P_pad)),
                jnp.broadcast_to(us[None], (D, T - 1)),
                n_particles=n_particles, sv_phi=phis, sv_sigma=sigs)
            return jnp.where(jnp.isfinite(ll), -ll, _PENALTY).reshape(S_, K)

        if full:
            step = jnp.concatenate(
                [0.025 + 0.05 * raw0[:, :-2],
                 jnp.full((raw0.shape[0], 2), 0.5, dtype=raw0.dtype)], axis=1)
            # nelder_mead_batched shares one step vector; per-start steps
            # differ only via raw0 — use the first start's (they are jittered
            # copies, and the SV coordinates' 0.5 is what matters)
            step = step[0]
        else:
            step = None
        return nelder_mead_batched(batch_fun, raw0, max_iters=max_iters,
                                   f_tol=f_tol, step=step)

    return jax.jit(run)


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_draw_logliks(spec: ModelSpec, T: int, n_particles: int,
                         sv_phi: float, sv_sigma: float):
    from ..ops.particle import draw_loglik_core

    return jax.jit(draw_loglik_core(spec, n_particles, sv_phi, sv_sigma))


def pf_draw_logliks(spec: ModelSpec, draws, data, key=None,
                    n_particles: int = 200, sv_phi: float = 0.95,
                    sv_sigma: float = 0.2):
    """(D,) common-random-numbers PF logliks for a (D, P) CONSTRAINED draw
    batch — the per-point objective value :func:`estimate_sv`'s searches
    evaluate, in the STREAMED-NOISE flavor of its fused/Pallas path: one
    shared noise pair (``ops/particle.draw_noise(key)``) reused by every
    draw, so the sweep is deterministic in the parameters (the fixed-surface
    CRN property) and pays the proposal/resampling RNG once instead of D
    times.  The lattice-callable seam: the fused scenario lattice
    (estimation/scenario.py) inlines the same core
    (ops/particle.draw_loglik_core) into its one-launch program, and parity
    between the two paths is pinned in tests/test_scenario.py."""
    if key is None:
        key = jax.random.PRNGKey(0)
    data = jnp.asarray(data, dtype=spec.dtype)
    draws = jnp.asarray(draws, dtype=spec.dtype)
    if draws.ndim == 1:
        draws = draws[None, :]
    fn = _jitted_draw_logliks(spec, data.shape[1], int(n_particles),
                              float(sv_phi), float(sv_sigma))
    return fn(draws, data, key)


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_sv_search(spec: ModelSpec, T: int, n_particles: int,
                      sv_phi: float, sv_sigma: float, max_iters: int,
                      f_tol: float):
    def single(raw0, data, key):
        def obj(raw):
            ll = particle_filter_loglik(
                spec, transform_params(spec, raw), data, key,
                n_particles=n_particles, sv_phi=sv_phi, sv_sigma=sv_sigma)
            return jnp.where(jnp.isfinite(ll), -ll, _PENALTY)

        return nelder_mead(obj, raw0, max_iters=max_iters, f_tol=f_tol)

    return jax.jit(jax.vmap(single, in_axes=(0, None, None)))


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_sv_search_full(spec: ModelSpec, T: int, n_particles: int,
                           max_iters: int, f_tol: float):
    """Search vector = (raw model params, raw φ_h, raw σ_h): the SV
    hyperparameters ride the same simplex through their natural bijections
    (φ_h ∈ (−1,1) via 2σ(x)−1, σ_h > 0 via exp — utils/transformations)."""
    def single(raw0, data, key):
        def obj(raw):
            phi_h = from_R_to_11(raw[-2])
            sigma_h = from_R_to_pos(raw[-1])
            ll = particle_filter_loglik(
                spec, transform_params(spec, raw[:-2]), data, key,
                n_particles=n_particles, sv_phi=phi_h, sv_sigma=sigma_h)
            return jnp.where(jnp.isfinite(ll), -ll, _PENALTY)

        # the SV raw coordinates live on bijection scales where a unit is a
        # big move in (φ_h, σ_h) — give them a commensurate initial step so
        # the simplex can actually reach them within the iteration budget
        step = jnp.concatenate([0.025 + 0.05 * raw0[:-2],
                                jnp.full((2,), 0.5, dtype=raw0.dtype)])
        return nelder_mead(obj, raw0, max_iters=max_iters, f_tol=f_tol,
                           step=step)

    return jax.jit(jax.vmap(single, in_axes=(0, None, None)))


def estimate_sv(
    spec: ModelSpec,
    data,
    raw_starts,
    key=None,
    n_particles: int = 200,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    max_iters: int = 200,
    f_tol: float = 1e-6,
    estimate_sv_params: bool = False,
):
    """Multi-start simulated MLE under SV measurement errors.

    ``raw_starts`` is (S, P) (or (P,)) of UNCONSTRAINED parameters.  Returns
    ``(best_params_constrained, best_ll, lls (S,), iters (S,))`` with the PF
    loglik evaluated at the shared common-random-numbers key.

    On TPU (``YFM_PF_PALLAS`` knob; "force" for interpret tests) the search
    runs lockstep-batched with every candidate evaluated through ONE fused
    PF kernel launch on shared noise arrays — the same fixed-surface CRN
    property, a different (but equally valid) noise realization than the
    key-splitting scan path.

    ``estimate_sv_params=False`` holds the volatility dynamics (φ_h, σ_h)
    fixed at ``sv_phi``/``sv_sigma``.  With ``estimate_sv_params=True`` they
    join the searched vector (``sv_phi``/``sv_sigma`` become the starting
    point, mapped through the (−1,1)/positive bijections) and a fifth return
    value ``(phi_h_hat, sigma_h_hat)`` carries the estimates.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    data = jnp.asarray(data, dtype=spec.dtype)
    raw_starts = jnp.asarray(raw_starts, dtype=spec.dtype)
    if raw_starts.ndim == 1:
        raw_starts = raw_starts[None, :]
    use_kernel = _pf_kernel_enabled() and spec.family in ("kalman_dns",
                                                          "kalman_afns")
    if estimate_sv_params:
        sv0 = jnp.asarray([from_11_to_R(jnp.asarray(float(sv_phi))),
                           from_pos_to_R(jnp.asarray(float(sv_sigma)))],
                          dtype=spec.dtype)
        raw_starts = jnp.concatenate(
            [raw_starts,
             jnp.broadcast_to(sv0, (raw_starts.shape[0], 2))], axis=1)
        if use_kernel:
            fn = _jitted_sv_search_pallas(spec, data.shape[1], n_particles,
                                          0.0, 0.0, int(max_iters),
                                          float(f_tol), True)
        else:
            fn = _jitted_sv_search_full(spec, data.shape[1], n_particles,
                                        int(max_iters), float(f_tol))
    elif use_kernel:
        fn = _jitted_sv_search_pallas(spec, data.shape[1], n_particles,
                                      float(sv_phi), float(sv_sigma),
                                      int(max_iters), float(f_tol), False)
    else:
        fn = _jitted_sv_search(spec, data.shape[1], n_particles,
                               float(sv_phi), float(sv_sigma), int(max_iters),
                               float(f_tol))
    xs, fs, iters = fn(raw_starts, data, key)
    lls = -np.asarray(fs, dtype=np.float64)
    lls[lls <= -_PENALTY * 0.99] = -np.inf
    if not np.isfinite(lls).any():
        # loud failure (optimization.jl:244-250 semantics): every start sat on
        # the penalty plateau — returning any simplex endpoint as "best" would
        # hand the caller garbage estimates
        raise RuntimeError(
            f"estimate_sv: PF loglik was non-finite at every point of all "
            f"{lls.shape[0]} simplex searches — starts/model/data are "
            f"structurally incompatible")
    best_j = int(np.argmax(np.where(np.isfinite(lls), lls, -np.inf)))
    if estimate_sv_params:
        best = transform_params(spec, xs[best_j][:-2])
        sv_hat = (float(from_R_to_11(xs[best_j][-2])),
                  float(from_R_to_pos(xs[best_j][-1])))
        return (np.asarray(best), float(lls[best_j]), lls, np.asarray(iters),
                sv_hat)
    best = transform_params(spec, xs[best_j])
    return np.asarray(best), float(lls[best_j]), lls, np.asarray(iters)
