"""MLE for the stochastic-volatility measurement-error extension.

The SV model (ops/particle.py) has no closed-form likelihood; the particle
filter provides a Monte-Carlo estimate.  Estimation here is simulated maximum
likelihood with **common random numbers**: one fixed PRNG key is reused for
every objective evaluation, making the estimated likelihood surface a
deterministic function of the parameters, so the gradient-free Nelder–Mead
simplex (estimation/neldermead.py — resampling makes the PF loglik piecewise
constant in places, and AD through systematic resampling is biased) descends
a fixed surface instead of chasing Monte-Carlo noise.

Multi-start: the whole simplex search is vmapped over the start axis — every
(start × simplex-vertex) particle filter runs in one device program, the same
batching thesis as estimation/optimize.py.  Beyond-reference capability
(the reference has no SV model); conventions follow kalman/filter.jl:190-195
via particle_filter_loglik.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..config import register_engine_cache
from ..models.params import transform_params
from ..models.specs import ModelSpec
from ..ops.particle import particle_filter_loglik
from ..utils.transformations import (from_11_to_R, from_pos_to_R,
                                     from_R_to_11, from_R_to_pos)
from .neldermead import nelder_mead

_PENALTY = 1e12


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_sv_search(spec: ModelSpec, T: int, n_particles: int,
                      sv_phi: float, sv_sigma: float, max_iters: int,
                      f_tol: float):
    def single(raw0, data, key):
        def obj(raw):
            ll = particle_filter_loglik(
                spec, transform_params(spec, raw), data, key,
                n_particles=n_particles, sv_phi=sv_phi, sv_sigma=sv_sigma)
            return jnp.where(jnp.isfinite(ll), -ll, _PENALTY)

        return nelder_mead(obj, raw0, max_iters=max_iters, f_tol=f_tol)

    return jax.jit(jax.vmap(single, in_axes=(0, None, None)))


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_sv_search_full(spec: ModelSpec, T: int, n_particles: int,
                           max_iters: int, f_tol: float):
    """Search vector = (raw model params, raw φ_h, raw σ_h): the SV
    hyperparameters ride the same simplex through their natural bijections
    (φ_h ∈ (−1,1) via 2σ(x)−1, σ_h > 0 via exp — utils/transformations)."""
    def single(raw0, data, key):
        def obj(raw):
            phi_h = from_R_to_11(raw[-2])
            sigma_h = from_R_to_pos(raw[-1])
            ll = particle_filter_loglik(
                spec, transform_params(spec, raw[:-2]), data, key,
                n_particles=n_particles, sv_phi=phi_h, sv_sigma=sigma_h)
            return jnp.where(jnp.isfinite(ll), -ll, _PENALTY)

        # the SV raw coordinates live on bijection scales where a unit is a
        # big move in (φ_h, σ_h) — give them a commensurate initial step so
        # the simplex can actually reach them within the iteration budget
        step = jnp.concatenate([0.025 + 0.05 * raw0[:-2],
                                jnp.full((2,), 0.5, dtype=raw0.dtype)])
        return nelder_mead(obj, raw0, max_iters=max_iters, f_tol=f_tol,
                           step=step)

    return jax.jit(jax.vmap(single, in_axes=(0, None, None)))


def estimate_sv(
    spec: ModelSpec,
    data,
    raw_starts,
    key=None,
    n_particles: int = 200,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    max_iters: int = 200,
    f_tol: float = 1e-6,
    estimate_sv_params: bool = False,
):
    """Multi-start simulated MLE under SV measurement errors.

    ``raw_starts`` is (S, P) (or (P,)) of UNCONSTRAINED parameters.  Returns
    ``(best_params_constrained, best_ll, lls (S,), iters (S,))`` with the PF
    loglik evaluated at the shared common-random-numbers key.

    ``estimate_sv_params=False`` holds the volatility dynamics (φ_h, σ_h)
    fixed at ``sv_phi``/``sv_sigma``.  With ``estimate_sv_params=True`` they
    join the searched vector (``sv_phi``/``sv_sigma`` become the starting
    point, mapped through the (−1,1)/positive bijections) and a fifth return
    value ``(phi_h_hat, sigma_h_hat)`` carries the estimates.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    data = jnp.asarray(data, dtype=spec.dtype)
    raw_starts = jnp.asarray(raw_starts, dtype=spec.dtype)
    if raw_starts.ndim == 1:
        raw_starts = raw_starts[None, :]
    if estimate_sv_params:
        sv0 = jnp.asarray([from_11_to_R(jnp.asarray(float(sv_phi))),
                           from_pos_to_R(jnp.asarray(float(sv_sigma)))],
                          dtype=spec.dtype)
        raw_starts = jnp.concatenate(
            [raw_starts,
             jnp.broadcast_to(sv0, (raw_starts.shape[0], 2))], axis=1)
        fn = _jitted_sv_search_full(spec, data.shape[1], n_particles,
                                    int(max_iters), float(f_tol))
    else:
        fn = _jitted_sv_search(spec, data.shape[1], n_particles,
                               float(sv_phi), float(sv_sigma), int(max_iters),
                               float(f_tol))
    xs, fs, iters = fn(raw_starts, data, key)
    lls = -np.asarray(fs, dtype=np.float64)
    lls[lls <= -_PENALTY * 0.99] = -np.inf
    if not np.isfinite(lls).any():
        # loud failure (optimization.jl:244-250 semantics): every start sat on
        # the penalty plateau — returning any simplex endpoint as "best" would
        # hand the caller garbage estimates
        raise RuntimeError(
            f"estimate_sv: PF loglik was non-finite at every point of all "
            f"{lls.shape[0]} simplex searches — starts/model/data are "
            f"structurally incompatible")
    best_j = int(np.argmax(np.where(np.isfinite(lls), lls, -np.inf)))
    if estimate_sv_params:
        best = transform_params(spec, xs[best_j][:-2])
        sv_hat = (float(from_R_to_11(xs[best_j][-2])),
                  float(from_R_to_pos(xs[best_j][-1])))
        return (np.asarray(best), float(lls[best_j]), lls, np.asarray(iters),
                sv_hat)
    best = transform_params(spec, xs[best_j])
    return np.asarray(best), float(lls[best_j]), lls, np.asarray(iters)
