"""Multi-start L-BFGS over a *natively batched* objective.

``optimize.estimate`` runs multi-start MLE as ``vmap(lbfgs(fun))`` — JAX
lockstep-batches the per-start optimizers, and each objective eval is the
vmapped ``lax.scan`` filter.  That composition cannot use the fused Pallas
kernels (``ops/pallas_kf_grad``): vmapping a ``pallas_call`` of batch 1 pads
every start to a full 8×128 VPU tile, wasting 1023/1024 lanes.

This module inverts the nesting: ONE L-BFGS loop whose iterate is the whole
``(S, P)`` start matrix and whose objective is a batched
``X (S, P) → (f (S,), g (S, P))`` — so every function/gradient evaluation
(including each backtracking-linesearch probe) is a single fused-kernel launch
covering all S starts.  All optimizer algebra (two-loop recursion, Armijo
backtracking, convergence bookkeeping) is per-start elementwise/reduction work
along the P axis, which XLA fuses into trivial VPU code.

Semantics per start match ``optimize._run_lbfgs`` (Optim.jl's
LBFGS(BackTracking) analogue, /root/reference/src/optimization.jl:329-410):
memory 10, Armijo geometric backtracking (factor 0.8, optax's default
granularity), max-|g| g_tol + |Δf| f_abstol stopping.  Converged starts freeze (their rows stop moving) while the batch
keeps iterating until all starts converge or ``max_iters`` is reached —
frozen rows ride along in the batched evals for free.

Returns per-start convergence flags and iteration counts — real ones, not the
reference's discarded Optim state (VERDICT round-1 item 8).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class BatchedLBFGSResult(NamedTuple):
    x: jax.Array          # (S, P) final iterates
    f: jax.Array          # (S,) final objective values
    iters: jax.Array      # (S,) iterations each start actually took
    converged: jax.Array  # (S,) bool: g_tol/f_abstol met before max_iters


def batched_lbfgs(value_and_grad: Callable[[jax.Array], Tuple[jax.Array, jax.Array]],
                  x0: jax.Array,
                  max_iters: int,
                  g_tol: float = 1e-6,
                  f_abstol: float = 1e-6,
                  memory_size: int = 10,
                  max_backtracks: int = 25,
                  armijo_c1: float = 1e-4,
                  shrink: float = 0.8,
                  invalid_above: float | None = None,
                  value_fn: Callable[[jax.Array], jax.Array] | None = None
                  ) -> BatchedLBFGSResult:
    """Minimize S objectives simultaneously; every eval is one batched call.

    ``value_and_grad``: (S, P) → ((S,), (S, P)), finite-valued (clamp ±Inf/NaN
    to a penalty before calling — linesearches need comparable numbers).
    ``invalid_above``: objective values ≥ this are the non-finite-loss penalty
    plateau; rows sitting there are never reported ``converged`` (the clamp
    zeroes their gradients, which would otherwise look like an optimum).
    ``value_fn``: optional value-only objective for the Armijo probes — the
    backtracking loop needs no gradients, so with a fused-kernel objective the
    probes run the forward-only kernel (no checkpoint writes, no adjoint) and
    only the accepted point pays for a gradient.
    """
    S, P = x0.shape
    dtype = x0.dtype
    m = memory_size

    f0, g0 = value_and_grad(x0)

    def dot(a, b):
        return jnp.sum(a * b, axis=-1)  # (S,)

    def two_loop(g, s_mem, y_mem, rho, n_hist):
        """Per-start two-loop recursion on stacked history (m, S, P)."""
        q = g
        alphas = jnp.zeros((m, S), dtype=dtype)

        def bwd(i, carry):
            q, alphas = carry
            # newest entry first: index (n_hist-1-i) mod m is valid for i < n_hist
            j = jnp.mod(n_hist - 1 - i, m)
            valid = i < n_hist  # (S,)
            a = rho[j, jnp.arange(S)] * dot(s_mem[j, jnp.arange(S)], q)
            a = jnp.where(valid, a, 0.0)
            q = q - a[:, None] * y_mem[j, jnp.arange(S)]
            alphas = alphas.at[i].set(a)
            return q, alphas

        q, alphas = jax.lax.fori_loop(0, m, bwd, (q, alphas))

        # initial Hessian scale γ = s·y / y·y of the newest pair
        jn = jnp.mod(n_hist - 1, m)
        sy = dot(s_mem[jn, jnp.arange(S)], y_mem[jn, jnp.arange(S)])
        yy = dot(y_mem[jn, jnp.arange(S)], y_mem[jn, jnp.arange(S)])
        gamma = jnp.where((n_hist > 0) & (yy > 0), sy / jnp.maximum(yy, 1e-30), 1.0)
        r = q * gamma[:, None]

        def fwd(i2, r):
            i = m - 1 - i2  # undo reversal: oldest first
            j = jnp.mod(n_hist - 1 - i, m)
            valid = i < n_hist
            b = rho[j, jnp.arange(S)] * dot(y_mem[j, jnp.arange(S)], r)
            corr = (alphas[i] - b)[:, None] * s_mem[j, jnp.arange(S)]
            return r + jnp.where(valid[:, None], corr, 0.0)

        r = jax.lax.fori_loop(0, m, fwd, r)
        return r  # (S, P) ≈ H·g

    if invalid_above is None:
        invalid_above = jnp.inf

    def valid_row(f):
        return jnp.isfinite(f) & (f < invalid_above)

    probe_value = value_fn if value_fn is not None else (
        lambda X: value_and_grad(X)[0])

    def linesearch(x, f, g, d, skip):
        """Per-start Armijo backtracking; each probe is ONE batched eval.
        ``skip`` rows are treated as pre-accepted so frozen starts cannot
        force the full backtracking budget on every outer iteration.  Probes
        are value-only; one gradient eval happens at the accepted points."""
        slope = dot(g, d)  # (S,) should be negative
        alpha = jnp.ones((S,), dtype=dtype)
        accepted = skip
        x_new = x

        def body(carry):
            alpha, accepted, x_new, k = carry
            probe = x + alpha[:, None] * d
            fp = probe_value(probe)
            ok = fp <= f + armijo_c1 * alpha * slope
            take = ok & ~accepted
            x_new = jnp.where(take[:, None], probe, x_new)
            accepted = accepted | ok
            alpha = jnp.where(accepted, alpha, alpha * shrink)
            return alpha, accepted, x_new, k + 1

        def cond(carry):
            _, accepted, _, k = carry
            return (~jnp.all(accepted)) & (k < max_backtracks)

        alpha, accepted, x_new, _ = jax.lax.while_loop(
            cond, body, (alpha, accepted, x_new, 0))
        f_new, g_new = value_and_grad(x_new)
        return x_new, f_new, g_new, accepted

    class Carry(NamedTuple):
        x: jax.Array
        f: jax.Array
        g: jax.Array
        s_mem: jax.Array
        y_mem: jax.Array
        rho: jax.Array
        n_hist: jax.Array     # (S,) valid history length per start
        it: jax.Array         # scalar global iteration
        iters: jax.Array      # (S,) per-start iterations actually applied
        done: jax.Array       # (S,)
        conv: jax.Array       # (S,) done via the g_tol/f_abstol criterion

    def step(c: Carry) -> Carry:
        d = -two_loop(c.g, c.s_mem, c.y_mem, c.rho, c.n_hist)
        # safeguard: if d is not a descent direction, fall back to -g
        descent = dot(c.g, d) < 0
        d = jnp.where(descent[:, None], d, -c.g)

        x_new, f_new, g_new, accepted = linesearch(c.x, c.f, c.g, d, c.done)

        move = accepted & ~c.done
        x_next = jnp.where(move[:, None], x_new, c.x)
        f_next = jnp.where(move, f_new, c.f)
        g_next = jnp.where(move[:, None], g_new, c.g)

        # history update (skip when sy too small or row frozen)
        s = x_next - c.x
        y = g_next - c.g
        sy = dot(s, y)
        store = move & (sy > 1e-12 * jnp.maximum(dot(y, y), 1e-30))
        slot = jnp.mod(c.n_hist, m)  # (S,)
        rows = jnp.arange(S)
        s_mem = c.s_mem.at[slot, rows].set(
            jnp.where(store[:, None], s, c.s_mem[slot, rows]))
        y_mem = c.y_mem.at[slot, rows].set(
            jnp.where(store[:, None], y, c.y_mem[slot, rows]))
        rho = c.rho.at[slot, rows].set(
            jnp.where(store, 1.0 / jnp.maximum(sy, 1e-30), c.rho[slot, rows]))
        n_hist = jnp.where(store, c.n_hist + 1, c.n_hist)

        gnorm = jnp.max(jnp.abs(g_next), axis=-1)
        df = jnp.abs(f_next - c.f)
        newly_done = move & ((gnorm <= g_tol) | (df <= f_abstol))
        stuck = ~accepted & ~c.done  # linesearch failed: no progress possible
        done = c.done | newly_done | stuck
        conv = c.conv | (newly_done & valid_row(f_next))
        iters = c.iters + move.astype(jnp.int32)
        return Carry(x_next, f_next, g_next, s_mem, y_mem, rho, n_hist,
                     c.it + 1, iters, done, conv)

    def cont(c: Carry):
        return (c.it < max_iters) & ~jnp.all(c.done)

    at_opt0 = (jnp.max(jnp.abs(g0), axis=-1) <= g_tol) & valid_row(f0)
    init = Carry(
        x=x0, f=f0, g=g0,
        s_mem=jnp.zeros((m, S, P), dtype=dtype),
        y_mem=jnp.zeros((m, S, P), dtype=dtype),
        rho=jnp.zeros((m, S), dtype=dtype),
        n_hist=jnp.zeros((S,), dtype=jnp.int32),
        it=jnp.asarray(0, dtype=jnp.int32),
        iters=jnp.zeros((S,), dtype=jnp.int32),
        done=~jnp.isfinite(f0) | at_opt0,
        conv=at_opt0,
    )
    out = jax.lax.while_loop(cont, step, init)
    return BatchedLBFGSResult(out.x, out.f, out.iters, out.conv)
