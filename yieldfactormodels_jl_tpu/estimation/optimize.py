"""Estimation layer: jitted losses, multi-start MLE, block-coordinate descent.

Counterpart of /root/reference/src/optimization.jl, re-designed for TPU:

- ``compute_loss`` transforms raw (unconstrained) parameters and negates the
  filter loss (:10-23) — one jitted scan, no per-eval re-allocation,
- ``estimate`` = multi-start LBFGS (:329-410); the whole start axis is a
  ``vmap`` so all starts optimize simultaneously on TPU (the reference loops
  them on one core),
- ``estimate_steps`` = block-coordinate descent with per-group optimizers
  (:137-295): "1"/"4"→Nelder–Mead, "2"→LBFGS(backtracking), "3"/"5"→Adam
  (:439-494); sub-objectives embed the active block into the full vector,
- ``try_initializations``: MSED A/B-guess grid, all candidates evaluated in
  one batched vmap pass instead of a 1000-iteration loop (:73-114); static
  jittered starts (:37-48),
- the ×0.95 invalid-start rescue (:173-184) and NaN→0 sanitization (:422-432).

Optimizer parity is tolerance-based, not bit-exact (SURVEY.md §7): Optim.jl's
LBFGS(BackTracking) maps to ``optax.lbfgs`` with a backtracking linesearch,
NelderMead to the jittable implementation in ``neldermead.py``, Adam to
``optax.adam`` with the same α.
"""

from __future__ import annotations

import os
import threading

from functools import lru_cache
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models import api
from ..models.params import transform_params, untransform_params, get_new_initial_params
from ..models.specs import ModelSpec
from ..config import register_engine_cache
from ..ops import newton as _newton_const
from ..orchestration import chaos as _chaos
from ..robustness import ladder as _ladder
from .batched_lbfgs import batched_lbfgs
from .neldermead import nelder_mead, nelder_mead_batched


# ---------------------------------------------------------------------------
# multi-start report (docs/DESIGN.md §11)
# ---------------------------------------------------------------------------

#: the last estimate()/estimate_steps() call's per-start outcome — PER
#: THREAD: the orchestrated supervisor runs one estimation per worker thread
#: (orchestration/supervisor.py), and a process-global here would let worker
#: B's report overwrite worker A's between A's estimate and A's
#: SentinelFailure, mislabeling quarantine rows.  Contents: final loglik,
#: iteration count, convergence flag and phase per start ("lbfgs" /
#: "newton" / "ladder:<rung>" — so the bench and quarantine diagnoses can
#: attribute wall-clock to phases), ladder traces (codes + rungs) for every
#: escalated start (robustness/ladder.py; empty unless YFM_ESCALATE armed
#: and starts died), optional second-order counters, and the winning index.
_REPORT_TLS = threading.local()
_EMPTY_REPORT: Dict = {"lls": [], "iters": [], "converged": [], "phase": [],
                       "ladder": [], "best": -1}


def last_multistart_report() -> Dict:
    """The calling thread's most recent multi-start report."""
    return getattr(_REPORT_TLS, "report", _EMPTY_REPORT)


def _record_report(lls, ladder_traces, best: int, iters=None, converged=None,
                   phase=None, newton=None) -> None:
    lls = np.asarray(lls).ravel()
    S = lls.shape[0]
    report = {
        "lls": [float(v) for v in lls],
        "iters": [int(v) for v in (np.zeros(S, np.int64) if iters is None
                                   else np.asarray(iters).ravel())],
        "converged": [bool(v) for v in (np.zeros(S, bool) if converged is None
                                        else np.asarray(converged).ravel())],
        "phase": list(phase) if phase is not None else ["lbfgs"] * S,
        "ladder": [t.as_dict() for t in ladder_traces],
        "best": int(best),
    }
    if newton is not None:
        # second-order counters: per-start Newton iterations and total CG
        # (HVP) iterations — the eval-equivalent accounting BENCH_NEWTON uses
        report["newton"] = {k: [int(x) for x in np.asarray(v).ravel()]
                            for k, v in newton.items()}
    _REPORT_TLS.report = report


def _apply_ladder(spec, data, rows_raw, fallback_raw, lls, start, end):
    """Escalate every non-finite start through the ladder (YFM_ESCALATE).

    ``rows_raw`` (S, P): each start's final unconstrained point (non-finite
    rows fall back to ``fallback_raw``); ``lls`` (S,) loglik per start.
    Returns ``(traces, lls', rows')`` with recovered starts' logliks and
    possibly-modified points substituted.  A no-op (no traces) when the
    ladder is disarmed or nothing failed — the historical drop-the-start
    behavior, bit-for-bit.
    """
    lls = np.asarray(lls, dtype=np.float64)
    if not _ladder.escalation_enabled():
        return [], lls, rows_raw
    failed = ~np.isfinite(lls)
    if not failed.any():
        return [], lls, rows_raw
    rows = np.asarray(rows_raw, dtype=np.float64).copy()
    bad_rows = ~np.isfinite(rows).all(axis=1)
    rows[bad_rows] = np.asarray(fallback_raw, dtype=np.float64)[bad_rows]
    traces, lad_lls, rows_new = _ladder.escalate_starts(
        spec, data, rows, failed, start, end)
    rec = np.isfinite(lad_lls)
    return traces, np.where(rec, lad_lls, lls), \
        np.where(rec[:, None], rows_new, rows)


class Convergence(NamedTuple):
    """Real optimizer exit state (the reference surfaces Optim's convergence
    flags, /root/reference/src/optimization.jl:375-407; round 1 hardcoded 0)."""
    converged: bool
    iterations: int

    def __bool__(self) -> bool:  # truthiness = "did it converge"
        return bool(self.converged)

    def __index__(self) -> int:  # backward compat with the old `0` slot
        return int(self.converged)


# ---------------------------------------------------------------------------
# objective
# ---------------------------------------------------------------------------

def compute_loss(spec: ModelSpec, data, raw_params, start=0, end=None):
    """Negative filter loss at unconstrained parameters (optimization.jl:10-23)."""
    constrained = transform_params(spec, raw_params)
    return -api.get_loss(spec, constrained, data, start, end)


#: objective values at/above this sit on the non-finite-loss penalty plateau.
#: Strictly below the 1e12 penalty because float32 rounds 1e12 down to
#: 999_999_995_904 — comparing against 1e12 exactly would never fire in f32.
#: Canonical home is ops/newton.py (the polish's entry-validity check and
#: this layer's plateau tests MUST agree, or a start one phase treats as
#: dead would move in the other) — aliased here, one definition.
_PENALTY_THRESH = _newton_const.PENALTY_THRESH


def _fused_check_mode() -> str:
    """Trust-but-verify policy for the fused-kernel optimum.

    Default is ``fallback`` (re-run the vmap path on disagreement) until the
    Pallas adjoint kernels pass their on-chip gradient gates: round-3 device
    window 1 recorded an unresolved optimum regression on the fused path
    (config 2's ll collapsed 16,100 → −30,278, BASELINE.md "Anomaly under
    investigation") while the restructured adjoints' hardware grad checks had
    never completed.  A guard that observes corruption and proceeds anyway is
    telemetry, not a guard (VERDICT round 3, weak #2).  Flip the default back
    to ``warn`` only with the hw_verify grad-gate evidence in hand.
    ``YFM_FUSED_CHECK=warn`` restores warn-only explicitly.
    """
    return os.environ.get("YFM_FUSED_CHECK", "fallback")


def _fused_disagrees(ll_engine: float, ll_scan: float) -> bool:
    """Shared disagreement criterion for every trust-but-verify guard
    (estimate / estimate_windows / estimate_steps): a finite engine-reported
    optimum whose one scan-engine re-eval is non-finite or off by more than
    0.5% relative.  One definition so the three guards can never drift."""
    return bool(np.isfinite(ll_engine)
                and (not np.isfinite(ll_scan)
                     or abs(ll_scan - ll_engine) > 5e-3 * max(abs(ll_scan), 1.0)))


def _warn_fused_disagreement(tag: str, ll_engine: float, ll_scan: float):
    import sys as _sys
    _sys.stderr.write(
        f"# {tag}: fused-kernel optimum disagrees with the scan engine "
        f"(fused {ll_engine:.6f} vs scan {ll_scan:.6f}) — suspect "
        f"kernel/compiler fault; YFM_FUSED_CHECK={_fused_check_mode()}\n")


def _finite_objective(spec: ModelSpec, data, raw_params, start, end, penalty=1e12):
    """Objective with ±Inf/NaN clamped to a large finite penalty so line
    searches and Adam keep moving (the reference's Optim handles Inf natively;
    optax linesearches want finite values)."""
    v = compute_loss(spec, data, raw_params, start, end)
    return jnp.where(jnp.isfinite(v), v, penalty)


@register_engine_cache
@lru_cache(maxsize=128)
def _jitted_loss(spec: ModelSpec, T: int):
    """Loss jitted once per (spec, data length); start/end stay traced so every
    rolling-window origin reuses the same executable."""
    return jax.jit(lambda p, data, start, end: api.get_loss(spec, p, data, start, end))


def _ssd_kernel_enabled(spec: ModelSpec) -> bool:
    """Whether the fused Pallas score-driven VALUE kernel (ops/pallas_ssd)
    serves this spec's bulk value evaluations (A/B grid, Nelder–Mead blocks,
    L-BFGS Armijo probes).  ``YFM_SSD_PALLAS``: "0" disables, "force" enables
    off-TPU too (interpret mode — the test hook), default = TPU only."""
    if spec.family not in ("msed_lambda", "msed_neural"):
        return False
    if not spec.detach_inner_beta:  # kernel implements the detached-β̄ score
        return False
    flag = os.environ.get("YFM_SSD_PALLAS", "auto")
    if flag == "0":
        return False
    if flag == "force":
        return True
    return jax.devices()[0].platform == "tpu"


@register_engine_cache
@lru_cache(maxsize=128)
def _jitted_ssd_batch_loss(spec: ModelSpec, T: int):
    """Fused-kernel twin of :func:`_jitted_batch_loss` (constrained batch)."""
    from ..ops.pallas_ssd import batched_loss as _ssd_loss

    return jax.jit(lambda p, data, start, end: _ssd_loss(spec, p, data,
                                                         start, end))


@register_engine_cache
@lru_cache(maxsize=128)
def _jitted_batch_loss(spec: ModelSpec, T: int):
    return jax.jit(
        jax.vmap(lambda p, data, start, end: api.get_loss(spec, p, data, start, end),
                 in_axes=(0, None, None, None))
    )


# ---------------------------------------------------------------------------
# core optimizers (all jittable; operate on a closed-over objective)
# ---------------------------------------------------------------------------

def _run_lbfgs(fun, x0, max_iters: int, g_tol: float, f_abstol: float):
    """LBFGS with backtracking linesearch ≈ Optim.LBFGS(BackTracking(order=3)).

    max_backtracking_steps=80, not optax's usual ~25: the first iteration's
    direction is the raw gradient, and a hard-misfit start (e.g. λ far off
    truth) can carry ‖g‖ ~ 3e6 while the finite region sits within ~1e-6 of
    x0 — 25 halvings of 0.8 only reach 4e-3·‖g‖, every probe lands on the
    1e12 penalty plateau (zero gradient), and the run NaNs out
    (tests/test_simulate.py::test_estimation_recovers_simulating_lambda was
    exactly this).  The extra budget is consumed ONLY when 25 steps would
    have failed — the search exits on the first Armijo success — so
    converging runs are unchanged.  Optim.jl survives the same start because
    its backtracking interpolates and handles Inf natively (SURVEY.md §7).
    """
    opt = optax.lbfgs(
        memory_size=10,
        linesearch=optax.scale_by_backtracking_linesearch(
            max_backtracking_steps=80, store_grad=True
        ),
    )
    value_and_grad = optax.value_and_grad_from_state(fun)

    def step(carry):
        x, state, prev_f, it = carry
        value, grad = value_and_grad(x, state=state)
        updates, state = opt.update(grad, state, x, value=value, grad=grad, value_fn=fun)
        x = optax.apply_updates(x, updates)
        return x, state, value, it + 1

    def cont(carry):
        x, state, prev_f, it = carry
        grad = optax.tree_utils.tree_get(state, "grad")
        value = optax.tree_utils.tree_get(state, "value")
        gnorm = jnp.max(jnp.abs(grad))
        not_converged = (gnorm > g_tol) & (jnp.abs(value - prev_f) > f_abstol) | (it < 2)
        return (it < max_iters) & not_converged & jnp.all(jnp.isfinite(x))

    state0 = opt.init(x0)
    x, state, f, it = jax.lax.while_loop(cont, step, (x0, state0, jnp.inf, 0))
    conv = (it < max_iters) & jnp.all(jnp.isfinite(x))
    return x, fun(x), it, conv


def _run_adam(fun, x0, max_iters: int, lr: float, g_tol: float = 1e-8):
    opt = optax.adam(lr)

    def step(carry):
        x, state, it, gnorm, _ = carry
        f, grad = jax.value_and_grad(fun)(x)
        grad = jnp.where(jnp.isfinite(grad), grad, 0.0)
        updates, state = opt.update(grad, state, x)
        x = optax.apply_updates(x, updates)
        return x, state, it + 1, jnp.max(jnp.abs(grad)), f

    def cont(carry):
        x, state, it, gnorm, _ = carry
        return (it < max_iters) & (gnorm > g_tol)

    # the last in-loop objective value rides the carry instead of a whole
    # re-evaluation pass after the loop — it is the value at the final
    # iteration's PRE-update point (one step stale, within the ΔLL tolerance
    # any converged run satisfies), and downstream consumers re-evaluate the
    # returned x anyway (estimate_steps' batch_loss convergence pass)
    x, _, it, _, f_last = jax.lax.while_loop(
        cont, step, (x0, opt.init(x0), 0, jnp.inf,
                     jnp.asarray(jnp.inf, dtype=x0.dtype)))
    return x, f_last, it, it < max_iters


def _run_neldermead(fun, x0, max_iters: int, f_tol: float = 1e-8):
    x, f, it = nelder_mead(fun, x0, max_iters=max_iters, f_tol=f_tol)
    return x, f, it, it < max_iters


# Default group → optimizer table (optimization.jl:439-494)
DEFAULT_OPTIMIZERS: Dict[str, Tuple[str, dict]] = {
    "1": ("neldermead", dict(max_iters=500)),
    "2": ("lbfgs", dict(max_iters=250, g_tol=1e-6, f_abstol=1e-6)),
    "3": ("adam", dict(max_iters=5000, lr=1e-3)),
    "4": ("neldermead", dict(max_iters=500)),
    "5": ("adam", dict(max_iters=10000, lr=1e-3)),
}


def _optimizer_for_group(g: str, table) -> Tuple[str, dict]:
    return table.get(g, table["1"])  # fallback (optimization.jl:501-508)


def _run_named(kind: str, fun, x0, opts: dict):
    if kind == "lbfgs":
        return _run_lbfgs(fun, x0, **opts)
    if kind == "adam":
        return _run_adam(fun, x0, **opts)
    if kind == "neldermead":
        return _run_neldermead(fun, x0, **opts)
    raise ValueError(f"unknown optimizer kind {kind!r}")


# ---------------------------------------------------------------------------
# initialization strategies (optimization.jl:33-114)
# ---------------------------------------------------------------------------

def _sanitize(params):
    """NaN/Inf → 0 (optimization.jl:422-432)."""
    p = np.asarray(params, dtype=np.float64).copy()
    p[~np.isfinite(p)] = 0.0
    return p


def try_initializations(spec: ModelSpec, best_params, data, max_tries: int = 0,
                        start=0, end=None, _force_scan: bool = False):
    """Returns a (P, S) matrix of candidate starting points (constrained).

    - MSED: evaluate the full A×B guess grid in one vmapped batch and keep the
      single best candidate (reference loops ≤1000 trials, :73-114),
    - static: stack ``max_tries`` jittered starts (:37-48),
    - random walk / kalman: passthrough (:33-35).
    """
    best_params = np.asarray(best_params, dtype=np.float64).reshape(-1)
    if spec.is_msed:
        trials = []
        t = 1
        while t <= 1000:
            cand = get_new_initial_params(spec, best_params, t)
            if cand is None:
                break
            trials.append(cand)
            t += 1
        cands = np.stack([best_params] + trials, axis=0)  # (S, P)
        data = jnp.asarray(data, dtype=spec.dtype)
        if end is None:
            end = data.shape[1]
        loss_fn = (_jitted_ssd_batch_loss
                   if _ssd_kernel_enabled(spec) and not _force_scan
                   else _jitted_batch_loss)(spec, data.shape[1])
        losses = np.asarray(loss_fn(jnp.asarray(cands, dtype=spec.dtype), data,
                                    jnp.asarray(start), jnp.asarray(end)))
        best = int(np.nanargmax(np.where(np.isfinite(losses), losses, -np.inf)))
        return cands[best][:, None]
    if spec.family == "random_walk":
        return best_params[:, None]
    if spec.is_static:
        cols = [best_params]
        for trial in range(1, max_tries + 1):
            cols.append(get_new_initial_params(spec, best_params, trial))
        return np.stack(cols, axis=1)
    return best_params[:, None]


# ---------------------------------------------------------------------------
# second-order polish: trust-region Newton-CG cascade (docs/DESIGN.md §17)
# ---------------------------------------------------------------------------

#: coarse-phase budget for the two-phase cascade: enough first-order
#: iterations to reach the basin, not to grind out the tail — the Newton
#: polish owns the tail at quadratic rate.  Only the ITERATION budget is
#: capped and the GRADIENT tolerance loosened; the caller's f_abstol is
#: kept as-is — loosening it makes the backtracking L-BFGS stall on the
#: first plateau stretch far from the basin (measured: f_abstol 1e-5
#: parked config-2-shaped starts at NLL +10.8k where the 1e-6 baseline
#: reaches −2.1k), and no polish can recover a basin never reached.
_NEWTON_COARSE_ITERS = 80
_NEWTON_COARSE_G_TOL = 1e-4
#: coarse budget when the start matrix came from the AMORTIZED surrogate
#: (docs/DESIGN.md §20): a token first-order cleanup only.  The coarse
#: phase's job — reach the basin — is already done by the forward pass, and
#: MORE first-order iterations from a warm point are actively harmful on
#: the razor-thin AFNS surface: the backtracking L-BFGS's non-Armijo
#: fallback step at a huge-gradient point can catapult the iterate six
#: orders of magnitude uphill (measured: ll +10.6k → −6.6e6 in 30 coarse
#: iters, which the trust-region polish then had to claw back).  The
#: polish's radius control is the right tool from a warm point.
_AMORT_COARSE_ITERS = 5
#: polish-phase budget: outer trust-region iterations and the per-iteration
#: Steihaug CG (= HVP) cap
_NEWTON_POLISH_ITERS = 40
_NEWTON_MAX_CG = 20


def _resolve_second_order(second_order) -> str:
    """The cascade arm switch → an HVP engine name, or "" for off.

    ``second_order=None`` (the default everywhere) defers to the
    ``YFM_NEWTON`` env knob: unset/"0" off, "1" = the "fisher" default, or
    an explicit engine name from ``config.NEWTON_ENGINES``.  ``True`` /
    ``False`` / an engine name override the knob per call — ``False`` is
    the bit-for-bit historical path (no second-order code runs at all)."""
    from .. import config as _config

    if second_order is None:
        env = os.environ.get("YFM_NEWTON", "0")
        if env in ("", "0"):
            return ""
        second_order = env
    if second_order is False or second_order == "":
        return ""
    if second_order is True or second_order == "1":
        return "fisher"
    if second_order not in _config.NEWTON_ENGINES:
        raise ValueError(f"unknown second_order engine {second_order!r}; "
                         f"pick from {_config.NEWTON_ENGINES} (or "
                         f"True/False)")
    return second_order


def _resolve_warm_start(spec: ModelSpec, warm_start):
    """The amortized warm-start switch → an ``amortize.Amortizer`` or None.

    ``warm_start=None`` (the default everywhere) defers to the ``YFM_AMORT``
    env knob against the process-wide registry (docs/DESIGN.md §20);
    ``False`` is the historical multi-start path bit-for-bit (no amortizer
    code runs beyond this check); ``True`` consults the registry per call;
    an :class:`~.amortize.Amortizer` instance is used directly.  A knob or
    ``True`` with no surrogate registered for THIS spec quietly resolves to
    None — arming the knob process-wide must not break specs nobody
    trained."""
    if warm_start is False:
        return None
    if warm_start is None:
        if os.environ.get("YFM_AMORT", "0") in ("0", ""):
            return None
        warm_start = True
    if warm_start is True:
        from . import amortize as _amortize

        return _amortize.get_amortizer(spec)
    return warm_start


def _warm_start_matrix(am, data, raw, key=None):
    """Replace most of the S-start spray with the surrogate's warm starts:
    the amortized point + jittered neighbors, plus the caller's FIRST start
    as the anchor row (so a mistrained surrogate can never do worse than a
    single-start run from the canonical init).  Returns ``(raw', origin)``
    with ``origin`` marking amortizer-born rows for the report's phase tags;
    a non-finite surrogate prediction keeps the historical spray untouched
    (sentinel in, historical behavior out)."""
    warm = am.starts(np.asarray(data), key=key)
    if warm is None:
        return raw, np.zeros(raw.shape[0], dtype=bool)
    warm = np.asarray(warm, dtype=np.float64)
    out = np.concatenate([warm, raw[:1]], axis=0)
    return out, np.concatenate([np.ones(warm.shape[0], dtype=bool),
                                np.zeros(1, dtype=bool)])


def _tag_amortized(phase, origin):
    """Phase labels for amortizer-born rows: ``"amortized"`` (first-order)
    or ``"amortized+<phase>"`` — consumers test membership ("newton" in p),
    so the cascade's own labels stay visible."""
    return [(("amortized" if p == "lbfgs" else f"amortized+{p}")
             if origin[i] else p) for i, p in enumerate(phase)]


def resolve_estimation_env() -> Dict:
    """The estimation-cascade env knobs resolved into EXPLICIT ``estimate()``
    kwargs: ``{"second_order": <engine or False>, "warm_start": <bool>}`` —
    exactly what the ``None`` defaults would do.  The perf ledger
    (benchmarks/run_all.py config 2) and bench.py's opt-in estimation benches
    share THIS resolution (via ``benchmarks/common.estimation_env_kwargs``),
    so the ledger can never measure a different cascade than the headline."""
    so = _resolve_second_order(None)
    return {"second_order": so if so else False,
            "warm_start": os.environ.get("YFM_AMORT", "0") not in ("0", "")}


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_newton_polish(spec: ModelSpec, T: int, max_iters: int,
                          g_tol: float, f_abstol: float, mode: str):
    """The polish phase as one jitted program over the whole (S, P) start
    matrix (ops/newton.polish — batched trust-region Newton-CG whose every
    value/gradient/HVP evaluation covers all S starts)."""
    from ..ops import newton as _newton

    def run(X0, data, start, end):
        return _newton.polish(spec, X0, data, start, end,
                              max_iters=max_iters, g_tol=g_tol,
                              f_abstol=f_abstol, mode=mode,
                              max_cg=_NEWTON_MAX_CG)

    return jax.jit(run)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_window_newton_polish(spec: ModelSpec, T: int, max_iters: int,
                                 g_tol: float, f_abstol: float, mode: str):
    """Rolling-window twin: the same polish vmapped over the window axis
    (per-window start/end bounds, shared data panel)."""
    from ..ops import newton as _newton

    def run_one(X0, data, start, end):
        return _newton.polish(spec, X0, data, start, end,
                              max_iters=max_iters, g_tol=g_tol,
                              f_abstol=f_abstol, mode=mode,
                              max_cg=_NEWTON_MAX_CG)

    return jax.jit(jax.vmap(run_one, in_axes=(0, None, 0, 0)))


def _apply_newton_polish(spec: ModelSpec, mode: str, xs_np, fs, its, convs,
                         data, start, end, g_tol, f_abstol):
    """Run the Newton polish on the first-order phase's (S, P) output and
    merge results (driver-side half of the cascade).

    The polish is monotone per start (only descent steps are accepted), so
    its x/f replace the coarse-phase values wherever it RAN; a start that
    was dead at entry (non-finite / penalty-plateau value) is frozen by the
    polish itself and keeps its first-order point — the sentinel contract,
    and the escalation ladder downstream sees exactly what it saw before.
    Returns (xs, fs, its, convs, took, newton_iters, newton_cg,
    newton_code) — ``took`` is the polish's OWN took-mask ((iters > 0) or
    converged-at-entry), the only honest basis for a "newton" phase label:
    the merged ``convs`` still carries phase-1 kernel flags for rows the
    polish froze, and labeling those "newton" would skip the fused
    trust-but-verify guard for exactly the silently-faulty-kernel winners
    it exists to catch.
    """
    T = data.shape[1]
    runner = _jitted_newton_polish(spec, T, _NEWTON_POLISH_ITERS, g_tol,
                                   f_abstol, mode)
    res = runner(jnp.asarray(xs_np, dtype=spec.dtype), data,
                 jnp.asarray(start), jnp.asarray(end))
    n_x = np.asarray(res.x, dtype=np.float64)
    n_f = np.asarray(res.f, dtype=np.float64)
    n_it = np.asarray(res.iters)
    n_conv = np.asarray(res.converged)
    n_cg = np.asarray(res.cg_iters)
    n_code = np.asarray(res.code)
    fs = np.asarray(fs, dtype=np.float64)
    # the polish evaluates through the scan engine; a fused/ssd phase-1
    # value can differ by engine rounding, so take the polished row exactly
    # when the polish moved it or certified convergence at entry
    took = (n_it > 0) | n_conv
    xs = np.where(took[:, None], n_x, np.asarray(xs_np, dtype=np.float64))
    fs = np.where(took, n_f, fs)
    its = np.asarray(its) + n_it
    convs = np.where(took, n_conv, np.asarray(convs, dtype=bool))
    return xs, fs, its, convs, took, n_it, n_cg, n_code


# ---------------------------------------------------------------------------
# estimate: multi-start LBFGS (optimization.jl:329-410)
# ---------------------------------------------------------------------------

#: families the differentiable fused Pallas kernel supports — all three
#: Kalman families (the TVλ EKF adjoint runs the checkpointed per-step
#: jax.vjp kernel, ops/pallas_kf_grad._bwd_kernel_tvl)
_FUSED_FAMILIES = ("kalman_dns", "kalman_afns", "kalman_tvl")


def fused_objectives(spec: ModelSpec, data, start, end, penalty=1e12,
                     win_starts=None, win_ends=None):
    """Batched MLE objectives through the fused Pallas kernels: returns
    (value_fn, value_and_grad) with X (B, P)-raw → f (B,) / (f, g (B, P)).

    ONE forward kernel launch evaluates all B objectives (used for every
    Armijo probe), one forward+adjoint launch pair produces all B gradients
    (used once per accepted L-BFGS point).  This replaces the reference's
    per-eval ForwardDiff filter replay (optimization.jl:329-410) with on-chip
    programs over the whole batch.  ``win_starts``/``win_ends``: optional
    per-row estimation windows — a rolling-window × start batch shares one
    program (see ops/pallas_kf_grad)."""
    from ..ops.pallas_kf import batched_loglik
    from ..ops.pallas_kf_grad import batched_loglik_diff

    def clamp(v):
        return jnp.where(jnp.isfinite(v), v, penalty)

    def value_fn(X):
        cb = jax.vmap(lambda r: transform_params(spec, r))(X)
        return clamp(-batched_loglik(spec, cb, data, start, end,
                                     starts=win_starts, ends=win_ends))

    def f(X):
        cb = jax.vmap(lambda r: transform_params(spec, r))(X)
        return clamp(-batched_loglik_diff(spec, cb, data, start, end,
                                          starts=win_starts, ends=win_ends))

    def vag(X):
        vals, pullback = jax.vjp(f, X)
        (grads,) = pullback(jnp.ones_like(vals))
        return vals, jnp.where(jnp.isfinite(grads), grads, 0.0)

    return value_fn, vag


def vmapped_value_and_grad(spec: ModelSpec, data, start, end, penalty=1e12):
    """Fallback batched objective: vmapped value_and_grad of the lax.scan
    loss — same signature as the value_and_grad half of
    :func:`fused_objectives`."""
    def single(p):
        return _finite_objective(spec, data, p, start, end, penalty)

    def vag(X):
        vals, grads = jax.vmap(jax.value_and_grad(single))(X)
        return vals, jnp.where(jnp.isfinite(grads), grads, 0.0)

    return vag


def _resolve_objective(spec: ModelSpec, objective: str) -> str:
    if objective not in ("auto", "fused", "vmap", "time_sharded"):
        raise ValueError(f"unknown objective {objective!r}; "
                         f"pick from ('auto', 'fused', 'vmap', "
                         f"'time_sharded')")
    if objective == "auto":
        on_tpu = jax.devices()[0].platform == "tpu"
        return "fused" if on_tpu and spec.family in _FUSED_FAMILIES else "vmap"
    if objective == "fused" and spec.family not in _FUSED_FAMILIES:
        raise ValueError(f"fused objective unavailable for family "
                         f"{spec.family!r}; use objective='vmap'")
    if objective == "time_sharded":
        from .. import config

        if config.tree_engine_for(spec) is None:
            raise ValueError(
                f"time_sharded objective needs a family with a "
                f"parallel-in-time engine (docs/DESIGN.md §13/§19); "
                f"config.engines_for({spec.family!r}) = "
                f"{config.engines_for(spec)} has none of 'assoc', 'slr', "
                f"'score_tree' — use objective='vmap'")
    return objective


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_fused_multistart(spec: ModelSpec, T: int, max_iters: int,
                             g_tol: float, f_abstol: float):
    def run(X0, data, start, end):
        value_fn, vag = fused_objectives(spec, data, start, end)
        res = batched_lbfgs(vag, X0, max_iters, g_tol=g_tol, f_abstol=f_abstol,
                            invalid_above=_PENALTY_THRESH, value_fn=value_fn)
        return res.x, res.f, res.iters, res.converged

    return jax.jit(run)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_multistart_lbfgs(spec: ModelSpec, T: int, max_iters: int,
                             g_tol: float, f_abstol: float):
    def single(x0, data, start, end):
        fun = lambda p: _finite_objective(spec, data, p, start, end)
        return _run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    batched = jax.vmap(single, in_axes=(0, None, None, None))
    return jax.jit(batched)


def estimate(spec: ModelSpec, data, all_params, start=0, end=None,
             max_iters: int = 1000, g_tol: float = 1e-6, f_abstol: float = 1e-6,
             printing: bool = False, objective: str = "auto",
             second_order=None, warm_start=None):
    """Multi-start LBFGS MLE.  ``all_params``: (P, S) constrained starts.

    All S starts run simultaneously — either as a vmapped per-start LBFGS
    (``objective="vmap"``), as ONE natively-batched LBFGS whose every
    function/gradient eval is a single fused Pallas kernel launch
    (``objective="fused"``, constant-measurement Kalman families on TPU), or
    as a vmapped LBFGS over the family's O(log T) parallel-in-time loss with
    the panel's TIME axis sharded across the device mesh
    (``objective="time_sharded"``, any family with a tree engine — assoc for
    constant-Z Kalman, iterated SLR for TVλ, score_tree for the capable
    score-driven specs — the long-panel path, docs/DESIGN.md §13/§19).
    ``"auto"`` picks fused whenever it is available.
    Independently of the objective, the loss ENGINE inside the vmap path
    follows ``config.set_kalman_engine`` / the ``YFM_LOGLIK_T_SWITCH``
    dispatch policy through ``api.get_loss``.

    ``second_order`` arms the two-phase cascade (docs/DESIGN.md §17):
    COARSE first-order iterations to the basin (the phase-1 budget is capped
    and its tolerances floored), then the batched trust-region Newton-CG
    polish (``ops/newton.py``) to the caller's ``g_tol``/``f_abstol`` —
    fewer, better iterations at ~3 filter passes per HVP.  ``True``/"fisher"
    = Gauss–Newton/Fisher curvature, "exact" = exact HVPs, ``None`` defers
    to the ``YFM_NEWTON`` knob, ``False`` = the historical first-order path
    bit-for-bit.  Sentinels throughout: a start that is dead at polish
    entry keeps its first-order point, and the escalation ladder
    (``YFM_ESCALATE=1``) rescues it exactly as before.

    ``warm_start`` arms the amortized warm start (docs/DESIGN.md §20): the
    surrogate's one-forward-pass estimate (plus jittered neighbors and the
    caller's first start as anchor) replaces the S-start spray, and the
    phases above fine-tune it — report rows carry the ``"amortized"`` tag.
    ``None`` defers to ``YFM_AMORT``; ``False`` is the historical spray
    bit-for-bit.

    Returns (init_params, ll, best_params, Convergence(converged, iterations))
    like the reference's estimate! — the last element carries the *actual*
    optimizer exit state (optimization.jl:375-407), not a placeholder.
    """
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    if end is None:
        end = T
    all_params = np.asarray(all_params, dtype=np.float64)
    if all_params.ndim == 1:
        all_params = all_params[:, None]
    raw = np.stack(
        [_sanitize(np.asarray(untransform_params(spec, c))) for c in all_params.T], axis=0
    )  # (S, P)
    warm_origin = np.zeros(raw.shape[0], dtype=bool)
    am = _resolve_warm_start(spec, warm_start)
    if am is not None:
        # the surrogate conditions on the ESTIMATION WINDOW only — feeding
        # the full panel would leak future columns into the warm start of a
        # rolling out-of-sample window (the forward pass is length-robust,
        # so the sliced panel is a first-class input)
        raw, warm_origin = _warm_start_matrix(
            am, np.asarray(data)[:, int(start):int(end)], raw)
    kind = _resolve_objective(spec, objective)
    so_mode = _resolve_second_order(second_order)
    if so_mode:
        # phase-1 budget: coarse iterations to the basin only (shorter still
        # when the surrogate already placed the starts in the basin)
        coarse = _AMORT_COARSE_ITERS if warm_origin.any() \
            else _NEWTON_COARSE_ITERS
        p1_iters = min(max_iters, coarse)
        p1_g_tol = max(g_tol, _NEWTON_COARSE_G_TOL)
        p1_f_abstol = f_abstol
    else:
        p1_iters, p1_g_tol, p1_f_abstol = max_iters, g_tol, f_abstol
    if kind == "time_sharded":
        from ..parallel.time_parallel import multistart_time_sharded

        xs, lls_ts, its, convs = multistart_time_sharded(
            spec, data, raw, start, end, max_iters=p1_iters, g_tol=p1_g_tol,
            f_abstol=p1_f_abstol)
        fs = -lls_ts
    else:
        if kind == "fused":
            runner = _jitted_fused_multistart(spec, T, p1_iters, p1_g_tol,
                                              p1_f_abstol)
        else:
            runner = _jitted_multistart_lbfgs(spec, T, p1_iters, p1_g_tol,
                                              p1_f_abstol)
        xs, fs, its, convs = runner(jnp.asarray(raw, dtype=spec.dtype), data,
                                    jnp.asarray(start), jnp.asarray(end))
    fs = np.asarray(fs, dtype=np.float64)
    xs_np = np.asarray(xs, dtype=np.float64)
    phase = ["lbfgs"] * fs.shape[0]
    newton_counters = None
    if so_mode:
        xs_np, fs, its, convs, n_took, n_it, n_cg, n_code = \
            _apply_newton_polish(spec, so_mode, xs_np, fs, its, convs, data,
                                 start, end, g_tol, f_abstol)
        phase = ["newton" if n_took[i] else "lbfgs"
                 for i in range(fs.shape[0])]
        newton_counters = {"iters": n_it, "cg_iters": n_cg, "code": n_code}
    phase = _tag_amortized(phase, warm_origin)
    lls = -fs
    traces = []
    recovered = np.zeros(lls.shape[0], dtype=bool)
    if _ladder.escalation_enabled():
        # a start parked on the penalty plateau never saw a finite objective
        # — hand it to the ladder as dead (−Inf) alongside the −Inf ones;
        # with YFM_ESCALATE off this whole block is skipped and the
        # historical drop-the-start flow below runs untouched
        dead = np.where(np.isfinite(lls) & (fs < _PENALTY_THRESH),
                        lls, -np.inf)
        traces, dead, xs_np = _apply_ladder(spec, data, xs_np, raw, dead,
                                            start, end)
        for t in traces:
            recovered[t.start] = t.recovered
        lls = np.where(recovered, dead, lls)
        fs = np.where(recovered, -dead, fs)
    j = int(np.nanargmax(np.where(np.isfinite(lls), lls, -np.inf)))
    if kind == "fused" and not recovered[j] and "newton" not in phase[j]:
        # trust-but-verify the kernel-reported optimum: ONE scan-engine eval
        # of the winner.  Motivated by the round-3 window-1 anomaly (device
        # config-2 optimum collapsed 16,100 → −30,278 with the restructured
        # adjoint unverified on hardware, BASELINE.md) — a silent kernel/
        # compiler fault must not corrupt results unnoticed.  Fallback by
        # default until the on-chip grad gates pass (_fused_check_mode).
        # A ladder-recovered winner is skipped: its loglik already came from
        # a scan-engine (or sqrt) re-evaluation, not the fused kernel — and
        # so is a Newton-polished one (the polish objective IS the scan).
        ll_scan = float(_jitted_loss(spec, T)(
            transform_params(spec, jnp.asarray(xs_np[j], dtype=spec.dtype)),
            data, jnp.asarray(start), jnp.asarray(end)))
        if _fused_disagrees(lls[j], ll_scan):
            _warn_fused_disagreement("estimate()", lls[j], ll_scan)
            if _fused_check_mode() == "fallback":
                return estimate(spec, data, all_params, start, end, max_iters,
                                g_tol, f_abstol, printing, objective="vmap",
                                second_order=second_order,
                                warm_start=warm_start)
    for t in traces:
        if t.recovered:
            phase[t.start] = f"ladder:{t.rung}"
    _record_report(lls, traces, j, iters=its, converged=convs, phase=phase,
                   newton=newton_counters)
    if printing:
        print(f"✓ Best LL = {lls[j]} from starting point {j + 1}/{len(lls)}")
    best = transform_params(spec, jnp.asarray(xs_np[j], dtype=spec.dtype))
    init = transform_params(spec, jnp.asarray(raw[j], dtype=spec.dtype))
    # a start parked on the penalty plateau has zero clamped gradients — that
    # is an invalid run, not a converged one (threshold below the f32-rounded
    # penalty: float32(1e12) ≈ 0.99999999e12).  A ladder-recovered start is a
    # *rescued evaluation*, not an optimizer convergence.
    valid_j = np.isfinite(lls[j]) and fs[j] < _PENALTY_THRESH \
        and not recovered[j]
    conv = Convergence(bool(np.asarray(convs)[j]) and valid_j,
                       int(np.asarray(its)[j]))
    return np.asarray(init), float(lls[j]), np.asarray(best), conv


# ---------------------------------------------------------------------------
# estimate_steps: block-coordinate descent (optimization.jl:137-295)
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=256)
def _jitted_group_opt_batched(spec: ModelSpec, T: int, inds: Tuple[int, ...],
                              kind: str, opts_items: tuple):
    """All starts' sub-vector optimizations for one group as ONE vmapped
    program — the batch axis the block-coordinate path was missing (VERDICT
    round 1, weak #8)."""
    opts = dict(opts_items)
    idx = jnp.asarray(inds, dtype=jnp.int32)

    def run(p_full, data, start, end):
        def sub(x_sub):
            p = p_full.at[idx].set(x_sub)
            return _finite_objective(spec, data, p, start, end)

        x, f, it, conv = _run_named(kind, sub, p_full[idx], opts)
        return p_full.at[idx].set(x), f

    return jax.jit(jax.vmap(run, in_axes=(0, None, None, None)))


@register_engine_cache
@lru_cache(maxsize=256)
def _jitted_group_opt_ssd(spec: ModelSpec, T: int, inds: Tuple[int, ...],
                          kind: str, opts_items: tuple):
    """Batch-level twin of :func:`_jitted_group_opt_batched` for the MSED
    families: candidate VALUES run through the fused Pallas score-driven
    kernel (ops/pallas_ssd) — one launch per Nelder-Mead stage / Armijo probe
    for the whole start batch — while L-BFGS gradients keep the
    differentiable scan (the value-probe/gradient split of the Kalman fused
    path, :func:`fused_objectives`).  For consistency the L-BFGS line search
    and convergence tests see KERNEL values everywhere (the scan supplies
    only gradients); the two engines agree to rounding, so this is the
    approximate-gradient regime quasi-Newton methods tolerate by design —
    optimizer parity stays tolerance-based (SURVEY.md S7)."""
    from ..ops.pallas_ssd import batched_loss as _ssd_loss

    opts = dict(opts_items)
    idx = jnp.asarray(inds, dtype=jnp.int32)

    def _values(P_rows, data, start, end):
        C = jax.vmap(lambda r: transform_params(spec, r))(P_rows)
        v = -_ssd_loss(spec, C, data, start, end)
        return jnp.where(jnp.isfinite(v), v, 1e12)

    if kind == "neldermead":
        def run_nm(P_full, data, start, end):  # (S, P) raw
            S, Pn = P_full.shape

            def batch_fun(X):  # (S, K, k) -> (S, K)
                K = X.shape[1]
                F = jnp.broadcast_to(P_full[:, None, :], (S, K, Pn))
                F = F.at[:, :, idx].set(X)
                return _values(F.reshape(S * K, Pn), data, start,
                               end).reshape(S, K)

            x, f, _ = nelder_mead_batched(batch_fun, P_full[:, idx],
                                          max_iters=opts["max_iters"],
                                          f_tol=opts.get("f_tol", 1e-8))
            return P_full.at[:, idx].set(x), f

        return jax.jit(run_nm)

    if kind != "lbfgs":
        raise ValueError(f"ssd group runner supports neldermead/lbfgs, "
                         f"not {kind!r}")

    def run_lb(P_full, data, start, end):
        def value_fn(Xs):  # (S, k)
            return _values(P_full.at[:, idx].set(Xs), data, start, end)

        def vag(Xs):
            def single(x_sub, p_row):
                p = p_row.at[idx].set(x_sub)
                return _finite_objective(spec, data, p, start, end)

            _, grads = jax.vmap(jax.value_and_grad(single))(Xs, P_full)
            return value_fn(Xs), jnp.where(jnp.isfinite(grads), grads, 0.0)

        res = batched_lbfgs(vag, P_full[:, idx], opts["max_iters"],
                            g_tol=opts.get("g_tol", 1e-6),
                            f_abstol=opts.get("f_abstol", 1e-6),
                            invalid_above=_PENALTY_THRESH, value_fn=value_fn)
        return P_full.at[:, idx].set(res.x), res.f

    return jax.jit(run_lb)


def _msed_closed_applicable(spec: ModelSpec, inds, data, start, end) -> bool:
    """Gate for the closed-form (δ, Φ) block solve (see
    :func:`_jitted_group_opt_msed_closed`).  Requires: an MSED or static
    (non-RW) family spec (M = 3 filter structure), the group being exactly
    the contiguous (δ, Φ) tail block, concrete window bounds, and a FULLY
    OBSERVED window — with missing columns β carries through Φ across steps
    (score_driven._step transition branch) and the sub-objective stops being
    quadratic."""
    ok_family = spec.is_msed or spec.family in ("static_lambda",
                                                "static_neural")
    if not ok_family or spec.M != 3:
        return False
    if os.environ.get("YFM_MSED_CLOSED", "1") == "0":
        return False
    lo_d, _ = spec.layout["delta"]
    _, hi_p = spec.layout["phi"]
    if tuple(inds) != tuple(range(lo_d, hi_p)):
        return False
    try:
        s, e = int(start), int(end)
    except TypeError:
        return False
    dnp = np.asarray(data)
    # the stacked design needs at least as many rows as unknowns or the
    # reduced QR's R is non-square (trace-time shape error); tiny windows
    # below that are degenerate for the block anyway
    if (dnp.shape[1] - 1) * dnp.shape[0] < spec.M + spec.M * spec.M:
        return False
    return bool(np.isfinite(dnp[:, s:e]).all())


@register_engine_cache
@lru_cache(maxsize=256)
def _jitted_group_opt_msed_closed(spec: ModelSpec, T: int):
    """Closed-form exact solve of the (δ, Φ) block for MSED/static models.

    Structure exploited (a TPU-first redesign of the reference's group-"2"
    L-BFGS, optimization.jl:439-494): in the score-driven recursion
    (/root/reference/src/models/filter.jl:52-91) the γ trajectory is driven
    only by (A, B, ω) through the inner score, and on every observed step the
    measurement β̄ is re-fit by OLS from scratch — so on a fully-observed
    window NEITHER depends on (δ, Φ).  The loss contribution at step t is
    −‖y_{t+1} − Z_{t+1}(μ + Φ β̄_t)‖² with Z_{t+1}, β̄_t, y_{t+1} all
    constants w.r.t. the block: the sub-objective is EXACTLY quadratic in
    (μ, vec Φ), a 12-dim linear least squares.  One trajectory pass + one
    12-unknown QR solve replaces hundreds of 2nd-order-AD filter passes (the
    ~131 ms/pass device latency wall behind BASELINE.md config 6's 0.12×).
    The static families (filter.jl:93-110) share the structure with a
    CONSTANT Z — handled by the same runner without a scan.

    δ is recovered from μ = (I − Φ)δ; the Φ diagonal is clipped into the
    (−1, 1) image of the R_TO_11 bijection.  The candidate is accepted only
    if it improves the full objective (evaluated by the scan engine), so
    block-coordinate monotonicity is preserved unconditionally — clipping,
    f32 rounding in the QR solve, or a singular (I − Φ) degrade to a no-op,
    never to corruption.
    """
    from ..models import score_driven as SD
    from ..models import static_model as ST
    from ..models.params import unpack_static
    from ..ops.linalg import ols_solve

    M = spec.M
    P_HI = jax.lax.Precision.HIGHEST  # normal equations must not ride bf16 MXU

    def run(p_raw, data, start, end):
        cons = transform_params(spec, p_raw)
        t_idx = jnp.arange(T - 1)
        contrib = ((t_idx >= start) & (t_idx <= end - 2)).astype(cons.dtype)
        if spec.is_msed:
            _, _, outs = SD.scan_filter(spec, cons, data, start, end)
            Z2, Z3 = outs["Z2"][:-1], outs["Z3"][:-1]      # (T-1, N) at γ_{t+1}
            X = jnp.stack([jnp.ones_like(Z2), Z2, Z3], -1)  # (T-1, N, M)
            bo = outs["beta_obs"][:-1]                      # (T-1, M)
        else:
            # static families: Z is constant (γ is a static parameter) and
            # β̄_t is per-column OLS — same quadratic structure, no scan
            sp = unpack_static(spec, cons)
            Zc = ST.loadings_fn(spec, sp.gamma)             # (N, M)
            ysafe = jnp.where(jnp.isfinite(data), data, 0.0)
            bo = jax.vmap(lambda y: ols_solve(Zc, y))(ysafe.T[:-1])  # (T-1, M)
            X = jnp.broadcast_to(Zc, (T - 1,) + Zc.shape)
        y1 = data[:, 1:].T                                # (T-1, N) targets
        # regressors for vec_rowmajor(Φ): column (m, k) is X[:, :, m]·β̄[k]
        Dphi = (X[:, :, :, None] * bo[:, None, None, :]).reshape(
            T - 1, X.shape[1], M * M)
        D = jnp.concatenate([X, Dphi], axis=-1)           # (T-1, N, M+M²)
        # mask by jnp.where, NEVER by multiplication: NaN data outside the
        # window (forecast tails) would otherwise poison the sums via 0·NaN
        # and silently no-op the solve forever (same rule as
        # window_contributions, models/common.py)
        keep = contrib[:, None, None] > 0
        Dm = jnp.where(keep, D, 0.0).reshape(-1, M + M * M)
        ym = jnp.where(keep[:, :, 0], y1, 0.0).reshape(-1)
        # solve the stacked LLS by QR, not normal equations: the device path
        # is f32 and κ(DᵀD) = κ(D)² would eat the mantissa exactly where the
        # accept-guard turns a noisy solve into a silent group-2 no-op
        # (masked-out zero rows contribute nothing to R or Qᵀy)
        Q, R = jnp.linalg.qr(Dm)
        qty = jnp.einsum("np,n->p", Q, ym, precision=P_HI)
        theta = jax.scipy.linalg.solve_triangular(R, qty, lower=False)
        # ridge fallback for a rank-deficient design (NaN/Inf pivots)
        G = jnp.einsum("np,nq->pq", Dm, Dm, precision=P_HI)
        lam = 1e-8 * jnp.trace(G) / G.shape[0]
        theta_r = jnp.linalg.solve(
            G + lam * jnp.eye(G.shape[0], dtype=G.dtype),
            jnp.einsum("np,n->p", Dm, ym, precision=P_HI))
        theta = jnp.where(jnp.all(jnp.isfinite(theta)), theta, theta_r)
        mu = theta[:M]
        Phi = theta[M:].reshape(M, M)
        d = jnp.clip(jnp.diagonal(Phi), -0.999999, 0.999999)
        Phi = Phi + jnp.diag(d - jnp.diagonal(Phi))
        delta = jnp.linalg.solve(jnp.eye(M, dtype=Phi.dtype) - Phi, mu)
        lo_d, hi_d = spec.layout["delta"]
        lo_p, hi_p = spec.layout["phi"]
        new_cons = (cons.at[lo_d:hi_d].set(delta)
                    .at[lo_p:hi_p].set(Phi.T.reshape(-1)))  # col-major vec
        new_raw = untransform_params(spec, new_cons)
        f_new = _finite_objective(spec, data, new_raw, start, end)
        f_old = _finite_objective(spec, data, p_raw, start, end)
        take = jnp.logical_and(f_new < f_old, jnp.all(jnp.isfinite(new_raw)))
        return jnp.where(take, new_raw, p_raw), jnp.minimum(f_new, f_old)

    return jax.jit(jax.vmap(run, in_axes=(0, None, None, None)))


def estimate_steps(spec: ModelSpec, data, all_params, param_groups: Sequence[str],
                   max_group_iters: int = 10, tol: float = 1e-8,
                   optimizers: Optional[Dict[str, Tuple[str, dict]]] = None,
                   start=0, end=None, max_tries: int = 0, printing: bool = False,
                   _force_scan: bool = False, checkpoint=None,
                   second_order=None, warm_start=None):
    """Block-coordinate estimation over parameter groups.

    Faithful to the reference control flow: improved initializations for the
    first start, untransform+sanitize, ×0.95 validity rescue, per-group
    optimization embedded in the full vector, ΔLL convergence, best-of-starts.
    Failure semantics follow optimization.jl:244-257: an all-penalty objective
    on the very first group iteration raises (the reference rethrows first-
    iteration errors); on later iterations the group loop aborts quietly.
    Returns (init_params, ll, best_params, Convergence(converged, iterations)).

    ``second_order`` (None = defer to ``YFM_NEWTON``, as in :func:`estimate`)
    appends a full-vector trust-region Newton-CG polish after the cascade
    converges — the block-coordinate loop finds the basin group-by-group,
    the polish takes joint second-order steps across ALL groups at once
    (docs/DESIGN.md §17; non-Kalman families ride the family-generic
    "exact" HVP recursion).  A polished start is accepted only when its
    re-evaluated loglik improves, so the cascade's monotonicity survives.

    ``warm_start`` (None = defer to ``YFM_AMORT``, as in :func:`estimate`)
    replaces the initialization spray with the amortized surrogate's warm
    starts + the caller's first start as anchor (docs/DESIGN.md §20); the
    warm columns' report rows carry the ``"amortized"`` phase tag.

    ``checkpoint`` (an ``orchestration.checkpoint.WindowCheckpoint``):
    persists the full lockstep state after every group iteration and, on a
    signature-matching reload, resumes the remaining iterations bit-for-bit
    — each iteration is a deterministic function of (raw, X, prev_ll, done)
    and the arrays round-trip in native dtype, so a preempted-and-resumed
    cascade equals an uninterrupted one exactly.
    """
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    if end is None:
        end = T
    table = optimizers if optimizers is not None else DEFAULT_OPTIMIZERS
    param_groups = list(param_groups)
    group_ids = sorted(set(param_groups))

    all_params = np.asarray(all_params, dtype=np.float64)
    if all_params.ndim == 1:
        all_params = all_params[:, None]
    # the CALLER's start matrix, before the warm-start/init machinery
    # mutates all_params: the fused-fallback recursion below must restart
    # from this, or the re-run's "anchor" would be the amortized point
    # instead of the canonical init
    caller_params = all_params

    _loss = _jitted_loss(spec, T)
    _start_j, _end_j = jnp.asarray(start), jnp.asarray(end)

    def loss_at(p):
        return _loss(transform_params(spec, p), data, _start_j, _end_j)

    use_ssd = _ssd_kernel_enabled(spec) and not _force_scan
    # resolved BEFORE the checkpoint signature: a warm-started cascade and a
    # historical one follow different trajectories, and a resumed checkpoint
    # from the other mode would silently splice them
    am = _resolve_warm_start(spec, warm_start)
    sig = None
    state = None
    if checkpoint is not None:
        # everything that determines the cascade's trajectory besides the
        # data panel itself — including a digest of the caller's initial
        # parameters and the loss engine; a mismatch silently discards the
        # checkpoint
        import hashlib

        init_digest = hashlib.sha1(
            np.ascontiguousarray(all_params).tobytes()).hexdigest()
        sig = dict(model=spec.model_string, T=int(T), start=int(start),
                   end=int(end), groups=",".join(param_groups),
                   tol=repr(float(tol)),
                   max_group_iters=int(max_group_iters),
                   max_tries=int(max_tries), P=int(all_params.shape[0]),
                   init=init_digest,
                   engine="ssd" if use_ssd else "scan",
                   warm="1" if am is not None else "0")
        state = checkpoint.load(sig)
    n_warm_cols = 0
    if state is not None:
        n_warm_cols = int(state.get("n_warm", 0))
        raw = np.asarray(state["raw"], dtype=np.float64)       # (P, S)
        X = jnp.asarray(state["X"])                            # (S, P)
        prev_ll = np.asarray(state["prev_ll"], dtype=np.float64)
        done = np.asarray(state["done"], dtype=bool)
        converged = np.asarray(state["converged"], dtype=bool)
        iters_done = np.asarray(state["iters_done"], dtype=np.int64)
        ll0 = float(state["ll0"])
        it0 = int(state["next_it"])
        first_group_of_run = False  # ≥1 iteration completed before the save
    else:
        # window-sliced for the same future-leak reason as estimate()
        warm_raw = am.starts(np.asarray(data)[:, int(start):int(end)]) \
            if am is not None else None
        if warm_raw is not None:
            # the amortized point + neighbors replace the init spray (the
            # caller's first start stays as the anchor column); the warm
            # rows are deterministic (Amortizer.starts' fixed key), so a
            # checkpoint resume replays them bit-for-bit
            cols = [np.asarray(transform_params(
                spec, jnp.asarray(w, dtype=spec.dtype)), dtype=np.float64)
                for w in np.asarray(warm_raw, dtype=np.float64)]
            all_params = np.stack(cols + [all_params[:, 0]], axis=1)
            n_warm_cols = len(cols)
        else:
            all_params = try_initializations(spec, all_params[:, 0], data,
                                             max_tries=max_tries,
                                             start=start, end=end,
                                             _force_scan=_force_scan)
        raw = np.stack(
            [_sanitize(np.asarray(untransform_params(spec, jnp.asarray(c))))
             for c in all_params.T],
            axis=1,
        )  # (P, S)

        # validity rescue on the first start (optimization.jl:173-184)
        ll0 = float(loss_at(jnp.asarray(raw[:, 0], dtype=spec.dtype)))
        for _ in range(10):
            if np.isfinite(ll0):
                break
            raw[:, 0] *= 0.95
            ll0 = float(loss_at(jnp.asarray(raw[:, 0], dtype=spec.dtype)))

        X = jnp.asarray(raw.T, dtype=spec.dtype)          # (S, P)
        prev_ll = np.full(raw.shape[1], -np.inf)
        done = np.zeros(raw.shape[1], dtype=bool)    # ΔLL met or aborted
        converged = np.zeros(raw.shape[1], dtype=bool)  # ΔLL met specifically
        iters_done = np.zeros(raw.shape[1], dtype=np.int64)
        it0 = 0
        first_group_of_run = True

    # ---- all starts in lockstep: every group optimization runs the whole
    # start batch through ONE vmapped program (the reference loops starts on
    # one core, optimization.jl:205; round 1 still looped them in Python) ----
    n_starts = S = raw.shape[1]
    batch_loss = (_jitted_ssd_batch_loss if use_ssd
                  else _jitted_batch_loss)(spec, T)
    inds_by_group = {g: tuple(i for i, gg in enumerate(param_groups) if gg == g)
                     for g in group_ids}
    # loop-invariant: one host-side finiteness scan, not one per group per
    # iteration (the gate pulls the data window to host)
    closed_ok = {g: _msed_closed_applicable(spec, inds_by_group[g], data,
                                            start, end) for g in group_ids}
    for it in range(it0, max_group_iters):
        if done.all():
            break
        aborted = np.zeros(S, dtype=bool)
        for g in group_ids:
            if g == "-1":  # placeholder group skipped (:221-223)
                continue
            kind, opts = _optimizer_for_group(g, table)
            inds = inds_by_group[g]
            if not inds:
                continue
            if closed_ok[g]:
                # exact block optimum in one trajectory pass + QR solve
                # (see _jitted_group_opt_msed_closed) — strictly dominates
                # any iterative minimizer of the same sub-objective, and the
                # accept-if-improved guard keeps descent monotone regardless
                runner = _jitted_group_opt_msed_closed(spec, T)
            elif use_ssd and kind in ("neldermead", "lbfgs"):
                runner = _jitted_group_opt_ssd(spec, T, inds, kind,
                                               tuple(sorted(opts.items())))
            else:
                runner = _jitted_group_opt_batched(spec, T, inds, kind,
                                                   tuple(sorted(opts.items())))
            X_new, f_g = runner(X, data, jnp.asarray(start), jnp.asarray(end))
            f_g = np.asarray(f_g, dtype=np.float64)
            obj_broken = f_g >= _PENALTY_THRESH  # (S,) clamped ⇒ never saw finite
            if first_group_of_run:
                first_group_of_run = False
                if obj_broken[0] and not np.isfinite(ll0):
                    # structurally broken objective: the rescued canonical
                    # start was non-finite at entry AND the first group
                    # optimization never found a finite value.  The reference
                    # rethrows first-iteration errors (optimization.jl:
                    # 244-250); a transient excursion of a healthy start is
                    # NOT an error and falls through to the quiet abort below.
                    raise RuntimeError(
                        f"estimate_steps: objective is non-finite at every "
                        f"point of the first group optimization (group "
                        f"{g!r}) — model/data are structurally incompatible")
            frozen = done | aborted
            X = jnp.where(jnp.asarray(frozen)[:, None], X, X_new)
            aborted = aborted | (obj_broken & ~done)  # abort group loop (:251-257)
        active = ~done
        iters_done[active] = it + 1
        lls = np.asarray(batch_loss(
            jax.vmap(lambda r: transform_params(spec, r))(X), data,
            _start_j, _end_j), dtype=np.float64)
        hit_tol = np.abs(lls - prev_ll) < tol
        converged |= active & hit_tol & ~aborted
        done = done | (active & (hit_tol | aborted))
        # an aborted start keeps its pre-iteration LL (the sequential loop
        # breaks before re-evaluating, optimization.jl:251-257)
        prev_ll = np.where(active & ~aborted, lls, prev_ll)
        if checkpoint is not None:
            # persist the iteration boundary BEFORE the chaos seam: a death
            # past the save is exactly "preempted after iteration ``it``",
            # and the successor resumes at it+1
            checkpoint.record_executed()
            checkpoint.save(sig, dict(
                raw=raw, X=np.asarray(X), prev_ll=prev_ll, done=done,
                converged=converged, iters_done=iters_done, ll0=ll0,
                next_it=it + 1, n_warm=n_warm_cols))
        _chaos.maybe_fail("estimate")
    if printing:
        for j in range(S):
            print(f"✓ LL = {prev_ll[j]} from start {j + 1}")

    # second-order polish (docs/DESIGN.md §17): joint Newton-CG steps over
    # the FULL parameter vector from the cascade's converged points — the
    # block-coordinate loop optimizes groups in isolation and stalls on
    # cross-group curvature; the polish sees it.  Accept-if-improved keeps
    # the cascade monotone; dead starts stay dead for the ladder below.
    so_mode = _resolve_second_order(second_order)
    newton_took = np.zeros(S, dtype=bool)
    newton_counters = None
    if so_mode:
        runner = _jitted_newton_polish(spec, T, _NEWTON_POLISH_ITERS,
                                       1e-6, tol, so_mode)
        res = runner(jnp.asarray(X, dtype=spec.dtype), data, _start_j, _end_j)
        lls_new = -np.asarray(res.f, dtype=np.float64)
        n_it = np.asarray(res.iters)
        newton_took = ((n_it > 0) | np.asarray(res.converged)) \
            & np.isfinite(lls_new) \
            & (~np.isfinite(prev_ll) | (lls_new >= prev_ll))
        X = jnp.where(jnp.asarray(newton_took)[:, None],
                      jnp.asarray(np.asarray(res.x, dtype=np.float64),
                                  dtype=spec.dtype), X)
        prev_ll = np.where(newton_took, lls_new, prev_ll)
        converged = converged | (newton_took & np.asarray(res.converged))
        newton_counters = {"iters": n_it,
                           "cg_iters": np.asarray(res.cg_iters),
                           "code": np.asarray(res.code)}

    # escalation ladder (YFM_ESCALATE, robustness/ladder.py): starts whose
    # cascade came back non-finite are retried through scan → sqrt → jitter
    # → ×0.95 instead of being dropped; recovered starts re-enter the
    # best-of comparison with their rescued loglik (and modified point, for
    # the jitter/shrink rungs).  Off by default — the historical behavior.
    ladder_traces = []
    escal_recovered = np.zeros(S, dtype=bool)
    if _ladder.escalation_enabled() and not np.isfinite(prev_ll).all():
        traces, lad_ll, rows_new = _apply_ladder(
            spec, data, np.asarray(X, dtype=np.float64), raw.T, prev_ll,
            start, end)
        ladder_traces = traces
        for t in traces:
            escal_recovered[t.start] = t.recovered
        prev_ll = np.where(escal_recovered, lad_ll, prev_ll)
        X = jnp.asarray(np.where(escal_recovered[:, None], rows_new,
                                 np.asarray(X, dtype=np.float64)),
                        dtype=spec.dtype)

    best_j = int(np.argmax(np.where(np.isfinite(prev_ll), prev_ll, -np.inf)))
    X_np = np.asarray(X, dtype=np.float64)
    best = np.asarray(transform_params(spec, jnp.asarray(X_np[best_j], dtype=spec.dtype)))
    init = np.asarray(transform_params(spec, jnp.asarray(raw[:, best_j], dtype=spec.dtype)))
    if use_ssd and not newton_took[best_j]:
        # trust-but-verify the kernel-reported winner, same contract as
        # estimate(): the convergence LLs above came from the fused SSD
        # kernel, and a silently-faulty kernel (the round-3 device anomaly
        # class) would otherwise own both the selection and the reported
        # optimum.  One scan-engine eval of the winner flags it; fallback
        # re-runs the whole estimation on the scan engine (threaded as a
        # call argument, not process-global env state).  A Newton-polished
        # winner is skipped: its loglik already came from the scan engine.
        ll_scan = float(_loss(jnp.asarray(best, dtype=spec.dtype), data,
                              _start_j, _end_j))
        ll_kern = float(prev_ll[best_j])
        if _fused_disagrees(ll_kern, ll_scan):
            _warn_fused_disagreement("estimate_steps()", ll_kern, ll_scan)
            if _fused_check_mode() == "fallback":
                # keep checkpointing through the scan re-run: its signature
                # carries engine="scan", so it ignores the fused state and
                # overwrites the file with its own resumable progress
                return estimate_steps(spec, data, caller_params, param_groups,
                                      max_group_iters, tol, optimizers,
                                      start, end, max_tries, printing,
                                      _force_scan=True, checkpoint=checkpoint,
                                      second_order=second_order,
                                      warm_start=warm_start)
    phase = ["newton" if newton_took[j] else "lbfgs" for j in range(S)]
    phase = _tag_amortized(
        phase, np.arange(S) < n_warm_cols)  # warm cols lead, anchor is last
    for t in ladder_traces:
        if t.recovered:
            phase[t.start] = f"ladder:{t.rung}"
    _record_report(prev_ll, ladder_traces, best_j, iters=iters_done,
                   converged=converged, phase=phase, newton=newton_counters)
    if printing:
        print(f"✓ Best overall LL = {prev_ll[best_j]} from start {best_j + 1}")
    return init, float(prev_ll[best_j]), best, Convergence(
        bool(converged[best_j]) and not escal_recovered[best_j],
        int(iters_done[best_j]))


# ---------------------------------------------------------------------------
# batched workloads: windows × starts in one device program
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_window_multistart(spec: ModelSpec, T: int, max_iters: int,
                              g_tol: float, f_abstol: float):
    def single(x0, data, start, end):
        fun = lambda p: _finite_objective(spec, data, p, start, end)
        return _run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    over_starts = jax.vmap(single, in_axes=(0, None, None, None))  # starts
    over_windows = jax.vmap(over_starts, in_axes=(None, None, 0, 0))  # windows
    return jax.jit(over_windows)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_fused_windows(spec: ModelSpec, T: int, max_iters: int,
                          g_tol: float, f_abstol: float):
    def run(X0, data, win_starts, win_ends):
        value_fn, vag = fused_objectives(spec, data, 0, T,
                                         win_starts=win_starts,
                                         win_ends=win_ends)
        res = batched_lbfgs(vag, X0, max_iters, g_tol=g_tol, f_abstol=f_abstol,
                            invalid_above=_PENALTY_THRESH, value_fn=value_fn)
        return res.x, res.f, res.iters, res.converged

    return jax.jit(run)


def estimate_windows(spec: ModelSpec, data, raw_starts, window_starts, window_ends,
                     max_iters: int = 1000, g_tol: float = 1e-6, f_abstol: float = 1e-6,
                     objective: str = "auto", second_order=None,
                     warm_start=None):
    """Re-estimate over W rolling windows × S starts in ONE jitted program.

    Masked windows are exactly equivalent to truncation (see models/kalman.py
    docstring), so this replaces the reference's per-origin process farm
    (forecasting.jl:120-199) with a (W, S) batch on the device.  With
    ``objective="fused"`` (auto on TPU for constant-measurement Kalman
    families) the whole (W·S) batch runs one natively-batched L-BFGS whose
    every eval is a single per-lane-windowed Pallas kernel launch.

    ``second_order`` arms the same two-phase cascade as :func:`estimate`
    (None defers to ``YFM_NEWTON``): the first-order phase runs with the
    coarse budget, then ONE window-vmapped trust-region Newton-CG program
    polishes every (window, start) cell to the caller's tolerances.

    ``warm_start`` (None = defer to ``YFM_AMORT``): one surrogate forward
    pass on the FULL panel replaces the shared start spray with the
    amortized point + neighbors (+ the caller's first start as anchor) for
    every window — the windows share starts exactly as before, just better
    ones (docs/DESIGN.md §20).

    Returns (params (W, S, P) unconstrained, logliks (W, S)) — higher is
    better; pick per-window starts with argmax.
    """
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    am = _resolve_warm_start(spec, warm_start)
    if am is not None:
        raw_np = np.asarray(raw_starts, dtype=np.float64)
        raw_starts, _ = _warm_start_matrix(am, data, raw_np)
    kind = _resolve_objective(spec, objective)
    so_mode = _resolve_second_order(second_order)
    if so_mode:
        p1 = (min(max_iters, _NEWTON_COARSE_ITERS),
              max(g_tol, _NEWTON_COARSE_G_TOL), f_abstol)
    else:
        p1 = (max_iters, g_tol, f_abstol)

    def _window_polish(xs, lls, ws, we):
        """(W, S, P) raw + (W, S) lls → polished, via one vmapped program."""
        if not so_mode:
            return xs, lls
        runner = _jitted_window_newton_polish(
            spec, T, _NEWTON_POLISH_ITERS, g_tol, f_abstol, so_mode)
        res = runner(jnp.asarray(xs, dtype=spec.dtype), data,
                     jnp.asarray(ws), jnp.asarray(we))
        took = (np.asarray(res.iters) > 0) | np.asarray(res.converged)
        xs = np.where(took[:, :, None], np.asarray(res.x, dtype=np.float64),
                      np.asarray(xs, dtype=np.float64))
        lls = np.where(took, -np.asarray(res.f, dtype=np.float64),
                       np.asarray(lls, dtype=np.float64))
        return xs, lls

    if kind == "fused":
        raw_starts = jnp.asarray(raw_starts, dtype=spec.dtype)
        S, Pn = raw_starts.shape
        ws = jnp.asarray(window_starts)
        we = jnp.asarray(window_ends)
        W = ws.shape[0]
        X0 = jnp.tile(raw_starts[None], (W, 1, 1)).reshape(W * S, Pn)
        starts_vec = jnp.repeat(ws, S)
        ends_vec = jnp.repeat(we, S)
        runner = _jitted_fused_windows(spec, T, *p1)
        xs, fs, its, convs = runner(X0, data, starts_vec, ends_vec)
        lls = -fs.reshape(W, S)
        if so_mode:
            xs_p, lls_p = _window_polish(
                np.asarray(xs, dtype=np.float64).reshape(W, S, Pn),
                np.asarray(lls, dtype=np.float64), ws, we)
            xs = jnp.asarray(xs_p, dtype=spec.dtype).reshape(W * S, Pn)
            lls = jnp.asarray(lls_p, dtype=jnp.float64)
        # trust-but-verify (same rationale as estimate()): ONE scan eval of
        # the first window's best start flags a silently-faulty kernel
        j0 = int(np.nanargmax(np.where(np.isfinite(np.asarray(lls[0])),
                                       np.asarray(lls[0]), -np.inf)))
        ll_scan = float(_jitted_loss(spec, T)(
            transform_params(spec, xs.reshape(W, S, Pn)[0, j0]),
            data, ws[0], we[0]))
        ll_fused = float(lls[0, j0])
        if _fused_disagrees(ll_fused, ll_scan):
            _warn_fused_disagreement("estimate_windows() window 0",
                                     ll_fused, ll_scan)
            if _fused_check_mode() == "fallback":
                return estimate_windows(spec, data, raw_starts, window_starts,
                                        window_ends, max_iters, g_tol,
                                        f_abstol, objective="vmap",
                                        second_order=second_order,
                                        warm_start=False)
        return xs.reshape(W, S, Pn), lls
    runner = _jitted_window_multistart(spec, T, *p1)
    xs, fs, its, convs = runner(
        jnp.asarray(raw_starts, dtype=spec.dtype),
        data,
        jnp.asarray(window_starts),
        jnp.asarray(window_ends),
    )
    if so_mode:
        return _window_polish(xs, -fs, window_starts, window_ends)
    return xs, -fs
