"""Moving-block bootstrap over a λ-decay grid (BASELINE.md config 5).

A capability beyond the reference: confidence intervals for model-selection
statistics via 2,000 block-bootstrap resamples of the yield panel, evaluated
for every λ on a grid — all (resample × λ) cells as one jit+vmap batch on the
accelerator instead of a CPU loop.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import api
from ..models.specs import ModelSpec
from ..config import register_engine_cache


def moving_block_indices(key, T: int, block_len: int, n_resamples: int):
    """(R, T) time indices: overlapping blocks of ``block_len`` glued together
    (standard Künsch moving-block bootstrap)."""
    n_blocks = -(-T // block_len)
    starts = jax.random.randint(key, (n_resamples, n_blocks), 0, T - block_len + 1)
    offs = jnp.arange(block_len)
    idx = (starts[:, :, None] + offs[None, None, :]).reshape(n_resamples, -1)
    return idx[:, :T]


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_grid_loss(spec: ModelSpec, T: int):
    def one(lam_driver, idx, params, data):
        p = params.at[0].set(lam_driver)
        resampled = data[:, idx]
        return api.get_loss(spec, p, resampled)

    over_lams = jax.vmap(one, in_axes=(0, None, None, None))
    over_resamples = jax.vmap(over_lams, in_axes=(None, 0, None, None))
    return jax.jit(over_resamples)


def bootstrap_lambda_grid(
    spec: ModelSpec,
    params,
    data,
    lambda_grid,
    n_resamples: int = 2000,
    block_len: int = 12,
    key: Optional[jax.Array] = None,
):
    """Loss surface over (resample, λ) for λ-decay model selection.

    ``lambda_grid`` holds decay rates λ; the γ driver solves λ = 1e-2 + e^γ
    (dns.jl:55).  Returns (losses (R, G), ci_low (G,), ci_high (G,),
    selection_freq (G,)): percentile CIs of the per-λ loss and how often each
    λ wins across resamples.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    lam = jnp.asarray(lambda_grid, dtype=spec.dtype)
    gammas = jnp.log(lam - 1e-2)
    idx = moving_block_indices(key, T, block_len, n_resamples)
    fn = _jitted_grid_loss(spec, T)
    losses = fn(gammas, idx, jnp.asarray(params, dtype=spec.dtype), data)  # (R, G)
    ci_low = jnp.percentile(losses, 2.5, axis=0)
    ci_high = jnp.percentile(losses, 97.5, axis=0)
    winner = jnp.argmax(losses, axis=1)
    freq = jnp.mean(winner[:, None] == jnp.arange(lam.shape[0])[None, :], axis=0)
    return losses, ci_low, ci_high, freq
