"""Moving-block bootstrap over a λ-decay grid (BASELINE.md config 5).

A capability beyond the reference: confidence intervals for model-selection
statistics via 2,000 block-bootstrap resamples of the yield panel, evaluated
for every λ on a grid — all (resample × λ) cells as one jit+vmap batch on the
accelerator instead of a CPU loop.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import api
from ..models.specs import ModelSpec
from ..config import register_engine_cache


def moving_block_indices(key, T: int, block_len: int, n_resamples: int):
    """(R, T) time indices: overlapping blocks of ``block_len`` glued together
    (standard Künsch moving-block bootstrap)."""
    n_blocks = -(-T // block_len)
    starts = jax.random.randint(key, (n_resamples, n_blocks), 0, T - block_len + 1)
    offs = jnp.arange(block_len)
    idx = (starts[:, :, None] + offs[None, None, :]).reshape(n_resamples, -1)
    return idx[:, :T]


def _grid_loss_scan_core(spec: ModelSpec, T: int):
    """Plain (un-jitted) general-engine grid-loss core: one ``api.get_loss``
    scan per (resample, λ) cell, vmapped over both axes.  Exposed un-jitted
    (via :func:`grid_loss_core`) so the fused scenario lattice
    (estimation/scenario.py) can inline it into ITS program; ``acc`` is the
    lattice's donated per-cell accumulator — ignored here (the scan engine
    carries its accumulator inside ``get_loss``), accepted for signature
    parity with the fused core."""
    def one(lam_driver, idx, params, data):
        p = params.at[0].set(lam_driver)
        resampled = data[:, idx]
        return api.get_loss(spec, p, resampled)

    over_lams = jax.vmap(one, in_axes=(0, None, None, None))
    over_resamples = jax.vmap(over_lams, in_axes=(None, 0, None, None))

    def core(gammas, idx, params, data, acc=None):
        del acc
        return over_resamples(gammas, idx, params, data)

    return core


def _grid_loss_fused_core(spec: ModelSpec, T: int):
    """MXU formulation of the static-λ grid loss for fully-observed panels.

    With every column observed the static filter carries no state
    (models/static_model.py:_static_scan re-OLS's β from each y_t), so

        pred_t = Z_g (μ + Φ Q_g y_t) = A_g y_t + b_g,
        A_g = Z_g Φ Q_g (N×N),  Q_g = (Z_gᵀZ_g)⁻¹Zᵀ,  b_g = Z_g μ,

    and the whole (resample × λ) sweep is one (G·N, N)@(N, R) matmul per time
    step with the R resamples riding the TPU lane axis — instead of 128k
    scalar filters whose M=3 carries waste 125/128 lanes.  Semantics match
    the scan core exactly on finite data (same ols_solve ridge-select,
    same t = 0..T−2 window, same /N/T normalization, −Inf sentinel).

    ``acc``: optional (R, G) recycle buffer for the per-cell accumulator —
    contents are IGNORED (zeroed before the scan); when the caller donates it
    (scenario lattice), XLA reuses its memory for the loss output instead of
    allocating a fresh (R, G) buffer every launch."""
    from ..models.loadings import dns_loadings
    from ..models.params import unpack_static
    from ..ops.linalg import ols_solve

    def fused(gammas, idx, params, data, acc=None):
        sp = unpack_static(spec, params)
        mats = spec.maturities_array
        Zg = jax.vmap(lambda g: dns_loadings(g[None], mats))(gammas)  # (G,N,M)
        eye_N = jnp.eye(spec.N, dtype=data.dtype)
        # Q = (ZᵀZ)⁻¹Zᵀ via the SAME ridge-select helper the scan engine uses
        # (ols_solve is linear in y, so solving against I_N yields the operator)
        Q = jax.vmap(lambda z: ols_solve(z, eye_N))(Zg)    # (G, M, N)
        A = jnp.einsum("gnm,mk,gkj->gnj", Zg, sp.Phi, Q)   # (G, N, N)
        b = Zg @ sp.mu                                     # (G, N)
        Gn, N = A.shape[0] * A.shape[1], A.shape[2]
        A2 = A.reshape(Gn, N)
        Y = data[:, idx]                     # (N, R, T) — one upfront gather
        Y = jnp.moveaxis(Y, -1, 0)           # (T, N, R)

        def step(acc_c, ys):
            y_t, y_next = ys
            pred = (A2 @ y_t).reshape(A.shape[0], N, -1) + b[:, :, None]
            v = y_next[None, :, :] - pred
            return acc_c - jnp.sum(v * v, axis=1), None

        if acc is None:
            acc0 = jnp.zeros((A.shape[0], Y.shape[2]), dtype=data.dtype)
        else:
            # recycle the donated buffer: keep the VALUE dependency (a dead
            # donated arg is dropped by XLA) but zero through a finiteness
            # mask — a plain ``acc * 0`` would turn recycled −Inf sentinel
            # cells into NaN carries and poison those cells forever
            acc0 = (jnp.where(jnp.isfinite(acc), acc, 0.0) * 0.0).T \
                .astype(data.dtype)
        acc_f, _ = jax.lax.scan(step, acc0, (Y[:-1], Y[1:]))
        loss = acc_f.T / spec.N / T          # (R, G), get_loss normalization
        return jnp.where(jnp.isfinite(loss), loss, -jnp.inf)

    return fused


def grid_loss_core(spec: ModelSpec, T: int, engine: str):
    """The lattice-callable seam: the PLAIN core for an already-resolved
    engine (``"fused"``/``"scan"``), suitable for inlining inside another
    jitted program (estimation/scenario.py's fused lattice).  Resolve the
    engine EAGERLY first (:func:`resolve_grid_engine` — the finiteness probe
    needs concrete data, so it cannot run at trace time)."""
    if engine == "fused":
        return _grid_loss_fused_core(spec, T)
    if engine == "scan":
        return _grid_loss_scan_core(spec, T)
    raise ValueError(f"grid_loss_core needs a resolved engine "
                     f"('fused'/'scan'), got {engine!r}")


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_grid_loss(spec: ModelSpec, T: int):
    return jax.jit(_grid_loss_scan_core(spec, T))


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_grid_loss_fused(spec: ModelSpec, T: int):
    """Jitted wrapper of :func:`_grid_loss_fused_core` (docstring there)."""
    return jax.jit(_grid_loss_fused_core(spec, T))


def lambda_to_gamma(lam):
    """γ driver solving λ = 1e-2 + e^γ (dns.jl:55) — the one place the
    grid's λ-parameterization lives (serial + sharded paths both call it)."""
    return jnp.log(lam - 1e-2)


def grid_losses(spec: ModelSpec, gammas, idx, params, data, engine: str = "auto"):
    """(R, G) loss surface for resample indices ``idx`` and γ drivers
    ``gammas`` — the engine-dispatch core of :func:`bootstrap_lambda_grid`.

    The MXU-fused kernel is exact for fully-observed static-λ panels (the
    bootstrap case — resampling a finite panel stays finite); panels with
    missing columns take the general scan engine.  The finiteness probe
    needs a concrete panel, so under an outer jit (tracer data) we keep the
    general engine and stay traceable.  Exposed separately so the mesh layer
    can shard the resample axis (parallel/mesh.py) without re-deriving the
    engine choice.

    ``engine``: ``"auto"`` (the dispatch above), ``"fused"``, or ``"scan"``.
    The two engines agree to rtol 1e-9 in float64 (tests/test_extensions.py)
    but differ at ~1e-3 in float32 — so under ``"auto"`` a jit-wrapped call
    (tracer data → scan engine) can differ slightly from the same eager call
    (fused engine) in f32.  Pass an explicit engine to pin one path across
    contexts (ADVICE r2).

    Forced ``"fused"`` validates its preconditions (static_lambda family,
    fully-observed panel) eagerly — but the finiteness check needs concrete
    data, so under an outer jit (tracer data) it CANNOT run and, per the
    repo's in-jit sentinel convention, cells whose resampled blocks touch
    missing values come back as −Inf rather than raising.  Validate eagerly
    once before jit-wrapping a pinned-fused call on data that might have
    gaps.
    """
    T = data.shape[1]
    resolved = resolve_grid_engine(spec, data, engine)
    fn = (_jitted_grid_loss_fused if resolved == "fused"
          else _jitted_grid_loss)(spec, T)
    return fn(gammas, idx, jnp.asarray(params, dtype=spec.dtype), data)


def resolve_grid_engine(spec: ModelSpec, data, engine: str = "auto") -> str:
    """EAGER engine dispatch for the (resample × λ) grid: returns ``"fused"``
    or ``"scan"``.  Extracted from :func:`grid_losses` so the scenario
    lattice (estimation/scenario.py) resolves the engine at the driver —
    with concrete data — and bakes the choice into its trace as a static
    builder key (the finiteness probe cannot run on tracers, per the
    in-jit sentinel convention).  Semantics identical to the historical
    inline dispatch, including the loud forced-``"fused"`` validation."""
    if engine not in ("auto", "fused", "scan"):
        raise ValueError(f"engine must be 'auto', 'fused' or 'scan', got {engine!r}")
    if engine == "fused":
        # enforce the same preconditions the auto dispatch checks — the fused
        # kernel has no missing-data handling, so forcing it onto a NaN panel
        # would silently flush affected cells to -Inf instead of the scan
        # engine's finite masked losses
        if spec.family != "static_lambda":
            raise ValueError("engine='fused' requires a static_lambda spec")
        if (not isinstance(data, jax.core.Tracer)
                and not bool(np.isfinite(np.asarray(data)).all())):
            raise ValueError(
                "engine='fused' requires a fully-observed (finite) panel; "
                "this data has missing values — use engine='scan'")
        return "fused"
    if (engine == "auto"
            and spec.family == "static_lambda"
            and not isinstance(data, jax.core.Tracer)
            and bool(np.isfinite(np.asarray(data)).all())):
        return "fused"
    return "scan"


def grid_stats(losses, n_lambdas: int):
    """(ci_low, ci_high, selection_freq) of an (R, G) loss surface."""
    ci_low = jnp.percentile(losses, 2.5, axis=0)
    ci_high = jnp.percentile(losses, 97.5, axis=0)
    winner = jnp.argmax(losses, axis=1)
    freq = jnp.mean(winner[:, None] == jnp.arange(n_lambdas)[None, :], axis=0)
    return ci_low, ci_high, freq


def bootstrap_lambda_grid(
    spec: ModelSpec,
    params,
    data,
    lambda_grid,
    n_resamples: int = 2000,
    block_len: int = 12,
    key: Optional[jax.Array] = None,
):
    """Loss surface over (resample, λ) for λ-decay model selection.

    ``lambda_grid`` holds decay rates λ; the γ driver solves λ = 1e-2 + e^γ
    (dns.jl:55).  Returns (losses (R, G), ci_low (G,), ci_high (G,),
    selection_freq (G,)): percentile CIs of the per-λ loss and how often each
    λ wins across resamples.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    lam = jnp.asarray(lambda_grid, dtype=spec.dtype)
    gammas = lambda_to_gamma(lam)
    idx = moving_block_indices(key, T, block_len, n_resamples)
    losses = grid_losses(spec, gammas, idx, params, data)  # (R, G)
    return (losses,) + grid_stats(losses, lam.shape[0])
