"""Amortized estimation: a neural surrogate that turns multi-start MLE into
a one-forward-pass warm start (docs/DESIGN.md §20; ROADMAP item 1,
arXiv:2210.07154).

Multi-start MLE is the repo's end-to-end wall-clock bottleneck (BASELINE
config 2).  This module trains a small JAX-native surrogate ONCE on simulated
``(panel → untransformed-params)`` pairs and then maps an observed panel to a
parameter estimate in a single jitted forward pass — the amortized point
(plus a few jittered neighbors) replaces most of the S-start spray, and the
existing coarse-LBFGS → trust-region-Newton cascade (docs/DESIGN.md §17)
fine-tunes to tolerance.  Three pieces:

- **Training-data pipeline** (``_jitted_sim_batch``): parameter draws from a
  Gaussian prior in UNCONSTRAINED space (every draw is feasible by
  construction — the transforms own the constraints) are pushed through
  ``models/simulate.py`` as ONE vmapped compile-once program, draw axis LAST
  per the lane rule.  The draw matrix is DONATED and flows back out as the
  ``raw`` output (the lattice's pass-through aliasing invariant,
  docs/DESIGN.md §14), so recurring rounds are alloc-light.  A draw whose
  simulation fails (non-stationary Φ → Cholesky breakdown) yields a NaN
  panel — a coded training sample, never an exception (YFM001).
- **Summary network + head** (``_forward_core``): a permutation/length-robust
  deep-set over the panel's time axis — a shared per-step MLP over
  ``(yₜ, Δyₜ)`` pairs, mean/second-moment pooled over VALID columns (a
  column with any non-finite entry is masked; masked counts normalize, so
  the same weights serve any T), concatenated with per-maturity panel
  moments, then a two-layer MLP head onto the raw parameter vector.  Pure
  pytree params, f64-safe, batch on the trailing axis throughout.  An
  all-invalid panel pools 0/0 → a NaN prediction — the sentinel downstream
  consumers test for.
- **Adam training loop** (``_jitted_train_step``): masked MSE on raw params
  over the whole lane batch; a sample whose panel (or prediction) is
  non-finite gets weight zero — bad simulated panels are masked, never
  raised.  ``params``/``opt_state`` are donated (consumed and returned), so
  a training round allocates nothing but the loss scalar.

Consumption surfaces: ``optimize.estimate``/``estimate_steps``/
``estimate_windows`` and ``scenario.refit_column`` accept ``warm_start=``
(None defers to the ``YFM_AMORT`` env knob against the process-wide
:func:`register_amortizer` registry); the serving layer's ``refit`` verbs
(``YieldCurveService.refit``, the gateways, ``ShardedStateStore.
publish_refit``) ride :func:`amortized_refit` — forward pass + one polish
step — for a request-path re-estimation.

``YFM_AMORT`` unset (or ``warm_start=False``) is the historical estimation
path bit-for-bit: no amortizer code runs beyond the env check.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from functools import lru_cache
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import make_trace_counter, register_engine_cache
from ..models.specs import ModelSpec

# trace counters (config.make_trace_counter): incremented INSIDE traced
# bodies so they count actual (re)compilations — the no-recompile tests pin
# them across repeated predict/train rounds
trace_counts, note_trace, reset_trace_counts = make_trace_counter()


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AmortizerConfig:
    """Static architecture/warm-start configuration (frozen + hashable — it
    keys the jitted-program caches alongside the spec).

    ``hidden``/``head`` size the per-step MLP and the head; ``n_warm`` is the
    number of starts :meth:`Amortizer.starts` emits (the amortized point plus
    ``n_warm − 1`` jittered neighbors); ``jitter`` scales the neighbors'
    Gaussian perturbation in raw space; ``seed`` fixes initialization AND the
    default start-jitter stream, so a warm-started estimation is
    deterministic end to end (checkpoint resume stays bit-for-bit)."""

    hidden: int = 32
    head: int = 32
    n_warm: int = 4
    jitter: float = 0.02
    seed: int = 0


def n_features(cfg: AmortizerConfig, spec: ModelSpec) -> int:
    """Pooled summary width: deep-set mean + second moment (2·hidden) plus
    per-maturity panel mean/std (2·N)."""
    return 2 * cfg.hidden + 2 * spec.N


def init_params(cfg: AmortizerConfig, spec: ModelSpec, key) -> Dict:
    """Fresh surrogate weights (pytree of ``spec.dtype`` arrays).

    ``y_mu``/``y_sd``/``dy_sd`` are input-normalization constants — identity
    until :func:`set_normalization` fits them to the first simulated batch;
    they ride the pytree but are ``stop_gradient``-ed in the forward pass, so
    Adam never moves them.  ``b3`` (the output bias) starts at zero and is
    usually re-anchored to the prior mean by :func:`train_amortizer`, so an
    undertrained surrogate degrades toward the prior point, not garbage."""
    dtype = spec.dtype
    N, P, H, H2 = spec.N, spec.n_params, cfg.hidden, cfg.head
    F = n_features(cfg, spec)
    k1, k2, k3 = jax.random.split(jnp.asarray(key), 3)

    def glorot(k, shape):
        lim = np.sqrt(6.0 / (shape[0] + shape[1]))
        return jax.random.uniform(k, shape, dtype=dtype, minval=-lim,
                                  maxval=lim)

    return {
        "y_mu": jnp.zeros((N,), dtype=dtype),
        "y_sd": jnp.ones((N,), dtype=dtype),
        "dy_sd": jnp.ones((N,), dtype=dtype),
        "W1": glorot(k1, (H, 2 * N)),
        "b1": jnp.zeros((H,), dtype=dtype),
        "W2": glorot(k2, (H2, F)),
        "b2": jnp.zeros((H2,), dtype=dtype),
        "W3": glorot(k3, (P, H2)) * 0.1,
        "Ws": jnp.zeros((P, F), dtype=dtype),
        "b3": jnp.zeros((P,), dtype=dtype),
    }


def set_normalization(params: Dict, panels) -> Dict:
    """Fit the input-normalization constants from a (N, T, B) panel batch
    (host-side, driver layer): per-maturity mean/std of the valid yields and
    std of their first differences.  Floors keep a degenerate batch from
    planting zero divisors."""
    Y = np.asarray(panels, dtype=np.float64)
    finite = np.isfinite(Y)
    Ysafe = np.where(finite, Y, np.nan)
    with np.errstate(all="ignore"):
        mu = np.nanmean(Ysafe, axis=(1, 2))
        sd = np.nanstd(Ysafe, axis=(1, 2))
        dsd = np.nanstd(Ysafe[:, 1:] - Ysafe[:, :-1], axis=(1, 2))
    mu = np.where(np.isfinite(mu), mu, 0.0)
    sd = np.where(np.isfinite(sd) & (sd > 1e-8), sd, 1.0)
    dsd = np.where(np.isfinite(dsd) & (dsd > 1e-8), dsd, 1.0)
    dtype = params["y_mu"].dtype
    out = dict(params)
    out["y_mu"] = jnp.asarray(mu, dtype=dtype)
    out["y_sd"] = jnp.asarray(sd, dtype=dtype)
    out["dy_sd"] = jnp.asarray(dsd, dtype=dtype)
    return out


# ---------------------------------------------------------------------------
# the summary network + head (plain inlinable cores)
# ---------------------------------------------------------------------------

def _forward_core(cfg: AmortizerConfig, params: Dict, Y):
    """Panel batch (N, T, B) → raw-parameter predictions (P, B).

    Deep-set over time: shared per-step MLP on the normalized ``(yₜ, Δyₜ)``
    pair, pooled by masked mean/second moment over the valid columns — the
    same weights serve any panel length, and time-permutation of the
    (yₜ₋₁, yₜ) pairs leaves the summary unchanged.  Masking: a column with
    ANY non-finite entry is invalid; an all-invalid panel pools 0/0 and the
    prediction comes out NaN (the sentinel contract — the driver layer
    decides what to do, nothing raises here)."""
    dtype = Y.dtype
    sg = jax.lax.stop_gradient
    y_mu = sg(params["y_mu"])[:, None, None]
    y_sd = sg(params["y_sd"])[:, None, None]
    dy_sd = sg(params["dy_sd"])[:, None, None]
    finite = jnp.isfinite(Y)
    valid = jnp.all(finite, axis=0)                       # (T, B)
    Ysafe = jnp.where(finite, Y, 0.0)
    Yn = (Ysafe - y_mu) / y_sd
    # (yₜ, Δyₜ) pair features on the T−1 transition steps
    pair_ok = (valid[1:] & valid[:-1]).astype(dtype)      # (T-1, B)
    dY = (Ysafe[:, 1:] - Ysafe[:, :-1]) / dy_sd
    X = jnp.concatenate([Yn[:, 1:], dY], axis=0)          # (2N, T-1, B)
    X = jnp.where(pair_ok[None] > 0, X, 0.0)
    H1 = jnp.tanh(jnp.einsum("hf,ftb->htb", params["W1"], X)
                  + params["b1"][:, None, None])          # (H, T-1, B)
    w = pair_ok[None]
    cnt = jnp.sum(w, axis=1)                              # (1, B)
    wv = valid.astype(dtype)[None]                        # (1, T, B)
    cv = jnp.sum(wv, axis=1)
    # SAFE denominators inside, sentinel only at the output: dividing by a
    # zero count here would make the whole weight gradient NaN for every
    # batch containing one dead panel (0/0 rides the chain rule), and the
    # train step's NaN→0 guard would then silently freeze all the weights —
    # measured: only the output bias trained.  The dead lanes are instead
    # poisoned at the END via jnp.where, which keeps the NaN sentinel for
    # consumers without contaminating the live lanes' gradients.
    dead = (cnt < 0.5) | (cv < 0.5)                       # (1, B)
    cnt_s = jnp.maximum(cnt, 1.0)
    cv_s = jnp.maximum(cv, 1.0)
    m1 = jnp.sum(H1 * w, axis=1) / cnt_s                  # (H, B)
    m2 = jnp.sum(H1 * H1 * w, axis=1) / cnt_s
    my = jnp.sum(Yn * wv, axis=1) / cv_s                  # (N, B)
    sy = jnp.sqrt(jnp.maximum(
        jnp.sum(Yn * Yn * wv, axis=1) / cv_s - my * my, 0.0))
    Z = jnp.concatenate([m1, m2, my, sy], axis=0)         # (F, B)
    # soft-clip the pooled summary at ±4 (features are ≈unit-scale after
    # normalization): a near-unit-root draw's panel can sit tens of σ out,
    # and an unbounded feature lets the linear head extrapolate wildly on
    # exactly the panels it knows least about (measured: held-out MSE 5-11×
    # the prior's before the clip, 0.6× after)
    Z = 4.0 * jnp.tanh(Z / 4.0)
    G = jnp.tanh(params["W2"] @ Z + params["b2"][:, None])
    # head = nonlinear MLP + a zero-initialized LINEAR skip from the pooled
    # summary: the linear regression component of panel → params (level
    # curve → δ, curvature → λ) is learned in a few dozen Adam steps, the
    # tanh path only has to model the residual interactions
    out = params["W3"] @ G + params["Ws"] @ Z + params["b3"][:, None]
    return jnp.where(dead, jnp.asarray(jnp.nan, dtype=dtype), out)


def _loss_core(cfg: AmortizerConfig, params: Dict, Y, targets):
    """Masked MSE on raw params over the lane batch: a sample whose panel
    produced a NaN prediction (failed simulation / all-invalid columns) or
    whose target is non-finite carries weight zero — bad simulated panels
    are masked, never raised (YFM001).  The mask is applied by ``jnp.where``
    BEFORE the square (double-where), so a masked sample's NaN cannot leak
    into the gradient either."""
    pred = _forward_core(cfg, params, Y)                  # (P, B)
    ok = jnp.all(jnp.isfinite(pred), axis=0) \
        & jnp.all(jnp.isfinite(targets), axis=0)          # (B,)
    keep = ok[None] & jnp.isfinite(pred) & jnp.isfinite(targets)
    err = jnp.where(keep, pred - jnp.where(keep, targets, 0.0), 0.0)
    n = jnp.maximum(jnp.sum(ok.astype(Y.dtype)), 1.0)
    return jnp.sum(err * err) / (n * targets.shape[0])


# ---------------------------------------------------------------------------
# jitted programs (compile-once; @register_engine_cache + @lru_cache)
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_sim_batch(spec: ModelSpec, T: int, B: int, donate: bool):
    """The training-data program: raw parameter draws (P, B) + per-draw PRNG
    keys → ``{"raw", "panels"}`` with panels (N, T, B), draw axis LAST (the
    lane rule).  The draw matrix is DONATED and passes through as the
    ``raw`` output (value-use + shape-matched alias — the scenario lattice's
    donation invariant, docs/DESIGN.md §14), so each training round re-feeds
    buffers instead of allocating; a failed simulation (Cholesky breakdown
    on a non-stationary draw) yields a NaN panel, never an exception."""
    from ..models.params import transform_params
    from ..models.simulate import simulate

    def run(raw, keys):
        note_trace("sim")

        def one(r, k):
            cons = transform_params(spec, r)
            return simulate(spec, cons, T, k)["data"]     # (N, T)

        panels = jax.vmap(one, in_axes=(1, 0), out_axes=-1)(raw, keys)
        return {"raw": raw, "panels": panels}

    return jax.jit(run, donate_argnums=(0,) if donate else ())


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_forward(cfg: AmortizerConfig, spec: ModelSpec, T: int, B: int):
    """One surrogate forward pass over a (N, T, B) panel batch → (P, B) raw
    predictions.  Keyed by (cfg, spec, T, B): serving refits at a fixed
    history length reuse one executable; a new panel length retraces once."""
    def run(params, Y):
        note_trace("forward")
        return _forward_core(cfg, params, Y)

    return jax.jit(run)


@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_train_step(cfg: AmortizerConfig, spec: ModelSpec, T: int, B: int,
                       lr: float):
    """One Adam step over the whole lane batch.  ``params`` and ``opt_state``
    are DONATED (consumed and returned updated — their values flow through
    ``optax.apply_updates`` into the outputs), so the training loop's
    recurring state reuses its allocations; non-finite gradients are zeroed
    (the masked loss already excludes bad samples — this guards the
    all-masked-batch edge where the loss itself is degenerate)."""
    import optax

    opt = optax.adam(lr)

    def step(params, opt_state, Y, targets):
        note_trace("train_step")
        loss, grads = jax.value_and_grad(
            lambda p: _loss_core(cfg, p, Y, targets))(params)
        grads = jax.tree_util.tree_map(
            lambda g: jnp.where(jnp.isfinite(g), g, 0.0), grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# the net's target space: steady-state parameterization of the δ block
# ---------------------------------------------------------------------------
#
# The net does NOT regress the raw δ block directly.  δ's posterior noise is
# dominated by the unknowable (Φ − Φ̄)·μ cross-term, and a componentwise
# posterior mean (δ̂, Φ̂) is INCONSISTENT as a pair: the implied steady state
# (I − Φ̂)⁻¹δ̂ amplifies δ̂'s residual ~10× and the predicted point lands
# thousands of nats below even the prior mean (measured).  Training targets
# therefore carry μ = (I − Φ)⁻¹δ in the δ slots (the steady state, directly
# observable in the panel's level), and prediction reconstructs
# δ̂ = (I − Φ̂)μ̂ — whatever Φ̂'s error, the PAIR is consistent with the
# recovered steady state, which is what the likelihood rewards.


def _phi_matrices(spec: ModelSpec, raw_BP: np.ndarray) -> np.ndarray:
    """(B, P) raw → (B, Ms, Ms) constrained transition matrices (Kalman
    layout: row-major Φ block, tanh on the diagonal)."""
    from ..models.params import transform_params

    lo_p, hi_p = spec.layout["phi"]
    Ms = spec.state_dim
    cons = np.asarray(jax.vmap(lambda r: transform_params(spec, r))(
        jnp.asarray(raw_BP, dtype=jnp.float64)), dtype=np.float64)
    return cons[:, lo_p:hi_p].reshape(-1, Ms, Ms)


def net_targets(spec: ModelSpec, raw_PB: np.ndarray) -> np.ndarray:
    """Raw draws (P, B) → net-space targets: δ slots replaced by the draw's
    steady state μ = (I − Φ)⁻¹δ.  A draw whose (I − Φ) is singular gets NaN
    μ — a masked training sample (weight zero in the loss), never an
    error."""
    raw = np.asarray(raw_PB, dtype=np.float64)
    if not spec.is_kalman:
        return raw
    lo_d, hi_d = spec.layout["delta"]
    Ms = spec.state_dim
    Phi = _phi_matrices(spec, raw.T)                      # (B, Ms, Ms)
    A = np.eye(Ms)[None] - Phi
    delta = raw[lo_d:hi_d].T                              # (B, Ms)
    mu = np.full_like(delta, np.nan)
    for b in range(delta.shape[0]):
        try:
            mu[b] = np.linalg.solve(A[b], delta[b])
        except np.linalg.LinAlgError:
            pass  # NaN target row → masked sample
    out = raw.copy()
    out[lo_d:hi_d] = mu.T
    return out


def raw_from_net(spec: ModelSpec, net_BP: np.ndarray) -> np.ndarray:
    """Net-space predictions (B, P) → raw parameter vectors: δ̂ = (I − Φ̂)μ̂
    (no inverse — always well defined)."""
    net = np.asarray(net_BP, dtype=np.float64)
    if not spec.is_kalman:
        return net
    lo_d, hi_d = spec.layout["delta"]
    Ms = spec.state_dim
    Phi = _phi_matrices(spec, net)                        # Φ slots are raw Φ
    mu = net[:, lo_d:hi_d]
    delta = np.einsum("bij,bj->bi", np.eye(Ms)[None] - Phi, mu)
    out = net.copy()
    out[:, lo_d:hi_d] = delta  # δ transforms are identity: raw == constrained
    return out


# ---------------------------------------------------------------------------
# the trained surrogate
# ---------------------------------------------------------------------------

class Amortizer:
    """A trained panel → raw-params surrogate for ONE model spec.

    Holds the weight pytree plus the warm-start policy; prediction is a
    single jitted forward pass (:meth:`predict_raw`), and :meth:`starts`
    turns it into the (n_warm, P) start matrix the estimation layer consumes
    — the amortized point first, jittered neighbors after, ``None`` when the
    prediction is non-finite (the caller keeps its historical start spray:
    sentinel in, historical behavior out)."""

    def __init__(self, spec: ModelSpec, cfg: AmortizerConfig, params: Dict,
                 info: Optional[Dict] = None):
        self.spec = spec
        self.cfg = cfg
        self.params = params
        self.info = dict(info or {})

    # ---- prediction -------------------------------------------------------

    def predict_raw_batch(self, panels) -> np.ndarray:
        """(B, N, T) panels → (B, P) raw predictions (NaN rows = sentinel).

        The forward pass emits NET-space vectors (δ slots carry the steady
        state μ̂); :func:`raw_from_net` reconstructs the consistent
        δ̂ = (I − Φ̂)μ̂ pair before anything downstream sees the vector."""
        spec = self.spec
        Y = jnp.asarray(panels, dtype=spec.dtype)
        if Y.ndim != 3 or Y.shape[1] != spec.N:
            raise ValueError(f"panels must be (B, N, T) with N={spec.N}; "
                             f"got {tuple(Y.shape)}")
        B, _, T = Y.shape
        fn = _jitted_forward(self.cfg, spec, int(T), int(B))
        out = np.asarray(fn(self.params, jnp.moveaxis(Y, 0, -1)),
                         dtype=np.float64).T              # (B, P) net space
        return raw_from_net(spec, out)

    def predict_raw(self, data) -> np.ndarray:
        """(N, T) panel → (P,) raw (unconstrained) prediction."""
        return self.predict_raw_batch(np.asarray(data)[None])[0]

    def predict(self, data) -> np.ndarray:
        """(N, T) panel → constrained parameter vector (driver convenience;
        non-finite raw predictions stay NaN through the transforms)."""
        from ..models.params import transform_params

        raw = self.predict_raw(data)
        return np.asarray(transform_params(
            self.spec, jnp.asarray(raw, dtype=self.spec.dtype)),
            dtype=np.float64)

    # ---- warm-start matrices ---------------------------------------------

    def _jittered(self, raw0: np.ndarray, key) -> np.ndarray:
        S = max(1, int(self.cfg.n_warm))
        if key is None:
            key = jax.random.PRNGKey(self.cfg.seed)
        if S == 1:
            return raw0[None]
        # neighbors via the STRUCTURED prior sampler around the amortized
        # point (Φ projected stationary, δ jittered in steady-state space)
        # — a plain isotropic raw jitter lands most AFNS neighbors on the
        # −Inf plateau (non-stationary Φ) where they are dead lanes
        nb = sample_prior_raw(self.spec, raw0, S - 1, key,
                              scale=self.cfg.jitter).T
        return np.concatenate([raw0[None], nb], axis=0)

    def starts(self, data, key=None) -> Optional[np.ndarray]:
        """(N, T) panel → (n_warm, P) raw start matrix, or ``None`` when the
        surrogate prediction is non-finite (caller falls back to its
        historical start spray)."""
        raw0 = self.predict_raw(np.asarray(data))
        if not np.all(np.isfinite(raw0)):
            return None
        return self._jittered(raw0, key)

    def starts_batch(self, panels, fallback_raw, key=None) -> np.ndarray:
        """(R, N, T) panels → (R, n_warm, P) per-panel warm starts, one
        batched forward pass for all R.  A panel whose prediction is
        non-finite gets ``fallback_raw`` as its amortized point instead (the
        per-row version of :meth:`starts`' None)."""
        preds = self.predict_raw_batch(panels)            # (R, P)
        fb = np.asarray(fallback_raw, dtype=np.float64).reshape(1, -1)
        bad = ~np.all(np.isfinite(preds), axis=1)
        preds = np.where(bad[:, None], fb, preds)
        return np.stack([self._jittered(p, key) for p in preds], axis=0)


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------

def sample_prior_raw(spec: ModelSpec, base_raw, B: int, key,
                     scale: float = 0.1) -> np.ndarray:
    """(P, B) unconstrained prior draws around the base point.

    Gaussian jitter in RAW space (the transforms make every draw feasible by
    construction — the multi-start spray's trick), with two structural
    adjustments for the Kalman families that keep the PRIOR PREDICTIVE sane:

    - the transition block Φ gets 0.3·``scale``: its effect on the panel is
      amplified through ``(I − Φ)⁻¹`` (≈10× at the stable points' 0.9
      diagonal), and full-scale off-diagonal jitter swings panel levels by
      hundreds — a prior predictive so dispersed that δ becomes statistically
      INDEPENDENT of the panel (measured: corr(panel mean, δ) ≈ 0.001) and
      no summary can amortize it;
    - Φ draws are PROJECTED back inside the unit circle (ρ(Φ) ≥ 0.995 →
      rescaled to 0.99): at a 0.98 base diagonal the stationarity margin is
      0.02, and an unprojected off-diagonal jitter makes a large fraction
      of draws non-stationary — NaN panels that waste training lanes (the
      loss masks them) and poison held-out evaluation;
    - δ is drawn in STEADY-STATE space: μ* = μ_base + ``scale``·max(1, |μ|)·ε
      elementwise, then δ = (I − Φ_draw) μ* per draw — the panel's level
      moves WITH the draw's δ at observable magnitude instead of being
      hostage to the Φ draw.

    Non-Kalman specs (and layouts without a (δ, Φ) block) keep the plain
    isotropic jitter."""
    base = np.asarray(base_raw, dtype=np.float64).reshape(-1)
    key = jnp.asarray(key)
    k1, k2 = jax.random.split(key)
    noise = scale * np.asarray(
        jax.random.normal(k1, (base.shape[0], B)), dtype=np.float64)
    if not spec.is_kalman:
        return base[:, None] + noise
    from ..models.params import transform_params, untransform_params

    lo_p, hi_p = spec.layout["phi"]
    lo_d, hi_d = spec.layout["delta"]
    Ms = spec.state_dim
    noise[lo_p:hi_p] *= 0.3
    draws = base[:, None] + noise
    cons = np.array(jax.vmap(
        lambda r: transform_params(spec, r))(
            jnp.asarray(draws.T, dtype=jnp.float64)), dtype=np.float64)
    # Kalman Φ is stored row-major (models/params.unpack_kalman)
    Phi = cons[:, lo_p:hi_p].reshape(B, Ms, Ms)
    rho = np.max(np.abs(np.linalg.eigvals(Phi)), axis=1)
    shrink = np.where(rho >= 0.995, 0.99 / np.maximum(rho, 1e-12), 1.0)
    Phi = Phi * shrink[:, None, None]
    Phi0 = np.asarray(transform_params(
        spec, jnp.asarray(base, dtype=jnp.float64)),
        dtype=np.float64)[lo_p:hi_p].reshape(Ms, Ms)
    mu0 = np.linalg.solve(np.eye(Ms) - Phi0, base[lo_d:hi_d])
    eps = np.asarray(jax.random.normal(k2, (B, Ms)), dtype=np.float64)
    mu = mu0[None] + scale * np.maximum(1.0, np.abs(mu0))[None] * eps
    delta = np.einsum("bij,bj->bi", np.eye(Ms)[None] - Phi, mu)
    cons[:, lo_p:hi_p] = Phi.reshape(B, -1)
    cons[:, lo_d:hi_d] = delta
    # back through the library's inverse bijections (the Φ diagonal rides
    # R_TO_11 — hand-rolling its inverse here would drift from the spec)
    return np.asarray(jax.vmap(
        lambda c: untransform_params(spec, c))(
            jnp.asarray(cons, dtype=jnp.float64)), dtype=np.float64).T


def train_amortizer(spec: ModelSpec, base_params, T: int, *,
                    cfg: Optional[AmortizerConfig] = None,
                    n_rounds: int = 8, batch: int = 64,
                    steps_per_round: int = 25, lr: float = 3e-3,
                    prior_scale: float = 0.1, key=None) -> Amortizer:
    """Train a surrogate ONCE for ``spec`` on simulated panels of length
    ``T`` around ``base_params`` (constrained — e.g. a previously fitted
    point or the shared stable test points).

    Each round draws ``batch`` raw parameter vectors from the prior, pushes
    them through the donated simulation program (fresh panels every round —
    the net never sees a pair twice), and takes ``steps_per_round`` donated
    Adam steps on the masked-MSE loss.  Everything is compile-once: one
    simulation program + one train-step program for the whole run.  Returns
    the trained :class:`Amortizer`; ``.info`` carries the loss trajectory
    and the prior so benches can report the train-once cost honestly."""
    if not spec.is_kalman:
        raise ValueError(
            f"train_amortizer needs a Kalman family (the simulator's "
            f"generative model); {spec.family!r} has none")
    from .optimize import _sanitize
    from ..models.params import untransform_params

    cfg = cfg if cfg is not None else AmortizerConfig()
    if key is None:
        key = jax.random.PRNGKey(cfg.seed)
    key = jnp.asarray(key)
    base_raw = _sanitize(np.asarray(untransform_params(
        spec, jnp.asarray(np.asarray(base_params, dtype=np.float64).reshape(-1),
                          dtype=spec.dtype)), dtype=np.float64))
    key, k_init = jax.random.split(key)
    params = init_params(cfg, spec, k_init)
    # anchor the output bias at the prior mean IN NET SPACE (δ slots carry
    # μ): the untrained net already predicts a feasible point, and training
    # only has to learn the residual
    base_net = net_targets(spec, base_raw[:, None])[:, 0]
    params["b3"] = jnp.asarray(np.where(np.isfinite(base_net), base_net,
                                        base_raw), dtype=spec.dtype)

    sim = _jitted_sim_batch(spec, int(T), int(batch), True)
    step = _jitted_train_step(cfg, spec, int(T), int(batch), float(lr))
    opt_state = None
    losses = []
    for r in range(n_rounds):
        key, k_draw, k_sim = jax.random.split(key, 3)
        draws = sample_prior_raw(spec, base_raw, batch, k_draw,
                                 scale=prior_scale)
        out = sim(jnp.asarray(draws, dtype=spec.dtype),
                  jax.random.split(k_sim, batch))
        panels = out["panels"]
        # net-space targets: δ slots → the draw's steady state (see the
        # "target space" block above); NaN rows are masked samples
        targets = jnp.asarray(net_targets(spec, np.asarray(out["raw"])),
                              dtype=spec.dtype)
        if r == 0:
            # input normalization from the FIRST simulated batch (host-side,
            # driver layer) — fixed for the rest of training and serving
            params = set_normalization(params, np.asarray(panels))
            import optax

            opt_state = optax.adam(float(lr)).init(params)
        for _ in range(steps_per_round):
            params, opt_state, loss = step(params, opt_state, panels, targets)
        losses.append(float(loss))
    return Amortizer(spec, cfg, params,
                     info={"losses": losses, "T": int(T),
                           "prior_scale": float(prior_scale),
                           "base_raw": base_raw, "n_rounds": int(n_rounds),
                           "batch": int(batch),
                           "steps_per_round": int(steps_per_round)})


# ---------------------------------------------------------------------------
# process-wide registry + the YFM_AMORT knob
# ---------------------------------------------------------------------------

_REG_LOCK = threading.Lock()
_REGISTRY: Dict[ModelSpec, Amortizer] = {}


def amortization_enabled() -> bool:
    """``YFM_AMORT=1`` arms the amortized warm start for every estimation
    entry whose caller leaves ``warm_start=None`` (default off — the
    historical multi-start path, bit-for-bit)."""
    return os.environ.get("YFM_AMORT", "0") not in ("0", "")


def register_amortizer(am: Amortizer) -> Amortizer:
    """Install a trained surrogate as the process-wide warm-start provider
    for its spec (what ``YFM_AMORT=1`` / ``warm_start=True`` consult)."""
    with _REG_LOCK:
        _REGISTRY[am.spec] = am
    return am


def get_amortizer(spec: ModelSpec) -> Optional[Amortizer]:
    with _REG_LOCK:
        return _REGISTRY.get(spec)


def clear_amortizers() -> None:
    with _REG_LOCK:
        _REGISTRY.clear()


# ---------------------------------------------------------------------------
# the one-forward-pass refit (the serving layer's entry)
# ---------------------------------------------------------------------------

def amortized_refit(spec: ModelSpec, data, *, amortizer: Optional[Amortizer]
                    = None, polish_iters: int = 1, g_tol: float = 1e-6,
                    f_abstol: float = 1e-8, mode: str = "fisher"):
    """One amortized re-estimation: surrogate forward pass + ``polish_iters``
    trust-region Newton steps (ops/newton.py through the cached polish
    program) — the millisecond-refit primitive behind the serving layer's
    ``refit`` verbs.

    Returns ``(raw_params (P,), loglik)``; ``(None, -inf)`` when the
    surrogate prediction is non-finite (sentinel — the caller owns the
    degrade policy).  ``polish_iters=0`` skips the polish and just evaluates
    the predicted point."""
    am = amortizer if amortizer is not None else get_amortizer(spec)
    if am is None:
        raise ValueError(
            f"no trained amortizer registered for {spec.model_string!r} — "
            f"train one (estimation.amortize.train_amortizer) and "
            f"register_amortizer() it, or pass amortizer=")
    data = jnp.asarray(data, dtype=spec.dtype)
    T = int(data.shape[1])
    raw0 = am.predict_raw(np.asarray(data))
    if not np.all(np.isfinite(raw0)):
        return None, float("-inf")
    from .optimize import _jitted_loss, _jitted_newton_polish
    from ..models.params import transform_params

    if polish_iters > 0:
        runner = _jitted_newton_polish(spec, T, int(polish_iters), g_tol,
                                       f_abstol, mode)
        res = runner(jnp.asarray(raw0[None], dtype=spec.dtype), data,
                     jnp.asarray(0), jnp.asarray(T))
        took = bool(np.asarray(res.iters)[0] > 0) \
            or bool(np.asarray(res.converged)[0])
        f = float(np.asarray(res.f)[0])
        if took and np.isfinite(f):
            return np.asarray(res.x, dtype=np.float64)[0], -f
    ll = float(_jitted_loss(spec, T)(
        transform_params(spec, jnp.asarray(raw0, dtype=spec.dtype)), data,
        jnp.asarray(0), jnp.asarray(T)))
    return raw0, ll
